"""Config 3 (BASELINE.json:9): very-sparse Li RP 10M×16384→512 on v5e-8.

density = 1/√d (Li/Hastie/Church 2006).  d = 16384 is the regime where the
contraction dimension is worth sharding: the mesh is DP×TP, R is generated
directly into its column-sharded layout (each chip only ever holds its
shard), and the transform is a partial einsum + one psum over ICI.

Run with `--devices 8` on CPU to exercise the exact sharded program on a
virtual mesh; on a real v5e-8 omit the flag.
"""

import argparse
import json
import os
import sys
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=["small", "full"], default="small")
    ap.add_argument("--devices", type=int, default=None,
                    help="force a virtual CPU mesh of this many devices")
    args = ap.parse_args()

    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, ".")
    import jax

    from randomprojection_tpu import SparseRandomProjection
    from randomprojection_tpu.parallel import make_mesh, mesh_shape_for
    from randomprojection_tpu.streaming import CallableSource

    n_dev = len(jax.devices())
    feature_shards = 2 if n_dev >= 4 and n_dev % 2 == 0 else 1
    mesh = make_mesh(mesh_shape_for(n_dev, feature_shards))

    if args.scale == "full":
        n, d, k, batch = 10_000_000, 16_384, 512, 131_072
    else:
        n, d, k, batch = 50_000, 2048, 64, 8192

    def read(lo, hi):
        return np.random.default_rng(lo).normal(size=(hi - lo, d)).astype(np.float32)

    src = CallableSource(read, n_rows=n, n_features=d, batch_rows=batch)
    rp = SparseRandomProjection(
        k, density="auto", random_state=0, backend="jax",
        backend_options={
            "mesh": mesh,
            "feature_axis": "feature" if feature_shards > 1 else None,
        },
    ).fit_source(src)

    t0 = time.perf_counter()
    total, checksum = 0, 0.0
    for lo, y in rp.transform_stream(src):
        total += y.shape[0]
        checksum += float(y[0, 0])
    dt = time.perf_counter() - t0
    print(json.dumps({
        "config": 3, "mesh": dict(mesh.shape), "density": rp.density_,
        "rows": total, "rows_per_s": round(total / dt, 1), "checksum": checksum,
    }))


if __name__ == "__main__":
    main()
