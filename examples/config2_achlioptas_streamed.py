"""Config 2 (BASELINE.json:8): Achlioptas s=3 RP 1M×4096→256, streamed.

The headline workload: sparse (density 1/3) kernel on the jax backend with
the split2 precision mode, fed through the streamed row-batch iterator with
cursor checkpointing.  Rows are synthesized per range (a stand-in for any
seekable out-of-core source), so `--scale full` streams the true 1M rows
without ever holding them.
"""

import argparse
import json
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, ".")
from randomprojection_tpu import SparseRandomProjection
from randomprojection_tpu.streaming import CallableSource
from randomprojection_tpu.utils.observability import StreamStats


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=["small", "full"], default="small")
    ap.add_argument("--backend", default="jax")
    ap.add_argument("--precision", default="split2")
    args = ap.parse_args()
    n = 1_000_000 if args.scale == "full" else 100_000
    d, k, batch = 4096, 256, 65_536

    def read(lo, hi):  # deterministic range reader = resumable source
        return (
            np.random.default_rng(lo)
            .normal(size=(hi - lo, d))
            .astype(np.float32)
        )

    src = CallableSource(read, n_rows=n, n_features=d, batch_rows=batch)
    opts = {"precision": args.precision} if args.backend == "jax" else None
    rp = SparseRandomProjection(
        k, density=1 / 3, random_state=0, backend=args.backend,
        backend_options=opts,
    ).fit_source(src)

    ckpt = tempfile.mktemp(suffix=".json")
    stats = StreamStats(log_every=4)
    t0 = time.perf_counter()
    total = 0
    checksum = 0.0
    for lo, y in rp.transform_stream(src, checkpoint_path=ckpt, stats=stats):
        total += y.shape[0]
        checksum += float(y[0, 0])
    dt = time.perf_counter() - t0
    print(json.dumps({
        "config": 2, "rows": total, "rows_per_s": round(total / dt, 1),
        "checksum": checksum, **stats.summary(),
    }))


if __name__ == "__main__":
    main()
