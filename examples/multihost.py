"""Multi-host streamed projection (SURVEY.md §3.4 process model).

One process per host, every process running THIS script unchanged.  The
Spark driver/executor pattern maps to SPMD: `distributed.initialize()`
joins the processes into one runtime, `host_row_range` gives each host its
own contiguous slice of the global stream (rows are independent in X·Rᵀ,
so no cross-host coordination is needed), and the counter-based PRNG makes
every host materialize the identical projection matrix from the seed.

Single process (a laptop, or one TPU VM):

    python examples/multihost.py

Manual two-process bring-up on one machine (what tests/test_distributed.py
automates; JAX_PLATFORMS=cpu so both processes are plain CPU hosts):

    JAX_PLATFORMS=cpu python examples/multihost.py \
        --coordinator localhost:8476 --num-processes 2 --process-id 0 &
    JAX_PLATFORMS=cpu python examples/multihost.py \
        --coordinator localhost:8476 --num-processes 2 --process-id 1

On a real TPU pod (GKE / TPU VM), omit the flags: `initialize()` uses the
environment's auto-detection, and each host drives its local chips.
"""

import argparse
import json
import sys
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", default=None,
                    help="coordinator address host:port (process 0 hosts it)")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--d", type=int, default=1024)
    ap.add_argument("--k", type=int, default=128)
    args = ap.parse_args()

    sys.path.insert(0, ".")
    from randomprojection_tpu.parallel import distributed

    distributed.initialize(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    import jax

    from randomprojection_tpu import SparseRandomProjection
    from randomprojection_tpu.streaming import CallableSource

    # this host's slice of the global row range — no communication needed
    lo, hi = distributed.host_row_range(args.rows)

    # the global source is any seekable range-reader; the local source maps
    # this host's [0, hi-lo) offsets onto GLOBAL rows [lo, hi) — the data a
    # row contains must depend on its global index, not which host reads it
    def read(a, b):
        return np.random.default_rng(lo + a).standard_normal(
            (b - a, args.d), dtype=np.float32
        )

    src = CallableSource(read, n_rows=hi - lo, n_features=args.d,
                         batch_rows=16384)

    # fit from schema: same (seed, k, d) on every host => identical matrix
    rp = SparseRandomProjection(
        args.k, density=1 / 3, random_state=0, backend="jax"
    ).fit_schema(args.rows, args.d, np.float32)

    t0 = time.perf_counter()
    done = 0
    for start, y in rp.transform_stream(src):
        done += y.shape[0]
    dt = time.perf_counter() - t0

    print(json.dumps({
        "process": jax.process_index(),
        "process_count": jax.process_count(),
        "row_range": [lo, hi],
        "rows_done": done,
        "rows_per_s": round(done / dt, 1),
    }))


if __name__ == "__main__":
    main()
