"""Config 4 (BASELINE.json:10): sign-RP / SimHash cosine-LSH over n×768.

Embeddings → 256-bit packed codes on device (32 bytes/row leaves the chip,
not 3 KB of f32 coordinates — the d2h reduction that makes 1B rows
feasible), then bulk Hamming scoring with on-device popcount and cosine
estimates from collision rates.
"""

import argparse
import json
import os
import sys
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=["small", "full"], default="small")
    ap.add_argument("--backend", default="jax")
    ap.add_argument("--devices", type=int, default=None,
                    help="force a virtual CPU mesh of this many devices")
    args = ap.parse_args()

    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, ".")
    from randomprojection_tpu import (
        SignRandomProjection,
        cosine_from_hamming,
        pairwise_hamming_device,
    )
    from randomprojection_tpu.streaming import CallableSource
    # full-scale config is 1e9 rows; this example streams what you give it
    n = 2_000_000 if args.scale == "full" else 50_000
    d, bits, batch = 768, 256, 65_536

    def read(lo, hi):
        rng = np.random.default_rng(lo)
        return rng.normal(size=(hi - lo, d)).astype(np.float32)

    src = CallableSource(read, n_rows=n, n_features=d, batch_rows=batch)
    rp = SignRandomProjection(bits, random_state=0, backend=args.backend)
    rp.fit_source(src)

    t0 = time.perf_counter()
    codes = []
    for lo, c in rp.transform_stream(src):
        codes.append(c)
    codes = np.concatenate(codes)
    dt = time.perf_counter() - t0
    assert codes.dtype == np.uint8 and codes.shape == (n, bits // 8)

    # query the code index: top-5 neighbors of the first 4 rows.  With more
    # than one device, shard the index rows across the mesh — the scale-out
    # for indexes beyond one chip's HBM (1B×32B codes = 32 GB)
    import jax

    if len(jax.devices()) > 1:
        from randomprojection_tpu import pairwise_hamming_sharded
        from randomprojection_tpu.parallel import default_mesh

        H = pairwise_hamming_sharded(codes[:4], codes, mesh=default_mesh())
    else:
        H = pairwise_hamming_device(codes[:4], codes)
    nn = np.argsort(H, axis=1)[:, 1:6]
    est_cos = cosine_from_hamming(np.take_along_axis(H, nn, axis=1), bits)
    print(json.dumps({
        "config": 4, "rows": n, "code_bytes": int(codes.shape[1]),
        "encode_rows_per_s": round(n / dt, 1),
        "first_query_top5_cos": [round(c, 3) for c in est_cos[0].tolist()],
    }))


if __name__ == "__main__":
    main()
