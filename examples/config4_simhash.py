"""Config 4 (BASELINE.json:10): sign-RP / SimHash cosine-LSH over n×768.

The serving pattern end to end: embeddings → 256-bit packed codes on
device (32 bytes/row leaves the chip, not 3 KB of f32 coordinates — the
d2h reduction that makes 1B rows feasible) → a ``SimHashIndex`` built
ONCE (device-resident, row-sharded over the mesh when one is available)
→ streamed query batches answered with the on-device ``query_topk``, so
each query ships O(m) candidates to the host, never the (queries × codes)
distance matrix (at the BL:10 scale, one 2048-row tile against 1B codes
would be 8 TB d2h).
"""

import argparse
import json
import os
import sys
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=["small", "full"], default="small")
    ap.add_argument("--backend", default="jax")
    ap.add_argument("--topk", type=int, default=5)
    ap.add_argument("--query-batches", type=int, default=8)
    ap.add_argument("--devices", type=int, default=None,
                    help="force a virtual CPU mesh of this many devices")
    args = ap.parse_args()

    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, ".")
    from randomprojection_tpu import SignRandomProjection, SimHashIndex
    from randomprojection_tpu.streaming import CallableSource

    # full-scale config is 1e9 rows; this example streams what you give it
    n = 2_000_000 if args.scale == "full" else 50_000
    d, bits, batch = 768, 256, 65_536
    q_tile = 2048

    def read(lo, hi):
        rng = np.random.default_rng(lo)
        return rng.normal(size=(hi - lo, d)).astype(np.float32)

    src = CallableSource(read, n_rows=n, n_features=d, batch_rows=batch)
    rp = SignRandomProjection(bits, random_state=0, backend=args.backend)
    rp.fit_source(src)

    # ---- build: encode the corpus and load the index ONCE -----------------
    import jax

    mesh = None
    if len(jax.devices()) > 1:
        # index rows shard across the mesh — the scale-out for indexes
        # beyond one chip's HBM (1B×32B codes = 32 GB)
        from randomprojection_tpu.parallel import default_mesh

        mesh = default_mesh()
    t0 = time.perf_counter()
    index = None
    for _lo, c in rp.transform_stream(src):
        # incremental build: each streamed code batch ships once (O(new)
        # per add) — no host-side concatenation of the whole corpus
        if index is None:
            index = SimHashIndex(c, mesh=mesh)
        else:
            index.add(c)
    build_dt = time.perf_counter() - t0

    # ---- serve: stream query batches against the resident index ----------
    rng = np.random.default_rng(123)
    n_q = 0
    t0 = time.perf_counter()
    for _ in range(args.query_batches):
        Q = rp.transform(rng.normal(size=(q_tile, d)).astype(np.float32))
        dist, ids = index.query_topk(Q, args.topk, tile=q_tile)
        n_q += Q.shape[0]
    serve_dt = time.perf_counter() - t0

    from randomprojection_tpu import cosine_from_hamming

    print(json.dumps({
        "config": 4, "rows": n, "code_bytes": bits // 8,
        "mesh_devices": 1 if mesh is None else int(np.prod(list(mesh.shape.values()))),
        "build_rows_per_s": round(n / build_dt, 1),
        "queries_per_s": round(n_q / serve_dt, 1),
        "topk_d2h_bytes_per_query": 2 * 4 * args.topk,
        "dense_d2h_bytes_per_query": 4 * index.n_codes,
        "first_query_top5_cos": [
            round(c, 3)
            for c in cosine_from_hamming(dist[0], bits).tolist()[:5]
        ],
    }))


if __name__ == "__main__":
    main()
