"""Config 1 (BASELINE.json:7): Gaussian RP 10k×512→64, dense, single host.

The "PR1 reference" workload: the numpy backend is the reference executor,
and the JL distance contract is checked on the output.
"""

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")
from randomprojection_tpu import GaussianRandomProjection


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=["small", "full"], default="full")
    ap.add_argument("--backend", default="numpy")
    args = ap.parse_args()
    n, d, k = (10_000, 512, 64) if args.scale == "full" else (1000, 512, 64)

    X = np.random.default_rng(0).normal(size=(n, d)).astype(np.float32)
    rp = GaussianRandomProjection(k, random_state=0, backend=args.backend)
    rp.fit(X)
    t0 = time.perf_counter()
    Y = np.asarray(rp.transform(X))
    dt = time.perf_counter() - t0

    # distance preservation on a sample
    idx = np.random.default_rng(1).choice(n, size=200, replace=False)
    dx = np.linalg.norm(X[idx, None] - X[None, idx], axis=-1) ** 2
    dy = np.linalg.norm(Y[idx, None] - Y[None, idx], axis=-1) ** 2
    iu = np.triu_indices(len(idx), 1)
    ratio = dy[iu] / np.maximum(dx[iu], 1e-12)
    print(
        f"config1 [{args.backend}]: {n}x{d}->{k}  {n/dt:,.0f} rows/s  "
        f"distance ratio mean={ratio.mean():.3f} "
        f"[{ratio.min():.2f}, {ratio.max():.2f}]"
    )


if __name__ == "__main__":
    main()
