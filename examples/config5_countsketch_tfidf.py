"""Config 5 (BASELINE.json:11): Count-Sketch / feature hashing on streaming
TF-IDF-style documents — end to end ON DEVICE at the stated 2^20 space.

Raw tokens → C++ murmur3 ``FeatureHasher`` (2^20-dim f32 CSR) →
``CountSketch`` down to 256 dims on the chip (resident hash tables +
gather/scatter-add; no one-hot matrix can exist at d=2^20), streamed as
one resumable pipeline via ``TokenSource``.  The full-scale config is
100M docs; throughput here is hasher-bound on one core (the hasher is
the native batch kernel in native/murmur3.cpp).
"""

import argparse
import json
import os
import sys
import time

import numpy as np


def synth_docs(lo, hi, vocab=50_000):
    rng = np.random.default_rng(lo)
    for i in range(hi - lo):
        n_tok = int(rng.integers(20, 120))
        toks = rng.integers(0, vocab, size=n_tok)
        tf = {}
        for t in toks:
            tf[f"w{t}"] = tf.get(f"w{t}", 0.0) + 1.0
        yield tf


def synth_token_columns(lo, hi, vocab=50_000):
    """The vectorized ingest layout: one flat token array + CSR indptr per
    batch (what a real tokenizer pipeline hands over) — no Python dicts."""
    rng = np.random.default_rng(lo)
    lens = rng.integers(20, 120, size=hi - lo)
    flat = rng.integers(0, vocab, size=int(lens.sum()))
    tokens = np.char.add("w", flat.astype("U7"))
    indptr = np.concatenate([[0], np.cumsum(lens)])
    return tokens, indptr


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=["small", "full"], default="small")
    ap.add_argument(
        "--ingest", choices=["dict", "tokens"], default="tokens",
        help="'tokens' = vectorized transform_tokens path (C++ batch "
        "murmur3, no per-token Python); 'dict' = the per-sample dict API",
    )
    ap.add_argument("--devices", type=int, default=None,
                    help="force a virtual CPU mesh of this many devices")
    ap.add_argument("--prefetch", type=int, default=0,
                    help="PrefetchSource depth: hash + H2D on a background "
                         "worker thread (0 = synchronous; tokens ingest "
                         "only)")
    ap.add_argument("--hash-threads", type=int, default=None,
                    help="C++ murmur3 worker threads (bit-identical "
                         "output; tokens ingest only)")
    args = ap.parse_args()
    if args.ingest == "dict" and (args.prefetch or args.hash_threads):
        # refuse rather than silently measuring the synchronous dict path
        # while the output is labeled as a prefetched run
        ap.error("--prefetch/--hash-threads apply to --ingest tokens only")

    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, ".")
    from randomprojection_tpu import CountSketch
    from randomprojection_tpu.ops.hashing import FeatureHasher
    from randomprojection_tpu.streaming import PrefetchSource, TokenSource
    from randomprojection_tpu.utils.observability import StreamStats

    n_docs = 200_000 if args.scale == "full" else 10_000
    hash_dim, k, batch = 2**20, 256, 2000

    # dtype=float32 ⇒ the sketch runs on device (CSR gather/scatter
    # against resident h_/s_ tables); float64 (the default) would keep
    # the exact host scatter
    hasher = FeatureHasher(
        n_features=hash_dim,
        input_type="dict" if args.ingest == "dict" else "string",
        dtype=np.float32,
    )

    t0 = time.perf_counter()
    done, checksum, tokens_seen = 0, 0.0, 0
    if args.ingest == "dict":
        cs = CountSketch(k, random_state=0).fit_schema(n_docs, hash_dim)
        while done < n_docs:
            hi = min(done + batch, n_docs)
            X = hasher.transform(synth_docs(done, hi))  # CSR, hashed
            Y = cs.transform(X)                         # (batch, k) sketch
            checksum += float(np.abs(Y[0]).sum())
            done = hi
    else:
        # the one-pipeline form: tokens → murmur3 → device sketch,
        # checkpoint/resumable (pass checkpoint_path= to make it durable)
        def read_tokens(lo, hi):
            nonlocal tokens_seen
            toks, indptr = synth_token_columns(lo, hi)
            tokens_seen += len(toks)
            return toks, indptr

        stats = StreamStats()
        source = TokenSource(
            read_tokens, n_docs, hasher, batch_rows=batch,
            hash_threads=args.hash_threads, stats=stats,
        )
        cs = CountSketch(k, random_state=0).fit_source(source)
        if args.prefetch:
            # overlapped ingest: hashing + early device upload run on the
            # prefetch worker while this thread dispatches and fetches
            source = PrefetchSource(
                source, depth=args.prefetch,
                prepare=cs.prepare_batch, stats=stats,
            )
        for _lo, Y in cs.transform_stream(source, stats=stats):
            checksum += float(np.abs(Y[0]).sum())
    dt = time.perf_counter() - t0
    out = {
        "config": 5, "docs": n_docs, "hash_dim": hash_dim, "k": k,
        "ingest": args.ingest, "docs_per_s": round(n_docs / dt, 1),
        "checksum": checksum,
    }
    if tokens_seen:
        out["tokens_per_s"] = round(tokens_seen / dt, 1)
    if args.ingest == "tokens" and args.prefetch:
        out["pipeline_overlap_ratio"] = round(stats.overlap_ratio(), 3)
        out["stage_wall_s"] = {
            name: round(wall, 4)
            for name, wall in sorted(stats.stage_wall.items())
        }

    # On a multi-chip slice the sketch DP-shards rows over the mesh — the
    # "100M docs on v5e-8" deployment shape.  (CSR batches shard too: the
    # tokens partition at shard row boundaries; dense batches shown here
    # use the MXU one-hot matmul per shard.)
    import jax

    if len(jax.devices()) > 1:
        from randomprojection_tpu.parallel import default_mesh

        dn, dd = 8192, 4096
        Xd = np.random.default_rng(0).standard_normal((dn, dd), np.float32)
        csd = CountSketch(k, random_state=0, mesh=default_mesh())
        csd.fit_schema(dn, dd)
        csd.transform(Xd)  # warm the full-size program (row buckets by n)
        td = time.perf_counter()
        csd.transform(Xd)
        out["dense_mesh_rows_per_s"] = round(dn / (time.perf_counter() - td), 1)
        out["mesh_devices"] = len(jax.devices())
    print(json.dumps(out))


if __name__ == "__main__":
    main()
