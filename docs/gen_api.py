"""Regenerate docs/API.md from the live package surface.

Run from the repo root: ``python docs/gen_api.py``.  Keeps the API doc in
lock-step with code — the doc is generated, never hand-edited.
"""

import inspect
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import randomprojection_tpu as rp  # noqa: E402

# RP_API_OUT overrides the output path (used by the staleness test)
OUT = os.environ.get("RP_API_OUT") or os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "API.md"
)


def sig(obj):
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"


def first_line(obj):
    """Docstring summary: all lines up to the first blank (a hard
    ``splitlines()[0]`` would cut wrapped summaries mid-sentence)."""
    d = inspect.getdoc(obj) or ""
    para = d.split("\n\n", 1)[0]
    return " ".join(line.strip() for line in para.splitlines())


def main():
    lines = [
        "# API reference", "",
        "Public surface of `randomprojection_tpu` (generated from the live",
        "package; regenerate with `python docs/gen_api.py` after surface",
        "changes).", "",
        "## Top level (`import randomprojection_tpu as rp`)", "",
    ]
    for name in sorted(rp.__all__):
        obj = getattr(rp, name)
        if inspect.isclass(obj):
            init_sig = (
                sig(obj.__init__).replace("(self, ", "(").replace("(self)", "()")
            )
            lines += [f"### `{name}{init_sig}`", "", first_line(obj), ""]
            # estimators document the canonical protocol order; other
            # classes (e.g. SimHashIndex) list every public method so new
            # surfaces can't silently vanish from the doc
            estimator_protocol = (
                "fit", "fit_schema", "fit_source", "transform",
                "fit_transform", "transform_stream", "inverse_transform",
                "get_feature_names_out", "get_params", "set_params",
                "components_as_numpy",
            )
            if any(callable(getattr(obj, m, None)) for m in ("fit", "transform")):
                methods = [
                    m for m in estimator_protocol
                    if callable(getattr(obj, m, None))
                ]
            else:
                methods = [
                    m for m, v in sorted(vars(obj).items())
                    if not m.startswith("_") and callable(v)
                ]
            if methods:
                lines += ["Methods: " + ", ".join(f"`{m}`" for m in methods), ""]
        elif callable(obj):
            lines += [f"### `{name}{sig(obj)}`", "", first_line(obj), ""]
        else:
            lines += [f"### `{name}` — {type(obj).__name__}", ""]

    import randomprojection_tpu.durable as durable
    import randomprojection_tpu.serialize as serialize
    import randomprojection_tpu.streaming as streaming
    import randomprojection_tpu.parallel as parallel
    from randomprojection_tpu.analysis import cfg as analysis_cfg
    from randomprojection_tpu.analysis import flowrules as analysis_flowrules
    from randomprojection_tpu.analysis import rplint
    from randomprojection_tpu.ops import (
        hashing,
        pallas_kernels,
        probe_kernels,
        split_matmul,
        topk_kernels,
    )
    from randomprojection_tpu.parallel import distributed
    from randomprojection_tpu.utils import (
        health,
        metrics_server,
        observability,
        telemetry,
        trace_report,
    )
    import randomprojection_tpu.loadgen as loadgen
    import randomprojection_tpu.ann as ann

    for title, mod in [
        ("`randomprojection_tpu.streaming`", streaming),
        ("`randomprojection_tpu.serialize`", serialize),
        ("`randomprojection_tpu.durable`", durable),
        ("`randomprojection_tpu.parallel`", parallel),
        ("`randomprojection_tpu.parallel.distributed`", distributed),
        ("`randomprojection_tpu.ops.hashing`", hashing),
        ("`randomprojection_tpu.ops.pallas_kernels`", pallas_kernels),
        ("`randomprojection_tpu.ops.topk_kernels`", topk_kernels),
        ("`randomprojection_tpu.ops.probe_kernels`", probe_kernels),
        ("`randomprojection_tpu.ops.split_matmul`", split_matmul),
        ("`randomprojection_tpu.utils.observability`", observability),
        ("`randomprojection_tpu.utils.telemetry`", telemetry),
        ("`randomprojection_tpu.utils.trace_report`", trace_report),
        ("`randomprojection_tpu.utils.health`", health),
        ("`randomprojection_tpu.utils.metrics_server`", metrics_server),
        ("`randomprojection_tpu.loadgen`", loadgen),
        ("`randomprojection_tpu.ann`", ann),
        ("`randomprojection_tpu.analysis.rplint`", rplint),
        ("`randomprojection_tpu.analysis.cfg`", analysis_cfg),
        ("`randomprojection_tpu.analysis.flowrules`", analysis_flowrules),
    ]:
        lines += [f"## {title}", ""]
        for name in getattr(mod, "__all__", []):
            obj = getattr(mod, name)
            if inspect.isclass(obj):
                lines.append(f"- **`{name}`** — {first_line(obj)}")
            elif callable(obj):
                lines.append(f"- **`{name}{sig(obj)}`** — {first_line(obj)}")
            else:
                lines.append(f"- **`{name}`** — {type(obj).__name__}")
        lines.append("")

    lines += [
        "## `backend_options` (jax backend)", "",
        "| key | values | effect |",
        "|---|---|---|",
        '| `precision` | `"default"`, `"high"` (f32 default), `"highest"`, '
        '`"split2"` | MXU arithmetic for the projection matmul; `split2` = '
        "X hi/lo bf16 split vs the exact ±1/0 mask (sparse/sign kinds only, "
        "f32-grade) |",
        '| `materialization` | `"dense"` (default), `"lazy"` | `lazy` '
        "regenerates the mask in-kernel (Pallas, TPU only, sparse/sign "
        "kinds): R never resides in HBM |",
        '| `compute_dtype` | `"float32"` (default), `"bfloat16"` | on-device '
        "compute dtype |",
        "| `mesh` | a `jax.sharding.Mesh` | DP row-sharding of batches; R "
        "replicated |",
        "| `feature_axis` | mesh axis name | TP: shard the contraction dim "
        "d; one `psum` per batch |",
        '| `data_axis` | mesh axis name (default `"data"`) | row-sharding '
        "axis |",
        "",
    ]
    with open(OUT, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
