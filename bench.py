"""Benchmark harness: the north-star metric as one JSON line on stdout.

Metric (BASELINE.json:2): rows/sec/chip projecting 4096→256 over 1M rows,
plus pairwise-distance distortion vs the CPU reference.  Reported number is
the **data-resident** throughput (SURVEY.md §7: a single host PCIe link caps
streamed feeding at ~1M rows/s, so the chip metric must be measured with
data on device; the streaming path is exercised separately in tests).

Method
------
- Achlioptas s=3 (density 1/3) projection matrix — the exact 1M×4096→256
  workload of BASELINE.json config 2 — in dense device layout.
- Two MXU modes are measured, and the headline is the FASTEST mode whose
  measured pairwise-distance distortion vs the CPU f64 reference (same R)
  meets the ≤1e-3 budget of BASELINE.json:5:
    * ``bf16``: bf16 inputs, f32 accumulation (1 MXU pass, ~1.6e-3 typical)
    * ``f32_high``: f32 inputs, 3-pass bf16 ("high" precision, ~2e-5)
- Iterations are dependency-chained through the input (x += tiny·y) inside
  one ``lax.scan``, and a checksum is returned, so neither DCE nor
  identical-call caching can fake the timing (SURVEY.md §7 measurement
  notes on this virtualized platform).  ``timing_suspect`` is set when the
  implied FLOP rate exceeds 2× the v5e peak — on real hardware it is false.
- ``vs_baseline`` = TPU rows/s ÷ CPU-reference rows/s, where the CPU
  reference is dense f32 BLAS on this host measured in the same run (the
  honest CPU number per SURVEY.md §7 — the reference's own sparse CSR path
  is orders slower).
"""

import json
import sys
import time

import numpy as np

K, D = 256, 4096
BATCH = 131072  # 2^17 rows per scan step; 8 steps = 1,048,576 rows per call
STEPS_PER_CALL = 8
TIMED_CALLS = 3
DENSITY = 1.0 / 3.0  # Achlioptas s=3
DISTORTION_BUDGET = 1e-3
V5E_PEAK_TFLOPS = 197.0


def pdist2(a):
    sq = (a * a).sum(1)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (a @ a.T)
    iu = np.triu_indices(a.shape[0], k=1)
    return np.maximum(d2[iu], 1e-30)


def measure_mode(jax, jnp, R_f32, dtype, precision):
    """Time the chained-scan projection loop in one MXU mode."""
    r = R_f32.astype(dtype)
    x0 = jax.random.normal(jax.random.key(1), (BATCH, D), dtype=dtype)

    @jax.jit
    def run_steps(x, r):
        def step(x, _):
            y = jnp.einsum(
                "nd,kd->nk",
                x,
                r,
                preferred_element_type=jnp.float32,
                precision=precision,
            )
            # chain the next input on this output: defeats DCE and
            # identical-argument call caching; numerically negligible
            x = x + (y[:, :1] * 1e-24).astype(x.dtype)
            return x, y[0, 0]

        return jax.lax.scan(step, x, None, length=STEPS_PER_CALL)

    x, checks = run_steps(x0, r)  # warmup / compile
    x.block_until_ready()

    t0 = time.perf_counter()
    for _ in range(TIMED_CALLS):
        x, checks = run_steps(x, r)
    x.block_until_ready()
    elapsed = time.perf_counter() - t0

    rows = TIMED_CALLS * STEPS_PER_CALL * BATCH
    return {
        "rows_per_s": rows / elapsed,
        "elapsed_s": elapsed,
        "rows_timed": rows,
        "checksum": float(checks.sum()),
    }


def measure_distortion(jax, jnp, R_f32, x_cpu, dtype, precision):
    """Max relative pairwise-distance error vs CPU f64, same R."""
    xs = x_cpu[:1024]
    y_dev = np.asarray(
        jax.jit(
            lambda a, b: jnp.einsum(
                "nd,kd->nk", a, b, preferred_element_type=jnp.float32,
                precision=precision,
            )
        )(jnp.asarray(xs, dtype=dtype), R_f32.astype(dtype))
    ).astype(np.float64)
    y_ref = xs.astype(np.float64) @ np.asarray(R_f32, dtype=np.float64).T
    return float(np.max(np.abs(pdist2(y_dev) / pdist2(y_ref) - 1.0)))


def main():
    import jax
    import jax.numpy as jnp

    from randomprojection_tpu.ops import kernels

    R = kernels.sparse_matrix(jax.random.key(0), K, D, DENSITY, jnp.float32)

    rng = np.random.default_rng(0)
    x_cpu = rng.normal(size=(16384, D)).astype(np.float32)

    modes = {
        "bf16": (jnp.bfloat16, "default"),
        "f32_high": (jnp.float32, "high"),
    }
    results = {}
    for name, (dtype, precision) in modes.items():
        perf = measure_mode(jax, jnp, R, dtype, precision)
        perf["distortion"] = measure_distortion(jax, jnp, R, x_cpu, dtype, precision)
        results[name] = perf

    eligible = [n for n, r in results.items() if r["distortion"] <= DISTORTION_BUDGET]
    if not eligible:  # nothing meets budget: report the most accurate mode
        eligible = [min(results, key=lambda n: results[n]["distortion"])]
    headline = max(eligible, key=lambda n: results[n]["rows_per_s"])
    head = results[headline]

    # CPU reference: dense f32 BLAS on this host, same shapes
    r_cpu = np.asarray(R, dtype=np.float32)
    x_cpu @ r_cpu.T  # warm BLAS
    t0 = time.perf_counter()
    x_cpu @ r_cpu.T
    cpu_rows_per_s = x_cpu.shape[0] / (time.perf_counter() - t0)

    implied_tflops = head["rows_per_s"] * 2 * D * K / 1e12

    print(
        json.dumps(
            {
                "metric": f"rows/sec/chip 4096->256 (Achlioptas s=3, data-resident, {headline})",
                "value": round(head["rows_per_s"], 1),
                "unit": "rows/s",
                "vs_baseline": round(head["rows_per_s"] / cpu_rows_per_s, 2),
                "cpu_baseline_rows_per_s": round(cpu_rows_per_s, 1),
                "distortion_eps_vs_cpu": head["distortion"],
                "mode": headline,
                "all_modes": {
                    n: {
                        "rows_per_s": round(r["rows_per_s"], 1),
                        "distortion": r["distortion"],
                        "elapsed_s": round(r["elapsed_s"], 4),
                    }
                    for n, r in results.items()
                },
                "rows_timed": head["rows_timed"],
                "implied_tflops": round(implied_tflops, 1),
                "timing_suspect": bool(implied_tflops > 2 * V5E_PEAK_TFLOPS),
                "checksum": head["checksum"],
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
