"""Benchmark harness: the north-star metric as JSON on stdout.

Output contract (tail-safe since r7): the FULL record is printed first as
one JSON line, then a self-contained ≤2 KB compact digest (headline mode
record, per-mode/per-config digests, and the round-over-round
``regressions`` tripwire computed against the newest committed
``BENCH_r*.json``) is printed as the FINAL line — the driver's tail
capture can truncate the multi-KB full line (it did in r5, losing the
flagship headline) but never the last 2 KB.

Metric (BASELINE.json:2): rows/sec/chip projecting 4096→256 over 1M rows,
plus pairwise-distance distortion vs the CPU reference.  Reported number is
the **data-resident** throughput (SURVEY.md §7: a single host PCIe link caps
streamed feeding at ~1M rows/s, so the chip metric must be measured with
data on device; the streaming path is exercised separately in tests and
``cli stream-bench``).

Method
------
- Achlioptas s=3 (density 1/3) projection matrix — the exact 1M×4096→256
  workload of BASELINE.json config 2 — in dense device layout.
- Five MXU modes are measured; the headline is the FASTEST mode that both
  meets the ≤1e-3 pairwise-distance budget of BASELINE.json:5 (vs the CPU
  f64 reference, same R) and has a believable timing:
    * ``bf16``: bf16 inputs, f32 accumulation (1 MXU pass, ~1.6e-3+)
    * ``bf16_split2``: X split hi/lo bf16 vs exact ±1 mask (2 passes, ~4e-6)
    * ``f32_high``: f32 inputs, 3-pass bf16 ("high" precision, ~2e-5)
    * ``lazy``: fused Pallas kernel, mask regenerated in VMEM — zero R HBM
      traffic (1 f32 pass, ~1e-3; TPU only)
    * ``lazy_split2``: fused kernel with in-VMEM hi/lo split of X — zero R
      AND zero X-halves HBM traffic (2 bf16 passes, ~3e-6; TPU only).
      The roofline-preferred route to the ≥50M rows/s/chip target.
- Iterations are dependency-chained through the input (x += tiny·y) inside
  one ``lax.scan``, every timed call sees distinct argument values (call
  index folded in on device), calls are serialized through a scalar carry,
  and a checksum is returned — so neither DCE nor call caching can fake the
  timing undetected (SURVEY.md §7 notes on this virtualized platform).
  Each mode carries ``implied_tflops`` (nominal 2·d·k per row),
  ``executed_tflops`` (× MXU passes actually run), and ``timing_suspect``
  (executed rate > 2× v5e peak); a suspect mode never beats a believable
  one for the headline (if every mode is suspect, the most accurate is
  reported with its flag set, marking the whole run untrustworthy).  On
  real hardware no mode trips the flag.
- ``vs_baseline`` = TPU rows/s ÷ CPU-reference rows/s, where the CPU
  reference is dense f32 BLAS on this host measured in the same run (the
  honest CPU number per SURVEY.md §7 — the reference's own sparse CSR path
  is orders slower).
- On THIS box the believable numbers are dominated by ~133 ms/dispatch
  virtualization overhead and are lower bounds on chip throughput — see
  BASELINE.md "What this box's believable numbers actually measure".
- Since r14 the record (and the compact digest) carries the execution-knob
  provenance of the lazy modes: ``transform_dma`` ("auto" = the kernel's
  default manual double-buffered x DMA route; "single" = the pre-r14
  automatic tiling, the A/B lever) and ``dispatch_steps`` (anti-cache
  steps chained through one traced dispatch — call-boundary host gaps
  amortize by 1/steps).  ``cli bench --transform-dma/--dispatch-steps``
  sets them; this wrapper runs the defaults.

Implementation lives in ``randomprojection_tpu/benchmark.py`` (presets,
reusable from the CLI); this wrapper keeps the driver's entry point stable.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from randomprojection_tpu.benchmark import main

if __name__ == "__main__":
    preset = "smoke" if "--smoke" in sys.argv else "full"
    sys.exit(main(preset))
