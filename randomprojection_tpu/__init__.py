"""randomprojection_tpu — a TPU-native random-projection framework.

Capabilities of ``afcarl/RandomProjection`` (Johnson–Lindenstrauss random
projection with Gaussian and sparse Achlioptas/Li kernels, JL
auto-dimensioning, streamed row-batch transform, plus the structured-RP
siblings sign-RP/SimHash and Count-Sketch), re-designed TPU-first:
jit-compiled XLA einsums behind a ``ProjectionBackend`` plugin boundary,
on-device ``jax.random`` matrix generation, and ``shard_map`` data/tensor
parallelism over a ``jax.sharding.Mesh``.

See ``SURVEY.md`` for the structural blueprint and provenance of every
behavioral contract cited in docstrings.
"""

from randomprojection_tpu.jl import johnson_lindenstrauss_min_dim
from randomprojection_tpu.utils.validation import (
    DataDimensionalityWarning,
    NotFittedError,
)

__version__ = "0.5.0"

_LAZY_ESTIMATORS = (
    "BaseRandomProjection",
    "GaussianRandomProjection",
    "SparseRandomProjection",
    "SignRandomProjection",
    "CountSketch",
    "SimHashIndex",
    "TopKServer",
    "pairwise_hamming",
    "pairwise_hamming_device",
    "pairwise_hamming_sharded",
    "cosine_from_hamming",
    "topk_bruteforce",
)

_LAZY_DURABLE = (
    "DurableIngest",
    "save_index",
    "load_index",
    "save_sharded_index",
    "load_sharded_index",
)

_LAZY_SERVING = ("ShardedSimHashIndex", "ShardedTopKServer", "shard_devices")

_LAZY_ANN = (
    "LSHSimHashIndex",
    "LSHShardedSimHashIndex",
    "load_lsh_index",
    "load_lsh_sharded_index",
)

__all__ = [
    "johnson_lindenstrauss_min_dim",
    "DataDimensionalityWarning",
    "NotFittedError",
    *_LAZY_ESTIMATORS,
    *_LAZY_DURABLE,
    *_LAZY_SERVING,
    *_LAZY_ANN,
]


def __getattr__(name):
    # Lazy imports keep `import randomprojection_tpu` cheap (no jax import
    # until an estimator or backend is actually touched).
    if name in _LAZY_ESTIMATORS:
        from randomprojection_tpu import models

        return getattr(models, name)
    if name in _LAZY_DURABLE:
        from randomprojection_tpu import durable

        return getattr(durable, name)
    if name in _LAZY_SERVING:
        from randomprojection_tpu import serving

        return getattr(serving, name)
    if name in _LAZY_ANN:
        from randomprojection_tpu import ann

        return getattr(ann, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
