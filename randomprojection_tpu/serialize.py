"""Fitted-model persistence (SURVEY.md §6 checkpoint/resume).

A fitted projection is fully determined by its ``ProjectionSpec`` (seed +
shape + kind + density + dtype) — a few hundred bytes of JSON.  Loading
re-materializes the matrix with any backend, bit-identical within the
backend family that saved it.  Optionally the materialized matrix (and
pinv) are bundled as ``.npz`` for backend-independent exact restore.

Format is versioned; readers reject unknown versions loudly.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from randomprojection_tpu.backends.base import ProjectionSpec

__all__ = ["save_model", "load_model"]

FORMAT_VERSION = 1

_CLASSES = {}


def _registry():
    # deferred import to avoid cycles
    from randomprojection_tpu.models.projections import (
        GaussianRandomProjection,
        SparseRandomProjection,
    )
    from randomprojection_tpu.models.sketch import CountSketch, SignRandomProjection

    if not _CLASSES:
        for cls in (
            GaussianRandomProjection,
            SparseRandomProjection,
            SignRandomProjection,
            CountSketch,
        ):
            _CLASSES[cls.__name__] = cls
    return _CLASSES


def save_model(est, path: str, *, include_matrix: bool = False) -> None:
    """Save a fitted estimator to ``path`` (JSON; ``path + '.npz'`` if
    ``include_matrix``)."""
    est._check_is_fitted()
    payload = {
        "format_version": FORMAT_VERSION,
        "class": type(est).__name__,
    }
    if hasattr(est, "spec_"):
        payload["spec"] = est.spec_.to_dict()
        payload["params"] = {
            "dense_output": getattr(est, "dense_output", None),
            "compute_inverse_components": est.compute_inverse_components,
        }
        # the lazy (Pallas PRNG) matrix is a different PRNG family from the
        # dense threefry one: record it, or a reload would silently
        # re-materialize a DIFFERENT matrix from the same seed
        state = getattr(est, "_state", None)
        if type(state).__name__ == "_LazyMask":
            payload["backend_options"] = {"materialization": "lazy"}
    else:  # CountSketch: seed-defined, no dense spec
        payload["countsketch"] = {
            "n_components": est.n_components_,
            "n_features": est.n_features_in_,
            "seed": est.seed_,
            # execution-path choice is part of the numeric contract: the
            # MXU path is f32-grade vs the scatter path's exactness
            "use_mxu": est.use_mxu,
        }
    if include_matrix and hasattr(est, "spec_"):
        import scipy.sparse as sp

        arrays = {}
        R = est.components_as_numpy()
        if sp.issparse(R):
            R = R.toarray()
        arrays["components"] = np.asarray(R)
        inv = getattr(est, "inverse_components_", None)
        if inv is not None:
            arrays["inverse_components"] = np.asarray(inv)
        np.savez(path + ".npz", **arrays)
        payload["matrix_file"] = os.path.basename(path) + ".npz"

    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, path)


def load_model(path: str, *, backend: Optional[str] = None):
    """Load a fitted estimator saved by ``save_model``.

    ``backend`` overrides the execution backend ('numpy'/'jax'); the
    projection re-materializes from the stored seed.  A matrix bundle is
    never loaded implicitly — the seed is the source of truth (pass the
    bundle to analyses that need the exact f64 matrix) — but a payload
    saved with ``include_matrix=True`` names its sibling ``.npz`` as
    part of the artifact, and loading verifies the bundle EXISTS: a
    missing one means the artifact was copied partially, and the exact-
    matrix analysis that eventually reaches for it would fail far from
    the cause.  Re-save without ``include_matrix`` for a matrix-less
    single-file artifact.
    """
    with open(path) as f:
        payload = json.load(f)
    version = payload.get("format_version")
    if version != FORMAT_VERSION:
        raise ValueError(
            f"Unsupported model format version {version!r} in {path} "
            f"(expected {FORMAT_VERSION})"
        )
    cls = _registry().get(payload.get("class"))
    if cls is None:
        raise ValueError(f"Unknown model class {payload.get('class')!r} in {path}")
    matrix_file = payload.get("matrix_file")
    if matrix_file is not None:
        # the payload promises a sibling matrix bundle: a missing one
        # means the artifact was copied partially (or the .npz deleted),
        # and any later exact-matrix analysis would fail far from the
        # cause with an opaque error — name the expected path HERE
        bundle = os.path.join(
            os.path.dirname(os.path.abspath(path)), matrix_file
        )
        if not os.path.exists(bundle):
            raise ValueError(
                f"{path} was saved with include_matrix=True but its "
                f"matrix bundle is missing: expected {bundle} alongside "
                "it.  Restore the sibling .npz, or re-save the model "
                "without include_matrix."
            )

    if "countsketch" in payload:
        d = payload["countsketch"]
        est = cls(d["n_components"], random_state=d["seed"],
                  backend=backend or "auto", use_mxu=d.get("use_mxu"))
        est.fit_schema(1, d["n_features"])
        return est

    spec = ProjectionSpec.from_dict(payload["spec"])
    kwargs = {}
    params = payload.get("params", {})
    if params.get("dense_output") is not None:
        kwargs["dense_output"] = params["dense_output"]
    if spec.kind == "sparse":
        kwargs["density"] = spec.density
    backend_options = payload.get("backend_options") or None
    if backend_options and backend is not None and backend != "jax":
        raise ValueError(
            f"This model was fitted with backend options {backend_options} "
            f"(a jax-only PRNG family); it cannot be loaded on backend="
            f"{backend!r} without changing the matrix"
        )
    est = cls(
        spec.n_components,
        random_state=spec.seed,
        backend=backend or ("jax" if backend_options else "auto"),
        backend_options=backend_options,
        compute_inverse_components=bool(params.get("compute_inverse_components")),
        **kwargs,
    )
    # n_samples only gates auto-dim, which a fixed-k respec never triggers
    est.fit_schema(1, spec.n_features, dtype=spec.np_dtype)
    assert est.spec_ == spec, "re-materialized spec must round-trip exactly"
    return est
