"""Tiered hot/cold chunk residency for the serving indexes (ISSUE 19 / r21).

The serving story so far topped out at "corpus per chip = HBM per chip":
every ``SimHashIndex`` chunk is device-resident.  The LSH candidate tier
changed the economics — at recall-preserving probe counts a query tile
touches a few percent of the corpus, so most chunks are cold most of the
time.  This module multiplies corpus-per-chip by letting cold chunks
leave HBM without leaving the index:

- **hot** — a device-resident chunk, exactly the pre-r21 path.  Queries
  gather/score it with zero new cost.
- **cold (host)** — the chunk's packed codes live in host memory as a
  plain ``np.ndarray``.  Candidate rows are gathered on host and
  streamed H2D asynchronously (``ops.topk_kernels.stage_rows``) so the
  upload overlaps the hot-tier kernel.
- **cold (disk)** — the host array is demoted once more into an r11-
  format spill file (``chunk-GGGGGG-SSSSSSSS.npy``, checksummed,
  generation-numbered, written write-tmp → fsync → replace) and served
  through a read-only ``np.load(mmap_mode='r')`` view: row gathers read
  only the touched pages.

Residency never changes ANSWERS — every path re-ranks with the same
exact kernels under the same (distance, lower-global-id) order, and the
hot/cold split re-merges through the union-of-top-m identity — it only
changes where bytes live and when they move.  The fallback ladder rung:
residency pressure or a failed staging upload degrades to a synchronous
fetch (``index.tier.fallback``, on the doctor's degraded audit), never
to wrong answers.

Admission/eviction: chunks are admitted hot at append until the HBM
budget is full; after that, per-chunk access counts folded from the
serving gathers (the same signal the ``index.lsh.*`` bucket counters
aggregate) drive a greedy re-plan (``plan_residency``), and promotions/
demotions run as BOUNDED background work — one worker thread behind a
bounded queue with sentinel shutdown and a joined ``close()``, the same
RP04/RP08/RP10 discipline every other thread substrate in this repo
follows.  A rebalance that loses the enqueue race is dropped, not
queued unboundedly; the next access re-plans.

Thread-safety: the manager's own state (residency table, scores, spill
map) is lock-protected.  ``chunk.b`` swaps happen under that lock, but
serving threads read ``chunk.b`` lock-free — a single attribute load —
and EITHER binding is correct: a stale device array still holds the
same rows, and a just-demoted numpy array round-trips through jax's
implicit (synchronous) upload.  Races cost a slow tile, never a wrong
one.  Telemetry is emitted OUTSIDE the lock (RP10).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

import numpy as np

from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS

__all__ = ["COLD_TIERS", "ResidencyPlan", "plan_residency",
           "TieredResidency"]

COLD_TIERS = ("host", "disk")

# bounded background work: at most this many promote/demote ops pending;
# the queue is sized one larger so close()'s sentinel always has a slot
_MAX_PENDING_OPS = 2


class ResidencyPlan:
    """Which chunks the budget keeps hot: ``hot`` is the set of chunk
    ordinals, ``hot_bytes`` their payload total, ``staging_bytes`` the
    transient headroom the serving paths may additionally occupy for
    double-buffered cold staging (two in-flight row buckets — reported
    so operators size budgets honestly, not charged against admission:
    staged buffers are transient and bounded by construction)."""

    __slots__ = ("hot", "hot_bytes", "budget_bytes", "staging_bytes")

    def __init__(self, hot, hot_bytes: int, budget_bytes: int,
                 staging_bytes: int):
        self.hot = frozenset(hot)
        self.hot_bytes = int(hot_bytes)
        self.budget_bytes = int(budget_bytes)
        self.staging_bytes = int(staging_bytes)


def plan_residency(chunk_bytes, budget_bytes: int,
                   scores=None) -> ResidencyPlan:
    """The residency planner (the tier's budget function, registered in
    rplint's ``KERNEL_BUDGET_FNS``): greedily admit chunks hot in
    descending access-score order (ties to the LOWER ordinal — older
    chunks, deterministic plans) until the next chunk would overflow
    ``budget_bytes``.  ``scores=None`` plans by ordinal alone (the
    append-order admission the constructor uses before any access
    statistics exist).  The two double-buffered staging slots are
    bounded by the largest cold chunk's single row bucket, reported as
    ``staging_bytes``."""
    sizes = [int(b) for b in chunk_bytes]
    if budget_bytes < 0:
        raise ValueError(f"budget_bytes must be >= 0, got {budget_bytes}")
    n = len(sizes)
    sc = [0.0] * n if scores is None else [float(s) for s in scores]
    if len(sc) != n:
        raise ValueError(
            f"scores has {len(sc)} entries for {n} chunks"
        )
    order = sorted(range(n), key=lambda i: (-sc[i], i))
    hot = set()
    hot_bytes = 0
    for i in order:
        if hot_bytes + sizes[i] <= budget_bytes:
            hot.add(i)
            hot_bytes += sizes[i]
    cold_max = max((sizes[i] for i in range(n) if i not in hot), default=0)
    return ResidencyPlan(hot, hot_bytes, budget_bytes, 2 * cold_max)


class _Entry:
    """Per-chunk residency record: the chunk object, its payload bytes,
    whether it is device-resident, its access score, and (disk tier)
    its spill manifest entry."""

    __slots__ = ("chunk", "nbytes", "hot", "score", "spill")

    def __init__(self, chunk, nbytes: int, hot: bool):
        self.chunk = chunk
        self.nbytes = int(nbytes)
        self.hot = bool(hot)
        self.score = 0.0
        self.spill: Optional[dict] = None


class TieredResidency:
    """Hot/cold residency manager for one index's chunk list (module
    docstring has the full story).  Created by ``SimHashIndex`` when
    ``hbm_budget_bytes`` is set; the index funnels every append through
    ``admit``/``place_cold``/``register`` and every serving gather
    through ``note_gather``/``note_fetch``, and calls ``close()`` when
    it is done (joins the background worker)."""

    _SENTINEL = object()

    def __init__(self, budget_bytes: int, *, cold_tier: str = "host",
                 cold_dir: Optional[str] = None,
                 device_put=None):
        if budget_bytes < 0:
            raise ValueError(
                f"hbm_budget_bytes must be >= 0, got {budget_bytes}"
            )
        if cold_tier not in COLD_TIERS:
            raise ValueError(
                f"cold_tier must be one of {COLD_TIERS}, got {cold_tier!r}"
            )
        if cold_tier == "disk":
            if not cold_dir:
                raise ValueError(
                    "cold_tier='disk' requires cold_dir= (the spill "
                    "directory for demoted chunks)"
                )
            os.makedirs(cold_dir, exist_ok=True)
        self.budget_bytes = int(budget_bytes)
        self.cold_tier = cold_tier
        self.cold_dir = cold_dir
        # uploads route through the owning index's placement (pinned
        # device or platform default); None = jnp.asarray
        self._device_put = device_put
        self._lock = threading.Lock()
        self._entries: list = []       # _Entry per chunk, append order
        self._by_row0: dict = {}       # chunk.row0 -> _Entry
        self._hot_bytes = 0
        self._gen = 1                  # spill generation (bumped on reset)
        self._spill_seq = 0
        import queue as _queue

        self._q: "_queue.Queue" = _queue.Queue(maxsize=_MAX_PENDING_OPS + 1)
        self._closed = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- append-time admission ----------------------------------------------

    def admit(self, nbytes: int) -> bool:
        """True when a new chunk of ``nbytes`` payload fits the budget
        alongside the currently hot set (it then uploads exactly like
        an untiered chunk); False routes it cold."""
        with self._lock:
            return self._hot_bytes + int(nbytes) <= self.budget_bytes

    def place_cold(self, codes: np.ndarray) -> np.ndarray:
        """Materialize a cold chunk's backing array: host tier keeps a
        private host copy; disk tier writes the r11-format spill and
        returns the read-only mmap view.  Returns the array to bind as
        ``chunk.b`` (``register`` records the spill entry)."""
        codes = np.ascontiguousarray(codes, dtype=np.uint8)
        if self.cold_tier == "host":
            return codes.copy()
        arr, self._pending_spill = self._spill_to_disk(codes)
        return arr

    def register(self, chunk, nbytes: int, hot: bool) -> None:
        """Record a freshly appended chunk's residency."""
        e = _Entry(chunk, nbytes, hot)
        if not hot and self.cold_tier == "disk":
            e.spill = self.__dict__.pop("_pending_spill", None)
        with self._lock:
            self._entries.append(e)
            self._by_row0[chunk.row0] = e
            if hot:
                self._hot_bytes += e.nbytes
            frac = self._hot_fraction_locked()
        telemetry.registry().gauge_set("index.tier.hot_fraction", frac)

    # -- residency queries (serving path, lock-held briefly) -----------------

    def chunk_is_hot(self, chunk) -> bool:
        with self._lock:
            e = self._by_row0.get(chunk.row0)
            return e is None or e.hot

    def any_cold(self) -> bool:
        with self._lock:
            return any(not e.hot for e in self._entries)

    def residency(self) -> dict:
        """Introspection snapshot: per-chunk tier tags plus byte
        accounting (the manifest block and the smoke assertions read
        this)."""
        with self._lock:
            chunks = [
                {
                    "row0": int(e.chunk.row0),
                    "rows": int(e.chunk.n),
                    "tier": "hot" if e.hot else self.cold_tier,
                }
                for e in self._entries
            ]
            hot_bytes = self._hot_bytes
        return {
            "cold_tier": self.cold_tier,
            "hbm_budget_bytes": self.budget_bytes,
            "hot_bytes": hot_bytes,
            "chunks": chunks,
        }

    def manifest_block(self) -> dict:
        """The ``tier`` manifest block ``durable.save_index`` persists:
        format-versioned so a future layout change fails loudly in old
        readers, carrying the budget, the cold tier tag and per-chunk
        residency at snapshot time (restore re-tiers by its own budget;
        the tags are provenance + verification surface)."""
        r = self.residency()
        return {"tier": {
            "format": 1,
            "cold_tier": r["cold_tier"],
            "hbm_budget_bytes": r["hbm_budget_bytes"],
            "chunks": r["chunks"],
        }}

    def _hot_fraction_locked(self) -> float:
        total = sum(e.nbytes for e in self._entries)
        return (self._hot_bytes / total) if total else 1.0

    # -- access accounting + background rebalance ----------------------------

    def note_gather(self, hot_rows: int, cold_rows: int,
                    per_chunk_rows: dict) -> None:
        """Fold one serving gather into the access statistics: row
        counts per side (the hot-hit signal) and per touched chunk (the
        admission/eviction signal), then re-plan.  ``per_chunk_rows``
        maps ``chunk.row0`` → rows gathered from that chunk."""
        with self._lock:
            for row0, rows in per_chunk_rows.items():
                e = self._by_row0.get(row0)
                if e is not None:
                    e.score += float(rows)
        reg = telemetry.registry()
        reg.counter_inc("index.tier.hot_rows", int(hot_rows))
        reg.counter_inc("index.tier.cold_rows", int(cold_rows))
        if telemetry.enabled():
            telemetry.emit(
                EVENTS.INDEX_TIER_HIT, hot_rows=int(hot_rows),
                cold_rows=int(cold_rows),
                **telemetry.trace_fields(),
            )
        self._maybe_rebalance()

    def note_fetch(self, *, rows: int, nbytes: int, wall_s: float,
                   overlap_s: float, source: str, sync: bool,
                   promote: bool = False) -> None:
        """Record one cold-tier fetch: the host-side gather+stage wall,
        and the overlap window the upload had to hide under the
        hot-tier kernel (0 on a synchronous rung)."""
        reg = telemetry.registry()
        reg.counter_inc("index.tier.fetches")
        reg.observe("index.tier.fetch_s", float(wall_s))
        if overlap_s > 0:
            reg.observe("index.tier.overlap_s", float(overlap_s))
        if telemetry.enabled():
            telemetry.emit(
                EVENTS.INDEX_TIER_FETCH, rows=int(rows),
                bytes=int(nbytes), wall_s=round(float(wall_s), 6),
                overlap_s=round(float(overlap_s), 6), source=source,
                sync=bool(sync), promote=bool(promote),
                **telemetry.trace_fields(),
            )

    def note_fallback(self, reason: str, *, rows: int = 0) -> None:
        """The degraded rung: residency pressure or a failed staging
        upload served synchronously — on the doctor's degraded audit,
        like every other ladder rung in this repo."""
        telemetry.registry().counter_inc("index.tier.fallbacks")
        telemetry.emit(
            EVENTS.INDEX_TIER_FALLBACK, reason=reason, rows=int(rows),
            **telemetry.trace_fields(),
        )

    def demote(self, row0: int) -> bool:
        """Synchronously demote one chunk by its first global row id —
        the maintenance/fault-harness surface (the serving path demotes
        in the background instead).  Returns True when the chunk was
        hot and is now cold."""
        with self._lock:
            e = self._by_row0.get(row0)
        if e is None or not e.hot:
            return False
        self._demote(e)
        return not e.hot

    def _maybe_rebalance(self) -> None:
        """Re-plan residency from the current scores and enqueue the
        diff as bounded background work.  Planning is O(chunks·log) on
        the serving thread; the byte movement happens on the worker.
        A full queue drops the rebalance (the next access re-plans) —
        background work stays bounded, never a backlog."""
        with self._lock:
            if self._closed.is_set():
                return
            sizes = [e.nbytes for e in self._entries]
            scores = [
                # current residency wins exact ties: no ping-pong churn
                # between equal-score chunks
                e.score + (0.5 if e.hot else 0.0)
                for e in self._entries
            ]
            plan = plan_residency(sizes, self.budget_bytes, scores)
            ops = [
                ("promote" if i in plan.hot else "demote", e)
                for i, e in enumerate(self._entries)
                if (i in plan.hot) != e.hot
            ]
            if not ops:
                return
            start_worker = self._thread is None
            if start_worker:
                from threading import Thread

                self._thread = Thread(
                    target=self._run, name="rp-tier-worker", daemon=True
                )
        # enqueue OUTSIDE the lock (RP11: a queue put never runs under
        # a held lock); put_nowait + qsize bound keeps the sentinel slot
        # free and the backlog at _MAX_PENDING_OPS
        for op in ops:
            if self._q.qsize() >= _MAX_PENDING_OPS:
                telemetry.registry().counter_inc("index.tier.rebalance_drops")
                break
            self._q.put_nowait(op)
        if start_worker:
            self._thread.start()

    # -- the background worker ----------------------------------------------

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is self._SENTINEL:
                return
            op, entry = item
            try:
                if op == "promote":
                    # the promotion d2h/H2D runs on THIS dedicated
                    # background worker, off every serving thread:
                    # blocking here is the design
                    self._promote(entry)  # rplint: allow[RP09] — background worker owns the blocking byte movement
                else:
                    # same: the demotion's host copy is the background
                    # work itself, not a hidden sync on a serving loop
                    self._demote(entry)  # rplint: allow[RP09] — background worker owns the blocking byte movement
            except Exception as e:
                # a failed byte movement degrades residency, never the
                # index: the chunk simply stays where it was (every
                # serving path handles either residency), recorded on
                # the degraded audit
                self.note_fallback(f"{op}_failed:{type(e).__name__}")

    def _promote(self, entry: _Entry) -> None:
        chunk = entry.chunk
        with self._lock:
            if entry.hot or self._closed.is_set():
                return
            if self._hot_bytes + entry.nbytes > self.budget_bytes:
                return  # plan went stale; the next access re-plans
            b = chunk.b
        t0 = time.perf_counter()
        host = np.ascontiguousarray(np.asarray(b))
        dev = (self._device_put(host) if self._device_put is not None
               else self._jnp_asarray(host))
        spill = None
        with self._lock:
            if entry.hot:
                return
            chunk.b = dev
            entry.hot = True
            self._hot_bytes += entry.nbytes
            spill, entry.spill = entry.spill, None
            frac = self._hot_fraction_locked()
        if spill is not None and self.cold_dir:
            try:
                os.unlink(os.path.join(self.cold_dir, spill["file"]))
            except OSError:
                pass  # a leftover spill is debris, not corruption
        reg = telemetry.registry()
        reg.counter_inc("index.tier.promotions")
        reg.gauge_set("index.tier.hot_fraction", frac)
        self.note_fetch(
            rows=int(chunk.n), nbytes=entry.nbytes,
            wall_s=time.perf_counter() - t0, overlap_s=0.0,
            source=self.cold_tier, sync=False, promote=True,
        )

    def _demote(self, entry: _Entry) -> None:
        from randomprojection_tpu import durable
        from randomprojection_tpu.models.sketch import _start_host_copy

        chunk = entry.chunk
        with self._lock:
            if not entry.hot or self._closed.is_set():
                return
            b = chunk.b
        t0 = time.perf_counter()
        _start_host_copy(b)
        host = np.ascontiguousarray(np.asarray(b)[: chunk.n])
        if self.cold_tier == "disk":
            arr, spill = self._spill_to_disk(host)
            # fault-injection point: the spill file exists but the
            # residency swap (and any manifest that would reference the
            # demotion) has not happened — a SIGKILL here must leave a
            # loadable snapshot with the file as sweepable debris
            durable._maybe_kill("mid-demotion")
        else:
            arr, spill = host, None
        with self._lock:
            if not entry.hot:
                return
            chunk.b = arr
            chunk.dead_dev = None   # device-resident mask goes with b
            chunk.dead_rev = -1
            entry.hot = False
            entry.spill = spill
            self._hot_bytes -= entry.nbytes
            frac = self._hot_fraction_locked()
        reg = telemetry.registry()
        reg.counter_inc("index.tier.evictions")
        reg.gauge_set("index.tier.hot_fraction", frac)
        telemetry.emit(
            EVENTS.INDEX_TIER_EVICT, rows=int(chunk.n),
            bytes=entry.nbytes, tier=self.cold_tier,
            wall_s=round(time.perf_counter() - t0, 6),
            **telemetry.trace_fields(),
        )

    def _spill_to_disk(self, codes: np.ndarray):
        """Write one cold chunk in the r11 spill format (atomic,
        checksummed, generation-numbered) and return ``(mmap_view,
        manifest_entry)``.  The write-back is verified by re-reading
        and re-hashing — a demotion must never trade a good device copy
        for a corrupt disk one."""
        from randomprojection_tpu import durable

        with self._lock:
            gen, seq = self._gen, self._spill_seq
            self._spill_seq += 1
        fname = f"chunk-{gen:06d}-{seq:08d}.npy"
        path = os.path.join(self.cold_dir, fname)
        sha = durable._sha256(codes)
        durable._write_npy_atomic(path, codes)
        arr = np.load(path, mmap_mode="r")
        if durable._sha256(np.asarray(arr)) != sha:
            raise ValueError(
                f"cold-tier spill {path} failed read-back verification"
            )
        entry = {"file": fname, "rows": int(codes.shape[0]), "sha256": sha}
        return arr, entry

    def _jnp_asarray(self, host):
        import jax.numpy as jnp

        return jnp.asarray(host)

    # -- lifecycle -----------------------------------------------------------

    def close(self, timeout: float = 10.0) -> None:
        """Stop the background worker (idempotent): mark closed, send
        the sentinel, join.  In-flight promotions/demotions finish;
        queued ones re-check the closed flag and no-op."""
        if self._closed.is_set():
            return
        self._closed.set()
        # the sentinel's slot is reserved by construction (queue holds
        # _MAX_PENDING_OPS + 1; producers stop at _MAX_PENDING_OPS) and
        # close() runs after _closed is set, so no producer races it in;
        # enqueued unconditionally — a worker started between the flag
        # and the join still drains to the sentinel and exits
        self._q.put(self._SENTINEL)
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():  # pragma: no cover — wedged put
                telemetry.registry().counter_inc("index.tier.close_timeouts")

    def reset(self) -> None:
        """Forget every chunk (compaction/rebuild path — the caller
        guarantees quiescence, as ``compact`` already documents) and
        unlink this manager's spill files; the rebuild re-registers the
        new chunks under a fresh spill generation."""
        with self._lock:
            spills = [e.spill for e in self._entries if e.spill]
            self._entries = []
            self._by_row0 = {}
            self._hot_bytes = 0
            self._gen += 1
            self._spill_seq = 0
        for spill in spills:
            try:
                os.unlink(os.path.join(self.cold_dir, spill["file"]))
            except OSError:
                pass  # debris, swept by the durable orphan scan


class _TileStager:
    """Double-buffered cold-chunk staging for the EXACT serving path
    (``SimHashIndex._topk_dispatch_tile``): ``resolve(i)`` returns the
    device array to score chunk ``i`` with (``None`` = the chunk is hot,
    use its resident handle) and starts the NEXT cold chunk's
    asynchronous upload before returning, so that transfer streams
    under chunk ``i``'s kernel — the in-kernel DMA double-buffering
    idiom applied at the tier boundary.  At most two staged buffers
    exist at once (one being consumed, one in flight): bounded
    transient HBM, sized by ``ResidencyPlan.staging_bytes``.  A failed
    upload degrades to dispatching the chunk's host array directly (jax
    commits it synchronously) on the degraded audit — never a wrong
    answer.  One stager serves one dispatched tile on one thread; the
    residency manager outlives it."""

    def __init__(self, chunks, tier: TieredResidency, device_put):
        self._chunks = chunks
        self._tier = tier
        self._put = device_put
        self._cold = [
            i for i, c in enumerate(chunks) if not tier.chunk_is_hot(c)
        ]
        self._staged: dict = {}  # ordinal -> (array, wall_s, t_started)
        self._hot_rows = 0
        self._cold_rows = 0
        self._per_chunk: dict = {}

    def _stage(self, i: int) -> None:
        if i in self._staged or len(self._staged) >= 2:
            return
        c = self._chunks[i]
        t0 = time.perf_counter()
        # np.asarray is the actual cold fetch: a host copy reads RAM, a
        # disk-tier memmap reads only this chunk's pages
        host = np.ascontiguousarray(np.asarray(c.b))
        try:
            dev = self._put(host)
        except Exception as e:
            self._tier.note_fallback(
                f"upload:{type(e).__name__}", rows=int(c.n)
            )
            dev = host  # degraded rung: sync upload at dispatch
        self._staged[i] = (dev, time.perf_counter() - t0,
                           time.perf_counter())

    def resolve(self, i: int):
        c = self._chunks[i]
        if self._tier.chunk_is_hot(c):
            self._hot_rows += int(c.n)
            b = None
        else:
            ent = self._staged.pop(i, None)
            prestaged = ent is not None
            if not prestaged:
                self._stage(i)
                ent = self._staged.pop(i)
            b, wall_s, t_started = ent
            overlap = (time.perf_counter() - t_started) if prestaged else 0.0
            self._cold_rows += int(c.n)
            self._tier.note_fetch(
                rows=int(c.n), nbytes=int(c.n) * int(c.b.shape[1]),
                wall_s=wall_s, overlap_s=overlap,
                source=self._tier.cold_tier, sync=not prestaged,
            )
        self._per_chunk[c.row0] = self._per_chunk.get(c.row0, 0) + int(c.n)
        # start the next cold chunk's upload BEFORE this chunk's kernel
        # dispatches — that H2D rides under the kernel's compute
        for j in self._cold:
            if j > i and j not in self._staged:
                # _stage's asarray is the host-side read of an
                # already-host (or memmap) chunk feeding an ASYNC
                # device_put: this call site IS the overlapped
                # prefetch the rule asks for, one chunk ahead
                self._stage(j)  # rplint: allow[RP09] — this call IS the one-ahead overlapped prefetch
                break
        return b

    def finish(self, queries: int) -> None:
        """Fold this tile's access pattern into the residency manager
        (the admission/eviction signal) once the dispatch loop is done."""
        self._tier.note_gather(
            self._hot_rows, self._cold_rows, self._per_chunk
        )
