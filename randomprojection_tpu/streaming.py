"""Streamed row-batch transform (layer L2) with checkpoint/resume.

The reference feeds ``transform`` through a "streamed row-batch iterator"
so datasets larger than memory can be projected (``BASELINE.json:5``;
SURVEY.md §2 L2, §4.5).  TPU-native design:

- **Seekable sources.**  A ``RowBatchSource`` yields fixed-size row batches
  *starting from any row offset*.  Fixed batch size ⇒ one XLA program for
  the whole stream (the ragged tail reuses the backend's row-bucketing).
- **Cursor checkpointing / elastic recovery** (SURVEY.md §6): progress is
  just ``rows_done``.  The projection matrix is derived from the seed and
  batches are pure functions of their row range, so a failed run resumed
  from its cursor produces **bit-identical** output — restart-from-cursor
  is the whole failure-recovery story, verified by fault-injection tests.
- **Double buffering**: with the jax backend, batch ``i+1`` is dispatched
  (host→HBM copy + einsum) while batch ``i``'s result is still being
  fetched — JAX's async dispatch overlaps them as long as we don't force
  materialization too early.  ``pipeline_depth`` bounds device memory
  (depth × batch bytes).
- **Async ingest** (the r5 perf finding: ``stream_transform`` consumed
  ``TokenSource`` synchronously, so murmur3 hashing, H2D transfer and
  device dispatch all serialized on one thread — the end-to-end config-5
  number ran ~4.5× slower than host hashing alone).  ``PrefetchSource``
  wraps any source with a bounded queue fed by a background worker thread:
  source production (including ``TokenSource``'s per-batch hash) and an
  optional ``prepare`` step (early ``jax.device_put`` of the batch, so H2D
  overlaps device compute) run OFF the consumer thread.  The cursor
  contract is untouched — prefetch changes *when batches are produced*,
  never when they are committed, so ``rows_done`` still advances only
  after the consumer has processed the yielded batch (ack-after-yield),
  and a resume recomputes any batch that was prefetched but never
  consumed.
- **Staged multi-worker ingest** (r9): ``StagedIngestSource`` splits the
  single prefetch worker into a POOL of hash workers (disjoint batches,
  reassembled in row order — bit-identical to serial) feeding a dedicated
  prep/H2D uploader stage through bounded queues; the cursor contract,
  deterministic shutdown and trace-root propagation hold across every
  stage boundary.  CLI: ``--ingest-workers N``.
- **Per-batch tracing** (r8): when a telemetry sink is configured
  (``--telemetry-jsonl``), every batch carries one trace — a root span
  created where production starts (the prefetch worker, for an
  overlapped pipeline) whose child spans cover hash, enqueue-wait, H2D,
  dispatch and d2h across both threads.  ``iter_traced`` is the
  protocol; ``utils/trace_report.py`` (surfaced as ``cli doctor``)
  rebuilds per-batch critical-path attribution from the span stream.
"""

from __future__ import annotations

import dataclasses
import json
import numbers
import os
import queue
import threading
from typing import Callable, Iterator, Optional, Tuple

import numpy as np
import scipy.sparse as sp

from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS
from randomprojection_tpu.utils.observability import (
    annotate,
    batch_nbytes,
    stage as _stage,
)

__all__ = [
    "RowBatchSource",
    "ArraySource",
    "CallableSource",
    "FaultInjectionSource",
    "TokenSource",
    "PrefetchSource",
    "StagedIngestSource",
    "StreamCursor",
    "iter_traced",
    "stream_transform",
    "stream_to_array",
    "stream_to_memmap",
]


def _fsync_dir(dirpath: str) -> None:
    """fsync a DIRECTORY so a just-``os.replace``'d entry survives a
    machine crash, not only a process crash — POSIX persists the rename
    itself only once the directory inode reaches disk.  Best-effort on
    filesystems/platforms that refuse to fsync directories (the rename
    is still process-crash-atomic there)."""
    try:
        fd = os.open(dirpath or ".", os.O_RDONLY)
    except OSError:  # pragma: no cover — unopenable dir (exotic fs)
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover — fs refuses directory fsync
        pass
    finally:
        os.close(fd)


def _batch_rows(batch, default: Optional[int] = None) -> Optional[int]:
    """Row count of one in-flight batch, tolerant of prepared operands.

    ``prepare_batch`` hooks may replace the raw batch with a device-side
    carrier (``models.sketch.DeviceBatch`` mirrors ``.shape``; future
    carriers may not, or may expose a 0-d / symbolic shape) — a bare
    ``batch.shape[0]`` then crashes the stream or, worse, records a wrong
    row count into telemetry the doctor treats as truth.  Resolution
    order: a real leading ``shape`` dimension, then a ``DeviceBatch``-
    style integral ``.n``, then ``default``.
    """
    shape = getattr(batch, "shape", None)
    if shape is not None:
        try:
            return int(shape[0])
        except (TypeError, IndexError, ValueError):
            pass
    n = getattr(batch, "n", None)
    if isinstance(n, numbers.Integral):
        return int(n)
    return default


def iter_traced(source, start_row: int = 0):
    """Iterate a source as ``(start_row, batch, trace_root)`` triples —
    the tracing-aware face of ``iter_batches``.

    Every batch gets ONE trace: a root span named ``batch`` opened when
    production of that batch begins and closed by whoever finishes the
    batch's lifecycle (``stream_transform`` ends it at commit; the plain
    ``iter_batches`` wrappers end it when the consumer's loop body
    returns).  Production runs with the root activated on the producing
    thread, so instrumented stages inside the source (``TokenSource``'s
    hash) emit correctly-parented child spans.  Sources that own a
    producer thread implement ``iter_batches_traced`` (see
    ``PrefetchSource``) and are deferred to — the root then travels
    explicitly from the worker thread through the queue.  With no
    telemetry sink installed the roots are all None and this wrapper is
    overhead-free.
    """
    traced = getattr(source, "iter_batches_traced", None)
    if traced is not None:
        yield from traced(start_row)
        return
    it = source.iter_batches(start_row)
    try:
        while True:
            root = telemetry.start_span("batch", new_trace=True)
            try:
                with telemetry.activate_span(root):
                    try:
                        item = next(it)
                    except StopIteration:
                        # production began but there was no next batch:
                        # close the root as empty, not as an orphan
                        telemetry.end_span(root, empty=True)
                        return
            except BaseException:
                telemetry.end_span(root, error=True)
                raise
            yield item[0], item[1], root
    finally:
        close = getattr(it, "close", None)
        if close is not None:
            close()


def _check_start_row(start_row: int, batch_rows: int, n_rows: int) -> None:
    """Resume offsets must land on a batch boundary — or be the end of the
    stream (a completed run's cursor equals n_rows, and re-running it must
    yield nothing, not raise)."""
    if start_row == n_rows:
        return
    if start_row % batch_rows:
        raise ValueError(
            f"start_row={start_row} must be a multiple of batch_rows="
            f"{batch_rows} or n_rows={n_rows} (cursors always are)"
        )


class RowBatchSource:
    """Protocol: a seekable, schema-bearing stream of row batches.

    Subclasses provide ``n_rows``, ``n_features``, ``dtype`` and
    ``iter_batches(start_row)`` yielding ``(start_row, batch)`` pairs where
    every batch has ``batch_rows`` rows except possibly the last.  Seeking
    by row is what makes resume exact: a resumed stream re-yields the same
    batches with the same row offsets.
    """

    batch_rows: int
    n_rows: int
    n_features: int
    dtype: np.dtype

    def iter_batches(self, start_row: int = 0) -> Iterator[Tuple[int, np.ndarray]]:
        raise NotImplementedError

    def schema(self) -> Tuple[int, int, np.dtype]:
        """(n_rows, n_features, dtype) — all that fit() needs (SURVEY.md §4.1)."""
        return self.n_rows, self.n_features, self.dtype


class ArraySource(RowBatchSource):
    """In-memory ndarray/CSR source — slicing is the seek."""

    def __init__(self, X, batch_rows: int = 65536):
        if batch_rows <= 0:
            raise ValueError(f"batch_rows must be positive, got {batch_rows}")
        if not sp.issparse(X):
            X = np.asarray(X)
        if X.ndim != 2:
            raise ValueError(f"Expected 2D input, got shape {getattr(X, 'shape', None)}")
        self._X = X
        self.batch_rows = batch_rows
        self.n_rows, self.n_features = X.shape
        self.dtype = X.dtype

    def iter_batches(self, start_row: int = 0):
        _check_start_row(start_row, self.batch_rows, self.n_rows)
        for lo in range(start_row, self.n_rows, self.batch_rows):
            hi = min(lo + self.batch_rows, self.n_rows)
            yield lo, self._X[lo:hi]


class CallableSource(RowBatchSource):
    """Out-of-core source: ``read(lo, hi) -> (hi-lo, d) array``.

    The callable abstracts any seekable storage (memory-mapped file, object
    store with range reads, database pagination).  It must be deterministic
    in ``(lo, hi)`` for resume to be exact.
    """

    def __init__(self, read: Callable[[int, int], np.ndarray], n_rows: int,
                 n_features: int, dtype=np.float32, batch_rows: int = 65536):
        if batch_rows <= 0:
            raise ValueError(f"batch_rows must be positive, got {batch_rows}")
        self._read = read
        self.batch_rows = batch_rows
        self.n_rows = n_rows
        self.n_features = n_features
        self.dtype = np.dtype(dtype)

    def iter_batches(self, start_row: int = 0):
        _check_start_row(start_row, self.batch_rows, self.n_rows)
        for lo in range(start_row, self.n_rows, self.batch_rows):
            hi = min(lo + self.batch_rows, self.n_rows)
            batch = self._read(lo, hi)
            if batch.shape != (hi - lo, self.n_features):
                raise ValueError(
                    f"Source returned shape {batch.shape} for rows [{lo},{hi}); "
                    f"expected {(hi - lo, self.n_features)}"
                )
            yield lo, batch


class TokenSource(RowBatchSource):
    """Raw-token documents → hashed CSR batches (the config-5 pipeline,
    BL:11 "streaming TF-IDF"; the hashing role sklearn implements in
    ``feature_extraction/_hashing_fast.pyx``, here the C++ murmur3 batch
    kernel feeding the device sketch).

    ``read_tokens(lo, hi)`` returns the tokens of documents ``[lo, hi)`` as
    ``(tokens, indptr)`` or ``(tokens, indptr, values)`` — ``tokens`` a flat
    array/sequence, ``indptr`` LOCAL row pointers of length ``hi-lo+1``
    (``indptr[0] == 0``).  Each batch is hashed by ``hasher``
    (``ops.hashing.FeatureHasher``) into a CSR that downstream estimators
    consume — composed with ``CountSketch.transform_stream`` this is
    tokens → murmur3 (C++) → device gather/scatter sketch, one pipeline,
    checkpoint/resume included (the cursor is rows of documents; resume
    re-hashes from the document boundary, which is exact because
    ``read_tokens`` is deterministic in ``(lo, hi)``).

    ``hash_threads`` opts the per-batch hash into the C++ kernel's
    thread-parallel path (``native/murmur3.cpp``): the output is
    bit-identical at any worker count — token i's hash depends only on
    token i — so this is purely a wall-clock knob.  ``None`` keeps the
    ambient ``RP_HASH_THREADS``/hardware default.  ``stats`` (a
    ``StreamStats``) attributes the hash wall to the ``'hash'`` stage;
    composed with ``PrefetchSource`` the hash then runs on the worker
    thread, overlapping device compute.
    """

    def __init__(self, read_tokens: Callable, n_rows: int, hasher,
                 batch_rows: int = 65536, *, hash_threads: Optional[int] = None,
                 stats=None):
        if batch_rows <= 0:
            raise ValueError(f"batch_rows must be positive, got {batch_rows}")
        if hash_threads is not None and int(hash_threads) < 1:
            raise ValueError(
                f"hash_threads must be >= 1 or None, got {hash_threads!r}"
            )
        self._read_tokens = read_tokens
        self.hasher = hasher
        self.batch_rows = batch_rows
        self.n_rows = n_rows
        self.n_features = hasher.n_features
        self.dtype = np.dtype(hasher.dtype)
        self.hash_threads = hash_threads
        self.stats = stats

    def iter_batches(self, start_row: int = 0):
        from randomprojection_tpu.ops.hashing import hash_threads_override

        _check_start_row(start_row, self.batch_rows, self.n_rows)
        for lo in range(start_row, self.n_rows, self.batch_rows):
            hi = min(lo + self.batch_rows, self.n_rows)
            out = self._read_tokens(lo, hi)
            tokens, indptr = out[0], out[1]
            values = out[2] if len(out) > 2 else None
            with annotate("rp:stream/hash_tokens"), \
                    _stage(self.stats, "hash"), \
                    hash_threads_override(self.hash_threads):
                batch = self.hasher.transform_tokens(tokens, indptr, values)
            if batch.shape != (hi - lo, self.n_features):
                raise ValueError(
                    f"read_tokens produced a {batch.shape} batch for rows "
                    f"[{lo},{hi}); expected {(hi - lo, self.n_features)} — "
                    "indptr must be local with indptr[0]=0"
                )
            yield lo, batch


class FaultInjectionSource(RowBatchSource):
    """Test wrapper: raises at the ``fail_after_batches``-th GLOBAL batch.

    The SURVEY.md §6 fault-injection harness: crash a stream mid-flight,
    resume from the checkpoint cursor, assert bit-identical output.

    The fault fires on the batch's global index (``lo // batch_rows``),
    not on a per-iterator yield count: a staged ingest pool opens one
    short iteration per batch (``StagedIngestSource``), so counting yields
    per iterator would never reach the threshold there.  For a full serial
    pass from row 0 — every shipped armed usage — the two rules pick the
    identical batch.
    """

    class InjectedFault(RuntimeError):
        pass

    def __init__(self, inner: RowBatchSource, fail_after_batches: int):
        self._inner = inner
        self.fail_after_batches = fail_after_batches
        self.batch_rows = inner.batch_rows
        self.n_rows = inner.n_rows
        self.n_features = inner.n_features
        self.dtype = inner.dtype
        self._armed = True

    def disarm(self):
        self._armed = False

    def iter_batches(self, start_row: int = 0):
        for lo, batch in self._inner.iter_batches(start_row):
            if self._armed and lo // self.batch_rows >= self.fail_after_batches:
                raise self.InjectedFault(
                    f"injected fault before batch {lo // self.batch_rows} "
                    f"(row {lo})"
                )
            yield lo, batch


class PrefetchSource(RowBatchSource):
    """Asynchronous producer stage: run ``inner.iter_batches`` (and an
    optional ``prepare`` step) on a background worker thread, feeding the
    consumer through a bounded queue.

    This is the overlapped-ingest pipeline (the r5 perf item): with a
    ``TokenSource`` inner, murmur3 hashing of batch ``i+1`` runs while the
    consumer dispatches/fetches batch ``i``; with ``prepare=
    estimator.prepare_batch``, the H2D upload of batch ``i+1`` is also
    issued from the worker, so by dispatch time the batch is already
    device-resident (H2D overlaps device compute instead of sitting in the
    dispatch path).

    Contract:

    - **Ordering** is the inner source's (one worker, FIFO queue).
    - **Cursor safety**: prefetch advances only *production*.  Commit
      (``StreamCursor``) stays with the consumer's ack-after-yield in
      ``stream_transform``; a batch hashed/uploaded ahead but never
      consumed is simply recomputed on resume (``iter_batches(start_row)``
      seeks the inner source, exactly like a fresh run).
    - **Exception propagation**: a worker-thread failure (source read,
      hash, prepare) is re-raised in the consumer *after* the batches
      produced before it — the same prefix-then-raise behavior a serial
      iteration of the failing source gives, so fault-injection/resume
      semantics are unchanged.
    - **Clean shutdown**: closing the generator (consumer ``break``,
      exception, or GC) stops and joins the worker; no thread outlives the
      iteration.  ``depth`` bounds host memory at ``depth + 1`` produced
      batches (queue plus the one in the worker's hands).

    ``stats`` (a ``StreamStats``) records the ``'h2d'`` stage wall for
    ``prepare`` and a queue-occupancy gauge sampled by the producer at
    each delivery: max 0 means producer-bound (the consumer always had
    the queue drained), ``depth`` means the queue was full and the
    producer had to wait (consumer-bound).
    """

    _DONE = object()  # worker sentinel: inner iterator exhausted
    _POLL_S = 0.05  # put/get poll so shutdown never deadlocks on a full/empty queue

    def __init__(self, inner: RowBatchSource, *, depth: int = 2,
                 prepare: Optional[Callable] = None, stats=None):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._inner = inner
        self.depth = depth
        self.prepare = prepare
        self.stats = stats
        self.batch_rows = inner.batch_rows
        self.n_rows = inner.n_rows
        self.n_features = inner.n_features
        self.dtype = inner.dtype

    def iter_batches(self, start_row: int = 0):
        it = self.iter_batches_traced(start_row)
        try:
            for lo, batch, root in it:
                try:
                    yield lo, batch
                finally:
                    # direct (untraced) consumers end the batch trace when
                    # their loop body returns; stream_transform consumes
                    # the traced face instead and ends roots at commit
                    telemetry.end_span(root, row=int(lo))
        finally:
            it.close()

    def iter_batches_traced(self, start_row: int = 0):
        """``iter_traced`` face: ``(lo, batch, trace_root)`` triples.

        The batch's trace root is created ON THE WORKER THREAD when
        production begins (so the inner source's hash span parents
        correctly), carried through the queue, and handed to the
        consumer — the explicit cross-thread propagation contract.  The
        caller owns ending the root.  Worker-side child spans: the
        inner production stages (via ``iter_traced``'s activation),
        ``h2d`` for the prepare step, and ``enqueue_wait`` for time the
        producer spent waiting for queue space (consumer-bound time —
        deliberately NOT a ``StreamStats`` stage: it is idle, not work,
        and must not inflate the overlap ratio's denominator).
        """
        q: queue.Queue = queue.Queue(maxsize=self.depth)
        stop = threading.Event()

        def _put(item) -> bool:
            # bounded put that notices shutdown: never blocks forever on a
            # queue the consumer stopped draining
            while not stop.is_set():
                try:
                    q.put(item, timeout=self._POLL_S)
                    return True
                except queue.Full:
                    pass
            return False

        def work():
            try:
                produced = iter_traced(self._inner, start_row)
                try:
                    for lo, batch, root in produced:
                        if self.prepare is not None:
                            with telemetry.activate_span(root), \
                                    _stage(self.stats, "h2d"):
                                batch = self.prepare(batch)
                        depth_now = q.qsize()
                        if self.stats is not None:
                            # occupancy the producer found at delivery: 0 =
                            # the consumer had drained the queue (producer-
                            # bound), depth = full, the producer must wait
                            # (consumer-bound)
                            self.stats.on_queue_depth(depth_now)
                        # live plane (r17): mirror the depth onto the
                        # PROCESS registry so a --metrics-port scrape
                        # sees it without a StreamStats wiring
                        telemetry.registry().gauge_set(
                            "stream.queue.depth", depth_now
                        )
                        telemetry.emit(
                            EVENTS.STREAM_PREFETCH_DELIVER, row=int(lo),
                            queue_depth=int(depth_now), capacity=self.depth,
                            **(
                                {"trace_id": root.trace_id}
                                if root is not None else {}
                            ),
                        )
                        with telemetry.span(
                            "enqueue_wait", parent=root, require_parent=True,
                        ):
                            delivered = _put((lo, batch, root))
                        if not delivered:
                            # consumer went away; close the in-flight trace
                            telemetry.end_span(root, abandoned=True)
                            return
                finally:
                    produced.close()
                _put(self._DONE)
            except BaseException as e:  # propagate to the consumer thread
                telemetry.emit(EVENTS.STREAM_PREFETCH_ERROR, error=repr(e))
                _put((self._DONE, e))

        worker = threading.Thread(
            target=work, name="rp-prefetch-worker", daemon=True
        )
        worker.start()
        try:
            while True:
                # poll so a worker that died without posting (e.g. killed
                # interpreter teardown) cannot hang the consumer
                try:
                    item = q.get(timeout=self._POLL_S)
                except queue.Empty:
                    if worker.is_alive():
                        continue
                    try:  # the worker may have posted right before exiting
                        item = q.get_nowait()
                    except queue.Empty:
                        raise RuntimeError(
                            "prefetch worker died without a result"
                        ) from None
                if item is self._DONE:
                    return
                if isinstance(item, tuple) and item[0] is self._DONE:
                    raise item[1]
                yield item
        finally:
            stop.set()
            # bounded join: a worker stuck inside the inner source's read
            # (stalled socket/pipe) or a hung prepare() never reaches the
            # stop-aware _put, and an unbounded join would hang the
            # CONSUMER on abandon.  The thread is a daemon, so timing out
            # leaks nothing past interpreter exit — but it is an anomaly
            # worth recording loudly.
            worker.join(timeout=5.0)
            # batches produced into the queue but never handed to the
            # consumer: close their traces as abandoned (resume recomputes
            # them) so an abandoned stream leaves no orphan spans
            while True:
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    break
                if isinstance(item, tuple) and len(item) == 3:
                    telemetry.end_span(item[2], row=int(item[0]),
                                       abandoned=True)
            if worker.is_alive():  # pragma: no cover — needs a hung read
                from randomprojection_tpu.utils.observability import logger

                logger.warning(
                    "prefetch worker did not stop within 5s of shutdown "
                    "(inner source read or prepare() appears hung); "
                    "abandoning the daemon thread"
                )
                telemetry.emit(EVENTS.STREAM_PREFETCH_SHUTDOWN_TIMEOUT)


class StagedIngestSource(RowBatchSource):
    """Staged multi-worker ingest: a POOL of hash workers producing
    disjoint batches, reassembled in row order, feeding a dedicated
    prep/H2D uploader stage through bounded queues.

    ``PrefetchSource`` (r6) moved production off the consumer thread but
    kept it on ONE worker: hash, CSR build and the prepare/H2D step all
    serialize there, so the pipeline tops out at that single thread's
    rate (r05: ~22% of the slowest stage's cap).  This source splits the
    pipeline into stages:

    - **hash pool** — ``workers`` threads; worker ``w`` owns batch
      indices ``w, w+N, w+2N, …`` and produces each by seeking the inner
      source (``iter_batches(lo)``, first batch only).  Output is
      **bit-identical to serial** because every shipped source is a pure
      function of its row range ``(lo, hi)`` — the same determinism the
      cursor-resume contract already requires.  ``TokenSource`` workers
      reuse the ``hash_threads`` murmur3 machinery one level up: each
      worker hashes its own batches (pin ``hash_threads=1`` per worker
      and let the pool supply the parallelism — or combine both knobs).
    - **uploader** — one thread reassembling the workers' outputs in
      batch order (worker queues are drained round-robin by index, so
      ordering is deterministic, not racy) and running the optional
      ``prepare`` step (early H2D) before delivering into the final
      bounded queue the consumer drains.

    Contract (same as ``PrefetchSource``, held across every stage
    boundary):

    - **Ordering**: batches reach the consumer in row order.
    - **Cursor safety**: the pool advances only *production*; commit
      stays with the consumer's ack-after-yield in ``stream_transform``.
      Batches produced ahead but never consumed are recomputed on
      resume.
    - **Exception propagation**: a failure producing (or preparing)
      batch ``i`` reaches the consumer *after* batches ``0..i-1`` — the
      serial prefix-then-raise behavior, so fault-injection/resume
      semantics are unchanged.
    - **Deterministic shutdown**: closing the generator (``break``,
      exception, GC) stops and joins every stage thread; queued-ahead
      batches close their traces as ``abandoned``.
    - **Tracing**: each batch's trace root is created on the hash worker
      that produces it (r8 protocol), travels through both queues, and
      is ended by the consumer at commit — ``h2d`` (uploader) and
      ``dispatch``/``d2h`` (consumer) spans join it across threads.

    The inner source must be seekable, deterministic in ``(lo, hi)``
    and safe for **concurrent** iteration from multiple threads (all
    shipped sources are; a custom ``CallableSource``/``TokenSource``
    reader must not share unsynchronized mutable state).  Host memory is
    bounded by ``~2·workers + depth + 1`` produced batches.
    """

    _DONE = object()
    _POLL_S = 0.05  # stop-aware put/get poll (see PrefetchSource)

    def __init__(self, inner: RowBatchSource, *, workers: int = 2,
                 depth: int = 2, prepare: Optional[Callable] = None,
                 stats=None):
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._inner = inner
        self.workers = workers
        self.depth = depth
        self.prepare = prepare
        self.stats = stats
        self.batch_rows = inner.batch_rows
        self.n_rows = inner.n_rows
        self.n_features = inner.n_features
        self.dtype = inner.dtype

    def iter_batches(self, start_row: int = 0):
        it = self.iter_batches_traced(start_row)
        try:
            for lo, batch, root in it:
                try:
                    yield lo, batch
                finally:
                    telemetry.end_span(root, row=int(lo))
        finally:
            it.close()

    def _produce_one(self, lo: int):
        """Produce the single batch starting at ``lo`` with its trace
        root opened on THIS (worker) thread, so the inner source's
        instrumented stages (TokenSource's hash) parent correctly."""
        root = telemetry.start_span("batch", new_trace=True)
        try:
            with telemetry.activate_span(root):
                it = self._inner.iter_batches(lo)
                try:
                    try:
                        got_lo, batch = next(it)
                    except StopIteration:
                        raise RuntimeError(
                            f"inner source yielded no batch at row {lo}"
                        ) from None
                finally:
                    close = getattr(it, "close", None)
                    if close is not None:
                        close()
            if got_lo != lo:
                raise RuntimeError(
                    f"inner source yielded row {got_lo}, expected {lo} "
                    "(seekable-source contract violation)"
                )
        except BaseException:
            telemetry.end_span(root, error=True)
            raise
        return batch, root

    def iter_batches_traced(self, start_row: int = 0):
        """``iter_traced`` face: ``(lo, batch, trace_root)`` in row order.
        The caller owns ending each root (``stream_transform`` ends them
        at commit)."""
        _check_start_row(start_row, self.batch_rows, self.n_rows)
        remaining = max(self.n_rows - start_row, 0)
        n_batches = -(-remaining // self.batch_rows) if remaining else 0
        n_workers = max(1, min(self.workers, n_batches or 1))
        # worker queues are tiny (each worker runs at most ~2 batches
        # ahead); the final queue carries the consumer-facing depth
        worker_qs = [queue.Queue(maxsize=1) for _ in range(n_workers)]
        out_q: queue.Queue = queue.Queue(maxsize=self.depth)
        stop = threading.Event()

        def _put(q: queue.Queue, item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=self._POLL_S)
                    return True
                except queue.Full:
                    pass
            return False

        def _get(q: queue.Queue, producer: threading.Thread):
            """Stop-aware get that notices a dead producer; None means a
            shutdown was requested."""
            while not stop.is_set():
                try:
                    return q.get(timeout=self._POLL_S)
                except queue.Empty:
                    if producer.is_alive():
                        continue
                    try:  # it may have posted right before exiting
                        return q.get_nowait()
                    except queue.Empty:
                        raise RuntimeError(
                            f"staged {producer.name} died without a result"
                        ) from None
            return None

        def hash_work(w: int):
            try:
                for i in range(w, n_batches, n_workers):
                    lo = start_row + i * self.batch_rows
                    batch, root = self._produce_one(lo)
                    if not _put(worker_qs[w], (i, lo, batch, root)):
                        # consumer went away mid-delivery
                        telemetry.end_span(root, row=int(lo), abandoned=True)
                        return
            except BaseException as e:
                telemetry.emit(
                    EVENTS.STREAM_STAGED_ERROR, stage="hash", worker=w,
                    error=repr(e),
                )
                _put(worker_qs[w], (self._DONE, e))

        hash_threads = [
            threading.Thread(
                target=hash_work, args=(w,),
                name=f"rp-staged-hash-{w}", daemon=True,
            )
            for w in range(n_workers)
        ]

        def upload_work():
            try:
                for i in range(n_batches):
                    item = _get(worker_qs[i % n_workers],
                                hash_threads[i % n_workers])
                    if item is None:  # shutdown requested
                        return
                    if isinstance(item, tuple) and item[0] is self._DONE:
                        # worker failure at this batch index: forward it
                        # AFTER the in-order prefix already delivered —
                        # the serial prefix-then-raise behavior (the
                        # worker emitted the staged.error event)
                        _put(out_q, (self._DONE, item[1]))
                        return
                    _i, lo, batch, root = item
                    try:
                        if self.prepare is not None:
                            with telemetry.activate_span(root), \
                                    _stage(self.stats, "h2d"):
                                batch = self.prepare(batch)
                    except BaseException:
                        telemetry.end_span(root, row=int(lo), error=True)
                        raise
                    depth_now = out_q.qsize()
                    if self.stats is not None:
                        self.stats.on_queue_depth(depth_now)
                    # live plane (r17): process-registry mirror, same as
                    # the prefetch deliver site
                    telemetry.registry().gauge_set(
                        "stream.queue.depth", depth_now
                    )
                    telemetry.emit(
                        EVENTS.STREAM_STAGED_DELIVER, row=int(lo),
                        queue_depth=int(depth_now), capacity=self.depth,
                        workers=n_workers,
                        **(
                            {"trace_id": root.trace_id}
                            if root is not None else {}
                        ),
                    )
                    with telemetry.span(
                        "enqueue_wait", parent=root, require_parent=True,
                    ):
                        delivered = _put(out_q, (lo, batch, root))
                    if not delivered:
                        telemetry.end_span(root, row=int(lo), abandoned=True)
                        return
                _put(out_q, self._DONE)
            except BaseException as e:
                telemetry.emit(
                    EVENTS.STREAM_STAGED_ERROR, stage="upload", error=repr(e)
                )
                _put(out_q, (self._DONE, e))

        uploader = threading.Thread(
            target=upload_work, name="rp-staged-upload", daemon=True
        )
        for t in hash_threads:
            t.start()
        uploader.start()
        all_threads = (*hash_threads, uploader)
        try:
            while True:
                try:
                    item = out_q.get(timeout=self._POLL_S)
                except queue.Empty:
                    if uploader.is_alive():
                        continue
                    try:
                        item = out_q.get_nowait()
                    except queue.Empty:
                        raise RuntimeError(
                            "staged upload worker died without a result"
                        ) from None
                if item is self._DONE:
                    return
                if isinstance(item, tuple) and item[0] is self._DONE:
                    raise item[1]
                yield item
        finally:
            stop.set()
            for t in all_threads:
                # bounded join, same rationale as PrefetchSource: a
                # worker stuck in a hung read/prepare never reaches the
                # stop-aware _put and must not hang the consumer
                t.join(timeout=5.0)
            # close the traces of batches produced but never handed to
            # the consumer — a clean break leaves no orphan spans
            for q in (*worker_qs, out_q):
                while True:
                    try:
                        item = q.get_nowait()
                    except queue.Empty:
                        break
                    if isinstance(item, tuple) and len(item) == 4:
                        telemetry.end_span(
                            item[3], row=int(item[1]), abandoned=True
                        )
                    elif isinstance(item, tuple) and len(item) == 3:
                        telemetry.end_span(
                            item[2], row=int(item[0]), abandoned=True
                        )
            if any(t.is_alive() for t in all_threads):  # pragma: no cover
                from randomprojection_tpu.utils.observability import logger

                logger.warning(
                    "staged ingest worker(s) did not stop within 5s of "
                    "shutdown (inner source read or prepare() appears "
                    "hung); abandoning the daemon thread(s)"
                )
                telemetry.emit(EVENTS.STREAM_STAGED_SHUTDOWN_TIMEOUT)


@dataclasses.dataclass
class StreamCursor:
    """Resumable position in a stream; serializes to a tiny JSON file.

    ``rows_done`` always lands on a batch boundary — a batch is committed
    only after the *consumer* has finished processing it (control returned
    from the yield), so a crash at any point — inside the transform, or
    inside the consumer's write of the current batch — loses at most
    uncommitted work, which the resume recomputes identically.
    """

    rows_done: int = 0

    def save(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": 1, "rows_done": self.rows_done}, f)
            # fsync data BEFORE the rename: os.replace alone is atomic
            # against a PROCESS crash, but a machine crash could persist
            # the rename while the new file's blocks never hit disk —
            # surfacing an empty/stale cursor (ISSUE 6 satellite)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic: a crash never leaves a torn cursor
        _fsync_dir(os.path.dirname(os.path.abspath(path)))

    @classmethod
    def load(cls, path: str) -> "StreamCursor":
        with open(path) as f:
            d = json.load(f)
        if d.get("version") != 1:
            raise ValueError(f"Unsupported cursor version in {path}: {d!r}")
        return cls(rows_done=int(d["rows_done"]))


def stream_transform(
    estimator,
    source: RowBatchSource,
    *,
    cursor: Optional[StreamCursor] = None,
    checkpoint_path: Optional[str] = None,
    pipeline_depth: int = 2,
    stats=None,
) -> Iterator[Tuple[int, np.ndarray]]:
    """Project a stream, yielding ``(start_row, Y_batch)`` in row order.

    ``estimator`` is a fitted projection estimator (any backend).  Pass a
    ``cursor`` (or a ``checkpoint_path`` holding one) to resume; batch i's
    cursor is advanced (and saved to ``checkpoint_path`` when given) only
    once the consumer asks for batch i+1 — acknowledging that batch i's
    yielded output was handled — so a crash inside the consumer never
    drops a row range on resume.

    ``pipeline_depth`` > 1 keeps that many batches in flight on the jax
    backend (double buffering); the numpy backend is synchronous and
    unaffected.
    """
    if pipeline_depth < 1:
        raise ValueError(f"pipeline_depth must be >= 1, got {pipeline_depth}")
    if cursor is None:
        if checkpoint_path is not None and os.path.exists(checkpoint_path):
            cursor = StreamCursor.load(checkpoint_path)
        else:
            cursor = StreamCursor()

    estimator._check_is_fitted()
    out_dtype = estimator._stream_out_dtype()

    if stats is not None:
        stats.start()

    pending: list = []  # [(start_row, n_rows, Y_lazy, in_nbytes, trace_root)]

    def materialize(entry):
        start_row, n_rows, y, in_nbytes, root = entry
        if not sp.issparse(y):  # forces device→host for lazy handles
            # re-activate the batch's trace root (created on whichever
            # thread produced the batch) so the d2h span joins its trace
            with telemetry.activate_span(root), \
                    annotate("rp:stream/fetch_d2h"), _stage(stats, "d2h"):
                y = np.asarray(y)
            if out_dtype is not None:
                y = y.astype(out_dtype, copy=False)
        return start_row, n_rows, y, in_nbytes, root

    def emit(entry):
        # Yield the batch FIRST; advance/save the cursor (and count the
        # commit) only after control returns from the yield — i.e. after
        # the consumer's loop body (the canonical write-output-after-yield
        # usage) has completed for this batch.  Committing before the yield
        # would let a crash inside the consumer silently drop the batch's
        # row range on resume: the cursor (or the stats log) would claim
        # rows the consumer never durably wrote.
        start_row, n_rows, y, in_nbytes, root = materialize(entry)
        committed = False
        try:
            yield start_row, y
            cursor.rows_done = start_row + n_rows
            if checkpoint_path is not None:
                cursor.save(checkpoint_path)
            with telemetry.activate_span(root):
                # commit inside the trace so stream.commit correlates
                if stats is not None:
                    stats.on_commit(start_row, in_nbytes, y)
            # the batch's trace ends at commit: production → dispatch →
            # d2h → consumer ack, one root span per batch
            telemetry.end_span(root, row=int(start_row), rows=int(n_rows))
            committed = True
        finally:
            if not committed:
                # consumer broke/crashed mid-yield (or the commit write
                # failed): the batch never committed — close its trace as
                # abandoned so a clean break is not mistaken for a crash
                # (orphaned span) by the doctor.  The CURSOR stays put by
                # design: resume recomputes this batch.
                telemetry.end_span(root, row=int(start_row), abandoned=True)

    batches = iter_traced(source, cursor.rows_done)
    try:
        for start_row, batch, root in batches:
            # _transform_async is each estimator's own (possibly overridden)
            # transform, returning a lazy device handle where supported;
            # the batch's trace root is re-activated so the dispatch span
            # (and the backend's own dispatch event) join its trace
            with telemetry.activate_span(root), \
                    annotate("rp:stream/dispatch"), _stage(stats, "dispatch"):
                y = estimator._transform_async(batch)
                # row count survives prepared operands without a plain
                # .shape (DeviceBatch carries .n; last resort is the
                # output handle, whose leading dim IS the batch's rows).
                # The count feeds the CURSOR as well as telemetry, so
                # undeterminable rows must fail loudly here — a defaulted
                # 0 would silently freeze rows_done and make every resume
                # recompute (or re-append) already-consumed batches
                n_rows = _batch_rows(batch)
                if n_rows is None:
                    n_rows = _batch_rows(y)
                if n_rows is None:
                    raise TypeError(
                        f"cannot determine the row count of batch "
                        f"{type(batch).__name__!r} (no usable .shape or "
                        f".n) or its transform output "
                        f"{type(y).__name__!r}; prepared batch carriers "
                        "must expose one or the other"
                    )
                telemetry.emit(
                    EVENTS.STREAM_DISPATCH, row=int(start_row),
                    rows=int(n_rows), **telemetry.trace_fields(),
                )
            fetch_async = getattr(y, "copy_to_host_async", None)
            if fetch_async is not None:
                # start the d2h as soon as the device finishes this batch:
                # the transfer then overlaps the NEXT batch's compute, and
                # the blocking np.asarray at emit time reuses the fetched
                # copy instead of paying the full transfer on the critical
                # path
                fetch_async()
            # keep only the byte count: retaining the batch itself would pin
            # pipeline_depth extra input batches of host memory
            pending.append(
                (start_row, n_rows, y, batch_nbytes(batch), root)
            )
            if len(pending) >= pipeline_depth:
                yield from emit(pending.pop(0))
        while pending:
            yield from emit(pending.pop(0))
    finally:
        # abandoned mid-flight (break or exception): close the traces of
        # batches that were dispatched but never reached the consumer —
        # their work is recomputed on resume, and the doctor must see a
        # deliberate abandon, not a crash's orphaned spans
        for entry in pending:
            telemetry.end_span(entry[4], row=int(entry[0]), abandoned=True)
        # deterministic producer shutdown: a PrefetchSource's worker thread
        # must be stopped/joined even when the consumer abandons the stream
        # mid-flight (break or exception) — relying on GC to close the
        # generator would leak the thread until collection
        close = getattr(batches, "close", None)
        if close is not None:
            close()


def stream_to_memmap(
    estimator,
    source: RowBatchSource,
    out_path: str,
    *,
    checkpoint_path: str,
    stats=None,
    pipeline_depth: int = 2,
) -> np.ndarray:
    """Stream into a durable on-disk ``.npy`` memmap, resumable mid-run.

    The durability contract: each batch is written to ``out_path`` and
    **flushed before** the stream cursor commits it (the cursor advances
    only when the next batch is requested — see ``stream_transform``), so a
    crash at any point — transform, write, or cursor save — resumes from
    the checkpoint without losing or duplicating rows.

    A fresh run creates the memmap from the first batch's dtype/width; a
    resume (``checkpoint_path`` has ``0 < rows_done < n_rows``) requires
    the memmap from the original run at ``out_path`` (a fresh buffer would
    leave the already-committed rows uninitialized) and the caller is
    responsible for verifying the estimator parameters match that run (see
    ``cli.cmd_project`` for a fingerprint-sidecar example).  Re-running a
    completed checkpoint is a no-op returning the existing memmap.
    Sparse output batches are densified into the memmap.
    """
    if not out_path.endswith(".npy"):
        raise ValueError(f"out_path must end in .npy, got {out_path!r}")
    rows_done = 0
    if os.path.exists(checkpoint_path):
        rows_done = StreamCursor.load(checkpoint_path).rows_done
    out = None
    if rows_done > 0:
        if not os.path.exists(out_path):
            raise ValueError(
                f"checkpoint {checkpoint_path} records progress "
                f"(rows_done={rows_done}) but {out_path} does not exist; "
                f"delete the checkpoint to restart"
            )
        out = np.lib.format.open_memmap(out_path, mode="r+")
        if out.shape[0] != source.n_rows:
            raise ValueError(
                f"{out_path} has {out.shape[0]} rows but the source has "
                f"{source.n_rows}; it belongs to a different run"
            )
        # a same-rows file written by a DIFFERENT estimator would silently
        # mix two projections; width/dtype are the library-level fingerprint
        # (the CLI's sidecar covers the full parameter set for CLI users)
        want_width = estimator._stream_out_width()
        want_dtype = estimator._stream_out_dtype()
        if want_dtype is not None:
            # .npy headers cannot express ml_dtypes names: a bf16 stream
            # reloads as raw void ('|V2') — same bits, degraded label.
            # Restore the typed view so the resume writes correctly.
            from randomprojection_tpu.utils.validation import restore_void_dtype

            out = restore_void_dtype(out, want_dtype)
        if out.ndim != 2 or out.shape[1] != want_width or (
            want_dtype is not None and out.dtype != np.dtype(want_dtype)
        ):
            raise ValueError(
                f"{out_path} has shape {out.shape} dtype {out.dtype} but this "
                f"estimator streams ({source.n_rows}, {want_width}) "
                f"{want_dtype if want_dtype is not None else out.dtype}; "
                f"resuming would mix two projections — delete the checkpoint "
                f"and output to restart"
            )
    for lo, y in stream_transform(
        estimator, source, checkpoint_path=checkpoint_path,
        stats=stats, pipeline_depth=pipeline_depth,
    ):
        if sp.issparse(y):
            y = y.toarray()
        if out is None:
            out = np.lib.format.open_memmap(
                out_path, mode="w+", dtype=y.dtype,
                shape=(source.n_rows, y.shape[1]),
            )
        out[lo : lo + y.shape[0]] = y
        out.flush()  # durable before the cursor commits this batch
    if out is None:  # 0-row source: nothing streamed, emit the empty file
        out = np.lib.format.open_memmap(
            out_path, mode="w+",
            dtype=estimator._stream_out_dtype() or np.float64,
            shape=(source.n_rows, estimator._stream_out_width()),
        )
    return out


def stream_to_array(estimator, source, out=None, **kwargs) -> np.ndarray:
    """Convenience: run ``stream_transform`` into one preallocated array.

    ``out`` defaults to a new ndarray of the stream's full output shape —
    only sensible when that fits in host memory.  Resuming a
    partially-complete checkpoint REQUIRES passing the ``out`` buffer from
    the earlier run (a fresh buffer would leave the already-committed rows
    uninitialized); a fully-complete checkpoint returns ``out`` unchanged
    (or an empty array when no buffer is given).
    """
    cursor = kwargs.get("cursor")
    checkpoint_path = kwargs.get("checkpoint_path")
    if cursor is None and checkpoint_path is not None and os.path.exists(
        checkpoint_path
    ):
        cursor = StreamCursor.load(checkpoint_path)
    resume_start = cursor.rows_done if cursor is not None else 0
    if out is None and 0 < resume_start < source.n_rows:
        raise ValueError(
            f"Resuming from rows_done={resume_start} without the output "
            "buffer of the interrupted run would leave earlier rows "
            "uninitialized; pass out= (or clear the checkpoint to restart)"
        )

    chunks = []
    for start_row, y in stream_transform(estimator, source, **kwargs):
        if out is None and not chunks and not sp.issparse(y):
            out = np.empty((source.n_rows, y.shape[1]), dtype=y.dtype)
        if out is not None:
            out[start_row : start_row + y.shape[0]] = (
                y.toarray() if sp.issparse(y) else y
            )
        else:
            chunks.append(y)
    if out is not None:
        return out
    if chunks:
        return (
            sp.vstack(chunks) if sp.issparse(chunks[0]) else np.concatenate(chunks)
        )
    # empty stream (0-row source, or a completed checkpoint with no buffer)
    width = estimator._stream_out_width()
    dtype = estimator._stream_out_dtype() or np.float64
    return np.empty((0, width), dtype=dtype)
