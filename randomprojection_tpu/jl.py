"""Johnson–Lindenstrauss dimensioning math (layer L0).

Behavioral contract: sklearn ``random_projection.johnson_lindenstrauss_min_dim``
(``sklearn/random_projection.py:63-146``) — the canonical open-source
implementation of the capability surface of the (unreadable) reference repo
``afcarl/RandomProjection``; see ``SURVEY.md`` §0/§1 for provenance.

The JL lemma: for ``n`` points and distortion ``eps``, a random projection to

    k >= 4 * ln(n) / (eps**2 / 2 - eps**3 / 3)

dimensions preserves all pairwise squared distances within a ``(1 ± eps)``
factor with high probability (Dasgupta & Gupta, 1999 tightening of
Johnson & Lindenstrauss, 1984).  The reference's shorthand ``k ≈ 4·log n/ε²``
(``BASELINE.json:5``) is this same bound; we implement the full denominator.

Pure NumPy on purpose: this is host-side planning math, never a device op.
"""

from __future__ import annotations

import numpy as np

__all__ = ["johnson_lindenstrauss_min_dim"]


def johnson_lindenstrauss_min_dim(n_samples, *, eps=0.1):
    """Minimum number of components to guarantee the JL bound.

    Parameters
    ----------
    n_samples : int or array-like of int
        Number of samples whose pairwise distances must be preserved.
    eps : float or array-like of float in (0, 1), default=0.1
        Maximum allowed distortion of pairwise squared distances.

    Returns
    -------
    int or ndarray of int
        Minimal safe number of components.  Scalar inputs give a Python
        ``int``; array inputs broadcast and give an ``ndarray`` of ints.

    Raises
    ------
    ValueError
        If any ``eps`` is outside the open interval (0, 1), or any
        ``n_samples`` is not strictly positive.

    Examples
    --------
    >>> johnson_lindenstrauss_min_dim(1_000_000, eps=0.5)
    663
    """
    eps_arr = np.asarray(eps, dtype=np.float64)
    n_arr = np.asarray(n_samples)

    if np.any(eps_arr <= 0.0) or np.any(eps_arr >= 1.0):
        raise ValueError(f"The JL bound is defined for eps in (0, 1); got {eps!r}")
    if np.any(n_arr <= 0):
        raise ValueError(
            f"The JL bound is defined for n_samples > 0; got {n_samples!r}"
        )

    denominator = (eps_arr**2 / 2) - (eps_arr**3 / 3)
    min_dim = (4 * np.log(n_arr) / denominator).astype(np.int64)
    if min_dim.ndim == 0:
        return int(min_dim)
    return min_dim
