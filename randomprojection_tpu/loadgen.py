"""Open-loop load generator for the serving tier (ISSUE r17).

Every serving number so far came from CLOSED-loop clients: ``topk-bench``
threads submit, wait, submit again — so a slow server slows its own
offered load and the measured q/s can never expose queueing collapse.
This module measures what ROADMAP #3/#5 actually need: an OPEN-loop
arrival process (requests land when the schedule says, whether or not
the server kept up) with mixed request sizes and fixed client labels,
producing per-label p50/p90/p99/p99.9 tail-latency tables — the
``topk_slo`` bench record the adaptive-control and multi-tenant
scenarios will reuse.

Determinism contract: ``build_schedule(seed, ...)`` is a pure function
of its arguments — one seeded ``np.random.default_rng`` draws
inter-arrival gaps, request sizes and client labels, so the identical
seed reproduces the identical schedule (``schedule_digest`` pins it in
tier-1).  Arrival models:

- ``poisson`` — exponential inter-arrival gaps at ``rate_qps`` requests
  per second: the memoryless baseline.
- ``bursty`` — a deterministic on/off duty cycle (period
  ``burst_period_s``, on-fraction ``burst_fraction``) where the ON
  phase runs at ``burst_factor``× the mean-preserving base rate and the
  OFF phase at the residual rate; inside each phase arrivals stay
  Poisson.  Models diurnal/spiky tenants without losing seedability.

The runner (``run``) drives any ``TopKServer``-shaped server
(``submit(codes, label=)`` returning a Future).  Submission lag is
tracked: if the single submitting thread falls behind the schedule
(``max_lag_s`` in the record), the run is flagged ``open_loop_suspect``
rather than silently becoming a closed loop.  Rejections
(``TopKServer`` backpressure ``RuntimeError``) are counted per label —
under overload the SLO table says who got shed, not just who got
served.  Client-observed latency is stamped submit→future-completion
via ``Future.add_done_callback`` (exact values, so the record's
quantiles are exact order statistics, not bucket estimates; the
server's own ``serve.latency.*`` histograms feed the live scrape in
parallel).
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import namedtuple
from typing import Optional, Sequence

import numpy as np

from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS

__all__ = [
    "ScheduledRequest",
    "ARRIVALS",
    "build_schedule",
    "schedule_digest",
    "slo_table",
    "run",
]

ARRIVALS = ("poisson", "bursty")

# one scheduled arrival: offset (seconds from run start), client label,
# query rows
ScheduledRequest = namedtuple("ScheduledRequest", "t label rows")


def build_schedule(
    *,
    seed: int,
    duration_s: float,
    rate_qps: float,
    arrival: str = "poisson",
    request_rows: Sequence[int] = (16, 64, 256),
    row_weights: Optional[Sequence[float]] = None,
    labels: Sequence[str] = ("tenant-a", "tenant-b"),
    burst_factor: float = 8.0,
    burst_fraction: float = 0.125,
    burst_period_s: float = 1.0,
) -> list:
    """Deterministic open-loop arrival schedule (see module docstring).

    Returns a time-sorted list of ``ScheduledRequest`` covering
    ``[0, duration_s)``.  ``rate_qps`` is the mean REQUEST rate (not
    rows/s).  The identical argument tuple yields the identical
    schedule — tier-1 pins this via ``schedule_digest``.
    """
    if arrival not in ARRIVALS:
        raise ValueError(f"arrival must be one of {ARRIVALS}, got {arrival!r}")
    if duration_s <= 0 or rate_qps <= 0:
        raise ValueError(
            f"duration_s and rate_qps must be > 0, got "
            f"{duration_s!r}/{rate_qps!r}"
        )
    if not labels:
        raise ValueError("labels must be non-empty")
    rows_arr = [int(r) for r in request_rows]
    if not rows_arr or any(r < 1 for r in rows_arr):
        raise ValueError(
            f"request_rows must be positive ints, got {request_rows!r}"
        )
    if row_weights is not None:
        w = np.asarray(row_weights, dtype=np.float64)
        if w.shape != (len(rows_arr),) or (w < 0).any() or w.sum() <= 0:
            raise ValueError(
                "row_weights must be non-negative, same length as "
                "request_rows, with a positive sum"
            )
        w = w / w.sum()
    else:
        w = None
    if arrival == "bursty":
        if not 0 < burst_fraction < 1:
            raise ValueError(
                f"burst_fraction must be in (0, 1), got {burst_fraction!r}"
            )
        if burst_factor * burst_fraction > 1:
            raise ValueError(
                "burst_factor * burst_fraction must be <= 1 so the OFF "
                f"phase keeps a non-negative rate, got "
                f"{burst_factor!r} * {burst_fraction!r} (== 1 means ALL "
                "traffic arrives in the burst window)"
            )
        if burst_period_s <= 0:
            raise ValueError(
                f"burst_period_s must be > 0, got {burst_period_s!r}"
            )

    rng = np.random.default_rng(seed)
    times = []
    if arrival == "poisson":
        t = 0.0
        while True:
            t += rng.exponential(1.0 / rate_qps)
            if t >= duration_s:
                break
            times.append(t)
    else:  # bursty: mean-preserving on/off duty cycle, Poisson within
        on_rate = rate_qps * burst_factor
        off_rate = rate_qps * (1.0 - burst_factor * burst_fraction) / (
            1.0 - burst_fraction
        )
        on_len = burst_period_s * burst_fraction
        t = 0.0
        while t < duration_s:
            phase = t % burst_period_s
            in_on = phase < on_len
            rate = on_rate if in_on else off_rate
            phase_end = t + ((on_len - phase) if in_on
                             else (burst_period_s - phase))
            t += rng.exponential(1.0 / rate) if rate > 0 else (
                phase_end - t
            )
            if rate > 0 and t < min(phase_end, duration_s):
                times.append(t)
            elif t >= phase_end:
                t = phase_end  # carry into the next phase, no arrival
    out = []
    for t in times:
        rows = rows_arr[int(rng.choice(len(rows_arr), p=w))]
        label = labels[int(rng.integers(len(labels)))]
        out.append(ScheduledRequest(float(t), str(label), int(rows)))
    return out


def schedule_digest(schedule) -> str:
    """SHA-256 over the canonical text of a schedule — the determinism
    pin: identical seed+params ⇒ identical digest (tier-1 asserts it),
    and the digest rides in the ``topk_slo`` record so two records are
    comparable only when their arrival schedules actually matched."""
    h = hashlib.sha256()
    for r in schedule:
        h.update(f"{r.t:.9f}|{r.label}|{r.rows}\n".encode())
    return h.hexdigest()


def _percentiles(values: Sequence[float]) -> dict:
    """Exact order-statistic quantiles (linear interpolation) of
    client-observed latencies, in milliseconds."""
    a = np.sort(np.asarray(list(values), dtype=np.float64))
    out = {}
    for q, key in ((50, "p50_ms"), (90, "p90_ms"), (99, "p99_ms"),
                   (99.9, "p99.9_ms")):
        out[key] = round(np.percentile(a, q) * 1e3, 3) if a.size else None
    out["mean_ms"] = round(a.mean() * 1e3, 3) if a.size else None
    out["max_ms"] = round(a.max() * 1e3, 3) if a.size else None
    return out


def slo_table(latencies_s: Sequence[float], *, rows: int = 0,
              rejects: int = 0) -> dict:
    """One SLO table row: exact p50/p90/p99/p99.9 (+mean/max) over the
    given latencies plus count/rows/rejects — the per-label unit of the
    ``topk_slo`` record."""
    out = {"count": len(latencies_s), "rows": int(rows),
           "rejects": int(rejects)}
    out.update(_percentiles(latencies_s))
    return out


def run(server, schedule, *, code_bytes: int, seed: int = 0,
        warmup_rows: int = 0,
        probe_policy: Optional[dict] = None) -> dict:
    """Drive ``server`` through ``schedule`` open-loop and return the
    ``topk_slo`` record (see module docstring).

    Query codes are drawn from one seeded pool (``seed`` — independent
    of the schedule's seed stream so changing the corpus draw cannot
    silently change arrival times); each request slices distinct rows
    so a device call cache cannot serve repeats.  ``warmup_rows > 0``
    issues one unmeasured blocking request first (compile warmup).

    ``probe_policy`` (label → probes) is RECORDED per label in the SLO
    table so mixed quality classes stay attributable — routing itself
    lives in the server (``TopKServer(probe_policy=...)``); pass the
    same dict to both.
    """
    total_rows = sum(r.rows for r in schedule)
    if total_rows == 0:
        raise ValueError("empty schedule")
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0xC0DE5]))
    pool = rng.integers(
        0, 256, size=(total_rows, int(code_bytes)), dtype=np.uint8
    )
    if warmup_rows > 0:
        server.query(
            rng.integers(0, 256, size=(warmup_rows, int(code_bytes)),
                         dtype=np.uint8)
        )

    done_lock = threading.Lock()
    lat_by_label: dict = {}
    rows_by_label: dict = {}
    rejects_by_label: dict = {}
    errors = 0
    done_count = 0
    pending = []
    max_lag = 0.0
    t0 = time.perf_counter()
    offset = 0
    for req in schedule:
        now = time.perf_counter() - t0
        delay = req.t - now
        if delay > 0:
            time.sleep(delay)
        else:
            max_lag = max(max_lag, -delay)
        codes = pool[offset:offset + req.rows]
        offset += req.rows
        t_sub = time.perf_counter()
        try:
            fut = server.submit(codes, label=req.label)
        except RuntimeError:
            # backpressure shed (queue full / server closed): the SLO
            # story for this label includes who got rejected
            with done_lock:
                rejects_by_label[req.label] = (
                    rejects_by_label.get(req.label, 0) + 1
                )
            continue

        def _on_done(f, label=req.label, rows=req.rows, t_sub=t_sub):
            nonlocal errors, done_count
            lat = time.perf_counter() - t_sub
            # f is already done when the callback runs, so f.exception()
            # below cannot block under the lock
            with done_lock:
                done_count += 1
                if f.exception() is not None:
                    errors += 1
                else:
                    lat_by_label.setdefault(label, []).append(lat)
                    rows_by_label[label] = (
                        rows_by_label.get(label, 0) + rows
                    )

        fut.add_done_callback(_on_done)
        pending.append(fut)
    # the offered-load window ends when the LAST request was submitted —
    # the drain below measures completion, and under overload completion
    # can run many times longer than the schedule: offered_qps computed
    # over drain-inclusive wall would understate the one number the
    # open-loop design exists to hold constant
    submit_elapsed = time.perf_counter() - t0
    for fut in pending:
        # block until every future resolved (results/errors land in the
        # callbacks, not here)
        fut.exception()
    # Future.set_result wakes waiters BEFORE it runs done-callbacks, so
    # the drain above can return while the dispatcher is still inside
    # the last _on_done — aggregating then would drop tail samples from
    # the very statistics this record exists to pin.  Wait for every
    # callback to have actually run.
    wait_deadline = time.monotonic() + 60.0
    while time.monotonic() < wait_deadline:
        with done_lock:
            if done_count >= len(pending):
                break
        time.sleep(0.001)
    else:  # pragma: no cover — a callback never ran (interpreter bug)
        raise RuntimeError(
            f"loadgen: only {done_count}/{len(pending)} completion "
            "callbacks ran within 60s"
        )
    elapsed = time.perf_counter() - t0

    all_lats: list = []
    labels_out = {}
    for label in sorted(
        set(lat_by_label) | set(rejects_by_label)
    ):
        lats = lat_by_label.get(label, [])
        all_lats.extend(lats)
        labels_out[label] = slo_table(
            lats, rows=rows_by_label.get(label, 0),
            rejects=rejects_by_label.get(label, 0),
        )
        if probe_policy is not None:
            # None = the server's default probes served this label
            labels_out[label]["probes"] = probe_policy.get(label)
    n_rejects = sum(rejects_by_label.values())
    record = {
        "metric": "topk_slo",
        "requests": len(schedule),
        "rows": int(total_rows),
        "elapsed_s": round(elapsed, 4),
        "submit_elapsed_s": round(submit_elapsed, 4),
        "offered_qps": round(len(schedule) / submit_elapsed, 2),
        "served_qps": round(
            (len(schedule) - n_rejects) / elapsed, 2
        ),
        "rejects": int(n_rejects),
        "errors": int(errors),
        "max_lag_s": round(max_lag, 4),
        # an open-loop claim is honest only while the submitter kept up:
        # one coalescing delay of lag is tolerated, beyond that flag it
        "open_loop_suspect": bool(max_lag > 0.25),
        "schedule_sha256": schedule_digest(schedule),
        "labels": labels_out,
        "total": slo_table(
            all_lats,
            rows=sum(t["rows"] for t in labels_out.values()),
            rejects=n_rejects,
        ),
        "server": server.stats(),
    }
    if telemetry.enabled():
        telemetry.emit(
            EVENTS.LOADGEN_RUN, requests=len(schedule),
            rows=int(total_rows), rejects=int(n_rejects),
            errors=int(errors), elapsed_s=round(elapsed, 4),
            max_lag_s=round(max_lag, 4),
            schedule_sha256=record["schedule_sha256"],
        )
    return record
