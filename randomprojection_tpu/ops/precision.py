"""The one dtype→MXU-precision policy, shared by every einsum site.

On TPU, f32 matmuls default to single-pass bf16, whose pairwise-distance
distortion (~1.6e-3 measured) exceeds the 1e-3 budget of BASELINE.json:5.
So f32 compute gets 'high' (3-pass bf16, ~2e-5 distortion at ~1/3 peak);
bf16 compute keeps 'default' — its inputs are already quantized, extra
passes buy nothing.
"""

from __future__ import annotations

import numpy as np

__all__ = ["default_matmul_precision"]


def default_matmul_precision(dtype) -> str:
    return "high" if np.dtype(dtype) == np.float32 else "default"
