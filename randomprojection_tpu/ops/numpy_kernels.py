"""Host-side NumPy projection-matrix kernels (numpy backend / parity oracle).

Same distributions as ``ops/kernels.py`` (contract:
``sklearn/random_projection.py:169-305``) but generated with NumPy's
Generator on host.  NOT bit-identical to the JAX kernels (different PRNGs —
SURVEY.md §8 "hard parts"): cross-backend parity is defined at the
distance-distortion level, seed-determinism within a backend.

Unlike the reference's per-row Python loop (RP.py:284-292, SURVEY.md §4.1
hot loop #2), the sparse kernel here is fully vectorized: i.i.d. per-entry
``{+v, 0, -v}`` sampling is distributionally identical to per-row
Binomial(d, density) nnz counts + uniform index sampling + fair signs.
"""

from __future__ import annotations

import math

import numpy as np
import scipy.sparse as sp

from randomprojection_tpu.utils.validation import check_density, check_input_size

__all__ = [
    "gaussian_random_matrix",
    "sparse_random_matrix",
    "rademacher_random_matrix",
]


def gaussian_random_matrix(n_components, n_features, rng: np.random.Generator):
    """Dense ``(k, d)`` matrix with i.i.d. N(0, 1/k) entries (RP.py:169-206)."""
    check_input_size(n_components, n_features)
    return rng.normal(
        loc=0.0, scale=1.0 / math.sqrt(n_components), size=(n_components, n_features)
    )


def sparse_random_matrix(
    n_components, n_features, density="auto", rng: np.random.Generator | None = None
):
    """Sparse Achlioptas/Li ``(k, d)`` matrix (RP.py:209-305).

    Returns a CSR array for ``density < 1`` (values ``±sqrt(1/(density·k))``)
    and a dense ``±1/sqrt(k)`` ndarray for ``density == 1`` (the RP.py:269-272
    fast path).
    """
    check_input_size(n_components, n_features)
    density = check_density(density, n_features)
    if rng is None:
        rng = np.random.default_rng()

    if density == 1.0:
        signs = rng.integers(0, 2, size=(n_components, n_features)) * 2 - 1
        return signs / math.sqrt(n_components)

    v = 1.0 / math.sqrt(density * n_components)
    if n_components * n_features <= (1 << 24):
        # small matrices: one vectorized pass over a dense uniform draw
        u = rng.random((n_components, n_features))
        data = np.where(u < density / 2, v, np.where(u < density, -v, 0.0))
        return sp.csr_array(data)

    # large matrices: O(nnz) memory — per-row Binomial(d, density) nnz count
    # + uniform index sample + fair signs (the RP.py:284-297 construction,
    # distributionally identical to the i.i.d. per-entry model above)
    nnz_per_row = rng.binomial(n_features, density, size=n_components)
    indptr = np.zeros(n_components + 1, dtype=np.int64)
    np.cumsum(nnz_per_row, out=indptr[1:])
    indices = np.empty(indptr[-1], dtype=np.int64)
    for i in range(n_components):
        indices[indptr[i] : indptr[i + 1]] = rng.choice(
            n_features, size=nnz_per_row[i], replace=False
        )
    data = (rng.integers(0, 2, size=indptr[-1]) * 2 - 1) * v
    return sp.csr_array(
        (data, indices, indptr), shape=(n_components, n_features)
    )


def rademacher_random_matrix(n_components, n_features, rng: np.random.Generator):
    """Dense ``(k, d)`` sign-RP matrix: entries ±1/sqrt(k) each w.p. 1/2."""
    check_input_size(n_components, n_features)
    signs = rng.integers(0, 2, size=(n_components, n_features)) * 2 - 1
    return signs / math.sqrt(n_components)
