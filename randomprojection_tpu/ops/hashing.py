"""Feature hashing (the hashing trick) over raw tokens → CSR.

Host-side ingest for config 5 (SURVEY.md §1: "Count-Sketch /
feature-hashing structured RP on streaming TF-IDF").  Semantics match
sklearn ``FeatureHasher`` (``sklearn/feature_extraction/_hash.py`` +
``_hashing_fast.pyx``): signed 32-bit murmur3 (seed 0) of the token bytes,
``index = |h| mod n_features``, optional alternating sign to make the
sketch unbiased.

The hot loop is the native C++ batch hasher (``native/murmur3.cpp``,
ctypes-bound); a pure-Python murmur3 is the no-compiler fallback and the
cross-check in tests.
"""

from __future__ import annotations

import contextlib
import ctypes
import numbers
import os
import struct
import threading
from typing import Iterable, Optional

import numpy as np
import scipy.sparse as sp

from randomprojection_tpu.native.build import load_murmur3
from randomprojection_tpu.utils import telemetry

__all__ = [
    "murmur3_32", "hash_tokens", "FeatureHasher", "hash_threads_override",
]

# Worker-count selection for the C++ batch kernel.  The preferred route
# is the explicit ``n_threads`` argument of the ``*_t`` entry points
# (native/murmur3.cpp) scoped through a THREAD-LOCAL override — no
# process-global state, so concurrent streams (e.g. two PrefetchSource
# pipelines) neither serialize nor leak their setting into each other.
# A stale prebuilt .so without those symbols falls back to the legacy
# RP_HASH_THREADS env override, guarded by a lock (process-global, so
# concurrent overrides serialize there — correctness is unaffected).
# Output is BIT-IDENTICAL at any thread count — token i's hash depends
# only on token i — so the override changes wall clock, never values.
_HASH_THREADS_LOCK = threading.Lock()
_THREAD_OVERRIDE = threading.local()


def _explicit_threads_supported() -> bool:
    lib = load_murmur3()
    return lib is not None and getattr(lib, "has_explicit_threads", False)


def _requested_threads(n_threads: Optional[int]) -> int:
    """Resolve the worker count for one kernel call: the explicit argument
    wins, else this thread's ``hash_threads_override`` scope, else 0 (=
    the kernel consults RP_HASH_THREADS / hardware concurrency)."""
    if n_threads is not None:
        return int(n_threads)
    return int(getattr(_THREAD_OVERRIDE, "n", None) or 0)


def _emit_hash_batch(path: str, n_tokens: int,
                     n_threads: Optional[int]) -> None:
    """One telemetry event per batch-hash call: which kernel path served
    it (``strided`` / ``list`` / ``python``) and the worker count it
    resolved to (0 = the kernel's hardware-concurrency default).  The
    python path is the no-compiler fallback — a stream quietly riding it
    is the silent 10× ingest regression this event exists to expose."""
    telemetry.registry().counter_inc(
        telemetry.EVENTS.HASH_BATCHES_FAMILY + path
    )
    if telemetry.enabled():
        threads = _requested_threads(n_threads)
        if not threads:
            # no explicit request or thread-local scope: the kernel (and,
            # on legacy .so builds, hash_threads_override itself) resolves
            # via RP_HASH_THREADS — report what will actually apply
            try:
                threads = int(os.environ.get("RP_HASH_THREADS", "0") or 0)
            except ValueError:
                threads = 0
        telemetry.emit(
            telemetry.EVENTS.HASH_BATCH, path=path, tokens=int(n_tokens),
            threads=threads, native=load_murmur3() is not None,
            **telemetry.trace_fields(),
        )


@contextlib.contextmanager
def hash_threads_override(n_threads: Optional[int]):
    """Scope the C++ batch hasher's worker count around a hash call.

    ``None`` is a no-op (keep the ambient default); any int >= 1 pins the
    worker count for calls inside the block.  Thread-local when the
    native library exposes the explicit-thread ABI; legacy .so builds
    fall back to a locked RP_HASH_THREADS env override.
    """
    if n_threads is None:
        yield
        return
    n = int(n_threads)
    if n < 1:
        raise ValueError(f"n_threads must be >= 1, got {n_threads!r}")
    if _explicit_threads_supported():
        prev = getattr(_THREAD_OVERRIDE, "n", None)
        _THREAD_OVERRIDE.n = n
        try:
            yield
        finally:
            _THREAD_OVERRIDE.n = prev
        return
    with _HASH_THREADS_LOCK:
        prev = os.environ.get("RP_HASH_THREADS")
        os.environ["RP_HASH_THREADS"] = str(n)
        try:
            yield
        finally:
            if prev is None:
                os.environ.pop("RP_HASH_THREADS", None)
            else:
                os.environ["RP_HASH_THREADS"] = prev


def _murmur3_32_py(data: bytes, seed: int = 0) -> int:
    """Pure-Python MurmurHash3 x86_32 (fallback + test oracle)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF
    n_blocks = len(data) // 4
    for i in range(n_blocks):
        (k,) = struct.unpack_from("<I", data, i * 4)
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    tail = data[n_blocks * 4 :]
    k = 0
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= len(data)
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def murmur3_32(data, seed: int = 0, *, signed: bool = True) -> int:
    """MurmurHash3 x86_32 of ``data`` (str or bytes)."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    lib = load_murmur3()
    if lib is not None:
        h = lib.murmur3_32(data, len(data), seed)
    else:
        h = _murmur3_32_py(data, seed)
    if signed and h >= 2**31:
        h -= 2**32
    return h


def _reject_token(t):
    raise TypeError(
        f"Feature names must be str or bytes, got {type(t).__name__}: {t!r}"
    )


def _nul_scan(mat2d: np.ndarray):
    """One pass over a fixed-width token buffer → ``(has_embedded_nul,
    first_nul_lengths int64)``.

    A row has an embedded NUL if any element after its first zero is
    nonzero — there first-NUL truncation (the strided kernel's length rule)
    would disagree with numpy's trailing-pad-strip item semantics.  The
    lengths double as the strided kernel's per-token lengths, so the hot
    path scans the buffer exactly once."""
    nz = mat2d != 0
    lengths = np.where(
        nz.all(axis=1), mat2d.shape[1], nz.argmin(axis=1)
    ).astype(np.int64)
    return bool(np.any(nz.sum(axis=1) != lengths)), lengths


def _hash_token_array(arr: np.ndarray, n_features: int, seed: int,
                      n_threads: Optional[int] = None):
    """Vectorized hashing of a numpy ``U``/``S`` token array.

    A fixed-width bytes array IS the strided buffer the C++ kernel wants:
    lengths come from one vectorized scan and the whole column hashes in a
    single FFI call — no per-token Python work.  ASCII unicode arrays are
    narrowed UCS-4→uint8 with one C-level cast (~an order of magnitude
    faster than ``np.char.encode``); non-ASCII falls back to utf-8 encode.

    Tokens containing embedded NUL bytes cannot take the strided path
    (numpy's fixed-width NUL padding is indistinguishable from content):
    they are detected up front and the whole column is routed through the
    list path, so every path hashes such tokens identically (all bytes up
    to the trailing pad — numpy's own item-access semantics).
    """
    if arr.ndim != 1:
        arr = arr.ravel()
    n = arr.shape[0]
    idx = np.empty(n, dtype=np.int32)
    sign = np.empty(n, dtype=np.int8)
    if n == 0:
        return idx, sign

    lib = load_murmur3()
    buf = None
    lengths = None
    if arr.dtype.kind == "U":
        w = arr.dtype.itemsize // 4
        codes = np.ascontiguousarray(arr).view(np.uint32).reshape(n, w)
        embedded, ulens = _nul_scan(codes)
        if embedded:
            telemetry.registry().counter_inc("hash.embedded_nul_fallbacks")
            return hash_tokens(arr.tolist(), n_features, seed,
                               n_threads=n_threads)
        if lib is not None and int(codes.max(initial=0)) < 128:
            buf = codes.astype(np.uint8)  # ASCII narrow: one C cast
            lengths = ulens  # ASCII ⇒ byte length == code-unit length
        else:
            # utf-8 of NUL-free text contains no zero bytes, so the S-path
            # below cannot re-trip the embedded-NUL routing
            arr = np.char.encode(arr, "utf-8")
    if buf is None:
        arr = np.ascontiguousarray(arr)
        sbuf = arr.view(np.uint8).reshape(n, arr.dtype.itemsize)
        embedded, lengths = _nul_scan(sbuf)
        if embedded:
            telemetry.registry().counter_inc("hash.embedded_nul_fallbacks")
            return hash_tokens(arr.tolist(), n_features, seed,
                               n_threads=n_threads)
        if lib is None:  # no compiler: per-token fallback
            _emit_hash_batch("python", n, n_threads)
            for i, tok in enumerate(arr.tolist()):
                h = murmur3_32(tok, seed)
                idx[i] = abs(h) % n_features
                sign[i] = 1 if h >= 0 else -1
            return idx, sign
        buf = sbuf

    args = (
        ctypes.c_void_p(buf.ctypes.data),
        buf.shape[1],
        lengths.ctypes.data_as(ctypes.c_void_p),
        n,
        seed,
        n_features,
        idx.ctypes.data_as(ctypes.c_void_p),
        sign.ctypes.data_as(ctypes.c_void_p),
    )
    _emit_hash_batch("strided", n, n_threads)
    if getattr(lib, "has_explicit_threads", False):
        lib.hash_tokens_strided_t(*args, _requested_threads(n_threads))
    else:
        lib.hash_tokens_strided(*args)
    return idx, sign


def hash_tokens(tokens: Iterable, n_features: int, seed: int = 0,
                n_threads: Optional[int] = None):
    """Batch-hash tokens → ``(idx int32, sign int8)`` arrays.

    Uses the C++ batch kernel on one concatenated buffer (one FFI call for
    the whole batch), falling back to per-token Python hashing.

    ``n_threads`` pins the kernel's worker count for this call (``None`` =
    this thread's ``hash_threads_override`` scope, else the
    RP_HASH_THREADS / hardware default).  Output is bit-identical at any
    count.

    Tokens must be ``str`` or ``bytes`` (sklearn ``FeatureHasher`` contract:
    non-string feature names raise ``TypeError`` — an int token passed to
    ``bytes()`` would silently become that many zero bytes, collapsing all
    equal-valued ints into one bucket).

    A numpy array of dtype ``U*``/``S*`` takes the fully-vectorized path
    (``_hash_token_array``): no per-token Python at all.
    """
    if isinstance(tokens, np.ndarray) and tokens.dtype.kind in ("U", "S"):
        return _hash_token_array(tokens, n_features, seed,
                                 n_threads=n_threads)
    encoded = [
        t.encode("utf-8")
        if isinstance(t, str)
        else bytes(t)
        if isinstance(t, (bytes, bytearray))
        else _reject_token(t)
        for t in tokens
    ]
    n = len(encoded)
    idx = np.empty(n, dtype=np.int32)
    sign = np.empty(n, dtype=np.int8)
    if n == 0:
        return idx, sign

    lib = load_murmur3()
    _emit_hash_batch("list" if lib is not None else "python", n, n_threads)
    if lib is not None:
        buf = b"".join(encoded)
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([len(e) for e in encoded], out=offsets[1:])
        args = (
            buf,
            offsets.ctypes.data_as(ctypes.c_void_p),
            n,
            seed,
            n_features,
            idx.ctypes.data_as(ctypes.c_void_p),
            sign.ctypes.data_as(ctypes.c_void_p),
        )
        if getattr(lib, "has_explicit_threads", False):
            lib.hash_tokens_t(*args, _requested_threads(n_threads))
        else:
            lib.hash_tokens(*args)
    else:
        for i, e in enumerate(encoded):
            h = murmur3_32(e, seed)
            idx[i] = abs(h) % n_features
            sign[i] = 1 if h >= 0 else -1
    return idx, sign


class FeatureHasher:
    """Hash raw feature tokens into a ``(n_samples, n_features)`` CSR matrix.

    Input per sample (``input_type``):
      - ``'string'``: iterable of tokens, each counts 1
      - ``'pair'``:   iterable of ``(token, value)``
      - ``'dict'``:   mapping ``token -> value``

    ``alternate_sign=True`` (default) multiplies each value by the hash
    sign, making downstream sketches unbiased (same role as ``s`` in
    ``CountSketch``).
    """

    def __init__(self, n_features: int = 2**20, *, input_type: str = "dict",
                 alternate_sign: bool = True, dtype=np.float64):
        if not isinstance(n_features, numbers.Integral) or n_features <= 0:
            raise ValueError(f"n_features must be a positive int, got {n_features!r}")
        if input_type not in ("dict", "pair", "string"):
            raise ValueError(
                f"input_type must be 'dict', 'pair' or 'string', got {input_type!r}"
            )
        if np.dtype(dtype).kind != "f":
            raise ValueError(
                f"dtype must be a float dtype, got {np.dtype(dtype)!r}"
            )
        self.n_features = int(n_features)
        self.input_type = input_type
        self.alternate_sign = alternate_sign
        # sklearn FeatureHasher parity knob; float32 is what feeds the
        # device CountSketch path without a cast (models/sketch.py keeps
        # float64 sketches on host by dtype policy)
        self.dtype = np.dtype(dtype)

    def transform(self, raw_X) -> sp.csr_array:
        tokens: list = []
        indptr = [0]
        if self.input_type == "string":
            # all values are 1.0: bulk-extend, no per-token Python loop
            for sample in raw_X:
                tokens.extend(sample)
                indptr.append(len(tokens))
            values = None
        else:
            values = []
            for sample in raw_X:
                items = sample.items() if self.input_type == "dict" else sample
                for tok, val in items:
                    if val == 0:
                        continue
                    tokens.append(tok)
                    values.append(val)
                indptr.append(len(tokens))
        return self._build_csr(tokens, indptr, values)

    def transform_tokens(self, tokens, indptr=None, values=None) -> sp.csr_array:
        """Vectorized pre-tokenized ingest (the streaming-TF-IDF fast path).

        ``tokens``: a flat 1-D numpy array of dtype ``U*``/``S*`` (one FFI
        call, zero per-token Python) or any flat sequence of str/bytes.
        ``indptr``: CSR row pointers, ``(n_samples + 1,)`` — sample ``i``
        owns ``tokens[indptr[i]:indptr[i+1]]``; ``None`` = one sample.
        ``values``: per-token weights (default 1.0 each).

        Unlike ``transform``, explicit zero ``values`` are kept as stored
        zeros in the CSR (filtering would require reindexing ``indptr``);
        downstream matmuls are unaffected.
        """
        if indptr is None:
            indptr = np.asarray([0, len(tokens)], dtype=np.int64)
        else:
            indptr = np.asarray(indptr, dtype=np.int64)
            if indptr.ndim != 1 or indptr.size == 0 or indptr[0] != 0 \
                    or indptr[-1] != len(tokens):
                raise ValueError(
                    f"indptr must be 1-D with indptr[0]=0 and "
                    f"indptr[-1]=len(tokens)={len(tokens)}"
                )
            if np.any(np.diff(indptr) < 0):
                # a non-monotone indptr would otherwise surface as an opaque
                # scipy internal error (or a silently malformed CSR)
                raise ValueError("indptr must be non-decreasing")
        if values is not None and len(values) != len(tokens):
            raise ValueError(
                f"values has length {len(values)} but there are "
                f"{len(tokens)} tokens"
            )
        return self._build_csr(tokens, indptr, values)

    def _build_csr(self, tokens, indptr, values) -> sp.csr_array:
        idx, sign = hash_tokens(tokens, self.n_features)
        if values is None:
            data = np.ones(len(idx), dtype=self.dtype)
        else:
            data = np.asarray(values, dtype=self.dtype)
        if self.alternate_sign:
            data = data * sign
        # copy indptr: sum_duplicates rewrites the CSR arrays in place, and
        # the caller's indptr (transform_tokens API) must not be mutated
        mat = sp.csr_array(
            (data, idx, np.array(indptr, dtype=np.int64, copy=True)),
            shape=(len(indptr) - 1, self.n_features),
        )
        mat.sum_duplicates()
        return mat

    fit_transform = transform

    def fit(self, X=None, y=None):
        return self
