"""Two-pass split-precision projection for exact-in-bf16 mask matrices.

For the sparse (Achlioptas/Li) and sign kernels the *unscaled* matrix
entries are ``{+1, -1, 0}`` — exactly representable in bf16.  Splitting
only ``X`` into high/low bf16 halves then gives f32-grade output from two
single-pass MXU contractions:

    X = X_hi + X_lo   (X_hi = top 16 bits of the f32 mantissa/exponent)
    Y = (X_hi · Mᵀ + X_lo · Mᵀ) · v

Measured pairwise-distance distortion ~3e-6 (vs ~1.1e-3 for one pass and
~2.2e-5 for the 3-pass 'high' mode) at 2/3 the cost of 'high' — the
fastest mode inside the 1e-3 budget for the mask kernels, and the bench's
headline mode on the BASELINE.json config-2 workload.

The high part is produced by **bit-masking** the f32 mantissa, not by an
f32→bf16→f32 convert pair: XLA's simplifier elides that convert round-trip,
which silently zeroes the low part (found empirically; the bitmask form is
opaque to the simplifier).  Truncation (vs round-to-nearest) is fine: the
low half absorbs the difference exactly up to its own bf16 rounding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["split_f32_to_bf16_pair", "split2_project"]


def split_f32_to_bf16_pair(x):
    """``x (f32) -> (x_hi, x_lo)`` bf16 with ``x_hi + x_lo == x`` to ~2^-16."""
    xu = jax.lax.bitcast_convert_type(x, jnp.uint32)
    x_hi_f32 = jax.lax.bitcast_convert_type(
        xu & jnp.uint32(0xFFFF0000), jnp.float32
    )
    x_hi = x_hi_f32.astype(jnp.bfloat16)  # exact: low mantissa bits are zero
    x_lo = (x - x_hi_f32).astype(jnp.bfloat16)
    return x_hi, x_lo


def split2_project(x, mask_bf16, scale):
    """``(x @ mask.T) * scale`` in two bf16 MXU passes, f32-grade accuracy.

    ``x`` f32 ``(n, d)``; ``mask_bf16`` ``(k, d)`` with entries exactly
    representable in bf16 (``{±1, 0}``); ``scale`` python float.
    """
    x_hi, x_lo = split_f32_to_bf16_pair(x.astype(jnp.float32))
    a = jnp.einsum("nd,kd->nk", x_hi, mask_bf16, preferred_element_type=jnp.float32)
    b = jnp.einsum("nd,kd->nk", x_lo, mask_bf16, preferred_element_type=jnp.float32)
    return (a + b) * scale
