"""Fused Pallas TPU kernel for SimHash Hamming top-k serving (ISSUE 7).

The r5 serving number — 1,687 q/s at a 16.7M-code index, 7.4% MXU
(``BENCH_r05.json`` config4) — was bounded by ``lax.scan`` loop overhead:
~2-3 ms per scan iteration on that box regardless of body size, dwarfing
the sub-ms dot+select body (the ``_TOPK_UNROLL``/32k-row-block tuning in
``models/sketch.py`` only amortized it).  This module replaces the scan
with ONE kernel dispatch per query tile: a Pallas grid over query tiles
whose body loops over the resident code blocks **inside the kernel** —
zero per-block dispatch cost — with the next block's HBM→VMEM transfer
manually double-buffered (``pltpu.make_async_copy``, the revolving
two-slot pattern) so the MXU never waits on HBM.

Per (query tile, code block) step the kernel fuses:

1. **DMA**: wait for block ``t``'s copy, start block ``t+1``'s into the
   other buffer slot.  Blocks are tiled over rows AND bytes, so code
   widths far beyond VMEM (the contraction dimension) stream through the
   same two slots.
2. **Hamming matmul**: packed uint8 codes unpack to ±1 bf16 in VMEM and
   contract against the ±1 query tile on the MXU with f32 accumulation —
   exact for any ``n_bits ≤ 2^24`` (``hamming = (bits - s_a·s_bᵀ)/2``;
   zero pad bits match on both sides and cancel).
3. **Tombstone / pad masking**: deleted and padded rows take the
   sentinel distance *before* selection, so they can never displace a
   live code from the running top-m.
4. **Running top-m merge** against VMEM-resident carries.  The carries
   are SEPARATE ``(dist, idx)`` int32 planes — the selection key never
   packs ``(dist, position)`` over the carry width, which is what
   imposed the old ``(n_bits+2)·(m+blk) < 2^31`` ceiling on the scan
   path (``m ≲ 8.3M`` at 256-bit codes).  Packing survives only
   *within* one block (``key = dist·B + pos``, ``B = pow2(blk)`` — the
   block auto-shrinks for wide codes, a perf knob, not a capability
   bound), where position order IS ascending-id order, so the values-
   only bitonic select is tie-correct by construction.  The merge step
   is the classic bitonic top-k update: ``low[i] = min(carry[i],
   block_top[M-1-i])`` under the (dist, id) lexicographic order yields
   exactly the M smallest as a bitonic sequence, sorted by one
   ``log2(M)``-stage merge network.

Contract (bit-for-bit with the retained scan path and
``topk_bruteforce``): ascending Hamming distance, exact ties broken by
the LOWER global id, identical across chunk layouts, block sizes and
query tiling.  Ids returned are chunk-local; empty slots carry
``(sentinel, 2^31-1)`` exactly like the scan path's init, so the host
cross-chunk merge is unchanged.

Interpreter mode (``interpret=True``, auto-selected off-TPU) runs the
identical kernel — DMAs, double buffering, masking, merge — under the
Pallas interpreter so tier-1 exercises the whole path on CPU.  Mosaic
lowering of the lane-axis rolls/reshapes in the sort networks is
untested on a real chip this round (no TPU on this box — see
BASELINE.md r12 note); the structure follows the guide's supported
patterns.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "TopkPlan",
    "plan_fused",
    "fused_topk",
    "interpret_default",
    "stage_rows",
]

# Mosaic's scoped-VMEM limit and the measured temporary headroom — same
# constants as ops/pallas_kernels.py (kept local: the two kernels budget
# independent buffer sets and must not couple their tuning).
_VMEM_LIMIT = 16 << 20
_VMEM_HEADROOM = 3 << 20

# f32-exact distance bound: the ±1 dot accumulates integers in f32, exact
# only up to 2^24 — codes wider than 2^24 bits cannot be served by the
# MXU Hamming path at all (scan shares the same arithmetic; the dense
# host path serves them).
_MAX_BITS_EXACT = 1 << 24

_INT32_MAX = (1 << 31) - 1


class TopkPlan(NamedTuple):
    """A VMEM-feasible tiling for one fused top-k shape.

    ``tq`` query rows per grid step, ``blk`` code rows per DMA block,
    ``cb`` code BYTES per DMA tile (``cb == n_bytes`` for narrow codes;
    wide codes stream the contraction dimension through the same two
    buffer slots), ``q_packed`` whether the query tile enters the kernel
    packed (unpacked per byte-tile in VMEM — only for codes too wide to
    keep the ±1 query plane resident), ``m_pad`` the pow2-padded carry
    width."""

    tq: int
    blk: int
    cb: int
    q_packed: bool
    m_pad: int


def _ceil_pow2(x: int) -> int:
    return 1 << max(int(x) - 1, 0).bit_length()


def interpret_default() -> bool:
    """Off-TPU platforms (this box's CPU tier-1, GPUs) run the kernel
    under the Pallas interpreter — same deny-list as the lazy-projection
    guard in ``backends/jax_backend.py`` (unknown platforms like the
    virtualized ``axon`` chip are TPU-backed)."""
    return jax.default_backend() in ("cpu", "gpu", "cuda", "rocm")


def plan_fused(nq: int, rows: int, n_bytes: int, m: int, *,
               minimal: bool = False) -> Optional[TopkPlan]:
    """The largest VMEM-feasible ``(tq, blk, cb)`` tiling for a fused
    top-``m`` over ``(nq queries) × (rows codes of n_bytes)``, or None
    when no tiling fits — the caller then falls back (scan path, or the
    dense host path for genuinely host-scale ``m``).  ``minimal=True``
    returns the SMALLEST feasible tiling instead: the degraded retry
    after a scoped-VMEM OOM on a shape the scan path cannot represent
    (same search space, so a shape with an auto plan always has a
    minimal one).

    Feasibility, in order of preference (large ``tq`` first — fewer
    kernel launches and query re-fetches — then large ``blk``):

    - packed-key bound: ``(sentinel+1)·pow2(blk) ≤ 2^31`` (the only
      place distance still packs with position, strictly within one
      block — wide codes shrink ``blk`` instead of capping ``m``);
    - byte tile: ``cb`` divides ``n_bytes`` (whole codes when they fit,
      else a pow2 divisor) and the unpacked ±1 block tile fits VMEM;
    - the budget: query plane + two DMA slots + unpacked tile + the
      (tq, blk) distance/accumulator/key planes + (tq, m_pad) carries +
      sort-network temporaries + Mosaic headroom ≤ the 16 MiB scoped
      limit.
    """
    if nq <= 0 or rows <= 0 or m <= 0:
        return None
    n_bits = n_bytes * 8
    sentinel = n_bits + 1
    if n_bits > _MAX_BITS_EXACT:
        return None  # distances not f32-exact: host path territory
    m_pad = max(8, _ceil_pow2(m))
    # carries alone must leave room for everything else even at tq=1
    if 2 * m_pad * 4 > _VMEM_LIMIT // 4:
        return None  # genuinely host-scale m
    b_cap = (1 << 31) // (sentinel + 1)  # pow2(blk) bound for the block key
    if b_cap < 8:
        return None  # pathologically wide codes (≥ ~2^27 bits/row)
    tq_cands = [t for t in (2048, 1024, 512, 256, 128, 64, 32, 16, 8, 4, 2, 1)
                if t <= max(_ceil_pow2(nq), 1)]
    blk_cands = [b for b in (4096, 2048, 1024, 512, 256, 128, 64, 32, 16, 8)
                 if b <= min(b_cap, max(_ceil_pow2(rows), 8))]
    if minimal:
        tq_cands = list(reversed(tq_cands))
        blk_cands = list(reversed(blk_cands))
    for tq in tq_cands:
        # resident ±1 query plane when it fits a quarter of VMEM;
        # otherwise the packed tile stays resident and each byte tile
        # unpacks its query slice on the fly
        q_unpacked_bytes = tq * n_bits * 2
        q_packed = q_unpacked_bytes > _VMEM_LIMIT // 4
        q_bytes = tq * n_bytes if q_packed else q_unpacked_bytes
        for blk in blk_cands:
            # byte tile: whole codes, else the largest pow2 divisor that
            # keeps the unpacked ±1 tile ≤ 4 MiB
            cb = n_bytes
            if blk * cb * 16 > (4 << 20):
                cb = 1
                while (
                    cb * 2 <= n_bytes
                    and n_bytes % (cb * 2) == 0
                    and blk * cb * 2 * 16 <= (4 << 20)
                ):
                    cb *= 2
                if n_bytes % cb or blk * cb * 16 > (4 << 20):
                    continue
            usage = (
                q_bytes
                + 2 * blk * cb                      # DMA double buffer
                + blk * cb * 8 * 2                  # unpacked ±1 tile
                + (tq * cb * 8 * 2 if q_packed else 0)
                + 3 * tq * blk * 4                  # acc, dist, keys
                + 2 * tq * m_pad * 4                # (dist, idx) carries
                + 6 * tq * m_pad * 4                # merge temporaries
                + _VMEM_HEADROOM
            )
            if usage <= _VMEM_LIMIT:
                return TopkPlan(tq, blk, cb, q_packed, m_pad)
    return None


def _unpack_pm1(codes_u8):
    """Packed uint8 → ±1 bf16 bits, little-endian within each byte
    (matches ``np.packbits(bitorder='little')`` and the scan path)."""
    b = codes_u8.astype(jnp.int32)
    bits = (b[:, :, None] >> jnp.arange(8, dtype=jnp.int32)) & 1
    bits = bits.reshape(codes_u8.shape[0], -1)
    return (2 * bits - 1).astype(jnp.bfloat16)


def _lane_iota(L: int):
    return jax.lax.broadcasted_iota(jnp.int32, (1, L), 1)


def _xor_partner(x, s: int):
    """``p[i] = x[i ^ s]`` along the lane axis for pow2 stride ``s`` —
    two cyclic rolls and a select (the XOR partner of a bitonic stage
    never wraps: bit ``s`` of ``i`` decides the roll direction)."""
    low = (_lane_iota(x.shape[-1]) & s) == 0
    return jnp.where(low, jnp.roll(x, -s, axis=-1), jnp.roll(x, s, axis=-1))


def _sort_stage(key, s: int, k: int):
    """One bitonic compare-exchange stage on int32 VALUES: partner at
    XOR distance ``s``, ascending runs where ``(iota & k) == 0``."""
    L = key.shape[-1]
    iota = _lane_iota(L)
    low = (iota & s) == 0
    p = _xor_partner(key, s)
    take_min = low == ((iota & k) == 0)
    return jnp.where(take_min, jnp.minimum(key, p), jnp.maximum(key, p))


def _merge_stage_pairs(d, i, s: int):
    """One ascending bitonic-merge stage on (dist, id) PAIRS under the
    lexicographic (dist, lower-id-wins) order — the total order the
    ``query_topk`` contract documents."""
    iota = _lane_iota(d.shape[-1])
    low = (iota & s) == 0
    pd, pi = _xor_partner(d, s), _xor_partner(i, s)
    p_lt = (pd < d) | ((pd == d) & (pi < i))
    sel_p = jnp.where(low, p_lt, ~p_lt)
    return jnp.where(sel_p, pd, d), jnp.where(sel_p, pi, i)


def _block_top(key, m_s: int):
    """Ascending top-``m_s`` VALUES of each row of ``key`` (t, B):
    bitonic-sort ``m_s``-segments, then merge-truncate rounds (keep the
    min half of each adjacent pair of sorted runs) until one run of
    ``m_s`` remains.  ``m_s`` and ``B`` are pow2, ``m_s ≤ B``."""
    t, B = key.shape
    k = 2
    while k <= m_s:
        s = k // 2
        while s >= 1:
            # direction from the index bit at merge size k — except at
            # the final k == m_s group, where EVERY segment must finish
            # ascending (the global bit m_s alternates per segment; the
            # all-ascending form has k ≥ width, making (iota & k) == 0)
            key = _sort_stage(key, s, 2 * B if k == m_s else k)
            s //= 2
        k *= 2
    W = B
    while W > m_s:
        a = key.reshape(t, W // (2 * m_s), 2, m_s)
        lo, hi = a[:, :, 0, :], jnp.flip(a[:, :, 1, :], axis=-1)
        key = jnp.minimum(lo, hi).reshape(t, W // 2)  # bitonic runs
        s = m_s // 2
        while s >= 1:
            key = _sort_stage(key, s, 2 * key.shape[-1])  # all-ascending
            s //= 2
        W //= 2
    return key


def _merge_carry(cd, ci, bd, bi, m_pad: int):
    """Exact running top-m update: carry (sorted asc) vs block
    candidates (sorted asc, sentinel-padded to ``m_pad``).
    ``low[i] = min(carry[i], block[M-1-i])`` under (dist, id) lex order
    is exactly the M smallest of the union, as a bitonic sequence; one
    merge network sorts it."""
    fd, fi = jnp.flip(bd, axis=-1), jnp.flip(bi, axis=-1)
    take_b = (fd < cd) | ((fd == cd) & (fi < ci))
    nd = jnp.where(take_b, fd, cd)
    ni = jnp.where(take_b, fi, ci)
    s = m_pad // 2
    while s >= 1:
        nd, ni = _merge_stage_pairs(nd, ni, s)
        s //= 2
    return nd, ni


def _topk_kernel(meta_ref, q_ref, codes_hbm, *rest, plan: TopkPlan,
                 rows_pad: int, n_bytes: int, masked: bool):
    """Kernel body for one query tile: in-kernel double-buffered DMA
    over (row block × byte tile) code tiles, fused Hamming matmul +
    masking + running top-m merge.  See the module docstring for the
    full argument/carry layout."""
    if masked:
        dead_hbm, od_ref, oi_ref, buf, sem, dead_buf, dead_sem = rest
    else:
        od_ref, oi_ref, buf, sem = rest
        dead_hbm = dead_buf = dead_sem = None
    tq, blk, cb, q_packed, m_pad = plan
    n_bits = n_bytes * 8
    sentinel = jnp.int32(n_bits + 1)
    nchunk = n_bytes // cb
    nblk = -(-rows_pad // blk)  # ragged tail: clamped-offset re-read
    B = _ceil_pow2(blk)
    m_s = min(m_pad, B)
    n_real = meta_ref[0]
    total = nblk * nchunk

    def tile_copy(t):
        bi = t // nchunk
        cj = t % nchunk
        row_off = jnp.minimum(bi * blk, rows_pad - blk)
        return pltpu.make_async_copy(
            codes_hbm.at[pl.ds(row_off, blk), pl.ds(cj * cb, cb)],
            buf.at[t % 2],
            sem.at[t % 2],
        )

    tile_copy(0).start()  # warm the pipeline

    pos_iota = jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1)[0]

    def block_step(bi, carry):
        cd, ci = carry
        row_off = jnp.minimum(bi * blk, rows_pad - blk)
        if masked:
            # started HERE so the tiny mask transfer rides under the
            # whole block's matmul loop instead of stalling selection
            # (single slot: the previous block's wait precedes this
            # start in program order)
            dcp = pltpu.make_async_copy(
                dead_hbm.at[pl.ds(row_off, blk)], dead_buf, dead_sem
            )
            dcp.start()

        def chunk_step(cj, acc):
            t = bi * nchunk + cj

            @pl.when(t + 1 < total)
            def _():
                tile_copy(t + 1).start()

            tile_copy(t).wait()
            s_b = _unpack_pm1(buf[t % 2])
            if q_packed:
                q = _unpack_pm1(q_ref[:, pl.ds(cj * cb, cb)])
            else:
                q = q_ref[:, pl.ds(cj * cb * 8, cb * 8)]
            return acc + jax.lax.dot_general(
                q, s_b,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        acc = jax.lax.fori_loop(
            0, nchunk, chunk_step, jnp.zeros((tq, blk), jnp.float32)
        )
        d = ((jnp.float32(n_bits) - acc) * 0.5).astype(jnp.int32)
        ids = row_off + pos_iota
        # two mask layers: the clamped last block re-reads rows earlier
        # blocks already scored (keep only ids >= bi*blk — never a
        # duplicate candidate), and trailing pad rows never existed
        keep = (ids >= bi * blk) & (ids < n_real)
        if masked:
            dcp.wait()
            keep = keep & (dead_buf[:, 0] == 0)
        d = jnp.where(keep[None, :], d, sentinel)
        # values-only select within the block: key = dist·B + pos packs
        # int32 by the plan bound; pos order IS ascending-id order, so
        # ascending key is the (dist, lower-id) total order
        key = d * jnp.int32(B) + pos_iota[None, :]
        if B > blk:
            key = jnp.pad(
                key, ((0, 0), (0, B - blk)),
                constant_values=sentinel * B + blk,
            )
        top = _block_top(key, m_s)
        bd = top >> B.bit_length() - 1
        bp = top & jnp.int32(B - 1)
        bi_ids = jnp.where(bd >= sentinel, jnp.int32(_INT32_MAX),
                           row_off + bp)
        bd = jnp.minimum(bd, sentinel)
        if m_s < m_pad:
            bd = jnp.pad(bd, ((0, 0), (0, m_pad - m_s)),
                         constant_values=int(n_bits + 1))
            bi_ids = jnp.pad(bi_ids, ((0, 0), (0, m_pad - m_s)),
                             constant_values=_INT32_MAX)
        return _merge_carry(cd, ci, bd, bi_ids, m_pad)

    init = (
        jnp.full((tq, m_pad), sentinel, jnp.int32),
        jnp.full((tq, m_pad), jnp.int32(_INT32_MAX)),
    )
    cd, ci = jax.lax.fori_loop(0, nblk, block_step, init)
    od_ref[:] = cd
    oi_ref[:] = ci


@functools.partial(
    jax.jit,
    static_argnames=("plan", "n_bytes", "m", "interpret", "masked"),
)
def _fused_impl(q, codes, n_real, dead, *, plan: TopkPlan, n_bytes: int,
                m: int, interpret: bool, masked: bool):
    tq, blk, cb, q_packed, m_pad = plan
    nq = q.shape[0]
    rows = codes.shape[0]
    # tiny indexes pad up to one block; big ones stream ragged last
    # blocks via the clamped-offset re-read (no per-call full-index pad)
    if rows < blk:
        codes = jnp.pad(codes, ((0, blk - rows), (0, 0)))
        if masked:
            dead = jnp.pad(dead, ((0, blk - rows), (0, 0)))
    # ragged tails stay ragged — the kernel clamps the last block's
    # offset and re-reads (id-masked) instead of padding the resident
    # index per call
    rows_pad = codes.shape[0]
    nq_pad = -(-nq // tq) * tq
    if nq_pad != nq:
        q = jnp.pad(q, ((0, nq_pad - nq), (0, 0)))
    if q_packed:
        q_in = q
        q_width = n_bytes
    else:
        q_in = _unpack_pm1(q)
        q_width = n_bytes * 8
    meta = jnp.asarray([n_real], dtype=jnp.int32)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((tq, q_width), lambda i: (i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pltpu.ANY),
    ]
    operands = [meta, q_in, codes]
    scratch = [
        pltpu.VMEM((2, blk, cb), jnp.uint8),
        pltpu.SemaphoreType.DMA((2,)),
    ]
    if masked:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
        operands.append(dead)
        scratch += [
            pltpu.VMEM((blk, 1), jnp.uint8),
            pltpu.SemaphoreType.DMA(()),
        ]
    od, oi = pl.pallas_call(
        functools.partial(
            _topk_kernel, plan=plan, rows_pad=rows_pad, n_bytes=n_bytes,
            masked=masked,
        ),
        grid=(nq_pad // tq,),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((tq, m_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tq, m_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((nq_pad, m_pad), jnp.int32),
            jax.ShapeDtypeStruct((nq_pad, m_pad), jnp.int32),
        ),
        scratch_shapes=scratch,
        cost_estimate=pl.CostEstimate(
            flops=2 * nq_pad * n_bytes * 8 * rows_pad,
            bytes_accessed=(
                (nq_pad // tq) * rows_pad * n_bytes + nq_pad * q_width
            ),
            transcendentals=0,
        ),
        interpret=interpret,
    )(*operands)
    return od[:nq, :m], oi[:nq, :m]


def fused_topk(q, codes, n_real, m: int, *, dead=None,
               plan: Optional[TopkPlan] = None,
               interpret: Optional[bool] = None):
    """Exact fused top-``m`` of one code chunk for one query tile.

    ``q`` (nq, n_bytes) uint8 packed query codes, ``codes`` (rows,
    n_bytes) uint8 resident chunk (pad rows beyond ``n_real`` are
    ignored), ``dead`` optional (rows,) uint8 tombstone mask (1 =
    deleted, filtered in-selection).  Returns ``(dist, idx)`` each
    ``(nq, m)`` int32 — ascending distance, ties to the LOWER chunk-
    local id, empty slots ``(n_bits+1, 2^31-1)`` — bit-identical to the
    scan path and ``topk_bruteforce``.

    ``plan=None`` resolves the VMEM tiling via ``plan_fused`` (raises
    ``ValueError`` when no tiling fits — callers route those requests
    to the scan or dense paths *before* dispatch); ``interpret=None``
    auto-selects the Pallas interpreter off-TPU."""
    if interpret is None:
        interpret = interpret_default()
    n_bytes = int(codes.shape[1])
    if plan is None:
        plan = plan_fused(int(q.shape[0]), int(codes.shape[0]), n_bytes, m)
        if plan is None:
            raise ValueError(
                f"no VMEM-feasible fused top-k tiling for nq={q.shape[0]}, "
                f"rows={codes.shape[0]}, n_bytes={n_bytes}, m={m}"
            )
    masked = dead is not None
    if masked:
        dead = jnp.asarray(dead, jnp.uint8).reshape(-1, 1)
    else:
        dead = jnp.zeros((0, 1), jnp.uint8)  # static placeholder
    return _fused_impl(
        q, codes, jnp.int32(n_real), dead, plan=plan, n_bytes=n_bytes,
        m=int(m), interpret=bool(interpret), masked=masked,
    )


def stage_rows(rows, *, device=None, pad_to: Optional[int] = None):
    """Tier-boundary H2D staging (ISSUE 19 / r21): start the upload of
    host-gathered candidate rows and return the device handle WITHOUT
    waiting for the transfer.  ``jax.device_put`` is asynchronous — the
    copy streams in the background and the first kernel that consumes
    the handle joins it — so a caller that stages its cold-tier rows
    *before* dispatching the hot-tier re-rank gets the upload for free
    under that kernel's compute (the in-kernel DMA double-buffering
    idiom applied at the tier boundary).

    ``pad_to`` zero-pads on the HOST before the put (one contiguous
    transfer, no device-side pad dispatch) so the fused re-rank
    compiles one program per row bucket, exactly like the resident
    gather path.  ``device=None`` targets the platform default."""
    import numpy as np

    rows = np.asarray(rows, dtype=np.uint8)
    if pad_to is not None and pad_to != rows.shape[0]:
        if pad_to < rows.shape[0]:
            raise ValueError(
                f"stage_rows pad_to={pad_to} below row count "
                f"{rows.shape[0]}"
            )
        padded = np.zeros((pad_to, rows.shape[1]), np.uint8)
        padded[: rows.shape[0]] = rows
        rows = padded
    if device is not None:
        return jax.device_put(rows, device)
    return jnp.asarray(rows)
