"""On-device projection-matrix kernels (layer L3), TPU-first.

Math contract (see SURVEY.md §1; canonical open-source anchor
``sklearn/random_projection.py``):

- Gaussian kernel: ``R[i, j] ~ N(0, 1/k)`` i.i.d. (RP.py:203-205).
- Sparse Achlioptas/Li kernel with ``s = 1/density``:
  ``R[i, j] ∈ {-sqrt(s/k), 0, +sqrt(s/k)}`` with probabilities
  ``{1/2s, 1 - 1/s, 1/2s}`` (RP.py:216-221, 274-305).  ``density=1``
  degenerates to dense Rademacher ``±1/sqrt(k)``.
- Rademacher (sign-RP) kernel: ``R[i, j] ∈ {-1, +1}/sqrt(k)`` each w.p. 1/2.

TPU-first design decisions
--------------------------
**Blocked, counter-based definition.**  ``R`` is *defined* as a sequence of
column blocks of fixed width ``COLUMN_BLOCK``; block ``b`` is a pure function
of ``jax.random.fold_in(key, b)``.  Consequences:

- The same ``(key, k, d)`` yields the *same matrix* no matter how the
  computation is laid out: full materialization, per-shard materialization
  under tensor parallelism (each chip builds only its column blocks), or
  lazy regeneration inside a fused kernel.  This resolves SURVEY.md §8's
  "PRNG parity vs streaming layout" hazard by construction.
- Blocks use the counter-based threefry PRNG, so generation is embarrassingly
  parallel and reproducible across meshes and JAX versions with the same
  PRNG implementation.

**Single-uniform trick for the sparse kernel.**  One uniform draw per entry
decides zero/sign: ``u < density/2 → +v``, ``u < density → -v``, else 0.
This is i.i.d.-equivalent to the reference's per-row binomial + index
sampling + sign flips (RP.py:284-297) but vectorizes to a pure elementwise
op on device — no Python row loop, no CSR assembly.

Sparse matrices are returned *dense* on device: on TPU the MXU consumes
dense bf16/f32 tiles, and a k×d projection matrix is small (256×4096 f32 =
4 MiB).  For huge ``k·d`` the mask is regenerated lazily block-by-block
(``ops/pallas_kernels.py``, planned; same block definition) instead of ever
being resident in HBM.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from randomprojection_tpu.utils.validation import check_density, check_input_size

__all__ = [
    "COLUMN_BLOCK",
    "num_column_blocks",
    "block_key",
    "gaussian_block",
    "sparse_block",
    "rademacher_block",
    "gaussian_matrix",
    "sparse_matrix",
    "rademacher_matrix",
    "materialize_columns",
]

# Canonical column-block width.  Part of the matrix *definition*: changing it
# changes every generated matrix, so it is a constant, not a knob.  512 lanes
# = 4 TPU vregs wide, and divides the lane tiling of every supported dtype.
COLUMN_BLOCK = 512


def num_column_blocks(n_features: int) -> int:
    return -(-n_features // COLUMN_BLOCK)


def block_key(key: jax.Array, block_index) -> jax.Array:
    """The PRNG key owning column block ``block_index`` of the matrix."""
    return jax.random.fold_in(key, block_index)


def _block_width(n_features: int, block_index: int) -> int:
    """Width of block ``block_index`` (the last block may be ragged)."""
    return min(COLUMN_BLOCK, n_features - block_index * COLUMN_BLOCK)


# ---------------------------------------------------------------------------
# Per-block generators (pure; jit-friendly; static shapes)
# ---------------------------------------------------------------------------


def gaussian_block(key, block_index, n_components, width, dtype=jnp.float32):
    """Column block of the Gaussian kernel: entries i.i.d. N(0, 1/k)."""
    bkey = block_key(key, block_index)
    std = 1.0 / math.sqrt(n_components)
    return (jax.random.normal(bkey, (n_components, width), dtype=jnp.float32) * std).astype(dtype)


def sparse_block(key, block_index, n_components, width, density, dtype=jnp.float32):
    """Column block of the Achlioptas/Li sparse kernel.

    Entries are i.i.d. ``{+v, -v, 0}`` with probabilities
    ``{density/2, density/2, 1-density}`` where ``v = 1/sqrt(density * k)``
    (equal to ``sqrt(s/k)`` with ``s = 1/density`` — RP.py:305).
    """
    bkey = block_key(key, block_index)
    u = jax.random.uniform(bkey, (n_components, width), dtype=jnp.float32)
    v = 1.0 / math.sqrt(density * n_components)
    plus = (u < density / 2).astype(jnp.float32)
    minus = ((u >= density / 2) & (u < density)).astype(jnp.float32)
    return ((plus - minus) * v).astype(dtype)


def rademacher_block(key, block_index, n_components, width, dtype=jnp.float32):
    """Column block of the sign/Rademacher kernel: ±1/sqrt(k) each w.p. 1/2."""
    bkey = block_key(key, block_index)
    bits = jax.random.bernoulli(bkey, 0.5, (n_components, width))
    v = 1.0 / math.sqrt(n_components)
    return jnp.where(bits, v, -v).astype(dtype)


# ---------------------------------------------------------------------------
# Full-matrix materialization (concatenation of blocks)
# ---------------------------------------------------------------------------


def _materialize(block_fn, key, n_components, n_features, dtype):
    check_input_size(n_components, n_features)
    blocks = []
    for b in range(num_column_blocks(n_features)):
        w = _block_width(n_features, b)
        blocks.append(block_fn(key, b, n_components, w, dtype=dtype))
    return jnp.concatenate(blocks, axis=1) if len(blocks) > 1 else blocks[0]


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def gaussian_matrix(key, n_components, n_features, dtype=jnp.float32):
    """Materialize the full ``(k, d)`` Gaussian projection matrix on device."""
    return _materialize(gaussian_block, key, n_components, n_features, dtype)


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def sparse_matrix(key, n_components, n_features, density, dtype=jnp.float32):
    """Materialize the full ``(k, d)`` sparse (Achlioptas/Li) matrix, dense layout.

    ``density`` must be numeric in (0, 1] (resolve ``'auto'`` with
    ``check_density`` first — done at the estimator layer).
    """
    density = check_density(density, n_features)
    block_fn = functools.partial(sparse_block, density=density)
    return _materialize(block_fn, key, n_components, n_features, dtype)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def rademacher_matrix(key, n_components, n_features, dtype=jnp.float32):
    """Materialize the full ``(k, d)`` sign-RP matrix on device."""
    return _materialize(rademacher_block, key, n_components, n_features, dtype)


def materialize_columns(
    block_fn, key, n_components, n_features, col_start, col_end, dtype=jnp.float32
):
    """Materialize columns ``[col_start, col_end)`` of the ``(k, n_features)`` matrix.

    Used by the tensor-parallel path: a chip owning a column shard builds
    exactly its blocks, and the result is bit-identical to slicing the full
    matrix.  Bit-identity requires generating each block at its *canonical*
    width (threefry output depends on the array shape), so ``col_start`` must
    be COLUMN_BLOCK-aligned and ``col_end`` aligned or at the matrix edge.
    """
    if col_start % COLUMN_BLOCK != 0:
        raise ValueError(
            f"col_start must be aligned to COLUMN_BLOCK={COLUMN_BLOCK}, got {col_start}"
        )
    if col_end % COLUMN_BLOCK != 0 and col_end != n_features:
        raise ValueError(
            f"col_end must be COLUMN_BLOCK-aligned or equal to n_features="
            f"{n_features}, got {col_end}"
        )
    if not 0 <= col_start < col_end <= n_features:
        raise ValueError(
            f"Expected 0 <= col_start < col_end <= n_features={n_features}, "
            f"got [{col_start}, {col_end})"
        )
    blocks = []
    b0 = col_start // COLUMN_BLOCK
    b1 = -(-col_end // COLUMN_BLOCK)
    for b in range(b0, b1):
        w = _block_width(n_features, b)
        blocks.append(block_fn(key, b, n_components, w, dtype=dtype))
    return jnp.concatenate(blocks, axis=1) if len(blocks) > 1 else blocks[0]
