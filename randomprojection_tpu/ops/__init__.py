"""Projection kernels and fused ops (layer L3/L1).

``kernels`` — on-device jax.random generators (blocked, counter-based).
``numpy_kernels`` — host NumPy generators (numpy backend / parity oracle).
``pallas_kernels`` — fused Pallas TPU kernels (lazy mask regeneration; planned).
"""
