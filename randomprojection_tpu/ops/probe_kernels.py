"""Device-fused LSH candidate generation (ISSUE 16).

r18's multi-probe tier made retrieval sublinear but left the probe half
of every query on the host: band-key extraction, CSR bucket walks, the
cross-band ``np.unique`` dedup and the candidate-id upload all run in
numpy per query tile while the device idles until ``jnp.take`` + the
r12 re-rank kernel fire.  This module moves the whole candidate
pipeline into the device program — one jitted dispatch per query tile,
zero per-tile host work:

1. **Band keys on device** (``device_band_keys``) — the packed query
   tile unpacks to bits and reduces to per-band keys with the identical
   little-endian bit order as the host ``ann.lsh.band_keys`` (test-
   pinned bit-equal), fused by XLA into the same program.
2. **CSR probe walk** (``_probe_kernel``) — a Pallas kernel over the
   device-resident banded CSR: per (query, band, probe) run it XORs the
   precomputed probe mask into the band key, reads the bucket's
   ``[start, end)`` run bounds from the VMEM-resident ``indptr``, and
   streams the run's id block(s) HBM→VMEM through the revolving
   two-slot ``pltpu.make_async_copy`` pattern (r12 discipline, RP07-
   checked), packing survivors densely into a sentinel-initialized
   candidate-slot buffer.  A run that would overflow the slot budget
   ``cap`` is skipped and flags ``overflow`` — the ladder's post-hoc
   budget rung.  Inactive queries (adaptive early-exit, pad rows)
   contribute zero-length runs.
3. **Sort-unique dedup + gather + re-rank** (``device_probe_topk``) —
   the slot buffer sorts on device (``jnp.sort``; the int32-max
   sentinel sorts past every real id), duplicates and tombstones become
   dead rows, candidate code rows gather from the resident chunks, and
   the r12 fused Hamming re-rank + running top-m merge scores the tile
   — local positions map back to global ids on device, so the host
   only ever copies back the final ``(dist, gid)`` planes plus the
   tile's scalar stats.

Sorting ascending before the re-rank preserves the documented
(distance, lower-global-id) tie order: lower slot index IS lower global
id among live candidates, and every duplicate/sentinel/tombstone slot
is masked dead so it can never displace a live row.  At full probe
coverage the slot buffer holds every live id of every band (the plan's
``cap`` bound is exact there), which keeps the device path bit-
identical to the host probe path and to ``topk_bruteforce`` — the
``make ann-smoke`` parity gate.

``plan_probe`` budgets the kernel's VMEM residents (the per-band
``indptr`` is the dominant term — band layouts past ~2^16 buckets/band
return no plan and the tier serves the host probe rung instead) and
picks the query sub-tile ``tq`` and slot budget ``cap``; the caller
must also hold an r12 ``plan_fused(tq, cap, n_bytes, m)`` for the
fused re-rank leg.

Interpreter mode (auto-selected off-TPU, same deny-list as
``topk_kernels.interpret_default``) runs the identical kernel — DMAs,
revolving slots, masked packing — under the Pallas interpreter so
tier-1 exercises the whole device path on CPU.  Mosaic lowering of the
dynamic-offset lane writes and scalar VMEM loads is untested on a real
chip this round (no TPU on this box — see BASELINE.md r19 note); the
structure follows the guide's supported patterns.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from randomprojection_tpu.ops.topk_kernels import (
    TopkPlan,
    _ceil_pow2,
    fused_topk,
    interpret_default,
)

__all__ = [
    "ProbePlan",
    "plan_probe",
    "device_band_keys",
    "probe_gather",
    "device_probe_topk",
    "interpret_default",
]

# Mosaic's scoped-VMEM limit and the measured temporary headroom — same
# constants as ops/topk_kernels.py (kept local: the probe kernel
# budgets an independent buffer set and must not couple its tuning).
_VMEM_LIMIT = 16 << 20
_VMEM_HEADROOM = 3 << 20

_INT32_MAX = (1 << 31) - 1
# empty-slot sentinel: sorts past every real candidate id, and is dead
# by construction (>= any corpus size the int32 id space can hold)
_SENTINEL_ID = _INT32_MAX

# candidate-slot skew slack: ``cap`` covers SLACK× the expected
# (average-bucket) gather so hot buckets don't trip the budget rung on
# ordinary skew; genuinely dense tiles overflow and fall back, which is
# the density ladder made structural
_CAP_SLACK = 4
# absolute slot ceiling — past this the slot buffer alone exceeds the
# scoped-VMEM budget and the tile is host-probe territory anyway
_CAP_CEILING = 1 << 22


class ProbePlan(NamedTuple):
    """A VMEM-feasible tiling for one device-probe shape.

    ``tq`` query rows per dispatch (the device path clamps the serving
    tile to this), ``cap`` pow2 candidate-slot budget per tile (the
    pre-dedup gather bound — overflow falls back to the exact rung),
    ``blk`` id rows per CSR-run DMA block (the revolving two-slot
    transfer size)."""

    tq: int
    cap: int
    blk: int


def plan_probe(nq: int, rows: int, bands: int, band_bits: int,
               n_probes: int, m: int) -> Optional[ProbePlan]:
    """The largest VMEM-feasible ``(tq, cap, blk)`` for a device-probe
    dispatch over ``nq`` queries against a ``rows``-id banded CSR, or
    None when no tiling fits — the caller then serves the host probe
    rung (r6 convention: classify, degrade, memoize, emit).

    The budget: the per-band ``indptr`` plane (the dominant resident —
    ``bands · (2^band_bits + 1)`` int32), the query band keys, probe
    masks and active mask, the per-query count plane, the packed
    candidate-slot buffer (``cap + blk`` — block writes round up to the
    DMA block), two revolving DMA slots, and the Mosaic headroom, all
    within the 16 MiB scoped limit.  ``cap`` itself is the density
    ladder made structural: ``_CAP_SLACK×`` the average-bucket gather
    expectation, exact (never overflowing) at full probe coverage,
    floored at ``4·m`` so a feasible plan can always fill a result."""
    if nq <= 0 or rows <= 0 or m <= 0 or n_probes <= 0:
        return None
    if bands < 1 or band_bits < 1:
        return None
    nb = 1 << band_bits
    n_probes = min(int(n_probes), nb)
    indptr_bytes = bands * (nb + 1) * 4
    bucket = max(1, -(-rows // nb))  # ceil average bucket size
    tq_cands = [t for t in (1024, 512, 256, 128, 64, 32, 16, 8, 4, 2, 1)
                if t <= max(_ceil_pow2(nq), 1)]
    for tq in tq_cands:
        expected = tq * bands * n_probes * bucket
        cap_req = min(tq * bands * rows, _CAP_SLACK * expected)
        cap = _ceil_pow2(max(cap_req, 4 * m, 128))
        if cap > _CAP_CEILING:
            continue
        for blk in (512, 256, 128, 64):
            usage = (
                indptr_bytes
                + bands * tq * 4            # query band keys
                + _ceil_pow2(n_probes) * 4  # probe masks
                + 2 * tq * 4                # active mask + count planes
                + (cap + blk) * 4           # packed candidate slots
                + 2 * blk * 4               # DMA double buffer
                + _VMEM_HEADROOM
            )
            if usage <= _VMEM_LIMIT:
                return ProbePlan(tq, cap, blk)
    return None


def device_band_keys(codes, bands: int, band_bits: int):
    """Band keys of a packed uint8 code tile ON DEVICE: ``(bands, n)``
    int32, key ``j`` of a row being its code bits ``[j·b, (j+1)·b)``
    little-endian within each byte — bit-equal to the host
    ``ann.lsh.band_keys`` (test-pinned), fused by XLA into the probe
    dispatch so no key byte ever crosses the host boundary."""
    b8 = codes.astype(jnp.int32)
    bits = (b8[:, :, None] >> jnp.arange(8, dtype=jnp.int32)) & 1
    bits = bits.reshape(codes.shape[0], -1)[:, : bands * band_bits]
    w = jnp.int32(1) << jnp.arange(band_bits, dtype=jnp.int32)
    keys = (bits.reshape(codes.shape[0], bands, band_bits)
            * w[None, None, :]).sum(axis=2, dtype=jnp.int32)
    return keys.T


def _probe_kernel(qkeys_ref, masks_ref, active_ref, indptr_ref, ids_hbm,
                  out_ref, cnt_ref, stat_ref, buf, sem, *, bands: int,
                  n_probes: int, tq: int, cap: int, blk: int):
    """Kernel body: walk every (query, band, probe) CSR run, packing
    the gathered ids densely into the sentinel-initialized slot buffer.
    Every run issues exactly one warm DMA plus guarded look-ahead
    copies through the two revolving slots — skipped/overflowing runs
    stream one fully-masked block so start/wait stay unconditional
    (RP07 discipline; the masked lanes write sentinels ABOVE the write
    cursor, which later runs overwrite or the dedup discards)."""
    out_ref[:] = jnp.full((1, cap + blk), _SENTINEL_ID, jnp.int32)
    cnt_ref[:] = jnp.zeros((1, tq), jnp.int32)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1)

    def run_step(t, carry):
        wr, ovf = carry
        q = t // (bands * n_probes)
        j = (t // n_probes) % bands
        p = t % n_probes
        qk = pl.load(qkeys_ref, (pl.ds(j, 1), pl.ds(q, 1)))[0, 0]
        mk = pl.load(masks_ref, (pl.ds(0, 1), pl.ds(p, 1)))[0, 0]
        key = qk ^ mk
        start = pl.load(indptr_ref, (pl.ds(j, 1), pl.ds(key, 1)))[0, 0]
        end = pl.load(indptr_ref, (pl.ds(j, 1), pl.ds(key + 1, 1)))[0, 0]
        act = pl.load(active_ref, (pl.ds(0, 1), pl.ds(q, 1)))[0, 0]
        ln = jnp.where(act != 0, end - start, 0)
        fits = wr + ln <= cap
        do = fits & (ln > 0)
        # attempted yield per query — the adaptive budget accounting
        # counts what the probes FOUND even when the slot budget trips
        prev = pl.load(cnt_ref, (pl.ds(0, 1), pl.ds(q, 1)))[0, 0]
        pl.store(cnt_ref, (pl.ds(0, 1), pl.ds(q, 1)),
                 jnp.reshape(prev + ln, (1, 1)))
        nblk = jnp.where(do, (ln + blk - 1) // blk, 1)
        ln_w = jnp.where(do, ln, 0)

        def run_copy(k):
            # ids_hbm is sentinel-padded by one block per band, so the
            # last (ragged) block of a run reads past ``end`` but never
            # past the pad — masked lanes replace the overread
            return pltpu.make_async_copy(
                ids_hbm.at[pl.ds(j, 1), pl.ds(start + k * blk, blk)],
                buf.at[k % 2],
                sem.at[k % 2],
            )

        run_copy(0).start()  # warm the pipeline (dummy block when idle)

        def blk_step(k, _):
            @pl.when(k + 1 < nblk)
            def _():
                run_copy(k + 1).start()

            run_copy(k).wait()
            rem = ln_w - k * blk
            mb = jnp.where(lane < rem, buf[k % 2], _SENTINEL_ID)
            pl.store(out_ref, (pl.ds(0, 1), pl.ds(wr + k * blk, blk)), mb)
            return 0

        jax.lax.fori_loop(0, nblk, blk_step, 0)
        ovf = ovf | jnp.where((~fits) & (ln > 0), jnp.int32(1),
                              jnp.int32(0))
        return wr + ln_w, ovf

    wr, ovf = jax.lax.fori_loop(
        0, tq * bands * n_probes, run_step,
        (jnp.int32(0), jnp.int32(0)),
    )
    stats = jnp.zeros((1, 8), jnp.int32)
    stats = stats.at[0, 0].set(wr)
    stats = stats.at[0, 1].set(ovf)
    stat_ref[:] = stats


def _probe_pallas(qkeys, masks, active, indptr, ids, *, plan: ProbePlan,
                  bands: int, n_probes: int, interpret: bool):
    """One probe-kernel launch: ``(slots (cap,), counts (tq,),
    stats (8,))`` — stats[0] ids written, stats[1] overflow flag."""
    tq, cap, blk = plan
    out, cnt, stat = pl.pallas_call(
        functools.partial(
            _probe_kernel, bands=bands, n_probes=n_probes, tq=tq,
            cap=cap, blk=blk,
        ),
        grid=(1,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),  # qkeys (bands, tq)
            pl.BlockSpec(memory_space=pltpu.VMEM),  # masks (1, P)
            pl.BlockSpec(memory_space=pltpu.VMEM),  # active (1, tq)
            pl.BlockSpec(memory_space=pltpu.VMEM),  # indptr (bands, nb+1)
            pl.BlockSpec(memory_space=pltpu.ANY),   # ids (bands, n+blk)
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((1, cap + blk), jnp.int32),
            jax.ShapeDtypeStruct((1, tq), jnp.int32),
            jax.ShapeDtypeStruct((1, 8), jnp.int32),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, 1, blk), jnp.int32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(qkeys, masks, active, indptr, ids)
    return out[0, :cap], cnt[0], stat[0]


@functools.partial(
    jax.jit,
    static_argnames=("plan", "bands", "n_probes", "interpret"),
)
def _probe_gather_impl(qkeys, masks, active, indptr, ids, *,
                       plan: ProbePlan, bands: int, n_probes: int,
                       interpret: bool):
    return _probe_pallas(
        qkeys, masks, active, indptr, ids, plan=plan, bands=bands,
        n_probes=n_probes, interpret=interpret,
    )


def probe_gather(qkeys, masks, active, indptr, ids, *, plan: ProbePlan,
                 interpret: Optional[bool] = None):
    """Probe-walk one query tile against a device-resident banded CSR.

    ``qkeys`` (bands, tq) int32 band keys, ``masks`` (1, P) int32 XOR
    probe masks, ``active`` (1, tq) int32 (0 = skip the query's runs),
    ``indptr`` (bands, 2^band_bits + 1) int32 clamped offsets, ``ids``
    (bands, n + blk) int32 with the trailing block sentinel-padded.
    Returns ``(slots, counts, stats)``: the densely-packed pre-dedup
    candidate ids (``cap``, sentinel = int32 max beyond the write
    cursor), per-query attempted yields, and ``[written, overflow, ...]``
    scalars.  Exposed for unit tests; serving fuses this into
    ``device_probe_topk``."""
    if interpret is None:
        interpret = interpret_default()
    return _probe_gather_impl(
        qkeys, masks, active, indptr, ids, plan=plan,
        bands=int(qkeys.shape[0]), n_probes=int(masks.shape[1]),
        interpret=bool(interpret),
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "pplan", "fplan", "bands", "band_bits", "m", "row0s", "rows",
        "interpret",
    ),
)
def _device_probe_topk_impl(q, masks, active, indptr, ids, dead_full,
                            chunks, *, pplan: ProbePlan,
                            fplan: TopkPlan, bands: int, band_bits: int,
                            m: int, row0s, rows, interpret: bool):
    tq, cap, blk = pplan
    n_total = int(dead_full.shape[0])
    qkeys = device_band_keys(q, bands, band_bits)
    slots, cnt, stat = _probe_pallas(
        qkeys, masks, active, indptr, ids, plan=pplan, bands=bands,
        n_probes=int(masks.shape[1]), interpret=interpret,
    )
    # sort-unique dedup: ascending slot order restores ascending global
    # id order (the tie-break contract), sentinels sort last, and every
    # duplicate / sentinel / tombstoned slot goes dead so it can never
    # displace a live candidate in the re-rank
    s = jnp.sort(slots)
    dup = jnp.concatenate(
        [jnp.zeros((1,), jnp.bool_), s[1:] == s[:-1]]
    )
    invalid = (s >= jnp.int32(n_total)) | dup
    sc = jnp.clip(s, 0, max(n_total - 1, 0))
    dead_c = invalid | (dead_full[sc] != 0)
    n_live_cand = jnp.sum(~dead_c).astype(jnp.int32)
    # gather candidate code rows from the resident chunks — each live
    # id lands in exactly one chunk's REAL row range (chunk arrays pad
    # trailing rows; ``rows`` carries the real counts); dead slots keep
    # zeros
    g = jnp.zeros((cap, q.shape[1]), jnp.uint8)
    for row0, nc, arr in zip(row0s, rows, chunks):
        inc = (sc >= row0) & (sc < row0 + nc)
        loc = jnp.clip(sc - row0, 0, max(nc - 1, 0))
        g = jnp.where(inc[:, None], arr[loc], g)
    d, idx = fused_topk(
        q, g, cap, m, dead=dead_c.astype(jnp.uint8), plan=fplan,
        interpret=interpret,
    )
    gid = jnp.where(
        idx >= cap, jnp.int32(_INT32_MAX),
        s[jnp.clip(idx, 0, cap - 1)],
    )
    stat = stat.at[2].set(n_live_cand)
    return d, gid, stat, cnt


def device_probe_topk(q, masks, active, indptr, ids, dead_full, chunks,
                      row0s, rows, m: int, *, pplan: ProbePlan,
                      fplan: TopkPlan,
                      band_bits: int,
                      interpret: Optional[bool] = None):
    """The fused probe → dedup → gather → re-rank program for one query
    tile: ONE device dispatch, zero per-tile host work.

    ``q`` (tq, n_bytes) uint8 padded query tile, ``masks`` (1, P)
    int32, ``active`` (1, tq) int32, ``indptr``/``ids`` the device-
    resident CSR (see ``probe_gather``), ``dead_full`` (n_total,) uint8
    full tombstone vector, ``chunks`` the resident code chunk arrays
    (possibly row-padded) with ``row0s``/``rows`` their static global
    row offsets and REAL row counts.  Returns device arrays ``(dist
    (tq, m), gid (tq, m), stats (8,), counts (tq,))`` — ``stats =
    [gathered, overflow, live_candidates, 0...]``; the caller applies
    the post-hoc fallback ladder (starved / dense / budget overflow)
    before trusting the tile."""
    if interpret is None:
        interpret = interpret_default()
    return _device_probe_topk_impl(
        q, masks, active, indptr, ids, dead_full, tuple(chunks),
        pplan=pplan, fplan=fplan, bands=int(indptr.shape[0]),
        band_bits=int(band_bits), m=int(m),
        row0s=tuple(int(r) for r in row0s),
        rows=tuple(int(r) for r in rows),
        interpret=bool(interpret),
    )
