"""Pallas TPU kernel: fused projection with in-kernel mask regeneration.

The memory-bound regime (SURVEY.md §8 step 4b): when ``k·d`` is large
(config 3: 512×16384, config 4 at code length 4096+), keeping ``R``
resident costs HBM capacity *and* bandwidth — every batch re-reads k·d
values.  Since sparse/sign projection matrices are pure PRNG functions of
``(seed, block)``, this kernel regenerates each ``(k, BLOCK_D)`` column
block **inside VMEM** from the TPU's hardware PRNG while contracting, so
``R`` never exists in HBM at all: HBM traffic drops from
``n·d + k·d + n·k`` to ``n·d + n·k`` per batch.

Matrix definition
-----------------
Block ``j`` of the matrix is a pure function of ``(seed, j)`` via
``pltpu.prng_seed(seed, j)`` — deterministic, row-tile-independent, and
reproducible across any row batching.  This is a *third* PRNG family
(alongside the numpy backend's Generator and the jax backend's threefry):
same distribution, different streams, as SURVEY.md §8 prescribes —
cross-family parity holds at the distance-distortion level only.
``BLOCK_D`` is part of the definition (like ``kernels.COLUMN_BLOCK``).

The mask is generated as exact ``{+1, -1, 0}`` values and the common scale
``v = sqrt(1/(density·k))`` is applied once to the accumulated output, so
mask quantization contributes zero error regardless of MXU precision.

.. warning:: ``BLOCK_D``, the ``(seed, block)`` seeding scheme, and
   ``_uniform_from_bits`` are part of the persisted-model format: any change
   silently redefines every saved lazy model.  The structural half of the
   contract is guarded by the always-on CPU tests
   (``tests/test_pallas.py::test_structural_invariants_everywhere``); the
   value half needs the real chip — run ``RP_TEST_TPU=1 pytest
   tests/test_pallas.py`` before changing any of them.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from randomprojection_tpu.ops.split_matmul import split_f32_to_bf16_pair
from randomprojection_tpu.utils.validation import check_density, check_input_size

__all__ = ["BLOCK_D", "BLOCK_N", "fused_sparse_project", "pallas_sparse_matrix"]

BLOCK_D = 512  # contraction-dim tile; part of the matrix definition
BLOCK_N = 256  # row tile (tunable; does NOT affect the matrix)

# Mosaic's scoped-VMEM limit is 16 MiB; the mask cache gets what is left
# after the pipeline's own buffers, with headroom for Mosaic temporaries
# (measured: a 2048-row split2 tile whose buffers sum to 16.5 MiB actually
# allocates 18.86 MiB — real overhead ≈ 2.4 MiB, so 3 MiB headroom).
_VMEM_LIMIT = 16 << 20
_VMEM_HEADROOM = 3 << 20


def _reserved_bytes(block_n: int, k: int, mxu_mode: str,
                    x_itemsize: int) -> int:
    """Scoped-VMEM estimate for the kernel's own buffers at one row tile:
    x double-buffered, the o block (+ revolving copy), the f32 mask
    generation temporary, the split2 hi/lo halves, plus Mosaic headroom."""
    return (
        2 * block_n * BLOCK_D * x_itemsize
        + 2 * block_n * k * 4
        + k * BLOCK_D * 4
        + (2 * block_n * BLOCK_D * 2 if mxu_mode == "split2" else 0)
        + _VMEM_HEADROOM
    )


def _auto_block_n(n: int, d: int, k: int, mxu_mode: str) -> int:
    """Largest row tile that helps and harms nothing.

    Measured on the real chip (round 4, 131072×4096→256 through the
    anti-cache harness): 1024-row tiles beat the 256 default by ~20–30%
    in every mxu mode (fewer grid rows ⇒ fewer o-block drains, better
    pipeline occupancy).  A bigger tile is taken only when it

    - fits scoped VMEM (2048 measurably blows the 16 MiB limit, and large
      ``k`` shrinks the feasible tile),
    - pads no extra rows vs the 256 baseline (a 1280-row bucketed batch
      must not balloon to 2048 — that would defeat ``row_bucket``'s ≤25%
      pad-waste cap), and
    - does not starve a mask cache that is FULL at the baseline tile (the
      larger tile's buffers shrink the cache budget; evicting a full
      cache re-pays mask generation per grid row, the exact cost the
      cache exists to remove).  When the cache is partial either way the
      larger tile wins (measured: config-3's d=16384 runs ~20% faster at
      1024 despite a smaller partial cache — fewer grid rows also mean
      fewer regenerations of the uncached blocks).
    """
    base = BLOCK_N
    if n < base:
        # small batch: one tile, padded to the sublane multiple — same
        # tile the backend used to request explicitly
        return max(8, -(-n // 8) * 8)
    x_itemsize = 2 if mxu_mode == "bf16" else 4
    nj = -(-d // BLOCK_D)
    block_bytes = k * BLOCK_D * (4 if mxu_mode == "f32" else 2)

    def slots(bn):
        free = _VMEM_LIMIT - _reserved_bytes(bn, k, mxu_mode, x_itemsize)
        return max(0, free) // block_bytes

    base_rows = -(-n // base) * base
    for bn in (1024, 512):
        if (
            _reserved_bytes(bn, k, mxu_mode, x_itemsize) <= _VMEM_LIMIT
            and -(-n // bn) * bn == base_rows
            and not (slots(bn) < nj <= slots(base))
        ):
            return bn
    return base


def _seed_to_i32(seed) -> int:
    """Fold any Python int seed into int32 (the SMEM scalar width).

    Part of the matrix definition: seeds are taken mod 2^32 and
    reinterpreted signed, so uint32 seeds from unseeded fits work."""
    import numpy as np

    return int(np.uint32(int(seed) & 0xFFFFFFFF).astype(np.int32))


def _uniform_from_bits(bits):
    # top 24 bits → uniform f32 in [0, 1): exact ulp spacing, no rounding
    # bias.  prng_random_bits yields signed int32 — bitcast to uint32 first
    # or the arithmetic shift folds the sign in and u spans [-0.5, 0.5).
    bits = pltpu.bitcast(bits, jnp.uint32) >> 8
    # Mosaic lacks uint32→f32; post-shift values fit in int31, so the
    # int32 reinterpretation is value-preserving and casts fine
    return pltpu.bitcast(bits, jnp.int32).astype(jnp.float32) * (1.0 / (1 << 24))


def _mask_block(density):
    """{+1, -1, 0} w.p. {density/2, density/2, 1-density} from one uniform."""

    def gen(shape):
        u = _uniform_from_bits(pltpu.prng_random_bits(shape))
        plus = u < density * 0.5
        minus = jnp.logical_and(u < density, jnp.logical_not(plus))
        return jnp.where(plus, 1.0, jnp.where(minus, -1.0, 0.0))

    return gen


_DOT_KD = (((1,), (1,)), ((), ()))  # x[n,d] · r[k,d] → [n,k]


def _project_kernel(seed_ref, x_ref, o_ref, *scratch, k, density, scale,
                    n_blocks_d, mxu_mode, cache_blocks):
    i = pl.program_id(0)
    j = pl.program_id(1)

    def _gen_mask(dtype):
        # (seed, global block) → bits: row-tile-free.  seed_ref[1] is the
        # column-block offset of this shard under feature-axis TP (0
        # unsharded), so a shard holding X[:, lo:hi] regenerates exactly
        # the mask blocks of its own column range — the same global
        # matrix, distributed.
        pltpu.prng_seed(seed_ref[0], j + seed_ref[1])
        # the bf16 cast is exact: entries are {+1, -1, 0}
        return _mask_block(density)((k, x_ref.shape[1])).astype(dtype)

    # Mask-block VMEM cache (round-4 probe finding: in the MXU-bound regime
    # — large k — regenerating the mask per (row tile, column block) grid
    # step costs ~half the throughput; with a constant mask the same dot
    # pipeline runs at ~86% of peak).  ``scratch[0]`` is a persistent VMEM
    # scratch of ``cache_blocks`` mask blocks (+1 shared regen slot when
    # not every block fits): block j's mask is GENERATED once, on the first
    # row tile, and re-read from VMEM by every later row tile — identical
    # values (the (seed, block) stream is unchanged), ~zero VPU cost after
    # row tile 0.  Overflow blocks (j >= cache_blocks) share the last slot
    # and regenerate every step, exactly like the pre-cache kernel.  When
    # even one slot doesn't fit in scoped VMEM there is no scratch at all
    # and every step regenerates (the pre-cache kernel, byte for byte).
    if not scratch:
        r = _gen_mask(jnp.bfloat16 if mxu_mode != "f32" else jnp.float32)
    else:
        r_ref = scratch[0]
        full = cache_blocks >= n_blocks_d
        slot = j if full else jnp.minimum(j, cache_blocks)
        gen = (i == 0) if full else jnp.logical_or(i == 0, j >= cache_blocks)

        @pl.when(gen)
        def _():
            r_ref[slot] = _gen_mask(r_ref.dtype)

        r = r_ref[slot]

    @pl.when(j == 0)
    def _():
        o_ref[:] = jnp.zeros_like(o_ref)

    if mxu_mode == "split2":
        # Split-precision contraction entirely in VMEM (the route to the T1
        # roofline, BASELINE.json:5): the mask is exact in bf16, X is split
        # into hi/lo bf16 halves by the shared mantissa-bitmask helper
        # (``ops/split_matmul.py`` — here with zero HBM roundtrip for the
        # halves), and two single-pass bf16 MXU contractions accumulate in
        # f32 — f32-grade output at 2 MXU passes per block, no R and no
        # X-halves traffic in HBM.
        x_hi, x_lo = split_f32_to_bf16_pair(x_ref[:])
        acc = jax.lax.dot_general(
            x_hi, r, dimension_numbers=_DOT_KD,
            preferred_element_type=jnp.float32,
        )
        acc += jax.lax.dot_general(
            x_lo, r, dimension_numbers=_DOT_KD,
            preferred_element_type=jnp.float32,
        )
        o_ref[:] += acc
    else:
        # 'bf16': x arrives bf16 (the data's own precision — half the x
        # HBM traffic of the f32 modes) and contracts against the exact
        # bf16 mask in ONE MXU pass with f32 accumulation.
        # 'f32': single f32 dot at Mosaic's default precision.
        o_ref[:] += jax.lax.dot_general(
            x_ref[:], r, dimension_numbers=_DOT_KD,
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == n_blocks_d - 1)
    def _():
        o_ref[:] = o_ref[:] * scale


def _matrix_kernel(seed_ref, o_ref, *, k, density, scale):
    j = pl.program_id(0)
    pltpu.prng_seed(seed_ref[0], j)
    o_ref[:] = _mask_block(density)((k, o_ref.shape[1])) * scale


def fused_sparse_project(
    x,
    seed,
    n_components: int,
    density: float,
    *,
    block_n: Optional[int] = None,
    block_offset=0,
    mxu_mode: str = "f32",
    interpret: bool = False,
    no_cache: bool = False,
):
    """``Y = X @ R(seed)ᵀ`` with ``R`` regenerated in-kernel, never in HBM.

    ``density=1`` degenerates to the sign/Rademacher kernel.  ``x`` is any
    ``(n, d)`` float array; ``n_components`` must be a multiple of 8 (f32
    sublane tiling).  Ragged ``n``/``d`` are zero-padded (zero rows/cols
    contribute nothing; the mask block for padded ``d`` is generated but
    multiplied by zeros).

    ``block_n=None`` (default) picks the largest row tile that fits scoped
    VMEM for this shape (``_auto_block_n``; 1024 at the headline shapes —
    measured 20–30% faster than 256 in every mxu mode); pass an explicit
    tile only to pin it (tests pin 128 to prove tile-invariance).

    ``block_offset`` (int or traced int32 scalar) shifts the column-block
    indices: a feature-axis TP shard holding ``X[:, lo:hi]`` (``lo``
    BLOCK_D-aligned) passes ``lo // BLOCK_D`` and computes its partial
    product against exactly its own blocks of the global matrix.  The
    per-call scale is linear, so ``psum`` of the scaled partials equals the
    unsharded result.

    ``mxu_mode`` selects the contraction arithmetic — NOT part of the matrix
    definition (all modes contract the identical mask):

    - ``'f32'``: f32 dot at Mosaic's default precision (bf16-grade output).
    - ``'split2'``: X split hi/lo bf16 in VMEM vs the exact-in-bf16 mask —
      2 single-pass MXU contractions, f32-grade output (~1e-6 distortion),
      the mode that reaches the T1 roofline (~R1/2 ≈ 47-94M rows/s).
    - ``'bf16'``: X kept bfloat16 end-to-end (half the x HBM traffic — the
      mode for bf16-fitted models, where 1 exact-mask pass IS the data's
      own precision), 1 MXU pass, f32 accumulation.

    VMEM-safety fallback: the mask-cache sizing relies on a measured 3 MiB
    Mosaic-temporary headroom (``_VMEM_HEADROOM``).  Should an untested
    ``(shape, block_n, k, mode)`` combination still blow the scoped-VMEM
    limit at compile, an eager call retries once with the cache disabled
    (the documented regenerate-every-step degeneration) and remembers the
    failing key.  Traced callers compile outside this frame and cannot be
    caught here — they opt into the degeneration explicitly with
    ``no_cache=True`` after catching the failure at their own call site
    (the mesh path: ``jax_backend._project_prepared``).  Cache presence
    does not change values — the (seed, block) streams are identical
    either way.
    """
    # keyed by input shape too: the VMEM-feasible tile and cache sizing are
    # resolved per (n, d) by _auto_block_n, so one failing exotic shape must
    # not disable the cache for the (k, mode)'s healthy shapes
    key = (tuple(x.shape), block_n, n_components, mxu_mode)
    if not no_cache and key not in _NO_CACHE_KEYS:
        try:
            return _fused_impl(
                x, seed, n_components, density, block_n=block_n,
                block_offset=block_offset, mxu_mode=mxu_mode,
                interpret=interpret, no_cache=False,
            )
        except Exception as e:  # pragma: no cover — needs a Mosaic VMEM OOM
            if not is_vmem_oom(e):
                raise
            from randomprojection_tpu.utils.observability import logger

            logger.warning(
                "fused kernel hit a scoped-VMEM limit for key %s; retrying "
                "without the in-VMEM mask cache (regenerate-every-step "
                "degradation)", key,
            )
            record_vmem_oom_retry(x.shape, mxu_mode, n_components)
            out = _fused_impl(
                x, seed, n_components, density, block_n=block_n,
                block_offset=block_offset, mxu_mode=mxu_mode,
                interpret=interpret, no_cache=True,
            )
            # memoize only once the degraded retry actually succeeded: a
            # misclassified error must not pin this shape to the slow path
            # for the process lifetime (ADVICE r5)
            _NO_CACHE_KEYS.add(key)
            return out
    return _fused_impl(
        x, seed, n_components, density, block_n=block_n,
        block_offset=block_offset, mxu_mode=mxu_mode,
        interpret=interpret, no_cache=True,
    )


_NO_CACHE_KEYS: set = set()

# Phrasings that mark a genuine allocation failure.  Mosaic/XLA spell
# scoped-VMEM exhaustion variously across versions ("scoped allocation ...
# exceeds", "RESOURCE_EXHAUSTED", "out of memory", "vmem limit"), so the
# classifier requires 'vmem' AND one of these — a diagnostic that merely
# *mentions* VMEM stats no longer routes into the degraded retry.
_VMEM_OOM_MARKERS = (
    "exceed", "alloc", "oom", "out of memory", "resource_exhausted",
    "resource exhausted", "limit", "too large", "too big", "insufficient",
)


def is_vmem_oom(exc: Exception) -> bool:
    """Classify a Mosaic scoped-VMEM exhaustion (the one failure the
    no-cache degeneration can fix) — shared by the eager fallback above and
    the mesh call site (``jax_backend._project_prepared``), so the two
    paths cannot drift when an error wording changes.  Requires the memory
    name ('vmem', covering 'scoped vmem' spellings) AND an allocation/
    exhaustion phrasing (ADVICE r5): a bare 'vmem' match swallowed any
    error that merely mentioned VMEM and silently degraded that shape to
    the regenerate-every-step path for the process lifetime."""
    s = str(exc).lower()
    return "vmem" in s and any(m in s for m in _VMEM_OOM_MARKERS)


def record_vmem_oom_retry(shape, mxu_mode: str, n_components: int) -> None:
    """Degraded-retry telemetry, shared by both call sites (the eager
    fallback above and ``jax_backend._project_prepared``'s mesh retry) —
    one counter name and one event schema, so the retry count can never
    split between the two paths."""
    from randomprojection_tpu.utils import telemetry

    telemetry.registry().counter_inc("backend.vmem_oom_retries")
    telemetry.emit(
        telemetry.EVENTS.BACKEND_VMEM_OOM_RETRY, shape=list(shape),
        mxu_mode=mxu_mode, n_components=n_components,
        **telemetry.trace_fields(),
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "seed", "n_components", "density", "block_n", "mxu_mode", "interpret",
        "no_cache",
    ),
)
def _fused_impl(
    x,
    seed,
    n_components: int,
    density: float,
    *,
    block_n: Optional[int],
    block_offset,
    mxu_mode: str,
    interpret: bool,
    no_cache: bool,
):
    if mxu_mode not in ("f32", "split2", "bf16"):
        raise ValueError(
            f"mxu_mode must be 'f32', 'split2' or 'bf16', got {mxu_mode!r}"
        )
    density = check_density(density, x.shape[1])
    check_input_size(n_components, x.shape[1])
    if n_components % 8:
        raise ValueError(
            f"n_components must be a multiple of 8 for the fused TPU kernel, "
            f"got {n_components}"
        )
    n, d = x.shape
    k = n_components
    scale = 1.0 / math.sqrt(density * k)
    if block_n is None:
        block_n = _auto_block_n(n, d, k, mxu_mode)

    seed = _seed_to_i32(seed)
    n_pad = -n % block_n
    d_pad = -d % BLOCK_D
    if n_pad or d_pad:
        x = jnp.pad(x, ((0, n_pad), (0, d_pad)))
    x = x.astype(jnp.bfloat16 if mxu_mode == "bf16" else jnp.float32)
    x_itemsize = x.dtype.itemsize
    ni = x.shape[0] // block_n
    nj = x.shape[1] // BLOCK_D

    # Mask-cache sizing: the cache holds the mask in the dtype the dot
    # consumes (bf16 for split2/bf16 — exact for ±1/0 — f32 otherwise) and
    # takes whatever scoped VMEM remains after the pipeline's own buffers
    # (x double-buffered, o block, the f32 generation temporary, the split
    # halves) plus headroom.  The overflow regen slot counts against the
    # same budget (``max_slots - 1``): cache_blocks == 0 degenerates to the
    # original regenerate-every-step kernel via the single shared slot, and
    # when not even that one slot fits the kernel gets NO scratch and
    # regenerates into a value, so no shape that compiled pre-cache can be
    # pushed over Mosaic's scoped-VMEM limit by the cache.
    cache_itemsize = 4 if mxu_mode == "f32" else 2
    block_bytes = k * BLOCK_D * cache_itemsize
    reserved = _reserved_bytes(block_n, k, mxu_mode, x_itemsize)
    max_slots = max(0, _VMEM_LIMIT - reserved) // block_bytes
    cache_blocks = nj if max_slots >= nj else max(0, max_slots - 1)
    slots = nj if cache_blocks >= nj else cache_blocks + 1
    # ni == 1: every block is generated once and read once — nothing to
    # reuse, so the cache would only add a VMEM round-trip per step; keep
    # the single-row-tile path byte-for-byte the pre-cache kernel
    scratch_shapes = (
        [
            pltpu.VMEM(
                (slots, k, BLOCK_D),
                jnp.float32 if cache_itemsize == 4 else jnp.bfloat16,
            )
        ]
        if max_slots > 0 and ni > 1 and not no_cache
        else []
    )

    seed_arr = jnp.stack(
        [jnp.int32(seed), jnp.asarray(block_offset, dtype=jnp.int32)]
    )
    y = pl.pallas_call(
        functools.partial(
            _project_kernel, k=k, density=density, scale=scale, n_blocks_d=nj,
            mxu_mode=mxu_mode, cache_blocks=cache_blocks,
        ),
        grid=(ni, nj),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (block_n, BLOCK_D),
                lambda i, j: (i, j),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (block_n, k), lambda i, j: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((x.shape[0], k), jnp.float32),
        scratch_shapes=scratch_shapes,
        cost_estimate=pl.CostEstimate(
            # split2 executes two MXU contractions per block
            flops=(2 if mxu_mode == "split2" else 1)
            * 2 * x.shape[0] * x.shape[1] * k,
            bytes_accessed=(
                x.shape[0] * x.shape[1] * x_itemsize + x.shape[0] * k * 4
            ),
            transcendentals=0,
        ),
        interpret=interpret,
    )(seed_arr, x)
    return y[:n]


@functools.partial(
    jax.jit,
    static_argnames=("seed", "n_components", "n_features", "density", "interpret"),
)
def pallas_sparse_matrix(
    seed, n_components: int, n_features: int, density: float, *,
    interpret: bool = False
):
    """Materialize the exact matrix ``fused_sparse_project`` uses (tests,
    ``components_`` introspection, pinv).  Same ``(seed, block)`` streams."""
    density = check_density(density, n_features)
    check_input_size(n_components, n_features)
    if n_components % 8:
        raise ValueError(
            f"n_components must be a multiple of 8 for the fused TPU kernel, "
            f"got {n_components}"
        )
    seed = _seed_to_i32(seed)
    k = n_components
    scale = 1.0 / math.sqrt(density * k)
    d_pad = -n_features % BLOCK_D
    d_full = n_features + d_pad
    nj = d_full // BLOCK_D

    seed_arr = jnp.asarray([seed], dtype=jnp.int32)
    R = pl.pallas_call(
        functools.partial(_matrix_kernel, k=k, density=density, scale=scale),
        grid=(nj,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec(
            (k, BLOCK_D), lambda j: (0, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((k, d_full), jnp.float32),
        interpret=interpret,
    )(seed_arr)
    return R[:, :n_features]
