"""Pallas TPU kernel: fused projection with in-kernel mask regeneration.

The memory-bound regime (SURVEY.md §8 step 4b): when ``k·d`` is large
(config 3: 512×16384, config 4 at code length 4096+), keeping ``R``
resident costs HBM capacity *and* bandwidth — every batch re-reads k·d
values.  Since sparse/sign projection matrices are pure PRNG functions of
``(seed, block)``, this kernel regenerates each ``(k, BLOCK_D)`` column
block **inside VMEM** from the TPU's hardware PRNG while contracting, so
``R`` never exists in HBM at all: HBM traffic drops from
``n·d + k·d + n·k`` to ``n·d + n·k`` per batch.

Matrix definition
-----------------
Block ``j`` of the matrix is a pure function of ``(seed, j)`` via
``pltpu.prng_seed(seed, j)`` — deterministic, row-tile-independent, and
reproducible across any row batching.  This is a *third* PRNG family
(alongside the numpy backend's Generator and the jax backend's threefry):
same distribution, different streams, as SURVEY.md §8 prescribes —
cross-family parity holds at the distance-distortion level only.
``BLOCK_D`` is part of the definition (like ``kernels.COLUMN_BLOCK``).

The mask is generated as exact ``{+1, -1, 0}`` values and the common scale
``v = sqrt(1/(density·k))`` is applied once to the accumulated output, so
mask quantization contributes zero error regardless of MXU precision.

.. warning:: ``BLOCK_D``, the ``(seed, block)`` seeding scheme, and
   ``_uniform_from_bits`` are part of the persisted-model format: any change
   silently redefines every saved lazy model.  The structural half of the
   contract is guarded by the always-on CPU tests
   (``tests/test_pallas.py::test_structural_invariants_everywhere``); the
   value half needs the real chip — run ``RP_TEST_TPU=1 pytest
   tests/test_pallas.py`` before changing any of them.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from randomprojection_tpu.ops.split_matmul import split_f32_to_bf16_pair
from randomprojection_tpu.utils.validation import check_density, check_input_size

__all__ = ["BLOCK_D", "BLOCK_N", "fused_sparse_project", "pallas_sparse_matrix"]

BLOCK_D = 512  # contraction-dim tile; part of the matrix definition
BLOCK_N = 256  # row tile (tunable; does NOT affect the matrix)


def _seed_to_i32(seed) -> int:
    """Fold any Python int seed into int32 (the SMEM scalar width).

    Part of the matrix definition: seeds are taken mod 2^32 and
    reinterpreted signed, so uint32 seeds from unseeded fits work."""
    import numpy as np

    return int(np.uint32(int(seed) & 0xFFFFFFFF).astype(np.int32))


def _uniform_from_bits(bits):
    # top 24 bits → uniform f32 in [0, 1): exact ulp spacing, no rounding
    # bias.  prng_random_bits yields signed int32 — bitcast to uint32 first
    # or the arithmetic shift folds the sign in and u spans [-0.5, 0.5).
    bits = pltpu.bitcast(bits, jnp.uint32) >> 8
    # Mosaic lacks uint32→f32; post-shift values fit in int31, so the
    # int32 reinterpretation is value-preserving and casts fine
    return pltpu.bitcast(bits, jnp.int32).astype(jnp.float32) * (1.0 / (1 << 24))


def _mask_block(density):
    """{+1, -1, 0} w.p. {density/2, density/2, 1-density} from one uniform."""

    def gen(shape):
        u = _uniform_from_bits(pltpu.prng_random_bits(shape))
        plus = u < density * 0.5
        minus = jnp.logical_and(u < density, jnp.logical_not(plus))
        return jnp.where(plus, 1.0, jnp.where(minus, -1.0, 0.0))

    return gen


_DOT_KD = (((1,), (1,)), ((), ()))  # x[n,d] · r[k,d] → [n,k]


def _project_kernel(seed_ref, x_ref, o_ref, *, k, density, scale, n_blocks_d,
                    mxu_mode):
    j = pl.program_id(1)
    # (seed, global block) → bits: row-tile-free.  seed_ref[1] is the
    # column-block offset of this shard under feature-axis TP (0 unsharded),
    # so a shard holding X[:, lo:hi] regenerates exactly the mask blocks of
    # its own column range — the same global matrix, distributed.
    pltpu.prng_seed(seed_ref[0], j + seed_ref[1])
    r = _mask_block(density)((k, x_ref.shape[1]))

    @pl.when(j == 0)
    def _():
        o_ref[:] = jnp.zeros_like(o_ref)

    if mxu_mode == "split2":
        # Split-precision contraction entirely in VMEM (the route to the T1
        # roofline, BASELINE.json:5): the mask is exact in bf16, X is split
        # into hi/lo bf16 halves by the shared mantissa-bitmask helper
        # (``ops/split_matmul.py`` — here with zero HBM roundtrip for the
        # halves), and two single-pass bf16 MXU contractions accumulate in
        # f32 — f32-grade output at 2 MXU passes per block, no R and no
        # X-halves traffic in HBM.
        x_hi, x_lo = split_f32_to_bf16_pair(x_ref[:])
        r16 = r.astype(jnp.bfloat16)  # exact: entries are {+1, -1, 0}
        acc = jax.lax.dot_general(
            x_hi, r16, dimension_numbers=_DOT_KD,
            preferred_element_type=jnp.float32,
        )
        acc += jax.lax.dot_general(
            x_lo, r16, dimension_numbers=_DOT_KD,
            preferred_element_type=jnp.float32,
        )
        o_ref[:] += acc
    else:
        o_ref[:] += jax.lax.dot_general(
            x_ref[:], r, dimension_numbers=_DOT_KD,
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == n_blocks_d - 1)
    def _():
        o_ref[:] = o_ref[:] * scale


def _matrix_kernel(seed_ref, o_ref, *, k, density, scale):
    j = pl.program_id(0)
    pltpu.prng_seed(seed_ref[0], j)
    o_ref[:] = _mask_block(density)((k, o_ref.shape[1])) * scale


@functools.partial(
    jax.jit,
    static_argnames=(
        "seed", "n_components", "density", "block_n", "mxu_mode", "interpret",
    ),
)
def fused_sparse_project(
    x,
    seed,
    n_components: int,
    density: float,
    *,
    block_n: int = BLOCK_N,
    block_offset=0,
    mxu_mode: str = "f32",
    interpret: bool = False,
):
    """``Y = X @ R(seed)ᵀ`` with ``R`` regenerated in-kernel, never in HBM.

    ``density=1`` degenerates to the sign/Rademacher kernel.  ``x`` is any
    ``(n, d)`` float array; ``n_components`` must be a multiple of 8 (f32
    sublane tiling).  Ragged ``n``/``d`` are zero-padded (zero rows/cols
    contribute nothing; the mask block for padded ``d`` is generated but
    multiplied by zeros).

    ``block_offset`` (int or traced int32 scalar) shifts the column-block
    indices: a feature-axis TP shard holding ``X[:, lo:hi]`` (``lo``
    BLOCK_D-aligned) passes ``lo // BLOCK_D`` and computes its partial
    product against exactly its own blocks of the global matrix.  The
    per-call scale is linear, so ``psum`` of the scaled partials equals the
    unsharded result.

    ``mxu_mode`` selects the contraction arithmetic — NOT part of the matrix
    definition (both modes contract the identical mask):

    - ``'f32'``: f32 dot at Mosaic's default precision (bf16-grade output).
    - ``'split2'``: X split hi/lo bf16 in VMEM vs the exact-in-bf16 mask —
      2 single-pass MXU contractions, f32-grade output (~1e-6 distortion),
      the mode that reaches the T1 roofline (~R1/2 ≈ 47-94M rows/s).
    """
    if mxu_mode not in ("f32", "split2"):
        raise ValueError(f"mxu_mode must be 'f32' or 'split2', got {mxu_mode!r}")
    density = check_density(density, x.shape[1])
    check_input_size(n_components, x.shape[1])
    if n_components % 8:
        raise ValueError(
            f"n_components must be a multiple of 8 for the fused TPU kernel, "
            f"got {n_components}"
        )
    n, d = x.shape
    k = n_components
    scale = 1.0 / math.sqrt(density * k)

    seed = _seed_to_i32(seed)
    n_pad = -n % block_n
    d_pad = -d % BLOCK_D
    if n_pad or d_pad:
        x = jnp.pad(x, ((0, n_pad), (0, d_pad)))
    x = x.astype(jnp.float32)
    ni = x.shape[0] // block_n
    nj = x.shape[1] // BLOCK_D

    seed_arr = jnp.stack(
        [jnp.int32(seed), jnp.asarray(block_offset, dtype=jnp.int32)]
    )
    y = pl.pallas_call(
        functools.partial(
            _project_kernel, k=k, density=density, scale=scale, n_blocks_d=nj,
            mxu_mode=mxu_mode,
        ),
        grid=(ni, nj),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (block_n, BLOCK_D),
                lambda i, j: (i, j),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (block_n, k), lambda i, j: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((x.shape[0], k), jnp.float32),
        cost_estimate=pl.CostEstimate(
            # split2 executes two MXU contractions per block
            flops=(2 if mxu_mode == "split2" else 1)
            * 2 * x.shape[0] * x.shape[1] * k,
            bytes_accessed=x.shape[0] * x.shape[1] * 4 + x.shape[0] * k * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(seed_arr, x)
    return y[:n]


@functools.partial(
    jax.jit,
    static_argnames=("seed", "n_components", "n_features", "density", "interpret"),
)
def pallas_sparse_matrix(
    seed, n_components: int, n_features: int, density: float, *,
    interpret: bool = False
):
    """Materialize the exact matrix ``fused_sparse_project`` uses (tests,
    ``components_`` introspection, pinv).  Same ``(seed, block)`` streams."""
    density = check_density(density, n_features)
    check_input_size(n_components, n_features)
    if n_components % 8:
        raise ValueError(
            f"n_components must be a multiple of 8 for the fused TPU kernel, "
            f"got {n_components}"
        )
    seed = _seed_to_i32(seed)
    k = n_components
    scale = 1.0 / math.sqrt(density * k)
    d_pad = -n_features % BLOCK_D
    d_full = n_features + d_pad
    nj = d_full // BLOCK_D

    seed_arr = jnp.asarray([seed], dtype=jnp.int32)
    R = pl.pallas_call(
        functools.partial(_matrix_kernel, k=k, density=density, scale=scale),
        grid=(nj,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec(
            (k, BLOCK_D), lambda j: (0, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((k, d_full), jnp.float32),
        interpret=interpret,
    )(seed_arr)
    return R[:, :n_features]
