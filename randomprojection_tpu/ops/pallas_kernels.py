"""Pallas TPU kernel: fused projection with in-kernel mask regeneration.

The memory-bound regime (SURVEY.md §8 step 4b): when ``k·d`` is large
(config 3: 512×16384, config 4 at code length 4096+), keeping ``R``
resident costs HBM capacity *and* bandwidth — every batch re-reads k·d
values.  Since sparse/sign projection matrices are pure PRNG functions of
``(seed, block)``, this kernel regenerates each ``(k, BLOCK_D)`` column
block **inside VMEM** from the TPU's hardware PRNG while contracting, so
``R`` never exists in HBM at all: HBM traffic drops from
``n·d + k·d + n·k`` to ``n·d + n·k`` per batch.

Matrix definition
-----------------
Block ``j`` of the matrix is a pure function of ``(seed, j)`` via
``pltpu.prng_seed(seed, j)`` — deterministic, row-tile-independent, and
reproducible across any row batching.  This is a *third* PRNG family
(alongside the numpy backend's Generator and the jax backend's threefry):
same distribution, different streams, as SURVEY.md §8 prescribes —
cross-family parity holds at the distance-distortion level only.
``BLOCK_D`` is part of the definition (like ``kernels.COLUMN_BLOCK``).

The mask is generated as exact ``{+1, -1, 0}`` values and the common scale
``v = sqrt(1/(density·k))`` is applied once to the accumulated output, so
mask quantization contributes zero error regardless of MXU precision.

.. warning:: ``BLOCK_D``, the ``(seed, block)`` seeding scheme, and
   ``_uniform_from_bits`` are part of the persisted-model format: any change
   silently redefines every saved lazy model.  The structural half of the
   contract is guarded by the always-on CPU tests
   (``tests/test_pallas.py::test_structural_invariants_everywhere``); the
   value half needs the real chip — run ``RP_TEST_TPU=1 pytest
   tests/test_pallas.py`` before changing any of them.

Double-buffered x DMA (ISSUE 9)
-------------------------------
The default single-device route now streams the ``(block_n, BLOCK_D)``
x tiles through the kernel itself: x stays HBM-resident
(``memory_space=ANY``), the grid runs over row tiles only, and the
column-block loop moves INSIDE the kernel with the next tile's HBM→VMEM
copy manually double-buffered (``pltpu.make_async_copy``, two revolving
VMEM slots + DMA semaphores — the exact ``ops/topk_kernels.py`` r12
pattern) so the MXU never waits on the x fetch.  This targets the ~13%
in-kernel x-fetch/compute interleave the r5 trace attributed
(BASELINE.md "r5 trace decomposition"); the automatic Pallas pipeline
(the pre-r14 kernel) remains as ``dma=False`` and as the VMEM-OOM
degraded retry.  DMA does not change values: both paths contract the
identical mask blocks against the identical x tiles in the identical
order (parity-gated by ``make transform-smoke`` and
``tests/test_pallas_dma.py``).

Interpreter mask stream (tests only)
------------------------------------
``pltpu.prng_seed``/``prng_random_bits`` have NO CPU lowering (not even
a zero-bits stub — the lowering raises ``NotImplementedError``), so
``interpret=True`` substitutes a pure-jnp integer-hash stream for the
hardware PRNG: same ``{+1, -1, 0}`` distribution, same ``(seed, block)``
keying, a DIFFERENT stream.  It exists so tier-1 can execute the whole
kernel — DMAs, double buffering, mask cache, accumulation — on CPU and
parity-check the DMA path against the single-buffered path and the
matching ``pallas_sparse_matrix(interpret=True)`` matrix.  It is NOT
part of the persisted-model format: real models run the hardware PRNG,
and the backend refuses lazy materialization off-TPU either way.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from randomprojection_tpu.ops.split_matmul import split_f32_to_bf16_pair
from randomprojection_tpu.utils.validation import check_density, check_input_size

__all__ = [
    "BLOCK_D",
    "BLOCK_N",
    "fused_sparse_project",
    "fused_project_multistep",
    "pallas_sparse_matrix",
]

BLOCK_D = 512  # contraction-dim tile; part of the matrix definition
BLOCK_N = 256  # row tile (tunable; does NOT affect the matrix)

# Default transform route (ISSUE 9): manual double-buffered x DMA.  The
# automatic-pipeline kernel stays reachable as ``dma=False`` and as the
# scoped-VMEM-OOM degraded retry (``_NO_DMA_KEYS`` memoizes shapes that
# only compile single-buffered).
_DMA_DEFAULT = True

# Mosaic's scoped-VMEM limit is 16 MiB; the mask cache gets what is left
# after the pipeline's own buffers, with headroom for Mosaic temporaries
# (measured: a 2048-row split2 tile whose buffers sum to 16.5 MiB actually
# allocates 18.86 MiB — real overhead ≈ 2.4 MiB, so 3 MiB headroom).
_VMEM_LIMIT = 16 << 20
_VMEM_HEADROOM = 3 << 20


def _reserved_bytes(block_n: int, k: int, mxu_mode: str,
                    x_itemsize: int, *, dma: bool = _DMA_DEFAULT) -> int:
    """Scoped-VMEM estimate for the kernel's own buffers at one row tile:
    x double-buffered (two automatic pipeline windows single-buffered, or
    the two manual revolving DMA slots — same two-slot footprint either
    way), the o block (+ revolving copy), the f32 mask generation
    temporary, the split2 hi/lo halves, plus Mosaic headroom.  The DMA
    route additionally budgets one x-tile value plane: the revolving slot
    is read out with a dynamic leading index before the dot, and Mosaic
    materializes that gather into a temporary the automatic pipeline
    never needs."""
    return (
        2 * block_n * BLOCK_D * x_itemsize
        + (block_n * BLOCK_D * x_itemsize if dma else 0)
        + 2 * block_n * k * 4
        + k * BLOCK_D * 4
        + (2 * block_n * BLOCK_D * 2 if mxu_mode == "split2" else 0)
        + _VMEM_HEADROOM
    )


def _auto_block_n(n: int, d: int, k: int, mxu_mode: str,
                  dma: bool = _DMA_DEFAULT) -> int:
    """Largest row tile that helps and harms nothing.

    Measured on the real chip (round 4, 131072×4096→256 through the
    anti-cache harness): 1024-row tiles beat the 256 default by ~20–30%
    in every mxu mode (fewer grid rows ⇒ fewer o-block drains, better
    pipeline occupancy).  A bigger tile is taken only when it

    - fits scoped VMEM (2048 measurably blows the 16 MiB limit, and large
      ``k`` shrinks the feasible tile),
    - pads no extra rows vs the 256 baseline (a 1280-row bucketed batch
      must not balloon to 2048 — that would defeat ``row_bucket``'s ≤25%
      pad-waste cap), and
    - does not starve a mask cache that is FULL at the baseline tile (the
      larger tile's buffers shrink the cache budget; evicting a full
      cache re-pays mask generation per grid row, the exact cost the
      cache exists to remove).  When the cache is partial either way the
      larger tile wins (measured: config-3's d=16384 runs ~20% faster at
      1024 despite a smaller partial cache — fewer grid rows also mean
      fewer regenerations of the uncached blocks).
    """
    base = BLOCK_N
    if n < base:
        # small batch: one tile, padded to the sublane multiple — same
        # tile the backend used to request explicitly
        return max(8, -(-n // 8) * 8)
    x_itemsize = 2 if mxu_mode == "bf16" else 4
    nj = -(-d // BLOCK_D)
    block_bytes = k * BLOCK_D * (4 if mxu_mode == "f32" else 2)

    def slots(bn):
        free = _VMEM_LIMIT - _reserved_bytes(bn, k, mxu_mode, x_itemsize,
                                             dma=dma)
        return max(0, free) // block_bytes

    base_rows = -(-n // base) * base
    for bn in (1024, 512):
        if (
            _reserved_bytes(bn, k, mxu_mode, x_itemsize, dma=dma)
            <= _VMEM_LIMIT
            and -(-n // bn) * bn == base_rows
            and not (slots(bn) < nj <= slots(base))
        ):
            return bn
    return base


def _seed_to_i32(seed) -> int:
    """Fold any Python int seed into int32 (the SMEM scalar width).

    Part of the matrix definition: seeds are taken mod 2^32 and
    reinterpreted signed, so uint32 seeds from unseeded fits work."""
    import numpy as np

    return int(np.uint32(int(seed) & 0xFFFFFFFF).astype(np.int32))


def _uniform_from_bits(bits):
    # top 24 bits → uniform f32 in [0, 1): exact ulp spacing, no rounding
    # bias.  prng_random_bits yields signed int32 — bitcast to uint32 first
    # or the arithmetic shift folds the sign in and u spans [-0.5, 0.5).
    bits = pltpu.bitcast(bits, jnp.uint32) >> 8
    # Mosaic lacks uint32→f32; post-shift values fit in int31, so the
    # int32 reinterpretation is value-preserving and casts fine
    return pltpu.bitcast(bits, jnp.int32).astype(jnp.float32) * (1.0 / (1 << 24))


def _mask_block(density):
    """{+1, -1, 0} w.p. {density/2, density/2, 1-density} from one uniform."""

    def gen(shape):
        u = _uniform_from_bits(pltpu.prng_random_bits(shape))
        plus = u < density * 0.5
        minus = jnp.logical_and(u < density, jnp.logical_not(plus))
        return jnp.where(plus, 1.0, jnp.where(minus, -1.0, 0.0))

    return gen


def _interp_mask_block(density, seed, block):
    """Interpreter-only stand-in for ``_mask_block`` (see the module
    docstring): the hardware PRNG has no CPU lowering at all, so
    ``interpret=True`` derives the uniforms from a pure-jnp integer hash
    of ``(seed, block, row, col)``.  Same distribution and ``(seed,
    block)`` keying — distinct blocks get distinct values, so CPU parity
    tests catch block-indexing bugs — but a DIFFERENT stream from the
    chip's; never part of the persisted-model format."""

    def gen(shape):
        ri = jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
        ci = jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
        h = (
            ri * jnp.uint32(0x9E3779B1)
            ^ ci * jnp.uint32(0x85EBCA77)
            ^ seed.astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D)
            ^ block.astype(jnp.uint32) * jnp.uint32(0x27D4EB2F)
        )
        h = (h ^ (h >> 15)) * jnp.uint32(0x2C1B3C6D)
        h = h ^ (h >> 13)
        u = (h >> 8).astype(jnp.float32) * (1.0 / (1 << 24))
        plus = u < density * 0.5
        minus = jnp.logical_and(u < density, jnp.logical_not(plus))
        return jnp.where(plus, 1.0, jnp.where(minus, -1.0, 0.0))

    return gen


def _gen_mask_block(seed_ref, j, shape, density, dtype, interpret):
    """One ``(k, BLOCK_D)`` mask block for global column block ``j +
    seed_ref[1]`` — the hardware stream on chip, the jnp hash stream
    under the interpreter.  Shared by both kernel bodies and the matrix
    materializer so the three can never drift."""
    blk = j + seed_ref[1]
    if interpret:
        return _interp_mask_block(density, seed_ref[0], blk)(shape).astype(
            dtype
        )
    # (seed, global block) → bits: row-tile-free.  seed_ref[1] is the
    # column-block offset of this shard under feature-axis TP (0
    # unsharded), so a shard holding X[:, lo:hi] regenerates exactly
    # the mask blocks of its own column range — the same global
    # matrix, distributed.
    pltpu.prng_seed(seed_ref[0], blk)
    # the bf16 cast is exact: entries are {+1, -1, 0}
    return _mask_block(density)(shape).astype(dtype)


_DOT_KD = (((1,), (1,)), ((), ()))  # x[n,d] · r[k,d] → [n,k]


def _fetch_mask_block(gen_mask, r_ref, i, j, cache_blocks, n_blocks_d,
                      mxu_mode):
    """Block ``j``'s mask, through the VMEM cache when one exists.

    Mask-block VMEM cache (round-4 probe finding: in the MXU-bound regime
    — large k — regenerating the mask per (row tile, column block) step
    costs ~half the throughput; with a constant mask the same dot
    pipeline runs at ~86% of peak).  ``r_ref`` is a persistent VMEM
    scratch of ``cache_blocks`` mask blocks (+1 shared regen slot when
    not every block fits): block j's mask is GENERATED once, on the first
    row tile, and re-read from VMEM by every later row tile — identical
    values (the (seed, block) stream is unchanged), ~zero VPU cost after
    row tile 0.  Overflow blocks (j >= cache_blocks) share the last slot
    and regenerate every step, exactly like the pre-cache kernel.  When
    even one slot doesn't fit in scoped VMEM ``r_ref`` is None and every
    step regenerates (the pre-cache kernel, byte for byte).

    Shared by the automatic-pipeline and DMA kernel bodies — with
    ``_contract_block`` below, the slot/gen/accumulation semantics exist
    in ONE place, so the two routes stay bit-identical by construction
    rather than by parallel copies."""
    if r_ref is None:
        return gen_mask(jnp.bfloat16 if mxu_mode != "f32" else jnp.float32)
    full = cache_blocks >= n_blocks_d
    slot = j if full else jnp.minimum(j, cache_blocks)
    gen = (i == 0) if full else jnp.logical_or(i == 0, j >= cache_blocks)

    @pl.when(gen)
    def _():
        r_ref[slot] = gen_mask(r_ref.dtype)

    return r_ref[slot]


def _contract_block(xb, r, mxu_mode, o_ref):
    """``o += xb · rᵀ`` for one column block, f32 accumulation.

    'split2': split-precision contraction entirely in VMEM (the route to
    the T1 roofline, BASELINE.json:5): the mask is exact in bf16, X is
    split into hi/lo bf16 halves by the shared mantissa-bitmask helper
    (``ops/split_matmul.py`` — here with zero HBM roundtrip for the
    halves), and two single-pass bf16 MXU contractions accumulate in f32
    — f32-grade output at 2 MXU passes per block, no R and no X-halves
    traffic in HBM.  'bf16': x arrives bf16 (the data's own precision —
    half the x HBM traffic of the f32 modes) and contracts against the
    exact bf16 mask in ONE MXU pass.  'f32': single f32 dot at Mosaic's
    default precision."""
    if mxu_mode == "split2":
        x_hi, x_lo = split_f32_to_bf16_pair(xb)
        acc = jax.lax.dot_general(
            x_hi, r, dimension_numbers=_DOT_KD,
            preferred_element_type=jnp.float32,
        )
        acc += jax.lax.dot_general(
            x_lo, r, dimension_numbers=_DOT_KD,
            preferred_element_type=jnp.float32,
        )
        o_ref[:] += acc
    else:
        o_ref[:] += jax.lax.dot_general(
            xb, r, dimension_numbers=_DOT_KD,
            preferred_element_type=jnp.float32,
        )


def _project_kernel(seed_ref, x_ref, o_ref, *scratch, k, density, scale,
                    n_blocks_d, mxu_mode, cache_blocks, interpret=False):
    i = pl.program_id(0)
    j = pl.program_id(1)

    def _gen_mask(dtype):
        return _gen_mask_block(
            seed_ref, j, (k, x_ref.shape[1]), density, dtype, interpret
        )

    r = _fetch_mask_block(
        _gen_mask, scratch[0] if scratch else None, i, j, cache_blocks,
        n_blocks_d, mxu_mode,
    )

    @pl.when(j == 0)
    def _():
        o_ref[:] = jnp.zeros_like(o_ref)

    _contract_block(x_ref[:], r, mxu_mode, o_ref)

    @pl.when(j == n_blocks_d - 1)
    def _():
        o_ref[:] = o_ref[:] * scale


def _project_kernel_dma(seed_ref, x_hbm, o_ref, *scratch, k, density, scale,
                        n_blocks_d, block_n, mxu_mode, cache_blocks,
                        interpret):
    """DMA kernel body (ISSUE 9): grid over row tiles only, column-block
    loop IN-KERNEL with the next ``(block_n, BLOCK_D)`` x tile's
    HBM→VMEM copy manually double-buffered through two revolving VMEM
    slots + DMA semaphores (the r12 ``topk_kernels`` pattern).  Mask
    generation, cache semantics and accumulation order are identical to
    ``_project_kernel`` — the two paths are bit-identical by
    construction (``j``-ascending ``o += x_j · r_jᵀ``, scale applied
    once at the end)."""
    i = pl.program_id(0)
    buf, sem = scratch[0], scratch[1]
    r_ref = scratch[2] if len(scratch) > 2 else None
    row_off = i * block_n

    def tile_copy(j):
        return pltpu.make_async_copy(
            x_hbm.at[pl.ds(row_off, block_n),
                     pl.ds(j * BLOCK_D, BLOCK_D)],
            buf.at[j % 2],
            sem.at[j % 2],
        )

    tile_copy(0).start()  # warm the pipeline
    o_ref[:] = jnp.zeros_like(o_ref)

    def block_step(j, _):
        # start block j+1's copy into the other slot BEFORE waiting on
        # block j: the MXU contracts block j while the DMA engine
        # fetches j+1 — the fetch/compute interleave the r5 trace
        # attributed ~13% of wall to is off the critical path
        @pl.when(j + 1 < n_blocks_d)
        def _():
            tile_copy(j + 1).start()

        tile_copy(j).wait()
        r = _fetch_mask_block(
            lambda dtype: _gen_mask_block(
                seed_ref, j, (k, BLOCK_D), density, dtype, interpret
            ),
            r_ref, i, j, cache_blocks, n_blocks_d, mxu_mode,
        )
        _contract_block(buf[j % 2], r, mxu_mode, o_ref)
        return 0

    jax.lax.fori_loop(0, n_blocks_d, block_step, 0)
    o_ref[:] = o_ref[:] * scale


def _matrix_kernel(seed_ref, o_ref, *, k, density, scale, interpret=False):
    j = pl.program_id(0)
    o_ref[:] = _gen_mask_block(
        seed_ref, j, (k, o_ref.shape[1]), density, jnp.float32, interpret
    ) * scale


def fused_sparse_project(
    x,
    seed,
    n_components: int,
    density: float,
    *,
    block_n: Optional[int] = None,
    block_offset=0,
    mxu_mode: str = "f32",
    interpret: bool = False,
    no_cache: bool = False,
    dma: Optional[bool] = None,
):
    """``Y = X @ R(seed)ᵀ`` with ``R`` regenerated in-kernel, never in HBM.

    ``density=1`` degenerates to the sign/Rademacher kernel.  ``x`` is any
    ``(n, d)`` float array; ``n_components`` must be a multiple of 8 (f32
    sublane tiling).  Ragged ``n``/``d`` are zero-padded (zero rows/cols
    contribute nothing; the mask block for padded ``d`` is generated but
    multiplied by zeros).

    ``block_n=None`` (default) picks the largest row tile that fits scoped
    VMEM for this shape (``_auto_block_n``; 1024 at the headline shapes —
    measured 20–30% faster than 256 in every mxu mode); pass an explicit
    tile only to pin it (tests pin 128 to prove tile-invariance).

    ``block_offset`` (int or traced int32 scalar) shifts the column-block
    indices: a feature-axis TP shard holding ``X[:, lo:hi]`` (``lo``
    BLOCK_D-aligned) passes ``lo // BLOCK_D`` and computes its partial
    product against exactly its own blocks of the global matrix.  The
    per-call scale is linear, so ``psum`` of the scaled partials equals the
    unsharded result.

    ``mxu_mode`` selects the contraction arithmetic — NOT part of the matrix
    definition (all modes contract the identical mask):

    - ``'f32'``: f32 dot at Mosaic's default precision (bf16-grade output).
    - ``'split2'``: X split hi/lo bf16 in VMEM vs the exact-in-bf16 mask —
      2 single-pass MXU contractions, f32-grade output (~1e-6 distortion),
      the mode that reaches the T1 roofline (~R1/2 ≈ 47-94M rows/s).
    - ``'bf16'``: X kept bfloat16 end-to-end (half the x HBM traffic — the
      mode for bf16-fitted models, where 1 exact-mask pass IS the data's
      own precision), 1 MXU pass, f32 accumulation.

    ``dma=None`` (default) takes the manual double-buffered x DMA route
    (``_DMA_DEFAULT``) — the default single-device transform path since
    ISSUE 9; ``dma=False`` pins the pre-r14 automatic-pipeline tiling
    (the parity suite pins both to prove bit-identity).

    VMEM-safety fallback: the mask-cache sizing relies on a measured 3 MiB
    Mosaic-temporary headroom (``_VMEM_HEADROOM``).  Should an untested
    ``(shape, block_n, k, mode)`` combination still blow the scoped-VMEM
    limit at compile, an eager call walks a degraded-retry ladder —
    first the DMA route falls back to the current single-buffered
    automatic tiling (``_NO_DMA_KEYS``), then the mask cache is disabled
    (the documented regenerate-every-step degeneration,
    ``_NO_CACHE_KEYS``) — and remembers the failing key.  Traced callers
    compile outside this frame and cannot be caught here — they opt into
    the degeneration explicitly with ``dma=False``/``no_cache=True``
    after catching the failure at their own call site (the mesh path:
    ``jax_backend._project_prepared``).  Neither knob changes values —
    the (seed, block) streams and accumulation order are identical on
    every rung.
    """
    # keyed by input shape too: the VMEM-feasible tile and cache sizing are
    # resolved per (n, d) by _auto_block_n, so one failing exotic shape must
    # not disable the cache (or DMA) for the (k, mode)'s healthy shapes
    key = (tuple(x.shape), block_n, n_components, mxu_mode)
    use_dma, use_cache = _resolve_route(key, dma, no_cache)

    def call(a_dma, a_nc):
        return _fused_impl(
            x, seed, n_components, density, block_n=block_n,
            block_offset=block_offset, mxu_mode=mxu_mode,
            interpret=interpret, no_cache=a_nc, dma=a_dma,
        )

    return _vmem_ladder(
        call, key, use_dma, use_cache, x.shape, mxu_mode, n_components,
        steps=1, traced=isinstance(x, jax.core.Tracer),
    )


_NO_CACHE_KEYS: set = set()
_NO_DMA_KEYS: set = set()


def _resolve_route(key, dma, no_cache):
    """(use_dma, use_cache) for one memo key: the caller's request,
    downgraded by the process-lifetime VMEM-OOM memos.  Shared by the
    plain and multistep entry points so the two can't drift."""
    use_dma = (_DMA_DEFAULT if dma is None else bool(dma)) \
        and key not in _NO_DMA_KEYS
    use_cache = not no_cache and key not in _NO_CACHE_KEYS
    return use_dma, use_cache


def multistep_chain_length(n: int, steps: int) -> int:
    """The number of kernel launches ``fused_project_multistep`` actually
    chains for ``n`` rows at a requested ``steps``: the clamp plus the
    ceil-split can round the chunk count below the request (n=10,
    steps=7 → per=2 → 5 chunks).  Telemetry (``kernel.dma.dispatch`` and
    the backend's ``backend.dispatch_fused``) records THIS value, so the
    doctor's mean-steps reflects launches that ran, not the knob."""
    n = max(int(n), 1)
    steps = max(1, min(int(steps), n))
    per = -(-n // steps)
    return -(-n // per)


def _emit_kernel_dispatch(shape, n_components, mxu_mode, use_dma, steps):
    """``kernel.dma.dispatch`` — one record per EAGER transform-kernel
    host dispatch, emitted at the ``_vmem_ladder`` rung that actually
    SERVED the call (so a DMA request downgraded by a VMEM-OOM retry is
    recorded as ``path="single"``, never as the route it asked for).
    ``steps`` is the dispatch-fusion chain length.  Traced callers (the
    mesh path, jitted bench harnesses) run this Python frame once per
    COMPILE, not per dispatch, so the ladder skips the emit for them —
    their dispatches are already counted by ``backend.dispatch`` /
    ``backend.dispatch_fused``.  Consumed by the doctor's transform
    section (``utils/trace_report.py``)."""
    from randomprojection_tpu.utils import telemetry

    if not telemetry.enabled():
        return
    telemetry.emit(
        telemetry.EVENTS.KERNEL_DMA_DISPATCH,
        rows=int(shape[0]), d=int(shape[1]), n_components=int(n_components),
        mxu_mode=mxu_mode, path="dma" if use_dma else "single",
        steps=int(steps), **telemetry.trace_fields(),
    )


def _vmem_ladder(call, key, use_dma, use_cache, shape, mxu_mode,
                 n_components, steps=1, traced=False,
                 no_dma_keys=None, no_cache_keys=None,
                 label="fused kernel"):
    """Shared scoped-VMEM degraded-retry ladder: ``(dma, cache) →
    (single-buffered, cache) → (single-buffered, no cache)``.  Memoizes
    only the rung that actually SUCCEEDED (a misclassified error must not
    pin the shape to a slow path for the process lifetime — ADVICE r5),
    and re-raises anything ``is_vmem_oom`` does not recognize.

    Used by the eager kernel entry points (module-level memo sets, one
    route event per host dispatch) and by the mesh call site
    (``jax_backend._project_prepared``: per-instance memo sets via
    ``no_dma_keys``/``no_cache_keys``, ``traced=True`` because its
    dispatches are already counted by ``backend.dispatch``).  ``use_dma``
    may be ``None`` — the kernel default route, which counts as DMA-on
    for ladder purposes but is passed through to ``call`` unresolved.

    Each rung records exactly the degradation it performs: the DMA rung
    emits ``kernel.dma.fallback`` alone, the cache rung
    ``backend.vmem_oom_retry`` alone — one incident, one degraded event
    (``backend.vmem_oom_retries`` keeps meaning "mask cache disabled",
    comparable with pre-r14 rounds)."""
    dma_on = use_dma is not False
    if no_dma_keys is None:
        no_dma_keys = _NO_DMA_KEYS
    if no_cache_keys is None:
        no_cache_keys = _NO_CACHE_KEYS
    ladder = [(use_dma, not use_cache)]
    if dma_on:
        ladder.append((False, not use_cache))
    if use_cache:
        ladder.append((False, True))
    # dedupe while keeping order (use_dma=False already collapses rungs)
    seen: set = set()
    ladder = [r for r in ladder if not (r in seen or seen.add(r))]

    for idx, (a_dma, a_nc) in enumerate(ladder):
        try:
            out = call(a_dma, a_nc)
        except Exception as e:  # pragma: no cover — needs a Mosaic VMEM OOM
            if idx == len(ladder) - 1 or not is_vmem_oom(e):
                raise
            from randomprojection_tpu.utils.observability import logger

            nxt = ladder[idx + 1]
            if a_dma is not False and nxt[0] is False:
                logger.warning(
                    "%s (DMA route) hit a scoped-VMEM limit for key %s; "
                    "retrying on the single-buffered automatic tiling",
                    label, key,
                )
                record_dma_fallback(shape, mxu_mode, n_components)
            else:
                logger.warning(
                    "%s hit a scoped-VMEM limit for key %s; retrying "
                    "without the in-VMEM mask cache (regenerate-every-step "
                    "degradation)", label, key,
                )
                record_vmem_oom_retry(shape, mxu_mode, n_components)
            continue
        if idx > 0:
            # memoize exactly what this successful rung dropped
            if dma_on and a_dma is False:
                no_dma_keys.add(key)
            if use_cache and a_nc:
                no_cache_keys.add(key)
        if not traced:
            _emit_kernel_dispatch(shape, n_components, mxu_mode,
                                  a_dma is not False, steps=steps)
        return out

# Phrasings that mark a genuine allocation failure.  Mosaic/XLA spell
# scoped-VMEM exhaustion variously across versions ("scoped allocation ...
# exceeds", "RESOURCE_EXHAUSTED", "out of memory", "vmem limit"), so the
# classifier requires 'vmem' AND one of these — a diagnostic that merely
# *mentions* VMEM stats no longer routes into the degraded retry.
_VMEM_OOM_MARKERS = (
    "exceed", "alloc", "oom", "out of memory", "resource_exhausted",
    "resource exhausted", "limit", "too large", "too big", "insufficient",
)


def is_vmem_oom(exc: Exception) -> bool:
    """Classify a Mosaic scoped-VMEM exhaustion (the one failure the
    no-cache degeneration can fix) — shared by the eager fallback above and
    the mesh call site (``jax_backend._project_prepared``), so the two
    paths cannot drift when an error wording changes.  Requires the memory
    name ('vmem', covering 'scoped vmem' spellings) AND an allocation/
    exhaustion phrasing (ADVICE r5): a bare 'vmem' match swallowed any
    error that merely mentioned VMEM and silently degraded that shape to
    the regenerate-every-step path for the process lifetime."""
    s = str(exc).lower()
    return "vmem" in s and any(m in s for m in _VMEM_OOM_MARKERS)


def record_vmem_oom_retry(shape, mxu_mode: str, n_components: int) -> None:
    """Degraded-retry telemetry, shared by both call sites (the eager
    fallback above and ``jax_backend._project_prepared``'s mesh retry) —
    one counter name and one event schema, so the retry count can never
    split between the two paths."""
    from randomprojection_tpu.utils import telemetry

    telemetry.registry().counter_inc("backend.vmem_oom_retries")
    telemetry.emit(
        telemetry.EVENTS.BACKEND_VMEM_OOM_RETRY, shape=list(shape),
        mxu_mode=mxu_mode, n_components=n_components,
        **telemetry.trace_fields(),
    )


def record_dma_fallback(shape, mxu_mode: str, n_components: int) -> None:
    """``kernel.dma.fallback`` — the DMA route blew scoped VMEM and the
    shape is being served by the single-buffered automatic tiling.
    Shared by the eager ladder and the mesh call site
    (``jax_backend._project_prepared``), like ``record_vmem_oom_retry``;
    surfaced in the doctor's degraded-event audit."""
    from randomprojection_tpu.utils import telemetry

    telemetry.registry().counter_inc("kernel.dma.fallbacks")
    telemetry.emit(
        telemetry.EVENTS.KERNEL_DMA_FALLBACK, shape=list(shape),
        mxu_mode=mxu_mode, n_components=n_components,
        **telemetry.trace_fields(),
    )


def _fused_raw(
    x,
    seed,
    n_components: int,
    density: float,
    *,
    block_n: Optional[int],
    block_offset,
    mxu_mode: str,
    interpret: bool,
    no_cache: bool,
    dma: bool = False,
):
    if mxu_mode not in ("f32", "split2", "bf16"):
        raise ValueError(
            f"mxu_mode must be 'f32', 'split2' or 'bf16', got {mxu_mode!r}"
        )
    density = check_density(density, x.shape[1])
    check_input_size(n_components, x.shape[1])
    if n_components % 8:
        raise ValueError(
            f"n_components must be a multiple of 8 for the fused TPU kernel, "
            f"got {n_components}"
        )
    n, d = x.shape
    k = n_components
    scale = 1.0 / math.sqrt(density * k)
    if block_n is None:
        block_n = _auto_block_n(n, d, k, mxu_mode, dma=dma)

    seed = _seed_to_i32(seed)
    n_pad = -n % block_n
    d_pad = -d % BLOCK_D
    if n_pad or d_pad:
        x = jnp.pad(x, ((0, n_pad), (0, d_pad)))
    x = x.astype(jnp.bfloat16 if mxu_mode == "bf16" else jnp.float32)
    x_itemsize = x.dtype.itemsize
    ni = x.shape[0] // block_n
    nj = x.shape[1] // BLOCK_D

    # Mask-cache sizing: the cache holds the mask in the dtype the dot
    # consumes (bf16 for split2/bf16 — exact for ±1/0 — f32 otherwise) and
    # takes whatever scoped VMEM remains after the pipeline's own buffers
    # (x double-buffered, o block, the f32 generation temporary, the split
    # halves) plus headroom.  The overflow regen slot counts against the
    # same budget (``max_slots - 1``): cache_blocks == 0 degenerates to the
    # original regenerate-every-step kernel via the single shared slot, and
    # when not even that one slot fits the kernel gets NO scratch and
    # regenerates into a value, so no shape that compiled pre-cache can be
    # pushed over Mosaic's scoped-VMEM limit by the cache.
    cache_itemsize = 4 if mxu_mode == "f32" else 2
    block_bytes = k * BLOCK_D * cache_itemsize
    reserved = _reserved_bytes(block_n, k, mxu_mode, x_itemsize, dma=dma)
    max_slots = max(0, _VMEM_LIMIT - reserved) // block_bytes
    cache_blocks = nj if max_slots >= nj else max(0, max_slots - 1)
    slots = nj if cache_blocks >= nj else cache_blocks + 1
    # ni == 1: every block is generated once and read once — nothing to
    # reuse, so the cache would only add a VMEM round-trip per step; keep
    # the single-row-tile path byte-for-byte the pre-cache kernel
    use_cache = max_slots > 0 and ni > 1 and not no_cache
    cache_scratch = (
        [
            # rplint: allow[RP07] — cache charged by construction: max_slots is derived FROM _reserved_bytes' remainder, so these slots can never exceed the post-reserve budget
            pltpu.VMEM(
                (slots, k, BLOCK_D),
                jnp.float32 if cache_itemsize == 4 else jnp.bfloat16,
            )
        ]
        if use_cache
        else []
    )

    seed_arr = jnp.stack(
        [jnp.int32(seed), jnp.asarray(block_offset, dtype=jnp.int32)]
    )
    cost = pl.CostEstimate(
        # split2 executes two MXU contractions per block
        flops=(2 if mxu_mode == "split2" else 1)
        * 2 * x.shape[0] * x.shape[1] * k,
        bytes_accessed=(
            x.shape[0] * x.shape[1] * x_itemsize + x.shape[0] * k * 4
        ),
        transcendentals=0,
    )
    if dma:
        # manual double-buffered x DMA: grid over row tiles only, x
        # HBM-resident (memory_space=ANY), the column-block loop inside
        # the kernel with two revolving VMEM slots + DMA semaphores
        y = pl.pallas_call(
            functools.partial(
                _project_kernel_dma, k=k, density=density, scale=scale,
                n_blocks_d=nj, block_n=block_n, mxu_mode=mxu_mode,
                cache_blocks=cache_blocks if use_cache else 0,
                interpret=interpret,
            ),
            grid=(ni,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.ANY),
            ],
            out_specs=pl.BlockSpec(
                (block_n, k), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            out_shape=jax.ShapeDtypeStruct((x.shape[0], k), jnp.float32),
            scratch_shapes=[
                pltpu.VMEM((2, block_n, BLOCK_D), x.dtype),
                pltpu.SemaphoreType.DMA((2,)),
            ]
            + cache_scratch,
            cost_estimate=cost,
            interpret=interpret,
        )(seed_arr, x)
        return y[:n]
    y = pl.pallas_call(
        functools.partial(
            _project_kernel, k=k, density=density, scale=scale, n_blocks_d=nj,
            mxu_mode=mxu_mode, cache_blocks=cache_blocks,
            interpret=interpret,
        ),
        grid=(ni, nj),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (block_n, BLOCK_D),
                lambda i, j: (i, j),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (block_n, k), lambda i, j: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((x.shape[0], k), jnp.float32),
        scratch_shapes=cache_scratch,
        cost_estimate=cost,
        interpret=interpret,
    )(seed_arr, x)
    return y[:n]


_fused_impl = functools.partial(
    jax.jit,
    static_argnames=(
        "seed", "n_components", "density", "block_n", "mxu_mode", "interpret",
        "no_cache", "dma",
    ),
)(_fused_raw)


def _multistep_raw(
    x,
    seed,
    n_components: int,
    density: float,
    *,
    steps: int,
    block_n: Optional[int],
    mxu_mode: str,
    interpret: bool,
    no_cache: bool,
    dma: bool,
):
    """``steps`` contiguous row-blocks of ``x`` through the fused kernel
    inside ONE trace — an unrolled python loop (NOT ``lax.scan``: the r5
    trace measured ~2-3 ms/iteration of scan loop overhead on this
    environment's chip, exactly the cost this mode exists to remove), so
    XLA compiles one program with ``steps`` back-to-back kernel launches
    and the host call boundary is paid once.  Each block goes through
    the raw kernel body — not its jitted wrapper — so no nested-pjit
    boundary survives into the program (the r9 ``estimator_vs_raw``
    lesson).  Bit-identical to ``steps`` separate dispatches on the same
    row split: the mask streams are row-tile-independent and each block
    pads/tiles exactly as a separate call would."""
    n = x.shape[0]
    per = -(-n // steps)
    outs = []
    lo = 0
    while lo < n:
        hi = min(lo + per, n)
        outs.append(
            _fused_raw(
                x[lo:hi], seed, n_components, density, block_n=block_n,
                block_offset=0, mxu_mode=mxu_mode, interpret=interpret,
                no_cache=no_cache, dma=dma,
            )
        )
        lo = hi
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)


_MULTISTEP_STATIC = (
    "seed", "n_components", "density", "steps", "block_n", "mxu_mode",
    "interpret", "no_cache", "dma",
)
_multistep_impl = functools.partial(
    jax.jit, static_argnames=_MULTISTEP_STATIC
)(_multistep_raw)
# the donating variant: the caller owns x (a padded/cast/uploaded buffer
# nothing else references) and hands its HBM back to XLA for the chain's
# intermediates/output — the multi-step mode's "donated buffers" leg
_multistep_impl_donated = functools.partial(
    jax.jit, static_argnames=_MULTISTEP_STATIC, donate_argnums=(0,)
)(_multistep_raw)


def fused_project_multistep(
    x,
    seed,
    n_components: int,
    density: float,
    *,
    steps: int,
    block_n: Optional[int] = None,
    mxu_mode: str = "f32",
    interpret: bool = False,
    dma: Optional[bool] = None,
    donate: bool = False,
):
    """Multi-step dispatch fusion (ISSUE 9): chain ``steps`` row-blocks
    of ``x`` through ONE traced dispatch so per-call host gaps (the r5
    trace's ~13% call-boundary attribution: device-busy 0.246 s vs
    0.282 s wall per call) amortize by ``1/steps``.

    Contract: bit-identical to splitting ``x`` into ``steps`` contiguous
    blocks of ``ceil(n/steps)`` rows and calling
    ``fused_sparse_project`` on each (asserted by the parity suite).
    ``steps`` is clamped to the row count; ``steps=1`` degenerates to
    the plain call.  ``donate=True`` hands ``x``'s device buffer to the
    chain (pass it only for a buffer you own — it is invalidated either
    way).  Donation is opportunistic XLA aliasing: it frees ``x``'s HBM
    for the chain only when an output matches the buffer's shape/dtype
    (the usual ``(n, d)`` f32 input vs ``(n, k)`` f32 output does not),
    so the "donated buffers were not usable" advisory is suppressed here
    — a non-aliasable donation is the expected no-op, not a bug.
    Walks the same scoped-VMEM degraded-retry ladder as
    ``fused_sparse_project``."""
    steps = max(1, min(int(steps), max(int(x.shape[0]), 1)))
    if steps == 1 and not donate:
        return fused_sparse_project(
            x, seed, n_components, density, block_n=block_n,
            mxu_mode=mxu_mode, interpret=interpret, dma=dma,
        )
    # steps==1 with donate=True stays on the (one-launch) donating chain
    # so the invalidation contract holds on the degenerate path too
    key = (tuple(x.shape), block_n, n_components, mxu_mode, steps)
    use_dma, use_cache = _resolve_route(key, dma, no_cache=False)
    impl = _multistep_impl_donated if donate else _multistep_impl

    def call(a_dma, a_nc):
        import contextlib
        import warnings

        with warnings.catch_warnings() if donate else contextlib.nullcontext():
            if donate:
                # non-aliasable donation (the usual (n,d)→(n,k) shape
                # mismatch) is the documented no-op, not a bug
                warnings.filterwarnings(
                    "ignore",
                    message="Some donated buffers were not usable",
                )
            return impl(
                x, seed, n_components, density, steps=steps,
                block_n=block_n, mxu_mode=mxu_mode, interpret=interpret,
                no_cache=a_nc, dma=a_dma,
            )

    return _vmem_ladder(
        call, key, use_dma, use_cache, x.shape, mxu_mode, n_components,
        steps=multistep_chain_length(x.shape[0], steps),
        traced=isinstance(x, jax.core.Tracer),
    )


@functools.partial(
    jax.jit,
    static_argnames=("seed", "n_components", "n_features", "density", "interpret"),
)
def pallas_sparse_matrix(
    seed, n_components: int, n_features: int, density: float, *,
    interpret: bool = False
):
    """Materialize the exact matrix ``fused_sparse_project`` uses (tests,
    ``components_`` introspection, pinv).  Same ``(seed, block)`` streams
    (under ``interpret=True``, the same jnp hash streams the interpreted
    projection kernel contracts — the CPU parity reference)."""
    density = check_density(density, n_features)
    check_input_size(n_components, n_features)
    if n_components % 8:
        raise ValueError(
            f"n_components must be a multiple of 8 for the fused TPU kernel, "
            f"got {n_components}"
        )
    seed = _seed_to_i32(seed)
    k = n_components
    scale = 1.0 / math.sqrt(density * k)
    d_pad = -n_features % BLOCK_D
    d_full = n_features + d_pad
    nj = d_full // BLOCK_D

    seed_arr = jnp.asarray([seed, 0], dtype=jnp.int32)
    R = pl.pallas_call(
        functools.partial(
            _matrix_kernel, k=k, density=density, scale=scale,
            interpret=interpret,
        ),
        grid=(nj,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec(
            (k, BLOCK_D), lambda j: (0, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((k, d_full), jnp.float32),
        interpret=interpret,
    )(seed_arr)
    return R[:, :n_features]
