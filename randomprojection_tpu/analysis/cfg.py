"""Flow-analysis substrate for rplint (ISSUE 11): CFG + call resolution.

The r10 rules are per-line AST pattern checks; the contracts r12/r14
added (DMA copy/wait discipline, thread shutdown paths, helper-hidden
host syncs) are properties of *paths*, not lines.  This module grows the
checker into a small framework the flow-sensitive rules
(``flowrules.py``) build on:

- **Statement-level CFG** (``build_cfg``): one node per executable
  statement plus synthetic entry/exit; edges model ``if``/``for``/
  ``while``/``try``-``finally``/``return``/``raise``/``break``/
  ``continue``.  Branch edges carry the branch condition (an
  ``ast.dump`` of the test plus polarity), and every node records the
  conditions governing it, so path queries can prune branches that
  contradict the conditions a statement already executes under (two
  ``if masked:`` blocks in one kernel body are the same world — a path
  taking the first and skipping the second is infeasible and must not
  produce a finding).
- **Pallas splicing** (``pallas=True``): inside kernel bodies the
  control flow lives in Pallas idioms, not Python statements — a nested
  ``def`` decorated ``@pl.when(cond)`` executes conditionally at its
  definition point, and ``jax.lax.fori_loop(lo, hi, body, init)`` runs
  ``body`` in a loop at the call point.  The builder splices both into
  the CFG (``fori_loop`` bodies as do-while loops: a Pallas grid/block
  loop with zero trips is not a shape the kernels emit, and modeling it
  would flag every warm-up DMA start as unwaited).
- **Path queries**: ``exit_reachable_without`` ("can the function exit
  from here without passing one of these nodes?" — the all-paths
  primitive behind the DMA-wait and thread-join rules) and
  ``dominators`` (the ack-after-yield rule is exactly "no cursor commit
  dominates its batch's yield").
- **Call resolution** (``PackageIndex``): a one-level intra-package
  call graph — module-level defs, same-file nested defs, ``self.``
  methods, and ``from randomprojection_tpu.x import f``-style imports
  resolved against the package file set — so RP09 can see a host sync
  one call away from a hot loop without whole-program analysis.

Pure stdlib, shared with ``rplint.py``'s static-only contract: nothing
here imports the code under analysis.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

__all__ = [
    "CFG",
    "Node",
    "build_cfg",
    "exit_reachable_without",
    "node_reachable_without",
    "dominators",
    "shallow_walk",
    "dotted",
    "parents_map",
    "ModuleInfo",
    "PackageIndex",
    "index_module",
    "lock_name",
    "LockRegions",
    "lock_regions",
    "thread_entries",
]

# a branch condition: (ast.dump of the test expression, polarity)
Fact = Tuple[str, bool]

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _dump(node: ast.AST) -> str:
    return ast.dump(node)


def dotted(node: ast.AST) -> str:
    """Dotted-name string of a Name/Attribute chain ('' when dynamic).
    THE shared receiver-matching primitive — rplint's emit/Thread
    detection and flowrules' threading checks must agree on it."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def parents_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    """child AST node -> parent, for enclosing-scope lookups."""
    return {
        child: parent
        for parent in ast.walk(tree)
        for child in ast.iter_child_nodes(parent)
    }


@dataclasses.dataclass
class Node:
    """One CFG node.  ``stmt`` is the owning AST statement (None for the
    synthetic entry/exit), ``kind`` distinguishes how much of the
    statement's subtree belongs to this node (compound statements own
    only their header — their bodies are separate nodes), ``facts`` are
    the branch conditions this node executes under, and ``succs`` are
    ``(node index, edge fact)`` pairs."""

    idx: int
    stmt: Optional[ast.AST]
    kind: str  # entry|exit|stmt|branch|loop|when|anchor
    facts: frozenset
    succs: List[Tuple[int, Optional[Fact]]] = dataclasses.field(
        default_factory=list
    )


class CFG:
    def __init__(self) -> None:
        self.nodes: List[Node] = []
        self.entry = self._new(None, "entry", frozenset())
        self.exit = self._new(None, "exit", frozenset())

    def _new(self, stmt, kind: str, facts: frozenset) -> int:
        n = Node(len(self.nodes), stmt, kind, facts)
        self.nodes.append(n)
        return n.idx

    def edge(self, a: int, b: int, fact: Optional[Fact] = None) -> None:
        self.nodes[a].succs.append((b, fact))

    def preds(self) -> List[List[int]]:
        out: List[List[int]] = [[] for _ in self.nodes]
        for n in self.nodes:
            for s, _ in n.succs:
                out[s].append(n.idx)
        return out


def shallow_walk(node: Node) -> Iterator[ast.AST]:
    """The AST nodes evaluated *at* this CFG node: the statement's own
    expressions, excluding bodies of compound statements (those are
    separate CFG nodes) and nested function/lambda/class definitions
    (those execute elsewhere, or not at all)."""
    stmt = node.stmt
    if stmt is None or node.kind in ("anchor", "entry", "exit"):
        return
    if node.kind == "when":
        # the pl.when branch node: only the decorator's test evaluates
        # here — the decorated body got its own nodes
        for dec in stmt.decorator_list:
            yield from _walk_expr(dec)
        return
    if isinstance(stmt, ast.If):
        yield from _walk_expr(stmt.test)
        return
    if isinstance(stmt, ast.While):
        yield from _walk_expr(stmt.test)
        return
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        yield from _walk_expr(stmt.target)
        yield from _walk_expr(stmt.iter)
        return
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            yield from _walk_expr(item.context_expr)
            if item.optional_vars is not None:
                yield from _walk_expr(item.optional_vars)
        return
    if isinstance(stmt, _FUNC_NODES + (ast.ClassDef,)):
        return
    yield from _walk_expr(stmt)


def _walk_expr(root: ast.AST) -> Iterator[ast.AST]:
    """Walk an expression/statement subtree without descending into
    nested function/lambda/class definitions or compound-statement
    bodies."""
    stack = [root]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, _FUNC_NODES + (ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)


def _is_pl_when(func: ast.FunctionDef) -> Optional[ast.AST]:
    """The pl.when condition expression when ``func`` is decorated
    ``@pl.when(cond)`` (or bare ``@when(cond)``), else None."""
    for dec in func.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        f = dec.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else ""
        )
        if name == "when" and dec.args:
            return dec.args[0]
    return None


def _fori_body_name(stmt: ast.stmt) -> Optional[str]:
    """The body-function Name of a ``lax.fori_loop(lo, hi, fn, init)``
    call inside this statement, if any."""
    for n in ast.walk(stmt):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else ""
        )
        if name == "fori_loop" and len(n.args) >= 3 and isinstance(
            n.args[2], ast.Name
        ):
            return n.args[2].id
    return None


class _Builder:
    """Recursive-descent CFG construction.  ``preds`` threading: every
    ``seq`` call receives the dangling ``(node, edge fact)`` frontier
    and returns the new frontier."""

    def __init__(self, pallas: bool):
        self.cfg = CFG()
        self.pallas = pallas
        # [header idx, [break node idxs], finally-depth at loop entry]
        self.loop_stack: List[List] = []
        self.exc_stack: List[int] = []    # innermost finally/handler anchors
        self.fin_stack: List[int] = []    # innermost FINALLY anchors only
        self.ret_stack: List[Optional[int]] = [None]  # splice return targets

    def connect(self, preds, node: int) -> None:
        for p, fact in preds:
            self.cfg.edge(p, node, fact)

    def seq(self, stmts: Sequence[ast.stmt], preds, facts: frozenset,
            env: Dict[str, ast.FunctionDef]):
        env = dict(env)
        for stmt in stmts:
            preds = self.one(stmt, preds, facts, env)
        return preds

    def one(self, stmt: ast.stmt, preds, facts, env):
        cfg = self.cfg
        if isinstance(stmt, ast.If):
            node = cfg._new(stmt, "branch", facts)
            self.connect(preds, node)
            d = _dump(stmt.test)
            t_out = self.seq(stmt.body, [(node, (d, True))],
                             facts | {(d, True)}, env)
            if stmt.orelse:
                f_out = self.seq(stmt.orelse, [(node, (d, False))],
                                 facts | {(d, False)}, env)
            else:
                f_out = [(node, (d, False))]
            return t_out + f_out
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            # NOTE: a while-loop's condition is re-evaluated every
            # iteration, so (unlike an `if` over a loop-invariant flag)
            # it must NOT become a persistent fact: a node inside the
            # body does reach the exit edge on a later iteration, and
            # pruning it would hide missing joins/waits whose escape
            # path is the normal loop exit.
            node = cfg._new(stmt, "loop", facts)
            self.connect(preds, node)
            self.loop_stack.append([node, [], len(self.fin_stack)])
            body_out = self.seq(stmt.body, [(node, None)], facts, env)
            _, breaks, _fd = self.loop_stack.pop()
            for p, fact in body_out:
                cfg.edge(p, node, fact)
            norm = [(node, None)]
            if stmt.orelse:
                norm = self.seq(stmt.orelse, norm, facts, env)
            return norm + [(b, None) for b in breaks]
        if isinstance(stmt, ast.Try):
            return self._try(stmt, preds, facts, env)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            node = cfg._new(stmt, "stmt", facts)
            self.connect(preds, node)
            return self.seq(stmt.body, [(node, None)], facts, env)
        if isinstance(stmt, ast.Return):
            # a return runs enclosing finally blocks, NOT except
            # handlers — route through the finally stack only
            node = cfg._new(stmt, "stmt", facts)
            self.connect(preds, node)
            target = self.ret_stack[-1]
            if target is None:
                target = self.fin_stack[-1] if self.fin_stack else cfg.exit
            cfg.edge(node, target)
            return []
        if isinstance(stmt, ast.Raise):
            node = cfg._new(stmt, "stmt", facts)
            self.connect(preds, node)
            cfg.edge(node, self.exc_stack[-1] if self.exc_stack else cfg.exit)
            return []
        if isinstance(stmt, ast.Break):
            # break/continue run finally blocks entered SINCE the loop
            # (an enclosing try around the loop is not exited)
            node = cfg._new(stmt, "stmt", facts)
            self.connect(preds, node)
            if self.loop_stack:
                header, breaks, fin_depth = self.loop_stack[-1]
                if len(self.fin_stack) > fin_depth:
                    cfg.edge(node, self.fin_stack[-1])
                else:
                    breaks.append(node)
            return []
        if isinstance(stmt, ast.Continue):
            node = cfg._new(stmt, "stmt", facts)
            self.connect(preds, node)
            if self.loop_stack:
                header, _breaks, fin_depth = self.loop_stack[-1]
                if len(self.fin_stack) > fin_depth:
                    cfg.edge(node, self.fin_stack[-1])
                else:
                    cfg.edge(node, header)
            return []
        if isinstance(stmt, _FUNC_NODES):
            if self.pallas:
                cond = _is_pl_when(stmt)
                if cond is not None:
                    # @pl.when(cond) body: a conditional branch executed
                    # at the definition point
                    node = cfg._new(stmt, "when", facts)
                    self.connect(preds, node)
                    d = _dump(cond)
                    t_out = self.seq(stmt.body, [(node, (d, True))],
                                     facts | {(d, True)}, env)
                    return t_out + [(node, (d, False))]
                env[stmt.name] = stmt
            return preds  # plain nested def: not part of this flow
        if isinstance(stmt, ast.ClassDef):
            return preds
        # plain statement — in pallas mode a fori_loop(.., fn, ..) call
        # splices fn's body as a do-while loop at this point
        node = cfg._new(stmt, "stmt", facts)
        self.connect(preds, node)
        if self.pallas:
            body_name = _fori_body_name(stmt)
            if body_name is not None and body_name in env:
                fn = env[body_name]
                # a latch anchor: the body's `return` means "end of this
                # iteration", not "exit the enclosing kernel"
                latch = cfg._new(stmt, "anchor", facts)
                self.ret_stack.append(latch)
                body_out = self.seq(fn.body, [(node, None)], facts, env)
                self.ret_stack.pop()
                self.connect(body_out, latch)
                cfg.edge(latch, node)   # back edge (next iteration)
                return [(latch, None)]  # do-while: body ran at least once
        return [(node, None)]

    def _try(self, stmt: ast.Try, preds, facts, env):
        cfg = self.cfg
        has_final = bool(stmt.finalbody)
        f_anchor = cfg._new(stmt, "anchor", facts) if has_final else None
        h_anchor = cfg._new(stmt, "anchor", facts) if stmt.handlers else None
        exc_target = h_anchor if h_anchor is not None else f_anchor
        if exc_target is not None:
            self.exc_stack.append(exc_target)
        if has_final:
            self.fin_stack.append(f_anchor)
        lo = len(cfg.nodes)
        body_out = self.seq(stmt.body, preds, facts, env)
        hi = len(cfg.nodes)
        if exc_target is not None:
            self.exc_stack.pop()
            # any statement in the try body may raise: conservative edge
            # from each to the handler/finally anchor
            for i in range(lo, hi):
                if cfg.nodes[i].kind in ("stmt", "branch", "loop", "when"):
                    cfg.edge(i, exc_target)
        if stmt.orelse:
            body_out = self.seq(stmt.orelse, body_out, facts, env)
        handler_outs = []
        if stmt.handlers:
            if has_final:
                self.exc_stack.append(f_anchor)
            for h in stmt.handlers:
                handler_outs += self.seq(h.body, [(h_anchor, None)],
                                         facts, env)
            if has_final:
                self.exc_stack.pop()
        if has_final:
            self.fin_stack.pop()
        outs = body_out + handler_outs
        if has_final:
            self.connect(outs, f_anchor)
            # the finally runs on the exception path too; after it, the
            # exception propagates — model both continuations (normal
            # fall-through and propagation to the next anchor/exit)
            f_out = self.seq(stmt.finalbody, [(f_anchor, None)], facts, env)
            for p, fact in f_out:
                cfg.edge(p, self.exc_stack[-1] if self.exc_stack
                         else cfg.exit, fact)
            return f_out
        return outs


def build_cfg(func: ast.AST, *, pallas: bool = False) -> CFG:
    """CFG of one function definition (or a module body).  ``pallas``
    enables the kernel-idiom splicing described in the module
    docstring."""
    b = _Builder(pallas)
    body = func.body if hasattr(func, "body") else []
    env: Dict[str, ast.FunctionDef] = {}
    out = b.seq(body, [(b.cfg.entry, None)], frozenset(), env)
    b.connect(out, b.cfg.exit)
    return b.cfg


def _traverse(cfg: CFG, start: int, blocked: Set[int],
              facts: Optional[frozenset]) -> Set[int]:
    """Nodes reachable from ``start`` by at least one edge, without
    entering ``blocked``, skipping branch edges that contradict
    ``facts`` (the conditions the start node is already executing
    under).  ``start`` itself is in the result only when a cycle leads
    back to it."""
    if facts is None:
        facts = cfg.nodes[start].facts
    seen: Set[int] = set()
    stack = [start]
    while stack:
        n = stack.pop()
        for s, fact in cfg.nodes[n].succs:
            if s in seen or s in blocked:
                continue
            if fact is not None and (fact[0], not fact[1]) in facts:
                continue
            seen.add(s)
            stack.append(s)
    return seen


def exit_reachable_without(cfg: CFG, start: int, blocked: Set[int],
                           facts: Optional[frozenset] = None) -> bool:
    """True when some path from ``start`` reaches the function exit
    without passing through any ``blocked`` node — i.e. the blocked set
    does NOT cover every path out.  The all-paths primitive: "is this
    start waited/joined on all paths" is the negation."""
    return cfg.exit in _traverse(cfg, start, blocked, facts)


def node_reachable_without(cfg: CFG, start: int, targets: Set[int],
                           blocked: Set[int],
                           facts: Optional[frozenset] = None) -> bool:
    """True when any of ``targets`` is reachable from ``start`` without
    first passing through a ``blocked`` node."""
    return bool(targets & _traverse(cfg, start, blocked, facts))


def dominators(cfg: CFG) -> List[Set[int]]:
    """Classic iterative dominator sets (edge facts ignored).  Node d
    dominates n iff every path from entry to n passes through d."""
    n = len(cfg.nodes)
    preds = cfg.preds()
    full = set(range(n))
    dom: List[Set[int]] = [set(full) for _ in range(n)]
    dom[cfg.entry] = {cfg.entry}
    changed = True
    while changed:
        changed = False
        for i in range(n):
            if i == cfg.entry:
                continue
            ps = preds[i]
            new = set.intersection(*(dom[p] for p in ps)) if ps else set(full)
            new = new | {i}
            if new != dom[i]:
                dom[i] = new
                changed = True
    return dom


# -- lock regions (ISSUE 12: RP10/RP11 substrate) ----------------------------


def lock_name(expr: ast.AST) -> Optional[str]:
    """Dotted name of a lock-like ``with`` context manager, else None.

    The heuristic: a *bare* Name/Attribute context manager
    (``with self._lock:``, ``with _SPAN_LOCK:``) is a synchronization
    primitive — locks, conditions and semaphores are the only common
    objects entered without a constructing call, while every other
    context manager (``open(...)``, ``span(...)``, ``Lock()``) reaches
    the ``with`` through a Call and is excluded."""
    if isinstance(expr, (ast.Name, ast.Attribute)):
        d = dotted(expr)
        return d or None
    return None


@dataclasses.dataclass
class LockRegions:
    """Lexical lock-region view of one function:

    - ``held``: ``id(ast node) -> tuple of lock names held`` where that
      node evaluates (outermost first).  Computed over ``with``-lock
      bodies; nested function definitions are excluded — their bodies
      run at their call sites, not inside the enclosing ``with``.
    - ``acquisitions``: every lock acquisition in the function as
      ``(lock name, line, locks already held at that point)`` — the
      raw edges of the lock-order graph.
    """

    held: Dict[int, Tuple[str, ...]]
    acquisitions: List[Tuple[str, int, Tuple[str, ...]]]


def lock_regions(func: ast.AST) -> LockRegions:
    """Per-node held-lock map + acquisition list for one function (or
    module) body.  Lexical: a ``with self._lock:`` region covers its
    body (and the later items of its own ``with`` statement — item k+1
    is acquired while item k is held), matching Python's guarantee that
    the lock is held exactly for the statement's suite."""
    held: Dict[int, Tuple[str, ...]] = {}
    acquisitions: List[Tuple[str, int, Tuple[str, ...]]] = []

    def visit(node: ast.AST, stack: Tuple[str, ...]) -> None:
        held[id(node)] = stack
        if isinstance(node, _FUNC_NODES + (ast.Lambda,)) and node is not func:
            return  # nested def: runs at its call site, not here
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = stack
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    held.setdefault(id(sub), inner)
                if item.optional_vars is not None:
                    for sub in ast.walk(item.optional_vars):
                        held.setdefault(id(sub), inner)
                name = lock_name(item.context_expr)
                if name is not None:
                    acquisitions.append(
                        (name, item.context_expr.lineno, inner)
                    )
                    inner = inner + (name,)
            for stmt in node.body:
                visit(stmt, inner)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, stack)

    body = getattr(func, "body", [])
    for stmt in body:
        visit(stmt, ())
    return LockRegions(held, acquisitions)


# -- thread roles (ISSUE 12: RP10 substrate) ---------------------------------


def thread_entries(
    scope: ast.AST,
    methods: Dict[str, ast.AST],
    nested: Dict[str, ast.AST],
) -> List[Tuple[str, ast.AST, int]]:
    """Thread entry points constructed anywhere in ``scope``: every
    ``Thread(target=X)`` whose target resolves statically — ``self.m``
    against ``methods`` or a bare name against ``nested`` (nested defs /
    module functions).  Returns ``(role name, entry def, construction
    line)`` triples; each entry function is the root of one thread
    *role* (the code that runs on that thread), the constructing code
    being the implicit "main" role."""
    out: List[Tuple[str, ast.AST, int]] = []
    seen: Set[int] = set()
    for n in ast.walk(scope):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else ""
        )
        if name != "Thread":
            continue
        target = next(
            (k.value for k in n.keywords if k.arg == "target"), None
        )
        if target is None:
            continue
        entry: Optional[ast.AST] = None
        role = ""
        if isinstance(target, ast.Attribute) and isinstance(
            target.value, ast.Name
        ) and target.value.id == "self":
            entry = methods.get(target.attr)
            role = f"self.{target.attr}"
        elif isinstance(target, ast.Name):
            entry = nested.get(target.id)
            role = target.id
        if entry is not None and id(entry) not in seen:
            seen.add(id(entry))
            out.append((role, entry, n.lineno))
    return out


# -- one-level intra-package call resolution ---------------------------------


@dataclasses.dataclass
class ModuleInfo:
    """Statically-indexed view of one package module for call
    resolution: module-level defs, (class, method) defs, every nested
    def by name, from-import aliases resolved to package-relative file
    paths, and the module's pragma-suppressed lines (so a host sync the
    owning file already suppressed with a reason does not propagate
    into RP09 findings at its callers)."""

    relpath: str
    tree: ast.Module
    funcs: Dict[str, ast.FunctionDef]
    methods: Dict[Tuple[str, str], ast.FunctionDef]
    nested: Dict[str, ast.FunctionDef]
    imports: Dict[str, Tuple[str, str]]  # alias -> (relpath, original name)
    suppressed: Dict[int, Set[str]]      # line -> rule ids allowed there


_PKG = "randomprojection_tpu"


def _import_relpath(module: Optional[str], level: int,
                    from_relpath: str) -> Optional[str]:
    """Package-relative file path of a ``from X import ...`` source, or
    None when it is not an intra-package module."""
    if level > 0:
        base = from_relpath.replace("\\", "/").rsplit("/", 1)
        parts = base[0].split("/") if len(base) == 2 else []
        parts = parts[: len(parts) - (level - 1)] if level > 1 else parts
        mod_parts = (module or "").split(".") if module else []
        return "/".join(parts + mod_parts) + ".py" if (
            parts or mod_parts
        ) else None
    if module and (module == _PKG or module.startswith(_PKG + ".")):
        rest = module[len(_PKG):].lstrip(".")
        return (rest.replace(".", "/") + ".py") if rest else None
    return None


def index_module(relpath: str, tree: ast.Module,
                 suppressed: Optional[Dict[int, Set[str]]] = None
                 ) -> ModuleInfo:
    funcs: Dict[str, ast.FunctionDef] = {}
    methods: Dict[Tuple[str, str], ast.FunctionDef] = {}
    nested: Dict[str, ast.FunctionDef] = {}
    imports: Dict[str, Tuple[str, str]] = {}
    for stmt in tree.body:
        if isinstance(stmt, _FUNC_NODES):
            funcs[stmt.name] = stmt
        elif isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                if isinstance(sub, _FUNC_NODES):
                    methods[(stmt.name, sub.name)] = sub
    for n in ast.walk(tree):
        if isinstance(n, _FUNC_NODES):
            for sub in ast.walk(n):
                if isinstance(sub, _FUNC_NODES) and sub is not n:
                    nested.setdefault(sub.name, sub)
        elif isinstance(n, ast.ImportFrom):
            rel = _import_relpath(n.module, n.level, relpath)
            if rel is not None:
                for a in n.names:
                    imports[a.asname or a.name] = (rel, a.name)
    return ModuleInfo(relpath, tree, funcs, methods, nested, imports,
                      suppressed or {})


class PackageIndex:
    """All package modules, indexed for one-level call resolution."""

    def __init__(self, modules: Optional[Dict[str, ModuleInfo]] = None):
        self.modules: Dict[str, ModuleInfo] = modules or {}

    def add(self, info: ModuleInfo) -> None:
        self.modules[info.relpath] = info

    def resolve(self, call: ast.Call, mod: ModuleInfo,
                encl_class: Optional[str]
                ) -> Optional[Tuple[ModuleInfo, ast.FunctionDef, str]]:
        """Resolve a call one level: same-module defs (module-level,
        then nested), ``self.<m>`` against the enclosing class, then
        from-imported package functions.  Returns ``(owning module,
        def, display name)`` or None for anything unresolvable."""
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in mod.funcs:
                return mod, mod.funcs[f.id], f.id
            if f.id in mod.nested:
                return mod, mod.nested[f.id], f.id
            target = mod.imports.get(f.id)
            if target is not None:
                other = self.modules.get(target[0])
                if other is not None and target[1] in other.funcs:
                    return (other, other.funcs[target[1]],
                            f"{target[0]}:{target[1]}")
            return None
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self":
            if encl_class is not None:
                m = mod.methods.get((encl_class, f.attr))
                if m is not None:
                    return mod, m, f"self.{f.attr}"
            return None
        return None
