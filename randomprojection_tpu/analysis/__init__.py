"""Static analysis for the pipeline's hand-enforced contracts.

The streaming/serving stack (r6–r9) is held together by conventions
that, until this package existed, only code review enforced: spans must
always be ended, queues must be bounded, threads must be joined, hot
paths must not block on host syncs, emitted event names must stay in
agreement with ``telemetry.EVENTS`` / ``trace_report`` / the docs, and
broad ``except`` handlers must not swallow errors silently.

``rplint`` is the AST-based checker that turns those conventions into
rules (RP01–RP06, see ``rplint.RULES``), each suppressible per line with
an inline pragma carrying a reason::

    # rplint: allow[RP03] — d2h already started at dispatch

Entry points: ``cli lint`` / ``make lint`` (runs over the shipped
package and must exit 0), ``make verify`` (lint before tier-1), and the
library surface below for programmatic use.  Pure stdlib — importing
this package never pulls jax/numpy in.
"""

from randomprojection_tpu.analysis.rplint import (
    RULES,
    Finding,
    check_registry_drift,
    lint_package,
    lint_source,
    load_event_registry,
    main,
)

__all__ = [
    "RULES",
    "Finding",
    "check_registry_drift",
    "lint_package",
    "lint_source",
    "load_event_registry",
    "main",
]
