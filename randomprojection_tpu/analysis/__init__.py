"""Static analysis for the pipeline's hand-enforced contracts.

The streaming/serving stack (r6–r14) is held together by conventions
that, until this package existed, only code review enforced: spans must
always be ended, queues must be bounded, threads must be joined on
every shutdown path, hot paths must not block on host syncs (not even
one call away), DMA copies must be waited before their slot revolves,
emitted event names must stay in agreement with ``telemetry.EVENTS`` /
``trace_report`` / the docs, and broad ``except`` handlers must not
swallow errors silently.

``rplint`` is the checker that turns those conventions into rules
(RP01–RP11, see ``rplint.RULES``).  Since ISSUE 11 it is a small
flow-sensitive framework: ``cfg.py`` builds statement-level CFGs (with
Pallas ``@pl.when``/``fori_loop`` splicing), lexical lock regions,
thread-role discovery and a one-level intra-package call index;
``flowrules.py`` implements the path-sensitive rules (RP07 DMA
discipline, RP08 thread/queue protocol, RP09 interprocedural
host-sync, and — since ISSUE 12 — RP10 cross-thread shared-state races
and RP11 lock-order deadlock analysis) on top; ``rplint.py`` keeps the
per-line rules, the pragma grammar, and the CLI.  Each finding is
suppressible per line with an inline pragma carrying a reason::

    # rplint: allow[RP03] — d2h already started at dispatch

Entry points: ``cli lint`` / ``make lint`` (runs over the shipped
package and must exit 0 — exit 1 means findings, exit 2 an internal
error, never silent success off a partial run), ``make lint-ci``
(``--baseline .rplint_baseline.json``: fail only on NEW findings;
``--update-baseline`` rewrites the baseline in place to accept them),
``--sarif PATH`` (SARIF 2.1.0 for CI/editor annotation), ``make
verify`` (before tier-1), and the library surface below for
programmatic use.  Pure stdlib — importing this package never pulls
jax/numpy in.
"""

from randomprojection_tpu.analysis.rplint import (
    RULES,
    Finding,
    check_registry_drift,
    diff_baseline,
    lint_package,
    lint_source,
    load_event_registry,
    main,
    to_sarif,
)

__all__ = [
    "RULES",
    "Finding",
    "check_registry_drift",
    "diff_baseline",
    "lint_package",
    "lint_source",
    "load_event_registry",
    "main",
    "to_sarif",
]
