"""rplint — AST-based invariant checker for this repo's contracts.

Generic linters cannot see the contracts the r6–r14 pipeline work relies
on (a ``start_span`` with no exception-safe ``end_span`` is legal
Python; an unbounded ``queue.Queue()`` is idiomatic; an un-waited
``make_async_copy`` compiles fine); this checker encodes them as
project rules over the stdlib ``ast``.  Since ISSUE 11 the checker is a
small *flow-sensitive* framework: a shared CFG/call-graph substrate
(``analysis/cfg.py``) feeds the path-sensitive rules
(``analysis/flowrules.py``) while the per-line rules keep their r10
shape.

- **RP01 span-balance** — a ``start_span`` whose handle neither escapes
  its function (returned / yielded / stored / passed on, e.g. through a
  pipeline queue) nor is closed by an ``end_span`` inside a ``finally``
  or ``except`` block leaks its span on the error path; and the
  ``span_start``/``span_end`` event pair may be emitted by
  ``utils/telemetry.py`` ONLY — hand-rolled span events bypass id
  allocation and corrupt trace reconstruction.
- **RP02 event-registry drift** — every statically-resolvable event
  name passed to ``emit()`` must be a member of ``telemetry.EVENTS``
  (f-string and ``FAMILY``-constant-anchored names must extend a
  registered ``FAMILIES`` prefix), and every registry member must be
  either consumed by ``utils/trace_report.py`` or documented in
  docs/ARCHITECTURE.md.  Names built dynamically (a variable, an
  unanchored concatenation, a ``.format()``) are reported as
  ``unresolvable-emit`` *informational* findings — they never fail the
  lint, but ``--json`` counts them so registry coverage is honest about
  its blind spot.
- **RP03 host-sync-in-hot-path** — inside loop bodies of the hot
  modules (``HOT_MODULES``), no ``np.asarray``, ``.block_until_ready``,
  ``jax.device_get`` or ``float()``-on-expression: a per-iteration host
  sync serializes device compute with d2h — exactly the ``query_topk``
  bug r9 fixed.  (Lexically scoped to loops: the commit-point fetch a
  pipeline performs once per batch *outside* any loop is the design.)
- **RP04 thread hygiene** — every ``threading.Thread`` is constructed
  with an explicit ``daemon=`` and its module contains a ``.join(``;
  every ``queue.Queue()`` is constructed with a bound.
- **RP05 determinism** — inside ``ops/`` (kernel and hashing bodies):
  no ``time.time()``, no global ``random.*``, no legacy
  ``np.random.<fn>`` calls (Generator construction is allowed) — RNG
  and clocks are threaded explicitly so kernels stay replayable.
- **RP06 silent-swallow** — broad ``except`` handlers (bare /
  ``Exception`` / ``BaseException``) in the pipeline/serving modules
  must re-raise, emit telemetry, or close the active span.
- **RP07 DMA discipline** (flow-sensitive; kernel modules) — inside
  Pallas kernel bodies, every ``make_async_copy`` start must reach a
  matching ``.wait()`` on all paths (``@pl.when`` bodies and
  ``fori_loop`` body functions are spliced into the CFG); revolving
  slot phases must stay within the declared slot count (a start at
  phase ``+c`` waited at phase ``+w`` re-targets its buffer after ``K``
  iterations, so ``0 <= c-w < K``); the revolving modulus must match a
  declared ``VMEM``/DMA-semaphore slot count; and the module's VMEM
  budget function (``_reserved_bytes`` / ``plan_fused``) must charge
  every VMEM operand the kernels actually allocate (re-derived from the
  AST).
- **RP08 thread/queue protocol** (flow-sensitive) — every thread
  started in a function is joined on *every* path out of it (early
  returns, raises, try/finally modeled); threads stored on ``self`` are
  joined by the class, reachably from its close-like method; a
  shutdown-sentinel enqueue is unconditionally reachable from
  ``close()`` (only closed-flag idempotence guards may skip it); and no
  cursor commit dominates its batch's ``yield`` (ack-after-yield).
- **RP09 interprocedural host-sync** (hot modules) — RP03 one call
  deeper: a loop-body call resolved one level through the package
  (same-module defs, ``self.`` methods, ``from randomprojection_tpu...
  import`` names) whose callee performs an unsuppressed host sync is
  reported at the call site — the helper-hidden stall r9 fixed by hand.
- **RP10 shared-state races** (concurrency modules; ISSUE 12) — thread
  roles derive from RP08's discovery (one role per ``Thread(target=…)``
  entry point plus the constructing "main" role, subclass hooks joining
  their base class's roles through the package index); per-role
  ``self.``-attribute read/write sets fold transitively one call level
  at a time with the lock context of each call site, and a cross-role
  write/write or read/write pair is a finding unless every access path
  holds the same lock, the value crosses roles only through the
  object's own method calls (the ``queue.Queue`` handoff), or every
  write dominates every ``.start()`` (init-only, by dominator query).
  Lock-holding classes and module globals *without* thread roles get
  the lock-consistency leg instead: state touched under a lock must
  hold it on every post-init access.
- **RP11 lock-order deadlocks** (concurrency modules; ISSUE 12) — the
  lock-acquisition ordering graph (nested ``with``-lock regions, one
  call level deep) must be acyclic, and no blocking call
  (``queue.put``, ``.join``, ``future.result``) may run while a lock is
  held.
- **RP12 resource lifecycle** (all modules; ISSUE 20) — RP01's
  span-balance engine generalized to paired acquire/release protocols:
  a telemetry subscription, ``MetricsServer``, ``HealthEngine``,
  ``open()`` handle, ``np.memmap``, or ``mkdtemp`` temp dir bound to a
  local must be released on every path out of the acquiring function
  (escaping handles exempt, ``if x is not None:`` release guards
  understood), and — the r17 bug shape — a later acquire outside any
  try while an earlier handle is live is flagged: if it raises, the
  earlier handle leaks.
- **RP13 durable-commit discipline** (durable/tiering/telemetry/
  streaming + the linter's own baseline writer; ISSUE 20) — every
  artifact landing goes tmp→flush→fsync→``os.replace`` (a raw
  ``open(final_path, "w")`` is a finding), the manifest replace is
  dominated by every chunk/spill write of the same commit
  (manifest-committed-LAST by dominator query), and a directory fsync
  is reachable after the replace (helpers whose callers fsync the
  directory are exempt).
- **RP14 degraded-path audit** (kernel/LSH/tiering ladders; ISSUE 20)
  — every fallback rung (broad except that continues) reachably emits
  an event that ``trace_report.DEGRADED_EVENTS`` consumes or calls a
  degraded-rung recorder; classified rungs memoize their degraded key
  (the r6 ``_NO_*_KEYS`` convention, CFG-reachability checked so the
  post-success ``.add()`` after a ladder loop counts); fallback
  counters need an adjacent event emit; and — the RP02-style reverse
  leg — every ``DEGRADED_EVENTS`` member must exist in the registry
  and be emitted somewhere outside trace_report.

Suppression pragma (same line as the finding, the line directly above
it, or any physical line of the same logical statement — so pragmas on
continuation lines work)::

    # rplint: allow[RP03] — d2h already started at dispatch
    # rplint: allow[RP04,RP06] — reason covering both rules

The reason is mandatory; a pragma that does not parse, names an unknown
rule, or omits the reason is itself reported (RP00) and suppresses
nothing.  A well-formed pragma that suppresses *nothing* — because the
code it excused has been edited away — is reported as a **stale
pragma** (RP00) when every rule it names was actually evaluated for
the file, so dead suppressions cannot accumulate.

Exit codes (``cli lint`` inherits them): **0** no unsuppressed finding,
**1** findings, **2** internal error (unreadable input, malformed
baseline, analysis crash) — a partial run can never report success.
``--json`` emits the stable findings schema (``rplint`` version, rule
id, path, line, message, severity, pragma state) for the bench/record
machinery.  ``--baseline <json>`` diffs against a prior ``--json``
record and fails only on NEW findings (matched on rule+path+message, so
line drift never re-flags a baselined finding) — strict rules can land
without blocking unrelated work.

The analysis prefers missing an exotic violation over flagging correct
code, because every false positive costs a pragma in the tree forever.
"""

from __future__ import annotations

import argparse
import ast
import concurrent.futures
import dataclasses
import io
import json
import os
import re
import sys
import time
import tokenize
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from randomprojection_tpu.analysis import flowrules
from randomprojection_tpu.analysis.cfg import (
    PackageIndex,
    dotted as _dotted,
    index_module,
    parents_map as _parents,
)

__all__ = [
    "RULES",
    "Finding",
    "EventRegistry",
    "load_event_registry",
    "check_registry_drift",
    "load_degraded_events",
    "check_degraded_drift",
    "diff_baseline",
    "lint_source",
    "lint_package",
    "package_root",
    "to_sarif",
    "main",
]

RULES = {
    "RP00": "pragma hygiene: rplint pragmas parse as "
            "`# rplint: allow[RPxx] — <reason>` with known rules and a "
            "reason, and a pragma that suppresses nothing is stale",
    "RP01": "span-balance: start_span handles escape or end in a "
            "finally/except; span_* events are emitted only by "
            "utils/telemetry.py",
    "RP02": "event-registry drift: emitted event names live in "
            "telemetry.EVENTS, and every registry entry is consumed by "
            "trace_report.py or documented in ARCHITECTURE.md "
            "(dynamically-built names are counted as unresolvable-emit "
            "informational findings)",
    "RP03": "host-sync-in-hot-path: no np.asarray / .block_until_ready / "
            "jax.device_get / float()-on-expression inside loop bodies of "
            "the hot modules",
    "RP04": "thread hygiene: threading.Thread has explicit daemon= and a "
            ".join( in the module; queue.Queue is bounded",
    "RP05": "determinism: no time.time(), global random.*, or legacy "
            "np.random.<fn> inside ops/",
    "RP06": "silent-swallow: broad except handlers in pipeline modules "
            "re-raise, emit telemetry, or close the span",
    "RP07": "DMA discipline: every make_async_copy start reaches a wait "
            "on all paths, revolving slots stay within the declared slot "
            "count, and the kernel VMEM budget charges every VMEM "
            "allocation",
    "RP08": "thread/queue protocol: threads join on every shutdown path, "
            "close() reaches the shutdown sentinel unconditionally, and "
            "no cursor commit dominates its batch's yield",
    "RP09": "interprocedural host-sync: hot-module loops must not call a "
            "package helper (one level deep) that performs a host sync",
    "RP10": "shared-state races: state shared across thread roles needs "
            "a common lock on every access path, a queue handoff, or "
            "init-only writes that dominate the thread start",
    "RP11": "lock-order deadlocks: the lock-acquisition ordering graph "
            "must be acyclic, and no blocking call (queue.put / .join / "
            "future.result) may run while a lock is held",
    "RP12": "resource lifecycle: subscriptions, MetricsServer, "
            "HealthEngine, open()/np.memmap handles and mkdtemp dirs are "
            "released on every path out of the acquiring function, and "
            "no unprotected later acquire can leak an earlier live "
            "handle (the r17 bug shape)",
    "RP13": "durable-commit discipline: artifact writes go tmp→flush→"
            "fsync→os.replace, the manifest is committed last (dominated "
            "by every chunk/spill write), and a directory fsync is "
            "reachable after the replace",
    "RP14": "degraded-path audit: every fallback rung emits a "
            "DEGRADED_EVENTS-consumed event or calls a recorder, "
            "classified rungs memoize their degraded key, fallback "
            "counters sit next to their emit, and every DEGRADED_EVENTS "
            "member is registered and emitted somewhere",
}

# -- rule scoping (paths are package-relative, '/'-separated) ----------------

TELEMETRY_MODULE = "utils/telemetry.py"
TRACE_REPORT_MODULE = "utils/trace_report.py"
ARCHITECTURE_DOC = os.path.join("docs", "ARCHITECTURE.md")
# RP03/RP09: the modules whose loops are the streamed/serving hot sections
HOT_MODULES = (
    "streaming.py",
    "backends/jax_backend.py",
    "ops/pallas_kernels.py",
    "ops/topk_kernels.py",
    "models/sketch.py",
    "serving/sharded_index.py",
    "serving/server.py",
    # r17 live plane: the scrape handler runs while the pipeline serves,
    # and the loadgen submit loop IS an open-loop latency measurement —
    # a hidden host sync in either falsifies what they observe
    "utils/metrics_server.py",
    "loadgen.py",
    # r18 LSH candidate tier: probe + gather + re-rank is the new
    # serving hot loop — a hidden host sync there re-serializes exactly
    # the dispatch/d2h overlap the tier inherits from query_topk
    "ann/lsh.py",
    # r19 device-fused probe path: the probe→gather→re-rank kernels run
    # per serving tile — a host sync here IS the host hop they remove
    "ops/probe_kernels.py",
    # r20 health plane: the engine's event fold + tick loop run for the
    # whole process lifetime beside the serving path — a host sync or
    # swallowed error there silently blinds every detector
    "utils/health.py",
    # r21 tiered residency: the stager/gather/fetch path runs per
    # serving tile — a hidden host sync there re-serializes exactly the
    # cold-upload/hot-kernel overlap the tier exists to provide
    "tiering.py",
)
# RP06: modules on the pipeline/serving path where a swallowed error
# strands a stream, a future, or a telemetry file
PIPELINE_MODULES = HOT_MODULES + (
    "ops/hashing.py",
    "utils/observability.py",
    TELEMETRY_MODULE,
    # r14: the bench harness carries the transform-route/dispatch-fusion
    # knobs whose provenance the tripwire depends on, and the doctor is
    # the consumer of the kernel.dma.* route records — a swallowed error
    # in either silently falsifies a measurement
    "benchmark.py",
    TRACE_REPORT_MODULE,
)
DETERMINISM_PREFIXES = ("ops/",)
# RP07: the manually-DMA'd Pallas kernel modules, each with the function
# that owns its scoped-VMEM budget (the allocation cross-check target)
KERNEL_BUDGET_FNS = {
    "ops/pallas_kernels.py": "_reserved_bytes",
    "ops/topk_kernels.py": "plan_fused",
    "ops/probe_kernels.py": "plan_probe",
    # r21 tiered residency: plan_residency owns the HBM-budget admission
    # plan (hot set + bounded staging headroom) the tier serves under
    "tiering.py": "plan_residency",
}
KERNEL_MODULES = tuple(KERNEL_BUDGET_FNS)
# RP10/RP11 (ISSUE 12): the modules where threads and locks meet — the
# four thread/queue substrates (PrefetchSource + StagedIngestSource,
# TopKServer, ShardedTopKServer) plus the lock-holding telemetry,
# sharded-index and hashing modules
CONCURRENCY_MODULES = (
    "streaming.py",
    "models/sketch.py",
    "serving/server.py",
    "serving/sharded_index.py",
    "utils/telemetry.py",
    "ops/hashing.py",
    # r17 live plane: subscriber dispatch threads (telemetry, above),
    # the metrics HTTP serving thread, and loadgen's completion-callback
    # lock are all born under RP10/RP11
    "utils/metrics_server.py",
    "loadgen.py",
    # r20 health plane: the engine lock is taken by both the subscriber
    # dispatch thread (event fold) and the tick thread (evaluate) — the
    # emit-outside-lock contract is exactly what RP10/RP11 police
    "utils/health.py",
    # r21 tiered residency: the manager lock is taken by serving threads
    # (admission, access accounting) and the promotion/demotion worker
    # (residency swaps) — emit-outside-lock and never-put-under-lock are
    # exactly its correctness story
    "tiering.py",
)
# RP13 (ISSUE 20): the modules that land durable artifacts — the
# snapshot/spill writers, the flight-recorder dump, the stream cursor,
# and the linter's own baseline/SARIF writer (it must practice the
# commit idiom it preaches)
RP13_MODULES = (
    "durable.py",
    "tiering.py",
    "utils/telemetry.py",
    "streaming.py",
    "analysis/rplint.py",
)
# RP14 (ISSUE 20): the ladder modules whose fallback rungs the doctor
# must be able to see — the kernel VMEM/DMA ladders, the LSH probe
# ladder, the residency tier ladder, and the serving-side fallbacks
RP14_MODULES = (
    "ops/pallas_kernels.py",
    "ops/topk_kernels.py",
    "ops/probe_kernels.py",
    "ann/lsh.py",
    "tiering.py",
    "models/sketch.py",
    "backends/jax_backend.py",
)
# RP05: Generator-construction surface of np.random that stays legal
RNG_FACTORY_OK = frozenset(
    {"default_rng", "Generator", "SeedSequence", "PCG64", "Philox",
     "MT19937", "bit_generator"}
)
# RP06: a handler containing a call to one of these has routed the error
# somewhere observable (record_vmem_oom_retry is the shared degraded-
# retry recorder — it emits + counts for both VMEM-OOM call sites)
RP06_MITIGATORS = frozenset(
    {"emit", "counter_inc", "end_span", "record_vmem_oom_retry",
     # r21: the tiered residency layer's shared degraded-rung recorder —
     # emits index.tier.fallback AND bumps the fallback counter, the
     # same emit+count contract as record_vmem_oom_retry
     "note_fallback"}
)

_PRAGMA_RE = re.compile(r"#\s*rplint:\s*(.*)$")
_ALLOW_RE = re.compile(
    r"^allow\[([A-Za-z0-9_,\s]+)\]\s*(?:[—–]|--|-)\s*(\S.*)$"
)


@dataclasses.dataclass
class Finding:
    """One lint finding; ``suppressed`` marks a pragma'd (accepted)
    violation, ``reason`` carries the pragma's justification,
    ``severity`` is ``"error"`` (fails the lint) or ``"info"``
    (reported and counted, never fatal — the unresolvable-emit
    class)."""

    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    reason: str = ""
    severity: str = "error"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "suppressed": self.suppressed,
            "reason": self.reason,
            "severity": self.severity,
        }

    def render(self) -> str:
        sup = "  [suppressed: %s]" % self.reason if self.suppressed else ""
        sev = " (info)" if self.severity == "info" else ""
        return f"{self.path}:{self.line}: {self.rule}{sev} {self.message}{sup}"


# -- pragma scanning ---------------------------------------------------------


@dataclasses.dataclass
class _Pragma:
    """One well-formed allow pragma: the comment's physical line, the
    rules it names, the mandatory reason, and whether it ended up
    suppressing anything (stale detection)."""

    line: int
    rules: Set[str]
    reason: str
    matched: bool = False


def _scan_pragmas(
    src: str, relpath: str
) -> Tuple[Dict[int, List[_Pragma]], List[Finding], List[_Pragma]]:
    """``{physical line: [pragmas attached there]}`` plus RP00 findings
    for malformed pragmas and the flat pragma list (for stale
    detection).  Comment tokens only — a pragma-shaped string literal
    is never a pragma.  A pragma on any physical line of a multi-line
    logical statement attaches to every line of that statement, so
    findings anchored at the statement's first line are suppressible
    from a continuation line."""
    allows: Dict[int, List[_Pragma]] = {}
    pragmas: List[_Pragma] = []
    findings: List[Finding] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(src).readline))
    except (tokenize.TokenError, IndentationError):  # pragma: no cover
        return allows, findings, pragmas  # ast.parse reported the syntax

    def parse(tok) -> Optional[_Pragma]:
        m = _PRAGMA_RE.search(tok.string)
        if m is None:
            return None
        line = tok.start[0]
        am = _ALLOW_RE.match(m.group(1).strip())
        if am is None:
            findings.append(Finding(
                "RP00", relpath, line,
                "unparseable rplint pragma (grammar: "
                "`# rplint: allow[RPxx] — <reason>`, reason required)",
            ))
            return None
        rules = {r.strip().upper() for r in am.group(1).split(",")
                 if r.strip()}
        unknown = sorted(rules - set(RULES))
        if unknown:
            # the whole pragma is void, including any known rules it
            # also names — a malformed pragma suppresses NOTHING, so a
            # typo can never silently accept a violation
            findings.append(Finding(
                "RP00", relpath, line,
                f"pragma names unknown rule(s): {', '.join(unknown)} — "
                "the pragma suppresses nothing",
            ))
            return None
        if not rules:
            return None
        return _Pragma(line, rules, am.group(2).strip())

    def register(p: _Pragma, lines) -> None:
        for ln in lines:
            lst = allows.setdefault(ln, [])
            if p not in lst:
                lst.append(p)

    span_start: Optional[int] = None
    pending: List[_Pragma] = []
    for tok in tokens:
        tt = tok.type
        if tt == tokenize.COMMENT:
            p = parse(tok)
            if p is not None:
                pragmas.append(p)
                register(p, [p.line])
                if span_start is not None:
                    pending.append(p)
            continue
        if tt == tokenize.NEWLINE:
            if span_start is not None and pending:
                # logical line ends: a pragma anywhere in it covers the
                # whole statement's physical span
                for p in pending:
                    register(p, range(span_start, tok.start[0] + 1))
            span_start = None
            pending = []
            continue
        if tt in (tokenize.NL, tokenize.INDENT, tokenize.DEDENT,
                  tokenize.ENDMARKER):
            continue
        if span_start is None:
            span_start = tok.start[0]
    return allows, findings, pragmas


# -- small AST helpers -------------------------------------------------------


def _callee(call: ast.Call) -> str:
    """Last path component of the callee ('emit' for telemetry.emit)."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _own_nodes(scope: ast.AST) -> Iterator[ast.AST]:
    """Nodes lexically owned by ``scope``: its subtree minus the bodies
    of nested function definitions (each nested def owns its own)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, _FUNC_NODES + (ast.Lambda,)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _scopes(tree: ast.Module) -> List[ast.AST]:
    """The module plus every (possibly nested) function definition."""
    return [tree] + [
        n for n in ast.walk(tree) if isinstance(n, _FUNC_NODES)
    ]


def _imports_name(tree: ast.Module, module_suffix: str, name: str) -> bool:
    """True when ``from <...module_suffix> import <name>`` appears."""
    for n in ast.walk(tree):
        if isinstance(n, ast.ImportFrom) and n.module and (
            n.module == module_suffix
            or n.module.endswith("." + module_suffix)
        ):
            if any(a.name == name for a in n.names):
                return True
    return False


def _is_emit_call(call: ast.Call, *, in_telemetry: bool,
                  emit_imported: bool) -> bool:
    """A call of the package's ``emit()``: ``telemetry.emit(...)``, a
    directly-imported ``emit(...)``, or (inside telemetry.py itself) the
    module-level ``emit(...)``.  ``TelemetryLog.emit``/arbitrary
    ``x.emit`` methods don't count — the registry governs the
    process-wide event stream, not every method named emit."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "emit":
        base = _dotted(f.value)
        return base == "telemetry" or base.endswith(".telemetry")
    if isinstance(f, ast.Name) and f.id == "emit":
        return emit_imported or in_telemetry
    return False


# -- the event registry (RP02) -----------------------------------------------


@dataclasses.dataclass
class EventRegistry:
    """Statically-parsed view of ``telemetry.EVENTS``: constant name →
    event string (families excluded), family prefixes, the source line
    of each constant (so drift findings anchor to the registry), and
    the family constant names (``*_FAMILY``) so a
    ``EVENTS.X_FAMILY + suffix`` concatenation resolves as a family
    extension."""

    events: Dict[str, str]
    families: Tuple[str, ...]
    lines: Dict[str, int]
    family_attrs: Dict[str, str] = dataclasses.field(default_factory=dict)

    def knows(self, name: str) -> bool:
        return name in self.events.values() or any(
            name.startswith(f) for f in self.families
        )


def load_event_registry(telemetry_src: str) -> Optional[EventRegistry]:
    """Parse the ``EVENTS`` class out of telemetry.py source (static —
    the linter never imports the package it checks)."""
    try:
        tree = ast.parse(telemetry_src)
    except SyntaxError:
        return None
    cls = next(
        (n for n in ast.walk(tree)
         if isinstance(n, ast.ClassDef) and n.name == "EVENTS"),
        None,
    )
    if cls is None:
        return None
    events: Dict[str, str] = {}
    lines: Dict[str, int] = {}
    families: List[str] = []
    family_attrs: Dict[str, str] = {}
    for stmt in cls.body:
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            continue
        attr = stmt.targets[0].id
        if attr == "FAMILIES" and isinstance(stmt.value, ast.Tuple):
            families.extend(
                e.value for e in stmt.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            )
            continue
        if not (isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, str)):
            continue
        if attr.endswith("_FAMILY"):
            families.append(stmt.value.value)
            family_attrs[attr] = stmt.value.value
            continue
        events[attr] = stmt.value.value
        lines[attr] = stmt.lineno
    return EventRegistry(events, tuple(dict.fromkeys(families)), lines,
                         family_attrs)


def check_registry_drift(
    registry: EventRegistry,
    consumer_text: str,
    doc_text: str,
    telemetry_relpath: str = TELEMETRY_MODULE,
) -> List[Finding]:
    """RP02, registry side: every entry must be consumed by trace_report
    (by literal value or ``EVENTS.<NAME>`` reference) or documented in
    ARCHITECTURE.md — an event nobody reads and nobody documents is
    dead weight drifting away from reality."""
    findings = []
    for attr, value in sorted(registry.events.items()):
        consumed = (
            value in consumer_text or f"EVENTS.{attr}" in consumer_text
        )
        documented = value in doc_text
        if not (consumed or documented):
            findings.append(Finding(
                "RP02", telemetry_relpath,
                registry.lines.get(attr, 1),
                f"registry event {value!r} ({attr}) is neither consumed "
                "by trace_report.py nor documented in ARCHITECTURE.md",
            ))
    return findings


# -- the degraded-events contract (RP14, reverse leg) ------------------------


def load_degraded_events(consumer_text: str) -> Tuple[Set[str], int]:
    """Parse trace_report's ``DEGRADED_EVENTS = (EVENTS.X, ...)`` tuple
    into the attr-name set RP14's emit matching consumes, plus the
    assignment's line (so reverse-leg findings anchor there).  Returns
    ``(set(), 1)`` when the consumer is missing or unparsable — RP14's
    forward leg then accepts any ``EVENTS.*`` emit."""
    try:
        tree = ast.parse(consumer_text)
    except SyntaxError:
        return set(), 1
    for n in ast.walk(tree):
        if not isinstance(n, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "DEGRADED_EVENTS"
                   for t in n.targets):
            continue
        attrs = {
            sub.attr for sub in ast.walk(n.value)
            if isinstance(sub, ast.Attribute)
            and _dotted(sub.value).split(".")[-1] == "EVENTS"
        }
        return attrs, n.lineno
    return set(), 1


def check_degraded_drift(
    degraded: Set[str],
    degraded_line: int,
    registry: EventRegistry,
    sources: Sequence[Tuple[str, str]],
    consumer_relpath: str = TRACE_REPORT_MODULE,
) -> List[Finding]:
    """RP14, reverse leg (the RP02 shape): every DEGRADED_EVENTS member
    must exist in the telemetry registry AND be emitted by some module
    other than trace_report — a consumed-but-never-produced degraded
    event means the doctor watches a signal nothing can raise."""
    findings: List[Finding] = []
    for attr in sorted(degraded):
        if attr not in registry.events and attr not in registry.family_attrs:
            findings.append(Finding(
                "RP14", consumer_relpath, degraded_line,
                f"DEGRADED_EVENTS names EVENTS.{attr}, which is not a "
                "telemetry registry member — the doctor consumes an "
                "event that cannot exist",
            ))
            continue
        pat = re.compile(rf"EVENTS\.{re.escape(attr)}\b")
        if not any(
            pat.search(src) for rel, src in sources
            if rel != consumer_relpath
        ):
            findings.append(Finding(
                "RP14", consumer_relpath, degraded_line,
                f"DEGRADED_EVENTS names EVENTS.{attr}, but no module "
                "outside trace_report emits it — the doctor watches a "
                "degraded signal nothing raises",
            ))
    return findings


# -- rules -------------------------------------------------------------------


def _rule_rp01(tree: ast.Module, relpath: str,
               parents: Dict[ast.AST, ast.AST],
               emit_imported: bool) -> List[Finding]:
    out: List[Finding] = []
    in_telemetry = relpath == TELEMETRY_MODULE

    if not in_telemetry:
        for call in (n for n in ast.walk(tree) if isinstance(n, ast.Call)):
            if not _is_emit_call(call, in_telemetry=False,
                                 emit_imported=emit_imported):
                continue
            a0 = call.args[0] if call.args else None
            if (isinstance(a0, ast.Constant) and isinstance(a0.value, str)
                    and a0.value.startswith("span_")):
                out.append(Finding(
                    "RP01", relpath, call.lineno,
                    f"emit of span event {a0.value!r} outside "
                    "utils/telemetry.py — use span()/start_span()/"
                    "end_span(), never hand-rolled span events",
                ))

    for scope in _scopes(tree):
        own = list(_own_nodes(scope))
        starts = [
            n for n in own
            if isinstance(n, ast.Call) and _callee(n) == "start_span"
        ]
        if not starts:
            continue
        protected = _has_protected_end(own)
        for call in starts:
            if _start_span_ok(call, own, parents, protected):
                continue
            out.append(Finding(
                "RP01", relpath, call.lineno,
                "start_span handle neither escapes this function nor is "
                "closed by an end_span inside a finally/except — the span "
                "leaks on the error path; use the span() context manager "
                "or end it in a finally",
            ))
    return out


def _has_protected_end(own: Sequence[ast.AST]) -> bool:
    """An ``end_span`` call inside a ``finally`` or ``except`` of this
    scope (exception-safe close)."""
    for n in own:
        if not isinstance(n, ast.Try):
            continue
        regions = list(n.finalbody)
        for h in n.handlers:
            regions.extend(h.body)
        for stmt in regions:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call) and _callee(sub) == "end_span":
                    return True
    return False


def _start_span_ok(call: ast.Call, own: Sequence[ast.AST],
                   parents: Dict[ast.AST, ast.AST],
                   protected: bool) -> bool:
    p = parents.get(call)
    # handle used directly: returned/yielded, element of a container, or
    # argument of another call — it escapes, the receiver owns ending it
    if isinstance(p, (ast.Return, ast.Yield, ast.Tuple, ast.List,
                      ast.keyword)):
        return True
    if isinstance(p, ast.Call) and p is not call:
        return True
    if isinstance(p, ast.Assign) and len(p.targets) == 1:
        tgt = p.targets[0]
        if isinstance(tgt, (ast.Attribute, ast.Subscript)):
            return True  # stored on an object: lifecycle escapes
        if isinstance(tgt, ast.Name):
            return protected or _name_escapes(own, tgt.id)
    # bare expression statement: the handle is discarded — nothing can
    # ever end this span, protected ends elsewhere notwithstanding
    return False


def _name_escapes(own: Sequence[ast.AST], name: str) -> bool:
    """The bound handle leaves the scope: returned/yielded, placed in a
    container, stored through an attribute/subscript, or passed to a
    call that may own it (activate_span/end_span/trace_fields read the
    span without taking ownership and don't count)."""
    non_owning = {"end_span", "activate_span", "trace_fields"}

    def contains(sub: ast.AST) -> bool:
        return any(
            isinstance(x, ast.Name) and x.id == name
            for x in ast.walk(sub)
        )

    for n in own:
        if isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)):
            if n.value is not None and contains(n.value):
                return True
        elif isinstance(n, (ast.Tuple, ast.List, ast.Set)):
            if any(isinstance(e, ast.Name) and e.id == name
                   for e in n.elts):
                return True
        elif isinstance(n, ast.Call) and _callee(n) not in non_owning:
            if any(contains(a) for a in n.args) or any(
                contains(k.value) for k in n.keywords
            ):
                return True
        elif isinstance(n, ast.Assign):
            if contains(n.value) and any(
                isinstance(t, (ast.Attribute, ast.Subscript))
                for t in n.targets
            ):
                return True
    return False


def _rule_rp02(tree: ast.Module, relpath: str,
               registry: Optional[EventRegistry],
               emit_imported: bool) -> List[Finding]:
    if registry is None:
        return []
    out: List[Finding] = []
    in_telemetry = relpath == TELEMETRY_MODULE

    def unresolvable(call: ast.Call, kind: str) -> Finding:
        return Finding(
            "RP02", relpath, call.lineno,
            f"unresolvable-emit: event name built dynamically ({kind}) "
            "— not statically checkable against telemetry.EVENTS; "
            "prefer an EVENTS constant or a FAMILY-anchored name",
            severity="info",
        )

    for call in (n for n in ast.walk(tree) if isinstance(n, ast.Call)):
        if not _is_emit_call(call, in_telemetry=in_telemetry,
                             emit_imported=emit_imported):
            continue
        a0 = call.args[0] if call.args else None
        if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
            if not registry.knows(a0.value):
                out.append(Finding(
                    "RP02", relpath, call.lineno,
                    f"emit of event {a0.value!r} not registered in "
                    "telemetry.EVENTS — add it to the registry (and "
                    "consume or document it)",
                ))
        elif isinstance(a0, ast.Attribute):
            base = _dotted(a0.value)
            if base == "EVENTS" or base.endswith(".EVENTS"):
                if a0.attr not in registry.events and (
                    a0.attr not in registry.family_attrs
                ):
                    out.append(Finding(
                        "RP02", relpath, call.lineno,
                        f"emit references unknown registry constant "
                        f"EVENTS.{a0.attr}",
                    ))
            else:
                # some other object's attribute: a dynamic name (was
                # silently skipped before ISSUE 11 — now counted)
                out.append(unresolvable(call, "attribute of a variable"))
        elif isinstance(a0, ast.JoinedStr):
            prefix = ""
            for part in a0.values:
                if isinstance(part, ast.Constant) and isinstance(
                    part.value, str
                ):
                    prefix += part.value
                else:
                    break
            if not any(prefix.startswith(f) for f in registry.families):
                out.append(Finding(
                    "RP02", relpath, call.lineno,
                    f"f-string event name (static prefix {prefix!r}) does "
                    "not extend any registered EVENTS.FAMILIES prefix",
                ))
        elif isinstance(a0, ast.BinOp) and isinstance(a0.op, ast.Add):
            left = a0.left
            l_base = _dotted(left.value) if isinstance(
                left, ast.Attribute) else ""
            if isinstance(left, ast.Attribute) and (
                l_base == "EVENTS" or l_base.endswith(".EVENTS")
            ) and left.attr in registry.family_attrs:
                pass  # EVENTS.<X>_FAMILY + suffix: a family extension
            elif isinstance(left, ast.Constant) and isinstance(
                left.value, str
            ):
                if not any(left.value.startswith(f)
                           for f in registry.families):
                    out.append(Finding(
                        "RP02", relpath, call.lineno,
                        f"concatenated event name (static prefix "
                        f"{left.value!r}) does not extend any registered "
                        "EVENTS.FAMILIES prefix",
                    ))
            else:
                out.append(unresolvable(call, "string concatenation"))
        elif a0 is not None:
            kind = type(a0).__name__
            out.append(unresolvable(
                call, {"Name": "a variable", "Call": "a call result"}.get(
                    kind, kind)
            ))
    return out


def _rule_rp03(tree: ast.Module, relpath: str) -> List[Finding]:
    out: List[Finding] = []
    seen: set = set()
    loops = [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.For, ast.While, ast.AsyncFor))
    ]
    for loop in loops:
        for n in ast.walk(loop):
            if not isinstance(n, ast.Call) or id(n) in seen:
                continue
            what = flowrules.host_sync_what(n)
            if what is not None:
                seen.add(id(n))
                out.append(Finding(
                    "RP03", relpath, n.lineno,
                    f"{what} inside a loop body of a hot module blocks "
                    "on a host sync every iteration — overlap the fetch "
                    "(copy_to_host_async + materialize one behind) or "
                    "hoist it out of the loop",
                ))
    return out


def _rule_rp04(tree: ast.Module, relpath: str,
               rp08_covered: Optional[Set[int]] = None) -> List[Finding]:
    out: List[Finding] = []
    rp08_covered = rp08_covered or set()
    thread_imported = _imports_name(tree, "threading", "Thread")
    queue_imported = any(
        _imports_name(tree, "queue", n) for n in ("Queue", "LifoQueue")
    )
    has_join = False
    threads: List[ast.Call] = []
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if isinstance(f, ast.Attribute) and f.attr == "join":
            # "sep".join(...) is string plumbing, not thread hygiene
            if not (isinstance(f.value, ast.Constant)
                    and isinstance(f.value.value, str)):
                has_join = True
        is_thread = (
            isinstance(f, ast.Attribute) and f.attr == "Thread"
            and _dotted(f.value).split(".")[-1] == "threading"
        ) or (
            isinstance(f, ast.Name) and f.id == "Thread" and thread_imported
        )
        if is_thread:
            threads.append(n)
            if not any(k.arg == "daemon" for k in n.keywords):
                out.append(Finding(
                    "RP04", relpath, n.lineno,
                    "threading.Thread constructed without an explicit "
                    "daemon= — decide (and document) whether this thread "
                    "may outlive interpreter shutdown",
                ))
        is_simple = (
            isinstance(f, ast.Attribute) and f.attr == "SimpleQueue"
            and _dotted(f.value).split(".")[-1] in ("queue", "_queue")
        ) or (
            isinstance(f, ast.Name) and f.id == "SimpleQueue"
            and _imports_name(tree, "queue", "SimpleQueue")
        )
        if is_simple:
            # SimpleQueue takes no maxsize at all — it is unbounded by
            # construction, invisible to the maxsize heuristic below
            out.append(Finding(
                "RP04", relpath, n.lineno,
                "queue.SimpleQueue() is unbounded by construction (it "
                "accepts no maxsize) — a stalled consumer grows it "
                "without limit; use queue.Queue(maxsize=...) instead",
            ))
        is_queue = (
            isinstance(f, ast.Attribute) and f.attr in ("Queue", "LifoQueue")
            and _dotted(f.value).split(".")[-1] in ("queue", "_queue")
        ) or (
            isinstance(f, ast.Name) and f.id in ("Queue", "LifoQueue")
            and queue_imported
        )
        if is_queue:
            bound = None
            if n.args:
                bound = n.args[0]
            for k in n.keywords:
                if k.arg == "maxsize":
                    bound = k.value
            # Python treats ANY maxsize <= 0 as unbounded: catch the
            # literal 0 and the negated-literal (-1) spellings alike
            val = None
            if isinstance(bound, ast.Constant) and isinstance(
                bound.value, (int, float)
            ):
                val = bound.value
            elif (isinstance(bound, ast.UnaryOp)
                    and isinstance(bound.op, ast.USub)
                    and isinstance(bound.operand, ast.Constant)
                    and isinstance(bound.operand.value, (int, float))):
                val = -bound.operand.value
            if bound is None or (val is not None and val <= 0):
                out.append(Finding(
                    "RP04", relpath, n.lineno,
                    "unbounded queue.Queue() — a stalled consumer grows "
                    "it without limit; construct with a maxsize bound",
                ))
    if threads and not has_join:
        for n in threads:
            if n.lineno in rp08_covered:
                # RP08's flow-sensitive join check already covers this
                # thread (flagged or passed) — one bug, one report
                continue
            out.append(Finding(
                "RP04", relpath, n.lineno,
                "threading.Thread constructed but no .join( appears in "
                "this module — threads must be joined (bounded) on "
                "shutdown",
            ))
    return out


def _rule_rp05(tree: ast.Module, relpath: str) -> List[Finding]:
    out: List[Finding] = []
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call) or not isinstance(
            n.func, ast.Attribute
        ):
            continue
        base = _dotted(n.func.value)
        attr = n.func.attr
        if base in ("time", "_time") and attr == "time":
            out.append(Finding(
                "RP05", relpath, n.lineno,
                "time.time() in ops/ — wall clocks don't belong in "
                "kernel bodies; take timestamps at the call site or use "
                "perf_counter in instrumentation",
            ))
        elif base == "random":
            out.append(Finding(
                "RP05", relpath, n.lineno,
                f"global random.{attr}() in ops/ — RNG must be threaded "
                "explicitly (np.random.Generator / jax key)",
            ))
        elif base in ("np.random", "numpy.random") and (
            attr not in RNG_FACTORY_OK
        ):
            out.append(Finding(
                "RP05", relpath, n.lineno,
                f"legacy np.random.{attr}() in ops/ mutates hidden "
                "global state — pass an np.random.Generator instead",
            ))
    return out


def _rule_rp06(tree: ast.Module, relpath: str) -> List[Finding]:
    out: List[Finding] = []
    for n in ast.walk(tree):
        if not isinstance(n, ast.ExceptHandler):
            continue
        t = n.type
        broad = t is None or (
            isinstance(t, (ast.Name, ast.Attribute))
            and _dotted(t).split(".")[-1] in ("Exception", "BaseException")
        )
        if not broad:
            continue
        handled = False
        for stmt in n.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Raise):
                    handled = True
                elif isinstance(sub, ast.Call) and (
                    _callee(sub) in RP06_MITIGATORS
                ):
                    handled = True
        if not handled:
            out.append(Finding(
                "RP06", relpath, n.lineno,
                "broad except handler swallows the error silently — "
                "re-raise, emit a telemetry event/counter, or close the "
                "span as errored",
            ))
    return out


# -- engine ------------------------------------------------------------------


def lint_source(src: str, relpath: str, *,
                registry: Optional[EventRegistry] = None,
                index: Optional[PackageIndex] = None,
                tree: Optional[ast.Module] = None,
                degraded: Optional[Set[str]] = None) -> List[Finding]:
    """Lint one module's source.  ``relpath`` is the package-relative
    path ('/'-separated) the rule scoping keys on; tests lint fixture
    text under virtual relpaths to exercise module-scoped rules.
    ``index`` (built by ``lint_package``) enables RP09's cross-module
    call resolution; without it RP09 resolves same-file calls only.
    ``tree`` is an optional pre-parsed AST of ``src`` (``lint_package``
    passes the one it already built for the index, so targets parse
    once per run).  ``degraded`` is trace_report's parsed
    DEGRADED_EVENTS attr set for RP14's emit matching; without it any
    ``EVENTS.*`` emit satisfies a rung (the fixture path)."""
    relpath = relpath.replace(os.sep, "/")
    if tree is None:
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            return [Finding(
                "RP00", relpath, e.lineno or 1, f"syntax error: {e.msg}"
            )]
    allows, findings, pragmas = _scan_pragmas(src, relpath)
    parents = _parents(tree)
    emit_imported = _imports_name(tree, "telemetry", "emit")
    # rules actually evaluated for this file — a pragma naming only
    # rules that never ran here cannot be judged stale
    evaluated: Set[str] = {"RP01", "RP04", "RP08", "RP12"}
    findings += _rule_rp01(tree, relpath, parents, emit_imported)
    findings += [
        Finding("RP12", relpath, ln, msg)
        for ln, msg in flowrules.rule_rp12(tree)
    ]
    if registry is not None:
        evaluated.add("RP02")
    findings += _rule_rp02(tree, relpath, registry, emit_imported)
    if relpath in HOT_MODULES:
        evaluated.add("RP03")
        findings += _rule_rp03(tree, relpath)
    # RP08 runs before RP04 so its flow-checked threads can stand the
    # per-line no-join heuristic down (one bug, one report — ISSUE 12)
    rp08_out, rp08_covered = flowrules.rule_rp08(tree)
    findings += [
        Finding("RP08", relpath, ln, msg) for ln, msg in rp08_out
    ]
    findings += _rule_rp04(tree, relpath, rp08_covered)
    if relpath.startswith(DETERMINISM_PREFIXES):
        evaluated.add("RP05")
        findings += _rule_rp05(tree, relpath)
    if relpath in PIPELINE_MODULES:
        evaluated.add("RP06")
        findings += _rule_rp06(tree, relpath)
    if relpath in KERNEL_MODULES:
        evaluated.add("RP07")
        findings += [
            Finding("RP07", relpath, ln, msg)
            for ln, msg in flowrules.rule_rp07(
                tree, KERNEL_BUDGET_FNS[relpath]
            )
        ]
    if relpath in HOT_MODULES:
        evaluated.add("RP09")
        sup = {
            ln: set().union(*(p.rules for p in ps))
            for ln, ps in allows.items()
        }
        findings += [
            Finding("RP09", relpath, ln, msg)
            for ln, msg in flowrules.rule_rp09(
                tree, relpath, index=index, suppressed=sup
            )
        ]
    if relpath in CONCURRENCY_MODULES:
        evaluated.update(("RP10", "RP11"))
        findings += [
            Finding("RP10", relpath, ln, msg)
            for ln, msg in flowrules.rule_rp10(tree, relpath, index=index)
        ]
        findings += [
            Finding("RP11", relpath, ln, msg)
            for ln, msg in flowrules.rule_rp11(tree, relpath, index=index)
        ]
    if relpath in RP13_MODULES:
        evaluated.add("RP13")
        findings += [
            Finding("RP13", relpath, ln, msg)
            for ln, msg in flowrules.rule_rp13(tree)
        ]
    if relpath in RP14_MODULES:
        evaluated.add("RP14")
        findings += [
            Finding("RP14", relpath, ln, msg)
            for ln, msg in flowrules.rule_rp14(tree, degraded=degraded)
        ]
    for f in findings:
        if f.rule == "RP00" or f.severity != "error":
            continue  # pragma hygiene / info findings aren't suppressible
        for ln in (f.line, f.line - 1):
            for p in allows.get(ln, []):
                if f.rule in p.rules:
                    f.suppressed = True
                    f.reason = p.reason
                    p.matched = True
                    break
            if f.suppressed:
                break
    for p in pragmas:
        if p.matched or not p.rules <= evaluated:
            continue
        findings.append(Finding(
            "RP00", relpath, p.line,
            f"stale pragma: allow[{','.join(sorted(p.rules))}] "
            "suppresses no finding at this site — the violation it "
            "covered is gone; remove the pragma",
        ))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def package_root() -> str:
    """The installed ``randomprojection_tpu`` package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def iter_package_files(root: str) -> List[str]:
    rels: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d != "__pycache__"
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                rels.append(rel.replace(os.sep, "/"))
    return rels


def _read(path: str) -> str:
    """Tolerant read for OPTIONAL analysis inputs (the doc, the
    consumer text): missing files stand a rule down, never crash."""
    try:
        with open(path, encoding="utf-8") as f:
            return f.read()
    except OSError:
        return ""


def _read_strict(path: str) -> str:
    """Strict read for the lint TARGETS themselves: an unreadable file
    must abort the run (internal error, exit 2), not silently shrink it
    — a partial run reporting 'clean' is the exit-code bug ISSUE 11
    closes."""
    with open(path, encoding="utf-8") as f:
        return f.read()


def _build_index(
    sources: Sequence[Tuple[str, str]],
) -> Tuple[PackageIndex, Dict[str, ast.Module]]:
    """RP09's one-level call-resolution index over the lint targets
    (``(relpath, source)`` pairs): parsed trees plus each file's
    pragma-suppressed lines (a sync the owning file justified does not
    propagate to its callers).  Also returns the parsed trees so
    ``lint_package`` parses each target exactly once."""
    idx = PackageIndex()
    trees: Dict[str, ast.Module] = {}
    for rel, src in sources:
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue  # the per-file lint reports the syntax error
        trees[rel] = tree
        allows, _f, _p = _scan_pragmas(src, rel)
        sup = {
            ln: set().union(*(p.rules for p in ps))
            for ln, ps in allows.items()
        }
        idx.add(index_module(rel, tree, sup))
    return idx, trees


_POOL_STATE: dict = {}


def _pool_init(sources: Sequence[Tuple[str, str]],
               registry: Optional[EventRegistry],
               degraded: Optional[Set[str]]) -> None:
    """ProcessPool initializer: each worker builds the cross-module
    index once, then lints the rels it is handed."""
    index, trees = _build_index(sources)
    _POOL_STATE.update(
        sources=dict(sources), registry=registry, degraded=degraded,
        index=index, trees=trees,
    )


def _pool_lint(rel: str) -> List[Finding]:
    s = _POOL_STATE
    return lint_source(
        s["sources"][rel], rel, registry=s["registry"], index=s["index"],
        tree=s["trees"].get(rel), degraded=s["degraded"],
    )


def default_jobs() -> int:
    """Default lint parallelism: ``min(8, cpu)`` — the package is ~45
    files, so more workers than that just pay fork+reindex cost."""
    return min(8, os.cpu_count() or 1)


def lint_package(root: Optional[str] = None,
                 files: Optional[Sequence[str]] = None,
                 jobs: Optional[int] = None) -> dict:
    """Lint the package tree (or an explicit file list) and return the
    stable findings record the CLI serializes with ``--json``:
    ``{rplint, root, files, findings[], counts, suppressed,
    unresolvable_emits, wall_s, ok}`` — rule id / path / line /
    message / severity / pragma state per finding.  ``jobs`` > 1 fans
    the per-file passes out over a process pool (finding order stays
    deterministic: results are folded in file order, and each file's
    findings are sorted).  Raises on unreadable lint targets (the CLI
    maps that to exit code 2)."""
    t0 = time.monotonic()
    root = os.path.abspath(root or package_root())
    registry = load_event_registry(
        _read(os.path.join(root, TELEMETRY_MODULE.replace("/", os.sep)))
    )
    consumer = _read(
        os.path.join(root, TRACE_REPORT_MODULE.replace("/", os.sep))
    )
    degraded_attrs, degraded_line = load_degraded_events(consumer)
    degraded = degraded_attrs or None
    if files is None:
        rels = iter_package_files(root)
        paths = [(os.path.join(root, r.replace("/", os.sep)), r)
                 for r in rels]
        run_drift = True
    else:
        paths = []
        for p in files:
            ap = os.path.abspath(p)
            rel = os.path.relpath(ap, root)
            if rel.startswith(".."):
                rel = os.path.basename(ap)
            paths.append((ap, rel.replace(os.sep, "/")))
        run_drift = False
    sources = [(rel, _read_strict(abspath)) for abspath, rel in paths]
    findings: List[Finding] = []
    njobs = default_jobs() if jobs is None else max(1, jobs)
    if njobs > 1 and len(sources) > 1:
        with concurrent.futures.ProcessPoolExecutor(
            max_workers=min(njobs, len(sources)),
            initializer=_pool_init,
            initargs=(sources, registry, degraded),
        ) as pool:
            # map() yields in submission order: per-file findings fold
            # back deterministically no matter which worker ran them
            for batch in pool.map(_pool_lint, [rel for rel, _ in sources]):
                findings += batch
    else:
        index, trees = _build_index(sources)
        for rel, src in sources:
            findings += lint_source(src, rel, registry=registry,
                                    index=index, tree=trees.get(rel),
                                    degraded=degraded)
    doc_path = os.path.join(os.path.dirname(root), ARCHITECTURE_DOC)
    if run_drift and registry is not None and os.path.exists(doc_path):
        # the drift check is a repo-time gate: an installed package
        # ships without docs/ (pyproject packages only the code), and
        # flagging every documented-only event there would fail a
        # correct tree.  The repo checkout always has the doc (and the
        # tier-1 suite asserts the check runs there).
        findings += check_registry_drift(registry, consumer, _read(doc_path))
    if run_drift and registry is not None:
        # RP14 reverse leg needs the whole package in view (like the
        # registry drift check): a degraded event nobody emits
        findings += check_degraded_drift(
            degraded_attrs, degraded_line, registry, sources
        )
    active = [f for f in findings
              if not f.suppressed and f.severity == "error"]
    counts: Dict[str, int] = {}
    for f in active:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "rplint": 4,
        "root": root,
        "files": len(paths),
        "findings": [f.to_dict() for f in findings],
        "counts": dict(sorted(counts.items())),
        "suppressed": len([f for f in findings if f.suppressed]),
        "unresolvable_emits": len(
            [f for f in findings if f.severity == "info"]
        ),
        "wall_s": round(time.monotonic() - t0, 3),
        "ok": not active,
    }


_SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"


def to_sarif(report: dict) -> dict:
    """Render a ``lint_package`` record as a SARIF 2.1.0 log, so CI
    runners and editors can annotate findings inline.  Mapping:
    ``severity`` ``error`` → level ``error``, ``info`` → ``note``;
    pragma-suppressed findings carry an ``inSource`` suppression with
    the pragma's reason as justification (SARIF viewers hide them by
    default but keep the audit trail)."""
    results = []
    for f in report["findings"]:
        res = {
            "ruleId": f["rule"],
            "level": "note" if f["severity"] == "info" else "error",
            "message": {"text": f["message"]},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f["path"]},
                    "region": {"startLine": max(1, int(f["line"]))},
                },
            }],
        }
        if f["suppressed"]:
            res["suppressions"] = [{
                "kind": "inSource",
                "justification": f["reason"],
            }]
        results.append(res)
    return {
        "version": "2.1.0",
        "$schema": _SARIF_SCHEMA,
        "runs": [{
            "tool": {"driver": {
                "name": "rplint",
                "version": str(report["rplint"]),
                "rules": [
                    {"id": rid, "shortDescription": {"text": RULES[rid]}}
                    for rid in sorted(RULES)
                ],
            }},
            "results": results,
        }],
    }


def diff_baseline(report: dict, baseline: dict) -> dict:
    """Diff a fresh lint record against a prior ``--json`` record.
    Findings match on ``(rule, path, message)`` — NOT line — so code
    motion above a baselined finding never re-flags it.  Returns
    ``{matched, new[], stale, ok}``: ``new`` are the findings to fail
    on, ``stale`` counts baseline entries the tree no longer produces
    (time to re-tighten the baseline)."""

    def active(fs) -> List[dict]:
        return [
            f for f in fs
            if not f.get("suppressed")
            and f.get("severity", "error") == "error"
        ]

    budget: Dict[Tuple[str, str, str], int] = {}
    for f in active(baseline.get("findings", [])):
        k = (f["rule"], f["path"], f["message"])
        budget[k] = budget.get(k, 0) + 1
    matched = 0
    new: List[dict] = []
    for f in active(report["findings"]):
        k = (f["rule"], f["path"], f["message"])
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            matched += 1
        else:
            new.append(f)
    stale = sum(v for v in budget.values() if v > 0)
    return {"matched": matched, "new": new, "stale": stale,
            "ok": not new}


def _fsync_dir(path: str) -> None:
    """Best-effort parent-directory fsync after an ``os.replace`` (the
    rename itself can be lost on crash without it); tolerant because
    some filesystems refuse O_RDONLY directory opens."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _write_json_atomic(path: str, obj: dict) -> None:
    """The commit idiom RP13 enforces, practiced by the linter's own
    artifact writers: tmp sibling → flush → fsync → ``os.replace`` →
    directory fsync."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(os.path.abspath(path)))


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI face (``cli lint`` delegates here).  Exit codes — the
    contract ``make lint-ci`` and the driver rely on: **0** no
    unsuppressed finding (none outside the baseline, when one is
    given), **1** findings, **2** internal error (analysis crash,
    unreadable target, malformed baseline) — a partial run never
    reports success."""
    ap = argparse.ArgumentParser(
        prog="rplint",
        description="AST-based invariant checks for this repo's "
                    "pipeline contracts (rules RP01-RP14; see "
                    "randomprojection_tpu/analysis/rplint.py)",
    )
    ap.add_argument("paths", nargs="*",
                    help="files to lint (default: the whole installed "
                         "package, plus the registry drift check)")
    ap.add_argument("--json", action="store_true",
                    help="emit the stable findings record as one JSON "
                         "object (includes suppressed and informational "
                         "findings, marked)")
    ap.add_argument("--root", default=None,
                    help="package root to resolve rule scoping against "
                         "(default: the installed package)")
    ap.add_argument("--baseline", default=None, metavar="JSON",
                    help="a prior `lint --json` record: fail only on "
                         "findings NOT in it (matched on rule+path+"
                         "message, so line drift never re-flags) — lets "
                         "strict rules land without blocking unrelated "
                         "work")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the --baseline file in place with the "
                         "fresh lint record: stale entries are pruned, "
                         "current findings become the accepted baseline "
                         "(exit 0) — the workflow for accepting intended "
                         "new findings instead of hand-editing JSON")
    ap.add_argument("--sarif", default=None, metavar="PATH",
                    help="also write the findings as a SARIF 2.1.0 log "
                         "to PATH, so CI and editors can annotate them "
                         "inline")
    ap.add_argument("--jobs", type=int, default=None, metavar="N",
                    help="fan the per-file rule passes out over N "
                         "processes (default: min(8, cpu)); finding "
                         "order stays deterministic, 1 disables the "
                         "pool")
    args = ap.parse_args(argv)
    updated: Optional[dict] = None
    try:
        if args.update_baseline and args.baseline is None:
            raise ValueError("--update-baseline requires --baseline PATH")
        report = lint_package(args.root, files=args.paths or None,
                              jobs=args.jobs)
        if args.baseline is not None:
            if args.update_baseline and not os.path.exists(args.baseline):
                base: dict = {"findings": []}  # first write starts empty
            else:
                with open(args.baseline, encoding="utf-8") as f:
                    base = json.load(f)
            if not isinstance(base, dict) or not isinstance(
                base.get("findings"), list
            ):
                raise ValueError(
                    f"{args.baseline} is not a lint --json record "
                    "(no findings list)"
                )
            report["baseline"] = diff_baseline(report, base)
            if args.update_baseline:
                fresh = {k: v for k, v in report.items() if k != "baseline"}
                _write_json_atomic(args.baseline, fresh)
                updated = {
                    "path": args.baseline,
                    "accepted_new": len(report["baseline"]["new"]),
                    "pruned_stale": report["baseline"]["stale"],
                }
                report["baseline_updated"] = updated
        if args.sarif is not None:
            _write_json_atomic(args.sarif, to_sarif(report))
    except Exception as e:
        # never exit 0 off a crashed/partial run (ISSUE 11 satellite)
        print(f"rplint: internal error: {e}", file=sys.stderr)
        return 2
    ok = report["baseline"]["ok"] if "baseline" in report else report["ok"]
    if updated is not None:
        ok = True  # the update IS the acceptance of the new findings
    if args.json:
        print(json.dumps(report))
        return 0 if ok else 1
    if "baseline" in report:
        shown = [Finding(**f) for f in report["baseline"]["new"]]
    else:
        shown = [
            Finding(**f) for f in report["findings"]
            if not f["suppressed"] and f["severity"] == "error"
        ]
    for f in shown:
        print(f.render())
    status = "clean" if ok else "%d finding(s)" % len(shown)
    extras = [
        f"{report['files']} file(s)",
        f"{report['suppressed']} suppressed finding(s)",
    ]
    if report["unresolvable_emits"]:
        extras.append(
            f"{report['unresolvable_emits']} unresolvable emit name(s)"
        )
    if "baseline" in report:
        b = report["baseline"]
        extras.append(
            f"baseline: {b['matched']} matched, {b['stale']} stale"
        )
    if updated is not None:
        status = "baseline updated"
        extras.append(
            f"{updated['path']} rewritten ({updated['accepted_new']} new "
            f"finding(s) accepted, {updated['pruned_stale']} stale "
            "entr(ies) pruned)"
        )
    print(f"rplint: {status} — " + ", ".join(extras))
    return 0 if ok else 1


if __name__ == "__main__":  # pragma: no cover — python -m convenience
    raise SystemExit(main())
