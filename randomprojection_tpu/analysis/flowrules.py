"""Flow-sensitive rplint rules RP07-RP14 (ISSUE 11, grown by ISSUE 12
and ISSUE 20).

Built on the ``cfg`` substrate.  Each rule function returns plain
``(line, message)`` pairs; ``rplint.py`` wraps them into findings,
applies pragma suppression, and owns scoping (which modules each rule
runs on).

- **RP07 DMA discipline** — inside Pallas kernel bodies: every
  ``make_async_copy`` start must reach a matching ``.wait()`` on all
  paths (CFG query, pl.when/fori_loop splicing included); revolving
  slot indices must stay within the declared slot count (the affine
  offset algebra: a start at ``base+c`` matched by a wait at ``base+w``
  re-targets its slot after ``K`` iterations, so ``0 <= c-w < K`` or
  the DMA engine overwrites an in-flight buffer); the revolving modulus
  must equal a declared slot count; and the module's VMEM budget
  function must charge every VMEM operand the kernels actually allocate
  (allocation dims re-derived from the AST, cross-checked against the
  budget function's name set).
- **RP08 thread/queue protocol** — every thread started in a function
  is joined on every path out of it (early returns, explicit raises and
  try/finally modeled); threads stored on ``self`` are joined by the
  class, reachable from its close-like method; a shutdown sentinel is
  enqueued unconditionally from ``close()`` (only closed-flag guards
  may skip it); and no cursor commit dominates its batch's ``yield``
  (the ack-after-yield contract).
- **RP09 interprocedural host-sync** — a host sync hidden one call away
  from a hot loop (the exact bug class r9 fixed by hand in
  ``query_topk``): loop-body calls resolve one level through the
  package index, and a callee containing an unsuppressed
  ``np.asarray`` / ``.block_until_ready`` / ``jax.device_get`` /
  ``float()``-on-expression is reported at the call site.
- **RP10 shared-state races** (ISSUE 12) — thread *roles* are derived
  from RP08's thread discovery (each ``Thread(target=...)`` entry point
  plus the constructing "main" role); per-role ``self.``-attribute
  read/write sets are computed transitively one call level at a time
  through the package index (lock context folding through each call
  site), and any attribute with a cross-role write/write or read/write
  pair is flagged unless every access path holds the *same* lock
  (``with self._lock:`` regions on the CFG), the value crosses roles
  only through the attribute's own method calls (the ``queue.Queue``
  put/get handoff — the object's methods own their synchronization), or
  every write dominates every thread ``.start()`` call (init-only
  state, via the dominator query).  Classes (and module globals) with
  no thread roles still get the lock-*consistency* leg: state touched
  under a lock somewhere must hold that lock on every post-init access.
- **RP11 lock-order deadlock lint** (ISSUE 12) — the lock-acquisition
  ordering graph (nested ``with``-lock regions, including one call
  level through the package index) must be acyclic, and no blocking
  call (``queue.put`` / ``.join`` / ``future.result``) may run while a
  lock is held.
- **RP12 resource lifecycle** (ISSUE 20) — RP01's span-balance engine
  generalized into a paired-acquire/release protocol checker: a
  telemetry subscription, ``MetricsServer``, ``HealthEngine``,
  ``open()`` handle, ``np.memmap``, or ``mkdtemp`` temp dir bound to a
  local name must be released on every path out of the acquiring
  function (``exit_reachable_without`` on the CFG, guard facts
  synthesized for ``if x is not None:``-style release guards), and —
  the r17 bug shape — no second acquire may run outside an
  exception-protected region while an earlier handle is still
  unreleased.  Escaping handles (returned, yielded, stored on an
  object, packed into a container, passed to an owning callee, or
  captured by a nested function) are exempt, like RP01/RP08.
- **RP13 durable-commit discipline** (ISSUE 20) — every ``os.replace``
  landing a snapshot/artifact must be preceded by flush+fsync on all
  paths, a raw ``open(final_path, "w")`` write (no tmp→replace) is a
  finding, the manifest replace must be dominated by every chunk/spill
  write in the same commit (manifest-committed-LAST, via the dominator
  query, with conditional writes promoted to their enclosing
  ``if``/loop headers), and a directory fsync must be reachable after
  the replace (helper functions whose callers fsync the directory are
  exempt).
- **RP14 degraded-path audit** (ISSUE 20) — every fallback rung (a
  broad ``except`` whose body is more than a bare re-raise) must
  reachably emit a ``DEGRADED_EVENTS``-consumed telemetry event or
  call a degraded-rung recorder; classified-failure rungs (those that
  re-raise unrecognized errors) must memoize the degraded key (the r6
  ``_NO_*_KEYS`` convention — the ``.add()`` may sit after the ladder
  loop, so reachability is CFG-checked, not lexical); and a
  ``counter_inc("*fallback*")`` without an adjacent degraded-event
  emit in the same block is flagged.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from randomprojection_tpu.analysis.cfg import (
    CFG,
    ModuleInfo,
    PackageIndex,
    build_cfg,
    dominators,
    dotted as _dotted,
    exit_reachable_without,
    index_module,
    lock_regions,
    node_reachable_without,
    parents_map as _parents_map,
    shallow_walk,
    thread_entries,
)

__all__ = [
    "host_sync_what",
    "rule_rp07",
    "rule_rp08",
    "rule_rp09",
    "rule_rp10",
    "rule_rp11",
    "rule_rp12",
    "rule_rp13",
    "rule_rp14",
]

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


# -- the host-sync detector (shared by RP03 and RP09) ------------------------

_HOST_SYNCS = {"asarray": ("np", "numpy"), "device_get": ("jax",)}


def host_sync_what(call: ast.Call) -> Optional[str]:
    """Human-readable description of the host sync this call performs,
    or None.  The single definition both the syntactic rule (RP03) and
    the interprocedural rule (RP09) share, so the two can never drift
    on what counts as a sync."""
    f = call.func
    if isinstance(f, ast.Attribute):
        bases = _HOST_SYNCS.get(f.attr)
        if bases and isinstance(f.value, ast.Name) and f.value.id in bases:
            return f"{f.value.id}.{f.attr}"
        if f.attr == "block_until_ready":
            return ".block_until_ready()"
    elif isinstance(f, ast.Name) and f.id == "float" and call.args:
        # float(scalar_name) is fine; float(<expression>) on an array
        # element/reduction forces a device sync
        if not isinstance(call.args[0], (ast.Name, ast.Constant)):
            return "float() on an expression"
    return None


# -- RP07: DMA discipline ----------------------------------------------------


def _is_async_copy(call: ast.Call) -> bool:
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else ""
    )
    return name == "make_async_copy"


def _slot_index(arg: ast.AST) -> Tuple[Optional[str], Optional[ast.AST]]:
    """``buf.at[IDX]`` -> (buffer name, IDX ast); plain names -> (name,
    None); anything else -> (None, None)."""
    if isinstance(arg, ast.Subscript) and isinstance(
        arg.value, ast.Attribute
    ) and arg.value.attr == "at" and isinstance(arg.value.value, ast.Name):
        return arg.value.value.id, arg.slice
    if isinstance(arg, ast.Name):
        return arg.id, None
    return None, None


def _mod_k(idx: Optional[ast.AST]) -> Tuple[Optional[ast.AST], Optional[int]]:
    """``E % K`` -> (E, K) for constant K; otherwise (idx, None)."""
    if isinstance(idx, ast.BinOp) and isinstance(idx.op, ast.Mod) and \
            isinstance(idx.right, ast.Constant) and isinstance(
                idx.right.value, int):
        return idx.left, idx.right.value
    return idx, None


def _affine(expr: Optional[ast.AST]) -> Tuple[Optional[str], Optional[int]]:
    """Normalize a slot-phase expression to (base name dump, constant
    offset): ``t`` -> (t, 0), ``t + 1`` -> (t, 1), ``3`` -> (None, 3);
    anything else -> (None, None)."""
    if expr is None:
        return None, None
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        return None, expr.value
    if isinstance(expr, ast.Name):
        return expr.id, 0
    if isinstance(expr, ast.BinOp) and isinstance(
        expr.op, (ast.Add, ast.Sub)
    ):
        sign = 1 if isinstance(expr.op, ast.Add) else -1
        if isinstance(expr.left, ast.Name) and isinstance(
            expr.right, ast.Constant
        ) and isinstance(expr.right.value, int):
            return expr.left.id, sign * expr.right.value
        if isinstance(expr.right, ast.Name) and isinstance(
            expr.left, ast.Constant
        ) and isinstance(expr.left.value, int) and sign == 1:
            return expr.right.id, expr.left.value
    return None, None


class _CopyFamily:
    """One DMA copy lineage inside a kernel: a helper def returning
    ``make_async_copy`` (revolving slots keyed by the helper's
    argument) or a named descriptor variable (single slot)."""

    def __init__(self, name: str, line: int,
                 slot_k: Optional[int], sem_k: Optional[int],
                 idx_mismatch: bool):
        self.name = name
        self.line = line
        self.slot_k = slot_k      # revolving modulus of the buffer index
        self.sem_k = sem_k        # revolving modulus of the semaphore index
        self.idx_mismatch = idx_mismatch
        self.starts: List[Tuple[int, Optional[str], Optional[int], int]] = []
        self.waits: List[Tuple[int, Optional[str], Optional[int], int]] = []


def _collect_families(func: ast.AST) -> Dict[str, _CopyFamily]:
    fams: Dict[str, _CopyFamily] = {}
    for n in ast.walk(func):
        if isinstance(n, _FUNC_NODES) and n is not func:
            for r in ast.walk(n):
                if isinstance(r, ast.Return) and isinstance(
                    r.value, ast.Call
                ) and _is_async_copy(r.value):
                    call = r.value
                    dst = call.args[1] if len(call.args) > 1 else None
                    sem = call.args[2] if len(call.args) > 2 else None
                    _, dst_idx = _slot_index(dst) if dst is not None else (
                        None, None)
                    _, sem_idx = _slot_index(sem) if sem is not None else (
                        None, None)
                    _dst_expr, dst_k = _mod_k(dst_idx)
                    _sem_expr, sem_k = _mod_k(sem_idx)
                    mism = (
                        dst_idx is not None and sem_idx is not None
                        and ast.dump(dst_idx) != ast.dump(sem_idx)
                    )
                    fams[n.name] = _CopyFamily(
                        n.name, n.lineno, dst_k, sem_k, mism
                    )
    return fams


def _vmem_allocs(
    tree: ast.Module,
) -> List[Tuple[int, List[str], Optional[int]]]:
    """Every ``pltpu.VMEM((dims...), dtype)`` allocation in the module:
    (line, symbolic dim names, constant LEADING dim or None).  Only the
    leading position can be a revolving slot count — a constant in a
    trailing position is a tile width, not a slot declaration."""
    out = []
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else ""
        )
        if name != "VMEM" or not n.args:
            continue
        dims = n.args[0]
        if not isinstance(dims, ast.Tuple) or not dims.elts:
            continue
        syms = [e.id for e in dims.elts if isinstance(e, ast.Name)]
        lead = dims.elts[0]
        lead_k = (
            lead.value
            if isinstance(lead, ast.Constant) and isinstance(lead.value, int)
            else None
        )
        out.append((n.lineno, syms, lead_k))
    return out


def _dma_sem_shapes(tree: ast.Module) -> Set[int]:
    """Declared DMA semaphore slot counts:
    ``pltpu.SemaphoreType.DMA((K,))``."""
    out: Set[int] = set()
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        if not (isinstance(n.func, ast.Attribute)
                and n.func.attr == "DMA"):
            continue
        if n.args and isinstance(n.args[0], ast.Tuple):
            for e in n.args[0].elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.add(e.value)
    return out


def _budget_names(budget: ast.FunctionDef) -> Set[str]:
    names = {a.arg for a in budget.args.args}
    names |= {a.arg for a in budget.args.kwonlyargs}
    for n in ast.walk(budget):
        if isinstance(n, ast.Name):
            names.add(n.id)
    return names


def rule_rp07(tree: ast.Module, budget_fn: str) -> List[Tuple[int, str]]:
    """DMA discipline over every kernel function in a module (see the
    module docstring).  ``budget_fn`` names the module's VMEM budget
    function for the allocation cross-check."""
    out: List[Tuple[int, str]] = []

    # -- budget cross-check (module-wide) --
    budget = next(
        (n for n in tree.body
         if isinstance(n, _FUNC_NODES) and n.name == budget_fn), None
    )
    allocs = _vmem_allocs(tree)
    if allocs and budget is None:
        out.append((
            allocs[0][0],
            f"module allocates VMEM scratch but has no {budget_fn}() "
            "budget function to charge it against",
        ))
    elif budget is not None:
        names = _budget_names(budget)
        for line, syms, _lead in allocs:
            missing = sorted(s for s in syms if s not in names)
            if missing:
                out.append((
                    line,
                    "VMEM allocation dimension(s) "
                    f"{', '.join(missing)} are not charged by the "
                    f"{budget_fn}() budget — every VMEM operand the "
                    "kernel allocates must appear in the budget "
                    "expression",
                ))

    # leading constant dims of VMEM allocs (revolving slot counts live
    # in the first position: VMEM((2, blk, cb), ...))
    vmem_leads: Set[int] = {
        lead for _, _syms, lead in allocs if lead is not None
    }
    dma_shapes = _dma_sem_shapes(tree)

    # -- per-kernel flow checks --
    for func in tree.body:
        if not isinstance(func, _FUNC_NODES):
            continue
        if not any(isinstance(n, ast.Call) and _is_async_copy(n)
                   for n in ast.walk(func)):
            continue
        fams = _collect_families(func)
        cfg = build_cfg(func, pallas=True)

        # named single-slot descriptors: x = pltpu.make_async_copy(...)
        descriptors: Set[str] = set()
        for node in cfg.nodes:
            for sub in shallow_walk(node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name) \
                        and isinstance(sub.value, ast.Call) \
                        and _is_async_copy(sub.value):
                    name = sub.targets[0].id
                    if name not in fams:
                        fams[name] = _CopyFamily(
                            name, sub.lineno, None, None, False
                        )
                    descriptors.add(name)

        # events
        for node in cfg.nodes:
            for sub in shallow_walk(node):
                if not (isinstance(sub, ast.Call) and isinstance(
                        sub.func, ast.Attribute)
                        and sub.func.attr in ("start", "wait")):
                    continue
                recv = sub.func.value
                fam = None
                phase: Tuple[Optional[str], Optional[int]] = (None, None)
                if isinstance(recv, ast.Call) and isinstance(
                    recv.func, ast.Name
                ) and recv.func.id in fams:
                    fam = fams[recv.func.id]
                    arg = recv.args[0] if recv.args else None
                    phase = _affine(arg)
                elif isinstance(recv, ast.Name) and recv.id in descriptors:
                    fam = fams[recv.id]
                elif isinstance(recv, ast.Call) and _is_async_copy(recv):
                    # inline form: make_async_copy(...).start()/.wait()
                    # with no helper and no bound name.  Family keyed by
                    # the targeted buffer so a reconstructed-descriptor
                    # wait (same buffer) still matches its start.
                    dst = recv.args[1] if len(recv.args) > 1 else None
                    buf_name, dst_idx = (
                        _slot_index(dst) if dst is not None
                        else (None, None)
                    )
                    expr, k = _mod_k(dst_idx)
                    key = f"make_async_copy->{buf_name or '<dynamic>'}"
                    fam = fams.get(key)
                    if fam is None:
                        fam = fams[key] = _CopyFamily(
                            key, sub.lineno, k, None, False
                        )
                    phase = _affine(expr)
                if fam is None:
                    continue
                ev = (node.idx, phase[0], phase[1], sub.lineno)
                (fam.starts if sub.func.attr == "start"
                 else fam.waits).append(ev)

        for fam in fams.values():
            if not fam.starts:
                continue
            if fam.idx_mismatch:
                out.append((
                    fam.line,
                    f"{fam.name}: buffer and DMA semaphore revolve on "
                    "different index expressions — copy and completion "
                    "would track different slots",
                ))
            if fam.slot_k is not None and (
                fam.slot_k not in vmem_leads or fam.slot_k not in dma_shapes
            ):
                out.append((
                    fam.line,
                    f"{fam.name}: revolving slot modulus % {fam.slot_k} "
                    "does not match a declared slot count (VMEM leading "
                    f"dims {sorted(vmem_leads) or 'none'}, DMA semaphore "
                    f"shapes {sorted(dma_shapes) or 'none'})",
                ))
            if not fam.waits:
                out.append((
                    fam.starts[0][3],
                    f"{fam.name}: make_async_copy started but never "
                    "waited in this kernel — the DMA completes into a "
                    "buffer nothing synchronizes on",
                ))
                continue
            wait_nodes = {w[0] for w in fam.waits}
            for node_idx, _base, _off, line in fam.starts:
                if exit_reachable_without(cfg, node_idx, wait_nodes):
                    out.append((
                        line,
                        f"{fam.name}: this start() can reach the kernel "
                        "exit without a matching .wait() on some path — "
                        "wait unconditionally (or under the same "
                        "predicate as the start)",
                    ))
            # single-slot descriptors: a re-start before the wait
            # overwrites an in-flight transfer
            if fam.slot_k is None and len(fam.starts) >= 1:
                start_nodes = {s[0] for s in fam.starts}
                for node_idx, _b, _o, line in fam.starts:
                    others = start_nodes  # incl. itself via the back edge
                    if node_reachable_without(cfg, node_idx, others,
                                              wait_nodes):
                        out.append((
                            line,
                            f"{fam.name}: the copy can be re-started "
                            "before its wait() (loop back-edge or "
                            "sibling start) — a single-slot descriptor "
                            "must complete before it is re-targeted",
                        ))
            # affine revolving-slot algebra
            if fam.slot_k is not None:
                K = fam.slot_k
                loop_starts = [(b, c, ln) for _n, b, c, ln in fam.starts
                               if b is not None]
                prolog_starts = [(c, ln) for _n, b, c, ln in fam.starts
                                 if b is None and c is not None]
                loop_waits = [(b, c) for _n, b, c, _ln in fam.waits
                              if b is not None]
                wait_offs = {w for _b, w in loop_waits}
                for base, c, line in loop_starts:
                    offs = {w for b, w in loop_waits if b == base}
                    if not offs:
                        continue  # different induction base: no algebra
                    if not any(0 <= c - w < K for w in offs):
                        if any(c - w >= K for w in offs):
                            out.append((
                                line,
                                f"{fam.name}: start at phase +{c} is "
                                f"waited {min(c - w for w in offs)} "
                                f"iterations later but only {K} slots "
                                "revolve — the slot is re-targeted "
                                "before its wait",
                            ))
                        else:
                            out.append((
                                line,
                                f"{fam.name}: start at phase +{c} has "
                                "no wait within its slot window "
                                f"(wait phases {sorted(offs)}, {K} "
                                "slots)",
                            ))
                for c, line in prolog_starts:
                    # warm-up start at slot c is waited by wait(t+w) at
                    # iteration c-w; legal while 0 <= c-w < K — a
                    # multi-deep warm-up (slots 0..K-2) is correct, its
                    # later slots are simply waited on later iterations
                    if wait_offs and not any(
                        0 <= c - w < K for w in wait_offs
                    ):
                        out.append((
                            line,
                            f"{fam.name}: warm-up start at slot "
                            f"{c % K} is not waited within its slot "
                            f"window (wait phases {sorted(wait_offs)}, "
                            f"{K} slots) — the slot is re-targeted "
                            "before any wait reaches it",
                        ))
                seen_mod: Dict[int, int] = {}
                for base, c, line in loop_starts:
                    prev = seen_mod.get(c % K)
                    if prev is not None and prev != c:
                        out.append((
                            line,
                            f"{fam.name}: two starts per iteration "
                            f"target the same slot (phases +{prev} and "
                            f"+{c} with {K} slots)",
                        ))
                    seen_mod.setdefault(c % K, c)
    return out


# -- RP08: thread/queue protocol ---------------------------------------------


def _is_thread_call(call: ast.Call, thread_imported: bool) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread":
        return _dotted(f.value).split(".")[-1] == "threading"
    return isinstance(f, ast.Name) and f.id == "Thread" and thread_imported


def _contains_thread_call(node: ast.AST, thread_imported: bool) -> bool:
    return any(
        isinstance(n, ast.Call) and _is_thread_call(n, thread_imported)
        for n in ast.walk(node)
    )


def _scopes(tree: ast.Module) -> List[ast.AST]:
    return [n for n in ast.walk(tree) if isinstance(n, _FUNC_NODES)]


def _name_escapes_scope(func: ast.AST, name: str) -> bool:
    """The thread (or thread collection) bound to ``name`` leaves this
    function — returned/yielded, stored on an object, or passed to a
    call other than its own start/join — so join responsibility
    escapes with it."""
    for n in ast.walk(func):
        if isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)):
            v = getattr(n, "value", None)
            if v is not None and any(
                isinstance(x, ast.Name) and x.id == name
                for x in ast.walk(v)
            ):
                return True
        elif isinstance(n, ast.Assign):
            if any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in n.targets) and any(
                isinstance(x, ast.Name) and x.id == name
                for x in ast.walk(n.value)
            ):
                return True
        elif isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Attribute) and f.attr in (
                "start", "join", "is_alive", "append",
            ):
                continue
            for a in list(n.args) + [k.value for k in n.keywords]:
                if any(isinstance(x, ast.Name) and x.id == name
                       for x in ast.walk(a)):
                    return True
    return False


def _thread_call_lines(node: ast.AST, thread_imported: bool) -> Set[int]:
    """Linenos of every ``Thread(...)`` construction inside ``node`` —
    the lines RP04's per-line findings anchor to, so RP08 coverage can
    be matched back for the one-bug-one-report dedupe."""
    return {
        n.lineno
        for n in ast.walk(node)
        if isinstance(n, ast.Call) and _is_thread_call(n, thread_imported)
    }


def _rp08_function(func: ast.AST, thread_imported: bool,
                   out: List[Tuple[int, str]],
                   covered: Set[int]) -> None:
    cfg = build_cfg(func)

    # thread variables and collections (name -> contents for closure);
    # cons_lines: thread name -> Thread() construction linenos
    threads: Set[str] = set()
    contents: Dict[str, Set[str]] = {}
    cons_lines: Dict[str, Set[int]] = {}
    for node in cfg.nodes:
        for sub in shallow_walk(node):
            # append-built pools: pool.append(t) makes pool a thread
            # collection containing t (the canonical accumulate-then-
            # join-in-finally idiom)
            if isinstance(sub, ast.Call) and isinstance(
                sub.func, ast.Attribute
            ) and sub.func.attr == "append" and isinstance(
                sub.func.value, ast.Name
            ) and sub.args and isinstance(sub.args[0], ast.Name) and \
                    sub.args[0].id in threads:
                coll = sub.func.value.id
                threads.add(coll)
                contents.setdefault(coll, set()).add(sub.args[0].id)
                continue
            if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Name)):
                continue
            tgt = sub.targets[0].id
            v = sub.value
            if isinstance(v, ast.Call) and _is_thread_call(
                v, thread_imported
            ):
                threads.add(tgt)
                cons_lines.setdefault(tgt, set()).add(v.lineno)
            elif isinstance(v, (ast.ListComp, ast.GeneratorExp)) and \
                    _contains_thread_call(v, thread_imported):
                threads.add(tgt)
                cons_lines.setdefault(tgt, set()).update(
                    _thread_call_lines(v, thread_imported)
                )
            elif isinstance(v, (ast.Tuple, ast.List)):
                inner: Set[str] = set()
                for e in v.elts:
                    if isinstance(e, ast.Starred) and isinstance(
                        e.value, ast.Name
                    ):
                        inner.add(e.value.id)
                    elif isinstance(e, ast.Name):
                        inner.add(e.id)
                if inner & threads or any(i in contents for i in inner):
                    threads.add(tgt)
                    contents[tgt] = inner
    if not threads:
        return

    def covers(join_target: str) -> Set[str]:
        seen = {join_target}
        stack = [join_target]
        while stack:
            t = stack.pop()
            for c in contents.get(t, ()):
                if c not in seen:
                    seen.add(c)
                    stack.append(c)
        return seen

    # events: direct x.start()/x.join(), and for-loops iterating a
    # thread collection whose body starts/joins the loop variable (the
    # event is the loop header: a zero-trip loop means zero threads, so
    # the header IS the collection-wide event)
    starts: List[Tuple[int, str, int]] = []   # (node, target, line)
    joins: List[Tuple[int, str]] = []         # (node, target)
    for node in cfg.nodes:
        stmt = node.stmt
        if node.kind == "loop" and isinstance(stmt, ast.For) and \
                isinstance(stmt.iter, ast.Name) and isinstance(
                    stmt.target, ast.Name) and stmt.iter.id in threads:
            lv, coll = stmt.target.id, stmt.iter.id
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call) and isinstance(
                    sub.func, ast.Attribute
                ) and isinstance(sub.func.value, ast.Name) and \
                        sub.func.value.id == lv:
                    if sub.func.attr == "start":
                        starts.append((node.idx, coll, stmt.lineno))
                    elif sub.func.attr == "join":
                        joins.append((node.idx, coll))
            continue
        for sub in shallow_walk(node):
            if isinstance(sub, ast.Call) and isinstance(
                sub.func, ast.Attribute
            ) and isinstance(sub.func.value, ast.Name) and \
                    sub.func.value.id in threads:
                if sub.func.attr == "start":
                    starts.append((node.idx, sub.func.value.id, sub.lineno))
                elif sub.func.attr == "join":
                    joins.append((node.idx, sub.func.value.id))

    for node_idx, target, line in starts:
        if _name_escapes_scope(func, target):
            continue  # ownership (and join duty) left this function
        # this thread's join protocol is flow-checked here — RP04's
        # per-line no-join heuristic would be a duplicate report
        for name in covers(target):
            covered.update(cons_lines.get(name, ()))
        join_nodes = {n for n, jt in joins if target in covers(jt)}
        if not join_nodes:
            out.append((
                line,
                f"thread {target!r} is started but never joined in "
                "this function (and does not escape it) — join it on "
                "the shutdown path, bounded",
            ))
        elif exit_reachable_without(cfg, node_idx, join_nodes):
            out.append((
                line,
                f"thread {target!r} is not joined on every path from "
                "its start() to the function exit (an early return, "
                "break or raise path skips the join) — join in a "
                "finally",
            ))


_CLOSE_METHODS = ("close", "shutdown", "stop", "__exit__", "__del__")
_CLOSED_GUARD_MARKERS = ("closed", "stop", "shutdown", "done")


def _rp08_class(cls: ast.ClassDef, thread_imported: bool,
                out: List[Tuple[int, str]],
                covered: Set[int]) -> None:
    # attribute-held threads: self.X = threading.Thread(...)
    attr_threads: Dict[str, int] = {}
    attr_cons: Dict[str, int] = {}
    for n in ast.walk(cls):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Attribute) and isinstance(
                    n.targets[0].value, ast.Name) and \
                n.targets[0].value.id == "self" and isinstance(
                    n.value, ast.Call) and _is_thread_call(
                    n.value, thread_imported):
            attr_threads[n.targets[0].attr] = n.lineno
            attr_cons[n.targets[0].attr] = n.value.lineno
    methods = {m.name: m for m in cls.body if isinstance(m, _FUNC_NODES)}
    close_like = [methods[m] for m in _CLOSE_METHODS if m in methods]

    def attr_calls(scope: ast.AST, attr: str) -> Set[str]:
        return {
            n.func.attr
            for n in ast.walk(scope)
            if isinstance(n, ast.Call) and isinstance(
                n.func, ast.Attribute)
            and isinstance(n.func.value, ast.Attribute)
            and n.func.value.attr == attr
            and isinstance(n.func.value.value, ast.Name)
            and n.func.value.value.id == "self"
        }

    for attr, line in attr_threads.items():
        if "start" not in attr_calls(cls, attr):
            continue
        covered.add(attr_cons[attr])  # flow-checked: dedupe RP04's no-join
        if "join" not in attr_calls(cls, attr):
            out.append((
                line,
                f"self.{attr} thread is started but the class never "
                f"joins it — a shutdown path (one of "
                f"{'/'.join(_CLOSE_METHODS[:3])}) must join",
            ))
            continue
        if close_like:
            reach = list(close_like)
            # one level of self-method calls from the close-like methods
            for m in close_like:
                for n in ast.walk(m):
                    if isinstance(n, ast.Call) and isinstance(
                        n.func, ast.Attribute
                    ) and isinstance(n.func.value, ast.Name) and \
                            n.func.value.id == "self" and \
                            n.func.attr in methods:
                        reach.append(methods[n.func.attr])
            if not any("join" in attr_calls(m, attr) for m in reach):
                out.append((
                    line,
                    f"self.{attr} thread's join is not reachable from "
                    f"the class's close-like method(s) — the shutdown "
                    "path never waits for the thread",
                ))

    # shutdown sentinel: enqueued unconditionally from close()
    sentinels = {
        n.targets[0].id
        for n in cls.body
        if isinstance(n, ast.Assign) and len(n.targets) == 1
        and isinstance(n.targets[0], ast.Name)
        and isinstance(n.value, ast.Call)
        and isinstance(n.value.func, ast.Name)
        and n.value.func.id == "object"
    }
    if not sentinels:
        return
    close = next((methods[m] for m in ("close", "shutdown", "stop")
                  if m in methods), None)
    if close is None:
        return
    cfg = build_cfg(close)
    put_nodes: Set[int] = set()
    for node in cfg.nodes:
        for sub in shallow_walk(node):
            if isinstance(sub, ast.Call) and isinstance(
                sub.func, ast.Attribute
            ) and sub.func.attr in ("put", "put_nowait"):
                refs = any(
                    isinstance(x, ast.Attribute) and x.attr in sentinels
                    for a in sub.args for x in ast.walk(a)
                )
                if refs:
                    put_nodes.add(node.idx)
    if not put_nodes:
        out.append((
            close.lineno,
            f"{close.name}() never enqueues the shutdown sentinel "
            f"({'/'.join(sorted(sentinels))}) — the dispatcher is never "
            "told to drain and stop",
        ))
        return
    # exits that skip the put must be idempotence guards (a return
    # governed by a closed/stopped-flag test), nothing else
    allowed_exits: Set[int] = set()
    for node in cfg.nodes:
        if isinstance(node.stmt, ast.Return) and node.kind == "stmt":
            if any(pol and any(m in dump.lower()
                               for m in _CLOSED_GUARD_MARKERS)
                   for dump, pol in node.facts):
                allowed_exits.add(node.idx)
    if exit_reachable_without(cfg, cfg.entry, put_nodes | allowed_exits,
                              frozenset()):
        out.append((
            close.lineno,
            f"{close.name}() can exit without enqueueing the shutdown "
            "sentinel on a path that is not a closed-flag guard — the "
            "sentinel enqueue must be unconditional",
        ))


def _rp08_ack_after_yield(func: ast.AST,
                          out: List[Tuple[int, str]]) -> None:
    cfg = build_cfg(func)
    commits: List[int] = []
    yields: List[int] = []
    for node in cfg.nodes:
        for sub in shallow_walk(node):
            if isinstance(sub, ast.Assign) and any(
                isinstance(t, ast.Attribute) and t.attr == "rows_done"
                for t in sub.targets
            ):
                commits.append(node.idx)
            elif isinstance(sub, (ast.Yield, ast.YieldFrom)):
                yields.append(node.idx)
    if not commits or not yields:
        return
    dom = dominators(cfg)
    for c in commits:
        if any(c in dom[y] for y in yields):
            out.append((
                cfg.nodes[c].stmt.lineno,
                "cursor commit dominates its batch's yield — the "
                "cursor advances before the consumer has acknowledged "
                "the batch (ack-after-yield contract): a crash in the "
                "consumer would silently drop the row range on resume",
            ))


def rule_rp08(tree: ast.Module) -> Tuple[List[Tuple[int, str]], Set[int]]:
    """Thread/queue protocol over one module (see module docstring).

    Returns ``(findings, covered)`` where ``covered`` is the set of
    ``Thread(...)`` construction linenos whose join protocol this rule
    actually flow-checked (started, non-escaping threads — flagged OR
    passed).  RP04's per-line no-join heuristic stands down on those
    lines so one missing join never reports twice (ISSUE 12)."""
    out: List[Tuple[int, str]] = []
    covered: Set[int] = set()
    thread_imported = any(
        isinstance(n, ast.ImportFrom) and n.module
        and n.module.endswith("threading")
        and any(a.name == "Thread" for a in n.names)
        for n in ast.walk(tree)
    )
    for func in _scopes(tree):
        _rp08_function(func, thread_imported, out, covered)
        _rp08_ack_after_yield(func, out)
    for n in ast.walk(tree):
        if isinstance(n, ast.ClassDef):
            _rp08_class(n, thread_imported, out, covered)
    return out, covered


# -- RP09: interprocedural host-sync -----------------------------------------


def _own_nodes(scope: ast.AST):
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, _FUNC_NODES + (ast.Lambda,)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _callee_syncs(callee: ast.AST, suppressed: Dict[int, Set[str]]
                  ) -> List[Tuple[int, str]]:
    """Unsuppressed host syncs lexically owned by ``callee`` (nested
    defs excluded: they do not run when the callee does)."""
    out = []
    for n in _own_nodes(callee):
        if isinstance(n, ast.Call):
            what = host_sync_what(n)
            if what is None:
                continue
            rules = suppressed.get(n.lineno, set()) | suppressed.get(
                n.lineno - 1, set()
            )
            if "RP03" in rules or "RP09" in rules:
                continue  # the owning file already justified this sync
            out.append((n.lineno, what))
    return out


def rule_rp09(tree: ast.Module, relpath: str,
              index: Optional[PackageIndex] = None,
              suppressed: Optional[Dict[int, Set[str]]] = None
              ) -> List[Tuple[int, str]]:
    """Interprocedural host-sync: loop bodies in a hot module calling
    (one level of) package functions that perform a host sync.  The
    finding anchors at the call site — that is where the hot loop pays
    the stall, and where a pragma belongs if the overlap is real.  A
    caller-provided ``index`` is never mutated; its entry for this
    module (same source, indexed once by ``lint_package``) is reused."""
    idx = index if index is not None else PackageIndex()
    self_info = idx.modules.get(relpath) if index is not None else None
    if self_info is None:
        self_info = index_module(relpath, tree, suppressed)
    parents = _parents_map(tree)

    def enclosing(node: ast.AST, kinds) -> Optional[ast.AST]:
        p = parents.get(node)
        while p is not None and not isinstance(p, kinds):
            p = parents.get(p)
        return p

    out: List[Tuple[int, str]] = []
    seen: Set[Tuple[int, str]] = set()
    loops = [n for n in ast.walk(tree)
             if isinstance(n, (ast.For, ast.While, ast.AsyncFor))]
    for loop in loops:
        encl_func = enclosing(loop, _FUNC_NODES)
        cls = enclosing(loop, (ast.ClassDef,))
        cls_name = cls.name if cls is not None else None
        for n in ast.walk(loop):
            if not isinstance(n, ast.Call) or host_sync_what(n) is not None:
                continue  # direct syncs are RP03's finding, not RP09's
            resolved = idx.resolve(n, self_info, cls_name)
            if resolved is None:
                continue
            owner, callee, display = resolved
            if callee is encl_func:
                continue  # recursion: the loop IS the callee
            syncs = _callee_syncs(callee, owner.suppressed)
            if not syncs:
                continue
            key = (n.lineno, display)
            if key in seen:
                continue
            seen.add(key)
            sline, what = syncs[0]
            where = (f"{owner.relpath}:{sline}"
                     if owner.relpath != relpath else f"line {sline}")
            out.append((
                n.lineno,
                f"call to {display}() inside a hot-module loop reaches "
                f"a host sync ({what} at {where}) — the helper blocks "
                "the loop on d2h every iteration; overlap the fetch or "
                "hoist the call",
            ))
    return out


# -- RP10: cross-thread shared-state races (ISSUE 12) ------------------------


@dataclasses.dataclass
class _Access:
    """One data access of a shared name: ``kind`` is ``read``/``write``
    for the binding itself and ``call`` for a method call *on* the
    bound object (``self._q.put(...)``) — call accesses are the
    object's own synchronization concern (the queue.Queue handoff
    exemption) and never participate in conflicts; ``init`` marks a
    write proven to happen before any thread publication."""

    name: str
    kind: str
    role: str
    locks: frozenset
    line: int
    fn: str
    relpath: str
    init: bool = False


def _fn_params(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = {x.arg for x in a.args + a.posonlyargs + a.kwonlyargs}
    if a.vararg is not None:
        names.add(a.vararg.arg)
    if a.kwarg is not None:
        names.add(a.kwarg.arg)
    return names


def _scan_self(func: ast.AST, parents: Dict[ast.AST, ast.AST],
               relpath: str, method_names: Set[str]):
    """``self.``-attribute data accesses of one function (with the
    locks lexically held at each), plus its resolvable call edges:
    ``("self", name, locks, line)`` for same-class method calls and
    ``("name", name, locks, line)`` for bare-name calls.  A direct
    ``self.x(...)`` call where ``x`` is NOT a class method is a *read*
    of a stored callable, not a call edge."""
    regions = lock_regions(func)
    accs: List[_Access] = []
    calls: List[Tuple[str, str, frozenset, int]] = []
    for n in _own_nodes(func):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name):
            calls.append((
                "name", n.func.id,
                frozenset(regions.held.get(id(n), ())), n.lineno,
            ))
            continue
        if not (isinstance(n, ast.Attribute) and isinstance(
                n.value, ast.Name) and n.value.id == "self"):
            continue
        locks = frozenset(regions.held.get(id(n), ()))
        p = parents.get(n)
        if isinstance(n.ctx, (ast.Store, ast.Del)):
            kind = "write"
        elif isinstance(p, ast.Call) and p.func is n:
            if n.attr in method_names:
                calls.append(("self", n.attr, locks, n.lineno))
                continue
            kind = "read"  # stored callable: self.prepare(batch)
        elif isinstance(p, ast.Attribute):
            gp = parents.get(p)
            kind = (
                "call"
                if isinstance(gp, ast.Call) and gp.func is p
                else "read"
            )
        elif isinstance(p, ast.Subscript) and p.value is n and isinstance(
            p.ctx, (ast.Store, ast.Del)
        ):
            kind = "write"  # container mutation: self._tallies[k] = v
        else:
            kind = "read"
        accs.append(_Access(
            n.attr, kind, "", locks, n.lineno,
            getattr(func, "name", "<module>"), relpath,
        ))
    return accs, calls


def _resolve_bare(name: str, func: ast.AST,
                  parents: Dict[ast.AST, ast.AST],
                  mod: ModuleInfo) -> Optional[ast.AST]:
    """A bare-name callee, preferring lexical proximity: nested defs of
    ``func``, then of its enclosing functions, then module-level defs."""
    scope: Optional[ast.AST] = func
    while scope is not None:
        if isinstance(scope, _FUNC_NODES):
            for stmt in ast.walk(scope):
                if isinstance(stmt, _FUNC_NODES) and stmt is not scope \
                        and stmt.name == name:
                    return stmt
        scope = parents.get(scope)
    if name in mod.funcs:
        return mod.funcs[name]
    return mod.nested.get(name)


def _merged_methods(cls: ast.ClassDef, mod: ModuleInfo,
                    index: PackageIndex
                    ) -> Tuple[Dict[str, Tuple[ast.AST, str]],
                               List[Tuple[ast.AST, str]]]:
    """The class's method table over one level of package-resolvable
    bases (same-module classes and ``from randomprojection_tpu...
    import`` names; derived definitions win) — so a subclass's hook
    methods join the thread roles its base class constructs.  Also
    returns the *shadowed* base definitions: an overridden base
    ``__init__`` still runs through ``super().__init__()``, so thread
    entry points constructed there must stay discoverable."""
    out: Dict[str, Tuple[ast.AST, str]] = {}
    shadowed: List[Tuple[ast.AST, str]] = []
    for base in cls.bases:
        if not isinstance(base, ast.Name):
            continue
        target = mod.imports.get(base.id)
        if target is not None:
            other = index.modules.get(target[0])
            if other is not None:
                for (cname, mname), fn in other.methods.items():
                    if cname == target[1]:
                        out[mname] = (fn, other.relpath)
        else:
            for (cname, mname), fn in mod.methods.items():
                if cname == base.id:
                    out[mname] = (fn, mod.relpath)
    for (cname, mname), fn in mod.methods.items():
        if cname == cls.name:
            prev = out.get(mname)
            if prev is not None:
                shadowed.append(prev)
            out[mname] = (fn, mod.relpath)
    return out, shadowed


def _publication_nodes(cfg: CFG) -> Set[int]:
    """CFG nodes of ``__init__`` that may publish ``self`` to a thread:
    any ``.start()`` call, and ``super().__init__(...)`` (the base
    constructor may start threads of its own)."""
    pubs: Set[int] = set()
    for node in cfg.nodes:
        for sub in shallow_walk(node):
            if not (isinstance(sub, ast.Call) and isinstance(
                    sub.func, ast.Attribute)):
                continue
            if sub.func.attr == "start":
                pubs.add(node.idx)
            elif sub.func.attr == "__init__" and isinstance(
                sub.func.value, ast.Call
            ) and isinstance(sub.func.value.func, ast.Name) and \
                    sub.func.value.func.id == "super":
                pubs.add(node.idx)
    return pubs


def _mark_init_writes(init_fn: ast.AST, accs: List[_Access]) -> None:
    """Mark writes in ``__init__`` that dominate every thread
    publication point (``.start()`` / ``super().__init__``) on its CFG
    as init-only: they happen-before the thread exists, so they can
    never race it."""
    cfg = build_cfg(init_fn)
    node_of: Dict[int, int] = {}
    for node in cfg.nodes:
        for sub in shallow_walk(node):
            node_of.setdefault(id(sub), node.idx)
    pubs = _publication_nodes(cfg)
    dom = dominators(cfg) if pubs else None
    by_line: Dict[int, List[int]] = {}
    for node in cfg.nodes:
        for sub in shallow_walk(node):
            if isinstance(sub, ast.Attribute) and isinstance(
                sub.value, ast.Name
            ) and sub.value.id == "self":
                by_line.setdefault(sub.lineno, []).append(node.idx)
    for a in accs:
        if a.kind != "write" or a.fn != "__init__":
            continue
        nodes = by_line.get(a.line, [])
        if not nodes:
            continue
        if dom is None:
            a.init = True  # no publication in __init__: trivially before
        else:
            a.init = all(
                any(n in dom[p] for n in nodes) for p in pubs
            )


_HANDOFF_NOTE = (
    "protect every access path with the same lock, hand the value "
    "across roles through a queue.Queue, or write it only before the "
    "thread starts"
)


def _report_conflicts(accs: List[_Access], has_roles: bool, relpath: str,
                      what: str, out: List[Tuple[int, str]]) -> None:
    """Conflict detection over one shared name's accesses.  With thread
    roles: a race is a *cross-role pair* with at least one write and no
    lock in common — judged pairwise, because same-role accesses run on
    one thread and can never race each other (an unlocked read on the
    writer's own thread must not fail a properly locked cross-role
    pair).  Without roles (no thread constructed here): the
    lock-*consistency* leg — state touched under a lock somewhere must
    hold that lock everywhere it is accessed."""
    post = [a for a in accs if a.kind in ("read", "write") and not a.init]
    writes = [a for a in post if a.kind == "write"]
    if not writes:
        return
    post.sort(key=lambda a: (a.line, a.kind))
    if has_roles:
        pairs = [
            (a, b)
            for i, a in enumerate(post) for b in post[i + 1:]
            if a.role != b.role
            and ("write" in (a.kind, b.kind))
            and not (a.locks & b.locks)
        ]
        if not pairs:
            return
        involved: List[_Access] = []
        seen: Set[int] = set()
        for a, b in pairs:
            for x in (a, b):
                if id(x) not in seen:
                    seen.add(id(x))
                    involved.append(x)
        involved.sort(key=lambda a: a.line)
        anchor = next((a for a in involved if a.relpath == relpath), None)
        if anchor is None:
            return  # conflict lives entirely in the base module's file
        mate = None
        for a, b in pairs:
            if a is anchor or b is anchor:
                m = b if a is anchor else a
                if mate is None or m.line < mate.line:
                    mate = m
        w = anchor if anchor.kind == "write" else mate
        other = mate if w is anchor else anchor
        out.append((
            anchor.line,
            f"{what} is written by role {w.role!r} ({w.fn}, line "
            f"{w.line}) and {'written' if other.kind == 'write' else 'read'}"
            f" by role {other.role!r} ({other.fn}, line {other.line}) "
            f"with no common lock — {_HANDOFF_NOTE}",
        ))
    else:
        common = frozenset.intersection(*(a.locks for a in post))
        if common:
            return
        locked = [a for a in post if a.locks]
        if not locked:
            return  # no lock basis to judge a thread-free class against
        bare = next((a for a in post if not a.locks
                     and a.relpath == relpath), None)
        if bare is None:
            return
        lock_disp = sorted(locked[0].locks)[0]
        out.append((
            bare.line,
            f"{what} is locked inconsistently: accessed under "
            f"{lock_disp} ({locked[0].fn}, line {locked[0].line}) but "
            f"{bare.kind} without it here ({bare.fn}) — every post-init "
            "access must hold the same lock",
        ))


def _class_rp10(cls: ast.ClassDef, mod: ModuleInfo, index: PackageIndex,
                parents_of: Dict[str, Dict[ast.AST, ast.AST]],
                out: List[Tuple[int, str]]) -> None:
    methods, shadowed = _merged_methods(cls, mod, index)
    method_names = set(methods)

    def parents_for(rel: str) -> Dict[ast.AST, ast.AST]:
        if rel not in parents_of:
            info = index.modules.get(rel)
            parents_of[rel] = _parents_map(
                info.tree if info is not None else mod.tree
            )
        return parents_of[rel]

    # thread entry points over the merged method bodies — and the
    # shadowed base bodies (super().__init__() still runs them), with
    # the target resolved against the MERGED table so a derived
    # override of the entry point wins
    rel_of = {id(f): r for _m, (f, r) in methods.items()}
    entries: List[Tuple[str, ast.AST, str]] = []
    entry_ids: Set[int] = set()
    scan = [(fn, rel) for _m, (fn, rel) in sorted(methods.items())]
    scan += shadowed
    mdefs = {m: f for m, (f, _r) in methods.items()}
    for fn, rel in scan:
        nested = {
            n.name: n for n in ast.walk(fn)
            if isinstance(n, _FUNC_NODES) and n is not fn
        }
        for role, entry, _line in thread_entries(fn, mdefs, nested):
            if id(entry) in entry_ids:
                continue
            entry_ids.add(id(entry))
            entries.append((role, entry, rel_of.get(id(entry), rel)))

    def fold(seeds: List[Tuple[ast.AST, str]], role: str
             ) -> Tuple[List[_Access], Set[int]]:
        """Transitive access collection, one call level at a time
        through the resolvable call edges; the locks held at each call
        site fold into the callee's access contexts."""
        accs: List[_Access] = []
        reached: Set[int] = set()
        visited: Set[Tuple[int, frozenset]] = set()
        stack = [(fn, rel, frozenset()) for fn, rel in seeds]
        while stack:
            fn, rel, ctx = stack.pop()
            key = (id(fn), ctx)
            if key in visited:
                continue
            visited.add(key)
            reached.add(id(fn))
            a, calls = _scan_self(fn, parents_for(rel), rel, method_names)
            for acc in a:
                acc = dataclasses.replace(
                    acc, role=role, locks=acc.locks | ctx
                )
                accs.append(acc)
            for ckind, cname, clocks, _cline in calls:
                tgt: Optional[Tuple[ast.AST, str]] = None
                if ckind == "self":
                    m = methods.get(cname)
                    if m is not None:
                        tgt = m
                else:
                    t = _resolve_bare(cname, fn, parents_for(rel),
                                      index.modules.get(rel, mod))
                    if t is not None:
                        tgt = (t, rel)
                if tgt is not None:
                    stack.append((tgt[0], tgt[1], ctx | clocks))
        return accs, reached

    role_accs: List[_Access] = []
    thread_reached: Set[int] = set()
    for role, entry, rel in entries:
        accs, reached = fold([(entry, rel)], role)
        role_accs += accs
        thread_reached |= reached

    has_roles = bool(entries)
    if has_roles:
        main_seeds = [
            (fn, rel) for _m, (fn, rel) in sorted(methods.items())
            if id(fn) not in thread_reached
        ]
        accs, _ = fold(main_seeds, "main")
        role_accs += accs
    else:
        # lock-consistency leg: per-method accesses, no role folding
        for _m, (fn, rel) in sorted(methods.items()):
            a, _calls = _scan_self(fn, parents_for(rel), rel, method_names)
            role_accs += [dataclasses.replace(x, role="main") for x in a]

    init = methods.get("__init__")
    if init is not None:
        _mark_init_writes(init[0], role_accs)

    by_attr: Dict[str, List[_Access]] = {}
    for a in role_accs:
        by_attr.setdefault(a.name, []).append(a)
    for attr in sorted(by_attr):
        _report_conflicts(
            by_attr[attr], has_roles, mod.relpath,
            f"shared attribute self.{attr} of {cls.name}", out,
        )


def _module_rp10(tree: ast.Module, relpath: str,
                 out: List[Tuple[int, str]]) -> None:
    """Module-global leg: names rebound through ``global`` declarations
    get the lock-consistency check across every function that touches
    them (the ``_RUN_TOKEN``/``_SPAN_SEQ`` class of state)."""
    gnames: Set[str] = set()
    funcs = [n for n in ast.walk(tree) if isinstance(n, _FUNC_NODES)]
    for fn in funcs:
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Global):
                gnames.update(stmt.names)
    for g in sorted(gnames):
        accs: List[_Access] = []
        for fn in funcs:
            if g in _fn_params(fn):
                continue
            declared = any(
                isinstance(s, ast.Global) and g in s.names
                for s in ast.walk(fn)
            )
            stores = any(
                isinstance(n, ast.Name) and n.id == g
                and isinstance(n.ctx, (ast.Store, ast.Del))
                for n in _own_nodes(fn)
            )
            if stores and not declared:
                continue  # local shadow, not the module global
            regions = lock_regions(fn)
            for n in _own_nodes(fn):
                if isinstance(n, ast.Name) and n.id == g:
                    kind = (
                        "write"
                        if isinstance(n.ctx, (ast.Store, ast.Del))
                        else "read"
                    )
                    accs.append(_Access(
                        g, kind, "main",
                        frozenset(regions.held.get(id(n), ())),
                        n.lineno, fn.name, relpath,
                    ))
        _report_conflicts(accs, False, relpath, f"module global {g}", out)


def rule_rp10(tree: ast.Module, relpath: str,
              index: Optional[PackageIndex] = None
              ) -> List[Tuple[int, str]]:
    """Cross-thread shared-state races over one module (see the module
    docstring).  ``index`` (built by ``lint_package``) lets a subclass
    in one file join the thread roles its base class constructs in
    another; without it, roles resolve within the file only."""
    idx = index if index is not None else PackageIndex()
    if relpath not in idx.modules:
        idx = PackageIndex(dict(idx.modules))
        idx.add(index_module(relpath, tree))
    mod = idx.modules[relpath]
    parents_of: Dict[str, Dict[ast.AST, ast.AST]] = {}
    out: List[Tuple[int, str]] = []
    for stmt in tree.body:
        if isinstance(stmt, ast.ClassDef):
            _class_rp10(stmt, mod, idx, parents_of, out)
    _module_rp10(tree, relpath, out)
    out.sort()
    return out


# -- RP11: lock-order deadlock lint (ISSUE 12) -------------------------------

_BLOCKING_CALLS = {
    "put": "a full queue blocks the producer inside the critical "
           "section",
    "join": "the joined thread may need this very lock to finish",
    "result": "the future's worker may need this very lock to complete",
}


def _blocking_what(call: ast.Call) -> Optional[str]:
    """The blocking-call class this call belongs to, with the string /
    path ``join`` idioms excluded.  A thread join's only positional
    argument is a numeric timeout — any other positional shape
    (``sep.join(parts)``, ``"".join(x for ...)``) is a string join."""
    f = call.func
    if not isinstance(f, ast.Attribute) or f.attr not in _BLOCKING_CALLS:
        return None
    if f.attr == "join":
        if isinstance(f.value, ast.Constant):
            return None  # "sep".join(...)
        base = _dotted(f.value)
        if "path" in base.split("."):
            return None  # os.path.join and friends
        if call.args and not (
            len(call.args) == 1
            and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, (int, float))
        ):
            return None  # iterable positional: a string join
    return f.attr


def _sccs(edges: Set[Tuple[str, str]]) -> List[Set[str]]:
    """Strongly connected components (iterative Tarjan) of the lock
    graph; only components that can deadlock (size > 1, or a self
    edge) are returned."""
    adj: Dict[str, List[str]] = {}
    nodes: Set[str] = set()
    for a, b in sorted(edges):
        adj.setdefault(a, []).append(b)
        nodes.add(a)
        nodes.add(b)
    idx: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]

    def strongconnect(root: str) -> None:
        work = [(root, iter(adj.get(root, ())))]
        idx[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in idx:
                    idx[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(adj.get(w, ()))))
                    advanced = True
                    break
                if w in on:
                    low[v] = min(low[v], idx[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == idx[v]:
                comp: Set[str] = set()
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.add(w)
                    if w == v:
                        break
                if len(comp) > 1 or (v, v) in edges:
                    out.append(comp)

    for n in sorted(nodes):
        if n not in idx:
            strongconnect(n)
    return out


def rule_rp11(tree: ast.Module, relpath: str,
              index: Optional[PackageIndex] = None
              ) -> List[Tuple[int, str]]:
    """Lock-order deadlock lint: build the lock-acquisition ordering
    graph (nested ``with``-lock regions, plus acquisitions one call
    level away through the package index), flag cycles, and flag
    blocking calls (``.put``/``.join``/``.result``) made while any lock
    is held."""
    idx = index if index is not None else PackageIndex()
    self_info = idx.modules.get(relpath)
    if self_info is None:
        self_info = index_module(relpath, tree)
    parents = _parents_map(tree)

    def encl_class(node: ast.AST) -> Optional[str]:
        p = parents.get(node)
        while p is not None and not isinstance(p, ast.ClassDef):
            p = parents.get(p)
        return p.name if isinstance(p, ast.ClassDef) else None

    method_class = {
        id(fn): cname for (cname, _m), fn in self_info.methods.items()
    }

    def qual(name: str, cls: Optional[str]) -> str:
        # self.X locks are per-instance: scope them by class so two
        # classes' self._lock never alias in the order graph
        if name.startswith("self.") and cls is not None:
            return f"{cls}.{name[len('self.'):]}"
        return name

    # locks constructed as threading.RLock(): re-entering one is legal,
    # so self-edges on them are not findings (order cycles still are)
    reentrant: Set[str] = set()
    for n in ast.walk(tree):
        if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.value, ast.Call)):
            continue
        f = n.value.func
        cname = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else ""
        )
        if cname != "RLock":
            continue
        tgt = n.targets[0]
        if isinstance(tgt, ast.Attribute) and isinstance(
            tgt.value, ast.Name
        ) and tgt.value.id == "self":
            reentrant.add(qual(f"self.{tgt.attr}", encl_class(n)))
        elif isinstance(tgt, ast.Name):
            reentrant.add(tgt.id)

    edges: Dict[Tuple[str, str], int] = {}  # (src, dst) -> earliest line
    blocking: List[Tuple[int, str, str, str]] = []

    def note_edge(a: str, b: str, line: int) -> None:
        prev = edges.get((a, b))
        if prev is None or line < prev:
            edges[(a, b)] = line

    for fn in _scopes(tree):
        cls = encl_class(fn)
        regions = lock_regions(fn)
        for name, line, held in regions.acquisitions:
            lid = qual(name, cls)
            for h in held:
                note_edge(qual(h, cls), lid, line)
        for n in _own_nodes(fn):
            if not isinstance(n, ast.Call):
                continue
            held = regions.held.get(id(n), ())
            if not held:
                continue
            what = _blocking_what(n)
            if what is not None:
                blocking.append((
                    n.lineno, what, qual(held[-1], cls), "",
                ))
                continue
            resolved = idx.resolve(n, self_info, cls)
            if resolved is None:
                continue
            owner, callee, display = resolved
            callee_cls = method_class.get(id(callee)) if (
                owner.relpath == relpath
            ) else None
            sub_regions = lock_regions(callee)
            for name2, _line2, _held2 in sub_regions.acquisitions:
                lid2 = qual(name2, callee_cls)
                for h in held:
                    note_edge(qual(h, cls), lid2, n.lineno)
            for sub in _own_nodes(callee):
                if isinstance(sub, ast.Call):
                    w = _blocking_what(sub)
                    if w is not None:
                        blocking.append((
                            n.lineno, w, qual(held[-1], cls),
                            display,
                        ))
                        break

    out: List[Tuple[int, str]] = []
    edge_set = set(edges)
    for comp in _sccs(edge_set):
        comp_edges = [
            (line, a, b) for (a, b), line in edges.items()
            if a in comp and b in comp
        ]
        line = min(l for l, _a, _b in comp_edges)
        if len(comp) == 1:
            lock = next(iter(comp))
            if lock in reentrant:
                continue  # threading.RLock: re-entry is legal
            out.append((
                line,
                f"lock {lock} is re-acquired while already held "
                "— threading.Lock is not reentrant; this deadlocks "
                "immediately",
            ))
            continue
        names = sorted(comp)
        out.append((
            line,
            "lock-order cycle: " + " -> ".join(names + [names[0]]) +
            " — these locks are acquired in conflicting orders on "
            "different paths; two threads interleaving them deadlock",
        ))
    for line, what, lock, via in blocking:
        reach = f"call to {via}() reaches " if via else ""
        out.append((
            line,
            f"{reach}blocking .{what}() while holding lock {lock} — "
            f"{_BLOCKING_CALLS[what]}; move the blocking call outside "
            "the lock region",
        ))
    out.sort()
    return out


# -- shared call-shape primitives for RP12-RP14 (ISSUE 20) -------------------


def _call_last(call: ast.Call) -> str:
    """Last component of the callee name ('' when dynamic)."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _call_base(call: ast.Call) -> str:
    """Last component of the callee's receiver ('' for bare names)."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return _dotted(f.value).split(".")[-1]
    return ""


# -- RP12: resource lifecycle (ISSUE 20) -------------------------------------


@dataclasses.dataclass(frozen=True)
class _Resource:
    """One paired-acquire/release protocol RP12 enforces."""

    what: str
    close_attrs: Tuple[str, ...]      # x.<attr>() releases the handle
    release_callees: Tuple[str, ...]  # <callee>(x, ...) releases it
    advice: str


_RP12_SUBSCRIPTION = _Resource(
    "telemetry subscription", ("close",), ("unsubscribe",),
    "unsubscribe it (or close the Subscription) in a finally",
)
_RP12_METRICS = _Resource(
    "MetricsServer", ("close", "shutdown"), (),
    "close() it in a finally or use it as a context manager",
)
_RP12_HEALTH = _Resource(
    "HealthEngine", ("close",), (),
    "close() it in a finally",
)
_RP12_OPEN = _Resource(
    "open() handle", ("close",), (),
    "use a with block or close() it in a finally",
)
_RP12_MEMMAP = _Resource(
    "np.memmap handle", ("close",), (),
    "close the underlying mmap (handle._mmap.close()) or hand the "
    "handle to an owner",
)
_RP12_TMPDIR = _Resource(
    "mkdtemp temp dir", (), ("rmtree",),
    "shutil.rmtree() it in a finally",
)

#: Passing a handle to one of these callees is the *release*, not an
#: ownership transfer — it must not exempt the acquire as an escape.
_RP12_NON_OWNING_CALLEES = frozenset({"unsubscribe", "rmtree"})


def _rp12_acquire(value: ast.AST) -> Optional[Tuple[ast.Call, _Resource]]:
    """The tracked acquire call inside an Assign value, unwrapping
    ``a if c else b`` / ``a or b`` alternatives and the
    ``Ctor(...).start()`` chained form (``.start()`` returns self)."""
    if isinstance(value, ast.IfExp):
        return _rp12_acquire(value.body) or _rp12_acquire(value.orelse)
    if isinstance(value, ast.BoolOp):
        for v in value.values:
            m = _rp12_acquire(v)
            if m is not None:
                return m
        return None
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    if (isinstance(f, ast.Attribute) and f.attr == "start"
            and isinstance(f.value, ast.Call)):
        return _rp12_acquire(f.value)
    name, base = _call_last(value), _call_base(value)
    if name == "subscribe" and (isinstance(f, ast.Name)
                                or base == "telemetry"):
        return value, _RP12_SUBSCRIPTION
    if name == "MetricsServer":
        return value, _RP12_METRICS
    if name == "HealthEngine":
        return value, _RP12_HEALTH
    if name == "open" and isinstance(f, ast.Name):
        return value, _RP12_OPEN
    if name == "memmap" and base in ("np", "numpy"):
        return value, _RP12_MEMMAP
    if name == "mkdtemp":
        return value, _RP12_TMPDIR
    return None


def _rp12_guard_facts(name: str) -> frozenset:
    """Branch facts consistent with ``name`` holding a live handle, so
    ``if x is not None: x.close()``-style guarded releases count: the
    pruned paths are exactly the ones with nothing to release."""
    def dump(expr: str) -> str:
        return ast.dump(ast.parse(expr, mode="eval").body)

    return frozenset({
        (dump(f"{name} is not None"), True),
        (dump(f"{name} is None"), False),
        (dump(name), True),
    })


def _rp12_aliases(value: ast.AST) -> Iterator[str]:
    """Names the assigned expression may directly BE (so ``y = x``
    aliases but ``data = x.read()`` — a derived value — does not)."""
    if isinstance(value, ast.Name):
        yield value.id
    elif isinstance(value, ast.IfExp):
        yield from _rp12_aliases(value.body)
        yield from _rp12_aliases(value.orelse)
    elif isinstance(value, ast.BoolOp):
        for v in value.values:
            yield from _rp12_aliases(v)
    elif isinstance(value, ast.NamedExpr):
        yield from _rp12_aliases(value.value)


def _rp12_escapes(func: ast.AST, name: str, res: _Resource) -> bool:
    """The handle outlives (or is owned beyond) this function: it is
    returned/yielded, packed into a container, re-bound (aliased or
    stored on an object — another owner can release it), passed to a
    callee that is not the paired release, or captured by a nested
    def/lambda."""
    def contains(sub: ast.AST) -> bool:
        return any(isinstance(x, ast.Name) and x.id == name
                   for x in ast.walk(sub))

    for n in _own_nodes(func):
        if isinstance(n, ast.Return):
            if n.value is not None and contains(n.value):
                return True
        elif isinstance(n, (ast.Yield, ast.YieldFrom)):
            if n.value is not None and contains(n.value):
                return True
        elif isinstance(n, (ast.Tuple, ast.List, ast.Set)):
            if any(isinstance(e, ast.Name) and e.id == name
                   for e in n.elts):
                return True
        elif isinstance(n, ast.Dict):
            vals = list(n.keys) + list(n.values)
            if any(isinstance(v, ast.Name) and v.id == name
                   for v in vals if v is not None):
                return True
        elif isinstance(n, ast.Call):
            if (_call_last(n) in res.release_callees
                    or _call_last(n) in _RP12_NON_OWNING_CALLEES):
                continue
            if any(contains(a) for a in n.args) or any(
                    contains(k.value) for k in n.keywords):
                return True
        elif isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if n.value is not None and name in _rp12_aliases(n.value):
                return True
        elif isinstance(n, (ast.Global, ast.Nonlocal)):
            if name in n.names:
                return True
    for nd in ast.walk(func):
        if nd is not func and isinstance(nd, _FUNC_NODES + (ast.Lambda,)):
            if any(isinstance(x, ast.Name) and x.id == name
                   for x in ast.walk(nd)):
                return True
    return False


def _rp12_release_nodes(cfg: CFG, name: str, res: _Resource) -> Set[int]:
    """CFG nodes that release the handle: ``x.close()``-style calls
    (receiver rooted at ``x``, so ``x._mmap.close()`` counts),
    ``unsubscribe(x)``-style release callees, ``del x``, and
    ``with x:`` / ``with closing(x):`` headers."""
    rel: Set[int] = set()
    for node in cfg.nodes:
        st = node.stmt
        if st is None:
            continue
        if isinstance(st, ast.Delete) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in st.targets):
            rel.add(node.idx)
            continue
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                ce = item.context_expr
                if isinstance(ce, ast.Name) and ce.id == name:
                    rel.add(node.idx)
                elif (isinstance(ce, ast.Call) and ce.args
                      and isinstance(ce.args[0], ast.Name)
                      and ce.args[0].id == name):
                    rel.add(node.idx)
        for sub in shallow_walk(node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            if (isinstance(f, ast.Attribute) and f.attr in res.close_attrs
                    and _dotted(f.value).split(".")[0] == name):
                rel.add(node.idx)
            elif _call_last(sub) in res.release_callees and any(
                    isinstance(a, ast.Name) and a.id == name
                    for a in sub.args):
                rel.add(node.idx)
    return rel


def _rp12_reach(cfg: CFG, start: int, targets: Set[int],
                blocked: Set[int], facts: frozenset) -> bool:
    """``node_reachable_without`` that ignores the start node's own
    exception edge: if the acquire statement itself raises, the handle
    was never bound — only exceptions *after* the bind can leak it."""
    seen: Set[int] = set()
    stack: List[int] = []
    for s, fact in cfg.nodes[start].succs:
        if cfg.nodes[s].kind == "anchor":
            continue
        if s in blocked:
            continue
        if fact is not None and (fact[0], not fact[1]) in facts:
            continue
        if s not in seen:
            seen.add(s)
            stack.append(s)
    while stack:
        n = stack.pop()
        for s, fact in cfg.nodes[n].succs:
            if s in seen or s in blocked:
                continue
            if fact is not None and (fact[0], not fact[1]) in facts:
                continue
            seen.add(s)
            stack.append(s)
    return bool(targets & seen)


def rule_rp12(tree: ast.Module) -> List[Tuple[int, str]]:
    """Resource lifecycle: paired acquire/release on every path, plus
    the r17 acquire-ordering leg (a later acquire outside any try while
    an earlier handle is live leaks the earlier handle if it raises)."""
    out: List[Tuple[int, str]] = []
    for func in (n for n in ast.walk(tree) if isinstance(n, _FUNC_NODES)):
        _rp12_function(func, out)
    out.sort()
    return out


def _rp12_function(func: ast.AST, out: List[Tuple[int, str]]) -> None:
    cfg = build_cfg(func)
    acquires = []
    for node in cfg.nodes:
        st = node.stmt
        if node.kind != "stmt" or not isinstance(st, ast.Assign):
            continue
        if len(st.targets) != 1 or not isinstance(st.targets[0], ast.Name):
            continue  # attribute/tuple targets: the owner releases it
        m = _rp12_acquire(st.value)
        if m is not None:
            acquires.append((node.idx, st.lineno, st.targets[0].id, m[1]))
    if not acquires:
        return
    acquires.sort(key=lambda a: a[1])
    balanced = []
    for idx, line, name, res in acquires:
        if _rp12_escapes(func, name, res):
            continue
        rel = _rp12_release_nodes(cfg, name, res)
        facts = cfg.nodes[idx].facts | _rp12_guard_facts(name)
        if _rp12_reach(cfg, idx, {cfg.exit}, rel, facts):
            out.append((
                line,
                f"{res.what} {name!r} is not released on every path out "
                f"of {getattr(func, 'name', '<fn>')}() — {res.advice}; "
                "escaping handles (returned/stored/passed to an owner) "
                "are exempt",
            ))
        else:
            balanced.append((idx, line, name, res, rel, facts))
    for b_idx, b_line, b_name, b_res in acquires:
        node = cfg.nodes[b_idx]
        if any(cfg.nodes[s].kind == "anchor" for s, _ in node.succs):
            continue  # exception-protected: the handler owns cleanup
        for a_idx, a_line, a_name, _a_res, rel, facts in balanced:
            if a_idx == b_idx or a_line >= b_line:
                continue
            if _rp12_reach(cfg, a_idx, {b_idx}, rel, facts):
                out.append((
                    b_line,
                    f"{b_res.what} {b_name!r} is acquired while "
                    f"{a_name!r} (line {a_line}) is still unreleased and "
                    "this statement is not exception-protected — if this "
                    f"acquire raises, {a_name!r} leaks; release it in an "
                    "except before re-raising, or move this acquire under "
                    "the existing try",
                ))
                break


# -- RP13: durable-commit discipline (ISSUE 20) ------------------------------

_RP13_MANIFEST_CALLEES = ("_commit_manifest", "_write_manifest")
_RP13_ARTIFACT_CALLEES = ("_write_npy_atomic", "_spill_chunk")
_RP13_WRITE_MODES = ("w", "wb", "x", "xb", "w+", "wb+", "w+b", "x+b")
_RP13_DIRFSYNC_RE = re.compile(r"fsync\w*dir|dir\w*fsync", re.I)


def _rp13_carriers(tree: ast.Module) -> Set[str]:
    """Module function names whose body reaches a directory-fsync
    (``_fsync_dir``-shaped name), transitively within the module, so a
    call to ``_commit_manifest`` counts as the caller's dir fsync."""
    fns = {n.name: n for n in ast.walk(tree)
           if isinstance(n, _FUNC_NODES)}
    carriers = {
        name for name, fn in fns.items()
        if _RP13_DIRFSYNC_RE.search(name) or any(
            isinstance(sub, ast.Call)
            and _RP13_DIRFSYNC_RE.search(_call_last(sub))
            for sub in ast.walk(fn)
        )
    }
    changed = True
    while changed:
        changed = False
        for name, fn in fns.items():
            if name in carriers:
                continue
            if any(isinstance(sub, ast.Call)
                   and _call_last(sub) in carriers
                   for sub in ast.walk(fn)):
                carriers.add(name)
                changed = True
    return carriers


def _rp13_is_dirfsync(call: ast.Call, carriers: Set[str]) -> bool:
    name = _call_last(call)
    return bool(_RP13_DIRFSYNC_RE.search(name)) or name in carriers


def _rp13_tmp_path(path_arg: ast.AST, replaces: List[ast.Call]) -> bool:
    """The opened path is a tmp staging path: it textually names a tmp,
    or it is the *source* of some ``os.replace`` in the same function
    (matched by name or by expression shape)."""
    if isinstance(path_arg, ast.Name) and "tmp" in path_arg.id.lower():
        return True
    for sub in ast.walk(path_arg):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            low = sub.value.lower()
            if "tmp" in low or ".partial" in low:
                return True
    want = ast.dump(path_arg)
    for r in replaces:
        if not r.args:
            continue
        src = r.args[0]
        if isinstance(path_arg, ast.Name) and isinstance(src, ast.Name):
            if path_arg.id == src.id:
                return True
        elif ast.dump(src) == want:
            return True
    return False


def rule_rp13(tree: ast.Module) -> List[Tuple[int, str]]:
    """Durable-commit discipline: tmp→flush→fsync→``os.replace`` for
    every artifact landing, manifest committed last (dominator query),
    and a directory fsync reachable after the replace."""
    out: List[Tuple[int, str]] = []
    carriers = _rp13_carriers(tree)
    fns = [n for n in ast.walk(tree) if isinstance(n, _FUNC_NODES)]
    cfgs: Dict[int, CFG] = {}

    def cfg_of(fn: ast.AST) -> CFG:
        c = cfgs.get(id(fn))
        if c is None:
            c = cfgs[id(fn)] = build_cfg(fn)
        return c

    for func in fns:
        _rp13_function(func, tree, fns, carriers, cfg_of, out)
    out.sort()
    return out


def _rp13_caller_fsyncs_dir(func_name: str, fns, carriers, cfg_of) -> bool:
    """Some same-module caller of ``func_name`` has a directory fsync
    reachable after the call site — the helper delegates durability of
    the parent directory to its callers (the ``_write_npy_atomic``
    pattern: ``save_index`` commits the manifest, whose commit fsyncs
    the directory)."""
    for g in fns:
        cfg_g = cfg_of(g)
        call_nodes, dir_nodes = set(), set()
        for node in cfg_g.nodes:
            for sub in shallow_walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                if _call_last(sub) == func_name:
                    call_nodes.add(node.idx)
                if _rp13_is_dirfsync(sub, carriers):
                    dir_nodes.add(node.idx)
        for c in call_nodes:
            if node_reachable_without(cfg_g, c, dir_nodes, set()):
                return True
    return False


def _rp13_function(func, tree, fns, carriers, cfg_of, out) -> None:
    cfg = cfg_of(func)
    replace_nodes: Dict[int, ast.Call] = {}
    fsync_nodes: Set[int] = set()
    flush_nodes: Set[int] = set()
    dirfsync_nodes: Set[int] = set()
    manifest_nodes: Dict[int, int] = {}
    artifact_nodes: Dict[int, int] = {}
    opens: List[Tuple[int, ast.Call]] = []
    for node in cfg.nodes:
        for sub in shallow_walk(node):
            if not isinstance(sub, ast.Call):
                continue
            name, base = _call_last(sub), _call_base(sub)
            if name == "replace" and base == "os":
                replace_nodes[node.idx] = sub
            elif name == "fsync" and base == "os":
                fsync_nodes.add(node.idx)
            elif name == "flush":
                flush_nodes.add(node.idx)
            if _rp13_is_dirfsync(sub, carriers):
                dirfsync_nodes.add(node.idx)
            if name in _RP13_MANIFEST_CALLEES:
                manifest_nodes[node.idx] = sub.lineno
            elif name in _RP13_ARTIFACT_CALLEES:
                artifact_nodes[node.idx] = sub.lineno
            if isinstance(sub.func, ast.Name) and sub.func.id == "open":
                opens.append((node.idx, sub))

    replaces = list(replace_nodes.values())

    # leg B: raw write to a final path (no tmp→replace staging)
    for _idx, sub in opens:
        mode = None
        if len(sub.args) >= 2 and isinstance(sub.args[1], ast.Constant):
            mode = sub.args[1].value
        for k in sub.keywords:
            if k.arg == "mode" and isinstance(k.value, ast.Constant):
                mode = k.value.value
        if not (isinstance(mode, str) and mode in _RP13_WRITE_MODES):
            continue
        if not sub.args:
            continue
        if _rp13_tmp_path(sub.args[0], replaces):
            continue
        out.append((
            sub.lineno,
            f"raw open(..., {mode!r}) writes the final path in place — "
            "a crash mid-write leaves a torn artifact; write a tmp "
            "sibling, flush+fsync it, then os.replace onto the final "
            "path",
        ))

    # legs A and D: per os.replace
    for r_idx, rcall in replace_nodes.items():
        missing = []
        if node_reachable_without(cfg, cfg.entry, {r_idx}, flush_nodes):
            missing.append("a flush")
        if node_reachable_without(cfg, cfg.entry, {r_idx}, fsync_nodes):
            missing.append("an os.fsync")
        if missing:
            out.append((
                rcall.lineno,
                "os.replace is reachable without "
                + " or ".join(missing)
                + " on the staged tmp file — a crash after the rename "
                "can publish an artifact whose bytes never hit disk; "
                "flush+fsync the tmp handle before replacing",
            ))
            continue
        if not node_reachable_without(cfg, r_idx, dirfsync_nodes, set()):
            fname = getattr(func, "name", "")
            if _rp13_caller_fsyncs_dir(fname, fns, carriers, cfg_of):
                continue
            out.append((
                rcall.lineno,
                "no directory fsync is reachable after this os.replace "
                "— the rename itself can be lost on crash; fsync the "
                "parent directory (or delegate to a caller that does)",
            ))

    # leg C: manifest committed LAST (dominated by every artifact write)
    if manifest_nodes and artifact_nodes:
        dom = dominators(cfg)
        parents = _parents_map(func)
        header_of = {
            id(node.stmt): node.idx
            for node in cfg.nodes
            if node.kind in ("branch", "loop") and node.stmt is not None
        }

        def promote(a_idx: int) -> int:
            # a write under an if/loop is represented by its outermost
            # enclosing header: the header is on every path even when
            # the conditional write is skipped (zero-trip loops,
            # nothing-to-spill branches)
            cur = a_idx
            p = parents.get(cfg.nodes[a_idx].stmt)
            while p is not None and not isinstance(p, _FUNC_NODES):
                if (isinstance(p, (ast.If, ast.For, ast.While))
                        and id(p) in header_of):
                    cur = header_of[id(p)]
                p = parents.get(p)
            return cur

        for m_idx, m_line in manifest_nodes.items():
            for a_idx, a_line in artifact_nodes.items():
                if promote(a_idx) not in dom[m_idx]:
                    out.append((
                        m_line,
                        "manifest commit is not dominated by the "
                        f"chunk/spill write at line {a_line} — the "
                        "manifest must be replaced LAST, after every "
                        "artifact it names is durable, or recovery "
                        "reads a manifest pointing at missing bytes",
                    ))
                    break


# -- RP14: degraded-path audit (ISSUE 20) ------------------------------------

_RP14_RECORDERS = (
    "note_fallback", "record_vmem_oom_retry", "record_dma_fallback",
)
_RP14_MEMO_RE = re.compile(r"no_\w*keys$|degraded", re.I)


def _rp14_degraded_emit(call: ast.Call, degraded: Optional[Set[str]]
                        ) -> Optional[str]:
    """What this call reports to the degraded-path plane: an
    ``EVENTS.<X>`` emit with X consumed by trace_report's
    DEGRADED_EVENTS (any EVENTS attr when ``degraded`` is None), or a
    recorder helper; None when it reports nothing."""
    name = _call_last(call)
    if name in _RP14_RECORDERS:
        return name
    if name == "emit" and call.args:
        a0 = call.args[0]
        if isinstance(a0, ast.Attribute):
            base = _dotted(a0.value)
            if base == "EVENTS" or base.endswith(".EVENTS"):
                if degraded is None or a0.attr in degraded:
                    return f"EVENTS.{a0.attr}"
    return None


def _rp14_memo_add(sub: ast.AST) -> bool:
    return (isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "add"
            and bool(_RP14_MEMO_RE.search(
                _dotted(sub.func.value).split(".")[-1])))


def rule_rp14(tree: ast.Module,
              degraded: Optional[Set[str]] = None
              ) -> List[Tuple[int, str]]:
    """Degraded-path audit: every fallback rung (broad except whose
    body is more than a bare re-raise) must reachably emit a
    DEGRADED_EVENTS-consumed event or call a recorder; classified
    rungs must memoize the degraded key (``_NO_*_KEYS`` / ``*degraded``
    add, CFG-reachable from the handler — the r6 convention); and a
    fallback counter without an adjacent emit is flagged."""
    out: List[Tuple[int, str]] = []
    parents = _parents_map(tree)

    def enclosing_func(n: ast.AST) -> Optional[ast.AST]:
        p = parents.get(n)
        while p is not None and not isinstance(p, _FUNC_NODES):
            p = parents.get(p)
        return p

    for func in (n for n in ast.walk(tree) if isinstance(n, _FUNC_NODES)):
        cfg: Optional[CFG] = None
        for h in (n for n in ast.walk(func)
                  if isinstance(n, ast.ExceptHandler)):
            if enclosing_func(h) is not func:
                continue
            t = h.type
            broad = t is None or (
                isinstance(t, (ast.Name, ast.Attribute))
                and _dotted(t).split(".")[-1] in ("Exception",
                                                  "BaseException"))
            if not broad:
                continue
            if len(h.body) == 1 and isinstance(h.body[0], ast.Raise):
                continue  # a bare re-raise is not a rung
            emits = [
                e for e in (
                    _rp14_degraded_emit(sub, degraded)
                    for sub in ast.walk(h) if isinstance(sub, ast.Call))
                if e is not None
            ]
            if not emits:
                out.append((
                    h.lineno,
                    "fallback rung (broad except that continues) never "
                    "emits a DEGRADED_EVENTS-consumed telemetry event "
                    "or calls a degraded-rung recorder ("
                    + "/".join(_RP14_RECORDERS)
                    + ") — trace_report's doctor cannot see this "
                    "degradation",
                ))
                continue
            classified = any(isinstance(s, ast.Raise) for s in ast.walk(h))
            if not classified:
                continue
            if any(_rp14_memo_add(sub) for sub in ast.walk(h)):
                continue
            if cfg is None:
                cfg = build_cfg(func)
            h_nodes = {
                node.idx for node in cfg.nodes
                if node.stmt is h.body[0]
            }
            memo_nodes = {
                node.idx for node in cfg.nodes
                if any(_rp14_memo_add(sub) for sub in shallow_walk(node))
            }
            reachable = any(
                node_reachable_without(cfg, hn, memo_nodes, set(),
                                       frozenset())
                for hn in h_nodes
            )
            if not reachable:
                out.append((
                    h.lineno,
                    "classified-failure rung re-raises unrecognized "
                    "errors but never memoizes the degraded key — no "
                    "`_NO_*_KEYS`-style / `*degraded` .add() is "
                    "reachable from the handler, so every later call "
                    "re-pays the failed attempt (the r6 convention)",
                ))

    # counter-fallback adjacency: a fallback counter must sit next to
    # the event emit in one of its own enclosing statement blocks
    # (climbing stops at the function boundary, and block scans never
    # descend into nested defs — another function's emit is not
    # adjacency)
    def block_of(node: ast.AST):
        child, p = node, parents.get(node)
        while p is not None:
            if isinstance(child, ast.stmt):
                for field in ("body", "orelse", "finalbody"):
                    blk = getattr(p, field, None)
                    if isinstance(blk, list) and child in blk:
                        return p, blk
            child, p = p, parents.get(p)
        return None, None

    def block_emits(blk) -> bool:
        for st in blk:
            for sub in [st, *_own_nodes(st)]:
                if isinstance(sub, ast.Call) and (
                        _rp14_degraded_emit(sub, degraded) is not None):
                    return True
        return False

    for c in (sub for sub in ast.walk(tree) if isinstance(sub, ast.Call)):
        if not (_call_last(c) == "counter_inc" and c.args
                and isinstance(c.args[0], ast.Constant)
                and isinstance(c.args[0].value, str)
                and "fallback" in c.args[0].value):
            continue
        covered = False
        node: ast.AST = c
        while True:
            owner, blk = block_of(node)
            if blk is None:
                break
            if block_emits(blk):
                covered = True
                break
            if owner is None or isinstance(owner, _FUNC_NODES):
                break
            node = owner
        if not covered:
            out.append((
                c.lineno,
                "fallback counter incremented without an adjacent "
                "degraded-event emit in the same block — counters "
                "aggregate but the doctor's timeline needs the event; "
                "emit the matching EVENTS.* alongside",
            ))
    out.sort()
    return out
