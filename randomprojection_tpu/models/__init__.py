"""Model families (layer L5): the user-facing estimator API.

Classic JL projections (``base``, ``projections``) plus the structured-RP
siblings (``sketch``: sign-RP/SimHash, Count-Sketch) — SURVEY.md §1 configs
1–5.
"""

from randomprojection_tpu.models.base import BaseRandomProjection
from randomprojection_tpu.models.projections import (
    GaussianRandomProjection,
    SparseRandomProjection,
)
from randomprojection_tpu.models.sketch import (
    CountSketch,
    SimHashIndex,
    SignRandomProjection,
    TopKServer,
    cosine_from_hamming,
    pairwise_hamming,
    pairwise_hamming_device,
    pairwise_hamming_sharded,
    topk_bruteforce,
)

__all__ = [
    "BaseRandomProjection",
    "GaussianRandomProjection",
    "SparseRandomProjection",
    "SignRandomProjection",
    "CountSketch",
    "SimHashIndex",
    "TopKServer",
    "pairwise_hamming",
    "pairwise_hamming_device",
    "pairwise_hamming_sharded",
    "cosine_from_hamming",
    "topk_bruteforce",
]
