"""``BaseRandomProjection`` — shared fit/transform machinery (layer L5).

Behavioral contract: sklearn ``BaseRandomProjection``
(``random_projection.py:308-468``), the canonical implementation of the
reference's estimator surface (SURVEY.md §0-§1).  Key semantics preserved:

- ``fit`` uses only ``X.shape`` and dtype, never the values
  (``random_projection.py:373-376``) — so ``fit_schema(n, d)`` fits with no
  data at all, which is what the streaming/distributed path uses.
- ``n_components='auto'`` resolves via the JL bound; raises when the bound
  exceeds ``n_features`` (``:403-409``); a user-fixed ``k > d`` warns
  ``DataDimensionalityWarning`` (``:410-418``).
- Dtype policy: f32→f32, f64→f64, ints promote to f64 (``:386-387``).
- Determinism: same seed ⇒ identical matrix and outputs within a backend
  (``test_random_projection.py:373-383``).

What the reference does *not* have: the ``backend=`` execution seam is
threaded through every operation (``BASELINE.json:5``), and a fitted model
serializes as its ``ProjectionSpec`` (seed + shape + kind), so checkpoints
are a few hundred bytes and backend-portable (SURVEY.md §6).
"""

from __future__ import annotations

import numbers
import warnings
from typing import Optional

import numpy as np

from randomprojection_tpu.backends.base import ProjectionSpec, resolve_backend
from randomprojection_tpu.jl import johnson_lindenstrauss_min_dim
from randomprojection_tpu.utils.validation import (
    DataDimensionalityWarning,
    NotFittedError,
    check_array,
    resolve_transform_dtype,
)

__all__ = ["BaseRandomProjection", "ParamsMixin"]


class ParamsMixin:
    """sklearn-compatible ``get_params``/``set_params``/``clone`` support.

    Parameter names are introspected from ``__init__`` the way sklearn does,
    so subclasses adding constructor params need no override.
    """

    @classmethod
    def _get_param_names(cls):
        import inspect

        sig = inspect.signature(cls.__init__)
        return sorted(
            p.name
            for p in sig.parameters.values()
            if p.name != "self" and p.kind is not p.VAR_KEYWORD
        )

    def get_params(self, deep: bool = True) -> dict:
        """The exact constructor arguments, so ``sklearn.clone(est)``
        reconstructs an identical unfitted estimator (``deep`` accepted for
        interface parity; there are no nested estimators)."""
        return {name: getattr(self, name) for name in self._get_param_names()}

    def set_params(self, **params):
        """In-place parameter update (enables ``clone``, CV composition).
        Unknown names raise."""
        valid = self._get_param_names()
        for name, value in params.items():
            if name not in valid:
                raise ValueError(
                    f"Invalid parameter {name!r} for estimator "
                    f"{type(self).__name__}. Valid parameters are: {valid}."
                )
            setattr(self, name, value)
        return self

    def __repr__(self):
        params = ", ".join(f"{k}={v!r}" for k, v in self.get_params().items())
        return f"{type(self).__name__}({params})"


def _resolve_seed(random_state) -> int:
    """Collapse ``random_state`` to one int seed — the only RNG state kept.

    ``None`` draws fresh OS entropy (so refits differ, like an unseeded
    reference run) but the *drawn* seed is stored, keeping every fitted
    model exactly reproducible and serializable.
    """
    if random_state is None:
        return int(np.random.SeedSequence().generate_state(1)[0])
    if isinstance(random_state, numbers.Integral):
        return int(random_state)
    if isinstance(random_state, np.random.Generator):
        return int(random_state.integers(0, 2**31 - 1))
    if isinstance(random_state, np.random.RandomState):
        return int(random_state.randint(0, 2**31 - 1))
    raise ValueError(
        f"random_state must be None, an int, or a numpy Generator/RandomState; "
        f"got {random_state!r}"
    )


def _feature_names_out(est, input_features=None):
    """Shared ``get_feature_names_out`` body for JL estimators and sketches.

    sklearn ``ClassNamePrefixFeaturesOutMixin`` semantics: validates
    ``input_features`` length against ``n_features_in_`` when given, and
    names outputs ``<classname_lowercase><i>`` (object dtype) — output
    dimensions have no input-feature lineage.
    """
    est._check_is_fitted()
    if input_features is not None and len(input_features) != est.n_features_in_:
        raise ValueError(
            "input_features should have length equal to number of features "
            f"seen during fit ({est.n_features_in_}), got {len(input_features)}"
        )
    prefix = type(est).__name__.lower()
    # one name per actual output column: n_components_ for coordinate
    # estimators (sklearn parity), ceil(k/8) for packed sign codes
    return np.asarray(
        [f"{prefix}{i}" for i in range(est._stream_out_width())], dtype=object
    )


class BaseRandomProjection(ParamsMixin):
    """Shared estimator machinery; subclasses define the matrix kind.

    Parameters (the reference's kwargs surface, kept fixed per BASELINE.json:5)
    ----------
    n_components : int or 'auto'
    eps : float in (0, 1) — JL distortion bound used by ``'auto'``
    compute_inverse_components : bool — precompute ``pinv(R)`` at fit
    random_state : None | int | np.random.Generator | np.random.RandomState
    backend : 'auto' | 'numpy' | 'jax' | ProjectionBackend instance
    backend_options : dict — forwarded to the backend factory
    """

    #: subclasses set: 'gaussian' | 'sparse' | 'rademacher'
    _kind: str = ""
    #: warn when a user-fixed k exceeds d (False for sign-RP: more bits
    #: than input dims is normal LSH usage, not a mistake)
    _warn_on_expand: bool = True

    def __init__(
        self,
        n_components="auto",
        *,
        eps: float = 0.1,
        compute_inverse_components: bool = False,
        random_state=None,
        backend="auto",
        backend_options: Optional[dict] = None,
    ):
        self.n_components = n_components
        self.eps = eps
        self.compute_inverse_components = compute_inverse_components
        self.random_state = random_state
        self.backend = backend
        self.backend_options = backend_options

    # -- subclass hooks ------------------------------------------------------

    def _resolve_density(self, n_features: int) -> Optional[float]:
        """Numeric density for sparse kinds; None otherwise."""
        return None

    # -- fitting -------------------------------------------------------------

    def _resolve_n_components(self, n_samples: int, n_features: int) -> int:
        if self.n_components == "auto":
            k = johnson_lindenstrauss_min_dim(n_samples, eps=self.eps)
            if k <= 0:
                raise ValueError(
                    f"eps={self.eps} and n_samples={n_samples} lead to a target "
                    f"dimension of {k} which is invalid"
                )
            if k > n_features:
                raise ValueError(
                    f"eps={self.eps} and n_samples={n_samples} lead to a target "
                    f"dimension of {k} which is larger than the original space "
                    f"with n_features={n_features}"
                )
            return int(k)
        if not isinstance(self.n_components, numbers.Integral) or isinstance(
            self.n_components, bool
        ):
            raise ValueError(
                f"n_components must be an int or 'auto', got {self.n_components!r}"
            )
        if self.n_components <= 0:
            raise ValueError(
                f"n_components must be strictly positive, got {self.n_components}"
            )
        if self.n_components > n_features and self._warn_on_expand:
            warnings.warn(
                f"The number of components is higher than the number of features: "
                f"n_features < n_components ({n_features} < {self.n_components}). "
                "The dimensionality of the problem will not be reduced.",
                DataDimensionalityWarning,
            )
        return int(self.n_components)

    def fit_schema(self, n_samples: int, n_features: int, dtype=np.float64):
        """Fit from shape/dtype alone — no data touched.

        The reference's fit reads only ``X.shape`` (SURVEY.md §4.1), so this
        is the primitive; ``fit(X)`` delegates here.  This is how streaming
        sources fit: pass the source's schema, never materialize rows.
        """
        if n_samples <= 0:
            raise ValueError(f"n_samples must be strictly positive, got {n_samples}")
        if n_features <= 0:
            raise ValueError(f"n_features must be strictly positive, got {n_features}")

        self._backend = resolve_backend(self.backend, **(self.backend_options or {}))
        k = self._resolve_n_components(n_samples, n_features)
        density = self._resolve_density(n_features)
        out_dtype = resolve_transform_dtype(dtype)
        seed = _resolve_seed(self.random_state)

        self.spec_ = ProjectionSpec(
            kind=self._kind,
            n_components=k,
            n_features=n_features,
            seed=seed,
            density=density,
            dtype=out_dtype.name,
        )
        self.n_components_ = k
        self.n_features_in_ = n_features
        if density is not None:
            self.density_ = density
        self._state = self._backend.materialize(self.spec_)
        if self.compute_inverse_components:
            self.inverse_components_ = self._backend.inverse_components(
                self._state, self.spec_
            )
        return self

    def fit(self, X, y=None):
        """Materialize the projection matrix sized to ``X``'s shape."""
        X = check_array(X, accept_sparse=True)
        n_samples, n_features = X.shape
        return self.fit_schema(n_samples, n_features, dtype=X.dtype)

    # -- inference -----------------------------------------------------------

    def _check_is_fitted(self):
        if not hasattr(self, "spec_"):
            raise NotFittedError(
                f"This {type(self).__name__} instance is not fitted yet. "
                "Call 'fit' with appropriate arguments before using this estimator."
            )

    def _validate_for_transform(self, X, n_expected: int, what: str):
        shape = getattr(X, "shape", None)
        if shape is None or len(shape) != 2:
            X = check_array(X, accept_sparse=True)
            shape = X.shape
        if shape[1] != n_expected:
            raise ValueError(
                f"X has {shape[1]} features, but {type(self).__name__} was fitted "
                f"expecting {n_expected} {what}"
            )
        return X

    def transform(self, X):
        """Project one batch: ``X @ R.T`` via the selected backend."""
        self._check_is_fitted()
        X = self._validate_for_transform(X, self.n_features_in_, "features")
        return self._backend.transform(
            X, self._state, self.spec_, dense_output=self._dense_output()
        )

    def fit_transform(self, X, y=None):
        return self.fit(X).transform(X)

    def inverse_transform(self, Y):
        """Reconstruct ``X̂ = Y @ pinv(R).T`` (``random_projection.py:435-462``)."""
        self._check_is_fitted()
        Y = self._validate_for_transform(Y, self.n_components_, "components")
        inv = getattr(self, "inverse_components_", None)
        if inv is None:
            inv = self._backend.inverse_components(self._state, self.spec_)
        return self._backend.inverse_transform(Y, inv, self.spec_)

    def _dense_output(self) -> bool:
        return True

    def get_feature_names_out(self, input_features=None):
        """Output feature names: ``<classname_lowercase><index>``.

        Matches sklearn's ``ClassNamePrefixFeaturesOutMixin`` naming for
        random projections (``test_random_projection.py:459-481`` asserts
        exactly these strings); projected dimensions have no input-feature
        lineage, so ``input_features`` only participates in validation.
        """
        return _feature_names_out(self, input_features)

    # -- streaming (layer L2) --------------------------------------------------

    def _transform_async(self, X):
        """Transform for the streaming pipeline: may return a lazy device
        handle.  Subclasses overriding ``transform`` must override this to
        match (it is their transform, minus eager host materialization)."""
        self._check_is_fitted()
        X = self._validate_for_transform(X, self.n_features_in_, "features")
        return self._backend.transform_async(
            X, self._state, self.spec_, dense_output=self._dense_output()
        )

    def prepare_batch(self, X):
        """Prefetch-stage hook (``streaming.PrefetchSource(prepare=...)``):
        validate a batch and start its H2D upload from the prefetch worker
        thread, returning an object ``_transform_async`` accepts with no
        further host work — so the transfer overlaps device compute instead
        of serializing in the dispatch path.  Backends without an upload
        step (numpy) return the batch unchanged, making the hook safe to
        wire unconditionally."""
        self._check_is_fitted()
        X = self._validate_for_transform(X, self.n_features_in_, "features")
        prepare = getattr(self._backend, "prepare_batch", None)
        if prepare is None:
            return X
        return prepare(X, self.spec_)

    def _stream_out_dtype(self):
        """Dtype committed stream batches are cast to (None = leave as-is)."""
        return self.spec_.np_dtype

    def _stream_out_width(self) -> int:
        """Column count of streamed output batches."""
        return self.n_components_

    def fit_source(self, source):
        """Fit from a ``RowBatchSource`` schema — zero rows materialized."""
        n_rows, n_features, dtype = source.schema()
        return self.fit_schema(n_rows, n_features, dtype=dtype)

    def transform_stream(self, source, **kwargs):
        """Stream-project a ``RowBatchSource``; see ``streaming.stream_transform``.

        Yields ``(start_row, Y_batch)`` in row order; supports cursor
        checkpoint/resume and double-buffered device feeding.
        """
        from randomprojection_tpu.streaming import stream_transform

        return stream_transform(self, source, **kwargs)

    # -- introspection / persistence ------------------------------------------

    @property
    def components_(self):
        """The projection matrix in backend-native form, shape ``(k, d)``."""
        self._check_is_fitted()
        return self._state

    def components_as_numpy(self):
        """Host copy of R (ndarray, or CSR for the numpy sparse kind)."""
        self._check_is_fitted()
        return self._backend.components_to_numpy(self._state, self.spec_)

    # get_params / set_params / __repr__ come from ParamsMixin
