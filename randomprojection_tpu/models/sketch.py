"""Structured-RP sketch family (SURVEY.md §1 configs 4–5).

- ``SignRandomProjection``: SimHash cosine-LSH.  Project onto k Gaussian
  hyperplanes, keep only sign bits, packed 8-per-byte.  Hamming distance
  between codes estimates the angle: ``cos(θ) ≈ cos(π·hamming/k)``
  (Charikar 2002).  Config 4's "1B×768 embeddings" workload: 256-bit codes
  are 32 bytes/row — the d2h transfer shrinks 96× vs f32 coordinates, so
  packing happens **on device** in the jax backend.
- ``CountSketch``: feature-hashing projection (Charikar-Chen-Farach-Colton;
  the dense-input analog of sklearn ``FeatureHasher`` — see
  ``ops/hashing.py`` for the raw-token hasher).  ``Y[i, h(j)] += s(j)·X[i,j]``
  with pairwise-independent ``h: [d]→[k]`` and sign ``s: [d]→{±1}``.
  Unbiased: ``E[s(j)·Y[h(j)]] = x[j]``; the decode is ``inverse_transform``.

Both keep the estimator surface (fit / fit_schema / transform / seeds) so
they compose with the streaming layer and backends like the JL estimators.
"""

from __future__ import annotations

import numbers
import threading
from typing import Optional

import numpy as np
import scipy.sparse as sp

from randomprojection_tpu.models.base import (
    BaseRandomProjection,
    ParamsMixin,
    _resolve_seed,
)
from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS
from randomprojection_tpu.utils.validation import NotFittedError, check_array

__all__ = [
    "SignRandomProjection",
    "CountSketch",
    "DeviceBatch",
    "SimHashIndex",
    "TopKServer",
    "pairwise_hamming",
    "pairwise_hamming_device",
    "pairwise_hamming_sharded",
    "cosine_from_hamming",
    "topk_bruteforce",
]


class SignRandomProjection(BaseRandomProjection):
    """SimHash: sign bits of a Gaussian projection, packed to uint8.

    ``transform`` returns shape ``(n, ceil(k/8))`` uint8 codes (little-endian
    bit order within each byte; trailing pad bits are zero for every row, so
    they cancel in Hamming distances).  Use ``pairwise_hamming`` /
    ``cosine_from_hamming`` on the codes.
    """

    _kind = "gaussian"  # Gaussian hyperplanes = unbiased angle estimates
    _warn_on_expand = False  # k bits > d dims is normal LSH usage

    def _packed_signs_fn(self):
        """The backend's fused sign path, resolved ONCE per backend (the
        per-batch ``getattr`` re-check was invariant work on the
        streaming dispatch path — ISSUE r9 satellite).  Keyed on backend
        identity so a refit / ``set_params(backend=...)`` re-resolves."""
        cached = self.__dict__.get("_packed_cache")
        if cached is None or cached[0] is not self._backend:
            cached = (
                self._backend,
                getattr(self._backend, "transform_packed_signs", None),
            )
            self.__dict__["_packed_cache"] = cached
        return cached[1]

    def transform(self, X):
        self._check_is_fitted()
        X = self._validate_for_transform(X, self.n_features_in_, "features")
        packed = self._packed_signs_fn()
        if packed is not None:
            return packed(X, self._state, self.spec_)
        y = np.asarray(self._backend.transform(X, self._state, self.spec_))
        return np.packbits(y > 0, axis=-1, bitorder="little")

    def _transform_async(self, X):
        # streaming variant of the override above: keep the packed codes as
        # a lazy device handle where the backend supports it
        self._check_is_fitted()
        X = self._validate_for_transform(X, self.n_features_in_, "features")
        packed = self._packed_signs_fn()
        if packed is not None:
            return packed(X, self._state, self.spec_, materialize=False)
        y = np.asarray(self._backend.transform(X, self._state, self.spec_))
        return np.packbits(y > 0, axis=-1, bitorder="little")

    def _stream_out_dtype(self):
        return np.uint8

    def _stream_out_width(self) -> int:
        return -(-self.n_components_ // 8)  # packed bytes per row

    def inverse_transform(self, Y):
        raise NotImplementedError(
            "Sign codes discard magnitudes; SimHash has no inverse. "
            "Use cosine_from_hamming for similarity estimates."
        )


def pairwise_hamming(A, B=None):
    """Hamming distances between packed sign codes.

    ``A: (n1, nbytes)``, ``B: (n2, nbytes)`` (default ``B=A``) → ``(n1, n2)``
    int32.  Host implementation (np.bitwise_count); use
    ``pairwise_hamming_device`` for bulk scoring of big code sets on TPU.
    """
    A = np.asarray(A, dtype=np.uint8)
    B = A if B is None else np.asarray(B, dtype=np.uint8)
    return (
        np.bitwise_count(A[:, None, :] ^ B[None, :, :]).sum(-1).astype(np.int32)
    )


_HAMMING_TILE_FN = None


def _hamming_counts(a, b):
    """The one device Hamming kernel: XOR + per-byte population count.
    ``a (n1, nbytes)`` × ``b (n2, nbytes)`` uint8 → ``(n1, n2)`` int32.
    Used by the single-device tiler and as the per-shard body of
    ``pairwise_hamming_sharded``."""
    import jax
    import jax.numpy as jnp

    x = jnp.bitwise_xor(a[:, None, :], b[None, :, :])
    return jax.lax.population_count(x).astype(jnp.int32).sum(-1)


def _hamming_tile_fn():
    global _HAMMING_TILE_FN
    if _HAMMING_TILE_FN is None:
        import jax

        _HAMMING_TILE_FN = jax.jit(_hamming_counts)
    return _HAMMING_TILE_FN


def pairwise_hamming_device(A, B=None, *, tile: int = 2048):
    """Device bulk Hamming: XOR + ``lax.population_count``, tiled over A.

    ``A (n1, nbytes)`` uint8 vs ``B (n2, nbytes)`` → ``(n1, n2)`` int32.
    One-shot convenience over ``SimHashIndex`` (which holds ``B`` resident
    across calls — use it directly when querying repeatedly): serves query
    batches against an index that fits HBM (n2·nbytes ≲ GBs) with n1
    arbitrarily large via ``tile``.  For an index beyond one chip's HBM,
    use ``pairwise_hamming_sharded`` / ``SimHashIndex(mesh=...)``.
    """
    A = np.asarray(A, dtype=np.uint8)
    return SimHashIndex(A if B is None else B).query(A, tile=tile)


def pairwise_hamming_sharded(A, B=None, *, mesh, data_axis: str = "data",
                             tile: int = 2048):
    """Device Hamming with the index ``B`` row-sharded over a mesh.

    The config-4 scale-out ``pairwise_hamming_device`` defers to: an index
    too large for one chip's HBM (1B×32B codes = 32 GB) shards its rows
    over ``data_axis`` — each device holds ``B[n2/p]`` and scores every
    query tile against its own shard; the ``(n1, n2)`` result assembles on
    the host with zero collectives (the output's column blocks ARE the
    shards).  Queries ``A`` stream through in ``tile``-row chunks,
    replicated to all devices.

    One-shot convenience: each call pads and re-ships ``B``.  For repeated
    queries construct ``SimHashIndex(B, mesh=mesh)`` once and reuse it —
    this function is that, inlined.
    """
    A = np.asarray(A, dtype=np.uint8)
    return SimHashIndex(
        A if B is None else B, mesh=mesh, data_axis=data_axis
    ).query(A, tile=tile)


def cosine_from_hamming(hamming, n_bits: int):
    """SimHash estimate: ``cos(π · hamming / k)`` (Charikar 2002)."""
    return np.cos(np.pi * np.asarray(hamming, dtype=np.float64) / n_bits)


def _host_topk_select(D, m: int):
    """Exact host top-``m`` of a dense distance matrix under the
    (distance, lower-global-id) total order — the single source of the
    tie-policy encoding, shared by ``topk_bruteforce``, the test suite,
    and ``query_topk``'s dense fallback, so the policy cannot drift."""
    D = np.asarray(D).astype(np.int64)
    shift = max(int(D.shape[1]).bit_length(), 1)
    key = (D << shift) | np.arange(D.shape[1], dtype=np.int64)[None, :]
    sel = np.argsort(key, axis=1, kind="stable")[:, :m]
    return (
        np.take_along_axis(D, sel, axis=1).astype(np.int32),
        sel.astype(np.int32),
    )


def topk_bruteforce(A, B, m: int):
    """Host reference for ``SimHashIndex.query_topk``: exact top-``m``
    under the documented (distance, lower-global-id) total order.

    O(n_queries · n_codes) host work — verification and small data only."""
    return _host_topk_select(pairwise_hamming(A, B), m)


def _scan_clamp(blk: int, m_c: int, sentinel: int):
    """Packed-key bound of the RETAINED scan path only.  The scan's
    selection packs ``dist·(m_c+blk) + position`` into one int32, so its
    block shrinks until the key fits; when even the floor block cannot
    represent the request the scan path cannot serve it.  The fused
    Pallas kernel (``ops/topk_kernels.py``) — the default single-device
    path since ISSUE 7 — keeps distance and index as SEPARATE carries
    and has no such ceiling; this bound now matters only for the mesh
    path, explicit ``topk_impl='scan'``, and the VMEM-OOM degraded
    retry.  Returns ``(clamped_blk, fits)``."""
    while blk > 8 and (sentinel + 1) * (m_c + blk) >= 2**31:
        blk //= 2
    width = m_c + blk
    return blk, sentinel * width + width < 2**31


def _start_host_copy(handle) -> None:
    """Start the device→host transfer of a lazy result handle without
    blocking (no-op for handles that cannot, e.g. numpy results): the
    later ``np.asarray`` then reuses the fetched copy instead of paying
    the full transfer on the critical path."""
    copy = getattr(handle, "copy_to_host_async", None)
    if copy is not None:
        copy()


class _IndexChunk:
    """One device-resident block of packed codes: ``b`` is ``(rows_pad,
    n_bytes)`` uint8 (row-sharded over the mesh when the index has one),
    ``n`` the real row count (pad rows are trailing zeros), ``row0`` the
    global id of the chunk's first row.  ``dead_dev``/``dead_rev`` cache
    the chunk's device-resident tombstone mask (None = no deleted rows
    in this chunk) against the index's tombstone revision."""

    __slots__ = ("b", "n", "row0", "dead_dev", "dead_rev")

    def __init__(self, b, n: int, row0: int = 0):
        self.b = b
        self.n = n
        self.row0 = row0
        self.dead_dev = None
        self.dead_rev = -1


class SimHashIndex:
    """A persistent device-resident SimHash code index (config 4 serving).

    ``pairwise_hamming_sharded`` is a per-call demo: it re-pads and
    re-ships the whole index ``B`` to the device(s) on every call — at the
    BL:10 scale (1B×32 B codes = 32 GB) that is a full-index host copy and
    reshard per query batch.  This class is the serving primitive: the
    codes are padded, uploaded, and (on a mesh) row-sharded ONCE at
    construction; every ``query`` reuses the resident shards and ships
    only the query tile, so steady-state traffic is queries + scores.

    - ``mesh=None``: ``B`` lives whole on the default device (fits-HBM
      regime of ``pairwise_hamming_device``).
    - ``mesh=...``: ``B`` row-shards over ``data_axis``; each device scores
      every query tile against its own shard and the ``(n1, n2)`` result
      assembles on the host with zero collectives (the output's column
      blocks ARE the shards).

    Codes live in device-resident CHUNKS: the constructor uploads one bulk
    chunk, and every ``add`` uploads ONLY the new codes as a fresh chunk —
    O(new) transfer, no host copy of the index, no reshard of the resident
    codes (VERDICT r4 weak #4: the previous rebuild-on-add shipped the
    whole index per append).  Queries score all chunks; global code ids
    are assigned in insertion order across chunks.  Many tiny ``add``\\ s
    accumulate per-chunk dispatch overhead — batch appends where possible.

    ``query`` returns the full ``(n_queries, n_codes)`` distance matrix —
    fine for analysis, fatal at serving scale (one 2048-row tile against
    1B codes is 8 TB d2h).  The serving path is ``query_topk``: the
    top-``m`` candidates are selected ON DEVICE and only ``O(m)`` values
    per query cross the host boundary.

    Capacity: at most ``2**31 - 1`` codes per index — device ids are
    int32 end to end, so ``add`` refuses past that rather than silently
    wrapping ids.  This is a PER-SHARD invariant, not the ceiling of the
    system: ``serving.ShardedSimHashIndex`` row-shards a corpus over
    many of these indexes (one per device) and widens ids to int64 at
    its merge boundary, so the aggregate corpus is bounded by devices,
    not by int32.

    ``device=`` pins every upload and query tile to one specific
    device (``jax.Device``) instead of the platform default — the
    per-shard placement the sharded tier is built from; ``label`` names
    the index in capacity errors so a full shard identifies itself.

    Thread-safety: queries may run concurrently with each other, but
    MUTATION (``add``/``delete``/``compact``) requires the index to be
    quiescent — no query in flight on another thread.  Serving stacks
    coordinate externally (e.g. drain a ``TopKServer`` before
    compacting).
    """

    _TOPK_IMPLS = ("auto", "fused", "scan")

    def __init__(self, codes, *, mesh=None, data_axis: str = "data",
                 n_bits: Optional[int] = None, topk_impl: str = "auto",
                 device=None, label: Optional[str] = None,
                 hbm_budget_bytes: Optional[int] = None,
                 cold_tier: str = "host", cold_dir: Optional[str] = None):
        if topk_impl not in self._TOPK_IMPLS:
            raise ValueError(
                f"topk_impl must be one of {self._TOPK_IMPLS}, "
                f"got {topk_impl!r}"
            )
        if device is not None and mesh is not None:
            raise ValueError(
                "device= pins a single-device index; it cannot combine "
                "with mesh= (one index is one shard OR one shard_map span)"
            )
        if hbm_budget_bytes is not None and mesh is not None:
            raise ValueError(
                "hbm_budget_bytes= tiers a single-device index; the mesh "
                "path shards residency across devices instead (tier the "
                "per-shard indexes of serving.ShardedSimHashIndex)"
            )
        self.mesh = mesh
        self.data_axis = data_axis
        self.device = device
        self.label = label
        # 'auto' = the fused Pallas kernel wherever it can serve (the
        # default device path; interpreter-mode off-TPU), scan for the
        # mesh path and degraded retries; 'scan' pins the retained
        # lax.scan reference path; 'fused' insists on the kernel where
        # plannable (still degrading to scan on VMEM OOM rather than
        # failing a serving request).  RP_TOPK_IMPL overrides per
        # process.
        self.topk_impl = topk_impl
        # fused-kernel degraded-retry memo: (nq, rows_pad, m_c) keys
        # that hit a scoped-VMEM OOM once are served by the scan path
        # for the process lifetime (r6 convention: memoize only after
        # the degraded retry succeeded — see _chunk_topk)
        self._fused_degraded: set = set()
        self._scan_fallback_noted: set = set()
        codes = np.asarray(codes, dtype=np.uint8)
        if codes.ndim != 2:
            raise ValueError(f"codes must be (n, nbytes), got {codes.shape}")
        self.n_bytes = codes.shape[1]
        # ragged k (e.g. 20 bits in 3 bytes): pad bits are zero in every
        # code so they cancel in Hamming, but the cosine estimate must
        # divide by the REAL bit count
        self.n_bits = self.n_bytes * 8 if n_bits is None else int(n_bits)
        if not 0 < self.n_bits <= self.n_bytes * 8:
            raise ValueError(
                f"n_bits={self.n_bits} outside (0, {self.n_bytes * 8}]"
            )
        self._chunks: list = []
        self.n_codes = 0
        self._topk_fns: dict = {}
        # tombstone bitmap (ISSUE 6): None until the first delete(); a
        # host bool array over global ids afterwards.  _dead_rev
        # invalidates the per-chunk device mask caches on mutation.
        self._dead: Optional[np.ndarray] = None
        self._n_deleted = 0
        self._dead_rev = 0
        # tiered hot/cold residency (ISSUE 19 / r21): None = every chunk
        # device-resident (the pre-r21 path, zero new cost); set = chunks
        # past the HBM budget live host- or disk-resident and the serving
        # paths stream their candidate rows H2D under the hot-tier kernel
        # (see tiering.TieredResidency)
        self._tier = None
        if hbm_budget_bytes is not None:
            from randomprojection_tpu.tiering import TieredResidency

            self._tier = TieredResidency(
                int(hbm_budget_bytes), cold_tier=cold_tier,
                cold_dir=cold_dir, device_put=self._device_queries,
            )
        if codes.shape[0]:
            self._upload_chunk(codes)

    def _codes_appended(self, codes: np.ndarray, row0: int) -> None:
        """Subclass hook: host ``codes`` just became global rows
        ``[row0, row0 + len(codes))`` of this index (every append path —
        construction, ``add``, snapshot restore, compaction re-upload —
        funnels through ``_upload_chunk`` and lands here).  The
        multi-probe LSH tier (``ann.LSHSimHashIndex``) folds the new
        rows into its banded bucket index from this hook; the base
        index keeps no derived structures."""

    def _upload_chunk(self, codes):
        import jax
        import jax.numpy as jnp

        n = codes.shape[0]
        if self.n_codes + n >= 2**31:
            # every device-side id (row0, local_ids, best_i) and the
            # returned idx are int32: past 2^31-1 codes, local ids would
            # silently wrap and query_topk would return wrong neighbors.
            # The per-index bound is deliberate — the beyond-int32 growth
            # story is ShardedSimHashIndex, whose GLOBAL ids are int64
            # while each shard keeps int32 locals — so refuse loudly
            # here, naming the shard when this index is one.
            who = (
                f"SimHashIndex {self.label!r}" if self.label
                else "SimHashIndex"
            )
            raise ValueError(
                f"{who} is limited to 2**31 - 1 codes (int32 device-local "
                f"ids); have {self.n_codes}, adding {n} would overflow. "
                "Grow past int32 by sharding over more devices "
                "(serving.ShardedSimHashIndex keeps global ids int64 and "
                "this bound per shard)"
            )
        hot = True
        if self.mesh is None:
            if self._tier is not None and not self._tier.admit(codes.nbytes):
                # past the HBM budget: the chunk lands cold (host array
                # or checksummed disk spill) and its candidate rows
                # stream H2D per query instead of residing
                b = self._tier.place_cold(codes)
                hot = False
            elif self.device is not None:
                b = jax.device_put(codes, self.device)
            else:
                b = jnp.asarray(codes)
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            p = self.mesh.shape[self.data_axis]
            pad = -n % p
            if pad:
                codes = np.pad(codes, ((0, pad), (0, 0)))
            # host numpy straight into the sharded device_put: routing
            # through jnp.asarray would materialize the WHOLE chunk on
            # device 0 first — the all-to-device-0 hop, fatal at the
            # beyond-one-HBM scale this class exists for
            b = jax.device_put(
                codes, NamedSharding(self.mesh, P(self.data_axis, None))
            )
        chunk = _IndexChunk(b, n, self.n_codes)
        self._chunks.append(chunk)
        if self._tier is not None:
            self._tier.register(chunk, n * self.n_bytes, hot)
        if self._dead is not None:
            self._dead = np.concatenate(
                [self._dead, np.zeros(n, dtype=bool)]
            )
        row0 = self.n_codes
        self.n_codes += n
        # codes[:n] is the pre-pad host view: mesh padding above never
        # reaches derived structures (pad rows have no global id)
        self._codes_appended(codes[:n], row0)

    def add(self, codes):
        """Append codes as a new resident chunk — ships only the new rows."""
        codes = np.asarray(codes, dtype=np.uint8)
        if codes.ndim != 2 or codes.shape[1] != self.n_bytes:
            raise ValueError(
                f"codes must be (n, {self.n_bytes}), got {codes.shape}"
            )
        if codes.shape[0]:
            self._upload_chunk(codes)
        return self

    # -- online mutation: tombstones + compaction (ISSUE 6) ------------------

    @property
    def n_deleted(self) -> int:
        """Codes tombstoned by ``delete`` and not yet folded by
        ``compact``."""
        return self._n_deleted

    @property
    def n_live(self) -> int:
        """Codes that can still win a query: ``n_codes - n_deleted``."""
        return self.n_codes - self._n_deleted

    def delete(self, ids) -> int:
        """Tombstone codes by global id; returns how many were newly
        deleted (already-deleted ids are idempotent).

        Deleted codes keep their global ids (no renumbering) but are
        filtered inside ``query_topk``'s selection — on the device path
        their distances are masked to the sentinel before the scanned
        top-k, on the dense-fallback path their columns are masked
        before host selection — so a deleted code can never appear in a
        result.  The plain ``query``/``query_cosine`` distance matrices
        still cover every id (analysis surface; the column layout IS
        the id space).  ``compact()`` folds tombstones and reclaims the
        device memory; ``save()`` persists the bitmap in the snapshot
        manifest.
        """
        ids = np.atleast_1d(np.asarray(ids))
        if ids.size == 0:
            return 0
        if not np.issubdtype(ids.dtype, np.integer):
            raise ValueError(
                f"delete ids must be integers, got dtype {ids.dtype}"
            )
        # dedupe before counting: duplicate ids in one call would each
        # count as "newly deleted" while the bitmap flips once, skewing
        # n_deleted/n_live and making the saved manifest's deleted count
        # disagree with its own bitmap (an unloadable snapshot)
        ids = np.unique(ids)
        lo, hi = int(ids.min()), int(ids.max())
        if lo < 0 or hi >= self.n_codes:
            raise ValueError(
                f"delete ids must be in [0, {self.n_codes}), got "
                f"[{lo}, {hi}]"
            )
        if self._dead is None:
            self._dead = np.zeros(self.n_codes, dtype=bool)
        newly = int(np.count_nonzero(~self._dead[ids]))
        if newly:
            self._dead[ids] = True
            self._n_deleted += newly
            self._dead_rev += 1  # invalidate per-chunk device masks
        return newly

    def _chunk_dead_device(self, chunk):
        """The chunk's device-resident tombstone mask ``(rows_pad,)``
        uint8 (1 = deleted), or None when the chunk has no deleted rows
        — the unmasked (pre-tombstone) kernel then serves it at zero
        overhead.  Cached per chunk against ``_dead_rev``."""
        if self._dead is None:
            return None
        if chunk.dead_rev == self._dead_rev:
            return chunk.dead_dev
        sl = self._dead[chunk.row0 : chunk.row0 + chunk.n]
        if not sl.any():
            dev = None
        else:
            mask = np.zeros(chunk.b.shape[0], dtype=np.uint8)
            mask[: chunk.n] = sl
            if self.mesh is None:
                dev = self._device_queries(mask)
            else:
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P

                dev = jax.device_put(
                    mask, NamedSharding(self.mesh, P(self.data_axis))
                )
        chunk.dead_dev = dev
        chunk.dead_rev = self._dead_rev
        return dev

    def _device_queries(self, a_np):
        """Upload one host operand to wherever this index lives: the
        pinned ``device`` when set (per-shard placement), else the
        platform default.  The jitted kernels follow the committed
        operands, so a pinned index computes entirely on its own device
        with no cross-device hops."""
        import jax
        import jax.numpy as jnp

        if self.device is not None:
            return jax.device_put(a_np, self.device)
        return jnp.asarray(a_np)

    def _fetch_chunk_host(self, chunk) -> np.ndarray:
        """Host copy of one chunk's REAL rows — a deliberate full-chunk
        d2h used only by the cold snapshot/compact paths, never per
        query (the serving paths overlap their fetches instead)."""
        _start_host_copy(chunk.b)
        return np.asarray(chunk.b)[: chunk.n]

    def compact(self) -> np.ndarray:
        """Fold tombstones and merge every chunk into ONE resident
        chunk; returns the old global ids of the surviving codes in
        their new id order (``new id i`` was ``mapping[i]``; the
        identity when nothing was deleted).

        Two costs this pays down at once: deleted codes stop occupying
        HBM and scan steps, and a finely-chunked index (e.g. one chunk
        per streamed ingest batch — the 1000-batch stream that built a
        1000-dispatch query) collapses to a single dispatch per query
        tile.  Host-side rebuild: O(n_codes · n_bytes) host memory and
        one full re-upload — a maintenance operation, not a serving-path
        one.  Global ids are reassigned compactly; callers holding old
        ids translate through the returned mapping.

        NOT safe under concurrent queries: like ``add``/``delete``, the
        index must be quiescent while mutating — a ``query_topk`` racing
        the rebuild could observe the empty intermediate state or return
        ids under the pre-compaction numbering.  With a ``TopKServer``
        on this index, ``close()`` it (drain) before compacting and
        start a fresh server after.
        """
        parts = [self._fetch_chunk_host(c) for c in self._chunks]
        codes = (
            np.concatenate(parts, axis=0)
            if parts
            else np.empty((0, self.n_bytes), np.uint8)
        )
        if self._dead is not None:
            mapping = np.flatnonzero(~self._dead).astype(np.int64)
            codes = codes[~self._dead]
        else:
            mapping = np.arange(self.n_codes, dtype=np.int64)
        self._rebuild_from_host(codes)
        return mapping

    def _rebuild_from_host(self, codes: np.ndarray) -> None:
        """Replace every resident chunk with ONE chunk holding
        ``codes`` and clear the tombstone state — the device-side half
        of ``compact()``, also called by maintenance paths that already
        hold the compacted host array (the durable-ingest compactor
        reads it back from its committed spill files, skipping the
        device fetch ``compact()`` would pay).  The caller guarantees
        ``codes`` is the live code set in id order."""
        old_n, old_chunks = self.n_codes, len(self._chunks)
        if self._tier is not None:
            # forget residency (and unlink this generation's spill
            # files) before the re-upload re-registers the new chunk —
            # the caller already guarantees quiescence here
            self._tier.reset()
        self._chunks = []
        self.n_codes = 0
        self._dead = None
        self._n_deleted = 0
        self._dead_rev += 1
        if codes.shape[0]:
            self._upload_chunk(np.ascontiguousarray(codes))
        telemetry.registry().counter_inc("simhash.compactions")
        telemetry.emit(
            EVENTS.INDEX_COMPACT, chunks_before=old_chunks,
            chunks_after=len(self._chunks), n_codes=self.n_codes,
            dropped=int(old_n - self.n_codes),
        )

    def close(self) -> None:
        """Release background resources: joins the tiered-residency
        worker when one exists (no-op otherwise, idempotent).  Untiered
        indexes need no close; tiered ones should close before process
        exit so in-flight promotions/demotions finish cleanly."""
        if self._tier is not None:
            self._tier.close()

    # -- durable snapshot/restore (ISSUE 6; see durable.py) ------------------

    def save(self, path: str) -> dict:
        """Durable snapshot of the index into directory ``path``:
        per-chunk ``.npy`` spills plus a versioned, checksummed
        ``manifest.json`` committed write-tmp → fsync → ``os.replace``
        (torn states impossible; see ``durable.save_index``).  Returns
        the manifest."""
        from randomprojection_tpu import durable

        return durable.save_index(self, path)

    @classmethod
    def load(cls, path: str, *, mesh=None, data_axis: str = "data"):
        """Restore an index saved by ``save`` (any process, any mesh
        shape): manifest version and per-chunk checksums are verified
        loudly before upload; chunk structure and the tombstone bitmap
        round-trip exactly (see ``durable.load_index``)."""
        from randomprojection_tpu import durable

        return durable.load_index(path, mesh=mesh, data_axis=data_axis)

    def _query_fn(self):
        import jax

        if self.mesh is None:
            # the module-level jitted kernel, shared with
            # pairwise_hamming_device — one compile cache for all indexes
            return _hamming_tile_fn()
        fn = self.__dict__.get("_fn")
        if fn is None:
            from jax.sharding import PartitionSpec as P

            fn = jax.jit(
                jax.shard_map(
                    _hamming_counts, mesh=self.mesh,
                    in_specs=(P(), P(self.data_axis, None)),
                    out_specs=P(None, self.data_axis),
                )
            )
            self.__dict__["_fn"] = fn
        return fn

    def query(self, A, *, tile: int = 2048):
        """Hamming distances ``(n_queries, n_codes)`` against the resident
        index; only the query tiles cross the host↔device boundary.

        Analysis-scale only — the result is dense over the whole index;
        use ``query_topk`` for serving.

        Per-tile d2h is OVERLAPPED (r9): every chunk's scores start their
        ``copy_to_host_async`` at dispatch and materialize one tile
        behind, so the transfer of tile ``i`` rides under tile ``i+1``'s
        compute instead of blocking the dispatch loop."""
        A = self._check_queries(A)
        fn = self._query_fn()
        out = np.empty((A.shape[0], self.n_codes), dtype=np.int32)
        pending: list = []  # [(lo, hi, [per-chunk device handles])]

        def finish(entry):
            lo, hi, handles = entry
            col = 0
            for c, h in zip(self._chunks, handles):
                # rplint: allow[RP03] — d2h already started at dispatch
                out[lo:hi, col : col + c.n] = np.asarray(h)[:, : c.n]
                col += c.n

        for lo in range(0, A.shape[0], tile):
            hi = min(lo + tile, A.shape[0])
            a = self._device_queries(A[lo:hi])
            handles = []
            for c in self._chunks:
                h = fn(a, c.b)
                _start_host_copy(h)
                handles.append(h)
            # per-chunk dispatch count: many tiny add()s accumulate one
            # device dispatch per chunk per tile — this is the counter
            # that makes that cost visible round-over-round
            telemetry.registry().counter_inc(
                "simhash.chunk_dispatches", len(self._chunks)
            )
            if telemetry.enabled():
                telemetry.emit(
                    EVENTS.SIMHASH_QUERY_TILE, queries=int(hi - lo),
                    chunks=len(self._chunks), n_codes=self.n_codes,
                    **telemetry.trace_fields(),
                )
            pending.append((lo, hi, handles))
            if len(pending) >= 2:
                finish(pending.pop(0))
        while pending:
            finish(pending.pop(0))
        return out

    def query_cosine(self, A, *, tile: int = 2048):
        """SimHash cosine estimates against the resident index."""
        return cosine_from_hamming(self.query(A, tile=tile), self.n_bits)

    def _check_queries(self, A):
        A = np.asarray(A, dtype=np.uint8)
        if A.ndim != 2 or A.shape[1] != self.n_bytes:
            raise ValueError(
                f"queries must be (n, {self.n_bytes}), got {A.shape}"
            )
        return A

    # -- serving path: on-device top-k (BL:10, the 1B-code regime) -----------

    # scan-path tuning (the RETAINED reference/mesh path; the fused
    # kernel sizes its own tiles via ops/topk_kernels.plan_fused):
    _TOPK_ROW_BLOCK = 32768  # code rows scored per scan step (dist tile
    # t×32768 f32 ≈ 256 MB at the default query tile — an HBM working set,
    # amortizing one MXU dot per step).  Measured r5 at a 16.7M-code index:
    # 16384 → 1457 q/s, 32768 → 1739 q/s (+19%); 65536 stalls in compile
    # on this box — do not raise without re-probing.
    _TOPK_UNROLL = 8  # scan unroll: on this box a lax.scan iteration costs
    # ~2-3 ms of loop overhead regardless of body size (measured r5 —
    # dwarfing the sub-ms dot+top_k body), so iterations are unrolled to
    # amortize it

    def query_topk(self, A, m: int, *, tile: int = 2048):
        """Top-``m`` nearest codes per query, selected ON DEVICE.

        Returns ``(dist, idx)``, each ``(n_queries, m_eff)`` int32 with
        ``m_eff = min(m, n_live)`` (tombstoned codes neither count nor
        appear — see ``delete``), sorted by ascending Hamming distance.
        Exact ties are broken by the LOWER global code id — a total order,
        so the result is deterministic and identical across mesh shapes,
        chunk layouts, and tiling (each shard's ``lax.top_k`` is stable,
        and a stable per-shard top-m under the (distance, id) order
        contains every global top-m element of that shard).

        The Hamming kernel is an MXU matmul, not a VPU popcount: codes
        unpack to ±1 bf16 on the fly (exact — f32 accumulation of ±1 sums)
        and ``hamming = (bits - s_a·s_bᵀ)/2``.  The fused Pallas kernel
        loops over code blocks INSIDE one dispatch per query tile
        (double-buffered DMA; the scan path iterates the same blocks via
        ``lax.scan``), carrying the running ``(dist, idx)`` top-m in
        VMEM, so the full ``(tile, n_codes)`` distance matrix never
        exists anywhere — HBM holds one block's scores, and d2h per
        query is ``O(p·m)`` (shard candidates), not ``O(n_codes)``.
        Host work is merging ``p·m`` candidates per query.

        Device path (ISSUE 7): the default is the fused Pallas kernel
        (``ops/topk_kernels.py``) — one dispatch per query tile whose
        in-kernel loop streams code blocks through double-buffered DMA
        and merges a running top-m against VMEM-resident ``(dist, idx)``
        carries.  Because distance and index are separate carries (no
        packed ``(dist, position)`` int32 key across the carry), the old
        ``(n_bits+2)·(m+blk) < 2**31`` ceiling is gone: any ``m`` whose
        carry fits VMEM runs on device.  The ``lax.scan`` path is
        retained for the mesh case, ``topk_impl='scan'``, and as the
        VMEM-OOM degraded retry.  Only genuinely host-scale requests —
        ``m`` beyond every VMEM-feasible carry AND beyond the scan
        path's packed key, or codes wider than 2^24 bits (past f32-exact
        Hamming) — fall back to the dense ``query()`` + host selection
        path: same results, same (distance, lower-id) tie order, but d2h
        is the full ``O(n_codes)`` row.
        """
        if not isinstance(m, numbers.Integral) or m <= 0:
            raise ValueError(f"m must be a positive int, got {m!r}")
        A = self._check_queries(A)
        if self.n_codes == 0:
            raise ValueError("query_topk on an empty index")
        if self.n_live == 0:
            raise ValueError(
                "query_topk on an index whose codes are all deleted "
                "(tombstoned); compact() or add() live codes first"
            )
        # m_eff counts LIVE codes only: tombstoned rows are masked to the
        # sentinel distance before selection (device path) or before the
        # host select (dense fallback), so they can never win — and the
        # result width never includes sentinel filler
        m_eff = int(min(m, self.n_live))
        tile_rows = max(int(min(tile, A.shape[0])), 1)
        if self._topk_route(tile_rows, m_eff) == "dense":
            # genuinely host-scale request: no device path (fused OR
            # scan) can represent it — serve dense rather than raising
            telemetry.registry().counter_inc("simhash.topk_dense_fallbacks")
            telemetry.emit(
                EVENTS.SIMHASH_TOPK_DENSE_FALLBACK, m=int(m_eff),
                n_codes=self.n_codes, n_bits=self.n_bytes * 8,
            )
            out_d = np.empty((A.shape[0], m_eff), dtype=np.int32)
            out_i = np.empty((A.shape[0], m_eff), dtype=np.int32)
            dense_sentinel = np.int32(self.n_bytes * 8 + 1)
            for lo in range(0, A.shape[0], tile):
                hi = min(lo + tile, A.shape[0])
                D = self.query(A[lo:hi], tile=tile)
                if self._dead is not None:
                    # tombstoned columns lose every comparison: the same
                    # filtered-selection contract as the device path
                    D[:, self._dead] = dense_sentinel
                # rplint: allow[RP09] — dense fallback IS the host path: query() already materialized D on the host, the helper's asarray is a no-op
                d, i = _host_topk_select(D, m_eff)
                out_d[lo:hi], out_i[lo:hi] = d, i
            return out_d, out_i
        nq = A.shape[0]
        out_d = np.empty((nq, m_eff), dtype=np.int32)
        out_i = np.empty((nq, m_eff), dtype=np.int32)
        # the per-chunk candidate fetch used to block (np.asarray per
        # chunk) INSIDE the dispatch loop, serializing device compute
        # with d2h and the host merge; now every chunk result starts its
        # copy_to_host_async at dispatch and tiles materialize one
        # behind, so tile i's d2h + host merge ride under tile i+1's
        # device compute (r9 — the serving-side half of the ISSUE)
        pending: list = []  # [(lo, hi, [(d_handle, i_handle)])]

        def finish(entry):
            lo, hi, handles = entry
            d, i = self._topk_finish_tile(handles, m_eff)
            out_d[lo:hi] = d
            out_i[lo:hi] = i

        for lo in range(0, nq, tile):
            hi = min(lo + tile, nq)
            pending.append(
                (lo, hi, self._topk_dispatch_tile(A[lo:hi], m_eff))
            )
            if len(pending) >= 2:
                finish(pending.pop(0))
        while pending:
            finish(pending.pop(0))
        return out_d, out_i

    # -- tile-level dispatch/finish halves (shared with the sharded tier) ----

    def _topk_dispatch_tile(self, a_np, m_eff: int) -> list:
        """Dispatch one query tile against every resident chunk and
        START each result's d2h; returns the per-chunk ``(dist, idx)``
        device-handle list for ``_topk_finish_tile``.  The split exists
        so a caller holding MANY single-device indexes — the sharded
        serving tier, one of these per shard device — can fan a tile
        out across all of them before fetching any, overlapping every
        shard's compute (dispatch is async; a dispatch-then-fetch loop
        per shard would serialize the whole mesh)."""
        a = self._device_queries(a_np)
        stager = None
        if self._tier is not None and self._tier.any_cold():
            from randomprojection_tpu.tiering import _TileStager

            stager = _TileStager(
                self._chunks, self._tier, self._device_queries
            )
        handles = []
        for ci, c in enumerate(self._chunks):
            m_c = int(min(m_eff, c.n))
            # the stager resolves a cold chunk to its staged device copy
            # (upload started while the PREVIOUS chunk's kernel ran) and
            # starts the next cold chunk's upload before this kernel
            # dispatches — the H2D streams under the hot-tier compute
            b = stager.resolve(ci) if stager is not None else None
            d, i = self._chunk_topk(a, c, m_c, b=b)
            _start_host_copy(d)
            _start_host_copy(i)
            handles.append((d, i))
        if stager is not None:
            stager.finish(int(a_np.shape[0]))
        telemetry.registry().counter_inc(
            "simhash.chunk_dispatches", len(self._chunks)
        )
        if telemetry.enabled():
            telemetry.emit(
                EVENTS.SIMHASH_TOPK_TILE, queries=int(a_np.shape[0]),
                m=int(m_eff),
                chunks=len(self._chunks), n_codes=self.n_codes,
                **telemetry.trace_fields(),
            )
        return handles

    def _topk_finish_tile(self, handles: list, m_eff: int):
        """Materialize one dispatched tile's per-chunk candidates and
        merge them across chunks under the (distance, lower-id) total
        order.  Returns ``(dist, idx)`` host arrays, each
        ``(tile_rows, m_eff)`` int32 with ``idx`` index-local."""
        # local id shift for the cross-chunk host merge: distances fit
        # n_bits ≤ 2^15 and ids fit int32, so (dist << shift) | id is an
        # exact int64 total-order key
        shift = max(self.n_codes.bit_length(), 1)
        cand_d, cand_i = [], []
        base = 0
        for c, (d, i) in zip(self._chunks, handles):
            # rplint: allow[RP03] — d2h already started at dispatch
            cand_d.append(np.asarray(d))
            # rplint: allow[RP03] — d2h already started at dispatch
            cand_i.append(np.asarray(i).astype(np.int64) + base)
            base += c.n
        d = np.concatenate(cand_d, axis=1)
        i = np.concatenate(cand_i, axis=1)
        # clamp sentinel ids (empty per-shard slots carry id 2^31-1)
        # so they cannot bleed into the dist bits of the merge key;
        # their sentinel dist (> n_bits) already orders them last
        key = (d.astype(np.int64) << shift) | np.minimum(
            i, (1 << shift) - 1
        )
        sel = np.argsort(key, axis=1, kind="stable")[:, :m_eff]
        return (
            np.take_along_axis(d, sel, axis=1),
            np.take_along_axis(i, sel, axis=1).astype(np.int32),
        )

    def _topk_impl_pref(self) -> str:
        """Constructor preference, overridable per process via the
        ``RP_TOPK_IMPL`` environment variable (``fused`` / ``scan`` /
        ``auto``)."""
        import os

        env = os.environ.get("RP_TOPK_IMPL", "").strip().lower()
        return env if env in self._TOPK_IMPLS else self.topk_impl

    def _scan_fits(self, rows_pad: int, m_c: int) -> bool:
        _, fits = _scan_clamp(
            min(self._TOPK_ROW_BLOCK, rows_pad), m_c, self.n_bytes * 8 + 1
        )
        return fits

    def _fused_mode(self, nq: int, rows_pad: int, m_c: int):
        """``(plan, degraded)`` when the fused kernel serves this chunk
        shape, else None.  Normally the auto (largest-feasible) plan;
        once the shape has hit a scoped-VMEM OOM (memoized in
        ``_fused_degraded``) the scan path takes over when it can
        represent the request, and the MINIMAL-VMEM fused tiling serves
        otherwise (over-the-old-ceiling shapes have no scan
        representation to degrade to).  Computed ONCE per dispatch and
        passed through to ``fused_topk`` — the routing and the kernel
        can never disagree on the tiling."""
        from randomprojection_tpu.ops import topk_kernels

        degraded = (nq, rows_pad, m_c) in self._fused_degraded
        if degraded and self._scan_fits(rows_pad, m_c):
            return None
        plan = topk_kernels.plan_fused(
            nq, rows_pad, self.n_bytes, m_c, minimal=degraded
        )
        return None if plan is None else (plan, degraded)

    def _note_scan_fallback(self, nq: int, rows_pad: int, m_c: int):
        """The default route wanted the kernel but the scan path is
        serving (unplannable tiling or a memoized VMEM-OOM): a
        degradation worth a line on the telemetry spine, once per
        shape."""
        key = (nq, rows_pad, m_c)
        if key not in self._scan_fallback_noted:
            self._scan_fallback_noted.add(key)
            telemetry.registry().counter_inc("simhash.topk_scan_fallbacks")
            telemetry.emit(
                EVENTS.TOPK_KERNEL_SCAN_FALLBACK, queries=int(nq),
                m=int(m_c), rows=int(rows_pad),
            )

    def _chunk_impl(self, nq: int, rows_pad: int, m_c: int) -> str:
        """Which device path serves one chunk at one query-tile shape:
        ``'fused'`` (the default Pallas kernel), ``'scan'`` (mesh,
        explicit preference, degraded retry, or an unplannable fused
        shape), or ``'dense'`` when neither device path can represent
        the request (genuinely host-scale ``m`` / pathological code
        width)."""
        pref = self._topk_impl_pref()
        wants_fused = self.mesh is None and pref != "scan"
        if wants_fused and self._fused_mode(nq, rows_pad, m_c) is not None:
            return "fused"
        if not self._scan_fits(rows_pad, m_c):
            return "dense"
        if wants_fused:
            self._note_scan_fallback(nq, rows_pad, m_c)
        return "scan"

    def _topk_route(self, tile_rows: int, m_eff: int) -> str:
        """``'device'`` when every chunk has a device path for this
        request shape, else ``'dense'`` (the host-scale fallback)."""
        for c in self._chunks:
            if self._chunk_impl(
                tile_rows, c.b.shape[0], int(min(m_eff, c.n))
            ) == "dense":
                return "dense"
        return "device"

    def _chunk_topk(self, a, chunk, m_c: int, b=None):
        """Device top-``m_c`` of one chunk for one query tile.  Returns
        ``(dist, local_idx)`` of shape ``(t, m_c)`` (mesh: ``(t, p·m_c)``
        — per-shard candidates, ids already chunk-global).  Pad rows —
        and, when the chunk carries tombstones, deleted rows — are
        masked to an impossible distance before selection; a chunk with
        no deletions runs the exact pre-tombstone kernel variant.

        Default path: the fused Pallas kernel.  A scoped-VMEM OOM at an
        untested shape retries once through the retained scan path
        (``is_vmem_oom`` + ``record_vmem_oom_retry``, the r6 convention)
        and memoizes the key so the shape stays on the scan path for the
        process lifetime."""
        import jax.numpy as jnp

        # b overrides the chunk's resident array (the tiered exact path
        # passes a pre-staged device copy of a cold chunk); shapes are
        # identical by construction, so every route below is unchanged
        if b is None:
            b = chunk.b
        dead = self._chunk_dead_device(chunk)
        nq, rows_pad = a.shape[0], b.shape[0]
        mode = None
        if self.mesh is None and self._topk_impl_pref() != "scan":
            mode = self._fused_mode(nq, rows_pad, m_c)
            if mode is None:
                self._note_scan_fallback(nq, rows_pad, m_c)
        if mode is not None:
            from randomprojection_tpu.ops.pallas_kernels import (
                is_vmem_oom,
                record_vmem_oom_retry,
            )

            plan, degraded = mode
            try:
                return self._dispatch_fused(a, chunk, m_c, dead, plan, b=b)
            except Exception as e:
                if not is_vmem_oom(e) or degraded:
                    # unclassified failures surface; a second OOM at the
                    # MINIMAL tiling means nothing smaller exists on
                    # device for this shape — also surface it (the next
                    # call routes dense via _chunk_impl when scan can't
                    # represent the request either)
                    raise
                # degraded retry (r6 convention): memoize only now —
                # after the failure is classified — so a misclassified
                # error cannot pin the shape to the slow path
                record_vmem_oom_retry(a.shape, "topk_fused", m_c)
                telemetry.emit(
                    EVENTS.TOPK_KERNEL_VMEM_RETRY, queries=int(nq),
                    m=int(m_c), rows=int(rows_pad),
                    **telemetry.trace_fields(),
                )
                self._fused_degraded.add((nq, rows_pad, m_c))
                retry = self._fused_mode(nq, rows_pad, m_c)
                if retry is not None:
                    # scan cannot represent this request (the shapes
                    # the old int32-key ceiling rejected): degrade
                    # WITHIN the kernel to the minimal-VMEM tiling
                    return self._dispatch_fused(
                        a, chunk, m_c, dead, retry[0], b=b
                    )
                # else the scan path serves this dispatch (and this
                # shape, for the process lifetime)
        fn = self._get_topk_fn(
            a.shape, rows_pad, m_c, masked=dead is not None
        )
        if dead is not None:
            return fn(a, b, jnp.int32(chunk.n), dead)
        return fn(a, b, jnp.int32(chunk.n))

    def _dispatch_fused(self, a, chunk, m_c: int, dead, plan, b=None):
        from randomprojection_tpu.ops import topk_kernels

        if b is None:
            b = chunk.b
        d, i = topk_kernels.fused_topk(
            a, b, chunk.n, m_c, dead=dead, plan=plan
        )
        if telemetry.enabled():
            telemetry.emit(
                EVENTS.TOPK_KERNEL_DISPATCH,
                queries=int(a.shape[0]), m=int(m_c),
                rows=int(b.shape[0]),
                masked=dead is not None,
                **telemetry.trace_fields(),
            )
        return d, i

    def _get_topk_fn(self, a_shape, rows_pad: int, m_c: int, *,
                     masked: bool = False):
        import jax
        import jax.numpy as jnp

        key = (tuple(a_shape), rows_pad, m_c, masked)
        fn = self._topk_fns.get(key)
        if fn is not None:
            return fn
        n_bits_total = self.n_bytes * 8
        blk = min(self._TOPK_ROW_BLOCK, rows_pad)
        data_axis = self.data_axis
        p = 1 if self.mesh is None else self.mesh.shape[data_axis]
        rows_local = rows_pad // p

        def unpack_pm1(codes):
            # packed uint8 → ±1 bf16 bits, little-endian within each byte
            # (matches np.packbits(bitorder='little')); exact in bf16
            bits = (
                codes[:, :, None] >> jnp.arange(8, dtype=jnp.uint8)
            ) & jnp.uint8(1)
            bits = bits.reshape(codes.shape[0], n_bits_total)
            return (2.0 * bits.astype(jnp.bfloat16) - 1.0)

        # Selection runs on PACKED int32 keys — key = dist·W + position in
        # the [carry | block] concat — so ``lax.top_k`` is values-only.
        # Measured on this box (r5): top_k that also RETURNS INDICES lowers
        # to a variadic sort at ~15 ms/step vs 0.7 ms/step for the
        # values-only custom TopK — 22× the whole dot+select body.  The
        # position (and from it the global id) is decoded arithmetically
        # from the packed key.  dist ≤ n_bits (sentinel n_bits+1), so the
        # key fits int32 for any practical (bits, block) pair.
        sentinel = n_bits_total + 1
        blk_requested = blk
        blk, fits = _scan_clamp(blk, m_c, sentinel)
        if blk != blk_requested:
            # wide codes / big m shrank the scan block to keep the packed
            # int32 key representable: same results, more scan steps —
            # recorded so a throughput drop has its cause on file
            telemetry.registry().counter_inc("simhash.topk_block_clamps")
            telemetry.emit(
                EVENTS.SIMHASH_TOPK_BLOCK_CLAMP, requested=int(blk_requested),
                clamped=int(blk), m=int(m_c), n_bits=n_bits_total,
            )
        width = m_c + blk  # packing base W
        # the routing (_chunk_impl) never sends an unrepresentable
        # request here — this guards direct callers of the scan builder
        if not fits:  # pragma: no cover
            raise ValueError(
                f"scan-path top-k key would overflow int32: "
                f"bits={n_bits_total}, m={m_c}, block={blk}"
            )

        def local_topk(a, b, n_real, dead=None):
            # a (t, nbytes) uint8, b (rows_local, nbytes) uint8 per shard;
            # dead (rows_local,) uint8 tombstone mask in the masked
            # variant (1 = deleted, filtered like a pad row)
            if self.mesh is None:
                row0 = jnp.int32(0)
            else:
                row0 = jax.lax.axis_index(data_axis) * rows_local
            a_s = unpack_pm1(a)
            nblk = -(-rows_local // blk)
            pad = nblk * blk - rows_local
            if pad:
                b = jnp.pad(b, ((0, pad), (0, 0)))
                if dead is not None:
                    dead = jnp.pad(dead, (0, pad))
            b_blocks = b.reshape(nblk, blk, b.shape[1])
            dead_blocks = (
                None if dead is None else dead.reshape(nblk, blk)
            )
            t = a.shape[0]
            w = jnp.int32(width)
            pos_blk = jnp.arange(blk, dtype=jnp.int32) + m_c

            def step(carry, inp):
                best_key, best_i = carry
                if dead_blocks is None:
                    b_blk, blk_i = inp
                    dead_blk = None
                else:
                    b_blk, blk_i, dead_blk = inp
                s_b = unpack_pm1(b_blk)
                dot = jax.lax.dot_general(
                    a_s, s_b,
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                d = ((n_bits_total - dot) * 0.5).astype(jnp.int32)
                # two pad layers to mask: in-fn block padding is LOCAL to
                # this shard (its global ids would collide with the next
                # shard's real range), upload padding is global-trailing
                local_ids = blk_i * blk + jnp.arange(blk, dtype=jnp.int32)
                ids = row0 + local_ids
                keep = (local_ids[None, :] < rows_local) & (
                    ids[None, :] < n_real
                )
                if dead_blk is not None:
                    # tombstoned rows are filtered in the SELECTION, not
                    # post-hoc: a deleted code can never displace a live
                    # one from the running top-m (ISSUE 6)
                    keep = keep & (dead_blk[None, :] == 0)
                d = jnp.where(keep, d, jnp.int32(sentinel))
                # keys over [carry | block]: the carry keys re-base to
                # position [0, m_c) (they are already (dist, id)-sorted,
                # and their ids are lower than this block's), the block
                # takes positions [m_c, W) in ascending id order — so
                # ascending key IS the (dist, lower-global-id-wins) total
                # order, with no index output needed from top_k
                cat = jnp.concatenate(
                    [
                        (best_key // w) * w
                        + jnp.arange(m_c, dtype=jnp.int32),
                        d * w + pos_blk[None, :],
                    ],
                    axis=1,
                )
                new_key = -jax.lax.top_k(-cat, m_c)[0]
                pos = new_key % w
                # resolve positions to global ids: carry entries gather
                # from the (t, m_c) carry (tiny), block entries are
                # arithmetic off the block offset
                carried = jnp.take_along_axis(
                    best_i, jnp.minimum(pos, m_c - 1), axis=1
                )
                new_i = jnp.where(
                    pos < m_c, carried, ids[0] + (pos - m_c)
                )
                return (new_key, new_i), None

            init = (
                jnp.full((t, m_c), jnp.int32(sentinel) * w, jnp.int32)
                + jnp.arange(m_c, dtype=jnp.int32),
                jnp.full((t, m_c), jnp.int32(2**31 - 1)),
            )
            if self.mesh is not None:
                # the scanned b varies over the mesh axis, so the carry
                # must be marked varying too (shard_map vma tracking)
                init = jax.lax.pcast(init, (data_axis,), to="varying")
            xs = (b_blocks, jnp.arange(nblk, dtype=jnp.int32))
            if dead_blocks is not None:
                xs = xs + (dead_blocks,)
            (best_key, best_i), _ = jax.lax.scan(
                step, init, xs,
                unroll=min(nblk, self._TOPK_UNROLL),
            )
            return best_key // w, best_i

        if self.mesh is None:
            fn = jax.jit(local_topk)
        else:
            from jax.sharding import PartitionSpec as P

            in_specs = (P(), P(data_axis, None), P())
            if masked:
                in_specs = in_specs + (P(data_axis),)
            fn = jax.jit(
                jax.shard_map(
                    local_topk, mesh=self.mesh,
                    in_specs=in_specs,
                    out_specs=(P(None, data_axis), P(None, data_axis)),
                )
            )
        self._topk_fns[key] = fn
        return fn


def _metric_label(label) -> str:
    """Sanitize a client label for use inside a registry metric name
    (``serve.latency.<server>.client.<label>``): any character that is
    not alphanumeric / ``_`` / ``.`` / ``-`` becomes ``_``, capped at
    64 chars so a hostile label cannot explode the metric namespace."""
    import re as _re

    s = _re.sub(r"[^A-Za-z0-9_.\-]", "_", str(label))[:64]
    return s or "_"


class TopKServer:
    """Micro-batching front-end for ``SimHashIndex.query_topk`` (the
    config-4 serving path under concurrent traffic).

    r05 measured serving at 1.7k queries/s and 7.4% MXU: every
    ``query_topk`` call pays the full scan-dispatch overhead however few
    rows it carries, so concurrent small requests leave the device idle
    on dispatch gaps 92% of the time.  The server coalesces them:
    callers ``submit()`` (returns a ``concurrent.futures.Future``) or
    ``query()`` (the blocking wrapper) from any thread; a dispatcher
    thread drains the request queue, stacks up to ``max_batch`` query
    rows into ONE array (waiting at most ``max_delay_s`` for stragglers
    once a request is in hand — latency is bounded, the batch is
    opportunistic), pads the coalesced batch to a row bucket
    (``parallel.sharded.row_bucket`` — one compiled top-k program per
    bucket, not one per request mix) and runs a single ``query_topk``
    dispatch, then scatters each request's result rows back to its
    future.

    Results are identical to per-request ``query_topk`` calls — the
    top-k selection is independent per query row — and rows never
    reorder within a request.  ``m`` is fixed per server (one coalesced
    dispatch serves one ``m``); run one server per (index, m) pair.

    Shutdown: ``close()`` (or leaving the context manager) serves every
    request already submitted, then stops the dispatcher; a
    ``submit()`` after close fails fast.  A request whose batch failed
    on device receives the exception through its future (and the server
    emits a ``serve.topk.error`` event + ``serve.topk.errors`` counter —
    a failing device must not be invisible to telemetry); the server
    itself keeps serving subsequent batches.

    The served index must not be MUTATED (``add``/``delete``/
    ``compact``) while the server is live — the dispatcher queries it
    from its own thread; ``close()`` (drain) first, mutate, then start
    a fresh server.

    Backpressure: the submit queue is BOUNDED (``max_pending``
    requests).  A dispatcher that stalls — a hung device, a wedged
    ``query_topk`` — must surface as a fast, explicit failure at the
    submitting client, not as unbounded host-memory growth in a queue
    nobody is draining: once ``max_pending`` requests are waiting,
    ``submit()`` raises ``RuntimeError`` (counted in
    ``serve.topk.rejects``) instead of enqueueing.

    Tail latency (r17): every request is stamped at enqueue, dispatch
    and completion; the enqueue→complete total (plus the queue-wait and
    on-device components) feeds HDR-style log2-bucket histograms on the
    process registry, keyed per SERVER NAME (``serve.latency.<name>``,
    ``name=`` at construction — two servers sharing a name share
    tallies, like the ``serve.topk.*`` counters always have) and, when
    a request carries a client ``label``, per label
    (``serve.latency.<name>.client.<label>``).  Quantiles
    (p50/p90/p99/p99.9) come out of ``stats()["latency"]``, the
    OpenMetrics exposition and the live metrics endpoint — the first
    honest per-request tail numbers for the serving tier.  Each
    completion also emits a ``serve.latency.request`` event (when
    telemetry is active) for the doctor's latency section.
    """

    _SENTINEL = object()

    def __init__(self, index: "SimHashIndex", m: int, *,
                 max_batch: int = 8192, max_delay_s: float = 0.002,
                 max_pending: int = 8192, name: str = "topk",
                 probe_policy: Optional[dict] = None,
                 start: bool = True):
        if not isinstance(m, numbers.Integral) or m <= 0:
            raise ValueError(f"m must be a positive int, got {m!r}")
        if probe_policy is not None:
            # per-label probe classes (ISSUE 16): label → probes, keyed
            # by the SANITIZED label (submit sanitizes before routing);
            # 0 pins a label onto the exact path.  Requires an index
            # whose query_topk takes ``probes`` (the LSH tier).
            if not isinstance(probe_policy, dict):
                raise ValueError(
                    f"probe_policy must be a dict of label -> probes, "
                    f"got {probe_policy!r}"
                )
            if not hasattr(index, "probes"):
                raise ValueError(
                    "probe_policy requires an LSH-tier index (its "
                    "query_topk must accept probes=); got "
                    f"{type(index).__name__}"
                )
            pol = {}
            for k, v in probe_policy.items():
                if (isinstance(v, bool)
                        or not isinstance(v, numbers.Integral) or v < 0):
                    raise ValueError(
                        f"probe_policy[{k!r}] must be a non-negative "
                        f"int, got {v!r}"
                    )
                pol[_metric_label(k)] = int(v)
            probe_policy = pol
        self.probe_policy = probe_policy
        if not isinstance(max_batch, numbers.Integral) or max_batch < 1:
            raise ValueError(
                f"max_batch must be a positive int, got {max_batch!r}"
            )
        if max_delay_s < 0:
            raise ValueError(
                f"max_delay_s must be >= 0, got {max_delay_s!r}"
            )
        if not isinstance(max_pending, numbers.Integral) or max_pending < 1:
            raise ValueError(
                f"max_pending must be a positive int, got {max_pending!r}"
            )
        if not isinstance(name, str) or not name:
            raise ValueError(f"name must be a non-empty str, got {name!r}")
        self.index = index
        self.m = int(m)
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_s)
        self.max_pending = int(max_pending)
        self.name = name
        # latency histogram key prefix on the PROCESS registry (shared
        # across same-named servers by design, see class doc)
        self._lat_name = f"serve.latency.{name}"
        import queue as _queue

        # bounded: a stalled drain rejects new submits (see class doc)
        # instead of growing the queue without limit (ISSUE r10)
        self._q: "_queue.Queue" = _queue.Queue(maxsize=self.max_pending + 1)
        self._closed = threading.Event()
        # serializes submit's closed-check+put against close's
        # set+sentinel: every accepted request is enqueued AHEAD of the
        # sentinel (FIFO), so the dispatcher's drain always serves it —
        # without this, a submit racing close could land its request
        # after the drain and strand the future forever
        self._submit_lock = threading.Lock()
        # dispatcher-thread-private tallies, published read-only via stats()
        self._batches = 0
        self._requests = 0
        self._queries = 0
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "TopKServer":
        if self._closed.is_set():
            # a closed server must stay closed: starting a dispatcher
            # over a queue whose sentinel already drained would strand
            # every future submitted through the race window
            raise RuntimeError(
                "server closed: cannot start() a closed TopKServer — "
                "construct a new one"
            )
        if self._thread is not None:
            raise RuntimeError("TopKServer already started")
        self._thread = threading.Thread(
            target=self._run, name="rp-topk-server", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        """Drain-and-stop: requests already submitted are still served."""
        with self._submit_lock:
            if self._closed.is_set():
                return
            self._closed.set()
            # rplint: allow[RP11] — never blocks by construction: the queue is sized max_pending + 1 and submit() bounds occupancy to max_pending under this same lock, so the sentinel's extra slot is always free
            self._q.put(self._SENTINEL)
        if self._thread is not None:
            self._thread.join()

    def __enter__(self) -> "TopKServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- request surface ----------------------------------------------------

    def submit(self, codes, *, label: Optional[str] = None):
        """Enqueue one request of packed codes ``(rows, n_bytes)`` (a 1-D
        code is one row) and return a Future resolving to that request's
        ``(dist, idx)`` — each ``(rows, m_eff)`` int32, identical to a
        direct ``query_topk`` call.  ``label`` tags the request with a
        client identity for the per-label latency histograms
        (``serve.latency.<server>.client.<label>``); sanitized to
        metric-name-safe characters."""
        import time as _time

        from concurrent.futures import Future

        t_enq = _time.perf_counter()
        if label is not None:
            label = _metric_label(label)
        codes = np.asarray(codes, dtype=np.uint8)
        if codes.ndim == 1:
            codes = codes[None, :]
        codes = self.index._check_queries(codes)
        if codes.shape[0] == 0:
            raise ValueError("empty request (0 query rows)")
        fut: Future = Future()
        with self._submit_lock:
            if self._closed.is_set():
                # fail fast and say so: the dispatcher is (or will be)
                # gone, so enqueueing would strand the future forever
                raise RuntimeError(
                    "server closed: TopKServer.submit() after close() — "
                    "the dispatcher no longer drains the queue"
                )
            # submits are serialized by the lock and the dispatcher only
            # drains, so this check is the bound: the queue can never
            # exceed max_pending requests, and close()'s sentinel always
            # fits in the reserved extra slot without blocking
            if self._q.qsize() >= self.max_pending:
                telemetry.registry().counter_inc("serve.topk.rejects")
                raise RuntimeError(
                    f"TopKServer submit queue is full (max_pending="
                    f"{self.max_pending} requests waiting; the dispatcher "
                    "is not draining — device hung or server overloaded)"
                )
            self._q.put_nowait((codes, fut, label, t_enq))
        return fut

    def query(self, codes, *, label: Optional[str] = None):
        """Blocking convenience: ``submit(codes).result()``."""
        return self.submit(codes, label=label).result()

    def stats(self) -> dict:
        """Coalescing tallies: served batches/requests/queries, the
        mean rows per coalesced dispatch, and (once any request has
        completed) the enqueue→complete latency quantiles."""
        # rplint: allow[RP10] — dispatcher-private monotone int tallies: rebinds are GIL-atomic and stats() is a best-effort snapshot (cross-field staleness acceptable by contract, see the __init__ comment)
        b, r, q = self._batches, self._requests, self._queries
        out = {
            "batches": b,
            "requests": r,
            "queries": q,
            "rows_per_batch_mean": round(q / b, 2) if b else 0.0,
        }
        lat = telemetry.registry().hist_quantiles(self._lat_name)
        if lat is not None:
            out["latency"] = lat
        return out

    # -- dispatcher ---------------------------------------------------------

    def _pick_index(self):
        """The index one coalesced dispatch runs against.  Hook for the
        sharded tier: ``serving.ShardedTopKServer`` overrides this to
        round-robin across replica groups (dispatcher-thread-only, so
        no locking)."""
        return self.index

    def _batch_served(self, index, rows: int, padded: int,
                      requests: int, wall: float) -> None:
        """Post-success hook per coalesced dispatch (dispatcher thread).
        The base server's accounting lives in ``_serve``; the sharded
        tier adds its ``serve.shard.*`` counters and routing event
        here."""

    def _collect(self, first):
        """One coalesced batch: ``first`` plus whatever arrives within
        ``max_delay_s``, capped at ``max_batch`` rows.  Returns
        ``(requests, saw_sentinel)``."""
        import queue as _queue
        import time as _time

        batch = [first]
        rows = first[0].shape[0]
        deadline = _time.perf_counter() + self.max_delay_s
        while rows < self.max_batch:
            remaining = deadline - _time.perf_counter()
            if remaining <= 0:
                break
            try:
                item = self._q.get(timeout=remaining)
            except _queue.Empty:
                break
            if item is self._SENTINEL:
                return batch, True
            batch.append(item)
            rows += item[0].shape[0]
        return batch, False

    def _serve(self, batch) -> None:
        """Run one coalesced dispatch (per probe class when a
        ``probe_policy`` is set — labels with different probe budgets
        cannot share a candidate dispatch) and scatter results."""
        if self.probe_policy is None:
            self._serve_group(batch, None)
            return
        groups: dict = {}
        for req in batch:
            p = self.probe_policy.get(req[2]) if req[2] is not None else None
            groups.setdefault(p, []).append(req)
        for p, group in groups.items():
            self._serve_group(group, p)

    def _serve_group(self, batch, probes: Optional[int]) -> None:
        """One coalesced dispatch for one probe class and its futures."""
        import time as _time

        from randomprojection_tpu.parallel.sharded import row_bucket

        arr = (
            batch[0][0]
            if len(batch) == 1
            else np.concatenate([req[0] for req in batch], axis=0)
        )
        n = arr.shape[0]
        # bucket-pad the coalesced rows so the jitted top-k compiles one
        # program per bucket, not one per traffic mix (pad rows are
        # scored and discarded: ≤25% extra compute, zero extra compiles)
        pad_to = row_bucket(n)
        if pad_to != n:
            arr = np.pad(arr, ((0, pad_to - n), (0, 0)))
        index = self._pick_index()
        t0 = _time.perf_counter()
        try:
            # only pass probes when a policy resolved one: the base
            # exact index has no probes kwarg, and the LSH default
            # should keep serving unlabeled traffic
            if probes is None:
                d, i = index.query_topk(arr, self.m, tile=pad_to)
            else:
                d, i = index.query_topk(
                    arr, self.m, tile=pad_to, probes=probes
                )
        except BaseException as e:
            # the exception reaches every caller through its future, but
            # an unobserved future would swallow it silently — record the
            # failed dispatch on the telemetry spine too (ISSUE r10 audit)
            telemetry.registry().counter_inc("serve.topk.errors")
            telemetry.emit(
                EVENTS.SERVE_TOPK_ERROR, error=repr(e), rows=int(n),
                requests=len(batch), m=int(self.m),
            )
            for req in batch:
                fut = req[1]
                if fut.set_running_or_notify_cancel():
                    fut.set_exception(e)
            return
        wall = _time.perf_counter() - t0
        self._batches += 1
        self._requests += len(batch)
        self._queries += n
        telemetry.registry().counter_inc("serve.topk.batches")
        telemetry.registry().counter_inc("serve.topk.requests", len(batch))
        telemetry.registry().counter_inc("serve.topk.queries", n)
        telemetry.registry().gauge_set("serve.topk.batch_rows", n)
        if telemetry.enabled():
            telemetry.emit(
                EVENTS.SERVE_TOPK_BATCH, rows=int(n), padded=int(pad_to),
                requests=len(batch), m=int(self.m),
                wall_s=round(wall, 6),
                **({} if probes is None else {"probes": int(probes)}),
            )
        self._batch_served(index, n, pad_to, len(batch), wall)
        reg = telemetry.registry()
        tel = telemetry.enabled()
        lo = 0
        for codes, fut, label, t_enq in batch:
            hi = lo + codes.shape[0]
            if fut.set_running_or_notify_cancel():
                fut.set_result((d[lo:hi], i[lo:hi]))
            # per-request tail-latency stamps (r17): enqueue (submit),
            # dispatch (t0, just before the coalesced query_topk) and
            # completion (now, after the future resolved) — all
            # perf_counter, so the differences are monotone
            t_comp = _time.perf_counter()
            total = t_comp - t_enq
            queue_wait = t0 - t_enq
            reg.observe(self._lat_name, total)
            reg.observe(self._lat_name + ".queue_wait", queue_wait)
            reg.observe(self._lat_name + ".serve", wall)
            if label is not None:
                reg.observe(f"{self._lat_name}.client.{label}", total)
            if tel:
                telemetry.emit(
                    EVENTS.SERVE_LATENCY_REQUEST, server=self.name,
                    label=label, rows=int(hi - lo), m=int(self.m),
                    queue_wait_s=round(queue_wait, 9),
                    serve_s=round(wall, 9), total_s=round(total, 9),
                    **({} if probes is None else {"probes": int(probes)}),
                )
            lo = hi

    def _run(self) -> None:
        import queue as _queue

        draining = False
        while True:
            if draining:
                try:
                    first = self._q.get_nowait()
                except _queue.Empty:
                    return
            else:
                first = self._q.get()
            if first is self._SENTINEL:
                draining = True  # serve what's already queued, then stop
                continue
            batch, saw_sentinel = self._collect(first)
            self._serve(batch)
            if saw_sentinel:
                draining = True


class DeviceBatch:
    """A streaming batch already laid out and uploaded for one device
    kernel, produced by ``CountSketch.prepare_batch`` on the prefetch
    worker thread so the H2D transfer overlaps device compute.

    ``kind`` names the kernel the layout targets (``'docmajor'`` /
    ``'flat'``); ``arrays`` are the device operands in that kernel's
    argument order.  ``shape``/``nbytes`` mirror the source CSR batch so
    the streaming layer's bookkeeping (row counts, ``batch_nbytes``) is
    unchanged by preparation.
    """

    __slots__ = ("kind", "arrays", "n", "n_pad", "t_pad", "shape", "nbytes")

    def __init__(self, kind: str, arrays: tuple, n: int, n_pad: int,
                 t_pad: int, shape: tuple, nbytes: int):
        self.kind = kind
        self.arrays = arrays
        self.n = n
        self.n_pad = n_pad
        self.t_pad = t_pad
        self.shape = shape
        self.nbytes = nbytes


def _flat_mesh_layout(X, p: int):
    """Token-balanced host layout of one CSR batch for the flat mesh
    kernel (ISSUE 8 satellite — VERDICT weak #3): rows partition at
    ``token_balanced_bounds`` cuts instead of equal row counts, so the
    padded token width ``t_pad`` tracks ``nnz/p`` instead of the worst
    shard's token count.  Shards therefore own UNEQUAL row ranges; each
    scatters into its own ``rows_blk``-row block (``rows_blk`` = the
    bucketed max rows any shard owns), and ``perm`` maps the
    block-concatenated output back to global row order (one device
    gather).  Returns ``(rows_l, idx, vals, rows_blk, t_pad, perm)``
    with the first three ``(p, t_pad)`` and ``perm`` ``(n,)`` int32.

    Pure host work — factored out of ``_transform_csr_jax`` so the
    partition/permutation algebra is unit-testable off-mesh (the mesh
    kernel itself needs a shard_map-capable jax)."""
    from randomprojection_tpu.parallel.sharded import (
        row_bucket,
        token_balanced_bounds,
    )

    n = X.shape[0]
    indptr = X.indptr.astype(np.int64, copy=False)
    bounds_rows = token_balanced_bounds(indptr, p)
    tok_bounds = indptr[bounds_rows]
    rows_per = np.diff(bounds_rows)
    counts = np.diff(tok_bounds)
    rows_blk = row_bucket(int(max(rows_per.max(), 1)))
    t_pad = row_bucket(int(max(counts.max(), 1)))
    rows_l = np.zeros((p, t_pad), np.int32)
    idx_s = np.zeros((p, t_pad), np.int32)
    vals_s = np.zeros((p, t_pad), np.float32)
    row_sizes = np.diff(indptr)
    perm = np.empty(n, np.int64)
    for s in range(p):
        r0, r1 = int(bounds_rows[s]), int(bounds_rows[s + 1])
        lo, hi = int(tok_bounds[s]), int(tok_bounds[s + 1])
        c = hi - lo
        rows_l[s, :c] = np.repeat(
            np.arange(r1 - r0, dtype=np.int32), row_sizes[r0:r1]
        )
        idx_s[s, :c] = X.indices[lo:hi]
        vals_s[s, :c] = X.data[lo:hi]
        perm[r0:r1] = s * rows_blk + np.arange(r1 - r0)
    return rows_l, idx_s, vals_s, rows_blk, t_pad, perm.astype(np.int32)


def _docmajor_kernel(k: int, t_pad: int, chunk: int):
    """Jittable doc-major compare-reduce sketch body
    ``(idx (n, t_pad) int32, val (n, t_pad) f32, hs packed table) -> (n, k)``
    — shared by ``CountSketch._transform_csr_docmajor`` and
    ``benchmark.measure_config5`` so the recorded bench number IS the
    shipped kernel, not a reimplementation that can drift."""
    import jax
    import jax.numpy as jnp

    iota = jnp.arange(k, dtype=jnp.int32)

    def kernel(idx_t, val_t, hs_t):
        g = hs_t[idx_t]  # ONE packed-table gather per token
        sv = val_t * (1 - 2 * (g & 1)).astype(jnp.float32)
        h2 = g >> 1

        def tile(args):
            h_c, sv_c = args
            return jnp.sum(
                jnp.where(
                    h_c[:, :, None] == iota[None, None, :],
                    sv_c[:, :, None],
                    0.0,
                ),
                axis=1,
            )

        nchunk = h2.shape[0] // chunk
        return jax.lax.map(
            tile,
            (
                h2.reshape(nchunk, chunk, t_pad),
                sv.reshape(nchunk, chunk, t_pad),
            ),
        ).reshape(h2.shape[0], k)

    return kernel


def _docmajor_chunk(rows_local: int, t_pad: int, k: int) -> int:
    """Doc-chunk for the masked reduction: bounds the (chunk, t_pad, k)
    working set to ~256 MB if XLA materializes it."""
    chunk = rows_local
    while chunk * t_pad * k * 4 > (1 << 28) and chunk % 2 == 0:
        chunk //= 2
    return chunk


class CountSketch(ParamsMixin):
    """Count-Sketch / hashing-trick projection ``(n, d) → (n, k)``.

    The hash maps ``h_`` (int32 ``[0, k)``) and signs ``s_`` (±1 int8) are
    derived from the seed on the host — a few KB, backend-independent — so
    numpy and jax paths compute the *same sketch* (identical ``h_``/``s_``;
    unlike the JL kernels, where each backend has its own PRNG —
    SURVEY.md §8).  Numeric agreement across backends is f32-grade
    (≲1e-5 relative) on the MXU path; f64 inputs stay on host and agree
    exactly.  Pass ``use_mxu=False`` to force the scatter path when exact
    cross-backend reproducibility matters more than throughput.

    Dense f32 inputs on the jax backend run on the MXU as a one-hot ±1
    matmul (split-precision, see ``_transform_dense_jax`` for the measured
    kernel bake-off) with a device scatter-add fallback when the one-hot
    matrix would be too large.  Sparse CSR f32 inputs run ON DEVICE as a
    gather + scatter-add against resident ``h_``/``s_`` tables
    (``_transform_csr_jax`` — the config-5 hot loop at ``d=2^20``, where
    no one-hot could fit); f64 CSR uses a vectorized host scatter (the
    Cython ``FeatureHasher`` fast path's role — sklearn
    ``_hashing_fast.pyx``).
    """

    def __init__(self, n_components, *, random_state=None, backend="auto",
                 use_mxu: Optional[bool] = None, mesh=None,
                 data_axis: str = "data"):
        if not isinstance(n_components, numbers.Integral) or n_components <= 0:
            raise ValueError(
                f"n_components must be a positive int, got {n_components!r}"
            )
        self.n_components = int(n_components)
        self.random_state = random_state
        self.backend = backend
        # None = auto (MXU one-hot matmul when the mask fits the size cap);
        # False = force the device scatter path — the opt-out for users who
        # need the pre-MXU exact cross-backend reproducibility (the MXU path
        # agrees with numpy at f32 grade only); True = require the MXU path
        # (raises at transform if the mask would exceed the cap).
        self.use_mxu = use_mxu
        # DP row-sharding over a jax Mesh (config 5 is a "100M docs on
        # v5e-8" workload — BASELINE.json:11): rows shard over `data_axis`,
        # the one-hot mask / hash maps replicate, zero collectives — the
        # same decomposition as the JL backend's DP path.
        self.mesh = mesh
        self.data_axis = data_axis

    def fit_schema(self, n_samples: int, n_features: int, dtype=np.float64):
        if n_features <= 0:
            raise ValueError(f"n_features must be strictly positive, got {n_features}")
        self.seed_ = _resolve_seed(self.random_state)
        # salted stream: a user sharing one seed between their data generator
        # and the sketch must not get h_/s_ correlated with their data (see
        # backends/numpy_backend.py::_STREAM_SALT)
        rng = np.random.default_rng(np.random.SeedSequence([0x43534B31, self.seed_]))
        self.n_components_ = self.n_components
        self.n_features_in_ = n_features
        self.h_ = rng.integers(0, self.n_components, size=n_features, dtype=np.int32)
        self.s_ = (rng.integers(0, 2, size=n_features, dtype=np.int8) * 2 - 1)
        self._resolve_execution()
        return self

    def _resolve_execution(self):
        """(Re)derive the execution path from backend/use_mxu and drop any
        cached device fn.  Called at fit and whenever ``set_params`` touches
        an execution-affecting parameter — the cached ``_jax_fn`` has the
        old one-hot mask / path choice baked in."""
        self._use_jax = self.backend in ("jax", "auto") and _jax_available()
        if self.use_mxu and not self._use_jax:
            # refuse rather than silently scattering on the host —
            # the documented use_mxu=True semantics are "require the MXU"
            raise ValueError(
                "use_mxu=True requires the jax backend (backend='jax' or "
                f"'auto' with jax importable), got backend={self.backend!r}"
            )
        self.__dict__.pop("_jax_fn", None)
        self.__dict__.pop("_slice_fns", None)
        self.__dict__.pop("_csr_fns", None)
        self.__dict__.pop("_dev_tables", None)
        self.__dict__.pop("_dev_packed", None)

    def set_params(self, **params):
        super().set_params(**params)
        if {"use_mxu", "backend", "mesh", "data_axis"} & params.keys():
            self._resolve_execution()
        return self

    def fit(self, X, y=None):
        X = check_array(X, accept_sparse=True)
        return self.fit_schema(*X.shape, dtype=X.dtype)

    def _check_is_fitted(self):
        if not hasattr(self, "h_"):
            raise NotFittedError(
                f"This {type(self).__name__} instance is not fitted yet."
            )

    def transform(self, X):
        self._check_is_fitted()
        if sp.issparse(X):
            if self.use_mxu:
                raise ValueError(
                    "use_mxu=True cannot serve sparse input (the MXU path "
                    "is dense-only); densify X or use use_mxu=None"
                )
            X = X.tocsr()
            if X.shape[1] != self.n_features_in_:
                raise ValueError(
                    f"X has {X.shape[1]} features, expected "
                    f"{self.n_features_in_}"
                )
            if self._csr_on_device(X):
                return self._transform_csr_jax(X)
            return self._transform_csr(X)
        X = check_array(X, accept_sparse=False)
        if X.shape[1] != self.n_features_in_:
            raise ValueError(
                f"X has {X.shape[1]} features, expected {self.n_features_in_}"
            )
        if self._use_jax:
            return self._transform_dense_jax(X)
        return self._transform_dense_np(X)

    def fit_transform(self, X, y=None):
        return self.fit(X).transform(X)

    def _transform_dense_np(self, X):
        Y = np.zeros((X.shape[0], self.n_components_), dtype=X.dtype)
        np.add.at(Y, (slice(None), self.h_), X * self.s_)
        return Y

    # one-hot sketch matrix cap: above this, M(k,d) bf16 stops being "a few
    # MB in HBM" and the scatter path wins on memory (e.g. d=2^20 hashing
    # space at k=256 would need 512 MB)
    _MXU_MASK_BYTES_CAP = 64 << 20

    def _shard_wrap(self, jax, local, n_extra_args: int):
        """jit ``local`` — under a mesh, shard_map'd with rows over
        ``data_axis`` and every other operand replicated (DP: zero
        collectives; each shard sketches its own rows)."""
        if self.mesh is None:
            return jax.jit(local)
        from jax.sharding import PartitionSpec as P

        in_specs = (P(self.data_axis, None),) + (P(),) * n_extra_args
        return jax.jit(
            jax.shard_map(
                local, mesh=self.mesh, in_specs=in_specs,
                out_specs=P(self.data_axis, None),
            )
        )

    def _build_jax_fn(self, jax, jnp):
        k, d = self.n_components_, self.n_features_in_

        fits_cap = 2 * k * d <= self._MXU_MASK_BYTES_CAP
        if self.use_mxu and not fits_cap:
            raise ValueError(
                f"use_mxu=True but the one-hot mask ({2 * k * d} bytes "
                f"bf16) exceeds the {self._MXU_MASK_BYTES_CAP}-byte cap; "
                "use use_mxu=None (auto) or False (scatter)"
            )
        if fits_cap if self.use_mxu is None else self.use_mxu:
            # MXU path: CountSketch IS a projection with a one-hot ±1
            # matrix M[h(j), j] = s(j) — exact in bf16, so the split2
            # two-pass matmul gives f32-grade output.  Measured on the
            # real chip (4096→256, f32 rows): one-hot split2 2.2M
            # rows/s vs scatter-add 1.10M, segment_sum 1.20M, one-hot
            # 'high' 1.40M — scatter is a slow path on TPU; the MXU
            # wins whenever M fits comfortably in HBM.
            from randomprojection_tpu.ops.split_matmul import split2_project

            mask = (
                jnp.zeros((k, d), jnp.float32)
                .at[jnp.asarray(self.h_), jnp.arange(d)]
                .set(jnp.asarray(self.s_, jnp.float32))
                .astype(jnp.bfloat16)
            )

            def sketch_mxu(x, mask):
                return split2_project(x, mask, 1.0).astype(x.dtype)

            fn = self._shard_wrap(jax, sketch_mxu, 1)
            self._jax_fn = lambda x: fn(x, mask)
        else:

            def sketch_scatter(x, h, s):
                signed = x * s
                # scatter-add over features: Y[:, h[j]] += x̃[:, j]
                y = jnp.zeros((x.shape[0], k), dtype=x.dtype)
                return y.at[:, h].add(signed)

            fn = self._shard_wrap(jax, sketch_scatter, 2)
            h_dev = jnp.asarray(self.h_)
            s_dev = jnp.asarray(self.s_, jnp.float32)
            self._jax_fn = lambda x: fn(x, h_dev, s_dev.astype(x.dtype))

    def _transform_dense_jax(self, X, *, materialize: bool = True):
        if X.dtype == np.float64:
            # jax (x64 disabled) would silently truncate to f32, breaking
            # the documented numpy/jax agreement; f64 stays on host
            if self.use_mxu:
                raise ValueError(
                    "use_mxu=True cannot serve float64 input (jax would "
                    "truncate to f32); cast X to float32 or use use_mxu=None"
                )
            return self._transform_dense_np(X)
        import jax
        import jax.numpy as jnp

        from randomprojection_tpu.parallel.sharded import (
            row_bucket,
            slice_rows_sharded,
        )

        if not hasattr(self, "_jax_fn"):
            self._build_jax_fn(jax, jnp)
        n = X.shape[0]
        pad_to = row_bucket(n, self.mesh, self.data_axis)
        if self.mesh is None:
            x = jnp.asarray(X)
            if pad_to != n:
                x = jnp.pad(x, ((0, pad_to - n), (0, 0)))
        else:
            # pad on host and device_put ROW-SHARDED (the jax backend's
            # _prepare_rows preamble): jnp.asarray would land the whole
            # batch on device 0 and pay an extra all-to-device-0 hop per
            # batch before the jitted shard_map reshards it
            from jax.sharding import NamedSharding, PartitionSpec as P

            x = np.asarray(X)
            if pad_to != n:
                x = np.pad(x, ((0, pad_to - n), (0, 0)))
            x = jax.device_put(
                x, NamedSharding(self.mesh, P(self.data_axis, None))
            )
        y = slice_rows_sharded(
            self._jax_fn(x), n, self.mesh, self.data_axis,
            cache=self.__dict__.setdefault("_slice_fns", {}),
        )
        if materialize:
            return np.asarray(y)
        return y  # lazy device handle: the stream pipeline fetches later

    def _csr_on_device(self, X) -> bool:
        """Device CSR eligibility: jax path, f32 data (f64 stays on host by
        the same truncation policy as the dense path), and a flat scatter
        index that fits int32 (jax x64 is off; a batch would need >6M rows
        at k=256 to overflow — far past any streaming batch size).  The
        guard uses the PADDED row count — ``_transform_csr_jax`` buckets
        rows up to +25% (``row_bucket``), and the flat index spans
        ``n_pad·k``, so guarding on the raw ``n`` would admit a narrow band
        of batches that overflow after padding.  Under a mesh the scatter
        accumulator is per shard, but the token-balanced row cuts can
        hand one shard up to EVERY row of a fully-skewed batch — the
        guard therefore uses the undivided bucket (conservative: a
        pathological >2^31/k-row mesh batch routes to the host path
        instead of risking a wrapped flat index)."""
        from randomprojection_tpu.parallel.sharded import row_bucket

        n_pad = row_bucket(max(X.shape[0], 1), self.mesh, self.data_axis)
        return (
            self._use_jax
            and X.dtype == np.float32
            and n_pad * self.n_components_ < 2**31
        )

    def _device_tables(self):
        """``h_``/``s_`` resident on device (4+1 MB at d=2^20), uploaded
        once per fit — per-batch traffic is only the batch's own tokens."""
        t = self.__dict__.get("_dev_tables")
        if t is None:
            import jax.numpy as jnp

            t = (jnp.asarray(self.h_), jnp.asarray(self.s_))
            self.__dict__["_dev_tables"] = t
        return t

    def _device_packed_table(self):
        """One combined table ``hs = 2·h + (s<0)`` (int32): the per-token
        table lookup is THE cost floor of the d=2^20 device sketch on TPU
        (measured r5: gather 77 ms vs scatter 141 ms vs everything else
        ~0 at 6.5M tokens), so the doc-major kernel pays it once, not
        twice — ``h = hs >> 1``, ``sign = 1 - 2·(hs & 1)``."""
        t = self.__dict__.get("_dev_packed")
        if t is None:
            import jax.numpy as jnp

            hs = (self.h_.astype(np.int64) * 2 + (self.s_ < 0)).astype(
                np.int32
            )
            t = jnp.asarray(hs)
            self.__dict__["_dev_packed"] = t
        return t

    # doc-major eligibility: padded-token-matrix inflation over the real
    # token count, and a per-row width cap (a single huge document must
    # not balloon every row's padding)
    _DOCMAJOR_MAX_INFLATION = 4.0
    _DOCMAJOR_MAX_WIDTH = 2048

    def _docmajor_host_layout(self, X, n_pad: int, t_pad: int):
        """CSR → padded doc-major ``(idxm, valm)`` numpy pair (host work
        only — shared by the dispatch path and ``prepare_batch``, so the
        prefetch worker lays out and uploads without duplicating the
        kernel's layout rules).  Pad tokens carry value 0."""
        n = X.shape[0]
        counts = np.diff(X.indptr)
        row_ids = np.repeat(np.arange(n, dtype=np.int64), counts)
        pos = np.arange(X.nnz, dtype=np.int64) - np.repeat(
            X.indptr[:-1].astype(np.int64), counts
        )
        idxm = np.zeros((n_pad, t_pad), np.int32)
        valm = np.zeros((n_pad, t_pad), np.float32)
        idxm[row_ids, pos] = X.indices
        valm[row_ids, pos] = X.data
        return idxm, valm

    def _docmajor_fn(self, n_pad: int, t_pad: int):
        """The cached jitted doc-major kernel for one padded shape."""
        import jax

        k = self.n_components_
        p = 1 if self.mesh is None else self.mesh.shape[self.data_axis]
        fns = self.__dict__.setdefault("_csr_fns", {})
        key = ("docmajor", n_pad, t_pad, p)
        fn = fns.get(key)
        if fn is None:
            chunk = _docmajor_chunk(n_pad // p, t_pad, k)
            kernel = _docmajor_kernel(k, t_pad, chunk)
            if self.mesh is None:
                fn = jax.jit(kernel)
            else:
                from jax.sharding import PartitionSpec as P

                fn = jax.jit(
                    jax.shard_map(
                        kernel, mesh=self.mesh,
                        in_specs=(
                            P(self.data_axis, None),
                            P(self.data_axis, None),
                            P(),
                        ),
                        out_specs=P(self.data_axis, None),
                    )
                )
            fns[key] = fn
        return fn

    def _docmajor_dispatch(self, idxm_dev, valm_dev, n: int, n_pad: int,
                           t_pad: int, *, materialize: bool):
        """Dispatch the doc-major kernel on already-device-resident
        operands and slice pad rows."""
        from randomprojection_tpu.parallel.sharded import slice_rows_sharded

        y = self._docmajor_fn(n_pad, t_pad)(
            idxm_dev, valm_dev, self._device_packed_table()
        )
        y = slice_rows_sharded(
            y, n, self.mesh, self.data_axis,
            cache=self.__dict__.setdefault("_slice_fns", {}),
        )
        if materialize:
            return np.asarray(y)
        return y

    def _transform_csr_docmajor(self, X, n_pad: int, t_pad: int, *,
                                materialize: bool = True):
        """Doc-major compare-reduce sketch — the d=2^20 winner (r5 bake-off).

        Measured on the real chip at 65536 docs × 100 tokens, d=2^20, k=256
        (honest per-batch dispatches, distinct values per call, every
        output forced): table gather alone 77 ms, scatter alone 141 ms,
        the flat gather+scatter kernel 175–300 ms, gather+compare-reduce
        75 ms.  TPU scatter is op-bound — avoiding it entirely beats every
        scatter formulation, and the remaining cost IS the table lookup.
        This kernel therefore (1) lays tokens out doc-major ``(n, T)`` so
        the sketch is a masked reduction ``Y[r, c] = Σ_t sv[r,t]·[h[r,t]=c]``
        with no scatter, and (2) gathers the PACKED ``2h+(s<0)`` table once
        per token instead of two separate h/s lookups.  Rows shard over
        ``data_axis`` under a mesh (same DP decomposition, zero
        collectives).  Pad tokens carry value 0 and contribute nothing.
        """
        import jax
        import jax.numpy as jnp

        idxm, valm = self._docmajor_host_layout(X, n_pad, t_pad)
        if self.mesh is None:
            idxm_dev, valm_dev = jnp.asarray(idxm), jnp.asarray(valm)
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            sh = NamedSharding(self.mesh, P(self.data_axis, None))
            idxm_dev = jax.device_put(idxm, sh)
            valm_dev = jax.device_put(valm, sh)
        return self._docmajor_dispatch(
            idxm_dev, valm_dev, X.shape[0], n_pad, t_pad,
            materialize=materialize,
        )

    def _transform_csr_jax(self, X, *, materialize: bool = True):
        """Sketch a CSR batch ON DEVICE (config 5's hot loop — BL:11).

        Kernel selection (r5 bake-off, see ``_transform_csr_docmajor``):
        low-skew batches take the doc-major compare-reduce kernel (no
        scatter, one packed-table gather — ~2-4× the flat kernel);
        skewed batches (padded doc-major layout would inflate >
        ``_DOCMAJOR_MAX_INFLATION``× the real token count, or a single
        row exceeds ``_DOCMAJOR_MAX_WIDTH`` tokens) keep the flat
        gather + scatter-add below.

        The 2^20-wide input space never materializes anywhere: per batch
        the host ships only ``(row_ids, indices, data)`` (~12 bytes/token),
        and the device gathers ``h_``/``s_`` from the resident tables and
        scatter-adds into ``(n, k)``:

            Y[row_t, h_[idx_t]] += s_[idx_t] · val_t

        Static shapes for one-program streams: token count and row count
        are padded on the octave ladder (``row_bucket``), pad tokens carry
        value 0.  Under a mesh, rows shard over ``data_axis`` (DP) at
        TOKEN-BALANCED row cuts (``token_balanced_bounds`` — the split is
        implicit in the CSR ``indptr``): each shard scatters its own
        token range into its own row block with zero collectives, and
        one device gather restores global row order.  The previous
        equal-row split padded every shard's token buffer to the worst
        shard's count (VERDICT weak #3); now ``t_pad`` tracks ``nnz/p``.
        """
        import jax
        import jax.numpy as jnp

        n = X.shape[0]
        kind, n_pad, t_row = self._csr_route(X)
        if kind == "docmajor":
            return self._transform_csr_docmajor(
                X, n_pad, t_row, materialize=materialize
            )
        if self.mesh is None:
            rows, idx, vals, t_pad = self._flat_host_layout(X)
            return self._flat_dispatch(
                jnp.asarray(rows), jnp.asarray(idx), jnp.asarray(vals),
                n, n_pad, t_pad, materialize=materialize,
            )

        fns = self.__dict__.setdefault("_csr_fns", {})
        h_dev, s_dev = self._device_tables()

        from jax.sharding import NamedSharding, PartitionSpec as P

        p = self.mesh.shape[self.data_axis]
        # token-balanced, row-aligned partition (ISSUE 8 satellite):
        # shard row ranges come from the indptr's token quantiles, so
        # t_pad tracks nnz/p instead of the worst shard's token count —
        # the previous equal-row split padded EVERY shard to the most
        # token-heavy shard (VERDICT weak #3).  Shards own unequal row
        # counts; each scatters into its own rows_blk block and one
        # device gather (perm) restores global row order.
        rows_l, idx_s, vals_s, rows_blk, t_pad, perm = _flat_mesh_layout(
            X, p
        )
        fn = fns.get(("flat_mesh", rows_blk, t_pad, p))
        if fn is None:
            kernel = self._scatter_body(rows_blk)

            def shard_body(rows, idx, vals, h, s):
                # operands arrive (1, t_pad) per shard: squeeze, then
                # run the shared kernel on this shard's row block
                return kernel(rows[0], idx[0], vals[0], h, s)

            da = self.data_axis
            fn = jax.jit(
                jax.shard_map(
                    shard_body, mesh=self.mesh,
                    in_specs=(P(da, None),) * 3 + (P(), P()),
                    out_specs=P(da, None),
                )
            )
            fns[("flat_mesh", rows_blk, t_pad, p)] = fn
        y = fn(rows_l, idx_s, vals_s, h_dev, s_dev)
        # reassemble shard blocks to global row order: perm has exactly
        # one entry per REAL row, so this gather also drops pad rows
        # (replicated output — the row partition is batch-dependent, and
        # XLA cannot slice a sharded dim raggedly; same policy as
        # slice_rows_sharded's ragged leg)
        gkey = ("flat_mesh_gather", rows_blk * p, n)
        gfn = fns.get(gkey)
        if gfn is None:
            gfn = jax.jit(
                lambda a, pm: jnp.take(a, pm, axis=0),
                out_shardings=NamedSharding(self.mesh, P()),
            )
            fns[gkey] = gfn
        y = gfn(y, jnp.asarray(perm))
        if materialize:
            return np.asarray(y)
        return y

    def _csr_route(self, X):
        """Kernel selection for one CSR batch — the SINGLE source of the
        doc-major/flat eligibility rule, shared by ``_transform_csr_jax``
        and ``prepare_batch`` so prepared and unprepared batches always
        target the same jitted program.  Returns ``(kind, n_pad, t_pad)``
        with ``kind`` ``'docmajor'`` (t_pad = bucketed max row width) or
        ``'flat'`` (t_pad None — the flat layout buckets by nnz)."""
        from randomprojection_tpu.parallel.sharded import row_bucket

        n = X.shape[0]
        n_pad = row_bucket(max(n, 1), self.mesh, self.data_axis)
        t_max = int(np.diff(X.indptr).max()) if n else 0
        if t_max:
            t_row = row_bucket(t_max)
            if (
                t_row <= self._DOCMAJOR_MAX_WIDTH
                and n_pad * t_row
                <= self._DOCMAJOR_MAX_INFLATION * max(X.nnz, 1)
            ):
                return "docmajor", n_pad, t_row
        return "flat", n_pad, None

    def _scatter_body(self, n_rows: int):
        """The one flat device sketch body (single-chip and per-shard):
        gather the resident tables at the batch's token indices,
        scatter-add into the flat ``(n_rows·k)`` accumulator."""
        import jax.numpy as jnp

        k = self.n_components_

        def body(rows, idx, vals, h, s):
            flat = rows * k + h[idx]
            y = jnp.zeros((n_rows * k,), jnp.float32)
            return y.at[flat].add(
                vals * s[idx].astype(jnp.float32)
            ).reshape(n_rows, k)

        return body

    def _flat_host_layout(self, X):
        """CSR → padded flat ``(rows, idx, vals, t_pad)`` numpy arrays for
        the gather+scatter kernel (host work only — shared by the dispatch
        path and ``prepare_batch``)."""
        from randomprojection_tpu.parallel.sharded import row_bucket

        n = X.shape[0]
        rows = np.repeat(
            np.arange(n, dtype=np.int32),
            np.diff(X.indptr.astype(np.int64, copy=False)),
        )
        t_pad = row_bucket(max(X.nnz, 1))
        pad = t_pad - X.nnz
        rows = np.pad(rows, (0, pad))
        idx = np.pad(X.indices.astype(np.int32, copy=False), (0, pad))
        vals = np.pad(X.data, (0, pad))
        return rows, idx, vals, t_pad

    def _flat_fn(self, n_pad: int, t_pad: int):
        """The cached jitted single-chip flat kernel for one padded shape."""
        import jax

        fns = self.__dict__.setdefault("_csr_fns", {})
        fn = fns.get((n_pad, t_pad))
        if fn is None:
            fn = jax.jit(self._scatter_body(n_pad))
            fns[(n_pad, t_pad)] = fn
        return fn

    def _flat_dispatch(self, rows_dev, idx_dev, vals_dev, n: int,
                       n_pad: int, t_pad: int, *, materialize: bool):
        """Dispatch the flat kernel on already-device-resident operands
        (single-chip path) and slice pad rows."""
        from randomprojection_tpu.parallel.sharded import slice_rows_sharded

        h_dev, s_dev = self._device_tables()
        y = self._flat_fn(n_pad, t_pad)(
            rows_dev, idx_dev, vals_dev, h_dev, s_dev
        )
        y = slice_rows_sharded(
            y, n, self.mesh, self.data_axis,
            cache=self.__dict__.setdefault("_slice_fns", {}),
        )
        if materialize:
            return np.asarray(y)
        return y

    def _transform_csr(self, X):
        if X.shape[1] != self.n_features_in_:
            raise ValueError(
                f"X has {X.shape[1]} features, expected {self.n_features_in_}"
            )
        out_dtype = X.dtype if X.dtype in (np.float32, np.float64) else np.float64
        Y = np.zeros((X.shape[0], self.n_components_), dtype=out_dtype)
        rows = np.repeat(
            np.arange(X.shape[0]), np.diff(X.indptr).astype(np.int64)
        )
        np.add.at(
            Y,
            (rows, self.h_[X.indices]),
            X.data.astype(out_dtype) * self.s_[X.indices],
        )
        return Y

    # -- streaming composition (same protocol as BaseRandomProjection) -------

    def fit_source(self, source):
        n_rows, n_features, dtype = source.schema()
        return self.fit_schema(n_rows, n_features, dtype=dtype)

    def transform_stream(self, source, **kwargs):
        from randomprojection_tpu.streaming import stream_transform

        return stream_transform(self, source, **kwargs)

    def prepare_batch(self, X):
        """Prefetch-stage hook (``PrefetchSource(prepare=...)``): lay out a
        CSR batch for its device kernel and START the H2D upload from the
        worker thread, so by dispatch time the consumer only launches the
        kernel — the transfer overlaps the previous batch's device compute
        instead of sitting in the dispatch path.

        Routing matches ``_transform_csr_jax`` exactly (same doc-major /
        flat eligibility, same padded shapes, so the same jitted programs
        serve prepared and unprepared batches).  Batches the device CSR
        path would not serve (dense, f64, ``use_mxu``, a mesh — the mesh
        path shards at dispatch) are returned unchanged and take their
        usual synchronous route."""
        self._check_is_fitted()
        if (
            not sp.issparse(X)
            or self.use_mxu
            or self.mesh is not None
        ):
            return X
        X = X.tocsr()
        if X.shape[1] != self.n_features_in_:
            raise ValueError(
                f"X has {X.shape[1]} features, expected "
                f"{self.n_features_in_}"
            )
        if not self._csr_on_device(X):
            return X
        import jax.numpy as jnp

        from randomprojection_tpu.utils.observability import batch_nbytes

        n = X.shape[0]
        nbytes = batch_nbytes(X)
        kind, n_pad, t_row = self._csr_route(X)
        if kind == "docmajor":
            idxm, valm = self._docmajor_host_layout(X, n_pad, t_row)
            return DeviceBatch(
                "docmajor", (jnp.asarray(idxm), jnp.asarray(valm)),
                n, n_pad, t_row, X.shape, nbytes,
            )
        rows, idx, vals, t_pad = self._flat_host_layout(X)
        return DeviceBatch(
            "flat",
            (jnp.asarray(rows), jnp.asarray(idx), jnp.asarray(vals)),
            n, n_pad, t_pad, X.shape, nbytes,
        )

    def _dispatch_prepared(self, b: DeviceBatch, *, materialize: bool):
        """Run the kernel a ``prepare_batch`` upload targeted — no host
        layout or H2D left on this (the dispatch) thread."""
        if b.kind == "docmajor":
            return self._docmajor_dispatch(
                *b.arrays, b.n, b.n_pad, b.t_pad, materialize=materialize
            )
        return self._flat_dispatch(
            *b.arrays, b.n, b.n_pad, b.t_pad, materialize=materialize
        )

    def _transform_async(self, X):
        """Streaming transform: returns a lazy device handle on the jax
        dense-f32 and CSR-f32 paths so the pipeline overlaps sketch batches
        (the host paths — f64, numpy backend — stay synchronous).  Accepts
        ``DeviceBatch`` objects from ``prepare_batch`` (pre-uploaded by the
        prefetch stage) and dispatches them directly."""
        self._check_is_fitted()
        if isinstance(X, DeviceBatch):
            return self._dispatch_prepared(X, materialize=False)
        if sp.issparse(X):
            X = X.tocsr()
            if X.shape[1] != self.n_features_in_:
                raise ValueError(
                    f"X has {X.shape[1]} features, expected "
                    f"{self.n_features_in_}"
                )
            if not self.use_mxu and self._csr_on_device(X):
                return self._transform_csr_jax(X, materialize=False)
            return self.transform(X)
        X = check_array(X, accept_sparse=False)
        if X.shape[1] != self.n_features_in_:
            raise ValueError(
                f"X has {X.shape[1]} features, expected {self.n_features_in_}"
            )
        if self._use_jax and X.dtype != np.float64:
            return self._transform_dense_jax(X, materialize=False)
        return self.transform(X)

    def _stream_out_dtype(self):
        return None  # keep whatever dtype transform produced

    def _stream_out_width(self) -> int:
        return self.n_components_

    def get_feature_names_out(self, input_features=None):
        """Output names ``countsketch<i>`` (same naming rule as the JL
        estimators; sketch buckets have no input-feature lineage)."""
        from randomprojection_tpu.models.base import _feature_names_out

        return _feature_names_out(self, input_features)

    def inverse_transform(self, Y):
        """Unbiased decode: ``x̂[j] = s(j) · Y[:, h(j)]``."""
        self._check_is_fitted()
        Y = np.asarray(Y)
        if Y.shape[1] != self.n_components_:
            raise ValueError(
                f"Y has {Y.shape[1]} components, expected {self.n_components_}"
            )
        return Y[:, self.h_] * self.s_


def _jax_available() -> bool:
    try:
        import jax  # noqa: F401

        return True
    except ImportError:  # pragma: no cover
        return False
