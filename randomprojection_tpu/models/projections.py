"""Gaussian and sparse (Achlioptas/Li) projection estimators (layer L5).

Behavioral contracts: sklearn ``GaussianRandomProjection``
(``random_projection.py:471-613``) and ``SparseRandomProjection``
(``random_projection.py:616-827``); see SURVEY.md §1 for the math.
"""

from __future__ import annotations

from typing import Optional

from randomprojection_tpu.models.base import BaseRandomProjection
from randomprojection_tpu.utils.validation import check_density

__all__ = ["GaussianRandomProjection", "SparseRandomProjection"]


class GaussianRandomProjection(BaseRandomProjection):
    """Dense Gaussian random projection: ``R[i,j] ~ N(0, 1/k)`` i.i.d.

    Contract: ``random_projection.py:471-613`` (kernel math at 203-205,
    transform ``X @ R.T`` at 613).

    Examples
    --------
    >>> import numpy as np
    >>> rp = GaussianRandomProjection(n_components=64, random_state=0,
    ...                               backend="numpy")
    >>> Y = rp.fit_transform(np.random.default_rng(0).normal(size=(100, 512)))
    >>> Y.shape
    (100, 64)
    """

    _kind = "gaussian"


class SparseRandomProjection(BaseRandomProjection):
    """Sparse random projection (Achlioptas 2003 / Li-Hastie-Church 2006).

    ``R[i,j] ∈ {-v, 0, +v}`` with probabilities ``{density/2, 1-density,
    density/2}`` and ``v = sqrt(1/(density·k))`` — ``random_projection.py:
    216-221, 274-305``.  ``density='auto'`` resolves to ``1/sqrt(d)``
    (Li 2006, ``:151-152``); ``density=1/3`` is Achlioptas' ``s=3``
    (``:240-241``); ``density=1`` degenerates to dense ±1/√k.

    ``dense_output`` follows scipy semantics on the numpy backend (sparse in
    → sparse out unless set; ``random_projection.py:825-827``); the jax
    backend always produces dense device arrays (SURVEY.md §8 "the sparse
    path").
    """

    _kind = "sparse"

    def __init__(
        self,
        n_components="auto",
        *,
        density="auto",
        eps: float = 0.1,
        dense_output: bool = False,
        compute_inverse_components: bool = False,
        random_state=None,
        backend="auto",
        backend_options: Optional[dict] = None,
    ):
        super().__init__(
            n_components,
            eps=eps,
            compute_inverse_components=compute_inverse_components,
            random_state=random_state,
            backend=backend,
            backend_options=backend_options,
        )
        self.density = density
        self.dense_output = dense_output

    def _resolve_density(self, n_features: int) -> float:
        return check_density(self.density, n_features)

    def _dense_output(self) -> bool:
        return self.dense_output
