"""Host NumPy/SciPy backend — the CPU reference executor and parity oracle.

This is the reference's ``backend='numpy'`` path (``BASELINE.json:5``):
dense BLAS GEMM for Gaussian, scipy CSR SpMM for the sparse kernel
(call-site contract ``random_projection.py:613`` and ``:825-827``).
The jax backend's outputs are validated against this one at the
distance-distortion level (SURVEY.md §5).
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

from randomprojection_tpu.backends.base import ProjectionBackend, ProjectionSpec
from randomprojection_tpu.ops.numpy_kernels import (
    gaussian_random_matrix,
    rademacher_random_matrix,
    sparse_random_matrix,
)

__all__ = ["NumpyBackend"]


#: Salt mixed into the seed before deriving the matrix stream.  Without it,
#: a user who generated their data with ``default_rng(s)`` and fit with
#: ``random_state=s`` would get R equal to the first k rows of their own X
#: (same generator, same stream) — silently breaking the JL guarantee with
#: pathological self-projection distortions.  Found the hard way.
_STREAM_SALT = 0x52503141  # "RP1A"


def _bf16():
    from randomprojection_tpu.utils.validation import bfloat16_dtype

    return bfloat16_dtype()


class NumpyBackend(ProjectionBackend):
    """Single-host CPU executor: ndarray / CSR state, BLAS matmuls."""

    name = "numpy"

    def materialize(self, spec: ProjectionSpec):
        rng = np.random.default_rng(np.random.SeedSequence([_STREAM_SALT, spec.seed]))
        if spec.kind == "gaussian":
            R = gaussian_random_matrix(spec.n_components, spec.n_features, rng)
        elif spec.kind == "sparse":
            R = sparse_random_matrix(
                spec.n_components, spec.n_features, density=spec.density, rng=rng
            )
        elif spec.kind == "rademacher":
            R = rademacher_random_matrix(spec.n_components, spec.n_features, rng)
        else:  # pragma: no cover - spec validates kind
            raise ValueError(spec.kind)
        # bf16 specs keep R in f32: quantizing R to 8 mantissa bits would
        # cost ~0.4% per entry (vs the ≤1e-3 distance budget); only the
        # OUTPUT is bf16, matching the jax backend's f32-compute policy
        store_dtype = (
            np.float32 if spec.np_dtype == _bf16() else spec.np_dtype
        )
        if sp.issparse(R):
            return R.astype(store_dtype)
        return np.ascontiguousarray(R, dtype=store_dtype)

    def transform(self, X, state, spec: ProjectionSpec, *, dense_output: bool = True):
        # scipy semantics (random_projection.py:825-827 via safe_sparse_dot):
        # output is sparse only if X is sparse AND dense_output=False.
        is_bf16_spec = spec.np_dtype == _bf16()
        if sp.issparse(X):
            Y = X @ state.T
            if dense_output and sp.issparse(Y):
                Y = Y.toarray()
            if is_bf16_spec and not sp.issparse(Y):
                # spec owns the output dtype regardless of input sparsity;
                # CSR outputs stay f32 (scipy cannot hold ml_dtypes)
                Y = Y.astype(spec.np_dtype, copy=False)
            return Y
        X = np.asarray(X)
        if X.dtype == _bf16():
            # ALWAYS upcast bf16 input (exact): scipy CSR cannot matmul
            # ml_dtypes arrays at all (f32-fitted sparse estimators would
            # crash), and the dense product would be mixed bf16×f32.  The
            # spec-gated cast below restores bf16 output when the spec
            # says so; an f32 spec correctly yields f32.
            X = X.astype(np.float32)
        if sp.issparse(state):
            # dense X · sparse Rᵀ: compute (R · Xᵀ)ᵀ so the CSR matmul drives
            Y = np.ascontiguousarray((state @ X.T).T)
        else:
            Y = X @ state.T
        # only the bf16 policy casts at the edge: f32-fit/f64-transform must
        # keep returning f64 (sklearn parity, test_random_projection dtype
        # contract)
        return Y.astype(spec.np_dtype, copy=False) if is_bf16_spec else Y

    def inverse_components(self, state, spec: ProjectionSpec) -> np.ndarray:
        # pinv of the densified (k, d) matrix (random_projection.py:360-365)
        R = state.toarray() if sp.issparse(state) else np.asarray(state)
        return np.linalg.pinv(R)  # shape (d, k)

    def inverse_transform(self, Y, inverse_components, spec: ProjectionSpec):
        if sp.issparse(Y):
            Y = Y.toarray()
        Y = np.asarray(Y)
        if spec.np_dtype == _bf16():
            # same bf16 edge policy as transform (cross-backend consistency)
            return (
                Y.astype(np.float32) @ inverse_components.T
            ).astype(spec.np_dtype, copy=False)
        return Y @ inverse_components.T

    def components_to_numpy(self, state, spec: ProjectionSpec):
        return state
