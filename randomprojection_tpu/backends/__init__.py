"""Backend plugin boundary (layer L4).

The reference gates execution behind a ``ProjectionBackend`` registry keyed
by ``backend='numpy'|'spark'|'jax'`` (``BASELINE.json:5``; SURVEY.md §2 L4).
Here ``numpy`` is the host parity oracle and ``jax`` is the TPU execution
path; ``spark`` is out of scope (no pyspark in env — the sharded jax backend
over a TPU mesh is its distributed replacement, SURVEY.md §3.4).

``jax`` is imported lazily: ``get_backend('numpy')`` never pulls in jax.
"""

from randomprojection_tpu.backends.base import (
    ProjectionBackend,
    ProjectionSpec,
    available_backends,
    get_backend,
    register_backend,
    resolve_backend,
)

__all__ = [
    "ProjectionBackend",
    "ProjectionSpec",
    "available_backends",
    "get_backend",
    "register_backend",
    "resolve_backend",
]
