"""``ProjectionBackend`` protocol, ``ProjectionSpec``, and the backend registry.

This is the reference's plugin boundary (``BASELINE.json:5``: "gated behind
the existing ProjectionBackend plugin boundary (backend='numpy'|'spark'|'jax'),
so fit()/transform() ... stay unchanged"; SURVEY.md §2 layer L4).

Design
------
A fitted projection is fully described by an immutable ``ProjectionSpec``
(kind, shape, seed, density, dtype).  A backend turns a spec into *state*
(its native representation of the projection matrix — ndarray, CSR, or a
device-resident ``jax.Array``) and executes the three operations against
that state:

- ``materialize(spec)``      → state                 (fit-time)
- ``transform(X, state, spec, dense_output)`` → Y    (the X·Rᵀ hot loop)
- ``inverse_components(state, spec)`` → pinv(R)      (optional, fit-time)
- ``inverse_transform(Y, inv)``       → X̂            (Y·pinv(R)ᵀ)

Because the spec — not the materialized matrix — is the source of truth, a
fitted model serializes as a few scalars (SURVEY.md §6 checkpoint/resume)
and any backend can re-materialize it, enabling cross-backend save/load.
Within a backend, materialization is deterministic in the seed; across
backends only the *distribution* matches (different PRNGs — SURVEY.md §8).
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any, Callable, Dict, Iterable, Optional

import numpy as np

__all__ = [
    "ProjectionSpec",
    "ProjectionBackend",
    "register_backend",
    "get_backend",
    "resolve_backend",
    "available_backends",
]

_VALID_KINDS = ("gaussian", "sparse", "rademacher")


@dataclasses.dataclass(frozen=True)
class ProjectionSpec:
    """Immutable description of one projection matrix.

    ``density`` is the *resolved* numeric density (``'auto'`` → ``1/sqrt(d)``
    happens at the estimator layer) and is ``None`` for non-sparse kinds.
    ``dtype`` is the transform output dtype (the reference's dtype policy:
    f32→f32, f64→f64, ints promote — ``random_projection.py:386-387``).
    """

    kind: str
    n_components: int
    n_features: int
    seed: int
    density: Optional[float] = None
    dtype: str = "float64"

    def __post_init__(self):
        if self.kind not in _VALID_KINDS:
            raise ValueError(
                f"Unknown projection kind {self.kind!r}; expected one of {_VALID_KINDS}"
            )
        if self.kind == "sparse":
            if self.density is None:
                raise ValueError("kind='sparse' requires a resolved numeric density")
        self.np_dtype  # must be a valid dtype string

    @property
    def np_dtype(self) -> np.dtype:
        if self.dtype == "bfloat16":
            # numpy only understands 'bfloat16' once ml_dtypes is imported;
            # resolve via the helper so a bf16 model loads in a fresh
            # process (serialize contract: the spec alone restores a model)
            from randomprojection_tpu.utils.validation import bfloat16_dtype

            dt = bfloat16_dtype()
            if dt is not None:
                return dt
        return np.dtype(self.dtype)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ProjectionSpec":
        return cls(**d)


class ProjectionBackend(abc.ABC):
    """Executor for a projection spec.  Subclass + register to plug in."""

    #: registry key; set by subclasses
    name: str = ""

    @abc.abstractmethod
    def materialize(self, spec: ProjectionSpec) -> Any:
        """Generate the projection matrix in backend-native form (fit-time)."""

    @abc.abstractmethod
    def transform(
        self, X, state: Any, spec: ProjectionSpec, *, dense_output: bool = True
    ):
        """Compute ``X @ R.T`` for one batch ``X`` of shape ``(n, d)``.

        ``dense_output=False`` asks sparse-aware backends to keep sparse
        outputs sparse when ``X`` is sparse (scipy semantics,
        ``random_projection.py:825-827``); dense-only backends may ignore it.
        """

    def transform_async(
        self, X, state: Any, spec: ProjectionSpec, *, dense_output: bool = True
    ):
        """Like ``transform`` but may return a lazy/device-resident handle.

        Used by the streaming pipeline: the returned handle is materialized
        later (``numpy.asarray``), letting async backends overlap the next
        batch's transfer+compute with this batch's fetch.  Synchronous
        backends just return ``transform``'s result.
        """
        return self.transform(X, state, spec, dense_output=dense_output)

    @abc.abstractmethod
    def inverse_components(self, state: Any, spec: ProjectionSpec) -> np.ndarray:
        """Moore–Penrose pseudo-inverse of R, shape ``(d, k)``."""

    @abc.abstractmethod
    def inverse_transform(self, Y, inverse_components, spec: ProjectionSpec):
        """Compute ``Y @ pinv(R).T``, shape ``(n, d)``."""

    def components_to_numpy(self, state: Any, spec: ProjectionSpec):
        """Host copy of R for introspection/serialization (ndarray or CSR)."""
        return np.asarray(state)

    def close(self) -> None:
        """Release backend resources (no-op by default)."""


_REGISTRY: Dict[str, Callable[..., ProjectionBackend]] = {}
_INSTANCES: Dict[str, ProjectionBackend] = {}


def register_backend(name: str, factory: Callable[..., ProjectionBackend]) -> None:
    """Register a backend factory under a string key (the plugin seam)."""
    if not name or not isinstance(name, str):
        raise ValueError(f"Backend name must be a non-empty string, got {name!r}")
    _REGISTRY[name] = factory


def available_backends() -> Iterable[str]:
    _ensure_builtin_backends()
    return tuple(sorted(_REGISTRY))


def get_backend(name: str, **options) -> ProjectionBackend:
    """Instantiate backend ``name``.  Option-free instances are cached."""
    _ensure_builtin_backends()
    if name not in _REGISTRY:
        raise ValueError(
            f"Unknown backend {name!r}; available: {', '.join(sorted(_REGISTRY))}"
        )
    if not options:
        if name not in _INSTANCES:
            _INSTANCES[name] = _REGISTRY[name]()
        return _INSTANCES[name]
    return _REGISTRY[name](**options)


def resolve_backend(backend, **options) -> ProjectionBackend:
    """Resolve the estimator-level ``backend=`` argument.

    Accepts a ``ProjectionBackend`` instance (passed through), a registry
    key, or ``'auto'`` — which prefers ``'jax'`` when jax imports cleanly and
    falls back to ``'numpy'`` otherwise.
    """
    if isinstance(backend, ProjectionBackend):
        return backend
    if backend == "auto":
        try:
            return get_backend("jax", **options)
        except ImportError:
            return get_backend("numpy", **options)
    return get_backend(backend, **options)


def _ensure_builtin_backends() -> None:
    # Deferred so `import randomprojection_tpu` stays jax-free: the numpy
    # backend registers eagerly here; 'jax' registers a lazy factory that
    # imports jax only when actually requested.
    if "numpy" not in _REGISTRY:
        from randomprojection_tpu.backends.numpy_backend import NumpyBackend

        register_backend("numpy", NumpyBackend)
    if "jax" not in _REGISTRY:

        def _jax_factory(**options):
            from randomprojection_tpu.backends.jax_backend import JaxBackend

            return JaxBackend(**options)

        register_backend("jax", _jax_factory)
