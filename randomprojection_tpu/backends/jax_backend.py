"""JAX/TPU backend — the jit-compiled XLA execution path (layer L4 → L1).

This is the backend the whole framework exists for (``BASELINE.json:5``):
the projection matrix is generated **on device** with counter-based
``jax.random`` (never transferred from host), and ``transform`` is a
jit-compiled einsum ``X @ R.T`` that runs on the MXU.

TPU-first decisions
-------------------
- **Compute dtype is float32 by default** (``bfloat16`` available via
  ``compute_dtype=``).  TPUs have no fast f64; a spec with ``dtype=float64``
  is *executed* in f32 and the output cast on the way out.  Cross-backend
  parity is therefore defined at the pairwise-distance-distortion level
  (target ≤1e-3, ``BASELINE.json:5``), not bitwise — SURVEY.md §8.
- **Sparse kernels are dense on device.**  The MXU consumes dense tiles; a
  k×d matrix is small (256×4096 f32 = 4 MiB).  Sparse *inputs* X are
  densified per batch.  ``dense_output`` is honored trivially (always dense).
- **Static shapes for XLA.**  Batches are row-padded up to a bucket
  (octave quarter-points, ≤25% waste, multiples of 8 —
  ``parallel.sharded.row_bucket``) so a streaming loop with ragged tails
  compiles O(log n) programs, not one per batch shape.
- **Sharding-ready.**  Pass ``mesh=`` (a ``jax.sharding.Mesh``) and the
  backend places R replicated and shards batch rows over ``data_axis``; XLA
  inserts any needed collectives.  Same code, 1 chip or a pod slice
  (SURVEY.md §3.3 DP row-parallelism — the Spark map-over-partitions
  equivalent, with ICI broadcast replacing driver→executor RPC).
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import scipy.sparse as sp

from randomprojection_tpu.backends.base import ProjectionBackend, ProjectionSpec

__all__ = ["JaxBackend"]


def _matrix_key(jax, seed: int):
    """Seed → matrix-stream key, salted so a user who draws their data from
    ``jax.random.key(s)`` and fits with ``random_state=s`` cannot collide
    with the matrix stream (see numpy_backend._STREAM_SALT for the numpy
    analog and the war story)."""
    return jax.random.fold_in(jax.random.key(seed), 0x5250)


# row padding / pad-slice rules live in parallel.sharded (row_bucket /
# slice_rows_sharded) — shared with the sketch family's mesh path


class _LazyMask:
    """State of ``materialization='lazy'``: no array — just the PRNG seed.

    The matrix is regenerated inside the fused Pallas kernel per transform
    (``ops/pallas_kernels.py``), so it is never resident in HBM.
    """

    __slots__ = ("seed", "density")

    def __init__(self, seed: int, density: float):
        self.seed = seed
        self.density = float(density)


class _SplitMask:
    """State of ``precision='split2'``: unscaled ±1/0 mask in bf16 + scale.

    The mask entries are exact in bf16, so the two-pass split projection
    (``ops/split_matmul.py``) delivers f32-grade output at ~2 bf16 MXU
    passes — the fastest mode inside the 1e-3 distortion budget for the
    sparse/sign kernels.
    """

    __slots__ = ("mask", "scale")

    def __init__(self, mask, scale: float):
        self.mask = mask
        self.scale = float(scale)


class JaxBackend(ProjectionBackend):
    """XLA executor: device-resident R, jit einsum transform."""

    name = "jax"

    def __init__(
        self,
        *,
        compute_dtype: str = "float32",
        precision: Optional[str] = None,
        mesh: Optional[object] = None,
        data_axis: str = "data",
        feature_axis: Optional[str] = None,
        materialization: str = "dense",
        dispatch_steps: int = 1,
        transform_dma: Optional[bool] = None,
    ):
        import jax  # deferred: `backend='numpy'` must never import jax

        from randomprojection_tpu.ops.precision import default_matmul_precision

        self._jax = jax
        self.compute_dtype = compute_dtype
        if precision is None:
            precision = default_matmul_precision(compute_dtype)
        if precision not in ("default", "high", "highest", "split2"):
            raise ValueError(
                "precision must be 'default', 'high', 'highest' or 'split2', "
                f"got {precision!r}"
            )
        self.precision = precision
        self.mesh = mesh
        self.data_axis = data_axis
        self.feature_axis = feature_axis
        if materialization not in ("dense", "lazy"):
            raise ValueError(
                f"materialization must be 'dense' or 'lazy', got {materialization!r}"
            )
        self.materialization = materialization
        # ISSUE 9 execution knobs — deliberately backend options, NOT
        # ProjectionSpec fields: the spec defines the matrix (and thus the
        # persisted-model format); DMA routing and dispatch fusion change
        # how a transform executes, never what it computes.
        if int(dispatch_steps) < 1:
            raise ValueError(
                f"dispatch_steps must be >= 1, got {dispatch_steps}"
            )
        #: chain this many row-blocks of each lazy transform through ONE
        #: traced dispatch (call-boundary host gaps amortize by 1/K);
        #: 1 = one kernel dispatch per call (the pre-r14 behavior)
        self.dispatch_steps = int(dispatch_steps)
        #: fused-kernel x routing: None = the kernel default (manual
        #: double-buffered DMA), False pins the single-buffered tiling
        if transform_dma not in (None, True, False):
            raise ValueError(
                "transform_dma must be None (kernel default), True or "
                f"False, got {transform_dma!r}"
            )
        self.transform_dma = transform_dma
        # the knobs only steer the fused lazy kernel's single-device route
        # — warn (don't raise: CLI wiring sets them unconditionally) when
        # this backend's configuration routes around them, so a bench run
        # can't silently measure a route it never took
        if self.materialization != "lazy" and (
            self.dispatch_steps > 1 or self.transform_dma is not None
        ):
            from randomprojection_tpu.utils.observability import logger

            logger.warning(
                "dispatch_steps/transform_dma affect only the fused lazy "
                "transform kernel; materialization=%r ignores them",
                self.materialization,
            )
        elif self.mesh is not None and self.dispatch_steps > 1:
            from randomprojection_tpu.utils.observability import logger

            logger.warning(
                "dispatch_steps is ignored on the mesh path (the shard_map "
                "program dispatches per shard; only the single-device lazy "
                "route chains row-blocks through one dispatch)"
            )
        self._transform_fn = None
        self._inverse_fn = None
        self._sign_fn = None
        self._sign_fn_raw = None
        self._pack_fn = None
        self._pack_fn_raw = None
        self._split_fn = None
        self._slice_fns = {}
        self._lazy_mesh_fns = {}

    def _einsum_precision(self) -> str:
        """Precision for plain einsums ('split2' applies only to the mask
        matmul path; other einsums — pinv reconstruct, gaussian sign — use
        the accuracy-equivalent 'high')."""
        return self.precision if self.precision != "split2" else "high"

    # -- sharding helpers ---------------------------------------------------

    def _replicated_sharding(self):
        """Layout for R: replicated under pure DP; column-sharded over the
        feature axis under TP (each chip holds R[:, d_shard] — SURVEY.md
        §3.3; XLA then completes the contraction with one psum over ICI).
        Built once and cached: mesh/axes are fixed at construction, and
        this sits on the per-batch dispatch path (ISSUE r9 satellite —
        invariant work happens once, not per batch)."""
        if self.mesh is None:
            return None
        sh = self.__dict__.get("_replicated_sharding_cache")
        if sh is None:
            from jax.sharding import NamedSharding, PartitionSpec

            if self.feature_axis is not None:
                sh = NamedSharding(
                    self.mesh, PartitionSpec(None, self.feature_axis)
                )
            else:
                sh = NamedSharding(self.mesh, PartitionSpec())
            self.__dict__["_replicated_sharding_cache"] = sh
        return sh

    def _row_sharding(self):
        """Layout for X batches: rows over 'data', features over the TP axis
        when configured.  Cached like ``_replicated_sharding`` — called
        once per streamed batch."""
        if self.mesh is None:
            return None
        sh = self.__dict__.get("_row_sharding_cache")
        if sh is None:
            from jax.sharding import NamedSharding, PartitionSpec

            sh = NamedSharding(
                self.mesh, PartitionSpec(self.data_axis, self.feature_axis)
            )
            self.__dict__["_row_sharding_cache"] = sh
        return sh

    # -- ProjectionBackend API ----------------------------------------------

    def materialize(self, spec: ProjectionSpec):
        import jax
        import jax.numpy as jnp

        from randomprojection_tpu.ops import kernels

        if self.materialization == "lazy":
            if spec.kind not in ("sparse", "rademacher"):
                raise ValueError(
                    "materialization='lazy' regenerates the mask in-kernel and "
                    f"supports kind='sparse'/'rademacher' only, got {spec.kind!r}"
                )
            if spec.n_components % 8:
                # fail at fit, like the dense path's materialization would
                raise ValueError(
                    "materialization='lazy' needs n_components to be a "
                    f"multiple of 8 (f32 sublane tiling), got {spec.n_components}"
                )
            if self.mesh is not None and self.feature_axis is not None:
                from randomprojection_tpu.ops.pallas_kernels import BLOCK_D

                fshards = self.mesh.shape[self.feature_axis]
                if spec.n_features % (fshards * BLOCK_D):
                    # each TP shard regenerates its own BLOCK_D-aligned
                    # column blocks; a ragged shard would pad mid-matrix and
                    # silently redefine the block streams vs unsharded
                    raise ValueError(
                        "materialization='lazy' under feature-axis TP needs "
                        f"n_features divisible by feature_shards*BLOCK_D = "
                        f"{fshards}*{BLOCK_D}, got {spec.n_features}"
                    )
            if jax.default_backend() in ("cpu", "gpu", "cuda", "rocm"):
                # the mask is defined by the TPU hardware PRNG (pltpu.prng_*):
                # the interpreter's substitute stream (_interp_mask_block) has
                # the right distribution but a DIFFERENT stream per seed, so a
                # CPU-built projection would silently mismatch the persisted
                # TPU matrix — refuse instead (tests drive interpret=True
                # explicitly at the kernel layer)
                raise RuntimeError(
                    "materialization='lazy' requires a TPU backend (the "
                    "in-kernel PRNG has no CPU/GPU emulation); use the default "
                    "dense materialization"
                )
            return _LazyMask(spec.seed, spec.density if spec.kind == "sparse" else 1.0)

        if self.precision == "split2":
            if spec.kind not in ("sparse", "rademacher"):
                raise ValueError(
                    "precision='split2' relies on the ±1/0 mask being exact "
                    "in bf16 and supports kind='sparse'/'rademacher' only; "
                    f"got {spec.kind!r} (use precision='high' for gaussian)"
                )
            import math

            key = _matrix_key(jax, spec.seed)
            density = float(spec.density) if spec.kind == "sparse" else 1.0
            scale = 1.0 / math.sqrt(density * spec.n_components)

            # R entries are exactly ±scale (or 0) in f32, so dividing by the
            # same f32 scale yields exact ±1/0 (IEEE division: a/a == 1)
            def mask_fn(key_, kc, nf, _dt):
                R = kernels.sparse_matrix(key_, kc, nf, density, jnp.float32)
                return (R / jnp.float32(scale)).astype(jnp.bfloat16)

            if self.mesh is not None:
                # generate directly INTO the mesh layout: under feature-axis
                # TP each chip derives only its own bf16 column shard — no
                # full (k, d) f32 intermediate on any one device (same
                # invariant as the dense mesh path)
                from randomprojection_tpu.parallel.sharded import (
                    materialize_sharded,
                )

                mask = materialize_sharded(
                    mask_fn,
                    key,
                    spec.n_components,
                    spec.n_features,
                    self.mesh,
                    feature_axis=self.feature_axis,
                    dtype=jnp.bfloat16,
                )
            else:
                mask = mask_fn(
                    key, spec.n_components, spec.n_features, jnp.bfloat16
                )
            return _SplitMask(mask, scale)

        key = _matrix_key(jax, spec.seed)
        dtype = jnp.dtype(self.compute_dtype)
        if spec.kind == "gaussian":
            matrix_fn = kernels.gaussian_matrix
        elif spec.kind == "sparse":
            density = float(spec.density)
            matrix_fn = lambda k_, kc, nf, dt: kernels.sparse_matrix(  # noqa: E731
                k_, kc, nf, density, dt
            )
        elif spec.kind == "rademacher":
            matrix_fn = kernels.rademacher_matrix
        else:  # pragma: no cover - spec validates kind
            raise ValueError(spec.kind)
        if self.mesh is not None:
            # generate directly INTO the mesh layout (out_shardings): under
            # feature-axis TP each chip materializes only its column shard —
            # no full-matrix intermediate on any one device (the partition-
            # able counter PRNG keeps values identical to unsharded)
            from randomprojection_tpu.parallel.sharded import materialize_sharded

            return materialize_sharded(
                matrix_fn,
                key,
                spec.n_components,
                spec.n_features,
                self.mesh,
                feature_axis=self.feature_axis,
                dtype=dtype,
            )
        return matrix_fn(key, spec.n_components, spec.n_features, dtype)

    def _get_transform_fn(self):
        if self._transform_fn is None:
            import jax
            import jax.numpy as jnp

            precision = self._einsum_precision()

            if self.feature_axis is not None:
                # TP: contraction dim is sharded — use the explicit
                # shard_map projector (partial einsum + one psum over ICI)
                from randomprojection_tpu.parallel.sharded import (
                    make_sharded_projector,
                )

                self._transform_fn = make_sharded_projector(
                    self.mesh,
                    data_axis=self.data_axis,
                    feature_axis=self.feature_axis,
                    precision=precision,
                )
                return self._transform_fn

            @jax.jit
            def _project(x, r):
                # einsum 'nd,kd->nk' — one MXU contraction per batch.
                # f32 accumulation even for bf16 inputs (MXU native); the
                # output is cast to the spec dtype only at the host edge.
                y = jnp.einsum(
                    "nd,kd->nk",
                    x,
                    r,
                    preferred_element_type=jnp.float32,
                    precision=precision,
                )
                return y.astype(x.dtype)

            self._transform_fn = _project
        return self._transform_fn

    def transform(self, X, state, spec: ProjectionSpec, *, dense_output: bool = True):
        y, device_resident = self._transform_impl(X, state, spec)
        if device_resident:
            return y
        return np.asarray(y).astype(spec.np_dtype, copy=False)

    def transform_async(
        self, X, state, spec: ProjectionSpec, *, dense_output: bool = True
    ):
        # device-resident handle either way; the stream pipeline fetches it
        # later, overlapping with the next batch's dispatch
        y, _ = self._transform_impl(X, state, spec)
        return y

    def prepare_batch(self, X, spec: ProjectionSpec):
        """Prefetch-stage hook: start the H2D upload of a streaming batch
        OFF the dispatch thread (``streaming.PrefetchSource(prepare=...)``).

        Returns a device-resident array that ``_prepare_rows`` recognizes
        (``device_resident=True``), so the later ``_transform_async`` call
        only pads on device and launches the kernel — the transfer overlaps
        the previous batch's device compute instead of serializing in the
        dispatch path.  Under a mesh the batch is returned unchanged (the
        dispatch path pads *before* sharding, so an early unsharded upload
        would just be re-laid-out); host backends never see this method.
        """
        import jax

        from randomprojection_tpu.utils.observability import annotate

        if self.mesh is not None:
            return X
        x = self._host_cast(X, allow_bf16=spec.dtype == "bfloat16")
        with annotate("rp:stream/h2d_prefetch"):
            return jax.device_put(x)

    def _host_cast(self, X, *, allow_bf16: bool):
        """Densify + apply the dtype policy (bf16 pass-through only when
        the spec allows it) + make contiguous — the host half of
        ``_prepare_rows``' preamble, shared with ``prepare_batch`` so the
        bytes-on-wire policy cannot drift between the prefetched and
        synchronous paths."""
        import jax.numpy as jnp

        if sp.issparse(X):
            X = X.toarray()
        X = np.asarray(X)
        keep_bf16 = allow_bf16 and jnp.dtype(X.dtype) == jnp.bfloat16
        return np.ascontiguousarray(
            X, dtype=None if keep_bf16 else self.compute_dtype
        )

    def _prepare_rows(self, X, *, allow_bf16: bool = False):
        """Shared batch preamble: densify, cast, row-bucket pad, shard, place.

        Returns ``(x_on_device, n_real_rows, device_resident)``.
        """
        import jax
        import jax.numpy as jnp

        from randomprojection_tpu.utils.observability import annotate

        with annotate("rp:backend/prepare"):
            device_resident = isinstance(X, jax.Array)

            # bf16 inputs stay bf16 through the h2d transfer (half the PCIe
            # bytes — SURVEY.md §7 R3); einsum/type promotion upcasts on
            # DEVICE, which is exact (every bf16 value is exact in f32).
            # Gated on the spec's dtype policy (``allow_bf16``): an
            # estimator fitted f32 must keep producing f32 even when handed
            # a bf16 array.  The host half of the policy lives in
            # ``_host_cast`` (shared with ``prepare_batch``).
            if device_resident:
                keep_bf16 = allow_bf16 and jnp.dtype(X.dtype) == jnp.bfloat16
                x = X if keep_bf16 else X.astype(jnp.dtype(self.compute_dtype))
            else:
                x = self._host_cast(X, allow_bf16=allow_bf16)
            n = x.shape[0]

            from randomprojection_tpu.parallel.sharded import row_bucket

            pad_to = row_bucket(n, self.mesh, self.data_axis)
            if pad_to != n:
                pad = ((0, pad_to - n), (0, 0))
                x = jnp.pad(x, pad) if device_resident else np.pad(x, pad)
            row_sharding = self._row_sharding()
            if not device_resident or row_sharding is not None:
                with annotate("rp:backend/h2d"):
                    x = jax.device_put(x, row_sharding)
        return x, n, device_resident

    def _get_split_fn(self):
        if self._split_fn is None:
            import jax

            if self.feature_axis is not None:
                # split2 × TP: per-shard hi/lo partial einsums, one psum —
                # the same collective budget as the dense TP path
                from randomprojection_tpu.parallel.sharded import (
                    make_sharded_split2_projector,
                )

                self._split_fn = make_sharded_split2_projector(
                    self.mesh,
                    data_axis=self.data_axis,
                    feature_axis=self.feature_axis,
                )
            else:
                from randomprojection_tpu.ops.split_matmul import split2_project

                @jax.jit
                def _project_split(x, mask, scale):
                    return split2_project(x, mask, scale).astype(x.dtype)

                self._split_fn = _project_split
        return self._split_fn

    def _lazy_mxu_mode(self) -> str:
        """Contraction arithmetic for the fused lazy kernel.

        Mosaic has no multi-pass f32 dot (``precision=HIGH`` raises
        ``NotImplementedError`` in the lowering), so precision requests of
        ``'high'``/``'highest'``/``'split2'`` — including the backend's f32
        *default* of ``'high'`` — are all served by the in-kernel split2
        contraction (``ops/pallas_kernels.py``): X split hi/lo bf16 in VMEM
        vs the exact-in-bf16 mask, 2 single-pass MXU contractions — MORE
        accurate than 3-pass 'high' (~1e-6 vs ~2.2e-5 distortion) at 2/3
        the MXU cost.  Only an explicit ``precision='default'`` opts into
        the single-pass f32 dot (bf16-grade, fastest).
        """
        return "f32" if self.precision == "default" else "split2"

    def _get_lazy_mesh_fn(self, state, spec: ProjectionSpec, mxu_mode: str,
                          no_cache: bool = False, dma: Optional[bool] = None):
        """shard_map'd fused lazy projection over the mesh.

        DP: each device runs the fused kernel on its row shard — the matrix
        definition is row-tile-independent, so every shard regenerates the
        same (full) mask stream; zero collectives.  DP×TP: each device
        passes its BLOCK_D-aligned column-block offset into the kernel seed
        (``fused_sparse_project(block_offset=...)``) so it contracts against
        exactly its own blocks of the global matrix, then one psum over the
        feature axis completes the contraction — same collective budget as
        the dense TP path, still no R in HBM anywhere.
        """
        cache_key = (
            state.seed, state.density, spec.n_components, mxu_mode, no_cache,
            dma,
        )
        fn = self._lazy_mesh_fns.get(cache_key)
        if fn is not None:
            return fn
        import jax
        from jax.sharding import PartitionSpec as P

        from randomprojection_tpu.ops.pallas_kernels import (
            BLOCK_D,
            fused_sparse_project,
        )

        seed, density, k = state.seed, state.density, spec.n_components
        data_axis, feature_axis = self.data_axis, self.feature_axis

        if feature_axis is None:
            in_specs = (P(data_axis, None),)

            def local(x):
                # block_n=None: the kernel picks the largest VMEM-fitting
                # row tile for this shard's row count
                return fused_sparse_project(
                    x, seed, k, density, mxu_mode=mxu_mode,
                    no_cache=no_cache, dma=dma,
                )

        else:
            in_specs = (P(data_axis, feature_axis),)

            def local(x):
                offset = jax.lax.axis_index(feature_axis) * (
                    x.shape[1] // BLOCK_D
                )
                partial = fused_sparse_project(
                    x, seed, k, density,
                    block_offset=offset,
                    mxu_mode=mxu_mode,
                    no_cache=no_cache,
                    dma=dma,
                )
                return jax.lax.psum(partial, feature_axis)

        fn = jax.jit(
            jax.shard_map(
                local, mesh=self.mesh, in_specs=in_specs,
                out_specs=P(data_axis, None),
                # pallas_call's out_shape carries no varying-mesh-axis info,
                # so shard_map's vma checker can't see through it; the
                # collective structure here is explicit (one psum) and
                # covered by tests
                check_vma=False,
            )
        )
        self._lazy_mesh_fns[cache_key] = fn
        return fn

    def _slice_rows(self, y, n: int):
        """Drop pad rows (see ``parallel.sharded.slice_rows_sharded`` for
        the mesh/ragged rules)."""
        from randomprojection_tpu.parallel.sharded import slice_rows_sharded

        return slice_rows_sharded(
            y, n, self.mesh, self.data_axis, cache=self._slice_fns
        )

    def _transform_impl(self, X, state, spec: ProjectionSpec):
        from randomprojection_tpu.utils import telemetry
        from randomprojection_tpu.utils.observability import annotate

        x, n, device_resident = self._prepare_rows(
            X, allow_bf16=spec.dtype == "bfloat16"
        )
        telemetry.registry().counter_inc("backend.dispatches")
        if telemetry.enabled():
            # trace_fields(): inside a streamed transform the dispatch
            # stage span is active on this thread, so the backend's own
            # dispatch record correlates with its batch trace
            telemetry.emit(
                telemetry.EVENTS.BACKEND_DISPATCH, kind=spec.kind,
                rows=int(n),
                n_features=spec.n_features, n_components=spec.n_components,
                device_resident=bool(device_resident),
                **telemetry.trace_fields(),
            )
        with annotate("rp:backend/project"):
            # donate only buffers this backend created (host uploads):
            # a user's device-resident input must survive the call
            return self._project_prepared(
                x, n, state, spec, donate=not device_resident
            ), device_resident

    def _project_prepared(self, x, n, state, spec: ProjectionSpec, *,
                          donate: bool = False):
        if isinstance(state, _SplitMask):
            y = self._get_split_fn()(
                x.astype(self._jax.numpy.float32), state.mask, state.scale
            ).astype(x.dtype)
        elif isinstance(state, _LazyMask):
            jnp = self._jax.numpy
            # bf16 input (only possible when the spec's dtype policy allowed
            # it in _prepare_rows) stays bf16 through the fused kernel: one
            # MXU pass against the exact mask IS the data's own precision,
            # at half the x HBM traffic of the f32 modes.
            if x.dtype == jnp.bfloat16:
                mxu_mode, xc = "bf16", x
            else:
                mxu_mode, xc = self._lazy_mxu_mode(), x.astype(jnp.float32)
            if self.mesh is not None:
                # per-SHAPE memos of scoped-VMEM compile failures: jit
                # compiles the (shape-agnostic) mesh fn per input shape, so
                # one exotic batch shape blowing VMEM must route only ITS
                # shape to a degraded variant — healthy shapes keep the
                # DMA + cached-mask kernel (same shape granularity as
                # pallas_kernels._NO_DMA_KEYS/_NO_CACHE_KEYS).  The
                # shard_map compiles outside fused_sparse_project's own
                # eager fallback frame, so the ladder — DMA off first
                # (single-buffered tiling), then the mask cache off
                # (regenerate-every-step) — lives at this call site.
                oom_shapes = self.__dict__.setdefault(
                    "_lazy_oom_shapes", set()
                )
                dma_off_shapes = self.__dict__.setdefault(
                    "_lazy_dma_off_shapes", set()
                )
                shape_key = (
                    state.seed, state.density, spec.n_components, mxu_mode,
                    tuple(xc.shape),
                )
                from randomprojection_tpu.ops.pallas_kernels import (
                    _vmem_ladder,
                )

                dma_opt = (
                    False if shape_key in dma_off_shapes
                    else self.transform_dma
                )

                def _mesh_call(a_dma, a_nc):
                    return self._get_lazy_mesh_fn(
                        state, spec, mxu_mode, no_cache=a_nc, dma=a_dma,
                    )(xc)

                # traced=True: these dispatches are already counted by
                # backend.dispatch — the eager route event would double-count
                y = _vmem_ladder(
                    _mesh_call, shape_key, dma_opt,
                    shape_key not in oom_shapes, xc.shape, mxu_mode,
                    spec.n_components, traced=True,
                    no_dma_keys=dma_off_shapes, no_cache_keys=oom_shapes,
                    label="fused lazy kernel",
                )
                y = y.astype(x.dtype)
            else:
                from randomprojection_tpu.ops.pallas_kernels import (
                    fused_project_multistep,
                    fused_sparse_project,
                    multistep_chain_length,
                )

                # block_n=None: the kernel's shape-aware auto tile (largest
                # VMEM-fitting row tile, no re-padding of small batches)
                if self.dispatch_steps > 1 and xc.shape[0] > 1:
                    # multi-step dispatch fusion (ISSUE 9): chain K
                    # row-blocks through ONE traced dispatch; donate only
                    # when the input arrived as a host array (the upload
                    # inside the jit is then a buffer nothing else
                    # references).  A device-resident input is never
                    # donated — including prepare_batch uploads: their
                    # provenance isn't tracked through the prefetch
                    # queue, so they are conservatively treated as
                    # user-owned and survive the call
                    y = fused_project_multistep(
                        xc,
                        state.seed,
                        spec.n_components,
                        state.density,
                        steps=self.dispatch_steps,
                        mxu_mode=mxu_mode,
                        dma=self.transform_dma,
                        donate=donate,
                    )
                    from randomprojection_tpu.utils import telemetry

                    if telemetry.enabled():
                        telemetry.emit(
                            telemetry.EVENTS.BACKEND_DISPATCH_FUSED,
                            rows=int(xc.shape[0]),
                            # launches actually chained, not the knob:
                            # the clamp + ceil-split can round below
                            # dispatch_steps on small batches
                            steps=multistep_chain_length(
                                xc.shape[0], self.dispatch_steps
                            ),
                            n_components=spec.n_components,
                            donated=bool(donate),
                            **telemetry.trace_fields(),
                        )
                    y = y.astype(x.dtype)
                else:
                    y = fused_sparse_project(
                        xc,
                        state.seed,
                        spec.n_components,
                        state.density,
                        mxu_mode=mxu_mode,
                        dma=self.transform_dma,
                    ).astype(x.dtype)
        else:
            y = self._get_transform_fn()(x, state)
        return self._slice_rows(y, n)

    def transform_packed_signs(
        self, X, state, spec: ProjectionSpec, *, materialize: bool = True
    ):
        """Fused SimHash path: einsum → sign → packbits, all on device.

        Output is ``(n, ceil(k/8))`` uint8 — shrinking the d2h transfer 32×
        vs f32 coordinates (the point of config 4's 1B-row workload).
        ``materialize=False`` returns the device handle (streaming pipeline).
        """
        import jax
        import jax.numpy as jnp

        if self._sign_fn is None:
            precision = self._einsum_precision()

            def _sign_project(x, r):
                y = jnp.einsum(
                    "nd,kd->nk", x, r,
                    preferred_element_type=jnp.float32, precision=precision,
                )
                return jnp.packbits(y > 0, axis=-1, bitorder="little")

            # keep the raw body alongside the jitted wrapper: when this
            # path is invoked INSIDE an outer trace (a jitted serving
            # loop or harness), calling the raw body inlines the
            # einsum+packbits into the caller's program — a nested-pjit
            # call boundary would survive into the jaxpr and fence XLA
            # fusion with the surrounding computation (the r05
            # estimator_vs_raw = 0.83 gap's structural suspect)
            self._sign_fn_raw = _sign_project
            self._sign_fn = jax.jit(_sign_project)

        if isinstance(state, (_LazyMask, _SplitMask)):
            # lazy/split paths: compute coordinates, then pack on device
            y_coords, device_resident = self._transform_impl(X, state, spec)
            if self._pack_fn is None:
                self._pack_fn_raw = lambda a: jnp.packbits(
                    a > 0, axis=-1, bitorder="little"
                )
                self._pack_fn = jax.jit(self._pack_fn_raw)
            pack = (
                self._pack_fn_raw
                if isinstance(y_coords, jax.core.Tracer)
                else self._pack_fn
            )
            y = pack(y_coords)
        else:
            from randomprojection_tpu.utils.observability import annotate

            x, n, device_resident = self._prepare_rows(
                X, allow_bf16=spec.dtype == "bfloat16"
            )
            fn = (
                self._sign_fn_raw
                if isinstance(x, jax.core.Tracer)
                else self._sign_fn
            )
            with annotate("rp:backend/sign_project"):
                y = self._slice_rows(fn(x, state), n)
        if device_resident or not materialize:
            return y
        return np.asarray(y)

    def _lazy_matrix(self, state, spec: ProjectionSpec):
        from randomprojection_tpu.ops.pallas_kernels import pallas_sparse_matrix

        return pallas_sparse_matrix(
            state.seed,
            spec.n_components,
            spec.n_features,
            state.density,
        )

    def inverse_components(self, state, spec: ProjectionSpec) -> np.ndarray:
        import jax.numpy as jnp

        if isinstance(state, _LazyMask):
            state = self._lazy_matrix(state, spec)
        elif isinstance(state, _SplitMask):
            state = state.mask.astype(jnp.float32) * state.scale
        # XLA SVD on the small (k, d) matrix; host copy for serialization
        return np.asarray(jnp.linalg.pinv(state.astype(jnp.float32)))

    def inverse_transform(self, Y, inverse_components, spec: ProjectionSpec):
        import jax
        import jax.numpy as jnp

        device_resident = isinstance(Y, jax.Array)
        if sp.issparse(Y):
            Y = Y.toarray()
        y = jnp.asarray(Y, dtype=jnp.dtype(self.compute_dtype))
        inv = jnp.asarray(inverse_components, dtype=jnp.dtype(self.compute_dtype))
        if self._inverse_fn is None:
            precision = self._einsum_precision()

            @jax.jit
            def _reconstruct(a, b):
                return jnp.einsum(
                    "nk,dk->nd", a, b,
                    preferred_element_type=jnp.float32, precision=precision,
                ).astype(a.dtype)

            self._inverse_fn = _reconstruct
        x = self._inverse_fn(y, inv)
        if device_resident:
            return x
        return np.asarray(x).astype(spec.np_dtype, copy=False)

    def components_to_numpy(self, state, spec: ProjectionSpec):
        if isinstance(state, _LazyMask):
            state = self._lazy_matrix(state, spec)
        elif isinstance(state, _SplitMask):
            state = state.mask.astype(self._jax.numpy.float32) * state.scale
        return np.asarray(state).astype(spec.np_dtype, copy=False)
