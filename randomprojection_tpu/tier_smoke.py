"""``make tier-smoke``: tiered hot/cold residency parity (ISSUE 19).

Asserts, at toy shapes on CPU, the acceptance contract of the r21
tiered-residency layer: an index whose corpus is 4× an artificially
capped HBM budget — one chunk hot, three cold — answers BIT-IDENTICALLY
to a fully resident index on every serving path (exact top-k, LSH
candidate tier at partial and full probe coverage, tombstones spanning
the hot/cold seam, the 8-shard merge with per-shard budgets, and the
disk rung's memmap-backed spills), the hot set never exceeds the
budget, the degraded rung (an injected staging-upload failure) still
returns exact answers while landing on the fallback counter, and a
tiered snapshot round-trips through ``durable`` with its residency
block verified.  Runs before tier-1 in ``make verify`` on the same
virtual-8-device topology the shard smoke uses.
"""

from __future__ import annotations

import os
import tempfile

import numpy as np

__all__ = ["main"]


def main() -> None:
    import jax

    from randomprojection_tpu import durable
    from randomprojection_tpu.ann import (
        LSHShardedSimHashIndex,
        LSHSimHashIndex,
    )
    from randomprojection_tpu.models import sketch as sk
    from randomprojection_tpu.models.sketch import SimHashIndex
    from randomprojection_tpu.utils import telemetry

    n_dev = len(jax.devices())
    rng = np.random.default_rng(0)
    chunk_rows, n_bytes, n_chunks = 600, 8, 4
    codes = rng.integers(
        0, 256, size=(chunk_rows * n_chunks, n_bytes), dtype=np.uint8
    )
    queries = rng.integers(0, 256, size=(24, n_bytes), dtype=np.uint8)
    m = 7
    # the acceptance shape: the corpus is 4× the HBM budget — exactly
    # one of the four equal chunks fits hot, three live cold
    budget = chunk_rows * n_bytes

    def build(cls, **kw):
        idx = cls(codes[:chunk_rows], **kw)
        for lo in range(chunk_rows, codes.shape[0], chunk_rows):
            idx.add(codes[lo : lo + chunk_rows])
        return idx

    # -- exact path: 4×-over-budget vs fully resident -----------------------
    ref = build(SimHashIndex)
    tiered = build(SimHashIndex, hbm_budget_bytes=budget)
    r = tiered._tier.residency()
    assert r["hot_bytes"] <= budget, "hot set exceeds the HBM budget"
    assert any(c["tier"] != "hot" for c in r["chunks"]), (
        "4x-over-budget index has no cold chunks — the cap is not binding"
    )
    rd, ri = ref.query_topk(queries, m)
    td, ti = tiered.query_topk(queries, m)
    assert np.array_equal(td, rd) and np.array_equal(ti, ri), (
        "exact path: tiered != fully resident"
    )

    # -- LSH candidate tier: partial + full probes, tombstones --------------
    full = 1 << 4
    lref = build(LSHSimHashIndex, bands=4, band_bits=4,
                 fallback_density=1.0, probe_path="host")
    ltier = build(LSHSimHashIndex, bands=4, band_bits=4,
                  fallback_density=1.0, probe_path="host",
                  hbm_budget_bytes=budget)
    for p in (2, full):
        rd2, ri2 = lref.query_topk(queries, m, probes=p)
        td2, ti2 = ltier.query_topk(queries, m, probes=p)
        assert np.array_equal(td2, rd2) and np.array_equal(ti2, ri2), (
            f"LSH path at probes={p}: tiered != fully resident"
        )
    # tombstones spanning the hot/cold chunk seam filter identically
    dead = np.arange(chunk_rows - 60, chunk_rows + 60)
    lref.delete(dead)
    ltier.delete(dead)
    rd3, ri3 = lref.query_topk(queries, m, probes=full)
    td3, ti3 = ltier.query_topk(queries, m, probes=full)
    assert np.array_equal(td3, rd3) and np.array_equal(ti3, ri3), (
        "tombstoned LSH path: tiered != fully resident"
    )
    # full coverage is still brute force through the tiered merge
    D = sk.pairwise_hamming(queries, codes).astype(np.int64)
    D[:, dead] = n_bytes * 8 + 1
    bd, bi = sk._host_topk_select(D, m)
    assert np.array_equal(td3, bd) and np.array_equal(ti3, bi), (
        "tiered full-probe LSH != masked brute force"
    )

    # -- degraded rung: injected upload failure, exact answers --------------
    from randomprojection_tpu.ops import topk_kernels

    reg = telemetry.registry()
    before = reg.counter("index.tier.fallbacks")
    orig = topk_kernels.stage_rows

    def _boom(*a, **k):
        raise RuntimeError("injected staging failure")

    topk_kernels.stage_rows = _boom
    try:
        fd, fi = ltier.query_topk(queries, m, probes=full)
    finally:
        topk_kernels.stage_rows = orig
    assert np.array_equal(fd, rd3) and np.array_equal(fi, ri3), (
        "upload-failure rung returned wrong answers"
    )
    assert reg.counter("index.tier.fallbacks") > before, (
        "upload-failure rung never hit the fallback counter"
    )

    # -- disk rung: memmap-backed spills, same parity -----------------------
    with tempfile.TemporaryDirectory() as td_:
        cold_dir = os.path.join(td_, "cold")
        disk = build(SimHashIndex, hbm_budget_bytes=budget,
                     cold_tier="disk", cold_dir=cold_dir)
        spills = [f for f in os.listdir(cold_dir)
                  if f.startswith("chunk-")]
        assert len(spills) == n_chunks - 1, (
            f"disk tier spilled {len(spills)} chunks, expected "
            f"{n_chunks - 1}"
        )
        dd, di = disk.query_topk(queries, m)
        assert np.array_equal(dd, rd) and np.array_equal(di, ri), (
            "disk-tier exact path != fully resident"
        )
        # tiered snapshot round-trip: the residency block verifies and
        # a budget-less restore loads everything hot with equal answers
        snap = os.path.join(td_, "snap")
        manifest = durable.save_index(disk, snap)
        assert manifest["tier"]["cold_tier"] == "disk"
        status = durable.verify_snapshot(snap)
        assert status["ok"] and status["tier"]["cold_chunks"] > 0, (
            f"tiered snapshot failed verification: {status}"
        )
        restored = durable.load_index(snap)
        ld, li = restored.query_topk(queries, m)
        assert np.array_equal(ld, rd) and np.array_equal(li, ri), (
            "snapshot-restored index != fully resident"
        )
        disk.close()

    # -- 8-shard merge with per-shard budgets (incl. tombstones) ------------
    sref = LSHShardedSimHashIndex(codes, n_shards=8, bands=4, band_bits=4,
                                  fallback_density=1.0, probe_path="host")
    stier = LSHShardedSimHashIndex(
        codes, n_shards=8, bands=4, band_bits=4, fallback_density=1.0,
        probe_path="host", hbm_budget_bytes=n_bytes,
    )  # per-shard budget below any chunk: every shard serves all-cold
    for idx_ in (sref, stier):
        idx_.delete(np.arange(200, 420))
    sd, si = sref.query_topk(queries, m, probes=full)
    td4, ti4 = stier.query_topk(queries, m, probes=full)
    assert np.array_equal(td4, sd) and np.array_equal(ti4, si), (
        "8-shard tiered merge != fully resident sharded"
    )
    stier.close()
    for idx_ in (ref, tiered, lref, ltier):
        idx_.close()

    print(
        f"tier-smoke OK: 4x-over-budget tiered index bit-identical to "
        f"fully resident on {n_dev} device(s) — exact + LSH "
        "(partial/full probes), seam-spanning tombstones, injected "
        "upload-failure rung, disk-tier memmap spills, snapshot "
        "round-trip with verified residency block, 8-shard all-cold "
        "merge"
    )


if __name__ == "__main__":
    main()
