"""Multi-probe LSH candidate tier over SimHash bucket indexes (ISSUE 15).

Every query so far was an exact linear Hamming scan: the r12 fused
kernel made the scan fast and r13 spread it over shards, but at the
BL:10 billion-code scale each query still touches every code, so q/s is
bounded by corpus bandwidth no matter how good the kernel gets.
SimHash codes ARE an LSH family (Charikar 2002; multi-probe after Lv et
al. 2007): two codes that agree on a contiguous ``b``-bit **band** of
their sign bits are close with probability rising steeply as their
angle shrinks, so bucketing every code by ``L`` disjoint band keys
turns candidate generation into ``O(candidates)`` bucket lookups — the
exact kernel then re-ranks ONLY the candidates.

The tier, bottom to top:

- **Band keys** (``band_keys``) — code bits ``[j·b, (j+1)·b)`` of each
  packed code word form band ``j``'s key (little-endian bit order,
  matching ``np.packbits(bitorder='little')``).  A pure function of the
  codes, so the banded index is always rebuildable from a snapshot.
- **Banded CSR buckets** (``BandedBuckets``) — per band, a counting-
  sorted CSR layout ``indptr (2^b + 1) → ids`` with ids ascending
  within every bucket.  ``add`` merges new rows *incrementally*: only
  the new rows' keys are extracted and counting-sorted, then spliced
  into the existing CSR by a vectorized two-way merge — resident rows
  are never re-hashed.  Host-resident by design: the index is O(L·n)
  int32 beside an O(n·n_bytes) corpus, and the per-query probe work is
  O(L·P) ``searchsorted``-free pointer lookups.
- **Multi-probe** (``probe_masks``) — each band probes its exact bucket
  plus the nearest ``P-1`` perturbations: XOR masks in (popcount,
  ascending value) order, the uniform-confidence specialization of
  Lv et al.'s score order (packed codes carry sign bits only — no
  per-bit projection magnitudes survive the sketch, so every bit is
  equally confident and the perturbation order is data-independent and
  deterministic).  ``P ≥ 2^b`` probes every bucket of every band —
  full probe coverage — which makes the candidate set the whole live
  corpus and the result **bit-identical to brute force** (the parity
  discipline every kernel round ships under; ``make ann-smoke``).
- **Exact re-rank** (``LSHSimHashIndex.query_topk``) — per query tile,
  candidates deduplicate across bands, probes and the tile's queries
  (one sorted ``np.unique`` union; ascending global id order is what
  makes the re-rank's local tie-break equal the documented
  (distance, lower-global-id) order), tombstoned rows are filtered,
  the candidate code rows are gathered ON DEVICE from the resident
  chunks, and the r12 fused kernel scores the tile against them —
  in-kernel DMA'd Hamming matmul + bitonic running top-m, exactly the
  machinery the full scan uses, on 1/10th (or 1/1000th) of the rows.
- **Fallback ladder** — the tier NEVER serves worse than the exact
  path: a tile whose candidate union is too dense (``> fallback_density
  · n_live`` — re-rank would approach scan cost) or too starved
  (``< m`` — the result could not fill) falls back to the exact
  device ladder for that tile, recorded as ``index.lsh.fallback``;
  a scoped-VMEM OOM in the re-rank kernel degrades to a device-Hamming
  + host-select rung (same order, same results).  ``probes=0`` pins
  the exact path outright.

Sharding: ``LSHShardedSimHashIndex`` builds one banded index per shard
(the shard hook ``ShardedSimHashIndex._make_shard``), probes and
re-ranks per shard, and merges per-shard candidates through the SAME
``_merge_tile`` lexsort as the exact tier — cross-shard tombstones and
``id_offset`` global ids carry over unchanged.  Serving: both classes
keep the ``query_topk(A, m, tile=)`` surface, so they plug directly
into ``TopKServer`` / ``ShardedTopKServer`` — the micro-batcher fans
coalesced batches into the LSH tier with no server changes.

Durability: band keys persist beside the chunks in the r11 manifest
(``lsh-<gen>.npy``, SHA-256-checksummed, **global id order** — so the
spill is layout-fungible exactly like r13 sharded snapshots), and
loading verifies the persisted keys against keys rebuilt from the
restored codes — corruption or extraction drift is a loud
``ValueError``, never a silently-wrong bucket index.  A pre-LSH
(r11-format) snapshot loads cleanly with the index rebuilt from codes.

**Device-fused candidate generation (ISSUE 16)** — the probe half
above runs on the host; at production q/s that hop is the serving
floor.  ``probe_path='device'`` (or ``'auto'`` on a real accelerator)
mirrors the banded CSR onto the device (``_lsh_device_csr``, revision-
clocked against every bucket mutation) and serves each tile through
``ops.probe_kernels.device_probe_topk`` — band keys, probe walks,
sort-unique dedup, tombstone masking, chunk gather and the r12 fused
re-rank in ONE dispatch, the only per-tile host bytes being the query
upload.  A post-hoc ladder (the stats plane read at finish time)
degrades overflowing / starved / too-dense tiles to the exact path,
and shapes the probe planner cannot tile serve the host rung
(``device_plan``, memoized).  ``adaptive=True`` escalates probes
per query in popcount rounds with an early-exit distance bound and an
optional ``candidate_budget`` (see ``_lsh_adaptive_tile`` — safe by
construction, recall monotone in the budget).  At full probe coverage
the device path remains bit-identical to host probing and to brute
force (``make ann-smoke``'s device-parity leg).

Telemetry: ``index.lsh.dispatch`` (probe counts, candidate fraction),
``index.lsh.fallback`` (reason — the doctor's degraded audit),
``index.lsh.build`` (bucket folds), plus the device tier's
``index.lsh.device_dispatch`` / ``index.lsh.device_upload`` /
``index.lsh.adaptive`` — all in ``telemetry.EVENTS`` and consumed by
``trace_report``'s candidate-generation section.
"""

from __future__ import annotations

import itertools
import numbers
import os
import time
from typing import Optional

import numpy as np

from randomprojection_tpu.models.sketch import (
    SimHashIndex,
    _hamming_tile_fn,
    _host_topk_select,
    _start_host_copy,
)
from randomprojection_tpu.serving.sharded_index import ShardedSimHashIndex
from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS

__all__ = [
    "BandPlan",
    "band_keys",
    "probe_masks",
    "BandedBuckets",
    "LSHSimHashIndex",
    "LSHShardedSimHashIndex",
    "load_lsh_index",
    "load_lsh_sharded_index",
]

# bucket-space ceiling: indptr is (2^b + 1) int64 per band — b=20 is
# 8 MB/band, past which the CSR pointer array stops being "beside the
# corpus" and becomes a corpus of its own
_MAX_BAND_BITS = 20
# band-key extraction block: bounds the unpacked bit matrix to
# ~2 MB/256-bit codes however large one add() is
_KEY_EXTRACT_BLOCK = 1 << 16
# device-CSR id pad: one maximal DMA block of sentinel ids per band, so
# a ragged last run block can overread past ``end`` without clamping
# (covers every ``blk`` ops/probe_kernels.plan_probe can pick)
_LSH_IDS_PAD = 512
_INT32_MAX = np.int32(2**31 - 1)

_PROBE_PATHS = ("auto", "host", "device")


def _check_probe_path(probe_path) -> str:
    if probe_path not in _PROBE_PATHS:
        raise ValueError(
            f"probe_path must be one of {_PROBE_PATHS}, got {probe_path!r}"
        )
    return str(probe_path)


def _check_ctor_probes(probes) -> int:
    """Constructor ``probes`` validation: a strictly positive real int.
    ``bool`` is ``numbers.Integral``, so it is rejected explicitly — a
    ``probes=True`` caller almost certainly meant a count, and silently
    probing once would be a recall cliff."""
    if (isinstance(probes, bool) or not isinstance(probes, numbers.Integral)
            or probes < 1):
        raise ValueError(
            f"probes must be a positive int, got {probes!r}"
        )
    return int(probes)


def _check_budget(budget) -> Optional[int]:
    """Adaptive per-query candidate budget: None (uncapped — the probes
    ceiling and early-exit bound alone stop escalation) or a strictly
    positive real int."""
    if budget is None:
        return None
    if (isinstance(budget, bool) or not isinstance(budget, numbers.Integral)
            or budget < 1):
        raise ValueError(
            f"candidate_budget must be a positive int or None, got "
            f"{budget!r}"
        )
    return int(budget)


class BandPlan:
    """Resolved band layout: ``bands`` disjoint ``band_bits``-bit key
    slices over the leading ``bands·band_bits`` code bits.

    Defaults: ``band_bits = min(16, n_bits)`` (65536 buckets — sparse
    at any per-shard corpus size that fits int32 ids) and ``bands =
    min(8, n_bits // band_bits)`` (8 independent collision chances per
    probe).  Bands must fit the real bit count — ragged codes (e.g. 20
    bits in 3 bytes) never key on pad bits."""

    __slots__ = ("n_bits", "bands", "band_bits")

    def __init__(self, n_bits: int, *, bands: Optional[int] = None,
                 band_bits: Optional[int] = None):
        n_bits = int(n_bits)
        if n_bits < 1:
            raise ValueError(f"n_bits must be >= 1, got {n_bits}")
        if band_bits is None:
            band_bits = min(16, n_bits)
        band_bits = int(band_bits)
        if not 1 <= band_bits <= _MAX_BAND_BITS:
            raise ValueError(
                f"band_bits must be in [1, {_MAX_BAND_BITS}], got "
                f"{band_bits}"
            )
        if bands is None:
            bands = max(1, min(8, n_bits // band_bits))
        bands = int(bands)
        if bands < 1:
            raise ValueError(f"bands must be >= 1, got {bands}")
        if bands * band_bits > n_bits:
            raise ValueError(
                f"bands={bands} x band_bits={band_bits} needs "
                f"{bands * band_bits} code bits but the codes carry only "
                f"{n_bits}; bands are disjoint slices of the real bits"
            )
        self.n_bits = n_bits
        self.bands = bands
        self.band_bits = band_bits

    def __eq__(self, other):
        return (
            isinstance(other, BandPlan)
            and (self.n_bits, self.bands, self.band_bits)
            == (other.n_bits, other.bands, other.band_bits)
        )

    def __repr__(self):  # pragma: no cover — debugging aid
        return (
            f"BandPlan(n_bits={self.n_bits}, bands={self.bands}, "
            f"band_bits={self.band_bits})"
        )


def band_keys(codes, plan: BandPlan) -> np.ndarray:
    """Band keys of packed codes: ``(bands, n)`` uint32, key ``j`` of a
    row being its code bits ``[j·b, (j+1)·b)`` (little-endian within
    each byte, matching ``np.packbits(bitorder='little')`` and the
    Hamming kernels).  Pure host function of the codes — the banded
    index is always rebuildable from any snapshot."""
    codes = np.ascontiguousarray(codes, dtype=np.uint8)
    if codes.ndim != 2:
        raise ValueError(f"codes must be (n, nbytes), got {codes.shape}")
    n = codes.shape[0]
    b = plan.band_bits
    out = np.empty((plan.bands, n), np.uint32)
    w = np.uint32(1) << np.arange(b, dtype=np.uint32)
    for lo in range(0, n, _KEY_EXTRACT_BLOCK):
        hi = min(lo + _KEY_EXTRACT_BLOCK, n)
        bits = np.unpackbits(codes[lo:hi], axis=1, bitorder="little")
        for j in range(plan.bands):
            sl = bits[:, j * b : (j + 1) * b].astype(np.uint32)
            out[j, lo:hi] = (sl * w[None, :]).sum(axis=1, dtype=np.uint32)
    return out


def probe_masks(band_bits: int, probes: int) -> np.ndarray:
    """The first ``probes`` XOR masks of the multi-probe perturbation
    sequence: the exact bucket first, then masks in (popcount,
    ascending value) order — flip one bit before two, lower bit
    positions before higher.  With sign-only codes every bit is equally
    confident, so this is the uniform-confidence specialization of the
    Lv et al. score order: deterministic, data-independent, and total
    (``probes ≥ 2^band_bits`` enumerates every bucket — full probe
    coverage)."""
    if not isinstance(probes, numbers.Integral) or probes < 1:
        raise ValueError(f"probes must be a positive int, got {probes!r}")
    band_bits = int(band_bits)
    probes = int(min(probes, 1 << band_bits))
    out = [0]
    flips = 1
    while len(out) < probes and flips <= band_bits:
        vals = sorted(
            sum(1 << p for p in combo)
            for combo in itertools.combinations(range(band_bits), flips)
        )
        out.extend(vals[: probes - len(out)])
        flips += 1
    return np.asarray(out, dtype=np.uint32)


class BandedBuckets:
    """Per-band CSR inverted bucket index over one shard's local id
    space (see module docstring).

    State per band: ``indptr`` ``(2^b + 1,)`` int64 and ``ids`` ``(n,)``
    int32, counting-sorted by bucket with ids ASCENDING within every
    bucket — the invariant that makes candidate unions id-sorted and
    the re-rank tie-break exact.  ``keys`` ``(bands, n)`` uint32 holds
    every row's band keys in id order: the persisted durable state
    (layout-fungible — id order IS the snapshot order) and what
    ``compact()``'s id remap folds without re-extraction."""

    __slots__ = ("plan", "n", "keys", "_indptr", "_ids")

    def __init__(self, plan: BandPlan):
        self.plan = plan
        self.n = 0
        self.keys = np.empty((plan.bands, 0), np.uint32)
        nb = 1 << plan.band_bits
        self._indptr = [
            np.zeros(nb + 1, np.int64) for _ in range(plan.bands)
        ]
        self._ids = [np.empty(0, np.int32) for _ in range(plan.bands)]

    @classmethod
    def from_keys(cls, plan: BandPlan, keys: np.ndarray) -> "BandedBuckets":
        """Rebuild from a persisted/remapped key matrix (one counting
        sort per band — no code bytes touched)."""
        keys = np.ascontiguousarray(keys, dtype=np.uint32)
        if keys.ndim != 2 or keys.shape[0] != plan.bands:
            raise ValueError(
                f"keys must be ({plan.bands}, n), got {keys.shape}"
            )
        b = cls(plan)
        b._append_keys(keys)
        return b

    def add(self, codes) -> int:
        """Fold new rows (appended at local ids ``[n, n+rows)``) into
        every band's CSR — extracts keys for the NEW rows only and
        splices them in with a vectorized merge; resident rows are
        never re-hashed.  Returns the number of rows folded."""
        new_keys = band_keys(codes, self.plan)
        self._append_keys(new_keys)
        return new_keys.shape[1]

    def _append_keys(self, new_keys: np.ndarray) -> None:
        m = new_keys.shape[1]
        if m == 0:
            return
        row0 = self.n
        if row0 + m > 2**31 - 1:
            raise ValueError(
                "BandedBuckets ids are int32 (the per-shard id space); "
                f"have {row0}, adding {m} would overflow"
            )
        nb = 1 << self.plan.band_bits
        for j in range(self.plan.bands):
            k = new_keys[j].astype(np.int64)
            counts = np.bincount(k, minlength=nb)
            csum = np.concatenate(([0], np.cumsum(counts)))
            old_indptr = self._indptr[j]
            old_ids = self._ids[j]
            old_counts = np.diff(old_indptr)
            indptr = old_indptr + csum
            out = np.empty(old_ids.size + m, np.int32)
            if old_ids.size:
                # old bucket k's run shifts right by the number of new
                # rows landing in buckets < k (csum[k])
                shift = np.repeat(csum[:-1], old_counts)
                out[np.arange(old_ids.size, dtype=np.int64) + shift] = (
                    old_ids
                )
            # stable sort groups new rows by bucket keeping id order —
            # within-bucket ids stay ascending, and every new id is
            # greater than every old id, so the invariant holds
            order = np.argsort(k, kind="stable")
            grp_start = np.repeat(csum[:-1], counts)
            within = np.arange(m, dtype=np.int64) - grp_start
            dest = np.repeat(indptr[:-1] + old_counts, counts) + within
            out[dest] = (row0 + order).astype(np.int32)
            self._indptr[j] = indptr
            self._ids[j] = out
        self.keys = np.concatenate([self.keys, new_keys], axis=1)
        self.n += m

    def candidates(self, qkeys: np.ndarray, masks: np.ndarray):
        """Union candidate ids for one query tile: probe bucket
        ``qkey ^ mask`` in every band for every perturbation mask,
        gather the bucket runs, and deduplicate across bands, probes
        AND the tile's queries.  Returns ``(ids, gathered)`` — ``ids``
        sorted ascending int32 (``np.unique``), ``gathered`` the
        pre-dedup candidate count (the duplication factor is a bucket-
        quality signal the dispatch event records)."""
        parts = []
        gathered = 0
        for j in range(self.plan.bands):
            buckets = (
                (qkeys[j][:, None] ^ masks[None, :])
                .ravel()
                .astype(np.int64)
            )
            indptr = self._indptr[j]
            starts = indptr[buckets]
            lens = indptr[buckets + 1] - starts
            total = int(lens.sum())
            if total == 0:
                continue
            csum = np.concatenate(([0], np.cumsum(lens)))
            take = np.repeat(starts - csum[:-1], lens) + np.arange(
                total, dtype=np.int64
            )
            parts.append(self._ids[j][take])
            gathered += total
        if not parts:
            return np.empty(0, np.int32), 0
        return np.unique(np.concatenate(parts)), gathered

    def bucket_ids(self, band: int, key: int) -> np.ndarray:
        """One bucket's id run (ascending) — introspection/testing."""
        indptr = self._indptr[band]
        return self._ids[band][indptr[key] : indptr[key + 1]].copy()


def _check_probes(probes, default: int) -> int:
    """Per-call ``probes`` resolution, validated like the constructor
    knob (a float would silently truncate to fewer probes than the
    caller computed, and a bool — which IS ``numbers.Integral`` — would
    silently pin the exact path or probe once): None → the serving
    default, else a non-negative real int (0 = the exact path)."""
    if probes is None:
        return default
    if (isinstance(probes, bool) or not isinstance(probes, numbers.Integral)
            or probes < 0):
        raise ValueError(
            f"probes must be a non-negative int, got {probes!r}"
        )
    return int(probes)


def _merge_topm_rows(bd, bg, nd, ng, sentinel: int):
    """Row-wise exact merge of two (dist, id) top-m planes under the
    documented (distance, lower-global-id) order, deduplicating ids —
    the union-of-top-m identity ``top_m(A ∪ B) = top_m(top_m(A) ∪
    top_m(B))`` is what makes the adaptive tier's per-level rounds
    exact over their cumulative candidate set.  A candidate surfacing
    in two rounds has ONE distance (distance is a function of (query,
    id)), so duplicates are key-identical and adjacent after the sort;
    all-but-first re-key to the empty-slot sentinel pair."""
    m = bd.shape[1]
    d = np.concatenate([bd, nd], axis=1).astype(np.int64)
    g = np.concatenate([bg, ng], axis=1).astype(np.int64)
    key = (d << 32) | g
    key.sort(axis=1)
    dup = np.zeros(key.shape, bool)
    dup[:, 1:] = key[:, 1:] == key[:, :-1]
    key[dup] = (np.int64(sentinel) << 32) | int(_INT32_MAX)
    key.sort(axis=1)
    key = key[:, :m]
    return (
        (key >> 32).astype(np.int32),
        (key & 0x7FFFFFFF).astype(np.int32),
    )


class LSHSimHashIndex(SimHashIndex):
    """``SimHashIndex`` with a banded multi-probe LSH candidate tier:
    ``query_topk`` probes the banded bucket index, exact-Hamming
    re-ranks only the candidates through the r12 fused kernel, and
    falls back to the exact device ladder whenever the candidate set is
    too dense or too starved — the tier never serves worse than the
    exact path (see module docstring).

    ``probes`` is the recall/q-s knob: perturbation buckets probed per
    band (1 = exact bucket only; ``2^band_bits`` = full coverage =
    bit-identical to brute force).  The constructor value is the
    serving default — a ``TopKServer`` coalescing onto this index uses
    it — and ``query_topk(probes=...)`` overrides per call (``0`` pins
    the exact scan path).  ``fallback_density`` is the ladder
    threshold: a tile whose candidate union exceeds that fraction of
    the live corpus re-ranks at near-scan cost, so it serves through
    the exact path instead.

    The bucket index maintains itself through every mutation path:
    ``add`` folds new rows incrementally, ``delete`` needs no bucket
    work (tombstones filter at re-rank), ``compact`` folds the id
    remap, and snapshot restore rebuilds (verifying against persisted
    keys when the snapshot carries them).  Single-device by
    construction (one LSH index is one shard) — the sharded tier is
    ``LSHShardedSimHashIndex``."""

    def __init__(self, codes, *, bands: Optional[int] = None,
                 band_bits: Optional[int] = None, probes: int = 8,
                 fallback_density: float = 0.1, probe_path: str = "auto",
                 adaptive: bool = False,
                 candidate_budget: Optional[int] = None, **kw):
        if kw.get("mesh") is not None:
            raise ValueError(
                "LSHSimHashIndex is single-device (one banded index is "
                "one shard); shard a corpus with ann.LSHShardedSimHashIndex"
            )
        self.probes = _check_ctor_probes(probes)
        if not 0.0 < float(fallback_density) <= 1.0:
            raise ValueError(
                f"fallback_density must be in (0, 1], got "
                f"{fallback_density!r}"
            )
        self.fallback_density = float(fallback_density)
        self.probe_path = _check_probe_path(probe_path)
        self.adaptive = bool(adaptive)
        self.candidate_budget = _check_budget(candidate_budget)
        self._lsh_cfg = (bands, band_bits)
        self._lsh_suspend = False
        self._masks_cache: dict = {}
        # scoped-VMEM OOM memo for the re-rank kernel (r6 convention,
        # mirroring _fused_degraded): a (nq, rows_pad, m) shape that
        # OOM'd once serves the host rung for the process lifetime
        # instead of re-paying the failed dispatch per tile
        self._lsh_fused_degraded: set = set()
        # device-resident probe state (ISSUE 16): the CSR mirror is
        # invalidated by a revision clock bumped from every bucket
        # mutation, the tombstone vector by the (n_codes, n_deleted)
        # pair, and shapes plan_probe cannot tile are memoized onto the
        # host probe rung.  Initialized BEFORE the base constructor —
        # the append hook fires during it.
        self._lsh_dev_rev = 0
        self._lsh_dev_csr = None      # (rev, indptr_dev, ids_dev)
        self._lsh_dev_masks: dict = {}  # probes -> (1, P) int32 on device
        self._lsh_dev_dead = None     # ((n_codes, n_deleted), dead_dev)
        self._lsh_device_degraded: set = set()
        # resolve the band plan BEFORE the base constructor uploads the
        # bulk chunk, so the append hook folds rows directly — no
        # deferred copy of the corpus (which at the BL:10 scale would
        # transiently double host memory).  n_bits mirrors the base
        # resolution; the base constructor still owns its validation.
        codes = np.asarray(codes, dtype=np.uint8)
        if codes.ndim != 2:
            raise ValueError(f"codes must be (n, nbytes), got {codes.shape}")
        n_bits = kw.get("n_bits")
        n_bits = codes.shape[1] * 8 if n_bits is None else int(n_bits)
        self.band_plan = BandPlan(n_bits, bands=bands, band_bits=band_bits)
        self._buckets = BandedBuckets(self.band_plan)
        super().__init__(codes, **kw)

    # -- bucket maintenance (hooks off the base mutation paths) --------------

    def _codes_appended(self, codes: np.ndarray, row0: int) -> None:
        if self._lsh_suspend:
            return
        self._lsh_fold(codes)

    def _lsh_buckets_changed(self) -> None:
        """Invalidate the device-resident CSR mirror — the next device
        probe dispatch re-uploads from the mutated host buckets."""
        self._lsh_dev_rev += 1

    def _lsh_fold(self, codes: np.ndarray) -> None:
        rows = self._buckets.add(codes)
        self._lsh_buckets_changed()
        telemetry.registry().counter_inc("index.lsh.builds")
        telemetry.emit(
            EVENTS.INDEX_LSH_BUILD, rows=int(rows),
            n=int(self._buckets.n), bands=self.band_plan.bands,
            band_bits=self.band_plan.band_bits,
        )

    def _rebuild_from_host(self, codes: np.ndarray) -> None:
        # a wholesale replacement (compact(), durable re-compaction)
        # starts the banded index over unless compact() is folding the
        # id remap itself (suspended — see compact())
        if not self._lsh_suspend and self._buckets is not None:
            self._buckets = BandedBuckets(self.band_plan)
            self._lsh_buckets_changed()
        super()._rebuild_from_host(codes)

    def compact(self) -> np.ndarray:
        """Fold tombstones/chunks exactly like the base ``compact``,
        then fold the returned old→new id mapping through the banded
        index: surviving rows keep their extracted band keys
        (``keys[:, mapping]``), renumbered compactly — no re-hash of
        the corpus."""
        old_keys = self._buckets.keys if self._buckets is not None else None
        self._lsh_suspend = True
        try:
            mapping = super().compact()
        finally:
            self._lsh_suspend = False
        if old_keys is not None:
            self._buckets = BandedBuckets.from_keys(
                self.band_plan, old_keys[:, mapping]
            )
            self._lsh_buckets_changed()
            telemetry.registry().counter_inc("index.lsh.builds")
            telemetry.emit(
                EVENTS.INDEX_LSH_BUILD, rows=int(self._buckets.n),
                n=int(self._buckets.n), bands=self.band_plan.bands,
                band_bits=self.band_plan.band_bits, remapped=True,
            )
        return mapping

    # -- durable persistence (see durable.save_index's extra hook) -----------

    def _durable_extra(self, dirpath: str, gen: int) -> dict:
        """Manifest extras for ``durable.save_index``: spill the band
        keys (id order — layout-fungible) beside the chunks,
        checksummed like them, plus the band layout and serving knobs
        so ``load_lsh_index`` restores the identical tier."""
        return _spill_lsh_keys(self, dirpath, gen, self._buckets.keys)

    @classmethod
    def load(cls, path: str, *, bands: Optional[int] = None,
             band_bits: Optional[int] = None,
             probes: Optional[int] = None,
             fallback_density: Optional[float] = None,
             mesh=None, data_axis: str = "data"):
        """Restore an LSH index from a snapshot directory — LSH-format
        or pre-LSH r11-format (the banded index is then rebuilt from
        the codes).  See ``load_lsh_index``."""
        if mesh is not None:
            raise ValueError(
                "LSHSimHashIndex is single-device; load a sharded "
                "snapshot with ann.load_lsh_sharded_index"
            )
        return load_lsh_index(
            path, bands=bands, band_bits=band_bits, probes=probes,
            fallback_density=fallback_density,
        )

    # -- the candidate tier --------------------------------------------------

    def _probe_masks(self, probes: int) -> np.ndarray:
        masks = self._masks_cache.get(probes)
        if masks is None:
            masks = probe_masks(self.band_plan.band_bits, probes)
            self._masks_cache[probes] = masks
        return masks

    def lsh_stats(self) -> dict:
        """Process-registry candidate-tier tallies (shared across
        same-process indexes, like every registry counter)."""
        reg = telemetry.registry()
        return {
            "dispatches": reg.counter("index.lsh.dispatches"),
            "fallbacks": reg.counter("index.lsh.fallbacks"),
            "candidates": reg.counter("index.lsh.candidates"),
            "probe_buckets": reg.counter("index.lsh.probe_buckets"),
            "builds": reg.counter("index.lsh.builds"),
            "device_dispatches": reg.counter("index.lsh.device.dispatches"),
            "device_uploads": reg.counter("index.lsh.device.uploads"),
            "adaptive_tiles": reg.counter("index.lsh.adaptive.tiles"),
        }

    def query_topk(self, A, m: int, *, tile: int = 2048,
                   probes: Optional[int] = None,
                   probe_path: Optional[str] = None,
                   adaptive: Optional[bool] = None,
                   candidate_budget: Optional[int] = None):
        """Top-``m`` via the candidate tier: same contract as
        ``SimHashIndex.query_topk`` — ``(dist, idx)`` int32, ``m_eff =
        min(m, n_live)`` columns, (distance, lower-global-id) order —
        but each tile touches only its candidate union unless the
        fallback ladder routes it to the exact path.  ``probes``
        overrides the serving default (``0`` = exact path; ``tile`` is
        also the candidate-union granularity — smaller tiles mean
        per-query-sharper candidate sets at more dispatches).

        ``probe_path`` picks the candidate generator per call
        (constructor default otherwise): ``'device'`` runs the fused
        probe → dedup → gather → re-rank program (ISSUE 16 — one
        dispatch per tile, no host CSR walk), ``'host'`` pins the r15
        host probe rung, ``'auto'`` takes the device path on a real
        accelerator only.  ``adaptive``/``candidate_budget`` control
        device-side per-query probe escalation (see
        ``_lsh_adaptive_tile``); both are inert on the host rung.

        Determinism under PARTIAL probes is per (query set, tile):
        the candidate union is tile-scoped, so grouping a query with
        different neighbors (a different ``tile``, or a coalescing
        server padding/batching requests) can ENLARGE its candidate
        set.  The effect is monotone — a superset of candidates can
        only return equal-or-closer answers, never displace a correct
        one — and vanishes at full probe coverage, where the union is
        the whole live corpus regardless of grouping."""
        p = _check_probes(probes, self.probes)
        if p == 0:
            return super().query_topk(A, m, tile=tile)
        if not isinstance(m, numbers.Integral) or m <= 0:
            raise ValueError(f"m must be a positive int, got {m!r}")
        A = self._check_queries(A)
        if self.n_codes == 0:
            raise ValueError("query_topk on an empty index")
        if self.n_live == 0:
            raise ValueError(
                "query_topk on an index whose codes are all deleted "
                "(tombstoned); compact() or add() live codes first"
            )
        device = self._lsh_probe_device(probe_path)
        adaptive_eff = self.adaptive if adaptive is None else bool(adaptive)
        budget_eff = (self.candidate_budget if candidate_budget is None
                      else _check_budget(candidate_budget))
        m_eff = int(min(m, self.n_live))
        masks = self._probe_masks(p)
        if device:
            tile = self._lsh_device_tile(tile, p, m_eff)
        nq = A.shape[0]
        out_d = np.empty((nq, m_eff), dtype=np.int32)
        out_i = np.empty((nq, m_eff), dtype=np.int32)
        # same one-behind overlap as the exact path: tile i's d2h +
        # select ride under tile i+1's probe/gather/dispatch
        pending: list = []  # [(lo, hi, kind, payload)]

        def finish(entry):
            lo, hi, kind, payload = entry
            if kind == "lsh":
                d, i = self._lsh_finish_tile(payload, m_eff)
            elif kind == "lsh_dev":
                d, i = self._lsh_finish_device_tile(payload, m_eff)
            elif kind == "exact":
                d, i = self._topk_finish_tile(payload, m_eff)
            else:  # 'done': served synchronously (dense host rung)
                d, i = payload
            out_d[lo:hi] = d
            out_i[lo:hi] = i

        for lo in range(0, nq, tile):
            hi = min(lo + tile, nq)
            kind, payload = self._lsh_tile_entry(
                A[lo:hi], m_eff, masks, p, tile, device, adaptive_eff,
                budget_eff,
            )
            pending.append((lo, hi, kind, payload))
            if len(pending) >= 2:
                finish(pending.pop(0))
        while pending:
            finish(pending.pop(0))
        return out_d, out_i

    def _lsh_dispatch_tile(self, a_np, m_eff: int, masks: np.ndarray,
                           tile: int):
        """Candidate generation + re-rank dispatch for one query tile.
        Returns ``(kind, payload)``: ``('lsh', ...)`` for a dispatched
        candidate re-rank, ``('exact', handles)`` when the ladder fell
        back to the exact device fan-out, ``('done', (d, i))`` when the
        exact path itself is host-scale (dense rung).  Shared with the
        sharded tier, which calls it per shard."""
        t0 = time.perf_counter()
        qkeys = band_keys(a_np, self.band_plan)
        cand, gathered = self._buckets.candidates(qkeys, masks)
        if self._dead is not None and cand.size:
            # tombstones filter at re-rank: a deleted code is never
            # gathered, so it can never win (ISSUE 15 storage contract)
            cand = cand[~self._dead[cand]]
        n_cand = int(cand.size)
        # host-probe wall (the hop the device path removes): key
        # extraction + CSR walk + np.unique dedup + tombstone filter
        telemetry.registry().observe(
            "index.lsh.probe.host_s", time.perf_counter() - t0
        )
        nq = int(a_np.shape[0])
        n_probes = nq * self.band_plan.bands * int(masks.size)
        reg = telemetry.registry()
        if n_cand < m_eff or n_cand > self.fallback_density * self.n_live:
            reason = "starved" if n_cand < m_eff else "dense"
            reg.counter_inc("index.lsh.fallbacks")
            telemetry.emit(
                EVENTS.INDEX_LSH_FALLBACK, reason=reason, queries=nq,
                probes=int(masks.size), candidates=n_cand,
                n_live=int(self.n_live),
                threshold=self.fallback_density,
                **telemetry.trace_fields(),
            )
            if self._topk_route(nq, m_eff) == "dense":
                # host-scale request: the exact path serves it whole
                return "done", SimHashIndex.query_topk(
                    self, a_np, m_eff, tile=tile
                )
            return "exact", self._topk_dispatch_tile(a_np, m_eff)
        frac = n_cand / max(self.n_live, 1)
        reg.counter_inc("index.lsh.dispatches")
        reg.counter_inc("index.lsh.probe_buckets", n_probes)
        reg.counter_inc("index.lsh.candidates", n_cand)
        reg.gauge_set("index.lsh.candidate_fraction", frac)
        if telemetry.enabled():
            telemetry.emit(
                EVENTS.INDEX_LSH_DISPATCH, queries=nq, m=int(m_eff),
                probes=int(masks.size), bands=self.band_plan.bands,
                candidates=n_cand, gathered=int(gathered),
                candidate_fraction=round(frac, 6),
                **telemetry.trace_fields(),
            )
        t1 = time.perf_counter()
        payload = self._lsh_rerank_dispatch(a_np, cand, m_eff)
        reg.observe("index.lsh.probe.dispatch_s", time.perf_counter() - t1)
        return "lsh", payload

    def _gather_codes_device(self, cand: np.ndarray):
        """Gather the candidate code rows ON DEVICE from the resident
        chunks (no host copy of any code byte) and zero-pad to the row
        bucket so the re-rank kernel compiles one program per bucket,
        not one per candidate count."""
        import jax.numpy as jnp

        from randomprojection_tpu.parallel.sharded import row_bucket

        parts = []
        base = 0
        for c in self._chunks:
            lo = np.searchsorted(cand, base)
            hi = np.searchsorted(cand, base + c.n)
            if hi > lo:
                local = self._device_queries(
                    (cand[lo:hi] - base).astype(np.int32)
                )
                parts.append(jnp.take(c.b, local, axis=0))
            base += c.n
        g = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)
        pad_to = row_bucket(int(cand.size))
        if pad_to != cand.size:
            g = jnp.pad(g, ((0, pad_to - cand.size), (0, 0)))
        return g

    def _lsh_rerank_dispatch(self, a_np, cand: np.ndarray, m_eff: int):
        """Dispatch the exact re-rank of one tile against its gathered
        candidates and START the d2h.  Default rung: the r12 fused
        Pallas kernel (in-kernel DMA'd Hamming matmul + bitonic running
        top-m — the same machinery the full scan uses).  A scoped-VMEM
        OOM, or a shape the planner cannot tile, degrades to one device
        Hamming dispatch + host select — same (dist, lower-id) order,
        same results (the candidate set is small by construction, the
        density gate bounds it).

        With a tiered index holding cold chunks, the candidate set
        splits by residency and the cold side's rows stream H2D under
        the hot side's kernel (``_lsh_rerank_tiered``) — bit-identical
        by the union-of-top-m identity the adaptive tier already relies
        on."""
        a = self._device_queries(a_np)
        if self._tier is not None and self._tier.any_cold():
            return self._lsh_rerank_tiered(a, a_np, cand, m_eff)
        return self._lsh_rerank_one(
            a, a_np, cand, self._gather_codes_device(cand), m_eff
        )

    def _lsh_rerank_one(self, a, a_np, cand: np.ndarray, cand_dev,
                        m_eff: int):
        """One re-rank dispatch against one gathered candidate plane
        (the whole tile's union, or one residency side of it)."""
        from randomprojection_tpu.ops import topk_kernels

        n_cand = int(cand.size)
        shape_key = (int(a_np.shape[0]), int(cand_dev.shape[0]), m_eff)
        plan = None
        if shape_key not in self._lsh_fused_degraded:
            plan = topk_kernels.plan_fused(*shape_key[:2], self.n_bytes,
                                           shape_key[2])
        if plan is not None:
            from randomprojection_tpu.ops.pallas_kernels import (
                is_vmem_oom,
                record_vmem_oom_retry,
            )

            try:
                d, i = topk_kernels.fused_topk(
                    a, cand_dev, n_cand, m_eff, plan=plan
                )
                _start_host_copy(d)
                _start_host_copy(i)
                return ("fused", d, i, cand)
            except Exception as e:
                if not is_vmem_oom(e):
                    raise
                # degraded retry, r6 convention: memoize only after the
                # failure is classified — this shape serves the host
                # rung for the process lifetime, never re-paying the
                # failed dispatch per tile
                record_vmem_oom_retry(a_np.shape, "lsh_rerank", m_eff)
                self._lsh_fused_degraded.add(shape_key)
        d = _hamming_tile_fn()(a, cand_dev)
        _start_host_copy(d)
        return ("host", d, None, cand)

    def _lsh_rerank_tiered(self, a, a_np, cand: np.ndarray, m_eff: int):
        """The tentpole dispatch (ISSUE 19): split the candidate union
        by chunk residency, start the cold rows' asynchronous H2D
        upload FIRST, dispatch the hot-tier re-rank (the upload streams
        under that kernel), then dispatch the cold-tier re-rank against
        the landed rows.  Each side selects its own top-``min(m_eff,
        side)`` and ``_lsh_finish_tile`` merges the planes under the
        documented (distance, lower-global-id) order — exact by the
        union-of-top-m identity, and full because the starved gate
        already guaranteed ``|hot| + |cold| ≥ m_eff``.  A failed
        staging upload degrades to committing the host rows at dispatch
        (synchronous fetch, degraded audit) — never wrong answers."""
        from randomprojection_tpu.ops import topk_kernels
        from randomprojection_tpu.parallel.sharded import row_bucket

        tier = self._tier
        t0 = time.perf_counter()
        hot_mask = np.zeros(cand.size, bool)
        per_chunk: dict = {}
        cold_parts = []
        base = 0
        for c in self._chunks:
            lo = np.searchsorted(cand, base)
            hi = np.searchsorted(cand, base + c.n)
            if hi > lo:
                per_chunk[c.row0] = int(hi - lo)
                if tier.chunk_is_hot(c):
                    hot_mask[lo:hi] = True
                else:
                    # the host-side cold fetch: a RAM copy for the host
                    # tier, touched pages only for a disk-tier memmap
                    local = (cand[lo:hi] - base).astype(np.int64)
                    # c.b is HOST-resident by the cold-tier invariant
                    # (ndarray or memmap): this asarray is a host
                    # gather, not a device sync, and its rows feed the
                    # async stage_rows upload below
                    cold_parts.append(np.asarray(c.b)[local])  # rplint: allow[RP03] — host gather of a host-resident cold chunk, no device sync
            base += c.n
        cand_hot = cand[hot_mask]
        cand_cold = cand[~hot_mask]
        tier.note_gather(int(cand_hot.size), int(cand_cold.size),
                         per_chunk)
        if cand_cold.size == 0:
            # the whole union is hot (residency races included): the
            # fully resident dispatch serves unchanged
            return self._lsh_rerank_one(
                a, a_np, cand, self._gather_codes_device(cand), m_eff
            )
        cold_rows = (cold_parts[0] if len(cold_parts) == 1
                     else np.concatenate(cold_parts, axis=0))
        pad_to = row_bucket(int(cand_cold.size))
        sync = False
        try:
            cold_dev = topk_kernels.stage_rows(
                cold_rows, device=self.device, pad_to=pad_to
            )
        except Exception as e:
            tier.note_fallback(
                f"upload:{type(e).__name__}", rows=int(cand_cold.size)
            )
            sync = True
            cold_dev = np.zeros((pad_to, self.n_bytes), np.uint8)
            cold_dev[: cold_rows.shape[0]] = cold_rows
        wall_s = time.perf_counter() - t0
        t_staged = time.perf_counter()
        hot_payload = None
        m_hot = 0
        if cand_hot.size:
            # hot-tier kernel dispatches while the cold upload streams
            m_hot = int(min(m_eff, cand_hot.size))
            hot_payload = self._lsh_rerank_one(
                a, a_np, cand_hot,
                self._gather_codes_device(cand_hot), m_hot,
            )
        # the window the upload had to hide under the hot dispatch
        overlap_s = (time.perf_counter() - t_staged) if not sync else 0.0
        m_cold = int(min(m_eff, cand_cold.size))
        cold_payload = self._lsh_rerank_one(
            a, a_np, cand_cold, cold_dev, m_cold
        )
        tier.note_fetch(
            rows=int(cand_cold.size),
            nbytes=int(cand_cold.size) * self.n_bytes, wall_s=wall_s,
            overlap_s=overlap_s, source=tier.cold_tier, sync=sync,
        )
        return ("tiered", (hot_payload, m_hot), (cold_payload, m_cold))

    def _lsh_finish_tile(self, payload, m_eff: int):
        """Materialize one re-rank dispatch and map candidate-local
        positions back to global ids.  ``cand`` is ascending, so the
        kernel's lower-local-id tie-break IS the documented
        lower-global-id order.

        A ``'tiered'`` payload carries one sub-payload per residency
        side: both finish through this same routine, pad to ``m_eff``
        columns with the empty-slot sentinel pair, and merge under the
        (distance, lower-global-id) key — the sides' candidate sets are
        disjoint, so the merge's dedup only ever collapses sentinel
        pads, and the starved gate guarantees ≥ ``m_eff`` real entries
        in the union (the merged plane is always full)."""
        if payload[0] == "tiered":
            _, (hp, m_hot), (cp, m_cold) = payload
            cd, ci = self._lsh_finish_tile(cp, m_cold)
            if hp is None:
                # all-cold tile: the gate guaranteed m_cold == m_eff
                return cd, ci
            hd, hi_ = self._lsh_finish_tile(hp, m_hot)
            sent = np.int32(self.n_bits + 1)
            if m_hot < m_eff:
                pad = ((0, 0), (0, m_eff - m_hot))
                hd = np.pad(hd, pad, constant_values=sent)
                hi_ = np.pad(hi_, pad, constant_values=_INT32_MAX)
            return _merge_topm_rows(hd, hi_, cd, ci, int(sent))
        kind, d, i, cand = payload
        if kind == "fused":
            # d2h already started at dispatch: these materialize the
            # prefetched copy, one tile behind the live dispatch
            dist = np.asarray(d)
            idx = np.asarray(i)
            return dist, cand[idx].astype(np.int32)
        # host-select rung: distances over the padded candidate rows —
        # slice the pad columns off before the exact host selection
        # (d2h started at dispatch, same one-behind contract)
        D = np.asarray(d)[:, : cand.size]
        dloc, iloc = _host_topk_select(D, m_eff)
        return dloc, cand[iloc].astype(np.int32)

    # -- device-fused probe path (ISSUE 16) ----------------------------------

    def _lsh_probe_device(self, probe_path: Optional[str]) -> bool:
        """Resolve the per-call probe path: ``'device'`` forces the
        fused device dispatch (interpreter included — the tier-1/CI
        parity mode), ``'host'`` pins the r15 host probe rung,
        ``'auto'`` takes the device path only on a real accelerator
        (the interpreter is correctness-grade, not a serving win)."""
        path = (self.probe_path if probe_path is None
                else _check_probe_path(probe_path))
        if path == "host":
            return False
        if self._tier is not None and self._tier.any_cold():
            # the fused probe program gathers from EVERY chunk on device
            # — with cold chunks that would re-upload whole chunks per
            # dispatch, the exact cost tiering exists to avoid.  The
            # host probe rung + tiered re-rank serves instead (same
            # answers; the candidate rows stream, not the chunks).
            return False
        if path == "device":
            return True
        from randomprojection_tpu.ops import probe_kernels

        return not probe_kernels.interpret_default()

    def _lsh_device_tile(self, tile: int, p: int, m_eff: int) -> int:
        """Clamp the serving tile to what one device-probe dispatch can
        carry: ``plan_probe``'s ``tq`` is the per-launch query ceiling,
        so a larger serving tile would force a per-tile degrade to the
        host rung — clamping keeps every tile on the fused path at more
        (cheap) dispatches."""
        from randomprojection_tpu.ops import probe_kernels

        pplan = probe_kernels.plan_probe(
            min(int(tile), 1024), max(int(self._buckets.n), 1),
            self.band_plan.bands, self.band_plan.band_bits, p, m_eff,
        )
        if pplan is not None:
            tile = min(int(tile), pplan.tq)
        return int(tile)

    def _lsh_device_csr(self):
        """The device-resident banded CSR mirror: per-band ``indptr``
        clamped to int32 (ids are int32 by the append guard, so offsets
        fit) stacked ``(bands, 2^b + 1)``, and per-band id runs packed
        into a uniform ``(bands, n + _LSH_IDS_PAD)`` int32 plane (each
        band holds exactly ``n`` ids — every row keys into every band)
        with the pad sentinel-filled so a ragged last DMA block
        overreads into sentinels, never clamps.  Cached against the
        bucket revision clock; re-uploads emit
        ``index.lsh.device_upload``."""
        cached = self._lsh_dev_csr
        if cached is not None and cached[0] == self._lsh_dev_rev:
            return cached[1], cached[2]
        t0 = time.perf_counter()
        b = self._buckets
        n = int(b.n)
        indptr = np.stack([
            np.minimum(ip, np.int64(_INT32_MAX)).astype(np.int32)
            for ip in b._indptr
        ])
        ids = np.full(
            (self.band_plan.bands, n + _LSH_IDS_PAD), _INT32_MAX, np.int32
        )
        for j, run in enumerate(b._ids):
            ids[j, : run.size] = run
        indptr_dev = self._device_queries(indptr)
        ids_dev = self._device_queries(ids)
        self._lsh_dev_csr = (self._lsh_dev_rev, indptr_dev, ids_dev)
        telemetry.registry().counter_inc("index.lsh.device.uploads")
        if telemetry.enabled():
            telemetry.emit(
                EVENTS.INDEX_LSH_DEVICE_UPLOAD, rows=n,
                bands=self.band_plan.bands,
                band_bits=self.band_plan.band_bits,
                bytes=int(indptr.nbytes + ids.nbytes),
                wall_s=round(time.perf_counter() - t0, 6),
                **telemetry.trace_fields(),
            )
        return indptr_dev, ids_dev

    def _lsh_device_dead(self):
        """The FULL tombstone vector on device (``(n_codes,)`` uint8,
        zeros when nothing is deleted — the probe program needs a dense
        operand either way), cached against the ``(n_codes,
        n_deleted)`` mutation clock like ``_chunk_dead_device``."""
        key = (int(self.n_codes), int(self._n_deleted))
        cached = self._lsh_dev_dead
        if cached is not None and cached[0] == key:
            return cached[1]
        if self._dead is None:
            dead = np.zeros(self.n_codes, np.uint8)
        else:
            dead = self._dead.astype(np.uint8)
        dead_dev = self._device_queries(dead)
        self._lsh_dev_dead = (key, dead_dev)
        return dead_dev

    def _lsh_device_masks(self, p: int, masks: np.ndarray):
        """The ``(1, P)`` int32 probe-mask plane on device, cached per
        ``probes`` (pure combinatorics — same keying as the host mask
        cache)."""
        dev = self._lsh_dev_masks.get(p)
        if dev is None:
            dev = self._device_queries(
                np.ascontiguousarray(masks.astype(np.int32))[None, :]
            )
            self._lsh_dev_masks[p] = dev
        return dev

    def _lsh_device_plans(self, nq: int, n_probes: int, m_eff: int):
        """Resolve the (probe, re-rank) plan pair for one device
        dispatch shape, or None when either planner cannot tile it —
        the caller then degrades (r6: classify, memoize, emit)."""
        from randomprojection_tpu.ops import probe_kernels, topk_kernels

        pplan = probe_kernels.plan_probe(
            nq, int(self._buckets.n), self.band_plan.bands,
            self.band_plan.band_bits, n_probes, m_eff,
        )
        if pplan is None or pplan.tq < nq:
            return None
        fplan = topk_kernels.plan_fused(
            pplan.tq, pplan.cap, self.n_bytes, m_eff
        )
        if fplan is None:
            return None
        return pplan, fplan

    def _lsh_device_dispatch_tile(self, a_np, m_eff: int,
                                  masks: np.ndarray, p: int, tile: int):
        """One fused device-probe dispatch: pad the tile to the plan's
        ``tq``, upload queries + active mask (the only per-tile host
        bytes — no CSR walk, no ``np.unique``), launch the fused
        probe → dedup → gather → re-rank program and START the d2h.
        Returns ``('lsh_dev', payload)``, or None when the shape has no
        plan — memoized per shape, ``index.lsh.fallback`` reason
        ``device_plan``, and the caller serves the host probe rung."""
        nq = int(a_np.shape[0])
        memo_key = (nq, int(self._buckets.n), p, m_eff)
        reg = telemetry.registry()
        if memo_key in self._lsh_device_degraded:
            return None
        plans = self._lsh_device_plans(nq, int(masks.size), m_eff)
        if plans is None:
            self._lsh_device_degraded.add(memo_key)
            reg.counter_inc("index.lsh.fallbacks")
            telemetry.emit(
                EVENTS.INDEX_LSH_FALLBACK, reason="device_plan",
                queries=nq, probes=int(masks.size),
                n_live=int(self.n_live),
                **telemetry.trace_fields(),
            )
            return None
        pplan, fplan = plans
        t0 = time.perf_counter()
        indptr_dev, ids_dev = self._lsh_device_csr()
        dead_dev = self._lsh_device_dead()
        masks_dev = self._lsh_device_masks(p, masks)
        qp = a_np
        if nq < pplan.tq:
            qp = np.zeros((pplan.tq, a_np.shape[1]), np.uint8)
            qp[:nq] = a_np
        active = np.zeros((1, pplan.tq), np.int32)
        active[0, :nq] = 1
        q_dev = self._device_queries(qp)
        act_dev = self._device_queries(active)
        # device-path "host probe" wall is upload prep only — the A/B
        # against the host rung's CSR-walk wall is the bench headline
        reg.observe("index.lsh.probe.host_s", time.perf_counter() - t0)
        t1 = time.perf_counter()
        from randomprojection_tpu.ops import probe_kernels

        d, gid, stat, _cnt = probe_kernels.device_probe_topk(
            q_dev, masks_dev, act_dev, indptr_dev, ids_dev, dead_dev,
            [c.b for c in self._chunks],
            [c.row0 for c in self._chunks],
            [c.n for c in self._chunks],
            m_eff, pplan=pplan, fplan=fplan,
            band_bits=self.band_plan.band_bits,
        )
        _start_host_copy(d)
        _start_host_copy(gid)
        _start_host_copy(stat)
        reg.observe("index.lsh.probe.dispatch_s", time.perf_counter() - t1)
        return "lsh_dev", (d, gid, stat, nq, p, tile, a_np)

    def _lsh_finish_device_tile(self, payload, m_eff: int):
        """Materialize one fused dispatch and apply the POST-HOC
        fallback ladder: the device program cannot consult the density
        gate before launching (the candidate count is ITS output), so
        the ladder reads the stats plane at finish time — candidate-
        slot overflow → ``device_budget``, fewer live candidates than
        ``m_eff`` → ``starved``, union denser than the gate →
        ``dense`` — and any rung serves the tile through the exact
        path (the tier never serves worse than exact)."""
        d, gid, stat, nq, p, tile, a_np = payload
        stat = np.asarray(stat)
        overflow = int(stat[1]) != 0
        n_cand = int(stat[2])
        reg = telemetry.registry()
        dense = n_cand > self.fallback_density * self.n_live
        if overflow or n_cand < m_eff or dense:
            reason = ("device_budget" if overflow
                      else "starved" if n_cand < m_eff else "dense")
            reg.counter_inc("index.lsh.fallbacks")
            telemetry.emit(
                EVENTS.INDEX_LSH_FALLBACK, reason=reason, queries=nq,
                probes=int(p), candidates=n_cand,
                n_live=int(self.n_live),
                threshold=self.fallback_density,
                **telemetry.trace_fields(),
            )
            return SimHashIndex.query_topk(self, a_np, m_eff, tile=tile)
        frac = n_cand / max(self.n_live, 1)
        reg.counter_inc("index.lsh.dispatches")
        reg.counter_inc("index.lsh.device.dispatches")
        reg.counter_inc(
            "index.lsh.probe_buckets", nq * self.band_plan.bands * p
        )
        reg.counter_inc("index.lsh.candidates", n_cand)
        reg.gauge_set("index.lsh.candidate_fraction", frac)
        if telemetry.enabled():
            telemetry.emit(
                EVENTS.INDEX_LSH_DEVICE_DISPATCH, queries=nq,
                m=int(m_eff), probes=int(p), bands=self.band_plan.bands,
                candidates=n_cand, gathered=int(stat[0]),
                candidate_fraction=round(frac, 6),
                **telemetry.trace_fields(),
            )
        dist = np.asarray(d)[:nq, :m_eff]
        idx = np.asarray(gid)[:nq, :m_eff].astype(np.int32)
        return dist, idx

    def _lsh_tile_entry(self, a_np, m_eff: int, masks: np.ndarray,
                        p: int, tile: int, device: bool, adaptive: bool,
                        budget: Optional[int]):
        """Route one query tile down the probe ladder: adaptive device
        rounds → fixed device-fused dispatch → host probe rung (which
        itself ladders to the exact path).  Adaptive probing is a
        device-path feature — on the host rung the fixed ``probes``
        serve (never fewer candidates, never worse answers)."""
        if device:
            if adaptive:
                served = self._lsh_adaptive_tile(
                    a_np, m_eff, p, tile, budget
                )
            else:
                served = self._lsh_device_dispatch_tile(
                    a_np, m_eff, masks, p, tile
                )
            if served is not None:
                return served
        return self._lsh_dispatch_tile(a_np, m_eff, masks, tile)

    def _lsh_adaptive_tile(self, a_np, m_eff: int, p: int, tile: int,
                           budget: Optional[int]):
        """Adaptive per-query probing: host-orchestrated ROUNDS of the
        fused device dispatch, one per popcount LEVEL of the (popcount,
        ascending value) probe sequence, with a per-query active mask —
        easy queries retire early, hard queries escalate toward the
        ``probes`` ceiling.

        Safety is by construction.  (1) Early exit is sound: after
        every popcount-``f`` mask has been probed, a candidate still
        unseen by query ``q`` differs from ``q``'s key by ≥ ``f+1``
        bits in EVERY band (else some probed bucket held it), and bands
        are disjoint bit ranges, so its distance is ≥ ``bands·(f+1)``;
        a query whose running m-th distance is STRICTLY below that
        bound can never be improved — nor tie-displaced (strictness
        covers the lower-id tie-break) — by any unprobed bucket.  The
        bound also covers tile-union cross-contamination: a candidate
        surfaced by a NEIGHBOR query but absent from ``q``'s probed
        buckets satisfies the same per-band inequality for ``q``.
        (2) Rounds merge exactly: ``top_m(A ∪ B) = top_m(top_m(A) ∪
        top_m(B))`` (``_merge_topm_rows``), so the running plane always
        equals the fixed-probes answer over the cumulative probe set.
        (3) Recall is monotone in ``candidate_budget``: a larger budget
        never deactivates a query earlier, so its effective probe set
        — and hence its candidate set — is a superset.  The truncated
        final level (a ``probes`` ceiling cutting a popcount class
        short) never early-exits on its own bound.

        Degrades whole-tile to the fixed path (return None) when any
        level has no plan (``device_plan``, memoized) or any round
        overflows its candidate slots (``device_budget``); queries
        still starved after the final round are served exactly
        (``starved`` rung), so the returned plane is always full."""
        from randomprojection_tpu.ops import probe_kernels

        nq = int(a_np.shape[0])
        bands = self.band_plan.bands
        reg = telemetry.registry()
        memo_key = ("adaptive", nq, int(self._buckets.n), p, m_eff)
        if memo_key in self._lsh_device_degraded:
            return None
        masks = self._probe_masks(p)
        pc = np.array([bin(int(x)).count("1") for x in masks], np.int64)
        # level f = the run of masks with popcount f (sequence order
        # groups them); the ceiling p may truncate the last level
        bnd = np.flatnonzero(np.diff(pc)) + 1
        levels = list(
            zip(np.concatenate(([0], bnd)),
                np.concatenate((bnd, [masks.size])))
        )
        full_bits = self.band_plan.band_bits
        plans = []
        for lo, hi in levels:
            pl = self._lsh_device_plans(nq, int(hi - lo), m_eff)
            if pl is None:
                self._lsh_device_degraded.add(memo_key)
                reg.counter_inc("index.lsh.fallbacks")
                telemetry.emit(
                    EVENTS.INDEX_LSH_FALLBACK, reason="device_plan",
                    queries=nq, probes=int(hi - lo),
                    n_live=int(self.n_live), adaptive=True,
                    **telemetry.trace_fields(),
                )
                return None
            plans.append(pl)
        sent_d = np.int32(self.n_bits + 1)
        best_d = np.full((nq, m_eff), sent_d, np.int32)
        best_g = np.full((nq, m_eff), _INT32_MAX, np.int32)
        active = np.ones(nq, bool)
        used = np.zeros(nq, np.int64)
        yielded = np.zeros(nq, np.int64)
        early_exits = budget_stops = rounds = 0
        live_cands = probe_buckets = 0
        t0 = time.perf_counter()
        indptr_dev, ids_dev = self._lsh_device_csr()
        dead_dev = self._lsh_device_dead()
        reg.observe("index.lsh.probe.host_s", time.perf_counter() - t0)
        for f, (lo, hi) in enumerate(levels):
            if not active.any():
                break
            pplan, fplan = plans[f]
            t1 = time.perf_counter()
            level_masks = self._device_queries(
                np.ascontiguousarray(masks[lo:hi].astype(np.int32))[None, :]
            )
            qp = a_np
            if nq < pplan.tq:
                qp = np.zeros((pplan.tq, a_np.shape[1]), np.uint8)
                qp[:nq] = a_np
            act = np.zeros((1, pplan.tq), np.int32)
            act[0, :nq] = active
            d, gid, stat, cnt = probe_kernels.device_probe_topk(
                self._device_queries(qp), level_masks,
                self._device_queries(act), indptr_dev, ids_dev,
                dead_dev,
                [c.b for c in self._chunks],
                [c.row0 for c in self._chunks],
                [c.n for c in self._chunks],
                m_eff, pplan=pplan, fplan=fplan,
                band_bits=self.band_plan.band_bits,
            )
            stat = np.asarray(stat)  # rplint: allow[RP03] — host-orchestrated round: the overflow verdict gates whether the NEXT level may launch, so this sync IS the orchestration point
            reg.observe(
                "index.lsh.probe.dispatch_s", time.perf_counter() - t1
            )
            rounds += 1
            reg.counter_inc("index.lsh.device.dispatches")
            if int(stat[1]) != 0:
                reg.counter_inc("index.lsh.fallbacks")
                telemetry.emit(
                    EVENTS.INDEX_LSH_FALLBACK, reason="device_budget",
                    queries=nq, probes=int(hi - lo),
                    n_live=int(self.n_live), adaptive=True,
                    **telemetry.trace_fields(),
                )
                return None
            # The per-round merge and the early-exit bound both read
            # these on host before the next level can launch; the sync
            # is the adaptive control point, not an accidental stall
            # (the fixed-probe path overlaps d2h via _start_host_copy).
            nd = np.asarray(d)[:nq, :m_eff]  # rplint: allow[RP03] — see above: round results feed the host-side merge deciding the next launch
            ng = np.asarray(gid)[:nq, :m_eff]  # rplint: allow[RP03] — see above
            cnt = np.asarray(cnt)[:nq]  # rplint: allow[RP03] — see above
            # merge ACTIVE rows only: retired queries stay frozen (their
            # plane is already proven-final or budget-stopped), which is
            # what makes the budget-monotonicity superset argument hold
            best_d[active], best_g[active] = _merge_topm_rows(
                best_d[active], best_g[active], nd[active], ng[active],
                int(sent_d),
            )
            used[active] += int(hi - lo)
            yielded[active] += cnt[active]
            live_cands += int(stat[2])
            probe_buckets += int(active.sum()) * bands * int(hi - lo)
            if int(hi - lo) == _level_size(full_bits, f):
                # complete level: the bands·(f+1) bound holds
                mth = best_d[:, m_eff - 1]
                exiting = active & (mth < bands * (f + 1))
                early_exits += int(exiting.sum())
                active &= ~exiting
            if budget is not None:
                stops = active & (yielded >= budget)
                budget_stops += int(stops.sum())
                active &= ~stops
        starved = best_g[:, m_eff - 1] == _INT32_MAX
        if starved.any():
            reg.counter_inc("index.lsh.fallbacks")
            telemetry.emit(
                EVENTS.INDEX_LSH_FALLBACK, reason="starved",
                queries=int(starved.sum()), probes=int(p),
                n_live=int(self.n_live), adaptive=True,
                **telemetry.trace_fields(),
            )
            sd, si = SimHashIndex.query_topk(
                self, np.ascontiguousarray(a_np[starved]), m_eff,
                tile=tile,
            )
            best_d[starved] = sd
            best_g[starved] = si.astype(np.int32)
        frac = live_cands / max(self.n_live, 1)
        reg.counter_inc("index.lsh.dispatches")
        reg.counter_inc("index.lsh.adaptive.tiles")
        reg.counter_inc("index.lsh.probe_buckets", probe_buckets)
        reg.counter_inc("index.lsh.candidates", live_cands)
        reg.gauge_set("index.lsh.candidate_fraction", frac)
        for u in used:
            reg.observe("index.lsh.adaptive.probes_used", float(u))
        if telemetry.enabled():
            telemetry.emit(
                EVENTS.INDEX_LSH_ADAPTIVE, queries=nq, m=int(m_eff),
                probes_ceiling=int(p), rounds=rounds,
                probes_used_mean=round(float(used.mean()), 3),
                probes_used_max=int(used.max()),
                early_exits=early_exits, budget_stops=budget_stops,
                starved=int(starved.sum()), candidates=live_cands,
                candidate_fraction=round(frac, 6),
                **telemetry.trace_fields(),
            )
        return "done", (best_d, best_g)


def _level_size(band_bits: int, f: int):
    """Number of ``band_bits``-bit masks with popcount ``f`` — the full
    size of popcount level ``f`` (math.comb)."""
    import math

    return math.comb(band_bits, f)


class LSHShardedSimHashIndex(ShardedSimHashIndex):
    """``ShardedSimHashIndex`` whose shards carry banded multi-probe
    LSH tiers: a query tile probes EVERY shard's bucket index, each
    shard exact-re-ranks its own candidates (full per-shard fallback
    ladder — a dense shard falls back to its exact scan while its
    neighbors stay sublinear), and the per-shard candidates merge
    through the same ``_merge_tile`` lexsort as the exact tier — so
    cross-shard tombstones, int64 global ids and ``id_offset`` behave
    identically, and full probe coverage is bit-identical to
    ``topk_bruteforce`` on the concatenated corpus.

    Plugs into ``ShardedTopKServer`` unchanged (the ``query_topk``
    surface is the contract); ``probes`` at construction is the serving
    default, per-call ``probes=`` overrides, ``0`` pins the exact
    path."""

    def __init__(self, codes, *, bands: Optional[int] = None,
                 band_bits: Optional[int] = None, probes: int = 8,
                 fallback_density: float = 0.1, probe_path: str = "auto",
                 adaptive: bool = False,
                 candidate_budget: Optional[int] = None, **kw):
        self.probes = _check_ctor_probes(probes)
        if not 0.0 < float(fallback_density) <= 1.0:
            raise ValueError(
                f"fallback_density must be in (0, 1], got "
                f"{fallback_density!r}"
            )
        self.fallback_density = float(fallback_density)
        self.probe_path = _check_probe_path(probe_path)
        self.adaptive = bool(adaptive)
        self.candidate_budget = _check_budget(candidate_budget)
        self._lsh_cfg = (bands, band_bits)
        super().__init__(codes, **kw)
        self.band_plan = self._shards[0].band_plan

    def _make_shard(self, s: int, dev):
        bands, band_bits = self._lsh_cfg
        return LSHSimHashIndex(
            np.empty((0, self.n_bytes), np.uint8),
            n_bits=self.n_bits, topk_impl=self.topk_impl, device=dev,
            label=f"shard {s}/{len(self._devices)} on {dev}",
            bands=bands, band_bits=band_bits, probes=self.probes,
            fallback_density=self.fallback_density,
            probe_path=self.probe_path, adaptive=self.adaptive,
            candidate_budget=self.candidate_budget,
            **self._tier_kwargs(s),
        )

    def _lsh_global_keys(self) -> np.ndarray:
        """Every row's band keys in GLOBAL id order — the
        layout-fungible durable state (segments translate each shard's
        local key columns into their global positions)."""
        out = np.empty((self.band_plan.bands, self.n_codes), np.uint32)
        for seg in self._segments:
            ks = self._shards[seg.shard]._buckets.keys
            out[:, seg.g0 : seg.g0 + seg.rows] = ks[
                :, seg.l0 : seg.l0 + seg.rows
            ]
        return out

    def _durable_extra(self, dirpath: str, gen: int) -> dict:
        return _spill_lsh_keys(
            self, dirpath, gen, self._lsh_global_keys()
        )

    @classmethod
    def load(cls, path: str, *, mesh=None, devices=None,
             n_shards: Optional[int] = None, data_axis: str = "data",
             topk_impl: str = "auto", bands: Optional[int] = None,
             band_bits: Optional[int] = None,
             probes: Optional[int] = None,
             fallback_density: Optional[float] = None):
        """Restore onto ANY shard layout — LSH-format or pre-LSH
        snapshots, sharded or plain.  See ``load_lsh_sharded_index``."""
        return load_lsh_sharded_index(
            path, mesh=mesh, devices=devices, n_shards=n_shards,
            data_axis=data_axis, topk_impl=topk_impl, bands=bands,
            band_bits=band_bits, probes=probes,
            fallback_density=fallback_density,
        )

    def query_topk(self, A, m: int, *, tile: int = 2048,
                   probes: Optional[int] = None,
                   probe_path: Optional[str] = None,
                   adaptive: Optional[bool] = None,
                   candidate_budget: Optional[int] = None):
        """Top-``m`` across every shard via per-shard candidate
        generation + exact re-rank + the documented (distance,
        lower-global-id) cross-shard merge.  Same contract as the base
        ``query_topk`` (``dist`` int32, ``idx`` int64 global ids,
        ``m_eff = min(m, n_live)``); ``probe_path`` / ``adaptive`` /
        ``candidate_budget`` route every shard's probe ladder exactly
        as on ``LSHSimHashIndex.query_topk``."""
        p = _check_probes(probes, self.probes)
        if p == 0:
            return super().query_topk(A, m, tile=tile)
        if not isinstance(m, numbers.Integral) or m <= 0:
            raise ValueError(f"m must be a positive int, got {m!r}")
        A = self._check_queries(A)
        if self.n_codes == 0:
            raise ValueError("query_topk on an empty index")
        if self.n_live == 0:
            raise ValueError(
                "query_topk on an index whose codes are all deleted "
                "(tombstoned); compact() or add() live codes first"
            )
        device = self._shards[0]._lsh_probe_device(probe_path)
        adaptive_eff = self.adaptive if adaptive is None else bool(adaptive)
        budget_eff = (self.candidate_budget if candidate_budget is None
                      else _check_budget(candidate_budget))
        m_eff = int(min(m, self.n_live))
        # shard 0's mask cache serves the whole tier (shards share one
        # band plan): the perturbation sequence is pure combinatorics,
        # not something to recompute per coalesced serving batch
        masks = self._shards[0]._probe_masks(p)
        if device:
            # one serving tile feeds EVERY shard's dispatch, so it
            # clamps to the tightest per-shard probe plan
            for shard in self._shards:
                if shard.n_live > 0:
                    tile = shard._lsh_device_tile(
                        tile, p, int(min(m_eff, shard.n_live))
                    )
        nq = A.shape[0]
        out_d = np.empty((nq, m_eff), dtype=np.int32)
        out_i = np.empty((nq, m_eff), dtype=np.int64)
        pending: list = []  # [(lo, hi, [(si, kind, payload, m_s)])]

        def finish(entry):
            lo, hi, per_shard = entry
            d_parts, g_parts = [], []
            for si, kind, payload, m_s in per_shard:
                shard = self._shards[si]
                if kind == "lsh":
                    d_s, li_s = shard._lsh_finish_tile(payload, m_s)
                elif kind == "lsh_dev":
                    d_s, li_s = shard._lsh_finish_device_tile(
                        payload, m_s
                    )
                elif kind == "exact":
                    d_s, li_s = shard._topk_finish_tile(payload, m_s)
                else:  # 'done'
                    d_s, li_s = payload
                d_parts.append(d_s)
                g_parts.append(self._local_to_global(si, li_s))
            out_d[lo:hi], out_i[lo:hi] = self._merge_tile(
                d_parts, g_parts, m_eff
            )

        for lo in range(0, nq, tile):
            hi = min(lo + tile, nq)
            tile_a = A[lo:hi]
            per_shard = []
            for si, shard in enumerate(self._shards):
                if shard.n_live == 0:
                    continue  # empty or fully-tombstoned shard
                m_s = int(min(m_eff, shard.n_live))
                kind, payload = shard._lsh_tile_entry(
                    tile_a, m_s, masks, p, tile, device, adaptive_eff,
                    budget_eff,
                )
                per_shard.append((si, kind, payload, m_s))
            telemetry.registry().counter_inc(
                "shard.dispatches", len(per_shard)
            )
            if telemetry.enabled():
                telemetry.emit(
                    EVENTS.SHARD_TOPK_TILE, queries=int(hi - lo),
                    m=int(m_eff), shards=len(per_shard),
                    n_codes=int(self.n_codes),
                    **telemetry.trace_fields(),
                )
            pending.append((lo, hi, per_shard))
            if len(pending) >= 2:
                finish(pending.pop(0))
        while pending:
            finish(pending.pop(0))
        return out_d, out_i


# -- durable spill/restore ---------------------------------------------------


def _spill_lsh_keys(index, dirpath: str, gen: int,
                    keys: np.ndarray) -> dict:
    """THE ``lsh`` manifest block (single source — the single-device
    and sharded writers differ only in which key view they spill, and
    ``_resolve_lsh_kwargs``/``_verify_lsh_keys`` read both
    interchangeably, so the block must never fork): write the keys
    spill atomically beside the chunks, return the checksummed entry
    plus the band layout and serving knobs."""
    from randomprojection_tpu import durable

    fname = f"lsh-{gen:06d}.npy"
    durable._write_npy_atomic(os.path.join(dirpath, fname), keys)
    return {"lsh": {
        "file": fname,
        "sha256": durable._sha256(keys),
        "rows": int(keys.shape[1]),
        "bands": index.band_plan.bands,
        "band_bits": index.band_plan.band_bits,
        "probes": index.probes,
        "fallback_density": index.fallback_density,
    }}


def _resolve_lsh_kwargs(manifest: dict, bands, band_bits, probes,
                        fallback_density):
    """Band layout / serving knobs for a restore: explicit kwargs win,
    the manifest's persisted ``lsh`` block fills the gaps, library
    defaults fill the rest (the pre-LSH-snapshot path)."""
    meta = manifest.get("lsh") or {}
    kw = {
        "bands": meta.get("bands") if bands is None else int(bands),
        "band_bits": (
            meta.get("band_bits") if band_bits is None else int(band_bits)
        ),
        "probes": (
            int(meta.get("probes", 8)) if probes is None else int(probes)
        ),
        "fallback_density": (
            float(meta.get("fallback_density", 0.1))
            if fallback_density is None
            else float(fallback_density)
        ),
    }
    return kw, meta


def _verify_lsh_keys(dirpath: str, meta: dict, plan: BandPlan,
                     keys: np.ndarray) -> None:
    """Cross-check rebuilt band keys against the snapshot's persisted
    spill: present + same band layout → must match bit-for-bit
    (checksum verified first), else a loud ``ValueError`` — a corrupt
    or drifted bucket index must never serve silently-wrong
    candidates.  Absent (pre-LSH snapshot) or differently-banded
    (caller override) → the rebuild stands on its own."""
    if not meta:
        telemetry.emit(
            EVENTS.INDEX_LSH_BUILD, path=dirpath, rows=int(keys.shape[1]),
            n=int(keys.shape[1]), bands=plan.bands,
            band_bits=plan.band_bits, rebuilt="pre-lsh-snapshot",
        )
        return
    if (
        meta.get("bands") != plan.bands
        or meta.get("band_bits") != plan.band_bits
    ):
        return  # caller overrode the band layout: persisted keys N/A
    from randomprojection_tpu import durable

    arr = durable._load_chunk_verified(dirpath, meta)
    if arr.shape != keys.shape or arr.dtype != np.uint32:
        raise ValueError(
            f"persisted LSH band keys in {dirpath} have shape "
            f"{arr.shape}/{arr.dtype}, expected {keys.shape}/uint32"
        )
    if not np.array_equal(arr, keys):
        raise ValueError(
            f"persisted LSH band keys in {dirpath} disagree with keys "
            "rebuilt from the restored codes — the snapshot is corrupt "
            "or the key extraction drifted; refusing to serve a wrong "
            "bucket index"
        )


def load_lsh_index(path: str, *, bands: Optional[int] = None,
                   band_bits: Optional[int] = None,
                   probes: Optional[int] = None,
                   fallback_density: Optional[float] = None
                   ) -> LSHSimHashIndex:
    """Restore a single-device LSH index from a snapshot directory.

    Accepts LSH-format snapshots (band layout + serving knobs restore
    from the manifest, persisted keys verified bit-identical against
    the rebuild) AND pre-LSH r11-format snapshots (the banded index
    rebuilds from the codes — explicit kwargs or defaults pick the
    layout).  Chunk checksums, coverage and tombstones verify exactly
    as ``durable.load_index``."""
    from randomprojection_tpu import durable

    manifest = durable.read_manifest(path)
    kw, meta = _resolve_lsh_kwargs(
        manifest, bands, band_bits, probes, fallback_density
    )
    index = durable.load_index(
        path, index_cls=LSHSimHashIndex, index_kwargs=kw
    )
    _verify_lsh_keys(path, meta, index.band_plan, index._buckets.keys)
    return index


def load_lsh_sharded_index(path: str, *, mesh=None, devices=None,
                           n_shards: Optional[int] = None,
                           data_axis: str = "data",
                           topk_impl: str = "auto",
                           bands: Optional[int] = None,
                           band_bits: Optional[int] = None,
                           probes: Optional[int] = None,
                           fallback_density: Optional[float] = None
                           ) -> LSHShardedSimHashIndex:
    """Restore a sharded LSH index onto ANY shard layout (the r13
    layout-fungibility contract): the corpus re-shards balanced, each
    shard rebuilds its banded index over its local rows, and the
    persisted global-id-ordered keys verify against the re-derived
    global view — so bucket contents are bit-identical whatever layout
    wrote or reads the snapshot.  Pre-LSH and plain (unsharded)
    snapshots load with the index rebuilt."""
    from randomprojection_tpu import durable

    manifest = durable.read_manifest(path)
    kw, meta = _resolve_lsh_kwargs(
        manifest, bands, band_bits, probes, fallback_density
    )
    index = durable.load_sharded_index(
        path, mesh=mesh, devices=devices, n_shards=n_shards,
        data_axis=data_axis, topk_impl=topk_impl,
        index_cls=LSHShardedSimHashIndex, index_kwargs=kw,
    )
    _verify_lsh_keys(
        path, meta, index.band_plan, index._lsh_global_keys()
    )
    return index
