"""Multi-probe LSH candidate tier over SimHash bucket indexes (ISSUE 15).

Every query so far was an exact linear Hamming scan: the r12 fused
kernel made the scan fast and r13 spread it over shards, but at the
BL:10 billion-code scale each query still touches every code, so q/s is
bounded by corpus bandwidth no matter how good the kernel gets.
SimHash codes ARE an LSH family (Charikar 2002; multi-probe after Lv et
al. 2007): two codes that agree on a contiguous ``b``-bit **band** of
their sign bits are close with probability rising steeply as their
angle shrinks, so bucketing every code by ``L`` disjoint band keys
turns candidate generation into ``O(candidates)`` bucket lookups — the
exact kernel then re-ranks ONLY the candidates.

The tier, bottom to top:

- **Band keys** (``band_keys``) — code bits ``[j·b, (j+1)·b)`` of each
  packed code word form band ``j``'s key (little-endian bit order,
  matching ``np.packbits(bitorder='little')``).  A pure function of the
  codes, so the banded index is always rebuildable from a snapshot.
- **Banded CSR buckets** (``BandedBuckets``) — per band, a counting-
  sorted CSR layout ``indptr (2^b + 1) → ids`` with ids ascending
  within every bucket.  ``add`` merges new rows *incrementally*: only
  the new rows' keys are extracted and counting-sorted, then spliced
  into the existing CSR by a vectorized two-way merge — resident rows
  are never re-hashed.  Host-resident by design: the index is O(L·n)
  int32 beside an O(n·n_bytes) corpus, and the per-query probe work is
  O(L·P) ``searchsorted``-free pointer lookups.
- **Multi-probe** (``probe_masks``) — each band probes its exact bucket
  plus the nearest ``P-1`` perturbations: XOR masks in (popcount,
  ascending value) order, the uniform-confidence specialization of
  Lv et al.'s score order (packed codes carry sign bits only — no
  per-bit projection magnitudes survive the sketch, so every bit is
  equally confident and the perturbation order is data-independent and
  deterministic).  ``P ≥ 2^b`` probes every bucket of every band —
  full probe coverage — which makes the candidate set the whole live
  corpus and the result **bit-identical to brute force** (the parity
  discipline every kernel round ships under; ``make ann-smoke``).
- **Exact re-rank** (``LSHSimHashIndex.query_topk``) — per query tile,
  candidates deduplicate across bands, probes and the tile's queries
  (one sorted ``np.unique`` union; ascending global id order is what
  makes the re-rank's local tie-break equal the documented
  (distance, lower-global-id) order), tombstoned rows are filtered,
  the candidate code rows are gathered ON DEVICE from the resident
  chunks, and the r12 fused kernel scores the tile against them —
  in-kernel DMA'd Hamming matmul + bitonic running top-m, exactly the
  machinery the full scan uses, on 1/10th (or 1/1000th) of the rows.
- **Fallback ladder** — the tier NEVER serves worse than the exact
  path: a tile whose candidate union is too dense (``> fallback_density
  · n_live`` — re-rank would approach scan cost) or too starved
  (``< m`` — the result could not fill) falls back to the exact
  device ladder for that tile, recorded as ``index.lsh.fallback``;
  a scoped-VMEM OOM in the re-rank kernel degrades to a device-Hamming
  + host-select rung (same order, same results).  ``probes=0`` pins
  the exact path outright.

Sharding: ``LSHShardedSimHashIndex`` builds one banded index per shard
(the shard hook ``ShardedSimHashIndex._make_shard``), probes and
re-ranks per shard, and merges per-shard candidates through the SAME
``_merge_tile`` lexsort as the exact tier — cross-shard tombstones and
``id_offset`` global ids carry over unchanged.  Serving: both classes
keep the ``query_topk(A, m, tile=)`` surface, so they plug directly
into ``TopKServer`` / ``ShardedTopKServer`` — the micro-batcher fans
coalesced batches into the LSH tier with no server changes.

Durability: band keys persist beside the chunks in the r11 manifest
(``lsh-<gen>.npy``, SHA-256-checksummed, **global id order** — so the
spill is layout-fungible exactly like r13 sharded snapshots), and
loading verifies the persisted keys against keys rebuilt from the
restored codes — corruption or extraction drift is a loud
``ValueError``, never a silently-wrong bucket index.  A pre-LSH
(r11-format) snapshot loads cleanly with the index rebuilt from codes.

Telemetry: ``index.lsh.dispatch`` (probe counts, candidate fraction),
``index.lsh.fallback`` (reason — the doctor's degraded audit),
``index.lsh.build`` (bucket folds) — all in ``telemetry.EVENTS`` and
consumed by ``trace_report``'s candidate-generation section.
"""

from __future__ import annotations

import itertools
import numbers
import os
from typing import Optional

import numpy as np

from randomprojection_tpu.models.sketch import (
    SimHashIndex,
    _hamming_tile_fn,
    _host_topk_select,
    _start_host_copy,
)
from randomprojection_tpu.serving.sharded_index import ShardedSimHashIndex
from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS

__all__ = [
    "BandPlan",
    "band_keys",
    "probe_masks",
    "BandedBuckets",
    "LSHSimHashIndex",
    "LSHShardedSimHashIndex",
    "load_lsh_index",
    "load_lsh_sharded_index",
]

# bucket-space ceiling: indptr is (2^b + 1) int64 per band — b=20 is
# 8 MB/band, past which the CSR pointer array stops being "beside the
# corpus" and becomes a corpus of its own
_MAX_BAND_BITS = 20
# band-key extraction block: bounds the unpacked bit matrix to
# ~2 MB/256-bit codes however large one add() is
_KEY_EXTRACT_BLOCK = 1 << 16


class BandPlan:
    """Resolved band layout: ``bands`` disjoint ``band_bits``-bit key
    slices over the leading ``bands·band_bits`` code bits.

    Defaults: ``band_bits = min(16, n_bits)`` (65536 buckets — sparse
    at any per-shard corpus size that fits int32 ids) and ``bands =
    min(8, n_bits // band_bits)`` (8 independent collision chances per
    probe).  Bands must fit the real bit count — ragged codes (e.g. 20
    bits in 3 bytes) never key on pad bits."""

    __slots__ = ("n_bits", "bands", "band_bits")

    def __init__(self, n_bits: int, *, bands: Optional[int] = None,
                 band_bits: Optional[int] = None):
        n_bits = int(n_bits)
        if n_bits < 1:
            raise ValueError(f"n_bits must be >= 1, got {n_bits}")
        if band_bits is None:
            band_bits = min(16, n_bits)
        band_bits = int(band_bits)
        if not 1 <= band_bits <= _MAX_BAND_BITS:
            raise ValueError(
                f"band_bits must be in [1, {_MAX_BAND_BITS}], got "
                f"{band_bits}"
            )
        if bands is None:
            bands = max(1, min(8, n_bits // band_bits))
        bands = int(bands)
        if bands < 1:
            raise ValueError(f"bands must be >= 1, got {bands}")
        if bands * band_bits > n_bits:
            raise ValueError(
                f"bands={bands} x band_bits={band_bits} needs "
                f"{bands * band_bits} code bits but the codes carry only "
                f"{n_bits}; bands are disjoint slices of the real bits"
            )
        self.n_bits = n_bits
        self.bands = bands
        self.band_bits = band_bits

    def __eq__(self, other):
        return (
            isinstance(other, BandPlan)
            and (self.n_bits, self.bands, self.band_bits)
            == (other.n_bits, other.bands, other.band_bits)
        )

    def __repr__(self):  # pragma: no cover — debugging aid
        return (
            f"BandPlan(n_bits={self.n_bits}, bands={self.bands}, "
            f"band_bits={self.band_bits})"
        )


def band_keys(codes, plan: BandPlan) -> np.ndarray:
    """Band keys of packed codes: ``(bands, n)`` uint32, key ``j`` of a
    row being its code bits ``[j·b, (j+1)·b)`` (little-endian within
    each byte, matching ``np.packbits(bitorder='little')`` and the
    Hamming kernels).  Pure host function of the codes — the banded
    index is always rebuildable from any snapshot."""
    codes = np.ascontiguousarray(codes, dtype=np.uint8)
    if codes.ndim != 2:
        raise ValueError(f"codes must be (n, nbytes), got {codes.shape}")
    n = codes.shape[0]
    b = plan.band_bits
    out = np.empty((plan.bands, n), np.uint32)
    w = np.uint32(1) << np.arange(b, dtype=np.uint32)
    for lo in range(0, n, _KEY_EXTRACT_BLOCK):
        hi = min(lo + _KEY_EXTRACT_BLOCK, n)
        bits = np.unpackbits(codes[lo:hi], axis=1, bitorder="little")
        for j in range(plan.bands):
            sl = bits[:, j * b : (j + 1) * b].astype(np.uint32)
            out[j, lo:hi] = (sl * w[None, :]).sum(axis=1, dtype=np.uint32)
    return out


def probe_masks(band_bits: int, probes: int) -> np.ndarray:
    """The first ``probes`` XOR masks of the multi-probe perturbation
    sequence: the exact bucket first, then masks in (popcount,
    ascending value) order — flip one bit before two, lower bit
    positions before higher.  With sign-only codes every bit is equally
    confident, so this is the uniform-confidence specialization of the
    Lv et al. score order: deterministic, data-independent, and total
    (``probes ≥ 2^band_bits`` enumerates every bucket — full probe
    coverage)."""
    if not isinstance(probes, numbers.Integral) or probes < 1:
        raise ValueError(f"probes must be a positive int, got {probes!r}")
    band_bits = int(band_bits)
    probes = int(min(probes, 1 << band_bits))
    out = [0]
    flips = 1
    while len(out) < probes and flips <= band_bits:
        vals = sorted(
            sum(1 << p for p in combo)
            for combo in itertools.combinations(range(band_bits), flips)
        )
        out.extend(vals[: probes - len(out)])
        flips += 1
    return np.asarray(out, dtype=np.uint32)


class BandedBuckets:
    """Per-band CSR inverted bucket index over one shard's local id
    space (see module docstring).

    State per band: ``indptr`` ``(2^b + 1,)`` int64 and ``ids`` ``(n,)``
    int32, counting-sorted by bucket with ids ASCENDING within every
    bucket — the invariant that makes candidate unions id-sorted and
    the re-rank tie-break exact.  ``keys`` ``(bands, n)`` uint32 holds
    every row's band keys in id order: the persisted durable state
    (layout-fungible — id order IS the snapshot order) and what
    ``compact()``'s id remap folds without re-extraction."""

    __slots__ = ("plan", "n", "keys", "_indptr", "_ids")

    def __init__(self, plan: BandPlan):
        self.plan = plan
        self.n = 0
        self.keys = np.empty((plan.bands, 0), np.uint32)
        nb = 1 << plan.band_bits
        self._indptr = [
            np.zeros(nb + 1, np.int64) for _ in range(plan.bands)
        ]
        self._ids = [np.empty(0, np.int32) for _ in range(plan.bands)]

    @classmethod
    def from_keys(cls, plan: BandPlan, keys: np.ndarray) -> "BandedBuckets":
        """Rebuild from a persisted/remapped key matrix (one counting
        sort per band — no code bytes touched)."""
        keys = np.ascontiguousarray(keys, dtype=np.uint32)
        if keys.ndim != 2 or keys.shape[0] != plan.bands:
            raise ValueError(
                f"keys must be ({plan.bands}, n), got {keys.shape}"
            )
        b = cls(plan)
        b._append_keys(keys)
        return b

    def add(self, codes) -> int:
        """Fold new rows (appended at local ids ``[n, n+rows)``) into
        every band's CSR — extracts keys for the NEW rows only and
        splices them in with a vectorized merge; resident rows are
        never re-hashed.  Returns the number of rows folded."""
        new_keys = band_keys(codes, self.plan)
        self._append_keys(new_keys)
        return new_keys.shape[1]

    def _append_keys(self, new_keys: np.ndarray) -> None:
        m = new_keys.shape[1]
        if m == 0:
            return
        row0 = self.n
        if row0 + m > 2**31 - 1:
            raise ValueError(
                "BandedBuckets ids are int32 (the per-shard id space); "
                f"have {row0}, adding {m} would overflow"
            )
        nb = 1 << self.plan.band_bits
        for j in range(self.plan.bands):
            k = new_keys[j].astype(np.int64)
            counts = np.bincount(k, minlength=nb)
            csum = np.concatenate(([0], np.cumsum(counts)))
            old_indptr = self._indptr[j]
            old_ids = self._ids[j]
            old_counts = np.diff(old_indptr)
            indptr = old_indptr + csum
            out = np.empty(old_ids.size + m, np.int32)
            if old_ids.size:
                # old bucket k's run shifts right by the number of new
                # rows landing in buckets < k (csum[k])
                shift = np.repeat(csum[:-1], old_counts)
                out[np.arange(old_ids.size, dtype=np.int64) + shift] = (
                    old_ids
                )
            # stable sort groups new rows by bucket keeping id order —
            # within-bucket ids stay ascending, and every new id is
            # greater than every old id, so the invariant holds
            order = np.argsort(k, kind="stable")
            grp_start = np.repeat(csum[:-1], counts)
            within = np.arange(m, dtype=np.int64) - grp_start
            dest = np.repeat(indptr[:-1] + old_counts, counts) + within
            out[dest] = (row0 + order).astype(np.int32)
            self._indptr[j] = indptr
            self._ids[j] = out
        self.keys = np.concatenate([self.keys, new_keys], axis=1)
        self.n += m

    def candidates(self, qkeys: np.ndarray, masks: np.ndarray):
        """Union candidate ids for one query tile: probe bucket
        ``qkey ^ mask`` in every band for every perturbation mask,
        gather the bucket runs, and deduplicate across bands, probes
        AND the tile's queries.  Returns ``(ids, gathered)`` — ``ids``
        sorted ascending int32 (``np.unique``), ``gathered`` the
        pre-dedup candidate count (the duplication factor is a bucket-
        quality signal the dispatch event records)."""
        parts = []
        gathered = 0
        for j in range(self.plan.bands):
            buckets = (
                (qkeys[j][:, None] ^ masks[None, :])
                .ravel()
                .astype(np.int64)
            )
            indptr = self._indptr[j]
            starts = indptr[buckets]
            lens = indptr[buckets + 1] - starts
            total = int(lens.sum())
            if total == 0:
                continue
            csum = np.concatenate(([0], np.cumsum(lens)))
            take = np.repeat(starts - csum[:-1], lens) + np.arange(
                total, dtype=np.int64
            )
            parts.append(self._ids[j][take])
            gathered += total
        if not parts:
            return np.empty(0, np.int32), 0
        return np.unique(np.concatenate(parts)), gathered

    def bucket_ids(self, band: int, key: int) -> np.ndarray:
        """One bucket's id run (ascending) — introspection/testing."""
        indptr = self._indptr[band]
        return self._ids[band][indptr[key] : indptr[key + 1]].copy()


def _check_probes(probes, default: int) -> int:
    """Per-call ``probes`` resolution, validated like the constructor
    knob (a float would silently truncate to fewer probes than the
    caller computed): None → the serving default, else a non-negative
    int (0 = the exact path)."""
    if probes is None:
        return default
    if not isinstance(probes, numbers.Integral) or probes < 0:
        raise ValueError(
            f"probes must be a non-negative int, got {probes!r}"
        )
    return int(probes)


class LSHSimHashIndex(SimHashIndex):
    """``SimHashIndex`` with a banded multi-probe LSH candidate tier:
    ``query_topk`` probes the banded bucket index, exact-Hamming
    re-ranks only the candidates through the r12 fused kernel, and
    falls back to the exact device ladder whenever the candidate set is
    too dense or too starved — the tier never serves worse than the
    exact path (see module docstring).

    ``probes`` is the recall/q-s knob: perturbation buckets probed per
    band (1 = exact bucket only; ``2^band_bits`` = full coverage =
    bit-identical to brute force).  The constructor value is the
    serving default — a ``TopKServer`` coalescing onto this index uses
    it — and ``query_topk(probes=...)`` overrides per call (``0`` pins
    the exact scan path).  ``fallback_density`` is the ladder
    threshold: a tile whose candidate union exceeds that fraction of
    the live corpus re-ranks at near-scan cost, so it serves through
    the exact path instead.

    The bucket index maintains itself through every mutation path:
    ``add`` folds new rows incrementally, ``delete`` needs no bucket
    work (tombstones filter at re-rank), ``compact`` folds the id
    remap, and snapshot restore rebuilds (verifying against persisted
    keys when the snapshot carries them).  Single-device by
    construction (one LSH index is one shard) — the sharded tier is
    ``LSHShardedSimHashIndex``."""

    def __init__(self, codes, *, bands: Optional[int] = None,
                 band_bits: Optional[int] = None, probes: int = 8,
                 fallback_density: float = 0.1, **kw):
        if kw.get("mesh") is not None:
            raise ValueError(
                "LSHSimHashIndex is single-device (one banded index is "
                "one shard); shard a corpus with ann.LSHShardedSimHashIndex"
            )
        if not isinstance(probes, numbers.Integral) or probes < 1:
            raise ValueError(
                f"probes must be a positive int, got {probes!r}"
            )
        if not 0.0 < float(fallback_density) <= 1.0:
            raise ValueError(
                f"fallback_density must be in (0, 1], got "
                f"{fallback_density!r}"
            )
        self.probes = int(probes)
        self.fallback_density = float(fallback_density)
        self._lsh_cfg = (bands, band_bits)
        self._lsh_suspend = False
        self._masks_cache: dict = {}
        # scoped-VMEM OOM memo for the re-rank kernel (r6 convention,
        # mirroring _fused_degraded): a (nq, rows_pad, m) shape that
        # OOM'd once serves the host rung for the process lifetime
        # instead of re-paying the failed dispatch per tile
        self._lsh_fused_degraded: set = set()
        # resolve the band plan BEFORE the base constructor uploads the
        # bulk chunk, so the append hook folds rows directly — no
        # deferred copy of the corpus (which at the BL:10 scale would
        # transiently double host memory).  n_bits mirrors the base
        # resolution; the base constructor still owns its validation.
        codes = np.asarray(codes, dtype=np.uint8)
        if codes.ndim != 2:
            raise ValueError(f"codes must be (n, nbytes), got {codes.shape}")
        n_bits = kw.get("n_bits")
        n_bits = codes.shape[1] * 8 if n_bits is None else int(n_bits)
        self.band_plan = BandPlan(n_bits, bands=bands, band_bits=band_bits)
        self._buckets = BandedBuckets(self.band_plan)
        super().__init__(codes, **kw)

    # -- bucket maintenance (hooks off the base mutation paths) --------------

    def _codes_appended(self, codes: np.ndarray, row0: int) -> None:
        if self._lsh_suspend:
            return
        self._lsh_fold(codes)

    def _lsh_fold(self, codes: np.ndarray) -> None:
        rows = self._buckets.add(codes)
        telemetry.registry().counter_inc("index.lsh.builds")
        telemetry.emit(
            EVENTS.INDEX_LSH_BUILD, rows=int(rows),
            n=int(self._buckets.n), bands=self.band_plan.bands,
            band_bits=self.band_plan.band_bits,
        )

    def _rebuild_from_host(self, codes: np.ndarray) -> None:
        # a wholesale replacement (compact(), durable re-compaction)
        # starts the banded index over unless compact() is folding the
        # id remap itself (suspended — see compact())
        if not self._lsh_suspend and self._buckets is not None:
            self._buckets = BandedBuckets(self.band_plan)
        super()._rebuild_from_host(codes)

    def compact(self) -> np.ndarray:
        """Fold tombstones/chunks exactly like the base ``compact``,
        then fold the returned old→new id mapping through the banded
        index: surviving rows keep their extracted band keys
        (``keys[:, mapping]``), renumbered compactly — no re-hash of
        the corpus."""
        old_keys = self._buckets.keys if self._buckets is not None else None
        self._lsh_suspend = True
        try:
            mapping = super().compact()
        finally:
            self._lsh_suspend = False
        if old_keys is not None:
            self._buckets = BandedBuckets.from_keys(
                self.band_plan, old_keys[:, mapping]
            )
            telemetry.registry().counter_inc("index.lsh.builds")
            telemetry.emit(
                EVENTS.INDEX_LSH_BUILD, rows=int(self._buckets.n),
                n=int(self._buckets.n), bands=self.band_plan.bands,
                band_bits=self.band_plan.band_bits, remapped=True,
            )
        return mapping

    # -- durable persistence (see durable.save_index's extra hook) -----------

    def _durable_extra(self, dirpath: str, gen: int) -> dict:
        """Manifest extras for ``durable.save_index``: spill the band
        keys (id order — layout-fungible) beside the chunks,
        checksummed like them, plus the band layout and serving knobs
        so ``load_lsh_index`` restores the identical tier."""
        return _spill_lsh_keys(self, dirpath, gen, self._buckets.keys)

    @classmethod
    def load(cls, path: str, *, bands: Optional[int] = None,
             band_bits: Optional[int] = None,
             probes: Optional[int] = None,
             fallback_density: Optional[float] = None,
             mesh=None, data_axis: str = "data"):
        """Restore an LSH index from a snapshot directory — LSH-format
        or pre-LSH r11-format (the banded index is then rebuilt from
        the codes).  See ``load_lsh_index``."""
        if mesh is not None:
            raise ValueError(
                "LSHSimHashIndex is single-device; load a sharded "
                "snapshot with ann.load_lsh_sharded_index"
            )
        return load_lsh_index(
            path, bands=bands, band_bits=band_bits, probes=probes,
            fallback_density=fallback_density,
        )

    # -- the candidate tier --------------------------------------------------

    def _probe_masks(self, probes: int) -> np.ndarray:
        masks = self._masks_cache.get(probes)
        if masks is None:
            masks = probe_masks(self.band_plan.band_bits, probes)
            self._masks_cache[probes] = masks
        return masks

    def lsh_stats(self) -> dict:
        """Process-registry candidate-tier tallies (shared across
        same-process indexes, like every registry counter)."""
        reg = telemetry.registry()
        return {
            "dispatches": reg.counter("index.lsh.dispatches"),
            "fallbacks": reg.counter("index.lsh.fallbacks"),
            "candidates": reg.counter("index.lsh.candidates"),
            "probe_buckets": reg.counter("index.lsh.probe_buckets"),
            "builds": reg.counter("index.lsh.builds"),
        }

    def query_topk(self, A, m: int, *, tile: int = 2048,
                   probes: Optional[int] = None):
        """Top-``m`` via the candidate tier: same contract as
        ``SimHashIndex.query_topk`` — ``(dist, idx)`` int32, ``m_eff =
        min(m, n_live)`` columns, (distance, lower-global-id) order —
        but each tile touches only its candidate union unless the
        fallback ladder routes it to the exact path.  ``probes``
        overrides the serving default (``0`` = exact path; ``tile`` is
        also the candidate-union granularity — smaller tiles mean
        per-query-sharper candidate sets at more dispatches).

        Determinism under PARTIAL probes is per (query set, tile):
        the candidate union is tile-scoped, so grouping a query with
        different neighbors (a different ``tile``, or a coalescing
        server padding/batching requests) can ENLARGE its candidate
        set.  The effect is monotone — a superset of candidates can
        only return equal-or-closer answers, never displace a correct
        one — and vanishes at full probe coverage, where the union is
        the whole live corpus regardless of grouping."""
        p = _check_probes(probes, self.probes)
        if p == 0:
            return super().query_topk(A, m, tile=tile)
        if not isinstance(m, numbers.Integral) or m <= 0:
            raise ValueError(f"m must be a positive int, got {m!r}")
        A = self._check_queries(A)
        if self.n_codes == 0:
            raise ValueError("query_topk on an empty index")
        if self.n_live == 0:
            raise ValueError(
                "query_topk on an index whose codes are all deleted "
                "(tombstoned); compact() or add() live codes first"
            )
        m_eff = int(min(m, self.n_live))
        masks = self._probe_masks(p)
        nq = A.shape[0]
        out_d = np.empty((nq, m_eff), dtype=np.int32)
        out_i = np.empty((nq, m_eff), dtype=np.int32)
        # same one-behind overlap as the exact path: tile i's d2h +
        # select ride under tile i+1's probe/gather/dispatch
        pending: list = []  # [(lo, hi, kind, payload)]

        def finish(entry):
            lo, hi, kind, payload = entry
            if kind == "lsh":
                d, i = self._lsh_finish_tile(payload, m_eff)
            elif kind == "exact":
                d, i = self._topk_finish_tile(payload, m_eff)
            else:  # 'done': served synchronously (dense host rung)
                d, i = payload
            out_d[lo:hi] = d
            out_i[lo:hi] = i

        for lo in range(0, nq, tile):
            hi = min(lo + tile, nq)
            kind, payload = self._lsh_dispatch_tile(
                A[lo:hi], m_eff, masks, tile
            )
            pending.append((lo, hi, kind, payload))
            if len(pending) >= 2:
                finish(pending.pop(0))
        while pending:
            finish(pending.pop(0))
        return out_d, out_i

    def _lsh_dispatch_tile(self, a_np, m_eff: int, masks: np.ndarray,
                           tile: int):
        """Candidate generation + re-rank dispatch for one query tile.
        Returns ``(kind, payload)``: ``('lsh', ...)`` for a dispatched
        candidate re-rank, ``('exact', handles)`` when the ladder fell
        back to the exact device fan-out, ``('done', (d, i))`` when the
        exact path itself is host-scale (dense rung).  Shared with the
        sharded tier, which calls it per shard."""
        qkeys = band_keys(a_np, self.band_plan)
        cand, gathered = self._buckets.candidates(qkeys, masks)
        if self._dead is not None and cand.size:
            # tombstones filter at re-rank: a deleted code is never
            # gathered, so it can never win (ISSUE 15 storage contract)
            cand = cand[~self._dead[cand]]
        n_cand = int(cand.size)
        nq = int(a_np.shape[0])
        n_probes = nq * self.band_plan.bands * int(masks.size)
        reg = telemetry.registry()
        if n_cand < m_eff or n_cand > self.fallback_density * self.n_live:
            reason = "starved" if n_cand < m_eff else "dense"
            reg.counter_inc("index.lsh.fallbacks")
            telemetry.emit(
                EVENTS.INDEX_LSH_FALLBACK, reason=reason, queries=nq,
                probes=int(masks.size), candidates=n_cand,
                n_live=int(self.n_live),
                threshold=self.fallback_density,
                **telemetry.trace_fields(),
            )
            if self._topk_route(nq, m_eff) == "dense":
                # host-scale request: the exact path serves it whole
                return "done", SimHashIndex.query_topk(
                    self, a_np, m_eff, tile=tile
                )
            return "exact", self._topk_dispatch_tile(a_np, m_eff)
        frac = n_cand / max(self.n_live, 1)
        reg.counter_inc("index.lsh.dispatches")
        reg.counter_inc("index.lsh.probe_buckets", n_probes)
        reg.counter_inc("index.lsh.candidates", n_cand)
        reg.gauge_set("index.lsh.candidate_fraction", frac)
        if telemetry.enabled():
            telemetry.emit(
                EVENTS.INDEX_LSH_DISPATCH, queries=nq, m=int(m_eff),
                probes=int(masks.size), bands=self.band_plan.bands,
                candidates=n_cand, gathered=int(gathered),
                candidate_fraction=round(frac, 6),
                **telemetry.trace_fields(),
            )
        return "lsh", self._lsh_rerank_dispatch(a_np, cand, m_eff)

    def _gather_codes_device(self, cand: np.ndarray):
        """Gather the candidate code rows ON DEVICE from the resident
        chunks (no host copy of any code byte) and zero-pad to the row
        bucket so the re-rank kernel compiles one program per bucket,
        not one per candidate count."""
        import jax.numpy as jnp

        from randomprojection_tpu.parallel.sharded import row_bucket

        parts = []
        base = 0
        for c in self._chunks:
            lo = np.searchsorted(cand, base)
            hi = np.searchsorted(cand, base + c.n)
            if hi > lo:
                local = self._device_queries(
                    (cand[lo:hi] - base).astype(np.int32)
                )
                parts.append(jnp.take(c.b, local, axis=0))
            base += c.n
        g = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)
        pad_to = row_bucket(int(cand.size))
        if pad_to != cand.size:
            g = jnp.pad(g, ((0, pad_to - cand.size), (0, 0)))
        return g

    def _lsh_rerank_dispatch(self, a_np, cand: np.ndarray, m_eff: int):
        """Dispatch the exact re-rank of one tile against its gathered
        candidates and START the d2h.  Default rung: the r12 fused
        Pallas kernel (in-kernel DMA'd Hamming matmul + bitonic running
        top-m — the same machinery the full scan uses).  A scoped-VMEM
        OOM, or a shape the planner cannot tile, degrades to one device
        Hamming dispatch + host select — same (dist, lower-id) order,
        same results (the candidate set is small by construction, the
        density gate bounds it)."""
        from randomprojection_tpu.ops import topk_kernels

        a = self._device_queries(a_np)
        cand_dev = self._gather_codes_device(cand)
        n_cand = int(cand.size)
        shape_key = (int(a_np.shape[0]), int(cand_dev.shape[0]), m_eff)
        plan = None
        if shape_key not in self._lsh_fused_degraded:
            plan = topk_kernels.plan_fused(*shape_key[:2], self.n_bytes,
                                           shape_key[2])
        if plan is not None:
            from randomprojection_tpu.ops.pallas_kernels import (
                is_vmem_oom,
                record_vmem_oom_retry,
            )

            try:
                d, i = topk_kernels.fused_topk(
                    a, cand_dev, n_cand, m_eff, plan=plan
                )
                _start_host_copy(d)
                _start_host_copy(i)
                return ("fused", d, i, cand)
            except Exception as e:
                if not is_vmem_oom(e):
                    raise
                # degraded retry, r6 convention: memoize only after the
                # failure is classified — this shape serves the host
                # rung for the process lifetime, never re-paying the
                # failed dispatch per tile
                record_vmem_oom_retry(a_np.shape, "lsh_rerank", m_eff)
                self._lsh_fused_degraded.add(shape_key)
        d = _hamming_tile_fn()(a, cand_dev)
        _start_host_copy(d)
        return ("host", d, None, cand)

    def _lsh_finish_tile(self, payload, m_eff: int):
        """Materialize one re-rank dispatch and map candidate-local
        positions back to global ids.  ``cand`` is ascending, so the
        kernel's lower-local-id tie-break IS the documented
        lower-global-id order."""
        kind, d, i, cand = payload
        if kind == "fused":
            # d2h already started at dispatch: these materialize the
            # prefetched copy, one tile behind the live dispatch
            dist = np.asarray(d)
            idx = np.asarray(i)
            return dist, cand[idx].astype(np.int32)
        # host-select rung: distances over the padded candidate rows —
        # slice the pad columns off before the exact host selection
        # (d2h started at dispatch, same one-behind contract)
        D = np.asarray(d)[:, : cand.size]
        dloc, iloc = _host_topk_select(D, m_eff)
        return dloc, cand[iloc].astype(np.int32)


class LSHShardedSimHashIndex(ShardedSimHashIndex):
    """``ShardedSimHashIndex`` whose shards carry banded multi-probe
    LSH tiers: a query tile probes EVERY shard's bucket index, each
    shard exact-re-ranks its own candidates (full per-shard fallback
    ladder — a dense shard falls back to its exact scan while its
    neighbors stay sublinear), and the per-shard candidates merge
    through the same ``_merge_tile`` lexsort as the exact tier — so
    cross-shard tombstones, int64 global ids and ``id_offset`` behave
    identically, and full probe coverage is bit-identical to
    ``topk_bruteforce`` on the concatenated corpus.

    Plugs into ``ShardedTopKServer`` unchanged (the ``query_topk``
    surface is the contract); ``probes`` at construction is the serving
    default, per-call ``probes=`` overrides, ``0`` pins the exact
    path."""

    def __init__(self, codes, *, bands: Optional[int] = None,
                 band_bits: Optional[int] = None, probes: int = 8,
                 fallback_density: float = 0.1, **kw):
        if not isinstance(probes, numbers.Integral) or probes < 1:
            raise ValueError(
                f"probes must be a positive int, got {probes!r}"
            )
        if not 0.0 < float(fallback_density) <= 1.0:
            raise ValueError(
                f"fallback_density must be in (0, 1], got "
                f"{fallback_density!r}"
            )
        self.probes = int(probes)
        self.fallback_density = float(fallback_density)
        self._lsh_cfg = (bands, band_bits)
        super().__init__(codes, **kw)
        self.band_plan = self._shards[0].band_plan

    def _make_shard(self, s: int, dev):
        bands, band_bits = self._lsh_cfg
        return LSHSimHashIndex(
            np.empty((0, self.n_bytes), np.uint8),
            n_bits=self.n_bits, topk_impl=self.topk_impl, device=dev,
            label=f"shard {s}/{len(self._devices)} on {dev}",
            bands=bands, band_bits=band_bits, probes=self.probes,
            fallback_density=self.fallback_density,
        )

    def _lsh_global_keys(self) -> np.ndarray:
        """Every row's band keys in GLOBAL id order — the
        layout-fungible durable state (segments translate each shard's
        local key columns into their global positions)."""
        out = np.empty((self.band_plan.bands, self.n_codes), np.uint32)
        for seg in self._segments:
            ks = self._shards[seg.shard]._buckets.keys
            out[:, seg.g0 : seg.g0 + seg.rows] = ks[
                :, seg.l0 : seg.l0 + seg.rows
            ]
        return out

    def _durable_extra(self, dirpath: str, gen: int) -> dict:
        return _spill_lsh_keys(
            self, dirpath, gen, self._lsh_global_keys()
        )

    @classmethod
    def load(cls, path: str, *, mesh=None, devices=None,
             n_shards: Optional[int] = None, data_axis: str = "data",
             topk_impl: str = "auto", bands: Optional[int] = None,
             band_bits: Optional[int] = None,
             probes: Optional[int] = None,
             fallback_density: Optional[float] = None):
        """Restore onto ANY shard layout — LSH-format or pre-LSH
        snapshots, sharded or plain.  See ``load_lsh_sharded_index``."""
        return load_lsh_sharded_index(
            path, mesh=mesh, devices=devices, n_shards=n_shards,
            data_axis=data_axis, topk_impl=topk_impl, bands=bands,
            band_bits=band_bits, probes=probes,
            fallback_density=fallback_density,
        )

    def query_topk(self, A, m: int, *, tile: int = 2048,
                   probes: Optional[int] = None):
        """Top-``m`` across every shard via per-shard candidate
        generation + exact re-rank + the documented (distance,
        lower-global-id) cross-shard merge.  Same contract as the base
        ``query_topk`` (``dist`` int32, ``idx`` int64 global ids,
        ``m_eff = min(m, n_live)``)."""
        p = _check_probes(probes, self.probes)
        if p == 0:
            return super().query_topk(A, m, tile=tile)
        if not isinstance(m, numbers.Integral) or m <= 0:
            raise ValueError(f"m must be a positive int, got {m!r}")
        A = self._check_queries(A)
        if self.n_codes == 0:
            raise ValueError("query_topk on an empty index")
        if self.n_live == 0:
            raise ValueError(
                "query_topk on an index whose codes are all deleted "
                "(tombstoned); compact() or add() live codes first"
            )
        m_eff = int(min(m, self.n_live))
        # shard 0's mask cache serves the whole tier (shards share one
        # band plan): the perturbation sequence is pure combinatorics,
        # not something to recompute per coalesced serving batch
        masks = self._shards[0]._probe_masks(p)
        nq = A.shape[0]
        out_d = np.empty((nq, m_eff), dtype=np.int32)
        out_i = np.empty((nq, m_eff), dtype=np.int64)
        pending: list = []  # [(lo, hi, [(si, kind, payload, m_s)])]

        def finish(entry):
            lo, hi, per_shard = entry
            d_parts, g_parts = [], []
            for si, kind, payload, m_s in per_shard:
                shard = self._shards[si]
                if kind == "lsh":
                    d_s, li_s = shard._lsh_finish_tile(payload, m_s)
                elif kind == "exact":
                    d_s, li_s = shard._topk_finish_tile(payload, m_s)
                else:  # 'done'
                    d_s, li_s = payload
                d_parts.append(d_s)
                g_parts.append(self._local_to_global(si, li_s))
            out_d[lo:hi], out_i[lo:hi] = self._merge_tile(
                d_parts, g_parts, m_eff
            )

        for lo in range(0, nq, tile):
            hi = min(lo + tile, nq)
            tile_a = A[lo:hi]
            per_shard = []
            for si, shard in enumerate(self._shards):
                if shard.n_live == 0:
                    continue  # empty or fully-tombstoned shard
                m_s = int(min(m_eff, shard.n_live))
                kind, payload = shard._lsh_dispatch_tile(
                    tile_a, m_s, masks, tile
                )
                per_shard.append((si, kind, payload, m_s))
            telemetry.registry().counter_inc(
                "shard.dispatches", len(per_shard)
            )
            if telemetry.enabled():
                telemetry.emit(
                    EVENTS.SHARD_TOPK_TILE, queries=int(hi - lo),
                    m=int(m_eff), shards=len(per_shard),
                    n_codes=int(self.n_codes),
                    **telemetry.trace_fields(),
                )
            pending.append((lo, hi, per_shard))
            if len(pending) >= 2:
                finish(pending.pop(0))
        while pending:
            finish(pending.pop(0))
        return out_d, out_i


# -- durable spill/restore ---------------------------------------------------


def _spill_lsh_keys(index, dirpath: str, gen: int,
                    keys: np.ndarray) -> dict:
    """THE ``lsh`` manifest block (single source — the single-device
    and sharded writers differ only in which key view they spill, and
    ``_resolve_lsh_kwargs``/``_verify_lsh_keys`` read both
    interchangeably, so the block must never fork): write the keys
    spill atomically beside the chunks, return the checksummed entry
    plus the band layout and serving knobs."""
    from randomprojection_tpu import durable

    fname = f"lsh-{gen:06d}.npy"
    durable._write_npy_atomic(os.path.join(dirpath, fname), keys)
    return {"lsh": {
        "file": fname,
        "sha256": durable._sha256(keys),
        "rows": int(keys.shape[1]),
        "bands": index.band_plan.bands,
        "band_bits": index.band_plan.band_bits,
        "probes": index.probes,
        "fallback_density": index.fallback_density,
    }}


def _resolve_lsh_kwargs(manifest: dict, bands, band_bits, probes,
                        fallback_density):
    """Band layout / serving knobs for a restore: explicit kwargs win,
    the manifest's persisted ``lsh`` block fills the gaps, library
    defaults fill the rest (the pre-LSH-snapshot path)."""
    meta = manifest.get("lsh") or {}
    kw = {
        "bands": meta.get("bands") if bands is None else int(bands),
        "band_bits": (
            meta.get("band_bits") if band_bits is None else int(band_bits)
        ),
        "probes": (
            int(meta.get("probes", 8)) if probes is None else int(probes)
        ),
        "fallback_density": (
            float(meta.get("fallback_density", 0.1))
            if fallback_density is None
            else float(fallback_density)
        ),
    }
    return kw, meta


def _verify_lsh_keys(dirpath: str, meta: dict, plan: BandPlan,
                     keys: np.ndarray) -> None:
    """Cross-check rebuilt band keys against the snapshot's persisted
    spill: present + same band layout → must match bit-for-bit
    (checksum verified first), else a loud ``ValueError`` — a corrupt
    or drifted bucket index must never serve silently-wrong
    candidates.  Absent (pre-LSH snapshot) or differently-banded
    (caller override) → the rebuild stands on its own."""
    if not meta:
        telemetry.emit(
            EVENTS.INDEX_LSH_BUILD, path=dirpath, rows=int(keys.shape[1]),
            n=int(keys.shape[1]), bands=plan.bands,
            band_bits=plan.band_bits, rebuilt="pre-lsh-snapshot",
        )
        return
    if (
        meta.get("bands") != plan.bands
        or meta.get("band_bits") != plan.band_bits
    ):
        return  # caller overrode the band layout: persisted keys N/A
    from randomprojection_tpu import durable

    arr = durable._load_chunk_verified(dirpath, meta)
    if arr.shape != keys.shape or arr.dtype != np.uint32:
        raise ValueError(
            f"persisted LSH band keys in {dirpath} have shape "
            f"{arr.shape}/{arr.dtype}, expected {keys.shape}/uint32"
        )
    if not np.array_equal(arr, keys):
        raise ValueError(
            f"persisted LSH band keys in {dirpath} disagree with keys "
            "rebuilt from the restored codes — the snapshot is corrupt "
            "or the key extraction drifted; refusing to serve a wrong "
            "bucket index"
        )


def load_lsh_index(path: str, *, bands: Optional[int] = None,
                   band_bits: Optional[int] = None,
                   probes: Optional[int] = None,
                   fallback_density: Optional[float] = None
                   ) -> LSHSimHashIndex:
    """Restore a single-device LSH index from a snapshot directory.

    Accepts LSH-format snapshots (band layout + serving knobs restore
    from the manifest, persisted keys verified bit-identical against
    the rebuild) AND pre-LSH r11-format snapshots (the banded index
    rebuilds from the codes — explicit kwargs or defaults pick the
    layout).  Chunk checksums, coverage and tombstones verify exactly
    as ``durable.load_index``."""
    from randomprojection_tpu import durable

    manifest = durable.read_manifest(path)
    kw, meta = _resolve_lsh_kwargs(
        manifest, bands, band_bits, probes, fallback_density
    )
    index = durable.load_index(
        path, index_cls=LSHSimHashIndex, index_kwargs=kw
    )
    _verify_lsh_keys(path, meta, index.band_plan, index._buckets.keys)
    return index


def load_lsh_sharded_index(path: str, *, mesh=None, devices=None,
                           n_shards: Optional[int] = None,
                           data_axis: str = "data",
                           topk_impl: str = "auto",
                           bands: Optional[int] = None,
                           band_bits: Optional[int] = None,
                           probes: Optional[int] = None,
                           fallback_density: Optional[float] = None
                           ) -> LSHShardedSimHashIndex:
    """Restore a sharded LSH index onto ANY shard layout (the r13
    layout-fungibility contract): the corpus re-shards balanced, each
    shard rebuilds its banded index over its local rows, and the
    persisted global-id-ordered keys verify against the re-derived
    global view — so bucket contents are bit-identical whatever layout
    wrote or reads the snapshot.  Pre-LSH and plain (unsharded)
    snapshots load with the index rebuilt."""
    from randomprojection_tpu import durable

    manifest = durable.read_manifest(path)
    kw, meta = _resolve_lsh_kwargs(
        manifest, bands, band_bits, probes, fallback_density
    )
    index = durable.load_sharded_index(
        path, mesh=mesh, devices=devices, n_shards=n_shards,
        data_axis=data_axis, topk_impl=topk_impl,
        index_cls=LSHShardedSimHashIndex, index_kwargs=kw,
    )
    _verify_lsh_keys(
        path, meta, index.band_plan, index._lsh_global_keys()
    )
    return index
