"""Multi-probe LSH candidate tier (ISSUE 15; ROADMAP open item 2).

Sublinear top-k retrieval over the SimHash serving stack: banded CSR
bucket indexes over the packed codes, multi-probe candidate generation
(probe count = the recall/q-s knob), exact-Hamming re-rank of ONLY the
candidates through the r12 fused kernel, and a fallback ladder that
never serves worse than the exact scan.  See ``lsh.py`` for the band
key / perturbation-order / durability arguments, and
docs/ARCHITECTURE.md "Multi-probe LSH candidate tier".
"""

from randomprojection_tpu.ann.lsh import (
    BandedBuckets,
    BandPlan,
    LSHShardedSimHashIndex,
    LSHSimHashIndex,
    band_keys,
    load_lsh_index,
    load_lsh_sharded_index,
    probe_masks,
)

__all__ = [
    "BandPlan",
    "band_keys",
    "probe_masks",
    "BandedBuckets",
    "LSHSimHashIndex",
    "LSHShardedSimHashIndex",
    "load_lsh_index",
    "load_lsh_sharded_index",
]
