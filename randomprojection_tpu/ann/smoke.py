"""``make ann-smoke``: multi-probe LSH tier parity on the interpreter.

Asserts, at toy shapes, the acceptance contract of the candidate tier
(ISSUE 15): at FULL probe coverage (every bucket of every band probed,
fallback ladder disabled so the re-rank path genuinely runs)
``LSHSimHashIndex.query_topk`` and ``LSHShardedSimHashIndex.query_topk``
are bit-identical to ``topk_bruteforce`` — including cross-shard
tombstones — on CPU via the Pallas interpreter, no chip required; the
density-fallback rung serves the same results through the exact ladder;
and partial-probe answers are self-consistent (every returned distance
is the true Hamming distance of its returned id).  Runs before tier-1
in ``make verify`` on the same virtual-8-device topology the shard
smoke uses.
"""

from __future__ import annotations

import numpy as np

__all__ = ["main"]


def main() -> None:
    import jax

    from randomprojection_tpu.ann import (
        LSHShardedSimHashIndex,
        LSHSimHashIndex,
    )
    from randomprojection_tpu.models import sketch as sk

    n_dev = len(jax.devices())
    rng = np.random.default_rng(0)
    codes = rng.integers(0, 256, size=(1100, 8), dtype=np.uint8)
    queries = rng.integers(0, 256, size=(24, 8), dtype=np.uint8)
    m = 7
    full = 1 << 8  # probes >= 2^band_bits = every bucket = full coverage
    rd, ri = sk.topk_bruteforce(queries, codes, m)

    # full probe coverage, ladder disabled: the candidate union is the
    # whole corpus and the RE-RANK path must reproduce brute force
    lsh = LSHSimHashIndex(codes, bands=4, band_bits=8,
                          fallback_density=1.0)
    d, i = lsh.query_topk(queries, m, probes=full)
    assert np.array_equal(d, rd), "full-probe LSH dist != brute force"
    assert np.array_equal(i, ri), "full-probe LSH ids != brute force"

    # density fallback rung: a tiny threshold trips the ladder and the
    # exact device path serves — never worse than today
    lo = LSHSimHashIndex(codes, bands=4, band_bits=8,
                         fallback_density=0.01)
    d2, i2 = lo.query_topk(queries, m, probes=full)
    assert np.array_equal(d2, rd) and np.array_equal(i2, ri), (
        "density-fallback rung != brute force"
    )

    # partial probes: approximate top-k, but every answer is EXACT for
    # the id it returns (the re-rank is exact Hamming by construction)
    dp, ip = lsh.query_topk(queries, m, probes=2)
    D = sk.pairwise_hamming(queries, codes)
    assert (np.take_along_axis(D, ip, axis=1) == dp).all(), (
        "partial-probe distances are not the true Hamming distances"
    )

    # sharded tier, cross-shard tombstones (8 shards of ~137 rows:
    # [200, 420) spans boundaries and tombstones one shard whole),
    # full probes == masked brute force
    sh = LSHShardedSimHashIndex(codes, n_shards=8, bands=4, band_bits=8,
                                fallback_density=1.0)
    dead = np.arange(200, 420)
    sh.delete(dead)
    Dm = D.astype(np.int64)
    Dm[:, dead] = 8 * 8 + 1
    rdm, rim = sk._host_topk_select(Dm, m)
    dm, im = sh.query_topk(queries, m, probes=full)
    assert np.array_equal(dm, rdm), (
        "sharded full-probe LSH dist != masked brute force"
    )
    assert np.array_equal(im, rim.astype(np.int64)), (
        "sharded full-probe LSH ids != masked brute force "
        "(cross-shard tombstones)"
    )

    print(
        f"ann-smoke OK: full-probe LSH == exact == brute force on "
        f"{n_dev} device(s) (single + 8-shard, cross-shard tombstones); "
        "density fallback exact; partial-probe distances exact"
    )


if __name__ == "__main__":
    main()
