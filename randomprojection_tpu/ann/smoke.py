"""``make ann-smoke``: multi-probe LSH tier parity on the interpreter.

Asserts, at toy shapes, the acceptance contract of the candidate tier
(ISSUE 15): at FULL probe coverage (every bucket of every band probed,
fallback ladder disabled so the re-rank path genuinely runs)
``LSHSimHashIndex.query_topk`` and ``LSHShardedSimHashIndex.query_topk``
are bit-identical to ``topk_bruteforce`` — including cross-shard
tombstones — on CPU via the Pallas interpreter, no chip required; the
density-fallback rung serves the same results through the exact ladder;
and partial-probe answers are self-consistent (every returned distance
is the true Hamming distance of its returned id).  Runs before tier-1
in ``make verify`` on the same virtual-8-device topology the shard
smoke uses.
"""

from __future__ import annotations

import numpy as np

__all__ = ["main"]


def main() -> None:
    import jax

    from randomprojection_tpu.ann import (
        LSHShardedSimHashIndex,
        LSHSimHashIndex,
    )
    from randomprojection_tpu.models import sketch as sk

    n_dev = len(jax.devices())
    rng = np.random.default_rng(0)
    codes = rng.integers(0, 256, size=(1100, 8), dtype=np.uint8)
    queries = rng.integers(0, 256, size=(24, 8), dtype=np.uint8)
    m = 7
    full = 1 << 8  # probes >= 2^band_bits = every bucket = full coverage
    rd, ri = sk.topk_bruteforce(queries, codes, m)

    # full probe coverage, ladder disabled: the candidate union is the
    # whole corpus and the RE-RANK path must reproduce brute force
    lsh = LSHSimHashIndex(codes, bands=4, band_bits=8,
                          fallback_density=1.0)
    d, i = lsh.query_topk(queries, m, probes=full)
    assert np.array_equal(d, rd), "full-probe LSH dist != brute force"
    assert np.array_equal(i, ri), "full-probe LSH ids != brute force"

    # density fallback rung: a tiny threshold trips the ladder and the
    # exact device path serves — never worse than today
    lo = LSHSimHashIndex(codes, bands=4, band_bits=8,
                         fallback_density=0.01)
    d2, i2 = lo.query_topk(queries, m, probes=full)
    assert np.array_equal(d2, rd) and np.array_equal(i2, ri), (
        "density-fallback rung != brute force"
    )

    # partial probes: approximate top-k, but every answer is EXACT for
    # the id it returns (the re-rank is exact Hamming by construction)
    dp, ip = lsh.query_topk(queries, m, probes=2)
    D = sk.pairwise_hamming(queries, codes)
    assert (np.take_along_axis(D, ip, axis=1) == dp).all(), (
        "partial-probe distances are not the true Hamming distances"
    )

    # sharded tier, cross-shard tombstones (8 shards of ~137 rows:
    # [200, 420) spans boundaries and tombstones one shard whole),
    # full probes == masked brute force
    sh = LSHShardedSimHashIndex(codes, n_shards=8, bands=4, band_bits=8,
                                fallback_density=1.0)
    dead = np.arange(200, 420)
    sh.delete(dead)
    Dm = D.astype(np.int64)
    Dm[:, dead] = 8 * 8 + 1
    rdm, rim = sk._host_topk_select(Dm, m)
    dm, im = sh.query_topk(queries, m, probes=full)
    assert np.array_equal(dm, rdm), (
        "sharded full-probe LSH dist != masked brute force"
    )
    assert np.array_equal(im, rim.astype(np.int64)), (
        "sharded full-probe LSH ids != masked brute force "
        "(cross-shard tombstones)"
    )

    # device-fused probe path (ISSUE 16): the on-device probe → gather →
    # re-rank dispatch (Pallas interpreter on CPU — the same kernels a
    # chip runs) must be BIT-IDENTICAL to the host probe path and, at
    # full coverage, to brute force.  probe_path="device" forces the
    # fused path (auto resolves to host under the interpreter).
    full4 = 1 << 4
    dv = LSHSimHashIndex(codes[:700], bands=4, band_bits=4,
                         fallback_density=1.0, probe_path="device")
    dv.add(codes[700:])              # second resident chunk
    dv.delete(np.arange(650, 760))   # tombstones spanning the chunk seam
    Dv = D.astype(np.int64)
    Dv[:, 650:760] = 8 * 8 + 1
    rdv, riv = sk._host_topk_select(Dv, m)
    dd, di = dv.query_topk(queries, m, probes=full4)
    assert np.array_equal(dd, rdv) and np.array_equal(di, riv), (
        "device-path full-probe LSH != brute force "
        "(multi-chunk + tombstones)"
    )
    hd, hi = dv.query_topk(queries, m, probes=3, probe_path="host")
    pd_, pi_ = dv.query_topk(queries, m, probes=3)
    assert np.array_equal(pd_, hd) and np.array_equal(pi_, hi), (
        "device-path partial-probe answers != host probe path"
    )

    # ragged n_bits (61 of 64): device vs host parity at full coverage
    rg_h = LSHSimHashIndex(codes, bands=4, band_bits=4, n_bits=61,
                           fallback_density=1.0, probe_path="host")
    rg_d = LSHSimHashIndex(codes, bands=4, band_bits=4, n_bits=61,
                           fallback_density=1.0, probe_path="device")
    hd, hi = rg_h.query_topk(queries, m, probes=full4)
    dd, di = rg_d.query_topk(queries, m, probes=full4)
    assert np.array_equal(dd, hd) and np.array_equal(di, hi), (
        "device-path LSH != host path at ragged n_bits=61"
    )

    # 8-shard device path with cross-shard tombstones (one shard wholly
    # dead): full coverage == the same masked brute force as the host leg
    sh2 = LSHShardedSimHashIndex(codes, n_shards=8, bands=4, band_bits=4,
                                 fallback_density=1.0,
                                 probe_path="device")
    sh2.delete(dead)
    Dm4 = D.astype(np.int64)
    Dm4[:, dead] = 8 * 8 + 1
    rdm4, rim4 = sk._host_topk_select(Dm4, m)
    dm2, im2 = sh2.query_topk(queries, m, probes=full4)
    assert np.array_equal(dm2, rdm4), (
        "sharded device-path full-probe LSH dist != masked brute force"
    )
    assert np.array_equal(im2, rim4.astype(np.int64)), (
        "sharded device-path full-probe LSH ids != masked brute force "
        "(cross-shard tombstones)"
    )

    print(
        f"ann-smoke OK: full-probe LSH == exact == brute force on "
        f"{n_dev} device(s) (single + 8-shard, cross-shard tombstones); "
        "density fallback exact; partial-probe distances exact; "
        "device-fused probe path bit-identical to host (multi-chunk, "
        "tombstones, ragged n_bits, 8-shard)"
    )


if __name__ == "__main__":
    main()
