"""Build + load the native murmur3 library (g++ → .so → ctypes).

No pybind11 in this image, so bindings are plain ctypes over an
``extern "C"`` surface.  The .so is built once next to the source and
reused; a build failure (no compiler) degrades gracefully — callers fall
back to the pure-Python implementation in ``ops/hashing.py``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional

_SRC = os.path.join(os.path.dirname(__file__), "murmur3.cpp")
_SO = os.path.join(os.path.dirname(__file__), "_murmur3.so")

_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _compile() -> bool:
    tmp = None
    try:
        # build to a temp file then atomically rename: concurrent importers
        # never see a half-written .so
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(_SO))
        os.close(fd)
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
             _SRC, "-o", tmp],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError):
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return False


def load_murmur3() -> Optional[ctypes.CDLL]:
    """The bound library, or None if no compiler is available.

    A stale-but-present .so (source newer than the binary, no compiler to
    rebuild) still loads: the legacy ABI keeps the fast batch path alive,
    and bindings added since (the ``*_t`` explicit-thread entry points)
    degrade gracefully via ``has_explicit_threads`` below — falling all
    the way to the pure-Python hasher would be orders slower.
    """
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    if not os.path.exists(_SO):
        if not _compile():
            _build_failed = True
            return None
    elif os.path.getmtime(_SO) < os.path.getmtime(_SRC):
        _compile()  # best effort: on failure the stale .so serves legacy ABI
    lib = ctypes.CDLL(_SO)
    lib.murmur3_32.restype = ctypes.c_uint32
    lib.murmur3_32.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_uint32,
    ]
    lib.hash_tokens.restype = None
    lib.hash_tokens.argtypes = [
        ctypes.c_char_p,   # concatenated token bytes
        ctypes.c_void_p,   # int64 offsets
        ctypes.c_int64,    # n_tokens
        ctypes.c_uint32,   # seed
        ctypes.c_uint32,   # n_features
        ctypes.c_void_p,   # int32 out_idx
        ctypes.c_void_p,   # int8 out_sign
    ]
    lib.hash_tokens_strided.restype = None
    lib.hash_tokens_strided.argtypes = [
        ctypes.c_void_p,   # fixed-width token buffer ('S<w>' array data)
        ctypes.c_int64,    # stride (itemsize)
        ctypes.c_void_p,   # int64 lengths
        ctypes.c_int64,    # n_tokens
        ctypes.c_uint32,   # seed
        ctypes.c_uint32,   # n_features
        ctypes.c_void_p,   # int32 out_idx
        ctypes.c_void_p,   # int8 out_sign
    ]
    # explicit-thread-count entry points (r6): absent from a stale
    # prebuilt .so — callers then fall back to the RP_HASH_THREADS env
    # override (ops/hashing.py), same results, process-global knob
    try:
        lib.hash_tokens_t.restype = None
        lib.hash_tokens_t.argtypes = lib.hash_tokens.argtypes + [
            ctypes.c_int64,  # n_threads (<= 0 = env/hardware default)
        ]
        lib.hash_tokens_strided_t.restype = None
        lib.hash_tokens_strided_t.argtypes = (
            lib.hash_tokens_strided.argtypes + [ctypes.c_int64]
        )
        lib.has_explicit_threads = True
    except AttributeError:  # pragma: no cover - needs a pre-r6 .so
        lib.has_explicit_threads = False
    _lib = lib
    return _lib
