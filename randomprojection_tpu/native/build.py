"""Build + load the native murmur3 library (g++ → .so → ctypes).

No pybind11 in this image, so bindings are plain ctypes over an
``extern "C"`` surface.  The .so is built once next to the source and
reused; a build failure (no compiler) degrades gracefully — callers fall
back to the pure-Python implementation in ``ops/hashing.py``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional

_SRC = os.path.join(os.path.dirname(__file__), "murmur3.cpp")
_SO = os.path.join(os.path.dirname(__file__), "_murmur3.so")

_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _compile() -> bool:
    tmp = None
    try:
        # build to a temp file then atomically rename: concurrent importers
        # never see a half-written .so
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(_SO))
        os.close(fd)
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
             _SRC, "-o", tmp],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError):
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return False


def load_murmur3() -> Optional[ctypes.CDLL]:
    """The bound library, or None if no compiler is available."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
        if not _compile():
            _build_failed = True
            return None
    lib = ctypes.CDLL(_SO)
    lib.murmur3_32.restype = ctypes.c_uint32
    lib.murmur3_32.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_uint32,
    ]
    lib.hash_tokens.restype = None
    lib.hash_tokens.argtypes = [
        ctypes.c_char_p,   # concatenated token bytes
        ctypes.c_void_p,   # int64 offsets
        ctypes.c_int64,    # n_tokens
        ctypes.c_uint32,   # seed
        ctypes.c_uint32,   # n_features
        ctypes.c_void_p,   # int32 out_idx
        ctypes.c_void_p,   # int8 out_sign
    ]
    lib.hash_tokens_strided.restype = None
    lib.hash_tokens_strided.argtypes = [
        ctypes.c_void_p,   # fixed-width token buffer ('S<w>' array data)
        ctypes.c_int64,    # stride (itemsize)
        ctypes.c_void_p,   # int64 lengths
        ctypes.c_int64,    # n_tokens
        ctypes.c_uint32,   # seed
        ctypes.c_uint32,   # n_features
        ctypes.c_void_p,   # int32 out_idx
        ctypes.c_void_p,   # int8 out_sign
    ]
    _lib = lib
    return _lib
