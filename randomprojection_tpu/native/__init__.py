"""Native (C++) host-side components, built on demand with g++ + ctypes."""

from randomprojection_tpu.native.build import load_murmur3

__all__ = ["load_murmur3"]
