// MurmurHash3 x86_32 (Austin Appleby, public domain) + batch token hashing.
//
// Native host-side component for the feature-hashing path (SURVEY.md §3.2:
// the reference's hot hashing loop is Cython/C++ — sklearn
// `feature_extraction/_hashing_fast.pyx`; this is its C++ equivalent for
// the TPU framework's host ingest).  Compiled by native/build.py with g++
// into _murmur3.so and bound via ctypes (no pybind11 in this image).
//
// Contract (matches sklearn FeatureHasher semantics):
//   h    = signed 32-bit murmur3 of the token bytes, seed 0
//   idx  = |h| mod n_features
//   sign = +1 if h >= 0 else -1        (alternate_sign)

#include <cstdint>
#include <cstring>

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6bu;
  h ^= h >> 13;
  h *= 0xc2b2ae35u;
  h ^= h >> 16;
  return h;
}

extern "C" {

uint32_t murmur3_32(const uint8_t* data, int64_t len, uint32_t seed) {
  const int64_t nblocks = len / 4;
  uint32_t h1 = seed;
  const uint32_t c1 = 0xcc9e2d51u;
  const uint32_t c2 = 0x1b873593u;

  for (int64_t i = 0; i < nblocks; i++) {
    uint32_t k1;
    std::memcpy(&k1, data + i * 4, 4);  // little-endian assumed (x86/ARM)
    k1 *= c1;
    k1 = rotl32(k1, 15);
    k1 *= c2;
    h1 ^= k1;
    h1 = rotl32(h1, 13);
    h1 = h1 * 5 + 0xe6546b64u;
  }

  const uint8_t* tail = data + nblocks * 4;
  uint32_t k1 = 0;
  switch (len & 3) {
    case 3: k1 ^= static_cast<uint32_t>(tail[2]) << 16; [[fallthrough]];
    case 2: k1 ^= static_cast<uint32_t>(tail[1]) << 8;  [[fallthrough]];
    case 1:
      k1 ^= tail[0];
      k1 *= c1;
      k1 = rotl32(k1, 15);
      k1 *= c2;
      h1 ^= k1;
  }

  h1 ^= static_cast<uint32_t>(len);
  return fmix32(h1);
}

// Batch: tokens concatenated in `buf`, token i = buf[offsets[i], offsets[i+1]).
// Writes idx (|h| mod n_features) and sign (±1) per token.
void hash_tokens(const uint8_t* buf, const int64_t* offsets, int64_t n_tokens,
                 uint32_t seed, uint32_t n_features, int32_t* out_idx,
                 int8_t* out_sign) {
  for (int64_t i = 0; i < n_tokens; i++) {
    const int64_t lo = offsets[i];
    const int64_t len = offsets[i + 1] - lo;
    const int32_t h = static_cast<int32_t>(murmur3_32(buf + lo, len, seed));
    const int64_t habs = h < 0 ? -static_cast<int64_t>(h) : h;
    out_idx[i] = static_cast<int32_t>(habs % n_features);
    out_sign[i] = h >= 0 ? 1 : -1;
  }
}

// Strided batch: token i = buf[i*stride, i*stride + lengths[i]).  This is
// the zero-copy layout of a numpy fixed-width bytes ('S<w>') array, so a
// whole token column ingests in ONE call with no per-token Python work —
// the vectorized path for the streaming TF-IDF workload.
void hash_tokens_strided(const uint8_t* buf, int64_t stride,
                         const int64_t* lengths, int64_t n_tokens,
                         uint32_t seed, uint32_t n_features,
                         int32_t* out_idx, int8_t* out_sign) {
  for (int64_t i = 0; i < n_tokens; i++) {
    const int32_t h = static_cast<int32_t>(
        murmur3_32(buf + i * stride, lengths[i], seed));
    const int64_t habs = h < 0 ? -static_cast<int64_t>(h) : h;
    out_idx[i] = static_cast<int32_t>(habs % n_features);
    out_sign[i] = h >= 0 ? 1 : -1;
  }
}

}  // extern "C"
