// MurmurHash3 x86_32 (Austin Appleby, public domain) + batch token hashing.
//
// Native host-side component for the feature-hashing path (SURVEY.md §3.2:
// the reference's hot hashing loop is Cython/C++ — sklearn
// `feature_extraction/_hashing_fast.pyx`; this is its C++ equivalent for
// the TPU framework's host ingest).  Compiled by native/build.py with g++
// into _murmur3.so and bound via ctypes (no pybind11 in this image).
//
// Contract (matches sklearn FeatureHasher semantics):
//   h    = signed 32-bit murmur3 of the token bytes, seed 0
//   idx  = |h| mod n_features
//   sign = +1 if h >= 0 else -1        (alternate_sign)

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6bu;
  h ^= h >> 13;
  h *= 0xc2b2ae35u;
  h ^= h >> 16;
  return h;
}

// Threading: token i's outputs depend only on token i, so splitting the
// range over threads is bit-identical to the serial loop at any thread
// count.  Engages only for large batches (>= 2^18 tokens) on multi-core
// hosts.  The worker count comes from the explicit `n_threads` argument
// of the *_t entry points (the streaming path's per-call opt-in — no
// process-global state, safe for concurrent streams); the legacy entry
// points pass 0 = consult RP_HASH_THREADS / hardware concurrency
// (0/1 = serial).  The dev box for this repo has one core — real ingest
// hosts (config 5: 100M docs) don't.
static int64_t hash_worker_count(int64_t n_tokens, int64_t requested) {
  int64_t hc = requested;
  if (hc <= 0) {
    hc = static_cast<int64_t>(std::thread::hardware_concurrency());
    if (const char* env = std::getenv("RP_HASH_THREADS")) {
      const long v = std::strtol(env, nullptr, 10);
      hc = v > 0 ? v : 1;
    }
  }
  if (hc <= 1 || n_tokens < (int64_t{1} << 18)) return 1;
  // keep >= 64k tokens per thread so spawn cost stays negligible
  return std::max<int64_t>(1, std::min(hc, n_tokens >> 16));
}

template <typename Fn>
static void parallel_over(int64_t n, int64_t requested, Fn fn) {
  const int64_t nw = hash_worker_count(n, requested);
  if (nw == 1) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(nw);
  const int64_t chunk = (n + nw - 1) / nw;
  int64_t dispatched = 0;  // rows [0, dispatched) are owned by threads
  // spawn failure (e.g. EAGAIN under RLIMIT_NPROC) must not escape the
  // extern "C" boundary into ctypes: finish the rest serially instead
  try {
    for (int64_t w = 0; w < nw; w++) {
      const int64_t lo = w * chunk;
      const int64_t hi = std::min(n, lo + chunk);
      if (lo >= hi) break;
      threads.emplace_back(fn, lo, hi);
      dispatched = hi;
    }
  } catch (...) {
  }
  if (dispatched < n) fn(dispatched, n);
  for (auto& t : threads) t.join();
}

extern "C" {

uint32_t murmur3_32(const uint8_t* data, int64_t len, uint32_t seed) {
  const int64_t nblocks = len / 4;
  uint32_t h1 = seed;
  const uint32_t c1 = 0xcc9e2d51u;
  const uint32_t c2 = 0x1b873593u;

  for (int64_t i = 0; i < nblocks; i++) {
    uint32_t k1;
    std::memcpy(&k1, data + i * 4, 4);  // little-endian assumed (x86/ARM)
    k1 *= c1;
    k1 = rotl32(k1, 15);
    k1 *= c2;
    h1 ^= k1;
    h1 = rotl32(h1, 13);
    h1 = h1 * 5 + 0xe6546b64u;
  }

  const uint8_t* tail = data + nblocks * 4;
  uint32_t k1 = 0;
  switch (len & 3) {
    case 3: k1 ^= static_cast<uint32_t>(tail[2]) << 16; [[fallthrough]];
    case 2: k1 ^= static_cast<uint32_t>(tail[1]) << 8;  [[fallthrough]];
    case 1:
      k1 ^= tail[0];
      k1 *= c1;
      k1 = rotl32(k1, 15);
      k1 *= c2;
      h1 ^= k1;
  }

  h1 ^= static_cast<uint32_t>(len);
  return fmix32(h1);
}

// Batch with an explicit worker count (`n_threads`; <= 0 = consult
// RP_HASH_THREADS / hardware default): tokens concatenated in `buf`,
// token i = buf[offsets[i], offsets[i+1]).  Writes idx (|h| mod
// n_features) and sign (±1) per token.
void hash_tokens_t(const uint8_t* buf, const int64_t* offsets,
                   int64_t n_tokens, uint32_t seed, uint32_t n_features,
                   int32_t* out_idx, int8_t* out_sign, int64_t n_threads) {
  parallel_over(n_tokens, n_threads, [=](int64_t a, int64_t b) {
    for (int64_t i = a; i < b; i++) {
      const int64_t lo = offsets[i];
      const int64_t len = offsets[i + 1] - lo;
      const int32_t h = static_cast<int32_t>(murmur3_32(buf + lo, len, seed));
      const int64_t habs = h < 0 ? -static_cast<int64_t>(h) : h;
      out_idx[i] = static_cast<int32_t>(habs % n_features);
      out_sign[i] = h >= 0 ? 1 : -1;
    }
  });
}

// Legacy ABI (worker count from the environment) — kept so a stale
// prebuilt .so and the current binding stay interoperable.
void hash_tokens(const uint8_t* buf, const int64_t* offsets, int64_t n_tokens,
                 uint32_t seed, uint32_t n_features, int32_t* out_idx,
                 int8_t* out_sign) {
  hash_tokens_t(buf, offsets, n_tokens, seed, n_features, out_idx, out_sign,
                0);
}

// Strided batch: token i = buf[i*stride, i*stride + lengths[i]).  This is
// the zero-copy layout of a numpy fixed-width bytes ('S<w>') array, so a
// whole token column ingests in ONE call with no per-token Python work —
// the vectorized path for the streaming TF-IDF workload.
void hash_tokens_strided_t(const uint8_t* buf, int64_t stride,
                           const int64_t* lengths, int64_t n_tokens,
                           uint32_t seed, uint32_t n_features,
                           int32_t* out_idx, int8_t* out_sign,
                           int64_t n_threads) {
  parallel_over(n_tokens, n_threads, [=](int64_t a, int64_t b) {
    for (int64_t i = a; i < b; i++) {
      const int32_t h = static_cast<int32_t>(
          murmur3_32(buf + i * stride, lengths[i], seed));
      const int64_t habs = h < 0 ? -static_cast<int64_t>(h) : h;
      out_idx[i] = static_cast<int32_t>(habs % n_features);
      out_sign[i] = h >= 0 ? 1 : -1;
    }
  });
}

void hash_tokens_strided(const uint8_t* buf, int64_t stride,
                         const int64_t* lengths, int64_t n_tokens,
                         uint32_t seed, uint32_t n_features,
                         int32_t* out_idx, int8_t* out_sign) {
  hash_tokens_strided_t(buf, stride, lengths, n_tokens, seed, n_features,
                        out_idx, out_sign, 0);
}

}  // extern "C"
