"""Multi-host bring-up (SURVEY.md §3.4 process model).

The reference's distributed fabric is a Spark driver plus executors; the
TPU-native equivalent is SPMD: one Python process per host, every process
running the same program, `jax.distributed.initialize()` wiring them into
one runtime whose mesh spans all chips.  Collectives ride ICI within a
slice and DCN across slices — there is no driver, no RPC layer, and no
hand-written networking in this framework.

Typical pod usage::

    from randomprojection_tpu.parallel import distributed, default_mesh

    distributed.initialize()            # no-op on single-process runs
    mesh = default_mesh()               # spans every chip in the job
    est = GaussianRandomProjection(256, random_state=0, backend="jax",
                                   backend_options={"mesh": mesh})
    est.fit_schema(n_rows, d)           # R generated sharding-invariantly
    for lo, y in est.transform_stream(my_source): ...  # rows of THIS host

Each host streams its own row range (`host_row_range` below): rows are
independent, so no cross-host coordination is needed beyond the gang-
scheduled collectives XLA emits.  Failure recovery is restart + cursor
resume (see `streaming.py`) — SPMD jobs are gang-scheduled, so a lost host
means the job restarts from checkpoints, exactly like the reference's
lineage recomputation but with explicit cursors.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

__all__ = ["initialize", "is_multi_process", "host_row_range"]

#: env vars whose presence means "this process was launched as part of a
#: distributed job" — if auto-detection then fails, that is a
#: misconfiguration to surface, not a single-machine run to degrade to
_DISTRIBUTED_ENV_MARKERS = (
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
    "JAX_NUM_PROCESSES",
    "JAX_PROCESS_ID",
)


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host runtime; safe no-op for single-process runs.

    With no arguments, relies on the TPU environment's auto-detection
    (GKE/TPU-VM metadata).  Explicit arguments support manual bring-up.
    Idempotent: repeated calls after a successful initialize are ignored.

    Failure policy: initialization errors are swallowed ONLY when nothing
    indicates a distributed launch (no explicit arguments, no coordinator
    env vars) — that is the ordinary single-machine case.  Any explicit
    argument, or a distributed-launch env marker, makes failure fatal:
    silently degrading a real pod job to single-process would compute 1/Nth
    of the work while claiming success.
    """
    import jax

    if getattr(initialize, "_done", False):
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        initialize._done = True
    except (ValueError, RuntimeError) as e:
        explicit = (
            coordinator_address is not None
            or process_id is not None
            or num_processes not in (None, 1)
        )
        markers = [v for v in _DISTRIBUTED_ENV_MARKERS if os.environ.get(v)]
        if explicit or markers:
            raise RuntimeError(
                "jax.distributed.initialize failed for what looks like a "
                f"distributed launch (explicit args={explicit}, env markers="
                f"{markers}); refusing to silently degrade to single-process"
            ) from e
        initialize._done = True
        import logging

        logging.getLogger("randomprojection_tpu").debug(
            "jax.distributed.initialize skipped: %s", e
        )


def is_multi_process() -> bool:
    import jax

    return jax.process_count() > 1


def host_row_range(
    n_rows: int,
    *,
    process_id: Optional[int] = None,
    process_count: Optional[int] = None,
) -> Tuple[int, int]:
    """This host's contiguous row slice ``[lo, hi)`` of a global stream.

    Rows are independent in X·Rᵀ, so the natural multi-host decomposition
    is block-by-process (the Spark partition map's equivalent).  The split
    is balanced to within one row and every process computes it without
    communication.  ``process_id``/``process_count`` default to the live
    runtime's values; passing them makes the function pure (tests, offline
    planning).
    """
    if n_rows < 0:
        raise ValueError(f"n_rows must be non-negative, got {n_rows}")
    if (process_id is None) != (process_count is None):
        # a half-specified pair silently overridden by the live runtime
        # would return a wrong partition plan with no error
        raise ValueError(
            "pass process_id and process_count together (or neither, to "
            "use the live runtime's values)"
        )
    if process_id is None:
        import jax

        process_id, process_count = jax.process_index(), jax.process_count()
    if not 0 <= process_id < process_count:
        raise ValueError(
            f"process_id {process_id} out of range for {process_count} processes"
        )
    base, extra = divmod(n_rows, process_count)
    lo = process_id * base + min(process_id, extra)
    hi = lo + base + (1 if process_id < extra else 0)
    return lo, hi
