"""Multi-host bring-up (SURVEY.md §3.4 process model).

The reference's distributed fabric is a Spark driver plus executors; the
TPU-native equivalent is SPMD: one Python process per host, every process
running the same program, `jax.distributed.initialize()` wiring them into
one runtime whose mesh spans all chips.  Collectives ride ICI within a
slice and DCN across slices — there is no driver, no RPC layer, and no
hand-written networking in this framework.

Typical pod usage::

    from randomprojection_tpu.parallel import distributed, default_mesh

    distributed.initialize()            # no-op on single-process runs
    mesh = default_mesh()               # spans every chip in the job
    est = GaussianRandomProjection(256, random_state=0, backend="jax",
                                   backend_options={"mesh": mesh})
    est.fit_schema(n_rows, d)           # R generated sharding-invariantly
    for lo, y in est.transform_stream(my_source): ...  # rows of THIS host

Each host streams its own row range (`host_row_range` below): rows are
independent, so no cross-host coordination is needed beyond the gang-
scheduled collectives XLA emits.  Failure recovery is restart + cursor
resume (see `streaming.py`) — SPMD jobs are gang-scheduled, so a lost host
means the job restarts from checkpoints, exactly like the reference's
lineage recomputation but with explicit cursors.
"""

from __future__ import annotations

from typing import Optional, Tuple

__all__ = ["initialize", "is_multi_process", "host_row_range"]


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host runtime; safe no-op for single-process runs.

    With no arguments, relies on the TPU environment's auto-detection
    (GKE/TPU-VM metadata).  Explicit arguments support manual bring-up.
    Idempotent: repeated calls after a successful initialize are ignored.
    """
    import jax

    if getattr(initialize, "_done", False):
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        initialize._done = True
    except (ValueError, RuntimeError) as e:
        # single-process environment (no coordinator configured): fine —
        # jax.devices() already covers the local chips
        if num_processes not in (None, 1):
            raise
        initialize._done = True
        import logging

        logging.getLogger("randomprojection_tpu").debug(
            "jax.distributed.initialize skipped: %s", e
        )


def is_multi_process() -> bool:
    import jax

    return jax.process_count() > 1


def host_row_range(n_rows: int) -> Tuple[int, int]:
    """This host's contiguous row slice ``[lo, hi)`` of a global stream.

    Rows are independent in X·Rᵀ, so the natural multi-host decomposition
    is block-by-process (the Spark partition map's equivalent).  The split
    is balanced to within one row and every process computes it without
    communication.
    """
    import jax

    p, n_p = jax.process_index(), jax.process_count()
    base, extra = divmod(n_rows, n_p)
    lo = p * base + min(p, extra)
    hi = lo + base + (1 if p < extra else 0)
    return lo, hi
