"""Sharded projection execution: DP over rows, optional TP over features.

The compute is one contraction, ``Y[n,k] = Σ_d X[n,d]·R[k,d]``.  Shardings:

- **DP (default)**: X row-sharded over ``'data'``, R replicated, Y
  row-sharded.  Zero collectives in steady state — the Spark map-over-
  partitions equivalent (SURVEY.md §3.3).
- **DP×TP**: X sharded ``(data, feature)``, R column-sharded over
  ``'feature'``; each chip computes a partial ``X_shard @ R_shardᵀ`` and a
  single ``psum`` over ``'feature'`` completes the contraction.  This is
  the contraction-dim sharding used when ``d`` is too large for one chip's
  HBM slice (configs 3–4, SURVEY.md §1) — ring-attention-style blockwise
  accumulation without attention (SURVEY.md §6 "long-context").

PRNG under sharding: ``jax.random`` is counter-based (threefry) and JAX's
partitionable-PRNG mode makes generation sharding-invariant, so
``materialize_sharded`` produces bit-identical values to single-device
materialization while each chip only ever touches its own shard.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from randomprojection_tpu.ops.precision import default_matmul_precision
from randomprojection_tpu.parallel.mesh import DATA_AXIS, FEATURE_AXIS

__all__ = [
    "replicated",
    "row_sharded",
    "feature_sharded",
    "materialize_sharded",
    "make_sharded_projector",
    "make_sharded_split2_projector",
    "row_bucket",
    "slice_rows_sharded",
    "token_balanced_bounds",
]


def row_bucket(n: int, mesh=None, data_axis: str = DATA_AXIS) -> int:
    """Pad target for a batch of ``n`` rows.

    Buckets at the quarter-points of each power-of-two octave
    (``{1, 1.25, 1.5, 1.75, 2}·2^k``): recompiles stay O(log n) over a
    stream of ragged shapes while pad waste is capped at 25% — a bare
    next-power-of-two bucket wastes up to 100% (a 65537-row batch would
    compute 131072 rows).  The result is a multiple of 8 (f32 sublane
    tiling); on a mesh it is additionally a multiple of 8×(data-axis
    size), so shard_map divides evenly AND every per-shard row count
    keeps the sublane tiling.
    """
    pow2 = max(8, 1 << (n - 1).bit_length())
    if pow2 < 64:
        pad_to = pow2  # tiny batches: waste is noise, keep one program
    else:
        step = pow2 // 8  # multiple of 8 whenever pow2 >= 64
        for frac in (4, 5, 6, 7, 8):
            pad_to = step * frac
            if pad_to >= n:
                break
    if mesh is not None:
        pad_to += -pad_to % (8 * mesh.shape[data_axis])
    return pad_to


def token_balanced_bounds(indptr, p: int) -> np.ndarray:
    """Row cut points ``(p + 1,)`` int64 splitting one CSR batch into
    ``p`` contiguous row ranges whose TOKEN counts balance (ISSUE 8
    satellite — VERDICT weak #3, carried since r3).

    The balanced split is already implicit in ``indptr``: cut ``s`` is
    the first row whose token prefix reaches ``s·nnz/p``
    (``searchsorted`` on the indptr), so every shard's token count is
    within one row's tokens of ``nnz/p`` — against the previous
    equal-ROW split, whose worst shard set the padded token width for
    every shard.  Cuts are row-aligned (each row's tokens stay whole,
    so per-shard scatter accumulators need no collectives) and
    monotone; empty ranges are legal for degenerate batches.
    """
    if p < 1:
        raise ValueError(f"p must be >= 1, got {p}")
    indptr = np.asarray(indptr, dtype=np.int64)
    n = indptr.shape[0] - 1
    total = int(indptr[-1])
    targets = (np.arange(1, p, dtype=np.int64) * total) // p
    cuts = np.searchsorted(indptr, targets, side="left")
    bounds = np.concatenate(
        [[0], np.minimum(cuts, n), [n]]
    ).astype(np.int64)
    return np.maximum.accumulate(bounds)


def slice_rows_sharded(y, n: int, mesh, data_axis: str = DATA_AXIS,
                       cache: Optional[dict] = None):
    """Drop pad rows from a (possibly row-sharded) batch result.

    Off-mesh this is a plain slice.  On a mesh, eager slicing of a sharded
    array hits sharding-in-types gather rules, so: a mesh-divisible ``n``
    slices under jit with an explicit row-sharded out_sharding (cached per
    row count in ``cache`` when given); a ragged ``n`` — only ever a
    stream's last batch — gathers to a replicated result, because XLA's
    partitioner cannot slice a sharded dim to a non-divisible size.
    """
    if y.shape[0] == n:
        return y
    if mesh is None:
        return y[:n]
    if n % mesh.shape[data_axis]:
        return y.at[:n].get(out_sharding=NamedSharding(mesh, P()))
    fn = cache.get(n) if cache is not None else None
    if fn is None:
        out_sh = NamedSharding(mesh, P(data_axis, None))
        fn = jax.jit(lambda a: a[:n], out_shardings=out_sh)
        if cache is not None:
            cache[n] = fn
    return fn(y)


def replicated(mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def row_sharded(mesh, data_axis: str = DATA_AXIS) -> NamedSharding:
    return NamedSharding(mesh, P(data_axis, None))


def feature_sharded(mesh, feature_axis: str = FEATURE_AXIS) -> NamedSharding:
    """R column-sharded: each chip holds R[:, d_shard]."""
    return NamedSharding(mesh, P(None, feature_axis))


def materialize_sharded(
    matrix_fn,
    key,
    n_components: int,
    n_features: int,
    mesh,
    *,
    feature_axis: Optional[str] = None,
    dtype=jnp.float32,
):
    """Materialize R directly into its mesh layout.

    ``matrix_fn`` is one of ``ops.kernels.{gaussian,sparse,rademacher}_matrix``
    (already jitted).  With ``feature_axis`` set, XLA partitions the
    counter-based generation so each chip computes only its column shard —
    values identical to the unsharded matrix.
    """
    sharding = (
        feature_sharded(mesh, feature_axis) if feature_axis else replicated(mesh)
    )
    fn = jax.jit(
        lambda k: matrix_fn(k, n_components, n_features, dtype),
        out_shardings=sharding,
    )
    return fn(key)


def make_sharded_projector(
    mesh,
    *,
    data_axis: str = DATA_AXIS,
    feature_axis: Optional[str] = None,
    accum_dtype=jnp.float32,
    precision: Optional[str] = None,
):
    """Build the jitted sharded transform ``(X, R) -> X @ R.T``.

    Returns a function expecting X laid out ``P(data, feature)`` (or
    ``P(data, None)`` without TP) and R laid out ``P(None, feature)`` /
    replicated.  Inputs not already on the mesh are placed by the ``jit``
    in/out shardings.
    """
    if feature_axis is None:
        in_specs = (P(data_axis, None), P())
        out_specs = P(data_axis, None)

        def local(x, r):
            prec = precision or default_matmul_precision(x.dtype)
            y = jnp.einsum(
                "nd,kd->nk", x, r,
                preferred_element_type=accum_dtype, precision=prec,
            )
            return y.astype(x.dtype)

    else:
        in_specs = (P(data_axis, feature_axis), P(None, feature_axis))
        out_specs = P(data_axis, None)

        def local(x, r):
            prec = precision or default_matmul_precision(x.dtype)
            partial = jnp.einsum(
                "nd,kd->nk", x, r,
                preferred_element_type=accum_dtype, precision=prec,
            )
            # one ICI all-reduce completes the contraction over sharded d
            y = jax.lax.psum(partial, feature_axis)
            return y.astype(x.dtype)

    sharded = jax.shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    return jax.jit(sharded)


def make_sharded_split2_projector(
    mesh,
    *,
    data_axis: str = DATA_AXIS,
    feature_axis: str = FEATURE_AXIS,
):
    """Split-precision (split2) transform under DP×TP.

    The contraction distributes over the feature shards exactly as in
    ``make_sharded_projector``: each chip splits its own ``X[:, d_shard]``
    into hi/lo bf16 halves, runs two partial mask einsums, and ONE ``psum``
    over ``feature_axis`` completes both halves at once (the two partial
    sums are added before the collective, so TP costs no extra
    communication vs the dense path).  The common ``·scale`` is applied
    after the psum.  Expects X laid out ``P(data, feature)``, the unscaled
    ±1/0 bf16 mask ``P(None, feature)``; returns Y ``P(data, None)`` in
    f32-grade accuracy (see ``ops/split_matmul.py``).
    """
    from randomprojection_tpu.ops.split_matmul import split_f32_to_bf16_pair

    in_specs = (P(data_axis, feature_axis), P(None, feature_axis), P())
    out_specs = P(data_axis, None)

    def local(x, mask, scale):
        x_hi, x_lo = split_f32_to_bf16_pair(x.astype(jnp.float32))
        a = jnp.einsum(
            "nd,kd->nk", x_hi, mask, preferred_element_type=jnp.float32
        )
        b = jnp.einsum(
            "nd,kd->nk", x_lo, mask, preferred_element_type=jnp.float32
        )
        y = jax.lax.psum(a + b, feature_axis)
        return (y * scale).astype(x.dtype)

    sharded = jax.shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    return jax.jit(sharded)
