"""Device-mesh construction helpers.

TPU mapping (SURVEY.md §3.4): multi-host bring-up is
``jax.distributed.initialize()`` + one process per host; the mesh spans all
chips and XLA routes collectives over ICI within a slice and DCN across
slices.  Mesh axes used by this framework:

- ``'data'``   — row parallelism (the reference's Spark partition map)
- ``'feature'`` — optional contraction-dim (d) sharding with psum (TP)
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax

__all__ = ["make_mesh", "default_mesh", "mesh_shape_for"]

DATA_AXIS = "data"
FEATURE_AXIS = "feature"


def make_mesh(axis_sizes: dict, devices: Optional[Sequence] = None):
    """Build a ``jax.sharding.Mesh`` from ``{axis_name: size}``.

    Sizes must multiply to the device count (pass ``devices`` to use a
    subset).  Axis order follows dict order; put the fastest-varying
    (innermost-ICI) axis last.
    """
    devices = list(devices if devices is not None else jax.devices())
    total = 1
    for s in axis_sizes.values():
        total *= s
    if total != len(devices):
        raise ValueError(
            f"Mesh axes {axis_sizes} require {total} devices, have {len(devices)}"
        )
    return jax.make_mesh(
        tuple(axis_sizes.values()), tuple(axis_sizes.keys()), devices=devices
    )


def mesh_shape_for(n_devices: int, feature_shards: int = 1) -> dict:
    """Default mesh factorization: all devices on 'data' unless TP requested."""
    if n_devices % feature_shards:
        raise ValueError(
            f"feature_shards={feature_shards} must divide n_devices={n_devices}"
        )
    shape = {DATA_AXIS: n_devices // feature_shards}
    if feature_shards > 1:
        shape[FEATURE_AXIS] = feature_shards
    return shape


def default_mesh(n_devices: Optional[int] = None, feature_shards: int = 1):
    """A ready mesh over the first ``n_devices`` devices (default: all)."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return make_mesh(mesh_shape_for(len(devices), feature_shards), devices)
