"""Parallelism layer: mesh management and sharded execution (SURVEY.md §3.3).

The reference's only strategy is data parallelism over rows (Spark map over
partitions).  Here that is the 1-D ``'data'`` mesh axis; an optional
``'feature'`` axis adds tensor-parallel sharding of the contraction
dimension ``d`` with a ``psum`` reduce — the structural analog of
sequence/context parallelism for this workload (SURVEY.md §6
"long-context").  All communication is XLA collectives over ICI/DCN; there
is no hand-written networking (SURVEY.md §3.4).
"""

from randomprojection_tpu.parallel import distributed
from randomprojection_tpu.parallel.mesh import (
    default_mesh,
    make_mesh,
    mesh_shape_for,
)
from randomprojection_tpu.parallel.sharded import (
    make_sharded_projector,
    materialize_sharded,
    replicated,
    row_sharded,
)

__all__ = [
    "distributed",
    "default_mesh",
    "make_mesh",
    "mesh_shape_for",
    "make_sharded_projector",
    "materialize_sharded",
    "replicated",
    "row_sharded",
]
