"""Benchmark core: the north-star metric, parameterized by preset.

See the method discussion in the repo-root ``bench.py`` docstring (which
wraps this module with the driver's default preset).  Everything here is
data-resident device throughput; streamed (PCIe-bound) throughput is a
separate number reported by ``cli stream-bench`` (SURVEY.md §7: the two
must not be conflated).
"""

from __future__ import annotations

import glob
import json
import os
import re
import time
from typing import Optional

import numpy as np

V5E_PEAK_TFLOPS = 197.0
DISTORTION_BUDGET = 1e-3

# compact-summary line: marker key + schema version, and the byte budget
# the driver's tail capture is guaranteed to keep intact (the driver keeps
# the TAIL of stdout, so the LAST line survives any truncation — r5 lost
# the flagship headline because the one bench line was multi-KB and was
# truncated from the front)
COMPACT_MARKER = "rp_bench_compact"
COMPACT_SCHEMA_VERSION = 1
COMPACT_MAX_BYTES = 2048
REGRESSION_THRESHOLD = 0.10

# top-k serving bench shapes (measure_config4_topk): the tier-1 policy
# test patches this table down to toy sizes to exercise the whole
# serving-bench composition without device-scale work
TOPK_BENCH_SHAPES = {
    "full": dict(n_idx=1 << 24, q_tile=2048, clients=16, req_rows=128,
                 reqs_per_client=4, max_batch=8192, shards=8, replicas=1),
    "smoke": dict(n_idx=1 << 18, q_tile=2048, clients=4, req_rows=64,
                  reqs_per_client=2, max_batch=1024, shards=4, replicas=2),
}

# multi-probe LSH candidate-tier bench shapes (measure_topk_lsh,
# ISSUE 15).  The workload is PLANTED neighbors — corpus rows are
# bit-flip perturbations of cluster centers, queries likewise — i.e.
# the near-duplicate-retrieval regime the sign-random-projection sketch
# exists for (uniform random codes have no meaningful neighbors: every
# distance concentrates at n_bits/2 and "recall" measures noise).
# ``cluster`` rows per center keeps the true top-``m`` inside the
# query's cluster, so recall@m is a real retrieval statistic.
LSH_BENCH_SHAPES = {
    "full": dict(n_idx=1 << 20, n_bytes=32, cluster=16, nq=256, m=10,
                 bands=8, band_bits=16, noise_bits=6,
                 probe_counts=(1, 2, 4, 8, 16), calls=3, rerank_tile=64),
    "smoke": dict(n_idx=1 << 12, n_bytes=16, cluster=16, nq=48, m=10,
                  bands=8, band_bits=16, noise_bits=4,
                  probe_counts=(1, 2), calls=1, rerank_tile=12),
}
# recall tripwire (ISSUE 15 acceptance): the committed curve must
# contain a probe setting reaching this recall@m while re-ranking less
# than this fraction of the corpus — a bucket bug that tanks recall
# fails the gate instead of shipping as "fast"
LSH_RECALL_GATE = 0.95
LSH_CANDIDATE_FRACTION_GATE = 0.10

# tiered hot/cold residency bench shapes (measure_topk_tiered, ISSUE
# 19 / r21).  The corpus is ingested in fixed-size chunks and the HBM
# budget admits ``budget_chunks`` of them — the rest serve from the
# cold tier, so the default shape runs 4x over budget.  Exact top-k
# over random codes is the right workload here: the bench measures the
# residency machinery (hot-hit fraction, cold-fetch wall/overlap,
# throughput vs resident), not retrieval quality — parity with the
# resident index is bit-exact by construction.
TIER_BENCH_SHAPES = {
    # 16 chunks, budget 4 (4x over budget): the planner's staging
    # reserve (2 x max cold chunk) still leaves a real hot set, so the
    # hot-hit fraction is a measurement, not a constant zero
    "full": dict(n_idx=1 << 20, n_bytes=32, nq=256, m=10, calls=3,
                 chunk_rows=1 << 16, budget_chunks=4, q_tile=256),
    "smoke": dict(n_idx=1 << 12, n_bytes=16, nq=48, m=10, calls=1,
                  chunk_rows=1 << 10, budget_chunks=1, q_tile=48),
}

PRESETS = {
    # batch rows, scan steps per call, timed calls.  Steps-per-call is high
    # because a dispatch costs ~100-133 ms on the virtualized dev chip
    # (BASELINE.md round-3 finding): work per dispatch must dwarf the
    # dispatch overhead or the bench measures the tunnel, not the chip.
    # r5 trace: at 128 steps ~13% of wall was still call-boundary gaps;
    # 256 steps measured +2.5% with the 3-call anti-cache chain intact.
    "full": dict(batch=131072, steps=256, calls=3),  # 33.6M rows per call
    "smoke": dict(batch=8192, steps=2, calls=2),
}


def pdist2(a):
    sq = (a * a).sum(1)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (a @ a.T)
    iu = np.triu_indices(a.shape[0], k=1)
    return np.maximum(d2[iu], 1e-30)


def _mode_project_fn(jax, jnp, name, scale, *, k=None, density=None,
                     lazy_seed=0, dma=None):
    """(project(x, r), input_dtype, r_transform) for one MXU mode.

    The ``lazy*`` modes run the fused Pallas kernel
    (``ops/pallas_kernels.py``): ``r`` is ignored — the mask is regenerated
    block-by-block in VMEM, so R never exists in HBM.  The caller passes the
    matching materialized matrix (``pallas_sparse_matrix``) as ``R_f32`` so
    the distortion reference contracts the identical matrix.
    """
    if name in ("lazy", "lazy_split2", "lazy_bf16", "lazy_f32_bf16data"):
        from randomprojection_tpu.ops.pallas_kernels import fused_sparse_project

        # lazy_f32_bf16data is the VERDICT r5 weak-#6 isolation: the
        # SAME f32 kernel as 'lazy', fed x that was quantized to bf16
        # and upcast back to f32 (see measure_mode) — if lazy_bf16's
        # rate advantage were about data content rather than halved x
        # HBM traffic, this mode would show it; matching 'lazy' instead
        # certifies lazy_bf16 as T1-within-T2-for-bf16-data
        mxu_mode = {"lazy": "f32", "lazy_split2": "split2",
                    "lazy_bf16": "bf16", "lazy_f32_bf16data": "f32"}[name]

        def project(x, r):  # r unused by design: zero R HBM traffic
            return fused_sparse_project(
                x, lazy_seed, k, density, mxu_mode=mxu_mode, dma=dma
            )

        in_dtype = jnp.bfloat16 if name == "lazy_bf16" else jnp.float32
        return project, in_dtype, lambda R_f32: R_f32

    if name == "bf16_split2":
        from randomprojection_tpu.ops.split_matmul import split2_project

        def project(x, r):  # r is the unscaled ±1/0 mask in bf16
            return split2_project(x, r, scale)

        def r_prep(R_f32):
            return (R_f32 / jnp.float32(scale)).astype(jnp.bfloat16)

        return project, jnp.float32, r_prep

    dtype, precision = {
        "bf16": (jnp.bfloat16, "default"),
        "f32_high": (jnp.float32, "high"),
    }[name]

    def project(x, r):
        return jnp.einsum(
            "nd,kd->nk", x, r,
            preferred_element_type=jnp.float32, precision=precision,
        )

    return project, dtype, lambda R_f32: R_f32.astype(dtype)


def measure_mode(jax, jnp, R_f32, name, scale, batch, steps, calls, d,
                 **mode_kw):
    """Time the chained-scan projection loop in one MXU mode.

    Anti-caching defenses, per SURVEY.md §7 (this environment's virtualized
    TPU has been observed serving repeated calls from a cache):

    - every timed call sees DISTINCT argument values: the call index is
      folded into the input on device (one buffer, no extra HBM);
    - a scalar carry from call ``i``'s checksum is folded into call
      ``i+1``'s input, serializing the calls;
    - within a call, scan steps chain through the input (defeats DCE).

    The caller cross-checks the resulting rate against the hardware peak
    per mode (``implied_tflops`` / ``timing_suspect``).
    """
    project, in_dtype, r_prep = _mode_project_fn(jax, jnp, name, scale,
                                                 **mode_kw)
    r = r_prep(R_f32)
    x0 = jax.random.normal(jax.random.key(1), (batch, d), dtype=in_dtype)
    if name == "lazy_f32_bf16data":
        # quantize→upcast: bf16-grade VALUES in an f32 container (full
        # f32 x HBM traffic — the data-precision isolation, not the
        # bandwidth win)
        x0 = x0.astype(jnp.bfloat16).astype(jnp.float32)
    rate, elapsed, checksum = _scan_harness(
        jax, jnp, lambda x: project(x, r), x0, steps, calls
    )
    return {
        "rows_per_s": rate,
        "elapsed_s": elapsed,
        "rows_timed": calls * steps * batch,
        "checksum": checksum,
    }


def select_headline(results: dict, budget: float = DISTORTION_BUDGET) -> str:
    """Fastest mode that (a) meets the distortion budget and (b) has a
    believable timing.  A ``timing_suspect`` mode is never preferred over
    any believable one; in the degenerate case where EVERY mode is suspect
    the most accurate one is reported — with its flag preserved in the
    JSON, so the whole run is self-describing as untrustworthy."""
    eligible = [
        n for n, r in results.items()
        if r["distortion"] <= budget and not r["timing_suspect"]
    ]
    if not eligible:
        non_suspect = [n for n, r in results.items() if not r["timing_suspect"]]
        pool = non_suspect or list(results)
        eligible = [min(pool, key=lambda n: results[n]["distortion"])]
    return max(eligible, key=lambda n: results[n]["rows_per_s"])


def detect_pass_invariance(results: dict, mxu_passes: dict) -> bool:
    """Virtualization tripwire (BASELINE.md round-3 finding): modes that
    execute 1× vs 2-3× the MXU work must not record near-identical elapsed
    times — if they do, the measured quantity is dispatch overhead or a
    call cache, not the arithmetic.  Informational: does not change the
    headline, but flags the whole run for the reader."""
    els = [results[n]["elapsed_s"] for n in results]
    passes = [mxu_passes[n] for n in results]
    return bool(
        len(els) >= 2
        and max(passes) >= 2 * min(passes)
        and max(els) > 0
        and (max(els) - min(els)) / max(els) < 0.15
    )


def measure_distortion(jax, jnp, R_f32, x_cpu, name, scale, **mode_kw):
    """Max relative pairwise-distance error vs CPU f64, same R."""
    project, in_dtype, r_prep = _mode_project_fn(jax, jnp, name, scale,
                                                 **mode_kw)
    xs = x_cpu[:1024]
    if name == "lazy_f32_bf16data":
        # the reference sees the SAME quantized values, so the reported
        # distortion isolates kernel arithmetic from input quantization
        xs = np.asarray(
            jnp.asarray(xs, jnp.bfloat16).astype(jnp.float32)
        ).astype(np.float64)
    y_dev = np.asarray(
        jax.jit(project)(jnp.asarray(xs, dtype=in_dtype), r_prep(R_f32))
    ).astype(np.float64)
    y_ref = xs.astype(np.float64) @ np.asarray(R_f32, dtype=np.float64).T
    return float(np.max(np.abs(pdist2(y_dev) / pdist2(y_ref) - 1.0)))


def _host_best_of(sample, trials: int = 3, max_trials: int = 7):
    """Guard for host-side wall-clock samples (VERDICT r3 missing #3: a
    single 0.3 s sample once under-recorded ingest throughput 11×, because
    an active in-process jax runtime steals the one CPU core in bursts).
    Runs ``sample() -> rate`` ``trials`` times and reports the best (the
    least-interfered run is closest to the machine's capability), the
    max/min spread, and a ``host_suspect`` flag when the spread exceeds 2×
    — the round-over-round comparability signal.

    Escalation (VERDICT r4 #5): while the flag trips, keep sampling up to
    ``max_trials`` and judge the spread over the best ``trials`` samples —
    a couple of interference-polluted runs then stop condemning the record
    (the polluted minima fall outside the judged window), and a genuinely
    unstable box stays flagged after ``max_trials``."""
    rates = [float(sample()) for _ in range(trials)]

    def spread_of(rs):
        top = sorted(rs, reverse=True)[:trials]
        return max(top) / max(min(top), 1e-9)

    while spread_of(rates) > 2.0 and len(rates) < max_trials:
        rates.append(float(sample()))
    spread = spread_of(rates)
    return {
        "best": round(max(rates), 1),
        "trials": len(rates),
        "spread": round(spread, 2),
        "host_suspect": bool(spread > 2.0),
    }


def measure_config5(n_docs: int = 65536, tok_per_doc: int = 100,
                    k: int = 256) -> dict:
    """Config-5 throughputs (SURVEY.md §1: streaming TF-IDF hashing), all
    at the stated ``hash_space = 2^20`` — the sketch runs ON DEVICE via the
    CSR gather/scatter path (``models/sketch.py::_transform_csr_jax``; no
    one-hot can exist at d=2^20).

    - ``ingest_tokens_per_s``: host feature-hashing of a flat token column
      (C++ murmur3, one FFI call per batch), best-of-N with escalation.
    - ``device_sketch_docs_per_s``: the device hot loop alone, tokens
      resident, timed as honest PER-BATCH dispatches (the real streaming
      pattern; the scan harness serializes TPU gather/scatter lowering
      ~500× and was r4's 303k-docs/s artifact).  The shipped doc-major
      compare-reduce kernel is reported; ``sketch_bakeoff_docs_per_s``
      records it against the flat gather+scatter and the packed-table
      gather floor.  Cross-checked against the byte roofline
      (``sketch_hbm_cap_docs_per_s``).
    - ``end_to_end_docs_per_s``: THE pipeline number — raw tokens →
      murmur3 CSR → device sketch through ``TokenSource`` +
      ``StagedIngestSource`` + ``transform_stream``, wall-clock including
      all hashing and transfers.  The r9 staged pipeline: a POOL of hash
      workers produces disjoint batches (bit-identical to serial),
      reassembled in row order through a dedicated prep/H2D uploader.
      The run is traced (scoped telemetry sink) and the doctor's
      critical-path attribution rides along as
      ``pipeline_stage_pct``/``pipeline_bubble_pct``.
      ``end_to_end_prefetch_docs_per_s`` keeps the r6 single-worker
      pipeline and ``end_to_end_serial_docs_per_s`` the pre-r6
      synchronous loop, for round-over-round comparability.
    """
    import os

    import jax
    import jax.numpy as jnp

    from randomprojection_tpu.models.sketch import CountSketch
    from randomprojection_tpu.ops.hashing import FeatureHasher, hash_tokens
    from randomprojection_tpu.streaming import TokenSource

    d = 1 << 20
    n_tokens = n_docs * tok_per_doc
    rng = np.random.default_rng(0)
    words = np.asarray([f"tok{i}" for i in range(50_000)])
    toks = words[rng.integers(0, len(words), size=n_tokens)]
    fh = FeatureHasher(n_features=d, input_type="string", dtype=np.float32)
    fh.transform_tokens(toks[:1000])  # warm: builds the .so on first use

    def ingest_sample():
        t0 = time.perf_counter()
        fh.transform_tokens(
            toks, np.arange(0, n_tokens + 1, tok_per_doc, dtype=np.int64)
        )
        return n_tokens / (time.perf_counter() - t0)

    # serial hashing pinned for run-to-run comparability on this 1-core box
    # (the C++ kernel reads the env per call); best-of-N guards against the
    # in-process jax runtime stealing the core mid-sample
    prev = os.environ.get("RP_HASH_THREADS")
    os.environ["RP_HASH_THREADS"] = "1"
    try:
        ingest_stats = _host_best_of(ingest_sample)

        # --- device hot loop, tokens resident, per-batch dispatches ---
        # r5 instrument finding (the r3-fold story repeating): inside a
        # lax.scan EVERY gather/scatter kernel variant collapses to ~0.3M
        # docs/s on this box (the loop forces a serialized lowering) while
        # honest standalone dispatches differ 4x between kernels — and
        # real streaming IS one dispatch per batch.  So this times
        # per-batch calls: distinct values every call (call index folded
        # on device), calls serialized on a carry scalar, every output
        # forced via the carry.
        from randomprojection_tpu.models.sketch import (
            _docmajor_chunk,
            _docmajor_kernel,
        )
        from randomprojection_tpu.parallel.sharded import row_bucket

        cs = CountSketch(k, random_state=0, backend="jax").fit_schema(
            n_docs, d, np.float32
        )
        hs = cs._device_packed_table()
        h_dev, s_dev = cs._device_tables()
        rows = jnp.asarray(
            np.repeat(np.arange(n_docs, dtype=np.int32), tok_per_doc)
        )
        idx0, _ = hash_tokens(toks, d)
        # pad to the SAME bucketed doc-major layout the shipped kernel uses
        # (_transform_csr_docmajor pads rows to t_pad; pad tokens value 0)
        t_pad = row_bucket(tok_per_doc)
        idxm = jnp.asarray(
            np.pad(
                idx0.reshape(n_docs, tok_per_doc).astype(np.int32),
                ((0, 0), (0, t_pad - tok_per_doc)),
            )
        )
        idx_flat = jnp.asarray(idx0)
        vals0 = jnp.asarray(
            np.pad(
                rng.standard_normal(n_tokens, dtype=np.float32).reshape(
                    n_docs, tok_per_doc
                ),
                ((0, 0), (0, t_pad - tok_per_doc)),
            )
        )
        dm_kernel = _docmajor_kernel(
            k, t_pad, _docmajor_chunk(n_docs, t_pad, k)
        )

        # the shipped doc-major kernel itself (shared builder — the bench
        # cannot drift from _transform_csr_docmajor), the pre-r5 flat
        # kernel, and the table-lookup floor every d=2^20 kernel must pay
        def dm_body(v, z, idxm, hs):
            return dm_kernel(idxm + z, v, hs)

        def flat_body(v, z, idx_flat, rows, h_dev, s_dev):
            flat = (rows + z) * k + h_dev[idx_flat + z]
            y = jnp.zeros((n_docs * k,), jnp.float32)
            return y.at[flat].add(
                v[:, :tok_per_doc].reshape(-1)
                * s_dev[idx_flat + z].astype(jnp.float32)
            ).reshape(n_docs, k)

        def gather_floor_body(v, z, idxm, hs):
            return v * (hs[idxm + z] & 1).astype(jnp.float32)

        def _per_batch_rate(body, operands, calls=5):
            # honest per-batch dispatches: token/table operands are passed
            # as jit ARGUMENTS (closure constants could be constant-folded
            # — the gather would then be compiled away) and additionally
            # offset by a data-dependent zero; values are distinct per
            # call and calls chain on a carry scalar
            @jax.jit
            def one(v, carry, ci, *ops):
                z = (carry * 1e-30).astype(jnp.int32)
                v = v + (carry * 1e-24 + ci * 1e-6).astype(v.dtype)
                return body(v, z, *ops).sum() * jnp.float32(1e-30)

            c = one(vals0, jnp.float32(0), jnp.float32(-1), *operands)
            c.block_until_ready()
            t0 = time.perf_counter()
            for i in range(calls):
                c = one(vals0, c, jnp.float32(i), *operands)
            c.block_until_ready()
            return calls * n_docs / (time.perf_counter() - t0)

        docs_per_s = _per_batch_rate(dm_body, (idxm, hs))
        flat_docs_per_s = _per_batch_rate(
            flat_body, (idx_flat, rows, h_dev, s_dev)
        )
        gather_floor = _per_batch_rate(gather_floor_body, (idxm, hs))
        # per-batch byte floor: read idx (4B/token) + packed-table gather
        # (4B/token random) + vals (4B/token) + write y (4B/element)
        step_bytes = n_tokens * (4 + 4 + 4) + n_docs * k * 4
        cap_docs = 819e9 / (step_bytes / n_docs)

        # --- the ONE pipeline number: tokens -> CSR -> device sketch ----
        def read_tokens(lo, hi):
            t = toks[lo * tok_per_doc : hi * tok_per_doc]
            return t, np.arange(
                0, (hi - lo) * tok_per_doc + 1, tok_per_doc, dtype=np.int64
            )

        source = TokenSource(read_tokens, n_docs, fh, batch_rows=8192)
        est = CountSketch(k, random_state=0, backend="jax").fit_source(source)
        for _, _y in est.transform_stream(source):  # warm compile, 1 batch
            break
        # serial reference: the pre-r6 synchronous consume loop (hash, H2D,
        # dispatch, d2h all on one thread, hashing pinned serial by the env
        # above) — kept for round-over-round comparability
        t0 = time.perf_counter()
        n_done = 0
        for _lo, y in est.transform_stream(source):
            n_done += y.shape[0]
        e2e_serial = n_done / (time.perf_counter() - t0)

        # pipelined path (r6): PrefetchSource runs hashing + early H2D on
        # a worker thread (hash multi-threaded via the C++ kernel —
        # bit-identical output), the consumer only dispatches and fetches.
        # Same TokenSource, same batch size, same per-batch-dispatch
        # methodology — only the serialization changes.
        from randomprojection_tpu.streaming import PrefetchSource
        from randomprojection_tpu.utils.observability import StreamStats

        hash_threads = max(os.cpu_count() or 1, 1)
        prefetch_depth = 3
        stats = StreamStats()
        psource = PrefetchSource(
            TokenSource(
                read_tokens, n_docs, fh, batch_rows=8192,
                hash_threads=hash_threads, stats=stats,
            ),
            depth=prefetch_depth, prepare=est.prepare_batch, stats=stats,
        )
        t0 = time.perf_counter()
        n_done = 0
        for _lo, y in est.transform_stream(psource, stats=stats):
            n_done += y.shape[0]
        e2e_prefetch = n_done / (time.perf_counter() - t0)

        # staged multi-worker ingest (r9): a POOL of hash workers
        # (disjoint batches, row-order reassembly — bit-identical to
        # serial) feeding a dedicated prep/H2D uploader stage.  THE
        # pipeline number.  Each worker hashes serially (hash_threads=1);
        # the pool supplies the parallelism — same methodology otherwise.
        # Telemetry is scoped to a temp file for this run so the
        # doctor's critical-path attribution (per-stage walls + bubble
        # fraction) rides along in the record as evidence.
        import tempfile

        from randomprojection_tpu.streaming import StagedIngestSource
        from randomprojection_tpu.utils import telemetry
        from randomprojection_tpu.utils.trace_report import build_report

        ingest_workers = max(2, min(os.cpu_count() or 2, 8))
        staged_stats = StreamStats()
        ssource = StagedIngestSource(
            TokenSource(
                read_tokens, n_docs, fh, batch_rows=8192,
                hash_threads=1, stats=staged_stats,
            ),
            workers=ingest_workers, depth=prefetch_depth,
            prepare=est.prepare_batch, stats=staged_stats,
        )
        prev_sink = telemetry.active_path()
        fd, trace_path = tempfile.mkstemp(suffix=".jsonl")
        os.close(fd)
        try:
            telemetry.configure(trace_path)
            try:
                t0 = time.perf_counter()
                n_done = 0
                for _lo, y in est.transform_stream(
                    ssource, stats=staged_stats
                ):
                    n_done += y.shape[0]
                e2e = n_done / (time.perf_counter() - t0)
            finally:
                telemetry.shutdown()
                if prev_sink is not None:
                    telemetry.configure(prev_sink)
            report = build_report(trace_path)
        finally:
            os.unlink(trace_path)
        staged_bubble_pct = report["batch"]["bubble"]["pct"]
        staged_stage_pct = {
            k: v["pct"] for k, v in report["batch"]["stages"].items()
        }
        # no overlapped pipeline can outrun its slowest stage: flag a
        # cache-served sample that beats the device sketch measured in the
        # SAME run, or the parallel-hash ceiling
        # the C++ kernel clamps effective workers to n_tokens >> 16
        # (native/murmur3.cpp::hash_worker_count), so a many-core host's
        # os.cpu_count() must not inflate the ceiling ~5x and blind the
        # suspect flag to cache-served samples
        batch_tokens = 8192 * tok_per_doc
        eff_hash_threads = min(hash_threads, max(1, batch_tokens >> 16))
        prefetch_ceiling = min(
            docs_per_s,
            ingest_stats["best"] * eff_hash_threads / tok_per_doc,
        )
        prefetch_suspect = bool(e2e_prefetch > 1.2 * prefetch_ceiling)
        # staged pool: each worker hashes serially, so the hash ceiling
        # scales by the CORE-limited worker count, not the pool size
        eff_workers = max(1, min(ingest_workers, os.cpu_count() or 1))
        staged_ceiling = min(
            docs_per_s, ingest_stats["best"] * eff_workers / tok_per_doc
        )
        pipe_suspect = bool(e2e > 1.2 * staged_ceiling)
        # the serial loop is hash-pinned to 1 thread and fully
        # serialized, so it cannot outrun EITHER of its stages — its own
        # independent suspect flag (the regression tripwire gates the
        # serial rate on this, not on the pipelined run's flag)
        serial_ceiling = min(docs_per_s, ingest_stats["best"] / tok_per_doc)
        serial_suspect = bool(e2e_serial > 1.2 * serial_ceiling)
    finally:
        if prev is None:
            os.environ.pop("RP_HASH_THREADS", None)
        else:
            os.environ["RP_HASH_THREADS"] = prev

    return {
        "ingest_tokens_per_s": ingest_stats["best"],
        "ingest_trial_spread": ingest_stats["spread"],
        "ingest_trials": ingest_stats["trials"],
        "ingest_host_suspect": ingest_stats["host_suspect"],
        "ingest_hash_threads": 1,
        "device_sketch_docs_per_s": round(docs_per_s, 1),
        "sketch_hbm_cap_docs_per_s": round(cap_docs, 1),
        # suspect when past the byte roofline OR materially past the
        # packed-table gather floor measured in the SAME run — no real
        # d=2^20 kernel can beat the table lookup it contains, so a
        # cache-served sample (observed on this box at ~100x) trips this
        # even though it sits far below the byte roofline
        "sketch_timing_suspect": bool(
            docs_per_s > 2 * cap_docs or docs_per_s > 1.5 * gather_floor
        ),
        "sketch_bakeoff_docs_per_s": {
            "docmajor_compare_reduce": round(docs_per_s, 1),
            "flat_gather_scatter": round(flat_docs_per_s, 1),
            "packed_gather_floor": round(gather_floor, 1),
        },
        "sketch_instrument": "per_batch_chained",
        "end_to_end_docs_per_s": round(e2e, 1),
        "end_to_end_prefetch_docs_per_s": round(e2e_prefetch, 1),
        "end_to_end_serial_docs_per_s": round(e2e_serial, 1),
        "serial_timing_suspect": serial_suspect,
        "prefetch_timing_suspect": prefetch_suspect,
        "ingest_workers": ingest_workers,
        "pipeline_overlap_ratio": round(staged_stats.overlap_ratio(), 3),
        "pipeline_stage_wall_s": {
            name: round(wall, 4)
            for name, wall in sorted(staged_stats.stage_wall.items())
        },
        # the doctor's critical-path attribution of the staged run: every
        # instant of batch wall → exactly one stage or the bubble (the
        # removed-bubble evidence the ISSUE asks the record to carry)
        "pipeline_stage_pct": staged_stage_pct,
        "pipeline_bubble_pct": staged_bubble_pct,
        "pipeline_queue_depth_max": staged_stats.queue_depth_max,
        # the staged run pins ONE hash thread per pool worker; the r6
        # prefetch run's multi-threaded hasher count is recorded under
        # its own key so neither methodology claims the other's walls
        "pipeline_hash_threads": 1,
        "prefetch_hash_threads": hash_threads,
        "pipeline_prefetch_batches": prefetch_depth,
        "pipeline_timing_suspect": pipe_suspect,
        "tokens_per_doc": tok_per_doc,
        "hash_space": d,
        "sketch_k": k,
        "countsketch_kernel": "csr_docmajor_compare_reduce",
    }


def harness_fold_cols(d: int) -> int:
    """Columns mutated by the per-step fold: ``d/32``, at least 64."""
    return max(64, d // 32)


def harness_hbm_cap_rows_per_s(d: int, k: int, in_itemsize: int = 4) -> float:
    """The harness's own HBM ceiling at 819 GB/s (v5e spec): per step the
    kernel reads x once, writes y, and the fold reads+writes ``fold_cols``
    columns.  A measured rate can approach but not exceed this — report it
    next to every mode so the reader can tell "kernel slow" from "harness
    at its own roofline"."""
    bytes_per_row = (
        d * in_itemsize + k * 4
        + 2 * min(harness_fold_cols(d), d) * in_itemsize
    )
    return 819e9 / bytes_per_row


def _scan_harness(jax, jnp, project, x0, steps, calls):
    """The one anti-cache timing loop every throughput number goes through.

    Defenses, per SURVEY.md §7 (this environment's virtualized TPU has been
    observed serving repeated calls from a cache):

    - every timed call sees DISTINCT argument values: the call index is
      folded into the whole input on device (one buffer, no extra HBM);
    - a scalar carry from call ``i``'s checksum is folded into call
      ``i+1``'s input, serializing the calls;
    - within a call, scan steps chain through the input (defeats DCE and
      loop-invariant hoisting of the projection).

    The per-step fold mutates only the first ``harness_fold_cols(d)``
    columns (round-4 finding): scan steps inside one compiled dispatch
    cannot be cache-served — the call-level defenses carry the anti-cache
    burden — so the fold only needs to make x step-distinct.  The original
    full-buffer fold read+wrote all of x every step, tripling HBM traffic
    and capping the measurable rate at ~1/3 of the data-resident roofline
    (r3's "22% of MXU peak" was this harness artifact, not the kernel).
    A too-small fold (1 element) has been observed tripping the tunnel's
    capricious call cache; d/32 columns (≥1 MB/step at bench shapes) has
    not, and the ``timing_suspect`` >2×-peak check guards regressions.

    ``project(x) -> (n, k')`` may return any dtype (sign codes are uint8);
    the chain casts through f32.  Callers cross-check the resulting rate
    against the hardware peak (``executed_tflops`` / ``timing_suspect``).
    """
    import time as _time

    fold_cols = min(harness_fold_cols(x0.shape[1]), x0.shape[1])

    @jax.jit
    def run_steps(x, carry, call_idx):
        # fold the call index and the previous call's result into this
        # call's input: calls can neither be cached (distinct values per
        # call) nor reordered (serialized on carry)
        x = x + (carry * 1e-24 + call_idx * 1e-6).astype(x.dtype)

        def step(x, _):
            y = project(x)
            upd = x[:, :fold_cols] + (
                y[:, :1].astype(jnp.float32) * 1e-24
            ).astype(x.dtype)
            x = jax.lax.dynamic_update_slice(x, upd, (0, 0))
            return x, y[0, 0].astype(jnp.float32)

        _, ys = jax.lax.scan(step, x, None, length=steps)
        return ys.sum()

    carry = run_steps(x0, jnp.float32(0), jnp.float32(-1))  # warmup/compile
    carry.block_until_ready()
    checks = []
    t0 = _time.perf_counter()
    for c in range(calls):
        carry = run_steps(x0, carry, jnp.float32(c))
        checks.append(carry)
    carry.block_until_ready()
    elapsed = _time.perf_counter() - t0
    rows = calls * steps * x0.shape[0]
    return rows / elapsed, elapsed, float(np.asarray(jnp.stack(checks)).sum())


def measure_config1() -> dict:
    """Config-1 (BASELINE.json:7): Gaussian ``10k×512→64`` on the numpy
    reference backend — the "PR1 ref" single-host CPU workload, measured
    through the estimator path (BLAS GEMM underneath)."""
    from randomprojection_tpu import GaussianRandomProjection

    rng = np.random.default_rng(0)
    X = rng.standard_normal((10_000, 512), dtype=np.float32)
    est = GaussianRandomProjection(64, random_state=0, backend="numpy").fit(X)
    est.transform(X[:100])  # warm BLAS

    def sample():
        t0 = time.perf_counter()
        est.transform(X)
        return 10_000 / (time.perf_counter() - t0)

    stats = _host_best_of(sample)
    return {
        "workload": "gaussian 10000x512->64, numpy backend (CPU reference)",
        "rows_per_s": stats["best"],
        "trial_spread": stats["spread"],
        "trials": stats["trials"],
        "host_suspect": stats["host_suspect"],
    }


def measure_config3(preset: str = "full", dma=None, steps=None,
                    block_n=None, no_cache=False) -> dict:
    """Config-3 (BASELINE.json:9): very-sparse Li RP ``16384→512`` at
    ``density = 1/√d = 1/128``, data-resident, via the fused lazy Pallas
    kernel in split2 mode — R (512×16384 = 32 MiB f32) never exists in HBM.
    TPU-only (the in-kernel PRNG has no CPU/GPU emulation); the TP variant
    of the same kernel is exercised by the multichip dryrun.

    ``dma``/``steps``/``block_n``/``no_cache`` are the isolation levers
    ``experiments/config3_bisect.py`` sweeps to attribute the r4→r5
    3.30M→2.88M decay (ROADMAP #3 sub-item): kernel route, anti-cache
    chain length, row tile, and mask-cache machinery — defaults
    reproduce the committed methodology exactly.
    """
    import math

    import jax
    import jax.numpy as jnp

    from randomprojection_tpu.ops.pallas_kernels import (
        fused_sparse_project,
        pallas_sparse_matrix,
    )

    d, k = 16384, 512
    density = 1.0 / math.sqrt(d)
    cfg = dict(batch=16384, steps=16, calls=3) if preset == "full" else dict(
        batch=2048, steps=2, calls=2
    )
    if steps is not None:
        cfg["steps"] = int(steps)

    def project(x):
        return fused_sparse_project(x, 0, k, density, mxu_mode="split2",
                                    dma=dma, block_n=block_n,
                                    no_cache=no_cache)

    x0 = jax.random.normal(jax.random.key(3), (cfg["batch"], d), jnp.float32)
    rate, elapsed, checksum = _scan_harness(
        jax, jnp, project, x0, cfg["steps"], cfg["calls"]
    )

    # distortion vs CPU f64 contraction of the identical lazy matrix
    rng = np.random.default_rng(3)
    xs = rng.normal(size=(512, d)).astype(np.float32)
    y_dev = np.asarray(jax.jit(project)(jnp.asarray(xs))).astype(np.float64)
    R = np.asarray(pallas_sparse_matrix(0, k, d, density)).astype(np.float64)
    distortion = float(np.max(np.abs(pdist2(y_dev) / pdist2(xs @ R.T) - 1.0)))

    executed = rate * 2 * 2 * d * k / 1e12  # split2: 2 MXU passes
    return {
        "workload": f"verysparse Li density=1/{int(math.sqrt(d))} {d}->{k}, lazy_split2",
        "transform_dma": (
            "auto" if dma is None else ("dma" if dma else "single")
        ),
        "rows_per_s": round(rate, 1),
        "distortion": distortion,
        "elapsed_s": round(elapsed, 4),
        "rows_timed": cfg["batch"] * cfg["steps"] * cfg["calls"],
        "executed_tflops": round(executed, 1),
        "mxu_utilization": round(executed / V5E_PEAK_TFLOPS, 3),
        "harness_hbm_cap_rows_per_s": round(harness_hbm_cap_rows_per_s(d, k), 1),
        "timing_suspect": bool(executed > 2 * V5E_PEAK_TFLOPS),
        "checksum": checksum,
    }


def measure_config4(preset: str = "full") -> dict:
    """Config-4 (BASELINE.json:10): SimHash sign-RP ``768→256`` including
    the on-device sign+packbits cost (output is 32 uint8 bytes/row — the
    96× d2h shrink that makes the 1B-row workload feasible).  Quality is
    reported as the sign-bit mismatch rate vs the CPU f64 projection of the
    same R (boundary flips only — there is no distance distortion for
    codes).

    ``rows_per_s`` is measured THROUGH THE ESTIMATOR PATH (VERDICT r4 weak
    #3): the backend's ``transform_packed_signs`` with its full
    ``_prepare_rows`` pad/shard/slice preamble, device-resident input,
    ``materialize=False`` — the rate a user gets from
    ``SignRandomProjection``.  The raw-kernel lambda is kept as
    ``raw_kernel_rows_per_s`` (round-over-round comparability; any
    estimator-plumbing regression now shows as a gap between the two).

    ``topk_serving`` times the OTHER half of the config-4 story — serving
    queries against a resident ``SimHashIndex`` with the on-device
    ``query_topk`` (MXU ±1-matmul Hamming + scanned running top-k), whose
    d2h is O(m) per query instead of the O(n_codes) dense row.
    """
    import jax
    import jax.numpy as jnp

    from randomprojection_tpu.models.sketch import SignRandomProjection
    from randomprojection_tpu.ops import kernels

    d, k = 768, 256
    cfg = dict(batch=131072, steps=32, calls=3) if preset == "full" else dict(
        batch=8192, steps=2, calls=2
    )
    R = kernels.gaussian_matrix(jax.random.key(7), k, d, jnp.float32)

    @jax.jit
    def project(x):
        y = jnp.einsum(
            "nd,kd->nk", x, R,
            preferred_element_type=jnp.float32, precision="high",
        )
        return jnp.packbits(y > 0, axis=-1, bitorder="little")

    x0 = jax.random.normal(jax.random.key(4), (cfg["batch"], d), jnp.float32)
    raw_rate, _, _ = _scan_harness(
        jax, jnp, project, x0, cfg["steps"], cfg["calls"]
    )

    # the user-reachable path: backend.transform_packed_signs traced into
    # the same harness (device-resident input skips host validation, which
    # is outside any jit and amortized across a stream anyway)
    est = SignRandomProjection(k, random_state=7, backend="jax")
    est.fit_schema(cfg["batch"], d, dtype=np.float32)

    def project_est(x):
        return est._backend.transform_packed_signs(
            x, est._state, est.spec_, materialize=False
        )

    rate, elapsed, checksum = _scan_harness(
        jax, jnp, project_est, x0, cfg["steps"], cfg["calls"]
    )

    rng = np.random.default_rng(4)
    xs = rng.normal(size=(2048, d)).astype(np.float32)
    codes_dev = np.asarray(jax.jit(project)(jnp.asarray(xs)))
    ref = xs.astype(np.float64) @ np.asarray(R, dtype=np.float64).T
    codes_ref = np.packbits(ref > 0, axis=-1, bitorder="little")
    mismatch = float(
        np.bitwise_count(codes_dev ^ codes_ref).sum() / (codes_ref.shape[0] * k)
    )

    executed = rate * 3 * 2 * d * k / 1e12  # 'high' = 3 MXU passes
    return {
        "workload": f"simhash sign-RP {d}->{k} packed uint8, f32_high, "
                    "estimator path",
        "rows_per_s": round(rate, 1),
        "raw_kernel_rows_per_s": round(raw_rate, 1),
        "estimator_vs_raw": round(rate / raw_rate, 3),
        "sign_mismatch_rate_vs_cpu": mismatch,
        "elapsed_s": round(elapsed, 4),
        "rows_timed": cfg["batch"] * cfg["steps"] * cfg["calls"],
        "executed_tflops": round(executed, 1),
        "mxu_utilization": round(executed / V5E_PEAK_TFLOPS, 3),
        "timing_suspect": bool(executed > 2 * V5E_PEAK_TFLOPS),
        "checksum": checksum,
        "code_bytes_per_row": k // 8,
        "topk_serving": measure_config4_topk(preset),
    }


def measure_config4_topk(preset: str = "full") -> dict:
    """Serving bench for the BL:10 index, two modes against one resident
    ``SimHashIndex`` (single chunk, one chip):

    - ``single_stream_queries_per_s`` — the r5 methodology: one
      ``query_topk`` tile dispatch at a time.  r05 recorded 1,687 q/s at
      7.4% MXU — the device idle on per-dispatch scan overhead.
    - ``queries_per_s`` (THE serving number since r9) — concurrent
      client threads submitting small requests through the
      ``TopKServer`` micro-batcher, which coalesces them into one tile
      dispatch (plus the overlapped per-chunk d2h inside ``query_topk``
      itself).  Same results per request, amortized dispatch.

    - ``sharded`` (ISSUE 8) — the same corpus as a
      ``ShardedSimHashIndex`` (``shards`` per replica group,
      ``replicas`` groups) served through ``ShardedTopKServer``'s
      round-robin replica routing: records queries/s, per-shard
      dispatch counts, cross-shard merge wall, and the replica batch
      spread.

    Every timed call/request sees DISTINCT query values (sliced from a
    pregenerated pool — the call cache cannot serve it); d2h per query
    is the reported byte count, not the dense ``4·n_codes`` row."""
    import threading

    from randomprojection_tpu.models.sketch import SimHashIndex, TopKServer

    from randomprojection_tpu.ops import topk_kernels

    shape = TOPK_BENCH_SHAPES[preset]
    n_idx = shape["n_idx"]
    m, q_tile, calls = 16, shape["q_tile"], 3
    rng = np.random.default_rng(10)
    codes = rng.integers(0, 256, size=(n_idx, 32), dtype=np.uint8)
    pool = rng.integers(0, 256, size=((calls + 1) * q_tile, 32), dtype=np.uint8)
    idx = SimHashIndex(codes)
    idx.query_topk(pool[calls * q_tile :], m, tile=q_tile)  # warm compile
    t0 = time.perf_counter()
    last = None
    for c in range(calls):
        last = idx.query_topk(
            pool[c * q_tile : (c + 1) * q_tile], m, tile=q_tile
        )
    elapsed = time.perf_counter() - t0
    qps = calls * q_tile / elapsed
    # MXU work per query: 2·n_idx·n_bits flops (±1 matmul Hamming)
    executed = qps * 2 * n_idx * 256 / 1e12

    # --- micro-batched serving: open-loop concurrent clients ------------
    clients, req_rows = shape["clients"], shape["req_rows"]
    reqs_per_client, max_batch = shape["reqs_per_client"], shape["max_batch"]
    n_requests = clients * reqs_per_client
    spool = rng.integers(
        0, 256, size=(2 * n_requests * req_rows, 32), dtype=np.uint8
    )
    server = TopKServer(idx, m, max_batch=max_batch, max_delay_s=0.01)

    def serve_round(offset):
        errs: list = []

        def client(ci):
            try:
                base = offset + ci * reqs_per_client
                futs = [
                    server.submit(
                        spool[(base + r) * req_rows : (base + r + 1) * req_rows]
                    )
                    for r in range(reqs_per_client)
                ]
                for f in futs:
                    f.result()
            except BaseException as e:  # rplint: allow[RP06] — client-thread errors are collected and re-raised after join (errs[0] below)
                errs.append(e)

        threads = [
            threading.Thread(target=client, args=(ci,), daemon=True)
            for ci in range(clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise errs[0]

    serve_round(0)  # warm: compiles the coalesced row bucket(s)
    warm_stats = server.stats()
    t0 = time.perf_counter()
    serve_round(n_requests)
    server_elapsed = time.perf_counter() - t0
    end_stats = server.stats()
    server.close()
    # coalescing tallies of the TIMED round only: the warm round pays
    # compile stalls and coalesces differently, and must not skew the
    # statistic recorded next to queries_per_s
    timed_batches = end_stats["batches"] - warm_stats["batches"]
    timed_queries = end_stats["queries"] - warm_stats["queries"]
    rows_per_batch = (
        round(timed_queries / timed_batches, 2) if timed_batches else 0.0
    )
    server_qps = n_requests * req_rows / server_elapsed
    server_executed = server_qps * 2 * n_idx * 256 / 1e12

    # --- sharded tier (ISSUE 8): the SAME corpus row-sharded, served
    # through replica-routed coalesced dispatches.  Each replica is a
    # ShardedSimHashIndex (per-shard fused dispatch + one cross-shard
    # merge); the server round-robins coalesced batches across
    # replicas.  On a single-chip box the shards share one device (the
    # merge/routing overhead is still real and measured); on a mesh
    # each shard owns a chip.
    shards, replicas = shape.get("shards", 0), shape.get("replicas", 1)
    sharded = None
    if shards:
        from randomprojection_tpu.serving import (
            ShardedSimHashIndex,
            ShardedTopKServer,
        )

        groups = [
            ShardedSimHashIndex(codes, n_shards=shards)
            for _ in range(replicas)
        ]
        sh_server = ShardedTopKServer(
            groups, m, max_batch=max_batch, max_delay_s=0.01,
        )
        # reuse the plain server's client harness against the sharded
        # server (globals-free closure over sh_server via patching the
        # submit target is uglier than a tiny local copy)

        def sh_round(offset):
            errs: list = []

            def client(ci):
                try:
                    base = offset + ci * reqs_per_client
                    futs = [
                        sh_server.submit(
                            spool[(base + r) * req_rows
                                  : (base + r + 1) * req_rows]
                        )
                        for r in range(reqs_per_client)
                    ]
                    for f in futs:
                        f.result()
                except BaseException as e:  # rplint: allow[RP06] — client-thread errors are collected and re-raised after join (errs[0] below)
                    errs.append(e)

            threads = [
                threading.Thread(target=client, args=(ci,), daemon=True)
                for ci in range(clients)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errs:
                raise errs[0]

        sh_round(0)  # warm: compiles every shard's bucket
        pre = [g.stats() for g in groups]
        t0 = time.perf_counter()
        sh_round(n_requests)
        sh_elapsed = time.perf_counter() - t0
        post = [g.stats() for g in groups]
        sh_stats = sh_server.stats()
        sh_server.close()
        merges = sum(b["merges"] - a["merges"] for a, b in zip(pre, post))
        merge_wall = sum(
            b["merge_wall_s"] - a["merge_wall_s"]
            for a, b in zip(pre, post)
        )
        sh_qps = n_requests * req_rows / sh_elapsed
        sh_executed = sh_qps * 2 * n_idx * 256 / 1e12
        sharded = {
            "shards": shards,
            "replicas": replicas,
            "queries_per_s": round(sh_qps, 1),
            "elapsed_s": round(sh_elapsed, 4),
            # per-shard dispatch count of the timed round: every live
            # shard is dispatched once per query tile (= per merge)
            "dispatches_per_shard": merges // max(replicas, 1),
            "shard_dispatches": merges * shards,
            "merges": merges,
            "merge_wall_s": round(merge_wall, 6),
            "replica_batches": sh_stats["replica_batches"],
            # r17 per-request tail latency (enqueue→complete quantiles
            # over warm + timed rounds of THIS process — the honest
            # client-observed number next to the throughput)
            "latency_quantiles": sh_stats.get("latency"),
            "executed_tflops": round(sh_executed, 1),
            "timing_suspect": bool(sh_executed > 2 * V5E_PEAK_TFLOPS),
        }

    return {
        "index_codes": n_idx,
        "m": m,
        # which device path served (ISSUE 7): 'fused' = the Pallas
        # scan+select kernel (the default), with the interpret flag
        # separating a real-chip record from a CPU interpreter run
        "topk_impl": idx._chunk_impl(
            q_tile, idx._chunks[0].b.shape[0], min(m, n_idx)
        ),
        "topk_interpret": topk_kernels.interpret_default(),
        "queries_per_s": round(server_qps, 1),
        "single_stream_queries_per_s": round(qps, 1),
        "server_vs_single_stream": round(server_qps / qps, 2),
        "server_clients": clients,
        "server_request_rows": req_rows,
        "server_max_batch": max_batch,
        "server_rows_per_batch_mean": rows_per_batch,
        # r17 per-request tail latency through the micro-batcher
        # (enqueue→complete quantiles over warm + timed rounds)
        "server_latency_quantiles": end_stats.get("latency"),
        "elapsed_s": round(server_elapsed, 4),
        "single_stream_elapsed_s": round(elapsed, 4),
        "executed_tflops": round(server_executed, 1),
        "mxu_utilization": round(server_executed / V5E_PEAK_TFLOPS, 3),
        "timing_suspect": bool(server_executed > 2 * V5E_PEAK_TFLOPS),
        "single_stream_executed_tflops": round(executed, 1),
        "single_stream_timing_suspect": bool(
            executed > 2 * V5E_PEAK_TFLOPS
        ),
        "d2h_bytes_per_query": 2 * 4 * m,
        "dense_d2h_bytes_per_query": 4 * n_idx,
        "checksum": int(last[0][0, 0]) if last is not None else None,
        "sharded": sharded,
        "lsh": measure_topk_lsh(preset),
        "tiered": measure_topk_tiered(preset),
    }


def _lsh_flip_bits(rng, codes, flips: int, n_bits: int) -> np.ndarray:
    """XOR ``flips`` random bit positions into every row (unbuffered —
    duplicate positions genuinely cancel)."""
    out = codes.copy()
    rows = np.repeat(np.arange(out.shape[0], dtype=np.int64), flips)
    pos = rng.integers(0, n_bits, size=rows.size)
    np.bitwise_xor.at(
        out, (rows, pos >> 3),
        np.left_shift(np.uint8(1), (pos & 7).astype(np.uint8)),
    )
    return out


def _lsh_exact_reference(queries, codes, m: int, *, q_block: int = 32,
                         c_block: int = 1 << 16):
    """Host brute-force reference, blocked over queries AND codes so
    the full-preset shape (2^20 codes) never materializes a multi-GB
    distance intermediate."""
    from randomprojection_tpu.models.sketch import (
        _host_topk_select,
        pairwise_hamming,
    )

    n = codes.shape[0]
    out_d = np.empty((queries.shape[0], m), np.int32)
    out_i = np.empty((queries.shape[0], m), np.int32)
    for lo in range(0, queries.shape[0], q_block):
        q = queries[lo : lo + q_block]
        D = np.empty((q.shape[0], n), np.int32)
        for c0 in range(0, n, c_block):
            D[:, c0 : c0 + c_block] = pairwise_hamming(
                q, codes[c0 : c0 + c_block]
            )
        d, i = _host_topk_select(D, m)
        out_d[lo : lo + q_block] = d
        out_i[lo : lo + q_block] = i
    return out_d, out_i


def _lsh_counters() -> tuple:
    from randomprojection_tpu.utils import telemetry

    reg = telemetry.registry()
    return (
        reg.counter("index.lsh.dispatches"),
        reg.counter("index.lsh.candidates"),
        reg.counter("index.lsh.fallbacks"),
        # host-vs-device wall split (ISSUE 16): host probe/prep seconds
        # vs fused dispatch seconds — deltas of these sums bracket the
        # timed window per curve point
        reg.hist_sum("index.lsh.probe.host_s") or 0.0,
        reg.hist_sum("index.lsh.probe.dispatch_s") or 0.0,
    )


def measure_topk_lsh(preset: str = "full") -> dict:
    """Recall-vs-q/s curve of the multi-probe LSH candidate tier
    (ISSUE 15): one planted-neighbor corpus (see ``LSH_BENCH_SHAPES``),
    one exact-serving baseline through the same index (``probes=0`` —
    the full fused-kernel ladder), then per probe count the EXACT
    recall@m against host brute force, the candidate fraction actually
    re-ranked (from the tier's own counters — what was touched, not
    what was hoped), fallback counts, and queries/s over distinct query
    slices.  The headline point is the cheapest probe setting clearing
    BOTH gates (recall ≥ ``LSH_RECALL_GATE``, candidate fraction ≤
    ``LSH_CANDIDATE_FRACTION_GATE``); ``recall_gate_ok`` is the
    tripwire — a bucket bug that tanks recall fails the bench instead
    of shipping as a fast wrong answer."""
    from randomprojection_tpu.ann import LSHSimHashIndex
    from randomprojection_tpu.ops import topk_kernels

    shape = LSH_BENCH_SHAPES[preset]
    n_idx, n_bytes = shape["n_idx"], shape["n_bytes"]
    cluster, nq, m = shape["cluster"], shape["nq"], shape["m"]
    noise_bits, calls = shape["noise_bits"], shape["calls"]
    rerank_tile = shape["rerank_tile"]
    n_bits = n_bytes * 8
    rng = np.random.default_rng(15)
    n_clusters = n_idx // cluster
    centers = rng.integers(0, 256, size=(n_clusters, n_bytes),
                           dtype=np.uint8)
    codes = _lsh_flip_bits(
        rng, np.repeat(centers, cluster, axis=0), noise_bits, n_bits
    )
    # (calls + 1) distinct query sets: set 0 measures recall (and warms
    # the compile buckets), sets 1..calls are the timed traffic — the
    # device call cache cannot serve repeats
    qc = rng.integers(0, n_clusters, size=(calls + 1) * nq)
    queries = _lsh_flip_bits(rng, centers[qc], noise_bits, n_bits)
    true_d, true_i = _lsh_exact_reference(queries[:nq], codes, m)

    index = LSHSimHashIndex(
        codes, bands=shape["bands"], band_bits=shape["band_bits"],
        fallback_density=1.0,  # the curve measures the tier, not the ladder
    )
    # exact-serving baseline through the SAME index (probes=0 pins the
    # fused/scan ladder): the denominator of speedup_vs_exact
    index.query_topk(queries[:nq], m, probes=0)  # warm compile
    t0 = time.perf_counter()
    for c in range(calls):
        index.query_topk(
            queries[(c + 1) * nq : (c + 2) * nq], m, probes=0
        )
    exact_qps = calls * nq / (time.perf_counter() - t0)

    curve = []
    for probes in shape["probe_counts"]:
        got_d, got_i = index.query_topk(
            queries[:nq], m, tile=rerank_tile, probes=probes
        )
        hits = 0
        for row_got, row_true in zip(got_i, true_i):
            hits += np.intersect1d(row_got, row_true).size
        recall = hits / true_i.size
        d0, c0, f0, h0, w0 = _lsh_counters()
        t0 = time.perf_counter()
        for c in range(calls):
            index.query_topk(
                queries[(c + 1) * nq : (c + 2) * nq], m,
                tile=rerank_tile, probes=probes,
            )
        elapsed = time.perf_counter() - t0
        d1, c1, f1, h1, w1 = _lsh_counters()
        tiles = d1 - d0
        frac = (
            (c1 - c0) / tiles / index.n_live if tiles else None
        )
        curve.append({
            "probes": int(probes),
            "recall_at_m": round(recall, 4),
            "candidate_fraction": (
                round(frac, 6) if frac is not None else None
            ),
            "queries_per_s": round(calls * nq / elapsed, 1),
            "fallbacks": int(f1 - f0),
            # the host-hop the device path removes, made visible: host
            # probe/prep wall vs fused-dispatch wall inside the timed
            # window (interpreter runs flag both suspect, no tripwire)
            "probe_host_s": round(h1 - h0, 6),
            "probe_dispatch_s": round(w1 - w0, 6),
            "timing_suspect": bool(topk_kernels.interpret_default()),
        })

    headline = None
    for point in curve:
        if (
            point["recall_at_m"] >= LSH_RECALL_GATE
            and point["candidate_fraction"] is not None
            and point["candidate_fraction"] <= LSH_CANDIDATE_FRACTION_GATE
        ):
            headline = dict(point)
            headline["speedup_vs_exact"] = round(
                point["queries_per_s"] / exact_qps, 2
            )
            break
    return {
        "metric": f"lsh recall@{m} vs q/s curve (probe count = knob)",
        "index_codes": n_idx,
        "code_bytes": n_bytes,
        "cluster_rows": cluster,
        "noise_bits": noise_bits,
        "queries": nq,
        "m": m,
        "bands": shape["bands"],
        "band_bits": shape["band_bits"],
        "rerank_tile": rerank_tile,
        "exact_queries_per_s": round(exact_qps, 1),
        "topk_interpret": topk_kernels.interpret_default(),
        # the candidate path auto-resolution (ISSUE 16): device-fused
        # probe → gather → re-rank on chips, host probe rung under the
        # interpreter — the wall split fields read against this
        "probe_path": "auto",
        "probe_path_resolved": (
            "device" if index._lsh_probe_device(None) else "host"
        ),
        "curve": curve,
        "recall_gate": LSH_RECALL_GATE,
        "candidate_fraction_gate": LSH_CANDIDATE_FRACTION_GATE,
        "headline": headline,
        "recall_gate_ok": headline is not None,
    }


def _tier_counters() -> tuple:
    from randomprojection_tpu.utils import telemetry

    reg = telemetry.registry()
    return (
        reg.counter("index.tier.hot_rows"),
        reg.counter("index.tier.cold_rows"),
        reg.counter("index.tier.fetches"),
        reg.counter("index.tier.fallbacks"),
        reg.hist_sum("index.tier.fetch_s") or 0.0,
        reg.hist_sum("index.tier.overlap_s") or 0.0,
    )


def measure_topk_tiered(
    preset: str = "full",
    *,
    hbm_budget_bytes: Optional[int] = None,
    cold_tier: str = "host",
) -> dict:
    """Tiered hot/cold serving bench (ISSUE 19 / r21): one chunked
    corpus served twice — fully resident (the baseline denominator)
    and through a ``TieredResidency``-managed index whose HBM budget
    admits only ``budget_chunks`` chunks (4x over budget at the default
    shape).  Reports the hot-hit fraction, the cold-fetch wall and its
    overlapped share (``cold_fetch_overlapped_s`` — the H2D seconds
    that rode under the hot-tier kernel), the cold-fetch p99, q/s vs
    the resident baseline, and a bit-parity verdict against the
    resident answers.  Interpreter runs flag ``timing_suspect`` — the
    wall numbers stay on the record but never become a tripwire; only
    ``parity_ok`` is a correctness statement."""
    import shutil
    import tempfile

    from randomprojection_tpu.models.sketch import SimHashIndex
    from randomprojection_tpu.ops import topk_kernels
    from randomprojection_tpu.utils import telemetry

    shape = TIER_BENCH_SHAPES[preset]
    n_idx, n_bytes = shape["n_idx"], shape["n_bytes"]
    nq, m, calls = shape["nq"], shape["m"], shape["calls"]
    chunk_rows, q_tile = shape["chunk_rows"], shape["q_tile"]
    if cold_tier not in ("host", "disk"):
        raise ValueError(f"cold_tier must be host or disk, got {cold_tier!r}")
    chunk_bytes = chunk_rows * n_bytes
    budget = (
        int(hbm_budget_bytes) if hbm_budget_bytes is not None
        else shape["budget_chunks"] * chunk_bytes
    )
    rng = np.random.default_rng(19)
    codes = rng.integers(0, 256, size=(n_idx, n_bytes), dtype=np.uint8)
    # (calls + 1) distinct query sets, same discipline as the LSH
    # bench: set 0 warms + checks parity, sets 1..calls are timed
    queries = rng.integers(
        0, 256, size=((calls + 1) * nq, n_bytes), dtype=np.uint8
    )

    def _ingest(index):
        # same chunk boundaries on both indexes — parity covers the
        # per-chunk merge, not just the final answer
        for lo in range(0, n_idx, chunk_rows):
            index.add(codes[lo : lo + chunk_rows])
        return index

    empty = codes[:0]
    resident = _ingest(SimHashIndex(empty))
    rd, ri = resident.query_topk(queries[:nq], m, tile=q_tile)  # warm
    t0 = time.perf_counter()
    for c in range(calls):
        resident.query_topk(
            queries[(c + 1) * nq : (c + 2) * nq], m, tile=q_tile
        )
    resident_qps = calls * nq / (time.perf_counter() - t0)

    cold_dir = tempfile.mkdtemp(prefix="rp_tier_bench_") \
        if cold_tier == "disk" else None
    tiered = _ingest(SimHashIndex(
        empty, hbm_budget_bytes=budget, cold_tier=cold_tier,
        cold_dir=cold_dir,
    ))
    try:
        td, ti = tiered.query_topk(queries[:nq], m, tile=q_tile)  # warm
        parity_ok = bool((td == rd).all() and (ti == ri).all())
        h0, c0, f0, fb0, w0, o0 = _tier_counters()
        t0 = time.perf_counter()
        for c in range(calls):
            tiered.query_topk(
                queries[(c + 1) * nq : (c + 2) * nq], m, tile=q_tile
            )
        elapsed = time.perf_counter() - t0
        h1, c1, f1, fb1, w1, o1 = _tier_counters()
        # p99 from the registry histogram: every observation is this
        # bench's own cold-fetch traffic (warm + timed), so the
        # lifetime quantile IS the bench quantile
        fq = telemetry.registry().hist_quantiles("index.tier.fetch_s")
        hot, cold = h1 - h0, c1 - c0
        chunk_tiers = [
            c["tier"] for c in tiered._tier.residency()["chunks"]
        ] if tiered._tier else []
    finally:
        tiered.close()
        resident.close()
        if cold_dir is not None:
            shutil.rmtree(cold_dir, ignore_errors=True)
    return {
        "metric": "tiered hot/cold serving vs resident baseline",
        "index_codes": n_idx,
        "code_bytes": n_bytes,
        "chunk_rows": chunk_rows,
        "chunks": -(-n_idx // chunk_rows),
        "queries": nq,
        "m": m,
        "cold_tier": cold_tier,
        "hbm_budget_bytes": budget,
        "over_budget_factor": round(n_idx * n_bytes / budget, 2),
        "hot_chunks": sum(1 for t in chunk_tiers if t == "hot"),
        "cold_chunks": sum(1 for t in chunk_tiers if t != "hot"),
        "resident_queries_per_s": round(resident_qps, 1),
        "queries_per_s": round(calls * nq / elapsed, 1),
        "slowdown_vs_resident": round(
            resident_qps / (calls * nq / elapsed), 3
        ),
        "hot_hit_fraction": (
            round(hot / (hot + cold), 4) if (hot + cold) else None
        ),
        "cold_fetches": int(f1 - f0),
        "cold_fetch_wall_s": round(w1 - w0, 6),
        # the H2D seconds that rode UNDER the hot-tier kernel inside
        # the timed window — the overlap the tier exists to buy
        "cold_fetch_overlapped_s": round(o1 - o0, 6),
        "cold_fetch_p99_s": (
            round(fq["p99"], 6) if fq and fq.get("p99") is not None
            else None
        ),
        "fallbacks": int(fb1 - fb0),
        "parity_ok": parity_ok,
        "timing_suspect": bool(topk_kernels.interpret_default()),
    }


# -- bench-record loading (shared with docs/gen_bench_tables.py) ------------


def _balanced_json(text: str, start: int) -> str:
    """The {...} object starting at ``text[start]`` (balanced braces; the
    bench JSON contains no braces inside strings)."""
    depth = 0
    for i in range(start, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return text[start : i + 1]
    raise ValueError("unbalanced JSON object")


def find_compact_line(text: str) -> Optional[dict]:
    """The LAST compact-summary object embedded in ``text`` (the driver's
    tail capture keeps the end of stdout, so when both the full record and
    the compact line survive, the compact line is the later, authoritative
    one for its keys).  None when no intact compact object is present."""
    found = None
    for m in re.finditer(r'\{"%s"' % COMPACT_MARKER, text):
        try:
            obj = json.loads(_balanced_json(text, m.start()))
        except ValueError:
            continue
        if obj.get(COMPACT_MARKER) == COMPACT_SCHEMA_VERSION:
            found = obj
    return found


def recover_bench_tail(tail: str) -> dict:
    """Rebuild the usable record from a FRONT-TRUNCATED bench line (the
    driver keeps only the tail of the output): every per-mode dict and
    every configN dict is extracted by key with balanced braces, and the
    headline is re-derived from the recovered modes with the bench's own
    ``select_headline`` — nothing is guessed."""
    out: dict = {"all_modes": {}}
    for m in re.finditer(r'"(\w+)":\s*(\{"rows_per_s")', tail):
        name = m.group(1)
        obj = json.loads(_balanced_json(tail, m.start(2)))
        if "distortion" in obj and "timing_suspect" in obj:
            out["all_modes"][name] = obj
        elif name.startswith("config"):
            out[name] = obj
    for m in re.finditer(r'"(config\d)":\s*(\{)', tail):
        if m.group(1) not in out:
            out[m.group(1)] = json.loads(_balanced_json(tail, m.start(2)))
    if not out["all_modes"] and not any(
        k.startswith("config") for k in out
    ):
        raise ValueError("nothing recoverable from the bench tail")
    if out["all_modes"]:
        head = select_headline(out["all_modes"])
        out.setdefault("mode", head)
        out.setdefault("value", out["all_modes"][head]["rows_per_s"])
        out.setdefault(
            "distortion_eps_vs_cpu", out["all_modes"][head]["distortion"]
        )
        # the re-derived headline inherits its mode's OWN suspect flag —
        # an all-suspect run must not become a trusted tripwire baseline
        out.setdefault(
            "timing_suspect", out["all_modes"][head]["timing_suspect"]
        )
        out.setdefault("metric", f"rows/sec/chip (headline mode {head})")
    out["_recovered_from_truncated_tail"] = True
    return out


def load_bench_record(path: str) -> dict:
    """Load one committed ``BENCH_r*.json`` into a bench record dict.

    Handles every committed shape: a bare record, the driver wrapper
    ``{n, cmd, rc, tail, parsed}`` with a parsed record, and a wrapper
    whose ``parsed`` is null — there the tail is scanned for, in order of
    preference, an intact full record line, the COMPACT summary line
    (tail-safe by construction: the final ≤2 KB stdout line), and last
    the balanced-brace recovery of a front-truncated full line."""
    with open(path) as f:
        j = json.load(f)
    if "parsed" not in j:
        return j
    parsed = j["parsed"]
    if parsed and COMPACT_MARKER not in parsed:
        return parsed
    # parsed is null OR the driver parsed the (final) compact line: the
    # richer full record may still sit intact in the tail — prefer it
    tail = j.get("tail", "")
    for m in re.finditer(r'\{"metric"', tail):
        try:
            obj = json.loads(_balanced_json(tail, m.start()))
        except ValueError:
            continue
        # the records themselves now EMBED {"metric": ...} objects (the
        # regressions entries), so a bare '{"metric"' match is not enough
        # — only an object carrying the record's own top-level keys is an
        # intact full record
        if "all_modes" in obj or "value" in obj:
            return obj
    compact = find_compact_line(tail) or (parsed if parsed else None)
    if compact is not None:
        rec = dict(compact)
        # normalize: older compact lines may lack the headline distortion
        # key — derive it from the headline mode's own digest so renderers
        # can rely on the full-record headline fields
        head = (rec.get("all_modes") or {}).get(rec.get("mode"))
        if head is not None:
            rec.setdefault("distortion_eps_vs_cpu", head.get("distortion"))
            rec.setdefault("value", head.get("rows_per_s"))
            rec.setdefault("timing_suspect", head.get("timing_suspect"))
        rec["_from_compact_summary"] = True
        return rec
    return recover_bench_tail(tail)


def committed_bench_paths(root: Optional[str] = None) -> list:
    """All committed ``BENCH_r*.json`` paths, oldest → newest (the zero-
    padded round numbers make the lexicographic sort chronological)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))


def newest_committed_bench(root: Optional[str] = None) -> Optional[str]:
    """Path of the newest committed ``BENCH_r*.json`` (None outside a
    checkout)."""
    files = committed_bench_paths(root)
    return files[-1] if files else None


def bench_trajectory(root: Optional[str] = None) -> list:
    """One row per committed ``BENCH_r*.json``, oldest → newest: the
    round-over-round trajectory the newest-vs-current tripwire cannot
    show.  Each row is ``{"file", "mode", "rates"}`` with ``rates`` from
    ``bench_rates`` (so every number carries its own suspect flag), or
    ``{"file", "error"}`` for a record the loader cannot salvage — a
    crashed round stays VISIBLE in the trajectory instead of silently
    shortening it."""
    rows = []
    for path in committed_bench_paths(root):
        name = os.path.basename(path)
        try:
            rec = load_bench_record(path)
        except (ValueError, json.JSONDecodeError) as e:
            rows.append({"file": name, "error": str(e)})
            continue
        rows.append({
            "file": name,
            "mode": rec.get("mode"),
            "rates": bench_rates(rec),
        })
    return rows


# -- regression tripwire -----------------------------------------------------


def bench_rates(record: dict) -> dict:
    """Every comparable throughput in a bench record, as
    ``{metric_name: (value, suspect)}`` — suspect carries the record's own
    self-flagging (``timing_suspect`` / ``host_suspect`` / pipeline
    flags), so the tripwire never condemns a number the record itself
    already disowned, and never trusts one either."""
    rates: dict = {}

    def put(name, container, key, suspect_key, default_suspect=False):
        if not isinstance(container, dict):
            return
        v = container.get(key)
        if isinstance(v, (int, float)) and v > 0:
            rates[name] = (
                float(v), bool(container.get(suspect_key, default_suspect))
            )

    put("headline.rows_per_s", record, "value", "timing_suspect")
    for n, r in (record.get("all_modes") or {}).items():
        put(f"mode.{n}.rows_per_s", r, "rows_per_s", "timing_suspect")
    put("config1.rows_per_s", record.get("config1"), "rows_per_s",
        "host_suspect")
    put("config3.rows_per_s", record.get("config3"), "rows_per_s",
        "timing_suspect")
    c4 = record.get("config4")
    put("config4.rows_per_s", c4, "rows_per_s", "timing_suspect")
    put("config4.raw_kernel_rows_per_s", c4, "raw_kernel_rows_per_s",
        "timing_suspect")
    if isinstance(c4, dict):
        put("config4.topk.queries_per_s", c4.get("topk_serving"),
            "queries_per_s", "timing_suspect")
        put("config4.topk.single_stream_queries_per_s",
            c4.get("topk_serving"), "single_stream_queries_per_s",
            "single_stream_timing_suspect")
        put("config4.topk.sharded_queries_per_s",
            (c4.get("topk_serving") or {}).get("sharded")
            if isinstance(c4.get("topk_serving"), dict) else None,
            "queries_per_s", "timing_suspect")
        if "config4.topk.queries_per_s" not in rates:
            # compact-line records flatten topk_serving.queries_per_s to
            # topk_queries_per_s (suspect flag: topk_timing_suspect) — a
            # previous round that survived only as its compact line must
            # still gate the serving rate
            put("config4.topk.queries_per_s", c4, "topk_queries_per_s",
                "topk_timing_suspect")
        if "config4.topk.sharded_queries_per_s" not in rates:
            put("config4.topk.sharded_queries_per_s", c4,
                "topk_sharded_queries_per_s", "topk_sharded_timing_suspect")
        # LSH candidate tier (ISSUE 15): the headline curve point's q/s
        # gates like any serving rate (its own suspect flag — interpret
        # runs never become a chip baseline)
        tk2 = c4.get("topk_serving")
        lsh = tk2.get("lsh") if isinstance(tk2, dict) else None
        put("config4.topk.lsh_queries_per_s",
            (lsh or {}).get("headline"), "queries_per_s",
            "timing_suspect")
        if "config4.topk.lsh_queries_per_s" not in rates:
            put("config4.topk.lsh_queries_per_s", c4,
                "topk_lsh_queries_per_s", "topk_lsh_timing_suspect")
        # tiered residency (ISSUE 19 / r21): the beyond-HBM rate gates
        # like any serving rate (its own suspect flag)
        tier2 = tk2.get("tiered") if isinstance(tk2, dict) else None
        put("config4.topk.tiered_queries_per_s", tier2,
            "queries_per_s", "timing_suspect")
        if "config4.topk.tiered_queries_per_s" not in rates:
            put("config4.topk.tiered_queries_per_s", c4,
                "topk_tiered_queries_per_s", "topk_tiered_timing_suspect")
    c5 = record.get("config5")
    put("config5.ingest_tokens_per_s", c5, "ingest_tokens_per_s",
        "ingest_host_suspect")
    put("config5.device_sketch_docs_per_s", c5, "device_sketch_docs_per_s",
        "sketch_timing_suspect")
    put("config5.end_to_end_docs_per_s", c5, "end_to_end_docs_per_s",
        "pipeline_timing_suspect")
    put("config5.end_to_end_prefetch_docs_per_s", c5,
        "end_to_end_prefetch_docs_per_s", "prefetch_timing_suspect")
    put("config5.end_to_end_serial_docs_per_s", c5,
        "end_to_end_serial_docs_per_s", "serial_timing_suspect")
    return rates


def compute_regressions(current: dict, previous: dict,
                        threshold: float = REGRESSION_THRESHOLD) -> list:
    """Rates in ``current`` that dropped more than ``threshold`` vs
    ``previous``, skipping any rate either side self-flagged as suspect —
    the config-3-style silent 13% decay (VERDICT r5) becomes a recorded
    ``regressions`` entry instead of a diff archaeology exercise."""
    cur, prev = bench_rates(current), bench_rates(previous)
    out = []
    for name in sorted(cur):
        if name not in prev:
            continue
        cv, c_sus = cur[name]
        pv, p_sus = prev[name]
        if c_sus or p_sus:
            continue
        drop = 1.0 - cv / pv
        if drop > threshold:
            out.append({
                "metric": name,
                "previous": round(pv, 1),
                "current": round(cv, 1),
                "drop_pct": round(100.0 * drop, 1),
            })
    # the headline IS one of the modes: when the same mode headlines both
    # rounds, its per-mode entry already carries the drop — listing the
    # identical numbers twice is noise.  A headline-mode CHANGE keeps the
    # headline entry (the flagship rate moved for selection reasons worth
    # flagging even if every individual mode improved).
    mode = current.get("mode")
    if mode and mode == previous.get("mode") and any(
        r["metric"] == f"mode.{mode}.rows_per_s" for r in out
    ):
        out = [r for r in out if r["metric"] != "headline.rows_per_s"]
    out.sort(key=lambda r: -r["drop_pct"])
    return out


def _lsh_gate_regressions(record: dict) -> list:
    """The recall tripwire (ISSUE 15): a record whose LSH curve failed
    the recall/candidate-fraction gate carries the failure as a
    regression entry — absolute, not baseline-relative, so a bucket
    bug cannot ship as "fast" even in the very round that introduces
    it.  Empty when the record has no LSH section or the gate passed."""
    tk = (record.get("config4") or {}).get("topk_serving") \
        if isinstance(record.get("config4"), dict) else None
    lsh = tk.get("lsh") if isinstance(tk, dict) else None
    if not isinstance(lsh, dict) or lsh.get("recall_gate_ok") is not False:
        return []
    best = max(
        (p.get("recall_at_m") or 0.0 for p in lsh.get("curve") or []),
        default=0.0,
    )
    gate = float(lsh.get("recall_gate", LSH_RECALL_GATE))
    return [{
        "metric": "config4.topk.lsh_recall_gate",
        "previous": gate,
        "current": round(best, 4),
        "drop_pct": round(100.0 * max(0.0, 1.0 - best / gate), 1),
    }]


def attach_regressions(record: dict, root: Optional[str] = None) -> dict:
    """Add the ``regressions`` / ``regressions_vs`` keys to a fresh record
    by comparing against the newest committed ``BENCH_r*.json``.  Only a
    full-preset default-shape run is comparable to the committed records;
    anything else gets an empty list with the skip reason on file.  The
    LSH recall gate (``_lsh_gate_regressions``) rides every path —
    including skipped comparisons — because it is absolute, not
    baseline-relative."""
    gate_regs = _lsh_gate_regressions(record)
    record["regressions"] = list(gate_regs)
    record.setdefault("regressions_vs", None)
    if record.get("preset") != "full" or record.get("shape_is_default") is False:
        record["regressions_skipped"] = (
            "only full-preset default-shape runs are comparable to the "
            "committed records"
        )
        return record
    paths = committed_bench_paths(root)
    if not paths:
        record["regressions_skipped"] = "no committed BENCH_r*.json found"
        return record
    # newest usable record wins: a round whose bench crashed (garbage
    # tail) must not turn the tripwire off — fall back to the next-newest
    # intact record instead of going silently dark
    for path in reversed(paths):
        try:
            prev = load_bench_record(path)
        except (ValueError, json.JSONDecodeError):
            continue
        if not bench_rates(prev):
            continue  # parsed, but nothing comparable in it
        record["regressions"] = gate_regs + compute_regressions(
            record, prev
        )
        record["regressions_vs"] = os.path.basename(path)
        record.pop("regressions_skipped", None)
        return record
    record["regressions_skipped"] = (
        "no committed BENCH_r*.json is parseable with comparable rates"
    )
    return record


# -- tail-safe compact summary -----------------------------------------------


def _sig(v, digits: int = 4):
    """Round to ``digits`` significant figures (compact-line byte budget)."""
    if not isinstance(v, (int, float)) or isinstance(v, bool):
        return v
    return float(f"{float(v):.{digits}g}")


def compact_summary(record: dict) -> dict:
    """The ≤2 KB digest printed as the FINAL stdout line of the bench.

    Self-contained: headline mode record, per-mode digests (rows/s,
    distortion, suspect), per-config digests, and the ``regressions``
    tripwire output — everything a reader (or ``gen_bench_tables``) needs
    when the multi-KB full record line is tail-truncated.  Key names
    mirror the full record so downstream loaders treat a compact record
    as a pruned full one.  If an unexpectedly fat payload would exceed
    the byte budget, the largest optional sections are dropped (never
    the headline or ``regressions``) and the drop is recorded.
    """
    c: dict = {COMPACT_MARKER: COMPACT_SCHEMA_VERSION}
    for k in ("metric", "mode", "unit", "preset"):
        if record.get(k) is not None:
            c[k] = record[k]
    for k in ("value", "vs_baseline", "distortion_eps_vs_cpu"):
        if record.get(k) is not None:
            c[k] = _sig(record[k])
    if record.get("timing_suspect") is not None:
        c["timing_suspect"] = bool(record["timing_suspect"])
    # ISSUE 9 execution-knob provenance: a compact-line-only record must
    # still say which transform route / chain length produced its rates,
    # or a single-buffered A/B run could silently become the tripwire
    # baseline for the DMA default
    for k in ("transform_dma", "dispatch_steps"):
        if record.get(k) is not None:
            c[k] = record[k]
    modes = record.get("all_modes") or {}
    if modes:
        c["all_modes"] = {
            n: {
                "rows_per_s": _sig(r.get("rows_per_s")),
                "distortion": _sig(r.get("distortion"), 3),
                "timing_suspect": bool(r.get("timing_suspect")),
            }
            for n, r in modes.items()
        }
    digests = {
        "config1": ("rows_per_s", "host_suspect"),
        "config3": ("rows_per_s", "distortion", "timing_suspect"),
        "config4": ("rows_per_s", "raw_kernel_rows_per_s",
                    "estimator_vs_raw", "timing_suspect"),
        "config5": ("end_to_end_docs_per_s", "end_to_end_prefetch_docs_per_s",
                    "end_to_end_serial_docs_per_s",
                    "ingest_tokens_per_s", "device_sketch_docs_per_s",
                    "ingest_workers", "pipeline_bubble_pct",
                    "ingest_host_suspect", "sketch_timing_suspect",
                    "pipeline_timing_suspect", "prefetch_timing_suspect",
                    "serial_timing_suspect"),
    }
    for name, keys in digests.items():
        src = record.get(name)
        if isinstance(src, dict):
            c[name] = {k: _sig(src[k]) for k in keys if k in src}
    tk = (record.get("config4") or {}).get("topk_serving")
    if isinstance(tk, dict) and "queries_per_s" in tk:
        c4d = c.setdefault("config4", {})
        c4d["topk_queries_per_s"] = _sig(tk["queries_per_s"])
        if "single_stream_queries_per_s" in tk:
            c4d["topk_single_stream_queries_per_s"] = _sig(
                tk["single_stream_queries_per_s"]
            )
        if "timing_suspect" in tk:
            # the serving bench self-flags independently of the main
            # config4 kernel — the flattened digest must keep ITS flag or
            # a suspect serving rate becomes a trusted baseline
            c4d["topk_timing_suspect"] = bool(tk["timing_suspect"])
        sh = tk.get("sharded")
        if isinstance(sh, dict) and "queries_per_s" in sh:
            # sharded-tier digest (ISSUE 8): enough to gate the rate and
            # reconstruct the layout, flat so the ≤2 KB bound holds
            c4d["topk_sharded_queries_per_s"] = _sig(sh["queries_per_s"])
            c4d["topk_sharded_shards"] = sh.get("shards")
            c4d["topk_sharded_replicas"] = sh.get("replicas")
            c4d["topk_sharded_timing_suspect"] = bool(
                sh.get("timing_suspect")
            )
        lsh = tk.get("lsh")
        if isinstance(lsh, dict):
            # LSH-tier digest (ISSUE 15): the headline point + the
            # recall tripwire verdict, flat so a compact-line-only
            # round still gates recall and the rate
            c4d["topk_lsh_recall_gate_ok"] = bool(
                lsh.get("recall_gate_ok")
            )
            hl = lsh.get("headline")
            if isinstance(hl, dict):
                c4d["topk_lsh_probes"] = hl.get("probes")
                c4d["topk_lsh_recall"] = _sig(hl.get("recall_at_m"), 3)
                c4d["topk_lsh_candidate_fraction"] = _sig(
                    hl.get("candidate_fraction"), 3
                )
                c4d["topk_lsh_queries_per_s"] = _sig(
                    hl.get("queries_per_s")
                )
                c4d["topk_lsh_timing_suspect"] = bool(
                    hl.get("timing_suspect")
                )
                # host-vs-device wall split at the headline point
                # (ISSUE 16): the host-hop removal, gate-free
                c4d["topk_lsh_probe_host_s"] = _sig(
                    hl.get("probe_host_s"), 3
                )
                c4d["topk_lsh_probe_dispatch_s"] = _sig(
                    hl.get("probe_dispatch_s"), 3
                )
            c4d["topk_lsh_probe_path"] = lsh.get("probe_path_resolved")
        tier = tk.get("tiered")
        if isinstance(tier, dict):
            # tiered-residency digest (ISSUE 19 / r21): the hot-hit
            # fraction, the cold-fetch wall/overlap/p99, the rate vs
            # resident, and the parity verdict, flat so a compact-line-
            # only round still reads the residency story
            c4d["topk_tiered_queries_per_s"] = _sig(
                tier.get("queries_per_s")
            )
            c4d["topk_tiered_slowdown_vs_resident"] = _sig(
                tier.get("slowdown_vs_resident"), 3
            )
            c4d["topk_tiered_hot_hit_fraction"] = _sig(
                tier.get("hot_hit_fraction"), 3
            )
            c4d["topk_tiered_cold_fetch_p99_s"] = _sig(
                tier.get("cold_fetch_p99_s"), 3
            )
            c4d["topk_tiered_cold_fetch_overlapped_s"] = _sig(
                tier.get("cold_fetch_overlapped_s"), 3
            )
            c4d["topk_tiered_cold_tier"] = tier.get("cold_tier")
            c4d["topk_tiered_parity_ok"] = bool(tier.get("parity_ok"))
            c4d["topk_tiered_timing_suspect"] = bool(
                tier.get("timing_suspect")
            )
    regs = record.get("regressions", [])
    if len(regs) > 8:
        c["regressions_truncated"] = len(regs) - 8
        regs = regs[:8]
    c["regressions"] = regs
    if record.get("regressions_vs") is not None:
        c["regressions_vs"] = record["regressions_vs"]
    if record.get("regressions_skipped"):
        c["regressions_skipped"] = record["regressions_skipped"]

    def size(d):
        return len(json.dumps(d, separators=(",", ":")).encode())

    for victim in ("all_modes", "config5", "config4"):
        if size(c) <= COMPACT_MAX_BYTES:
            break
        if victim in c:  # pragma: no cover — needs a pathological record
            del c[victim]
            c.setdefault("compact_dropped", []).append(victim)
    return c


def emit_bench_output(record: dict) -> None:
    """Print the full record, then the compact digest as the FINAL stdout
    line — the driver's tail capture can truncate the former but, at ≤2 KB,
    never loses the latter."""
    print(json.dumps(record))
    print(json.dumps(compact_summary(record), separators=(",", ":")))


def run(preset: str = "full", k: int = 256, d: int = 4096,
        density: float = 1.0 / 3.0, transform_dma=None,
        dispatch_steps=None) -> dict:
    """``transform_dma``/``dispatch_steps`` are the ISSUE 9 execution
    knobs, recorded in the output (and the compact digest) so a committed
    record is self-describing about which transform route it measured:

    - ``transform_dma``: ``None`` takes the kernel default (the manual
      double-buffered x DMA route since ISSUE 9); ``False`` pins the
      single-buffered automatic tiling (the pre-r14 kernel) — the A/B
      lever for attributing a rate delta to the DMA pipeline.
    - ``dispatch_steps``: overrides the preset's anti-cache
      steps-per-dispatch for the headline modes.  The harness already
      chains its steps through ONE traced dispatch (``_scan_harness``'s
      ``lax.scan``), so this IS the bench-path dispatch-fusion chain
      length: call-boundary host gaps (~13% of wall in the r5 trace)
      amortize by 1/steps.  The anti-cache defenses are call-level and
      survive any steps value.
    """
    import jax
    import jax.numpy as jnp

    from randomprojection_tpu.ops import kernels

    import math

    cfg = dict(PRESETS[preset])
    if dispatch_steps is not None:
        if int(dispatch_steps) < 1:
            raise ValueError(
                f"dispatch_steps must be >= 1, got {dispatch_steps}"
            )
        cfg["steps"] = int(dispatch_steps)
    R = kernels.sparse_matrix(jax.random.key(0), k, d, density, jnp.float32)
    scale = 1.0 / math.sqrt(density * k)

    rng = np.random.default_rng(0)
    x_cpu = rng.normal(size=(16384, d)).astype(np.float32)

    # effective MXU FLOPs per row differ per mode: bf16 is 1 pass over the
    # contraction, split2 runs it twice, 'high' three times — the peak
    # check must use what the hardware actually executes
    mxu_passes = {"bf16": 1, "bf16_split2": 2, "f32_high": 3,
                  "lazy": 1, "lazy_split2": 2, "lazy_bf16": 1,
                  "lazy_f32_bf16data": 1}
    in_itemsize = {"bf16": 2, "lazy_bf16": 2}  # default 4 (f32 input;
    # lazy_f32_bf16data deliberately keeps the f32 container)

    # the fused lazy Pallas modes regenerate the mask in VMEM (zero R HBM
    # traffic — ops/pallas_kernels.py); the pltpu PRNG has no CPU or GPU
    # emulation, so they run only on a real TPU-family chip (same deny-list
    # as backends/jax_backend.py's lazy guard: unknown platforms like this
    # box's virtualized 'axon' are TPU-backed).  Their distortion reference
    # is the matching materialized matrix (same (seed, block) streams).
    mode_names = ["bf16", "bf16_split2", "f32_high"]
    lazy_kw = {}
    R_by_mode = {}
    if jax.default_backend() not in ("cpu", "gpu", "cuda", "rocm"):
        from randomprojection_tpu.ops.pallas_kernels import pallas_sparse_matrix

        lazy_seed = 0
        R_lazy = pallas_sparse_matrix(lazy_seed, k, d, density)
        for name in ("lazy", "lazy_split2", "lazy_bf16",
                     "lazy_f32_bf16data"):
            mode_names.append(name)
            lazy_kw[name] = dict(k=k, density=density, lazy_seed=lazy_seed,
                                 dma=transform_dma)
            R_by_mode[name] = R_lazy

    results = {}
    for name in mode_names:
        kw = lazy_kw.get(name, {})
        R_mode = R_by_mode.get(name, R)
        perf = measure_mode(jax, jnp, R_mode, name, scale, d=d, **cfg, **kw)
        perf["distortion"] = measure_distortion(
            jax, jnp, R_mode, x_cpu, name, scale, **kw
        )
        # nominal rate (the comparable rows/s·2dk number) and executed rate
        # (× MXU passes) — the suspect flag keys on the EXECUTED rate
        nominal = perf["rows_per_s"] * 2 * d * k / 1e12
        perf["implied_tflops"] = round(nominal, 1)
        perf["executed_tflops"] = round(nominal * mxu_passes[name], 1)
        perf["mxu_utilization"] = round(
            perf["executed_tflops"] / V5E_PEAK_TFLOPS, 3
        )
        perf["harness_hbm_cap_rows_per_s"] = round(
            harness_hbm_cap_rows_per_s(d, k, in_itemsize.get(name, 4)), 1
        )
        perf["timing_suspect"] = bool(
            perf["executed_tflops"] > 2 * V5E_PEAK_TFLOPS
        )
        results[name] = perf

    headline = select_headline(results)
    head = results[headline]

    elapsed_pass_invariant = detect_pass_invariance(results, mxu_passes)

    # CPU reference: dense f32 BLAS on this host, same shapes
    r_cpu = np.asarray(R, dtype=np.float32)
    x_cpu @ r_cpu.T  # warm BLAS
    t0 = time.perf_counter()
    x_cpu @ r_cpu.T
    cpu_rows_per_s = x_cpu.shape[0] / (time.perf_counter() - t0)

    workload = (
        "Achlioptas s=3"
        if abs(density - 1.0 / 3.0) < 1e-12
        else f"sparse density={density:.4g}"
    )
    record = {
        "metric": f"rows/sec/chip {d}->{k} ({workload}, data-resident, {headline})",
        "value": round(head["rows_per_s"], 1),
        "unit": "rows/s",
        "vs_baseline": round(head["rows_per_s"] / cpu_rows_per_s, 2),
        "cpu_baseline_rows_per_s": round(cpu_rows_per_s, 1),
        "distortion_eps_vs_cpu": head["distortion"],
        "mode": headline,
        "all_modes": {
            n: {
                "rows_per_s": round(r["rows_per_s"], 1),
                "distortion": r["distortion"],
                "elapsed_s": round(r["elapsed_s"], 4),
                "implied_tflops": r["implied_tflops"],
                "executed_tflops": r["executed_tflops"],
                "mxu_utilization": r["mxu_utilization"],
                "harness_hbm_cap_rows_per_s": r["harness_hbm_cap_rows_per_s"],
                "timing_suspect": r["timing_suspect"],
            }
            for n, r in results.items()
        },
        "rows_timed": head["rows_timed"],
        # ISSUE 9 execution-knob provenance: which transform route the
        # lazy modes ran ("dma" / "single" / "auto"=kernel default) and
        # the per-dispatch anti-cache chain length actually used
        "transform_dma": (
            "auto" if transform_dma is None
            else ("dma" if transform_dma else "single")
        ),
        "dispatch_steps": cfg["steps"],
        "implied_tflops": head["implied_tflops"],
        "timing_suspect": head["timing_suspect"],
        "elapsed_pass_invariant": elapsed_pass_invariant,
        "checksum": head["checksum"],
        # per-config tracked numbers (BASELINE.json:7-11) so every workload
        # has a recorded throughput; config2 IS the headline above; config3
        # needs the TPU-only lazy kernel
        "config1": measure_config1(),
        **(
            {"config3": measure_config3(preset)}
            if jax.default_backend() not in ("cpu", "gpu", "cuda", "rocm")
            else {}
        ),
        "config4": measure_config4(preset),
        "config5": (
            measure_config5()
            if preset == "full"
            else measure_config5(n_docs=8192)
        ),
        "preset": preset,
        "shape_is_default": bool(
            k == 256 and d == 4096 and abs(density - 1.0 / 3.0) < 1e-12
        ),
    }
    # the round-over-round tripwire: any non-suspect rate >10% under the
    # newest committed record is listed under "regressions" — config-3's
    # silent r5 decay becomes a recorded event (ISSUE r7)
    return attach_regressions(record)


def main(preset: str = "full") -> None:
    emit_bench_output(run(preset))
