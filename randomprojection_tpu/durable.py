"""Durable index lifecycle: snapshot/restore + exactly-once crash
recovery (ISSUE 6; ROADMAP open item 5).

Everything upstream of this module is build-once-then-query inside one
process: ``serialize.py`` persists fitted *estimators*, while
``SimHashIndex`` device chunks and the ingest cursor evaporate on a
crash.  This module extends the streaming layer's recovery contract —
ack-after-yield cursors, batches as pure functions of their row range —
across **process restarts**, so "resume is bit-identical" survives a
``kill -9``, not just a raised exception.

Three layers:

- **Snapshot format** — ``save_index``/``load_index`` spill an index's
  packed-code chunks to per-chunk ``.npy`` files under a directory,
  described by a versioned ``manifest.json`` carrying per-chunk SHA-256
  payload checksums (and the tombstone bitmap, when any).  Torn states
  are impossible by construction: every file is written
  write-tmp → fsync → ``os.replace``, the manifest is committed LAST
  (followed by a directory fsync), and chunk files are
  generation-numbered so a rewrite never touches a file the
  currently-committed manifest references.  Readers reject unknown
  format versions loudly and verify every checksum before upload.
- **Durable ingest** — ``DurableIngest`` binds ``stream_transform``'s
  checkpoint cursor to the index snapshot it corresponds to: each
  consumed batch appends one chunk file, and the cursor commit
  (``rows_done``) and the chunk flush are ONE atomic manifest replace.
  A crashed run resumed from disk replays exactly the uncommitted row
  ranges, and the rebuilt index is bit-identical to an uninterrupted
  run (chunk layout included).
- **Fault harness** — deterministic kill points (``RP_DURABLE_KILL=
  <point>@<n>`` self-delivers an uncatchable SIGKILL, exactly a
  ``kill -9`` at that instant), a subprocess child entry
  (``cli recover --child``) and ``crash_smoke``, which runs the full
  kill matrix (mid-batch, post-yield pre-ack, mid-snapshot-rename) at
  toy shapes, restarts each crashed run, and asserts no row range was
  dropped or double-committed and the recovered index is bit-identical
  to the clean run — wired into ``make verify`` before tier-1.

Telemetry: ``index.snapshot.save``/``index.snapshot.load`` on every
commit/restore, ``recover.resume`` (replayed ranges),
``recover.orphan_chunk`` (uncommitted spills swept at resume) and
``recover.checksum_mismatch`` (corruption, also in the doctor's
degraded audit) — all registered in ``telemetry.EVENTS``.
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import sys
from typing import Optional

import numpy as np

from randomprojection_tpu.streaming import (
    StreamCursor,
    _fsync_dir,
    stream_transform,
)
from randomprojection_tpu.utils import telemetry
from randomprojection_tpu.utils.telemetry import EVENTS

__all__ = [
    "INDEX_FORMAT_VERSION",
    "MANIFEST_NAME",
    "KILL_POINTS",
    "DurableIngest",
    "save_index",
    "load_index",
    "save_sharded_index",
    "load_sharded_index",
    "read_manifest",
    "verify_snapshot",
    "check_coverage",
    "demo_ingest",
    "crash_smoke",
]

INDEX_FORMAT_VERSION = 1
MANIFEST_NAME = "manifest.json"

# fault-injection points, in pipeline order; RP_DURABLE_KILL="<point>@<n>"
# SIGKILLs the process the n-th time that point is reached.  The last
# one lives in the tiered-residency demotion path (tiering.py): the
# cold-tier spill file exists but the residency swap has not happened —
# a crash there must leave the committed snapshot untouched and the
# spill as sweepable debris.
KILL_POINTS = ("mid-batch", "post-yield-pre-ack", "mid-snapshot-rename",
               "mid-demotion")
KILL_ENV = "RP_DURABLE_KILL"
_kill_counts: dict = {}


def _maybe_kill(point: str) -> None:
    """Fault-injection hook: if ``RP_DURABLE_KILL=<point>@<n>`` names
    this point, deliver an uncatchable SIGKILL on its n-th hit — no
    cleanup, no atexit, no flushing: exactly a ``kill -9``."""
    spec = os.environ.get(KILL_ENV)
    if not spec:
        return
    want, _, nth = spec.partition("@")
    if want != point:
        return
    _kill_counts[point] = _kill_counts.get(point, 0) + 1
    if _kill_counts[point] >= int(nth or 1):
        os.kill(os.getpid(), signal.SIGKILL)  # pragma: no cover — dies


def _sha256(arr: np.ndarray) -> str:
    """Payload checksum: over the raw row bytes, not the .npy container,
    so verification is immune to header/layout differences."""
    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()


def _write_npy_atomic(path: str, arr: np.ndarray) -> None:
    """Crash-safe array spill: write-tmp → flush → fsync → ``os.replace``
    — a reader never observes a torn file, and the payload is on disk
    before the name exists."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.save(f, arr)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _commit_manifest(dirpath: str, manifest: dict) -> None:
    """THE commit point: the single atomic ``os.replace`` of
    ``manifest.json`` flips the snapshot from old state to new state
    with no intermediate visible.  Ordering for MACHINE crashes, not
    just process crashes: spill payloads were fsync'd by
    ``_write_npy_atomic``, but their rename directory entries need the
    directory fsync BEFORE the manifest rename — otherwise a crash
    could persist a manifest that references chunk files whose renames
    never reached disk.  The directory fsync afterwards then makes the
    manifest rename itself durable."""
    _fsync_dir(dirpath)  # chunk renames reach disk before the commit
    path = os.path.join(dirpath, MANIFEST_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    _maybe_kill("mid-snapshot-rename")
    os.replace(tmp, path)
    _fsync_dir(dirpath)


def _next_generation_from_files(dirpath: str) -> int:
    """Smallest generation safely past every spill already on disk —
    used when no READABLE manifest records one (fresh directory, or a
    corrupt manifest being repaired by a re-save), so a new snapshot
    never overwrites an existing file."""
    import re

    gen = -1
    for fn in os.listdir(dirpath):
        m = re.match(r"(?:chunk|tombstones|lsh)-(\d{6})", fn)
        if m:
            gen = max(gen, int(m.group(1)))
    return gen + 1


def _spill_chunk(dirpath: str, gen: int, seq: int, arr: np.ndarray,
                 row0: int) -> dict:
    """Write one chunk spill under its generation-numbered name and
    return its manifest entry — the single source of the filename
    template and entry schema (save, ingest commit and compaction all
    spill through here, so the format cannot drift between writers)."""
    fname = f"chunk-{gen:06d}-{seq:08d}.npy"
    _write_npy_atomic(os.path.join(dirpath, fname), arr)
    return {
        "file": fname, "rows": int(arr.shape[0]), "row0": int(row0),
        "sha256": _sha256(arr),
    }


def read_manifest(dirpath: str) -> dict:
    """Load and validate a snapshot manifest; unknown format versions
    (and non-index manifests) are rejected loudly, never guessed at."""
    path = os.path.join(dirpath, MANIFEST_NAME)
    with open(path) as f:
        m = json.load(f)
    version = m.get("format_version")
    if version != INDEX_FORMAT_VERSION:
        raise ValueError(
            f"Unsupported index manifest version {version!r} in {path} "
            f"(expected {INDEX_FORMAT_VERSION})"
        )
    if m.get("kind") != "simhash_index":
        raise ValueError(
            f"{path} is not a SimHash index manifest "
            f"(kind={m.get('kind')!r})"
        )
    return m


def check_coverage(manifest: dict) -> int:
    """Assert the manifest's chunk row ranges tile ``[0, n_codes)``
    exactly once, in order — the no-drop / no-double-commit invariant
    the crash harness holds every recovered manifest to.  Returns the
    covered row count; raises ``ValueError`` on any gap or overlap."""
    pos = 0
    for entry in manifest["chunks"]:
        if entry["row0"] != pos:
            raise ValueError(
                f"chunk {entry['file']} starts at row {entry['row0']}, "
                f"expected {pos}: row ranges must tile without gaps or "
                "overlaps (a dropped or double-committed batch)"
            )
        pos += entry["rows"]
    if pos != manifest["n_codes"]:
        raise ValueError(
            f"chunks cover {pos} rows but the manifest records "
            f"n_codes={manifest['n_codes']}"
        )
    return pos


def _estimator_fingerprint(est) -> dict:
    """What makes two ingest estimators 'the same projection': the
    class plus the full spec (seed included) when the estimator carries
    one, else the resolved seed.  Recorded in the ingest manifest so a
    resume with a same-SHAPE but different-PROJECTION estimator (e.g.
    another seed) is refused instead of silently mixing two projections
    in one index."""
    fp = {"class": type(est).__name__}
    spec = getattr(est, "spec_", None)
    if spec is not None:
        fp["spec"] = spec.to_dict()
    elif hasattr(est, "seed_"):
        fp["seed"] = int(est.seed_)
    return fp


def _referenced_files(manifest: dict) -> set:
    refs = {e["file"] for e in manifest["chunks"]}
    if manifest.get("tombstones"):
        refs.add(manifest["tombstones"]["file"])
    if manifest.get("lsh"):
        refs.add(manifest["lsh"]["file"])
    return refs


def _scan_orphans(dirpath: str, manifest: Optional[dict]) -> list:
    """Spill files present in the directory but not referenced by the
    committed manifest: the debris of a crash between a chunk flush and
    its manifest commit (plus any ``.tmp`` a kill mid-write left)."""
    refs = _referenced_files(manifest) if manifest else set()
    orphans = []
    for fn in sorted(os.listdir(dirpath)):
        if fn.endswith(".tmp") or (
            fn.endswith(".npy")
            and (
                fn.startswith("chunk-")
                or fn.startswith("tombstones-")
                or fn.startswith("lsh-")
            )
            and fn not in refs
        ):
            orphans.append(fn)
    return orphans


def _load_chunk_verified(dirpath: str, entry: dict) -> np.ndarray:
    """Read one spill file and verify it against its manifest entry;
    corruption fails loudly with a ``recover.checksum_mismatch`` event,
    never a silently-wrong index."""
    path = os.path.join(dirpath, entry["file"])
    try:
        arr = np.load(path)
    except (OSError, ValueError) as e:
        telemetry.emit(
            EVENTS.RECOVER_CHECKSUM_MISMATCH, file=entry["file"],
            error=repr(e),
        )
        raise ValueError(
            f"snapshot chunk {path} is unreadable ({e}); the manifest "
            "references it — the snapshot is corrupt"
        ) from e
    actual = _sha256(arr)
    if actual != entry["sha256"]:
        telemetry.emit(
            EVENTS.RECOVER_CHECKSUM_MISMATCH, file=entry["file"],
            expected=entry["sha256"], actual=actual,
        )
        raise ValueError(
            f"snapshot chunk {path} fails checksum verification "
            f"(expected sha256 {entry['sha256'][:16]}…, got "
            f"{actual[:16]}…); refusing to load a corrupt index"
        )
    return arr


def save_index(index, dirpath: str, *, ingest: Optional[dict] = None) -> dict:
    """Write a durable snapshot of a ``SimHashIndex`` under ``dirpath``.

    Per-chunk ``.npy`` spills (one per resident device chunk — chunk
    structure round-trips) plus the tombstone bitmap, then one atomic
    manifest commit.  Files are generation-numbered: a re-save over an
    existing snapshot writes a NEW generation and only then unlinks the
    old one's files, so a crash at any instant leaves either the old or
    the new snapshot fully loadable — never a mix.  ``ingest`` attaches
    the durable-ingest cursor binding (see ``DurableIngest``).  Returns
    the committed manifest.
    """
    os.makedirs(dirpath, exist_ok=True)
    old = None
    try:
        old = read_manifest(dirpath)
    except FileNotFoundError:
        pass
    except ValueError:
        # a corrupt/unknown-version manifest must not block the natural
        # repair path (re-save the index) — nothing loadable exists to
        # protect, but the generation must still advance past every
        # on-disk spill name so no existing file is overwritten
        pass
    if old is not None:
        gen = old.get("generation", 0) + 1
    else:
        gen = _next_generation_from_files(dirpath)
    entries = []
    for i, chunk in enumerate(index._chunks):
        entries.append(_spill_chunk(
            dirpath, gen, i, index._fetch_chunk_host(chunk), chunk.row0
        ))
    tomb = None
    if index.n_deleted:
        packed = np.packbits(index._dead, bitorder="little")
        fname = f"tombstones-{gen:06d}.npy"
        _write_npy_atomic(os.path.join(dirpath, fname), packed)
        tomb = {
            "file": fname, "deleted": int(index.n_deleted),
            "sha256": _sha256(packed),
        }
    manifest = {
        "format_version": INDEX_FORMAT_VERSION,
        "kind": "simhash_index",
        "n_bytes": int(index.n_bytes),
        "n_bits": int(index.n_bits),
        "n_codes": int(index.n_codes),
        "generation": gen,
        "chunks": entries,
        "tombstones": tomb,
    }
    if ingest is not None:
        manifest["ingest"] = ingest
    # index-class extras (the ann LSH tier spills its band keys beside
    # the chunks and records them here): spilled BEFORE the manifest
    # commit, so the atomicity argument is unchanged — a crash leaves
    # the extra file as an orphan the next sweep collects
    extra_hook = getattr(index, "_durable_extra", None)
    if extra_hook is not None:
        manifest.update(extra_hook(dirpath, gen))
    # tiered residency (ISSUE 19): record the index's hot/cold placement
    # at snapshot time.  The snapshot's chunk spills above already went
    # through _fetch_chunk_host, which serves hot AND cold chunks alike,
    # so the payload is residency-independent — the block is provenance
    # and a verification surface (`cli recover` checks the tags), never
    # a load-time requirement (a restore re-tiers under its own budget)
    tier = getattr(index, "_tier", None)
    if tier is not None:
        manifest.update(tier.manifest_block())
    _commit_manifest(dirpath, manifest)
    # the new snapshot is committed: the previous generation's files are
    # now unreferenced debris (a crash before this sweep just leaves
    # orphans for the next resume's sweep)
    for fn in _scan_orphans(dirpath, manifest):
        os.unlink(os.path.join(dirpath, fn))
    telemetry.emit(
        EVENTS.INDEX_SNAPSHOT_SAVE, path=dirpath, generation=gen,
        chunks=len(entries), n_codes=int(index.n_codes),
        deleted=int(index.n_deleted),
        **({"rows_done": ingest["rows_done"]} if ingest else {}),
    )
    return manifest


def _check_tier_block(manifest: dict) -> None:
    """Validate a manifest's tiered-residency block (no-op when absent
    — pre-tier snapshots simply load with everything hot).  Unknown
    formats or tier tags fail LOUDLY: a silent skip would load a
    snapshot whose residency provenance this reader cannot interpret."""
    block = manifest.get("tier")
    if block is None:
        return
    if block.get("format") != 1:
        raise ValueError(
            f"unknown tier-block format {block.get('format')!r} "
            "(this reader understands format 1)"
        )
    from randomprojection_tpu.tiering import COLD_TIERS

    if block.get("cold_tier") not in COLD_TIERS:
        raise ValueError(
            f"unknown cold_tier {block.get('cold_tier')!r} in tier "
            f"block (expected one of {COLD_TIERS})"
        )
    known = ("hot",) + COLD_TIERS
    rows_by_tag = {e["row0"]: e["rows"] for e in manifest["chunks"]}
    for entry in block.get("chunks", []):
        if entry.get("tier") not in known:
            raise ValueError(
                f"unknown residency tag {entry.get('tier')!r} for chunk "
                f"row0={entry.get('row0')} (expected one of {known})"
            )
        if rows_by_tag.get(entry.get("row0")) != entry.get("rows"):
            raise ValueError(
                f"tier block names chunk row0={entry.get('row0')} "
                f"rows={entry.get('rows')} but the manifest's chunk "
                "table disagrees"
            )


def load_index(dirpath: str, *, mesh=None, data_axis: str = "data",
               index_cls=None, index_kwargs: Optional[dict] = None):
    """Rebuild a ``SimHashIndex`` from a snapshot directory.

    The manifest's format version is checked first, every chunk payload
    is SHA-256-verified before upload (corruption → loud ``ValueError``
    + ``recover.checksum_mismatch``), chunk structure and global id
    assignment are restored exactly, and the tombstone bitmap (if any)
    is re-armed.  ``mesh`` re-shards the restored chunks — the snapshot
    format is mesh-agnostic, and a snapshot written by the SHARDED tier
    (``save_sharded_index`` spills in global id order) loads here as a
    plain single-device index with identical query results.

    ``index_cls``/``index_kwargs`` restore a subclass instead (the ann
    LSH tier — its append hook rebuilds derived structures as the
    chunks re-add); ``ann.load_lsh_index`` is the public face.
    """
    from randomprojection_tpu.models.sketch import SimHashIndex

    manifest = read_manifest(dirpath)
    check_coverage(manifest)
    _check_tier_block(manifest)
    if manifest.get("id_offset"):
        # a plain SimHashIndex has no id-offset concept: loading would
        # silently renumber the corpus to 0-based ids — refuse and point
        # at the loader that restores the offset
        raise ValueError(
            f"{dirpath} was saved with id_offset="
            f"{manifest['id_offset']} (a sharded-tier global id space); "
            "load it with ShardedSimHashIndex.load / "
            "durable.load_sharded_index, which restores the offset"
        )
    cls = SimHashIndex if index_cls is None else index_cls
    kw = dict(index_kwargs or {})
    kw.update(mesh=mesh, data_axis=data_axis)
    index = cls(
        np.empty((0, manifest["n_bytes"]), np.uint8),
        n_bits=manifest["n_bits"], **kw,
    )
    for entry in manifest["chunks"]:
        arr = _load_chunk_verified(dirpath, entry)
        if arr.ndim != 2 or arr.shape != (entry["rows"], manifest["n_bytes"]):
            raise ValueError(
                f"snapshot chunk {entry['file']} has shape {arr.shape}, "
                f"manifest says ({entry['rows']}, {manifest['n_bytes']})"
            )
        index.add(arr)
    if index.n_codes != manifest["n_codes"]:
        raise ValueError(
            f"restored {index.n_codes} codes but the manifest records "
            f"{manifest['n_codes']}"
        )
    tomb = manifest.get("tombstones")
    if tomb:
        packed = _load_chunk_verified(dirpath, tomb)
        dead = np.unpackbits(
            packed, count=manifest["n_codes"], bitorder="little"
        ).astype(bool)
        if int(dead.sum()) != tomb["deleted"]:
            raise ValueError(
                f"tombstone bitmap in {dirpath} marks {int(dead.sum())} "
                f"codes deleted but the manifest records {tomb['deleted']}"
            )
        index._dead = dead
        index._n_deleted = int(dead.sum())
        index._dead_rev += 1
    telemetry.emit(
        EVENTS.INDEX_SNAPSHOT_LOAD, path=dirpath,
        generation=manifest["generation"], chunks=len(manifest["chunks"]),
        n_codes=int(index.n_codes), deleted=int(index.n_deleted),
    )
    return index


def save_sharded_index(index, dirpath: str) -> dict:
    """Durable snapshot of a ``serving.ShardedSimHashIndex`` — the
    MESH-AGNOSTIC layout: one spill per segment (= per shard chunk) in
    **global id order**, so the on-disk format is exactly a plain index
    snapshot of the concatenated corpus (same manifest kind, same
    coverage invariant, same per-chunk SHA-256 verification) plus two
    provenance fields: ``sharded`` records the writing layout, and
    ``id_offset`` (when nonzero) the global id base.  Consequences, by
    construction:

    - restore under ANY shard count (``load_sharded_index``) or as a
      plain single-device ``SimHashIndex`` (``load_index``, when
      ``id_offset`` is 0) — query results are bit-identical because
      global ids and the (distance, lower-global-id) merge order are
      layout-independent;
    - the same torn-write-impossible commit discipline as
      ``save_index`` (generation-numbered spills, manifest replaced
      LAST, old generation swept only after the commit).

    Tombstones persist as the GLOBAL bitmap — a deleted range that
    spans shard boundaries round-trips to whatever boundaries the
    loading layout has.  Returns the committed manifest."""
    os.makedirs(dirpath, exist_ok=True)
    old = None
    try:
        old = read_manifest(dirpath)
    except FileNotFoundError:
        pass
    except ValueError:
        pass  # corrupt manifest: re-save repairs (same policy as save_index)
    if old is not None:
        gen = old.get("generation", 0) + 1
    else:
        gen = _next_generation_from_files(dirpath)
    entries = []
    for seq, (g0, rows) in enumerate(index._iter_segment_host()):
        entries.append(_spill_chunk(dirpath, gen, seq, rows, g0))
    tomb = None
    dead = index._dead_global()
    if dead is not None:
        packed = np.packbits(dead, bitorder="little")
        fname = f"tombstones-{gen:06d}.npy"
        _write_npy_atomic(os.path.join(dirpath, fname), packed)
        tomb = {
            "file": fname, "deleted": int(index.n_deleted),
            "sha256": _sha256(packed),
        }
    manifest = {
        "format_version": INDEX_FORMAT_VERSION,
        "kind": "simhash_index",
        "n_bytes": int(index.n_bytes),
        "n_bits": int(index.n_bits),
        "n_codes": int(index.n_codes),
        "generation": gen,
        "chunks": entries,
        "tombstones": tomb,
        "sharded": {"shards": int(index.n_shards)},
    }
    if index.id_offset:
        manifest["id_offset"] = int(index.id_offset)
    # index-class extras (see save_index): the sharded LSH tier spills
    # its band keys in GLOBAL id order, layout-fungible like the chunks
    extra_hook = getattr(index, "_durable_extra", None)
    if extra_hook is not None:
        manifest.update(extra_hook(dirpath, gen))
    check_coverage(manifest)  # the writer holds itself to the invariant
    _commit_manifest(dirpath, manifest)
    for fn in _scan_orphans(dirpath, manifest):
        os.unlink(os.path.join(dirpath, fn))
    telemetry.emit(
        EVENTS.INDEX_SNAPSHOT_SAVE, path=dirpath, generation=gen,
        chunks=len(entries), n_codes=int(index.n_codes),
        deleted=int(index.n_deleted), shards=int(index.n_shards),
    )
    return manifest


def load_sharded_index(dirpath: str, *, mesh=None, devices=None,
                       n_shards=None, data_axis: str = "data",
                       topk_impl: str = "auto", index_cls=None,
                       index_kwargs: Optional[dict] = None):
    """Rebuild a ``serving.ShardedSimHashIndex`` from a snapshot
    directory onto ANY shard layout (``mesh`` / ``devices`` /
    ``n_shards`` — resolution as in ``serving.shard_devices``).  Works
    on snapshots written by ``save_sharded_index`` AND on plain
    ``save_index`` snapshots (both store the corpus in global id
    order); every chunk is checksum-verified BEFORE any upload, the
    corpus re-shards balanced over the new layout, the tombstone
    bitmap re-arms at the new shard boundaries, and ``id_offset``
    restores from the manifest — so ``query_topk`` answers are
    bit-identical to the saved index's, whatever layout wrote it."""
    from randomprojection_tpu.serving.sharded_index import ShardedSimHashIndex

    manifest = read_manifest(dirpath)
    check_coverage(manifest)
    parts = []
    for entry in manifest["chunks"]:
        arr = _load_chunk_verified(dirpath, entry)
        if arr.ndim != 2 or arr.shape != (entry["rows"], manifest["n_bytes"]):
            raise ValueError(
                f"snapshot chunk {entry['file']} has shape {arr.shape}, "
                f"manifest says ({entry['rows']}, {manifest['n_bytes']})"
            )
        parts.append(arr)
    codes = (
        np.concatenate(parts, axis=0)
        if parts
        else np.empty((0, manifest["n_bytes"]), np.uint8)
    )
    if codes.shape[0] != manifest["n_codes"]:
        raise ValueError(
            f"restored {codes.shape[0]} codes but the manifest records "
            f"{manifest['n_codes']}"
        )
    id_offset = int(manifest.get("id_offset", 0))
    cls = ShardedSimHashIndex if index_cls is None else index_cls
    index = cls(
        codes, mesh=mesh, devices=devices, n_shards=n_shards,
        data_axis=data_axis, n_bits=manifest["n_bits"],
        topk_impl=topk_impl, id_offset=id_offset,
        **(index_kwargs or {}),
    )
    tomb = manifest.get("tombstones")
    if tomb:
        packed = _load_chunk_verified(dirpath, tomb)
        dead = np.unpackbits(
            packed, count=manifest["n_codes"], bitorder="little"
        ).astype(bool)
        if int(dead.sum()) != tomb["deleted"]:
            raise ValueError(
                f"tombstone bitmap in {dirpath} marks {int(dead.sum())} "
                f"codes deleted but the manifest records {tomb['deleted']}"
            )
        index.delete(np.flatnonzero(dead).astype(np.int64) + id_offset)
    telemetry.emit(
        EVENTS.INDEX_SNAPSHOT_LOAD, path=dirpath,
        generation=manifest["generation"], chunks=len(manifest["chunks"]),
        n_codes=int(index.n_codes), deleted=int(index.n_deleted),
        shards=int(index.n_shards),
    )
    return index


def verify_snapshot(dirpath: str) -> dict:
    """Operational status of a snapshot directory (the ``cli recover``
    face): manifest validity, per-chunk checksum verification, orphan
    spills, row-range coverage.  Reports instead of raising — a corrupt
    chunk is a ``corrupt`` entry (and a ``recover.checksum_mismatch``
    event), ``ok`` is the overall verdict."""
    status: dict = {"path": dirpath, "ok": False}
    try:
        manifest = read_manifest(dirpath)
    except FileNotFoundError:
        status["error"] = f"no {MANIFEST_NAME} in {dirpath}"
        return status
    except (ValueError, OSError) as e:
        # unknown version, garbled JSON, not-a-directory, permission
        # denied … — all must come back as a status, not a traceback
        status["error"] = str(e)
        return status
    try:
        return _verify_manifest(dirpath, manifest, status)
    except (KeyError, TypeError, AttributeError) as e:
        # a structurally-malformed manifest (right version/kind, body
        # truncated or hand-edited) must come back as a status, not a
        # traceback — diagnosing exactly this is the command's job
        status["error"] = (
            f"malformed manifest body in {dirpath}: {e!r}"
        )
        return status


def _verify_manifest(dirpath: str, manifest: dict, status: dict) -> dict:
    status.update({
        "format_version": manifest["format_version"],
        "generation": manifest["generation"],
        "n_codes": manifest["n_codes"],
        "n_bytes": manifest["n_bytes"],
        "n_bits": manifest["n_bits"],
        "chunks": len(manifest["chunks"]),
        "deleted": (manifest.get("tombstones") or {}).get("deleted", 0),
        "rows_done": (manifest.get("ingest") or {}).get("rows_done"),
        "sharded": (manifest.get("sharded") or {}).get("shards"),
        "id_offset": manifest.get("id_offset", 0),
        "lsh": (
            {
                "bands": manifest["lsh"].get("bands"),
                "band_bits": manifest["lsh"].get("band_bits"),
            }
            if manifest.get("lsh")
            else None
        ),
        "tier": (
            {
                "cold_tier": manifest["tier"].get("cold_tier"),
                "hbm_budget_bytes":
                    manifest["tier"].get("hbm_budget_bytes"),
                "hot_chunks": sum(
                    1 for e in manifest["tier"].get("chunks", [])
                    if e.get("tier") == "hot"
                ),
                "cold_chunks": sum(
                    1 for e in manifest["tier"].get("chunks", [])
                    if e.get("tier") != "hot"
                ),
            }
            if manifest.get("tier")
            else None
        ),
    })
    corrupt = []
    try:
        # residency metadata verifies like coverage: unknown tier tags
        # or chunk-table disagreements are a corrupt manifest, reported
        # (pre-tier snapshots have no block and verify unchanged)
        _check_tier_block(manifest)
    except ValueError as e:
        corrupt.append({"file": MANIFEST_NAME, "error": str(e)})
    entries = list(manifest["chunks"])
    if manifest.get("tombstones"):
        entries.append(manifest["tombstones"])
    if manifest.get("lsh"):
        # the banded-index key spill verifies like any chunk (it is
        # rebuildable from the codes, but serving a corrupt one silently
        # is exactly what `cli recover` exists to catch)
        entries.append(manifest["lsh"])
    for entry in entries:
        try:
            _load_chunk_verified(dirpath, entry)
        except ValueError as e:
            corrupt.append({"file": entry["file"], "error": str(e)})
    try:
        check_coverage(manifest)
        coverage_ok = True
    except ValueError as e:
        coverage_ok = False
        corrupt.append({"file": MANIFEST_NAME, "error": str(e)})
    status["corrupt"] = corrupt
    status["coverage_ok"] = coverage_ok
    status["orphan_chunks"] = _scan_orphans(dirpath, manifest)
    status["ok"] = not corrupt
    return status


class DurableIngest:
    """Crash-durable ingest of a packed-code stream into a
    ``SimHashIndex``: the cursor commit and the chunk flush are one
    atomic manifest update, so a ``kill -9`` anywhere leaves a state
    that resumes exactly-once.

    ``run(estimator, source)`` streams the source through the estimator
    (any estimator whose streamed output is uint8 packed codes — i.e.
    ``SignRandomProjection``), appends each committed batch to the
    resident index AND to a chunk spill file, and commits
    ``rows_done = lo + rows`` together with the new chunk entries in
    one manifest replace.  Crash windows:

    - **mid-batch** (before any durable write): the manifest still
      names the previous batch boundary; resume replays this batch.
    - **post-yield pre-ack** (chunk file written, manifest not): the
      chunk file is an unreferenced orphan; resume sweeps it
      (``recover.orphan_chunk``) and replays the batch, rewriting an
      identical file (batches are pure functions of their row range).
    - **mid-snapshot-rename** (manifest tmp written, not replaced):
      the ``.tmp`` is swept with the orphans; the committed manifest is
      still the previous state.

    In every case the resumed run replays exactly the rows past the
    committed ``rows_done`` and the final index — chunk layout included
    — is bit-identical to an uninterrupted run, which the subprocess
    kill harness (``crash_smoke``/``cli recover --smoke``) asserts at
    every injection point.

    ``commit_every_batches`` amortizes the per-commit fsyncs (a crash
    then replays up to that many batches); ``compact_after_chunks``
    folds the accumulated one-chunk-per-batch spills into a single
    chunk (new snapshot generation) whenever the chunk count reaches
    the threshold — bounding the per-query dispatch count a long
    ingest would otherwise build up (the 1000-batch → 1000-dispatch
    weak item).  Compaction preserves ids (ingest never tombstones), so
    results are unchanged; chunk *layout* after a crash may then differ
    from the clean run's, but the code content and every query result
    remain bit-identical.
    """

    def __init__(self, path: str, *, commit_every_batches: int = 1,
                 compact_after_chunks: Optional[int] = None):
        if commit_every_batches < 1:
            raise ValueError(
                f"commit_every_batches must be >= 1, got "
                f"{commit_every_batches}"
            )
        if compact_after_chunks is not None and compact_after_chunks < 2:
            raise ValueError(
                f"compact_after_chunks must be >= 2 or None, got "
                f"{compact_after_chunks}"
            )
        self.path = path
        self.commit_every_batches = int(commit_every_batches)
        self.compact_after_chunks = compact_after_chunks

    # -- state ---------------------------------------------------------------

    def rows_done(self) -> int:
        """The committed cursor: rows durably ingested (0 when the
        directory has no manifest yet)."""
        try:
            manifest = read_manifest(self.path)
        except FileNotFoundError:
            return 0
        ingest = manifest.get("ingest")
        if ingest is None:
            raise ValueError(
                f"{self.path} holds a plain index snapshot, not a durable "
                "ingest (no cursor binding in its manifest)"
            )
        return int(ingest["rows_done"])

    def _resume_or_fresh(self, n_bytes: int, n_bits: int):
        """Load the committed state (verifying checksums), sweep crash
        debris, and report the resume point."""
        try:
            manifest = read_manifest(self.path)
        except FileNotFoundError:
            os.makedirs(self.path, exist_ok=True)
            from randomprojection_tpu.models.sketch import SimHashIndex

            index = SimHashIndex(
                np.empty((0, n_bytes), np.uint8), n_bits=n_bits
            )
            return index, 0, [], 0
        ingest = manifest.get("ingest")
        if ingest is None:
            raise ValueError(
                f"{self.path} holds a plain index snapshot, not a durable "
                "ingest run; point DurableIngest at its own directory"
            )
        if manifest["n_bytes"] != n_bytes or manifest["n_bits"] != n_bits:
            raise ValueError(
                f"durable ingest at {self.path} holds "
                f"{manifest['n_bits']}-bit/{manifest['n_bytes']}-byte "
                f"codes but the estimator streams {n_bits}-bit/"
                f"{n_bytes}-byte codes; resuming would mix two projections"
            )
        recorded = ingest.get("estimator")
        if recorded is not None and recorded != self._est_fp:
            # same shape is NOT same projection: a different seed/spec
            # would encode the replayed rows under a different matrix —
            # permanently inconsistent neighbors with no error anywhere
            raise ValueError(
                f"durable ingest at {self.path} was written by estimator "
                f"{recorded} but this run uses {self._est_fp}; resuming "
                "would mix two projections in one index"
            )
        # sweep the debris of a crash BEFORE loading: uncommitted chunk
        # spills and manifest tmps are replayed deterministically
        for fn in _scan_orphans(self.path, manifest):
            telemetry.emit(EVENTS.RECOVER_ORPHAN_CHUNK, path=self.path,
                           file=fn)
            os.unlink(os.path.join(self.path, fn))
        check_coverage(manifest)
        index = load_index(self.path)
        return (
            index, int(ingest["rows_done"]), list(manifest["chunks"]),
            int(manifest["generation"]),
        )

    # -- the run -------------------------------------------------------------

    def run(self, estimator, source):
        """Ingest ``source`` through ``estimator`` into the durable
        index, resuming from the committed cursor; returns the live
        ``SimHashIndex`` (fully committed through the last batch)."""
        estimator._check_is_fitted()
        out_dtype = estimator._stream_out_dtype()
        if out_dtype is None or np.dtype(out_dtype) != np.uint8:
            raise ValueError(
                "DurableIngest ingests packed uint8 codes (e.g. "
                "SignRandomProjection); this estimator streams "
                f"{out_dtype!r}"
            )
        n_bytes = int(estimator._stream_out_width())
        n_bits = int(estimator.n_components_)
        self._est_fp = _estimator_fingerprint(estimator)
        index, rows_done, entries, gen = self._resume_or_fresh(
            n_bytes, n_bits
        )
        if rows_done > source.n_rows:
            raise ValueError(
                f"committed cursor rows_done={rows_done} exceeds the "
                f"source's {source.n_rows} rows; wrong source for this "
                "ingest directory"
            )
        if rows_done:
            telemetry.emit(
                EVENTS.RECOVER_RESUME, path=self.path,
                rows_done=rows_done,
                replay_rows=int(source.n_rows - rows_done),
            )
        self._entries = entries
        self._generation = gen
        pending: list = []
        for lo, y in stream_transform(
            estimator, source, cursor=StreamCursor(rows_done)
        ):
            _maybe_kill("mid-batch")
            codes = np.ascontiguousarray(y, dtype=np.uint8)
            index.add(codes)
            pending.append((lo, codes))
            if len(pending) >= self.commit_every_batches:
                self._commit(index, pending)
                pending = []
                if (
                    self.compact_after_chunks is not None
                    and len(self._entries) >= self.compact_after_chunks
                ):
                    self._compact_commit(index)
        if pending:
            self._commit(index, pending)
        return index

    def _commit(self, index, pending: list) -> None:
        """One durable commit: flush the pending batches' chunk files,
        then bind the advanced cursor to them in a single atomic
        manifest replace (THE ack — a crash on either side of it is a
        clean replay, never a drop or a double-commit)."""
        rows_done = None
        for lo, codes in pending:
            self._entries.append(_spill_chunk(
                self.path, self._generation, len(self._entries), codes, lo
            ))
            rows_done = int(lo + codes.shape[0])
        _maybe_kill("post-yield-pre-ack")
        self._write_manifest(index, rows_done)
        telemetry.emit(
            EVENTS.INDEX_SNAPSHOT_SAVE, path=self.path,
            generation=self._generation, chunks=len(self._entries),
            n_codes=int(index.n_codes), deleted=int(index.n_deleted),
            rows_done=rows_done,
        )

    def _write_manifest(self, index, rows_done: int) -> None:
        _commit_manifest(self.path, {
            "format_version": INDEX_FORMAT_VERSION,
            "kind": "simhash_index",
            "n_bytes": int(index.n_bytes),
            "n_bits": int(index.n_bits),
            "n_codes": int(index.n_codes),
            "generation": self._generation,
            "chunks": self._entries,
            "tombstones": None,
            "ingest": {
                "rows_done": int(rows_done),
                "estimator": self._est_fp,
            },
        })

    def _compact_commit(self, index) -> None:
        """Fold the accumulated per-batch chunks into one (new snapshot
        generation), then sweep the superseded files: old-state files
        are unlinked only AFTER the new manifest is committed, so a
        crash at any instant leaves a loadable snapshot.  The compacted
        host array is read back from the COMMITTED spill files — every
        ingested code is already on disk — so compaction pays disk
        reads plus one re-upload, never a full-index device fetch."""
        rows_done = self.rows_done()
        codes = _codes_of(self.path)
        # ingest never tombstones, so the committed codes in id order
        # ARE the compacted content; rebuild the resident index from
        # them (the device side of compact()) and spill the same host
        # array as the new generation's single chunk
        index._rebuild_from_host(codes)
        self._generation += 1
        old_files = [e["file"] for e in self._entries]
        self._entries = []
        if codes.shape[0]:
            self._entries.append(_spill_chunk(
                self.path, self._generation, 0, codes, 0
            ))
        self._write_manifest(index, rows_done)
        for fn in old_files:
            try:
                os.unlink(os.path.join(self.path, fn))
            except FileNotFoundError:  # pragma: no cover — already swept
                pass


# -- deterministic demo ingest + subprocess crash harness --------------------


def demo_ingest(path: str, *, rows: int = 192, batch_rows: int = 32,
                d: int = 16, bits: int = 64, seed: int = 0,
                commit_every: int = 1,
                compact_after: Optional[int] = None) -> dict:
    """The harness child: a fully deterministic SimHash ingest (seeded
    synthetic rows → ``SignRandomProjection`` on the numpy backend →
    ``DurableIngest``) whose every byte is a pure function of the
    arguments — so a killed-and-resumed run can be compared
    bit-for-bit against a clean one.  Returns a summary dict."""
    from randomprojection_tpu.models.sketch import (
        SignRandomProjection,
        SimHashIndex,
    )
    from randomprojection_tpu.streaming import CallableSource

    def read(lo, hi):
        rng = np.random.default_rng([seed, lo])
        return rng.standard_normal((hi - lo, d), dtype=np.float32)

    source = CallableSource(read, rows, d, dtype=np.float32,
                            batch_rows=batch_rows)
    est = SignRandomProjection(bits, random_state=seed, backend="numpy")
    est.fit_source(source)
    ingest = DurableIngest(path, commit_every_batches=commit_every,
                           compact_after_chunks=compact_after)
    index = ingest.run(est, source)
    # tiered-demotion fault leg (ISSUE 19): re-open the committed codes
    # as a disk-tiered index (spills in a subdirectory the orphan sweep
    # never enters) and synchronously demote every chunk — each pass
    # crosses the "mid-demotion" kill point between the spill write and
    # the residency swap, proving a crash there leaves the committed
    # snapshot loadable with the spill as debris (the kill matrix's
    # resume re-runs this leg cleanly)
    tiered = SimHashIndex(
        np.empty((0, index.n_bytes), np.uint8), n_bits=index.n_bits,
        hbm_budget_bytes=1 << 40, cold_tier="disk",
        cold_dir=os.path.join(path, "cold"),
    )
    for chunk in index._chunks:
        tiered.add(index._fetch_chunk_host(chunk))
    demoted = sum(
        tiered._tier.demote(c.row0) for c in tiered._chunks
    )
    tiered.close()
    return {
        "path": path,
        "rows_done": ingest.rows_done(),
        "n_codes": int(index.n_codes),
        "chunks": len(index._chunks),
        "tier_demotions": int(demoted),
    }


def _child_argv(path: str, *, rows: int, batch_rows: int, d: int,
                bits: int, seed: int) -> list:
    return [
        sys.executable, "-m", "randomprojection_tpu", "recover",
        "--child", path, "--rows", str(rows),
        "--batch-rows", str(batch_rows), "--d", str(d),
        "--bits", str(bits), "--seed", str(seed),
    ]


def run_child(path: str, *, rows: int = 192, batch_rows: int = 32,
              d: int = 16, bits: int = 64, seed: int = 0,
              kill: Optional[str] = None, timeout: float = 180.0):
    """Run one harness child ingest as a real subprocess (so SIGKILL
    kills a whole process, cache and buffers included).  ``kill`` is a
    ``"<point>@<n>"`` spec for ``RP_DURABLE_KILL``; returns the
    ``CompletedProcess`` (returncode ``-SIGKILL`` when the kill
    fired)."""
    import subprocess

    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop(KILL_ENV, None)
    if kill is not None:
        env[KILL_ENV] = kill
    return subprocess.run(
        _child_argv(path, rows=rows, batch_rows=batch_rows, d=d,
                    bits=bits, seed=seed),
        capture_output=True, text=True, timeout=timeout, env=env,
    )


def _codes_of(dirpath: str) -> np.ndarray:
    """All committed codes of a snapshot, in global id order, straight
    from the verified spill files (no device round-trip)."""
    manifest = read_manifest(dirpath)
    check_coverage(manifest)
    parts = [
        _load_chunk_verified(dirpath, e) for e in manifest["chunks"]
    ]
    return (
        np.concatenate(parts, axis=0)
        if parts
        else np.empty((0, manifest["n_bytes"]), np.uint8)
    )


def crash_smoke(workdir: str, *, rows: int = 192, batch_rows: int = 32,
                d: int = 16, bits: int = 64, seed: int = 0,
                query_m: int = 5) -> dict:
    """The process-kill fault matrix at toy shapes: one clean run, then
    for each ``KILL_POINTS`` entry a run SIGKILLed at that point (third
    hit — mid-stream, not at an edge) and restarted.  Asserts for every
    recovered directory: the kill actually fired, row ranges tile
    exactly once, codes are bit-identical to the clean run, the
    manifests' chunk checksums agree, and ``query_topk`` answers match.
    Returns a verdict dict (``ok`` plus per-case detail); raises
    nothing — the caller turns ``ok`` into an exit code."""
    import shutil

    shapes = dict(rows=rows, batch_rows=batch_rows, d=d, bits=bits,
                  seed=seed)

    def fresh(name: str) -> str:
        # a leftover completed ingest from a previous smoke would resume
        # instantly (zero replay), so the kill point would never fire
        # and a healthy system would read as a harness failure — every
        # case starts from an empty directory
        path = os.path.join(workdir, name)
        shutil.rmtree(path, ignore_errors=True)
        return path

    import subprocess

    def child(path, **kw):
        # 'raises nothing' includes a wedged child: a timeout becomes a
        # failed case in the verdict, not a traceback through make verify
        try:
            return run_child(path, **kw)
        except subprocess.TimeoutExpired as e:
            return subprocess.CompletedProcess(
                e.cmd, returncode=999,
                stdout="", stderr=f"harness child timed out: {e}",
            )

    clean_dir = fresh("clean")
    proc = child(clean_dir, **shapes)
    if proc.returncode != 0:
        return {
            "ok": False, "error": "clean ingest failed",
            "returncode": proc.returncode,
            "stderr": proc.stderr[-2000:],
        }
    clean_manifest = read_manifest(clean_dir)
    clean_codes = _codes_of(clean_dir)
    rng = np.random.default_rng(seed + 1)
    queries = rng.integers(
        0, 256, size=(8, clean_manifest["n_bytes"]), dtype=np.uint8
    )
    clean_index = load_index(clean_dir)
    ref_d, ref_i = clean_index.query_topk(queries, query_m)
    cases = []
    ok = True
    for point in KILL_POINTS:
        case: dict = {"kill_at": point}
        case_dir = fresh(point.replace("-", "_"))
        crashed = child(case_dir, kill=f"{point}@3", **shapes)
        case["crash_returncode"] = crashed.returncode
        if crashed.returncode != -signal.SIGKILL:
            case["error"] = (
                "kill point never fired (run finished with "
                f"rc={crashed.returncode}): the harness is not covering "
                "this window"
            )
            ok = False
            cases.append(case)
            continue
        resumed = child(case_dir, **shapes)
        case["resume_returncode"] = resumed.returncode
        if resumed.returncode != 0:
            case["error"] = f"resume failed: {resumed.stderr[-2000:]}"
            ok = False
            cases.append(case)
            continue
        try:
            manifest = read_manifest(case_dir)
            check_coverage(manifest)
            codes = _codes_of(case_dir)
        except ValueError as e:
            case["error"] = f"recovered state invalid: {e}"
            ok = False
            cases.append(case)
            continue
        case["rows_done"] = manifest["ingest"]["rows_done"]
        case["bit_identical_codes"] = bool(
            np.array_equal(codes, clean_codes)
        )
        case["manifest_chunks_identical"] = [
            e["sha256"] for e in manifest["chunks"]
        ] == [e["sha256"] for e in clean_manifest["chunks"]]
        index = load_index(case_dir)
        got_d, got_i = index.query_topk(queries, query_m)
        case["query_results_match"] = bool(
            np.array_equal(got_d, ref_d) and np.array_equal(got_i, ref_i)
        )
        if not (
            case["bit_identical_codes"]
            and case["manifest_chunks_identical"]
            and case["query_results_match"]
            and case["rows_done"] == rows
        ):
            ok = False
        cases.append(case)
    return {"ok": ok, "workdir": workdir, "shapes": shapes,
            "cases": cases}
